// Native page-serde primitives: LZ4 block codec + xxHash64.
//
// Reference parity: execution/buffer/PagesSerde.java:41-74 — the
// reference compresses serialized pages with LZ4 (airlift-compressor)
// and the wire format carries checksums. Here the byte-level hot loops
// live in C++ (ctypes-loaded shared library, built by native/Makefile);
// the page framing itself is trino_tpu/serde.py. Both the compressor
// and the hash are from-scratch implementations of the public formats
// (LZ4 block format spec; xxHash64 spec), not vendored code.
//
// Exported C ABI:
//   int64_t tt_lz4_compress(const uint8_t*, int64_t, uint8_t*, int64_t)
//   int64_t tt_lz4_decompress(const uint8_t*, int64_t, uint8_t*, int64_t)
//   uint64_t tt_xxh64(const uint8_t*, int64_t, uint64_t)
//   int64_t tt_lz4_max_compressed(int64_t)

#include <cstdint>
#include <cstring>

extern "C" {

int64_t tt_lz4_max_compressed(int64_t n) {
    return n + n / 255 + 16;
}

// ---------------------------------------------------------------------
// LZ4 block compressor (greedy, 16-bit hash chain-less table)
// ---------------------------------------------------------------------

static inline uint32_t read32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
}

static inline uint32_t hash4(uint32_t v) {
    return (v * 2654435761u) >> 16;   // 16-bit table index
}

int64_t tt_lz4_compress(const uint8_t* src, int64_t n, uint8_t* dst,
                        int64_t cap) {
    if (n < 0 || cap < tt_lz4_max_compressed(n)) return -1;
    uint8_t* op = dst;
    const uint8_t* ip = src;
    const uint8_t* const iend = src + n;
    // matches must end >= 12 bytes before the end; last 5 bytes literal
    const uint8_t* const mlimit = iend - 12;
    const uint8_t* anchor = ip;

    int32_t table[1 << 16];
    for (int i = 0; i < (1 << 16); i++) table[i] = -1;

    if (n >= 13) {
        ip++;  // first byte can't be a match target
        while (ip <= mlimit) {
            uint32_t h = hash4(read32(ip));
            int32_t cand = table[h];
            table[h] = (int32_t)(ip - src);
            if (cand >= 0 && (ip - src) - cand <= 65535 &&
                read32(src + cand) == read32(ip)) {
                // extend the match forward
                const uint8_t* match = src + cand;
                const uint8_t* mip = ip + 4;
                const uint8_t* mm = match + 4;
                while (mip < iend - 5 && *mip == *mm) { mip++; mm++; }
                int64_t mlen = mip - ip;           // >= 4
                int64_t litlen = ip - anchor;
                // token
                uint8_t* token = op++;
                if (litlen >= 15) {
                    *token = 15 << 4;
                    int64_t rest = litlen - 15;
                    while (rest >= 255) { *op++ = 255; rest -= 255; }
                    *op++ = (uint8_t)rest;
                } else {
                    *token = (uint8_t)(litlen << 4);
                }
                std::memcpy(op, anchor, litlen);
                op += litlen;
                // offset
                uint16_t off = (uint16_t)(ip - match);
                *op++ = (uint8_t)(off & 0xff);
                *op++ = (uint8_t)(off >> 8);
                int64_t mrest = mlen - 4;
                if (mrest >= 15) {
                    *token |= 15;
                    mrest -= 15;
                    while (mrest >= 255) { *op++ = 255; mrest -= 255; }
                    *op++ = (uint8_t)mrest;
                } else {
                    *token |= (uint8_t)mrest;
                }
                ip += mlen;
                anchor = ip;
            } else {
                ip++;
            }
        }
    }
    // trailing literals
    int64_t litlen = iend - anchor;
    uint8_t* token = op++;
    if (litlen >= 15) {
        *token = 15 << 4;
        int64_t rest = litlen - 15;
        while (rest >= 255) { *op++ = 255; rest -= 255; }
        *op++ = (uint8_t)rest;
    } else {
        *token = (uint8_t)(litlen << 4);
    }
    std::memcpy(op, anchor, litlen);
    op += litlen;
    return op - dst;
}

int64_t tt_lz4_decompress(const uint8_t* src, int64_t n, uint8_t* dst,
                          int64_t cap) {
    const uint8_t* ip = src;
    const uint8_t* const iend = src + n;
    uint8_t* op = dst;
    uint8_t* const oend = dst + cap;
    while (ip < iend) {
        uint8_t token = *ip++;
        int64_t litlen = token >> 4;
        if (litlen == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                litlen += b;
            } while (b == 255);
        }
        if (ip + litlen > iend || op + litlen > oend) return -1;
        std::memcpy(op, ip, litlen);
        ip += litlen;
        op += litlen;
        if (ip >= iend) break;   // last sequence has no match
        if (ip + 2 > iend) return -1;
        uint16_t off = (uint16_t)(ip[0] | (ip[1] << 8));
        ip += 2;
        if (off == 0 || op - dst < off) return -1;
        int64_t mlen = (token & 15) + 4;
        if ((token & 15) == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                mlen += b;
            } while (b == 255);
        }
        if (op + mlen > oend) return -1;
        const uint8_t* match = op - off;
        // overlapping copy must run byte-wise
        for (int64_t i = 0; i < mlen; i++) op[i] = match[i];
        op += mlen;
    }
    return op - dst;
}

// ---------------------------------------------------------------------
// xxHash64 (spec-faithful)
// ---------------------------------------------------------------------

static const uint64_t P1 = 11400714785074694791ull;
static const uint64_t P2 = 14029467366897019727ull;
static const uint64_t P3 = 1609587929392839161ull;
static const uint64_t P4 = 9650029242287828579ull;
static const uint64_t P5 = 2870177450012600261ull;

static inline uint64_t rotl(uint64_t x, int r) {
    return (x << r) | (x >> (64 - r));
}

static inline uint64_t read64(const uint8_t* p) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    return v;
}

static inline uint64_t round1(uint64_t acc, uint64_t input) {
    acc += input * P2;
    acc = rotl(acc, 31);
    return acc * P1;
}

static inline uint64_t merge_round(uint64_t acc, uint64_t val) {
    acc ^= round1(0, val);
    return acc * P1 + P4;
}

uint64_t tt_xxh64(const uint8_t* p, int64_t len, uint64_t seed) {
    const uint8_t* const end = p + len;
    uint64_t h;
    if (len >= 32) {
        uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed,
                 v4 = seed - P1;
        const uint8_t* const limit = end - 32;
        do {
            v1 = round1(v1, read64(p)); p += 8;
            v2 = round1(v2, read64(p)); p += 8;
            v3 = round1(v3, read64(p)); p += 8;
            v4 = round1(v4, read64(p)); p += 8;
        } while (p <= limit);
        h = rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18);
        h = merge_round(h, v1);
        h = merge_round(h, v2);
        h = merge_round(h, v3);
        h = merge_round(h, v4);
    } else {
        h = seed + P5;
    }
    h += (uint64_t)len;
    while (p + 8 <= end) {
        h ^= round1(0, read64(p));
        h = rotl(h, 27) * P1 + P4;
        p += 8;
    }
    if (p + 4 <= end) {
        uint32_t v;
        std::memcpy(&v, p, 4);
        h ^= (uint64_t)v * P1;
        h = rotl(h, 23) * P2 + P3;
        p += 4;
    }
    while (p < end) {
        h ^= (*p) * P5;
        h = rotl(h, 11) * P1;
        p++;
    }
    h ^= h >> 33;
    h *= P2;
    h ^= h >> 29;
    h *= P3;
    h ^= h >> 32;
    return h;
}

}  // extern "C"
