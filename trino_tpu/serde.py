"""Columnar page wire/spill format.

Reference parity: execution/buffer/{PagesSerde.java:41-74,
SerializedPage.java:25-47, PagesSerdeUtil.java:64-100, PageCodecMarker}
— header + per-block encodings, optional LZ4, checksum. TPU-first frame:
struct-of-arrays (one contiguous lane per column — uploads straight into
device buffers), little-endian, xxHash64 trailer. The LZ4/xxh64 hot
loops are native C++ (native/pageserde.cpp) loaded via ctypes; a
pure-python "store" codec keeps everything working when the library
hasn't been built.

Frame layout:
  magic 'TPG1' | u8 codec | u32 ncols | u64 nrows
  per column:
    u16 name_len | name utf8 | u16 type_len | type utf8 | u8 flags
    lane DATA  [flags&1: VALID lane] [flags&2: DATA2 lane]
    [flags&4: dictionary — u32 count | per value u32 len + utf8]
  u64 xxh64 of everything before the trailer
Each lane: u8 dtype_code | u64 raw_len | u64 stored_len | bytes.
"""

from __future__ import annotations

import ctypes
import os
import threading
import struct
import subprocess
from typing import Dict, Optional

import numpy as np

from .columnar import Batch, Column, StringDictionary
from .config import capacity_for
from .types import Type, parse_type

_MAGIC = b"TPG1"
CODEC_STORE = 0
CODEC_LZ4 = 1

_DTYPES = [np.dtype(x) for x in
           ("bool", "int8", "int16", "int32", "int64", "float32",
            "float64", "uint64")]
_DTYPE_CODE = {d: i for i, d in enumerate(_DTYPES)}


# --------------------------------------------------------------------------
# native library loading (build on demand, cache the result)
# --------------------------------------------------------------------------

_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False
_LIB_LOCK = threading.Lock()


def _load_native() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    with _LIB_LOCK:
        return _load_native_locked()


def _load_native_locked() -> Optional[ctypes.CDLL]:
    """Must hold _LIB_LOCK. The flag flips only AFTER the load settles:
    a concurrent first call must block, not observe a half-initialized
    state — a worker thread that raced here used to fall back to
    crc32/STORE framing while its peers (and the coordinator) used
    xxh64/LZ4, surfacing as flaky 'page checksum mismatch' on tiny
    pages serialized inside the race window."""
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    try:
        _LIB = _do_load()
    finally:
        # flips LAST so the unlocked fast path can never observe
        # TRIED=True with the load still in flight
        _LIB_TRIED = True
    return _LIB


def _do_load() -> Optional[ctypes.CDLL]:
    here = os.path.dirname(os.path.abspath(__file__))
    so = os.path.join(here, "native", "libpageserde.so")
    src = os.path.normpath(os.path.join(here, "..", "native",
                                        "pageserde.cpp"))
    if not os.path.exists(so) and os.path.exists(src):
        try:
            os.makedirs(os.path.dirname(so), exist_ok=True)
            subprocess.run(
                ["g++", "-O3", "-fPIC", "-shared", "-std=c++17",
                 "-o", so, src],
                check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError):
            return None
    if not os.path.exists(so):
        return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    for name, restype, argtypes in [
        ("tt_lz4_compress", ctypes.c_int64,
         [ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
          ctypes.c_int64]),
        ("tt_lz4_decompress", ctypes.c_int64,
         [ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
          ctypes.c_int64]),
        ("tt_lz4_max_compressed", ctypes.c_int64, [ctypes.c_int64]),
        ("tt_xxh64", ctypes.c_uint64,
         [ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint64]),
    ]:
        fn = getattr(lib, name)
        fn.restype = restype
        fn.argtypes = argtypes
    return lib


def native_available() -> bool:
    return _load_native() is not None


def checksum(data: bytes, seed: int = 0) -> int:
    lib = _load_native()
    if lib is not None:
        return int(lib.tt_xxh64(data, len(data), seed))
    import zlib
    return zlib.crc32(data) ^ (seed & 0xFFFFFFFF)   # python fallback


def _compress(data: bytes, codec: int) -> bytes:
    if codec == CODEC_LZ4:
        lib = _load_native()
        cap = int(lib.tt_lz4_max_compressed(len(data)))
        out = ctypes.create_string_buffer(cap)
        n = lib.tt_lz4_compress(data, len(data), out, cap)
        if n < 0:
            raise ValueError("lz4 compression failed")
        return out.raw[:n]
    return data


def _decompress(data: bytes, raw_len: int, codec: int) -> bytes:
    if codec == CODEC_LZ4:
        lib = _load_native()
        out = ctypes.create_string_buffer(raw_len)
        n = lib.tt_lz4_decompress(data, len(data), out, raw_len)
        if n != raw_len:
            raise ValueError(
                f"lz4 decompression failed ({n} != {raw_len})")
        return out.raw
    return data


# --------------------------------------------------------------------------
# framing
# --------------------------------------------------------------------------

def _emit_lane(out: list, arr: np.ndarray, codec: int):
    arr = np.ascontiguousarray(arr)
    code = _DTYPE_CODE[arr.dtype]
    raw = arr.tobytes()
    stored = _compress(raw, codec)
    if len(stored) >= len(raw):
        stored, lane_codec = raw, CODEC_STORE
    else:
        lane_codec = codec
    out.append(struct.pack("<BBQQ", code, lane_codec, len(raw),
                           len(stored)))
    out.append(stored)


def _read_lane(buf: memoryview, off: int):
    code, lane_codec, raw_len, stored_len = struct.unpack_from(
        "<BBQQ", buf, off)
    off += struct.calcsize("<BBQQ")
    stored = bytes(buf[off:off + stored_len])
    off += stored_len
    raw = _decompress(stored, raw_len, lane_codec)
    return np.frombuffer(raw, dtype=_DTYPES[code]).copy(), off


def _emit_column(out: list, name: str, col: Column, n: int, codec: int):
    nb = name.encode()
    tb = col.type.name.encode()
    flags = ((1 if col.valid is not None else 0)
             | (2 if col.data2 is not None else 0)
             | (4 if col.dictionary is not None else 0)
             | (8 if col.elements is not None else 0))
    out.append(struct.pack("<H", len(nb)))
    out.append(nb)
    out.append(struct.pack("<H", len(tb)))
    out.append(tb)
    out.append(struct.pack("<B", flags))
    out.append(struct.pack("<Q", n))
    _emit_lane(out, np.asarray(col.data)[:n], codec)
    if col.valid is not None:
        _emit_lane(out, np.asarray(col.valid)[:n], codec)
    if col.data2 is not None:
        _emit_lane(out, np.asarray(col.data2)[:n], codec)
    if col.dictionary is not None:
        vals = col.dictionary.values
        out.append(struct.pack("<I", len(vals)))
        for v in vals:
            vb = str(v).encode()
            out.append(struct.pack("<I", len(vb)))
            out.append(vb)
    if col.elements is not None:
        # arrays ship their whole flat elements column (offsets index
        # into it; spi/block/ArrayBlock's values block analog)
        el = col.elements
        _emit_column(out, "$elements", el,
                     int(np.asarray(el.data).shape[0]), codec)


def serialize_batch(batch: Batch, codec: Optional[int] = None) -> bytes:
    """Batch -> framed bytes (live prefix only)."""
    if codec is None:
        codec = CODEC_LZ4 if native_available() else CODEC_STORE
    n = batch.num_rows_host()
    out: list = [_MAGIC, struct.pack("<BIQ", codec,
                                     len(batch.columns), n)]
    for name, col in batch.columns.items():
        _emit_column(out, name, col, n, codec)
    body = b"".join(out)
    return body + struct.pack("<Q", checksum(body))


def _read_column(buf: memoryview, off: int):
    (nlen,) = struct.unpack_from("<H", buf, off)
    off += 2
    name = bytes(buf[off:off + nlen]).decode()
    off += nlen
    (tlen,) = struct.unpack_from("<H", buf, off)
    off += 2
    typ = parse_type(bytes(buf[off:off + tlen]).decode())
    off += tlen
    (flags,) = struct.unpack_from("<B", buf, off)
    off += 1
    (n,) = struct.unpack_from("<Q", buf, off)
    off += 8
    data_arr, off = _read_lane(buf, off)
    valid = d2 = dictionary = elements = None
    if flags & 1:
        valid, off = _read_lane(buf, off)
    if flags & 2:
        d2, off = _read_lane(buf, off)
    if flags & 4:
        (cnt,) = struct.unpack_from("<I", buf, off)
        off += 4
        vals = []
        for _ in range(cnt):
            (vlen,) = struct.unpack_from("<I", buf, off)
            off += 4
            vals.append(bytes(buf[off:off + vlen]).decode())
            off += vlen
        dictionary = StringDictionary(np.asarray(vals, dtype=object))
    if flags & 8:
        _, elements, off = _read_column(buf, off)
    cap = capacity_for(max(int(n), 1), minimum=8)
    pad = cap - len(data_arr)
    data_arr = np.pad(data_arr, (0, pad))
    if valid is not None:
        valid = np.pad(valid, (0, pad))
    if d2 is not None:
        d2 = np.pad(d2, (0, pad))
    return name, Column(typ, data_arr, valid, dictionary, d2,
                        elements), off


def frame_valid(data: bytes) -> bool:
    """Cheap integrity check of a serialized frame (magic prefix +
    xxh64 trailer) WITHOUT decoding it — the exchange puller's guard
    against accepting a non-frame HTTP 200 body (a wedged or foreign
    endpoint) as a partition during its candidate-worker sweep."""
    if len(data) < 12 or data[:4] != _MAGIC:
        return False
    buf = memoryview(data)
    (csum,) = struct.unpack_from("<Q", buf, len(buf) - 8)
    return checksum(bytes(buf[:-8])) == csum


def deserialize_batch(data: bytes) -> Batch:
    buf = memoryview(data)
    body, (csum,) = buf[:-8], struct.unpack_from("<Q", buf, len(buf) - 8)
    if checksum(bytes(body)) != csum:
        raise ValueError("page checksum mismatch")
    if bytes(buf[:4]) != _MAGIC:
        raise ValueError("bad page magic")
    codec, ncols, nrows = struct.unpack_from("<BIQ", buf, 4)
    off = 4 + struct.calcsize("<BIQ")
    cols: Dict[str, Column] = {}
    for _ in range(ncols):
        # _read_column pads each column to capacity_for(its n); every
        # top-level column carries the batch's n, so they share the
        # batch's capacity bucket
        name, col, off = _read_column(buf, off)
        cols[name] = col
    return Batch(cols, int(nrows))


# --------------------------------------------------------------------------
# spill (spiller/FileSingleStreamSpiller.java analog)
# --------------------------------------------------------------------------

class Spiller:
    """Writes batches to local disk pages and reads them back — the
    HBM -> host-RAM -> disk overflow tier (SURVEY.md §5
    checkpoint/resume: spill/unspill is the reference's only
    state-offload mechanism)."""

    # every live spill file across instances, for the leak detector
    # (server/diagnostics.py — a spill file outliving its query is the
    # reference's revocable-memory leak analog)
    _LIVE: "set[str]" = set()
    _LIVE_LOCK = threading.Lock()

    def __init__(self, directory: Optional[str] = None):
        import tempfile
        self._dir = directory or tempfile.mkdtemp(prefix="trino_tpu_spill_")
        self._files: list = []

    @classmethod
    def live_files(cls) -> list:
        with cls._LIVE_LOCK:
            return sorted(cls._LIVE)

    def spill(self, batch: Batch) -> str:
        path = os.path.join(self._dir, f"page_{len(self._files)}.bin")
        with open(path, "wb") as f:
            f.write(serialize_batch(batch))
        self._files.append(path)
        with Spiller._LIVE_LOCK:
            Spiller._LIVE.add(path)
        return path

    def unspill(self, path: str) -> Batch:
        with open(path, "rb") as f:
            return deserialize_batch(f.read())

    def unspill_all(self):
        return [self.unspill(p) for p in self._files]

    def close(self):
        gone = []
        for p in self._files:
            try:
                os.unlink(p)
                gone.append(p)
            except OSError:
                pass        # stays in _LIVE: still on disk == a leak
        with Spiller._LIVE_LOCK:
            Spiller._LIVE.difference_update(gone)
        self._files.clear()
