"""SQL type system, TPU-first.

Mirrors the reference's type SPI (reference: core/trino-spi/src/main/java/io/
trino/spi/type/ — 60+ classes) but each type here declares its *physical*
device representation: the jnp dtype of the value lanes plus how NULLs and
variable-width data are encoded. Design decisions (SURVEY.md §7.1):

- Fixed-width SQL types map 1:1 onto a single dense ``jax.Array`` lane.
- DECIMAL(p,s) with p<=18 is a scaled int64 ("short decimal",
  reference: spi/type/DecimalType.java, Int128 only for p>18).
- DECIMAL(p>18) is a pair of int64 lanes (hi, lo) emulating Int128.
- VARCHAR/CHAR are dictionary-encoded: an int32 code lane per row plus a
  host-side deduplicated dictionary (reference analog: spi/block/
  DictionaryBlock.java made the *primary* representation, because equality/
  group-by/join on codes is MXU/VPU-friendly while raw bytes are not).
- DATE is days-since-epoch int32; TIMESTAMP(p) is an int64 of 10^-p units
  since epoch (reference: spi/type/DateType.java, TimestampType.java).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

import numpy as np

__all__ = [
    "Type", "BOOLEAN", "TINYINT", "SMALLINT", "INTEGER", "BIGINT", "REAL",
    "DOUBLE", "VARCHAR", "VARBINARY", "DATE", "UNKNOWN", "DecimalType",
    "VarcharType", "CharType", "TimestampType", "TimeType", "ArrayType",
    "MapType", "RowType", "HyperLogLogType", "HYPER_LOG_LOG",
    "TDigestType", "T_DIGEST", "QDigestType", "GeometryType",
    "GEOMETRY",
    "IntervalDayTime", "IntervalYearMonth", "parse_type", "common_super_type",
    "is_numeric", "is_integral", "is_exact_numeric", "is_string",
]


@dataclass(frozen=True)
class Type:
    """Base SQL type. ``name`` is the SQL display name."""

    name: str

    # --- physical layout -------------------------------------------------
    @property
    def np_dtype(self) -> Optional[np.dtype]:
        """dtype of the primary value lane, or None for multi-lane types."""
        return _PHYSICAL.get(self.name)

    @property
    def lanes(self) -> int:
        return 1

    @property
    def is_dictionary(self) -> bool:
        return False

    def __str__(self) -> str:  # SQL display form
        return self.name

    def display(self) -> str:
        return self.name


_PHYSICAL = {
    "boolean": np.dtype(np.bool_),
    "tinyint": np.dtype(np.int8),
    "smallint": np.dtype(np.int16),
    "integer": np.dtype(np.int32),
    "bigint": np.dtype(np.int64),
    "real": np.dtype(np.float32),
    "double": np.dtype(np.float64),
    "date": np.dtype(np.int32),
    "interval day to second": np.dtype(np.int64),  # millis
    "interval year to month": np.dtype(np.int32),  # months
    "unknown": np.dtype(np.bool_),
}


@dataclass(frozen=True)
class HyperLogLogType(Type):
    """HLL sketch (reference: spi/type/HyperLogLogType + airlift-stats).

    Physically an ARRAY-like column: offsets into a flat register lane
    (``ops/hll.py``). ``bucket_bits`` is static per column so kernels see
    a fixed register width."""

    bucket_bits: int = 11

    def __init__(self, bucket_bits: int = 11):
        object.__setattr__(self, "name", "hyperloglog")
        object.__setattr__(self, "bucket_bits", bucket_bits)

    @property
    def num_buckets(self) -> int:
        return 1 << self.bucket_bits

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int64)  # offset lane


HYPER_LOG_LOG = HyperLogLogType()


@dataclass(frozen=True)
class GeometryType(Type):
    """GEOMETRY (reference: trino-geospatial's GeometryType over ESRI
    shapes). TPU-first representation: POINT geometries are two float64
    lanes (x in ``data``, y in ``data2``) — ST_Distance/ST_Contains are
    pure VPU math; non-point shapes ride dictionary-coded WKT text."""

    def __init__(self):
        object.__setattr__(self, "name", "geometry")


GEOMETRY = GeometryType()


@dataclass(frozen=True)
class TDigestType(Type):
    """t-digest sketch (reference: spi/type/TDigestType + airlift-stats
    TDigest). Physically like an ARRAY column: ``data`` = per-row start
    into flat centroid lanes, ``data2`` = centroid count, ``elements`` =
    centroid means (f64), ``elements2`` = centroid weights (f64)."""

    compression: int = 100

    def __init__(self, compression: int = 100):
        object.__setattr__(self, "name", "tdigest")
        object.__setattr__(self, "compression", compression)

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int64)  # offset lane


T_DIGEST = TDigestType()


@dataclass(frozen=True)
class QDigestType(Type):
    """Quantile digest over a numeric type (spi/type/QDigestType).
    Same physical layout as TDigestType; ``value_type`` drives the
    result type of value_at_quantile."""

    value_type: "Type" = None  # type: ignore

    def __init__(self, value_type: "Type"):
        object.__setattr__(self, "name", f"qdigest({value_type.name})")
        object.__setattr__(self, "value_type", value_type)

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int64)  # offset lane


@dataclass(frozen=True)
class DecimalType(Type):
    precision: int = 38
    scale: int = 0

    def __init__(self, precision: int, scale: int):
        object.__setattr__(self, "name", f"decimal({precision},{scale})")
        object.__setattr__(self, "precision", precision)
        object.__setattr__(self, "scale", scale)
        if not (1 <= precision <= 38):
            raise ValueError(f"DECIMAL precision out of range: {precision}")
        if not (0 <= scale <= precision):
            raise ValueError(f"DECIMAL scale out of range: {scale}")

    @property
    def is_short(self) -> bool:
        return self.precision <= 18

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int64)

    @property
    def lanes(self) -> int:
        return 1 if self.is_short else 2


@dataclass(frozen=True)
class VarcharType(Type):
    length: Optional[int] = None  # None == unbounded

    def __init__(self, length: Optional[int] = None):
        object.__setattr__(
            self, "name",
            "varchar" if length is None else f"varchar({length})")
        object.__setattr__(self, "length", length)

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int32)  # dictionary code lane

    @property
    def is_dictionary(self) -> bool:
        return True


@dataclass(frozen=True)
class CharType(Type):
    length: int = 1

    def __init__(self, length: int = 1):
        object.__setattr__(self, "name", f"char({length})")
        object.__setattr__(self, "length", length)

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int32)

    @property
    def is_dictionary(self) -> bool:
        return True


@dataclass(frozen=True)
class TimestampType(Type):
    precision: int = 3

    def __init__(self, precision: int = 3):
        object.__setattr__(self, "name", f"timestamp({precision})")
        object.__setattr__(self, "precision", precision)

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int64)


def iso_timestamp_millis(s: str) -> int:
    """ISO timestamp text -> epoch milliseconds (shared by literal
    planning and varchar casts so the conversions cannot diverge)."""
    import datetime
    dt = datetime.datetime.fromisoformat(s.strip())
    epoch = datetime.datetime(1970, 1, 1)
    return int((dt - epoch).total_seconds() * 1000)


def iso_time_millis(s: str) -> int:
    """ISO time text -> milliseconds of day."""
    import datetime
    t = datetime.time.fromisoformat(s.strip())
    return (((t.hour * 60 + t.minute) * 60 + t.second) * 1000
            + t.microsecond // 1000)


@dataclass(frozen=True)
class TimestampTZType(Type):
    """TIMESTAMP(p) WITH TIME ZONE (spi/type/
    TimestampWithTimeZoneType.java packs millis+zoneKey in one long).
    TPU-first layout: the ``data`` lane is the UTC instant in epoch
    milliseconds — so comparison/ordering/grouping/joins are plain
    int64 lane ops with the correct instant semantics — and the
    ``data2`` lane carries the per-value zone offset in MINUTES, used
    only for display and field extraction (it does NOT participate in
    equality, matching the reference's instant-based equality)."""
    precision: int = 3

    def __init__(self, precision: int = 3):
        object.__setattr__(self, "name",
                           f"timestamp({precision}) with time zone")
        object.__setattr__(self, "precision", precision)

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int64)


def zone_offset_minutes(zone: str, instant_ms=None) -> int:
    """Fixed-offset zone string ('+05:30', '-08:00', 'UTC', or an IANA
    name resolved at ``instant_ms``) -> offset minutes."""
    z = zone.strip()
    if z.upper() in ("UTC", "Z"):
        return 0
    if z and z[0] in "+-":
        sign = -1 if z[0] == "-" else 1
        hh, _, mm = z[1:].partition(":")
        return sign * (int(hh) * 60 + int(mm or 0))
    import datetime
    from zoneinfo import ZoneInfo
    dt = (datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
          + datetime.timedelta(milliseconds=int(instant_ms or 0)))
    off = dt.astimezone(ZoneInfo(z)).utcoffset()
    return int(off.total_seconds() // 60)


def iso_timestamp_tz(s: str):
    """Timestamp text with zone -> (utc_millis, offset_minutes).
    Accepts '2020-01-01 00:00:00 +05:30', '...Z', '... UTC', and
    '... Region/City' forms; None offset part -> (naive, None)."""
    import datetime
    import re as _re
    text = s.strip()
    m = _re.match(
        r"^(\d{4}-\d{2}-\d{2}[ T]\d{2}:\d{2}(?::\d{2}(?:\.\d+)?)?)"
        r"\s*(Z|UTC|[+-]\d{2}(?::?\d{2})?|[A-Za-z_]+/[A-Za-z_]+)?$",
        text)
    if not m:
        raise ValueError(f"cannot parse timestamp: {s!r}")
    base, zone = m.group(1), m.group(2)
    naive = datetime.datetime.fromisoformat(base.replace("T", " "))
    local_ms = int((naive - datetime.datetime(1970, 1, 1))
                   .total_seconds() * 1000)
    if zone is None:
        return local_ms, None
    if "/" in zone:
        from zoneinfo import ZoneInfo
        aware = naive.replace(tzinfo=ZoneInfo(zone))
        off = aware.utcoffset()
        offset_min = int(off.total_seconds() // 60)
    else:
        offset_min = zone_offset_minutes(zone)
    return local_ms - offset_min * 60000, offset_min


@dataclass(frozen=True)
class TimeType(Type):
    """TIME(p): milliseconds of day in an int64 lane
    (spi/type/TimeType.java)."""
    precision: int = 3

    def __init__(self, precision: int = 3):
        object.__setattr__(self, "name", f"time({precision})")
        object.__setattr__(self, "precision", precision)

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int64)


@dataclass(frozen=True)
class ArrayType(Type):
    element: Type = None  # type: ignore

    def __init__(self, element: Type):
        object.__setattr__(self, "name", f"array({element.name})")
        object.__setattr__(self, "element", element)


@dataclass(frozen=True)
class MapType(Type):
    """MAP(k, v): physically offsets+lengths lanes over two flat element
    columns (keys, values) — spi/type/MapType.java redesigned as
    struct-of-arrays like ArrayType (see columnar.Column docstring)."""
    key: Type = None    # type: ignore
    value: Type = None  # type: ignore

    def __init__(self, key: Type, value: Type):
        object.__setattr__(self, "name", f"map({key.name}, {value.name})")
        object.__setattr__(self, "key", key)
        object.__setattr__(self, "value", value)


@dataclass(frozen=True)
class RowType(Type):
    fields: Tuple[Tuple[Optional[str], Type], ...] = ()

    def __init__(self, fields):
        fields = tuple((n, t) for n, t in fields)
        object.__setattr__(
            self, "name",
            "row(" + ", ".join(
                (f"{n} {t.name}" if n else t.name) for n, t in fields) + ")")
        object.__setattr__(self, "fields", fields)


BOOLEAN = Type("boolean")
TINYINT = Type("tinyint")
SMALLINT = Type("smallint")
INTEGER = Type("integer")
BIGINT = Type("bigint")
REAL = Type("real")
DOUBLE = Type("double")
DATE = Type("date")
UNKNOWN = Type("unknown")  # type of NULL literal
VARBINARY = Type("varbinary")
VARCHAR = VarcharType(None)
IntervalDayTime = Type("interval day to second")
IntervalYearMonth = Type("interval year to month")


def is_integral(t: Type) -> bool:
    return t.name in ("tinyint", "smallint", "integer", "bigint")


def is_exact_numeric(t: Type) -> bool:
    return is_integral(t) or isinstance(t, DecimalType)


def is_numeric(t: Type) -> bool:
    return is_exact_numeric(t) or t.name in ("real", "double")


def is_string(t: Type) -> bool:
    return isinstance(t, (VarcharType, CharType))


_NUMERIC_LADDER = ["tinyint", "smallint", "integer", "bigint", "real",
                   "double"]


def default_decimal_for(t: Type) -> DecimalType:
    return {
        "tinyint": DecimalType(3, 0), "smallint": DecimalType(5, 0),
        "integer": DecimalType(10, 0), "bigint": DecimalType(19, 0),
    }[t.name]


def common_super_type(a: Type, b: Type) -> Optional[Type]:
    """The implicit-coercion join of two types (reference:
    core/trino-main/.../type/TypeCoercion.java)."""
    if a == b:
        return a
    if a == UNKNOWN:
        return b
    if b == UNKNOWN:
        return a
    if is_string(a) and is_string(b):
        return VARCHAR
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        if a.name in ("double",) or b.name in ("double",):
            return DOUBLE
        if a.name in ("real",) or b.name in ("real",):
            return REAL
        da = a if isinstance(a, DecimalType) else (
            default_decimal_for(a) if is_integral(a) else None)
        db = b if isinstance(b, DecimalType) else (
            default_decimal_for(b) if is_integral(b) else None)
        if da is None or db is None:
            return None
        scale = max(da.scale, db.scale)
        ip = max(da.precision - da.scale, db.precision - db.scale)
        return DecimalType(min(38, ip + scale), scale)
    if is_numeric(a) and is_numeric(b):
        ia, ib = _NUMERIC_LADDER.index(a.name), _NUMERIC_LADDER.index(b.name)
        return a if ia >= ib else b
    if a == DATE and isinstance(b, TimestampType):
        return b
    if b == DATE and isinstance(a, TimestampType):
        return a
    if isinstance(a, ArrayType) and isinstance(b, ArrayType):
        e = common_super_type(a.element, b.element)
        return None if e is None else ArrayType(e)
    if isinstance(a, MapType) and isinstance(b, MapType):
        k = common_super_type(a.key, b.key)
        v = common_super_type(a.value, b.value)
        return None if k is None or v is None else MapType(k, v)
    if isinstance(a, RowType) and isinstance(b, RowType):
        if len(a.fields) != len(b.fields):
            return None
        fields = []
        for (na, ta), (nb, tb) in zip(a.fields, b.fields):
            t = common_super_type(ta, tb)
            if t is None:
                return None
            fields.append((na if na == nb else None, t))
        return RowType(fields)
    return None


def _split_top_level(s: str):
    """Split on commas not nested inside parentheses."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def _looks_like_type(tok: str) -> bool:
    tok = tok.split("(")[0]
    return (tok in _SIMPLE
            or tok in ("decimal", "char", "timestamp", "time", "array",
                       "map", "row"))


_TYPE_RE = re.compile(r"^\s*([a-z_ ]+?)\s*(?:\(\s*([0-9]+)\s*(?:,\s*([0-9]+)\s*)?\))?\s*$")

_SIMPLE = {t.name: t for t in [
    BOOLEAN, TINYINT, SMALLINT, INTEGER, BIGINT, REAL, DOUBLE, DATE,
    VARBINARY, UNKNOWN, IntervalDayTime, IntervalYearMonth]}
_SIMPLE["int"] = INTEGER
_SIMPLE["string"] = VARCHAR
_SIMPLE["varchar"] = VARCHAR
_SIMPLE["timestamp"] = TimestampType(3)
_SIMPLE["hyperloglog"] = HYPER_LOG_LOG
_SIMPLE["geometry"] = GEOMETRY
_SIMPLE["tdigest"] = T_DIGEST
_SIMPLE["p4hyperloglog"] = HYPER_LOG_LOG


def parse_type(s: str) -> Type:
    """Parse a SQL type name, e.g. 'decimal(12,2)' or
    'array(varchar(25))' (reference:
    core/trino-main/.../type/TypeRegistry.java)."""
    low = s.strip().lower()
    if low.startswith("array(") and low.endswith(")"):
        return ArrayType(parse_type(low[len("array("):-1]))
    if low.startswith("map(") and low.endswith(")"):
        parts = _split_top_level(low[len("map("):-1])
        if len(parts) != 2:
            raise ValueError(f"cannot parse map type: {s!r}")
        return MapType(parse_type(parts[0]), parse_type(parts[1]))
    if low.startswith("row(") and low.endswith(")"):
        fields = []
        for part in _split_top_level(low[len("row("):-1]):
            part = part.strip()
            # "name type" or bare "type"
            toks = part.split(None, 1)
            if len(toks) == 2 and not _looks_like_type(toks[0]):
                fields.append((toks[0], parse_type(toks[1])))
            else:
                fields.append((None, parse_type(part)))
        return RowType(fields)
    low2 = " ".join(low.split())
    if low2.endswith(" with time zone"):
        mtz = _TYPE_RE.match(low2[:-len(" with time zone")])
        if mtz and mtz.group(1) == "timestamp":
            return TimestampTZType(int(mtz.group(2))
                                   if mtz.group(2) else 3)
        raise ValueError(f"unknown type: {s!r}")
    if low2.endswith(" without time zone"):
        return parse_type(low2[:-len(" without time zone")])
    m = _TYPE_RE.match(s.lower())
    if not m:
        raise ValueError(f"cannot parse type: {s!r}")
    base, p1, p2 = m.group(1), m.group(2), m.group(3)
    if base in _SIMPLE and p1 is None:
        return _SIMPLE[base]
    if base == "decimal":
        return DecimalType(int(p1 or 38), int(p2 or 0))
    if base == "varchar":
        return VarcharType(int(p1)) if p1 else VARCHAR
    if base == "char":
        return CharType(int(p1 or 1))
    if base == "timestamp":
        return TimestampType(int(p1) if p1 else 3)
    if base == "time":
        return TimeType(int(p1) if p1 else 3)
    raise ValueError(f"unknown type: {s!r}")
