"""Authentication + authorization (engine-side).

Reference parity: server/security/ (PasswordAuthenticator flow),
plugin/trino-password-authenticators (file-based: username:bcrypt
lines — ours uses salted SHA-256 from hashlib since bcrypt isn't in
the image), security/AccessControlManager.java + the SPI
(spi/security/SystemAccessControl.java, ConnectorAccessControl), and
the file-based access control's catalog/schema/table rules."""

from __future__ import annotations

import hashlib
import hmac
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class AccessDeniedError(Exception):
    """spi/security/AccessDeniedException.java"""

    def __init__(self, what: str):
        super().__init__(f"Access Denied: {what}")


# --------------------------------------------------------------------------
# authentication
# --------------------------------------------------------------------------

class PasswordAuthenticator:
    """spi/security/PasswordAuthenticator — authenticate(user, password)
    -> bool."""

    def authenticate(self, user: str, password: str) -> bool:
        raise NotImplementedError


class InMemoryPasswordAuthenticator(PasswordAuthenticator):
    """Salted-hash store (the file-based authenticator's model,
    plugin/trino-password-authenticators FileAuthenticator)."""

    def __init__(self, users: Optional[Dict[str, str]] = None):
        self._store: Dict[str, Tuple[bytes, bytes]] = {}
        for user, pw in (users or {}).items():
            self.set_password(user, pw)

    @staticmethod
    def _digest(salt: bytes, password: str) -> bytes:
        return hashlib.pbkdf2_hmac("sha256", password.encode(), salt,
                                   10_000)

    def set_password(self, user: str, password: str) -> None:
        salt = os.urandom(16)
        self._store[user] = (salt, self._digest(salt, password))

    def authenticate(self, user: str, password: str) -> bool:
        entry = self._store.get(user)
        if entry is None:
            return False
        salt, want = entry
        return hmac.compare_digest(want, self._digest(salt, password))


def load_password_file(text: str) -> InMemoryPasswordAuthenticator:
    """'user:password' lines (test/dev convenience; the reference file
    format carries bcrypt digests)."""
    auth = InMemoryPasswordAuthenticator()
    for line in text.splitlines():
        line = line.strip()
        if line and not line.startswith("#") and ":" in line:
            user, _, pw = line.partition(":")
            auth.set_password(user, pw)
    return auth


# --------------------------------------------------------------------------
# authorization
# --------------------------------------------------------------------------

class AccessControl:
    """SystemAccessControl SPI surface the engine consults. Default:
    allow everything (AllowAllSystemAccessControl)."""

    def check_can_select(self, user: str, catalog: str, schema: str,
                         table: str) -> None:
        pass

    def check_can_create_table(self, user: str, catalog: str,
                               schema: str, table: str) -> None:
        pass

    def check_can_drop_table(self, user: str, catalog: str,
                             schema: str, table: str) -> None:
        pass

    def check_can_insert(self, user: str, catalog: str, schema: str,
                         table: str) -> None:
        pass

    def check_can_update(self, user: str, catalog: str, schema: str,
                         table: str) -> None:
        # UPDATE/MERGE require the same write privilege as DELETE
        self.check_can_delete(user, catalog, schema, table)

    def check_can_delete(self, user: str, catalog: str, schema: str,
                         table: str) -> None:
        pass

    def check_can_set_session(self, user: str, name: str) -> None:
        pass

    def check_can_kill_query(self, user: str, owner: str) -> None:
        pass


ALLOW_ALL = AccessControl()


@dataclass
class AccessRule:
    """One rule of the file-based access control
    (plugin resource-group-managers style): regexes over
    (user, catalog.schema.table) -> allowed privileges."""
    user: str = ".*"
    table: str = ".*"            # catalog\.schema\.table regex
    privileges: Tuple[str, ...] = ("select", "insert", "delete",
                                   "create", "drop")

    def matches(self, user: str, fqtn: str) -> bool:
        return (re.fullmatch(self.user, user or "") is not None
                and re.fullmatch(self.table, fqtn) is not None)


class RuleBasedAccessControl(AccessControl):
    """First-match-wins rule list (file-based access control
    semantics); no matching rule denies."""

    def __init__(self, rules: List[AccessRule]):
        self.rules = list(rules)

    def _check(self, privilege: str, user: str, catalog: str,
               schema: str, table: str) -> None:
        fqtn = f"{catalog}.{schema}.{table}"
        for rule in self.rules:
            if rule.matches(user, fqtn):
                if privilege in rule.privileges:
                    return
                break
        raise AccessDeniedError(
            f"Cannot {privilege} table {fqtn} as user {user}")

    def check_can_select(self, user, catalog, schema, table):
        self._check("select", user, catalog, schema, table)

    def check_can_create_table(self, user, catalog, schema, table):
        self._check("create", user, catalog, schema, table)

    def check_can_drop_table(self, user, catalog, schema, table):
        self._check("drop", user, catalog, schema, table)

    def check_can_insert(self, user, catalog, schema, table):
        self._check("insert", user, catalog, schema, table)

    def check_can_delete(self, user, catalog, schema, table):
        self._check("delete", user, catalog, schema, table)


class GrantBasedAccessControl(AccessControl):
    """Consults the engine-level grant store maintained by GRANT/REVOKE/
    DENY statements (catalog.CatalogManager.grants). Superusers bypass;
    DENY beats GRANT (reference: connector grant semantics +
    io.trino.spi.security.Privilege)."""

    def __init__(self, catalogs, superusers=("admin",)):
        self.catalogs = catalogs
        self.superusers = set(superusers)

    def _check(self, privilege: str, user: str, catalog: str,
               schema: str, table: str) -> None:
        if user in self.superusers:
            return
        key = (user, privilege, catalog, schema, table)
        if key in self.catalogs.denies:
            raise AccessDeniedError(
                f"Cannot {privilege} table "
                f"{catalog}.{schema}.{table} as user {user}")
        if key in self.catalogs.grants:
            return
        raise AccessDeniedError(
            f"Cannot {privilege} table {catalog}.{schema}.{table} "
            f"as user {user}")

    def check_can_select(self, user, catalog, schema, table):
        self._check("select", user, catalog, schema, table)

    def check_can_insert(self, user, catalog, schema, table):
        self._check("insert", user, catalog, schema, table)

    def check_can_delete(self, user, catalog, schema, table):
        self._check("delete", user, catalog, schema, table)

    def check_can_update(self, user, catalog, schema, table):
        self._check("update", user, catalog, schema, table)

    def check_can_create_table(self, user, catalog, schema, table):
        if user not in self.superusers:
            raise AccessDeniedError(
                f"Cannot create table {catalog}.{schema}.{table} "
                f"as user {user}")

    def check_can_drop_table(self, user, catalog, schema, table):
        if user not in self.superusers:
            raise AccessDeniedError(
                f"Cannot drop table {catalog}.{schema}.{table} "
                f"as user {user}")


class TokenAuthenticator:
    """Bearer-token authentication (spi: the Authenticator family —
    server/security/jwt/JwtAuthenticator.java).
    ``authenticate_token(token)`` returns the principal or None."""

    def authenticate_token(self, token: str):
        raise NotImplementedError


class JwtAuthenticator(TokenAuthenticator):
    """HS256 JWT validation on a shared secret
    (http-server.authentication.jwt with a symmetric key):
    signature check, ``exp`` enforcement, principal from the
    ``principal_field`` claim (default ``sub``)."""

    def __init__(self, secret: bytes, principal_field: str = "sub",
                 required_audience: Optional[str] = None,
                 required_issuer: Optional[str] = None,
                 require_exp: bool = True):
        self.secret = secret
        self.principal_field = principal_field
        self.required_audience = required_audience
        self.required_issuer = required_issuer
        # a token without exp can never age out, so a leaked one is a
        # permanent credential; reject by default (require_exp=False
        # restores the legacy accept-forever behavior for internal
        # mint-on-boot tokens)
        self.require_exp = require_exp

    @staticmethod
    def _b64url_decode(part: str) -> bytes:
        import base64
        pad = "=" * (-len(part) % 4)
        return base64.urlsafe_b64decode(part + pad)

    @staticmethod
    def _b64url_encode(raw: bytes) -> str:
        import base64
        return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()

    def sign(self, claims: dict) -> str:
        """Mint a token (test harness / internal-node auth helper —
        InternalAuthenticationManager mints its own JWTs the same
        way)."""
        import json as _json
        header = self._b64url_encode(
            _json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        body = self._b64url_encode(_json.dumps(claims).encode())
        signing_input = f"{header}.{body}".encode()
        sig = hmac.new(self.secret, signing_input,
                       hashlib.sha256).digest()
        return f"{header}.{body}.{self._b64url_encode(sig)}"

    def authenticate_token(self, token: str):
        import json as _json
        import time as _time
        try:
            header_b64, body_b64, sig_b64 = token.split(".")
            header = _json.loads(self._b64url_decode(header_b64))
            if header.get("alg") != "HS256":
                return None          # alg confusion is an instant reject
            signing_input = f"{header_b64}.{body_b64}".encode()
            want = hmac.new(self.secret, signing_input,
                            hashlib.sha256).digest()
            if not hmac.compare_digest(want,
                                       self._b64url_decode(sig_b64)):
                return None
            claims = _json.loads(self._b64url_decode(body_b64))
            if not isinstance(claims, dict):
                return None
            exp = claims.get("exp")
            if exp is None:
                if self.require_exp:
                    return None
            elif _time.time() > float(exp):
                return None
            nbf = claims.get("nbf")
            if nbf is not None and _time.time() < float(nbf):
                return None          # not yet valid (RFC 7519 4.1.5)
            if self.required_issuer is not None \
                    and claims.get("iss") != self.required_issuer:
                return None
            if self.required_audience is not None:
                aud = claims.get("aud")
                auds = aud if isinstance(aud, list) else [aud]
                if self.required_audience not in auds:
                    return None
            principal = claims.get(self.principal_field)
            return (principal if isinstance(principal, str)
                    else None)
        except Exception:    # malformed token or odd claim shapes
            return None
