"""TupleDomain predicate/domain model + DomainTranslator.

Reference parity: core/trino-spi/.../predicate/ (TupleDomain.java,
Domain.java, ValueSet / SortedRangeSet / EquatableValueSet, Range) and
sql/planner/DomainTranslator.java. This is the currency of predicate
pushdown: the optimizer turns filter conjuncts into a TupleDomain over
connector columns, offers it to the connector (applyFilter —
spi ConnectorMetadata.applyFilter), and connectors prune rows/splits.

TPU-first note: a Domain compiles to a vectorized numpy/jnp mask
(``mask_for``) so connectors prune whole column lanes at generation
time — no per-row interpretation."""

from __future__ import annotations

from dataclasses import dataclass, field, replace as dc_replace
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .rex import Call, Const, InputRef, RowExpr, split_conjuncts
from .types import Type


@dataclass(frozen=True)
class Range:
    """One interval of an orderable type (spi/predicate/Range.java).
    ``low``/``high`` of None mean unbounded. For a point value use
    low == high with both bounds inclusive."""
    low: Optional[object] = None
    low_inclusive: bool = False
    high: Optional[object] = None
    high_inclusive: bool = False

    def is_point(self) -> bool:
        return (self.low is not None and self.low == self.high
                and self.low_inclusive and self.high_inclusive)

    def overlaps_or_adjacent(self, other: "Range") -> bool:
        a, b = (self, other) if _le_low(self, other) else (other, self)
        if a.high is None or b.low is None:
            return True
        if a.high > b.low:
            return True
        if a.high == b.low:
            return a.high_inclusive or b.low_inclusive
        return False

    def merge(self, other: "Range") -> "Range":
        lo, li = _min_low(self, other)
        hi, hc = _max_high(self, other)
        return Range(lo, li, hi, hc)

    def intersect(self, other: "Range") -> Optional["Range"]:
        lo, li = _max_low(self, other)
        hi, hc = _min_high(self, other)
        if lo is not None and hi is not None:
            if lo > hi or (lo == hi and not (li and hc)):
                return None
        return Range(lo, li, hi, hc)


def _le_low(a: Range, b: Range) -> bool:
    if a.low is None:
        return True
    if b.low is None:
        return False
    if a.low != b.low:
        return a.low < b.low
    return a.low_inclusive >= b.low_inclusive


def _min_low(a: Range, b: Range):
    if a.low is None or b.low is None:
        return None, False
    if a.low < b.low:
        return a.low, a.low_inclusive
    if b.low < a.low:
        return b.low, b.low_inclusive
    return a.low, a.low_inclusive or b.low_inclusive


def _max_low(a: Range, b: Range):
    if a.low is None:
        return b.low, b.low_inclusive
    if b.low is None:
        return a.low, a.low_inclusive
    if a.low > b.low:
        return a.low, a.low_inclusive
    if b.low > a.low:
        return b.low, b.low_inclusive
    return a.low, a.low_inclusive and b.low_inclusive


def _max_high(a: Range, b: Range):
    if a.high is None or b.high is None:
        return None, False
    if a.high > b.high:
        return a.high, a.high_inclusive
    if b.high > a.high:
        return b.high, b.high_inclusive
    return a.high, a.high_inclusive or b.high_inclusive


def _min_high(a: Range, b: Range):
    if a.high is None:
        return b.high, b.high_inclusive
    if b.high is None:
        return a.high, a.high_inclusive
    if a.high < b.high:
        return a.high, a.high_inclusive
    if b.high < a.high:
        return b.high, b.high_inclusive
    return a.high, a.high_inclusive and b.high_inclusive


@dataclass(frozen=True)
class Domain:
    """Allowed values of one column (spi/predicate/Domain.java):
    a union of disjoint sorted ranges + whether NULL is allowed.
    ``is_all`` short-circuits the unconstrained domain."""
    type: Type
    ranges: Tuple[Range, ...] = ()
    null_allowed: bool = False
    is_all: bool = False

    # --- constructors ----------------------------------------------------
    @staticmethod
    def all(t: Type) -> "Domain":
        return Domain(t, (), True, True)

    @staticmethod
    def none(t: Type) -> "Domain":
        return Domain(t, (), False)

    @staticmethod
    def only_null(t: Type) -> "Domain":
        return Domain(t, (), True)

    @staticmethod
    def not_null(t: Type) -> "Domain":
        return Domain(t, (Range(),), False)

    @staticmethod
    def single(t: Type, value) -> "Domain":
        return Domain(t, (Range(value, True, value, True),), False)

    @staticmethod
    def in_values(t: Type, values: Sequence) -> "Domain":
        rs = tuple(Range(v, True, v, True)
                   for v in sorted(set(values)))
        return Domain(t, rs, False)

    @staticmethod
    def range(t: Type, low, low_inclusive, high,
              high_inclusive) -> "Domain":
        return Domain(t, (Range(low, low_inclusive, high,
                                high_inclusive),), False)

    # --- algebra ---------------------------------------------------------
    def is_none(self) -> bool:
        return not self.is_all and not self.ranges \
            and not self.null_allowed

    def intersect(self, other: "Domain") -> "Domain":
        if self.is_all:
            return other
        if other.is_all:
            return self
        out: List[Range] = []
        for a in self.ranges:
            for b in other.ranges:
                r = a.intersect(b)
                if r is not None:
                    out.append(r)
        return Domain(self.type, _normalize(out),
                      self.null_allowed and other.null_allowed)

    def union(self, other: "Domain") -> "Domain":
        if self.is_all or other.is_all:
            return Domain.all(self.type)
        return Domain(self.type,
                      _normalize(list(self.ranges) + list(other.ranges)),
                      self.null_allowed or other.null_allowed)

    def single_values(self) -> Optional[List[object]]:
        """All-point domain -> its values (connector IN pruning)."""
        if self.is_all or not all(r.is_point() for r in self.ranges):
            return None
        return [r.low for r in self.ranges]

    # --- vectorized evaluation ------------------------------------------
    def mask_for(self, data: np.ndarray,
                 valid: Optional[np.ndarray] = None,
                 decode=None) -> np.ndarray:
        """Boolean keep-mask over a column lane. ``decode`` maps lane
        values to domain-comparable values (dictionary codes ->
        strings); given as an array it is applied by gather."""
        if self.is_all:
            return np.ones(len(data), bool)
        vals = data
        if decode is not None:
            vals = decode(data)
        m = np.zeros(len(data), bool)
        for r in self.ranges:
            rm = np.ones(len(data), bool)
            if r.low is not None:
                rm &= (vals >= r.low) if r.low_inclusive \
                    else (vals > r.low)
            if r.high is not None:
                rm &= (vals <= r.high) if r.high_inclusive \
                    else (vals < r.high)
            m |= rm
        if valid is not None:
            m = np.where(valid, m, self.null_allowed)
        return m


def _normalize(ranges: List[Range]) -> Tuple[Range, ...]:
    """Sort + merge overlapping/adjacent ranges (SortedRangeSet)."""
    if not ranges:
        return ()
    rs = sorted(ranges, key=lambda r: (
        r.low is not None, r.low if r.low is not None else 0,
        not r.low_inclusive))
    out = [rs[0]]
    for r in rs[1:]:
        if out[-1].overlaps_or_adjacent(r):
            out[-1] = out[-1].merge(r)
        else:
            out.append(r)
    return tuple(out)


@dataclass(frozen=True)
class TupleDomain:
    """Conjunction of per-column Domains (spi/predicate/
    TupleDomain.java); ``is_none`` marks a contradiction (scan prunes to
    zero rows)."""
    domains: Tuple[Tuple[str, Domain], ...] = ()
    is_none: bool = False

    @staticmethod
    def all() -> "TupleDomain":
        return TupleDomain(())

    @staticmethod
    def none() -> "TupleDomain":
        return TupleDomain((), True)

    @staticmethod
    def of(domains: Dict[str, Domain]) -> "TupleDomain":
        for d in domains.values():
            if d.is_none():
                return TupleDomain.none()
        return TupleDomain(tuple(sorted(
            (k, v) for k, v in domains.items() if not v.is_all)))

    def as_dict(self) -> Dict[str, Domain]:
        return dict(self.domains)

    def is_all(self) -> bool:
        return not self.is_none and not self.domains

    def intersect(self, other: "TupleDomain") -> "TupleDomain":
        if self.is_none or other.is_none:
            return TupleDomain.none()
        out = self.as_dict()
        for col, dom in other.domains:
            out[col] = out[col].intersect(dom) if col in out else dom
        return TupleDomain.of(out)

    def domain(self, col: str) -> Optional[Domain]:
        return self.as_dict().get(col)

    def __str__(self):
        if self.is_none:
            return "NONE"
        if not self.domains:
            return "ALL"
        parts = []
        for col, d in self.domains:
            sv = d.single_values()
            if sv is not None and len(sv) <= 3:
                parts.append(f"{col} IN {sv}")
            else:
                parts.append(f"{col}:{len(d.ranges)} ranges")
        return ", ".join(parts)


def filter_batch_host(batch, constraint: Optional["TupleDomain"],
                      limit: Optional[int] = None):
    """Apply an accepted pushdown to a connector batch host-side:
    vectorized domain masks + row compaction (+ per-split limit). The
    enforcement half of applyFilter — connectors call this from
    read_split."""
    from .columnar import Batch, pad_batch
    from .config import capacity_for
    if constraint is not None and constraint.is_none:
        return Batch(batch.columns, 0)
    n = batch.num_rows_host()
    if constraint is None or constraint.is_all():
        if limit is not None and n > limit:
            return Batch(batch.columns, limit)
        return batch
    mask = np.ones(n, bool)
    for col, dom in constraint.domains:
        if col not in batch.columns:
            continue
        c = batch.columns[col]
        data = np.asarray(c.data)[:n]
        valid = None if c.valid is None else np.asarray(c.valid)[:n]
        decode = None
        if c.dictionary is not None:
            vals = c.dictionary.values.astype(str)
            decode = (lambda codes, vals=vals:
                      vals[np.clip(codes.astype(np.int64), 0,
                                   len(vals) - 1)])
        mask &= dom.mask_for(data, valid, decode)
    idx = np.nonzero(mask)[0]
    if limit is not None:
        idx = idx[:limit]
    from .exec.complex import _take_flat
    cols = {k: _take_flat(c, idx) for k, c in batch.columns.items()}
    out = Batch(cols, len(idx))
    return pad_batch(out, capacity_for(max(len(idx), 1), minimum=8))


# --------------------------------------------------------------------------
# DomainTranslator: rex conjuncts -> TupleDomain
# --------------------------------------------------------------------------

_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}


def _comparable_const(e: RowExpr):
    if isinstance(e, Const) and e.value is not None \
            and not isinstance(e.value, bool):
        return e.value
    return None


def extract_tuple_domain(predicate: Optional[RowExpr],
                         symbol_types: Dict[str, Type]):
    """Split a predicate into (TupleDomain over symbols, residual
    conjuncts that could not be translated) —
    sql/planner/DomainTranslator.fromPredicate."""
    domains: Dict[str, Domain] = {}
    residual: List[RowExpr] = []
    for conj in split_conjuncts(predicate):
        got = _translate_conjunct(conj, symbol_types)
        if got is None:
            residual.append(conj)
        else:
            sym, dom = got
            domains[sym] = domains[sym].intersect(dom) \
                if sym in domains else dom
    return TupleDomain.of(domains), residual


def _translate_conjunct(e: RowExpr, types: Dict[str, Type]):
    if not isinstance(e, Call):
        return None
    if e.fn in ("=", "<", "<=", ">", ">=") and len(e.args) == 2:
        a, b = e.args
        op = e.fn
        if isinstance(b, InputRef) and not isinstance(a, InputRef):
            a, b = b, a
            op = _FLIP.get(op, op)
        if not (isinstance(a, InputRef) and a.name in types):
            return None
        v = _comparable_const(b)
        if v is None:
            return None
        t = types[a.name]
        if op == "=":
            return a.name, Domain.single(t, v)
        if op == "<":
            return a.name, Domain.range(t, None, False, v, False)
        if op == "<=":
            return a.name, Domain.range(t, None, False, v, True)
        if op == ">":
            return a.name, Domain.range(t, v, False, None, False)
        return a.name, Domain.range(t, v, True, None, False)
    if e.fn == "is_null" and len(e.args) == 1 \
            and isinstance(e.args[0], InputRef) \
            and e.args[0].name in types:
        return e.args[0].name, Domain.only_null(types[e.args[0].name])
    if e.fn == "not" and len(e.args) == 1 \
            and isinstance(e.args[0], Call) \
            and e.args[0].fn == "is_null" \
            and isinstance(e.args[0].args[0], InputRef) \
            and e.args[0].args[0].name in types:
        name = e.args[0].args[0].name
        return name, Domain.not_null(types[name])
    if e.fn == "or":
        # OR of same-column translatable conjuncts -> domain union
        sides = []
        stack = [e]
        while stack:
            x = stack.pop()
            if isinstance(x, Call) and x.fn == "or":
                stack.extend(x.args)
            else:
                sides.append(x)
        got = [_translate_conjunct(s, types) for s in sides]
        if any(g is None for g in got):
            return None
        syms = {g[0] for g in got}
        if len(syms) != 1:
            return None
        sym = syms.pop()
        dom = got[0][1]
        for _, d in got[1:]:
            dom = dom.union(d)
        return sym, dom
    if e.fn == "in_list" and e.args \
            and isinstance(e.args[0], InputRef) \
            and e.args[0].name in types:
        vals = [_comparable_const(a) for a in e.args[1:]]
        if any(v is None for v in vals):
            return None
        return e.args[0].name, Domain.in_values(
            types[e.args[0].name], vals)
    return None
