"""Interactive SQL console.

Reference parity: client/trino-cli (Console.java, QueryRunner,
StatusPrinter, aligned output) — a readline REPL over StatementClient,
or directly over an in-process LocalQueryRunner with --local.

Usage:
    python -m trino_tpu.cli --local [--distributed]
    python -m trino_tpu.cli --server http://127.0.0.1:8080
"""

from __future__ import annotations

import argparse
import sys
import time


def _render(columns, rows, elapsed_s: float) -> str:
    if not columns:
        return ""
    cells = [[("NULL" if v is None else str(v)) for v in row]
             for row in rows]
    widths = [max([len(c)] + [len(r[i]) for r in cells])
              for i, c in enumerate(columns)]
    sep = "-+-".join("-" * w for w in widths)
    out = [" | ".join(c.ljust(w) for c, w in zip(columns, widths)), sep]
    for r in cells:
        out.append(" | ".join(v.ljust(w) for v, w in zip(r, widths)))
    out.append(f"({len(rows)} row{'s' if len(rows) != 1 else ''}, "
               f"{elapsed_s:.2f}s)")
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="trino-tpu")
    ap.add_argument("--server", default=None,
                    help="coordinator URI (client mode)")
    ap.add_argument("--local", action="store_true",
                    help="run the engine in-process")
    ap.add_argument("--distributed", action="store_true",
                    help="in-process engine over the device mesh")
    ap.add_argument("--catalog", default="tpch")
    ap.add_argument("--schema", default="tiny")
    ap.add_argument("--execute", "-e", default=None,
                    help="run one statement and exit")
    args = ap.parse_args(argv)

    if args.server:
        from .client import ClientError, StatementClient
        client = StatementClient(args.server, catalog=args.catalog,
                                 schema=args.schema)

        def run(sql):
            t0 = time.time()
            res = client.execute(sql)
            if res.update_type:
                n = f" ({res.update_count} rows)" \
                    if res.update_count is not None else ""
                return f"{res.update_type}{n}"
            return _render(res.column_names, res.rows, time.time() - t0)
        errtype = ClientError
    else:
        from .exec import QueryError
        from .runner import LocalQueryRunner
        from .session import Session
        runner = LocalQueryRunner(
            session=Session(catalog=args.catalog, schema=args.schema),
            distributed=args.distributed)

        def run(sql):
            t0 = time.time()
            res = runner.execute(sql)
            if res.update_type:
                n = f" ({res.update_count} rows)" \
                    if res.update_count is not None else ""
                return f"{res.update_type}{n}"
            return _render(res.columns, res.rows, time.time() - t0)
        errtype = QueryError

    if args.execute:
        try:
            print(run(args.execute))
            return 0
        except errtype as e:
            print(f"Query failed: {e}", file=sys.stderr)
            return 1

    print("trino-tpu console (quit/exit to leave)")
    buf = []
    while True:
        try:
            line = input("trino-tpu> " if not buf else "        -> ")
        except EOFError:
            break
        except KeyboardInterrupt:
            print()
            buf = []      # abandon the half-typed statement
            continue
        if not buf and line.strip().lower() in ("quit", "exit"):
            break
        buf.append(line)
        if line.rstrip().endswith(";") or (len(buf) == 1
                                           and not line.strip()):
            sql = "\n".join(buf).strip().rstrip(";")
            buf = []
            if not sql:
                continue
            try:
                print(run(sql))
            except errtype as e:
                print(f"Query failed: {e}", file=sys.stderr)
            except KeyboardInterrupt:
                print("(interrupted)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
