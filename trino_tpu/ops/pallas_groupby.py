"""Pallas TPU kernel: fused small-domain grouped sums/counts.

Reference parity: the hot loop of HashAggregationOperator
(operator/HashAggregationOperator.java:381-413) for low-cardinality
GROUP BY — the q1 shape. The XLA fallback in ops/groupby.py
(_masked_agg) lowers every (group, aggregate) pair to its own masked
reduction, i.e. up to nseg x K passes over the value lanes. This kernel
does ONE pass over HBM: per row-block, a one-hot [B, G] matrix is
built from the packed group ids and every aggregate lane is reduced
with a single [K, B] x [B, G] matmul on the MXU, accumulating per-block
partials that are combined in f64 outside the kernel.

f64 strategy (the TPU MXU is f32): each f64 lane is split into THREE
f32 lanes — two 12-bit fixed-point digit lanes (integers scaled by the
lane's power-of-2 magnitude, so block sums of <= 512 values stay below
2^24 and are EXACT in f32) plus a tiny residual lane (|r| <= 2^-25 of
the lane magnitude, whose own f32 accumulation error is ~2^-49
relative). The three per-group sums recombine in f64 afterwards, so
the result matches a pure-f64 reduction to ~1e-14 relative — naive
f32 one-hot matmuls lose ~1e-4 at money-like magnitudes (measured),
which SQL aggregate tolerances cannot absorb. Counts are exact.

Gating: used on the TPU backend (or when TRINO_TPU_PALLAS=interpret,
which runs the kernel in interpreter mode — how the CPU test suite
exercises it). Kinds beyond sum/count keep the XLA path; exact-sum
types (DECIMAL, wide ints) also stay on the XLA path.
"""

from __future__ import annotations

import os
from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

BLOCK = 512
G_PAD = 128          # one-hot width: MXU-friendly and >= FAST_DOMAIN+1


_TPU_OK: list = []          # memoized probe result


def _tpu_kernel_works() -> bool:
    """One-time probe: some TPU attachments (e.g. remote-compile
    tunnels) cannot lower Mosaic kernels even though the backend
    reports 'tpu'; compile a trivial kernel once and fall back to the
    XLA path if that fails."""
    if not _TPU_OK:
        try:
            gid = jnp.zeros((1024,), jnp.int32)
            vals = jnp.ones((8, 1024), jnp.float32)
            out = _grouped_sums_impl(gid, vals, False)
            _TPU_OK.append(bool(out[0, 0] == 1024.0))
        except Exception:
            _TPU_OK.append(False)
    return _TPU_OK[0]


def mode() -> str:
    """'tpu' (real kernel), 'interpret' (forced, for CPU tests), or
    '' (disabled)."""
    env = os.environ.get("TRINO_TPU_PALLAS", "auto")
    if env == "0":
        return ""
    if env == "interpret":
        return "interpret"
    if env in ("auto", "1"):
        try:
            if jax.default_backend() != "tpu":
                return ""
            return "tpu" if _tpu_kernel_works() else ""
        except Exception:
            return ""
    return ""


def _kernel(gid_ref, vals_ref, out_ref):
    g = gid_ref[:]                                   # [B] int32
    b = g.shape[0]
    onehot = (g[:, None] == jax.lax.broadcasted_iota(
        jnp.int32, (b, G_PAD), 1)).astype(jnp.float32)
    out_ref[0] = jax.lax.dot_general(
        vals_ref[:], onehot,                         # [K, B] x [B, G]
        dimension_numbers=(((1,), (0,)), ((), ())),
        # HIGHEST = true-f32 matmul (bf16 multi-pass decomposition on
        # the MXU); the default TPU bf16 path rounds the 12-bit digit
        # lanes and breaks the exact-sum design (measured 2e-4)
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)          # [K, G_PAD]


@partial(jax.jit, static_argnames=("interpret",))
def _grouped_sums_impl(gid: jax.Array, vals: jax.Array,
                       interpret: bool) -> jax.Array:
    """vals [K, cap] f32 -> f64 [K, G_PAD] per-group sums."""
    from jax.experimental import pallas as pl
    k, cap = vals.shape
    b = min(BLOCK, cap)
    nblocks = cap // b
    partials = pl.pallas_call(
        _kernel,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((b,), lambda i: (i,)),
            pl.BlockSpec((k, b), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, k, G_PAD), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nblocks, k, G_PAD),
                                       jnp.float32),
        interpret=interpret,
    )(gid, vals)
    return jnp.sum(partials.astype(jnp.float64), axis=0)


def grouped_sums(gid: jax.Array, lanes: Sequence[jax.Array],
                 nseg: int, interpret: bool = False) -> List[jax.Array]:
    """Per-group f64 sums for every lane.

    ``gid``: int32 [cap] packed group ids; rows to exclude from ALL
    lanes must carry an id >= G_PAD (they one-hot to zero). Per-lane
    exclusion is the caller's job (zero the lane entry — exact for
    sums). Returns one f64 [nseg] array per input lane.
    """
    assert nseg <= G_PAD
    cols: List[jax.Array] = []
    splits: List[Tuple[int, int, jax.Array]] = []  # (a_idx, scale)
    for lane in lanes:
        f = jnp.asarray(lane).astype(jnp.float64)
        # power-of-2 magnitude scale; digits a (top 12 bits), b (next
        # 12), residual r — a/b sums are exact in f32 (<= 2^21 per
        # 512-row block), r is ~2^-25 of the magnitude
        maxabs = jnp.max(jnp.abs(f))
        s = jnp.exp2(jnp.ceil(jnp.log2(jnp.maximum(maxabs, 1e-300))))
        s = jnp.where(maxabs > 0, s, 1.0)
        a = jnp.round(f / s * 4096.0)
        r1 = f - a * (s / 4096.0)
        b = jnp.round(r1 / s * 16777216.0)
        r2 = r1 - b * (s / 16777216.0)
        splits.append((len(cols), s))
        cols.extend([a.astype(jnp.float32), b.astype(jnp.float32),
                     r2.astype(jnp.float32)])
    k8 = max(8, -(-len(cols) // 8) * 8)  # sublane-friendly row count
    while len(cols) < k8:
        cols.append(jnp.zeros_like(cols[0]))
    vals = jnp.stack(cols, axis=0)       # [K8, cap] f32
    sums = _grouped_sums_impl(jnp.asarray(gid, jnp.int32), vals,
                              interpret)
    return [sums[i, :nseg] * (s / 4096.0)
            + sums[i + 1, :nseg] * (s / 16777216.0)
            + sums[i + 2, :nseg]
            for i, s in splits]
