"""Device sort / TopN — OrderByOperator and TopNOperator, TPU style.

Reference parity: operator/OrderByOperator.java (PagesIndex sort),
operator/TopNOperator.java, util/MergeSortedPages for distributed sort.
On TPU, multi-key ordering is a single ``jnp.lexsort`` over order-preserving
uint64 key lanes — sorting networks map well onto the VPU, and one fused
sort replaces the row-at-a-time comparator Trino generates via
OrderingCompiler (sql/gen/OrderingCompiler.java).

Per sort key we emit a small tuple of comparable lanes (rather than one
packed uint64 — the TPU backend's x64 emulation cannot bitcast f64 lanes):
a null-ordering lane, for floats a NaN lane, then the value lane (negated /
complemented for DESC). A leading liveness lane pushes dead rows past the
end. ``jnp.lexsort`` over the lane list realizes the full ORDER BY.

Trino default null ordering: nulls are largest (ASC -> last, DESC -> first;
reference: sql/tree/SortItem.java UNDEFINED + SortOrder.ASC_NULLS_LAST).
Float total order: NaN is largest (reference: spi/type/DoubleType.java
comparison via Double.compare).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar import Batch, Column
from ..types import is_string


@dataclass(frozen=True)
class SortKey:
    column: str
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None -> Trino default (nulls = max)

    def resolved_nulls_first(self) -> bool:
        if self.nulls_first is not None:
            return self.nulls_first
        return not self.ascending  # nulls largest


def _key_lanes_for(col: Column, asc: bool, nulls_first: bool,
                   live: jax.Array) -> List[jax.Array]:
    d = jnp.asarray(col.data)
    lanes: List[jax.Array] = []

    # null-ordering lane: 0 sorts first
    is_null = (~col.valid_mask()) & live
    lanes.append(jnp.where(is_null, 0 if nulls_first else 1,
                           1 if nulls_first else 0).astype(jnp.int32))

    if is_string(col.type):
        ranks = jnp.asarray(col.dictionary.rank_codes())
        v = jnp.take(ranks, jnp.clip(d, 0, max(len(ranks) - 1, 0)),
                     mode="clip").astype(jnp.int64)
        lanes.append(v if asc else -v)
    elif d.dtype in (jnp.float32, jnp.float64):
        f = d.astype(jnp.float64)
        nan = jnp.isnan(f)
        lanes.append(jnp.where(nan, 1 if asc else 0,
                               0 if asc else 1).astype(jnp.int32))
        v = jnp.where(nan, 0.0, f)
        lanes.append(v if asc else -v)
    elif d.dtype == jnp.bool_:
        v = d.astype(jnp.int32)
        lanes.append(v if asc else 1 - v)
    else:
        v = d.astype(jnp.int64)
        lanes.append(v if asc else jnp.bitwise_not(v))
    # neutralize null rows' value lanes so null ordering is decided solely
    # by the null lane (keeps lexsort stable among nulls)
    lanes[1:] = [jnp.where(is_null, jnp.zeros_like(l), l)
                 for l in lanes[1:]]
    return lanes


def sort_lanes(batch: Batch, keys: Sequence[SortKey]) -> List[jax.Array]:
    """Lane list, most-significant first: liveness, then per-key lanes."""
    live = batch.row_valid()
    lanes: List[jax.Array] = [(~live).astype(jnp.int32)]
    for k in keys:
        col = batch.column(k.column)
        lanes.extend(_key_lanes_for(col, k.ascending,
                                    k.resolved_nulls_first(), live))
    return lanes


def sort_order(batch: Batch, keys: Sequence[SortKey]) -> jax.Array:
    """Stable permutation realizing ORDER BY."""
    lanes = sort_lanes(batch, keys)
    # jnp.lexsort: last key is primary -> reverse
    return jnp.lexsort(lanes[::-1])


def sort_batch(batch: Batch, keys: Sequence[SortKey]) -> Batch:
    order = sort_order(batch, keys)
    return batch.gather(order, batch.num_rows)


def topn_batch(batch: Batch, keys: Sequence[SortKey], n: int) -> Batch:
    """ORDER BY ... LIMIT n. Full device sort then truncate — on TPU the
    bitonic sort is bandwidth-bound and cheap relative to a heap emulation
    (reference: operator/TopNOperator.java uses a row heap; anti-pattern
    under SIMD)."""
    sorted_batch = sort_batch(batch, keys)
    count = jnp.minimum(sorted_batch.num_rows_device(),
                        jnp.asarray(n, dtype=jnp.int64))
    return Batch(sorted_batch.columns, count)
