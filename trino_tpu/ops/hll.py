"""HyperLogLog sketches, TPU style.

Reference parity: Trino's HyperLogLog type + approx_set / merge /
cardinality surface (core/trino-main/.../operator/aggregation/
ApproximateSetAggregation.java, MergeHyperLogLogAggregation.java,
operator/scalar/HyperLogLogFunctions.java; the sketch itself lives in
airlift-stats). Redesigned for XLA instead of ported:

- A sketch is a SPARSE set of (bucket, rank) entries packed into one
  int32 lane (``bucket * 64 + rank``; ranks are <= 61 so 6 bits
  suffice). A column of sketches is stored like an ARRAY column:
  ``data`` = per-row start offset into the flat packed ``elements``
  lane, ``data2`` = per-row entry count. Buckets absent from the entry
  list have rank 0. This is the airlift SparseHll idea made primary —
  the dense register vector would cost ``groups x 2**bits`` HBM in a
  grouped aggregation, while sparse entries are bounded by the input
  row count and build with the same lexsort+segment machinery as every
  other grouped aggregate here (ops/groupby.py).
- Building per-group sketches: bucket/rank are pure VPU bit ops on the
  row hashes; one sort by (group, bucket) + segment-max dedups to
  per-(group, bucket) entries — no scatter matrix, static shapes.
- ``cardinality`` evaluates the standard HLL estimator (with the
  linear-counting small-range correction) per row from the entries via
  a cumulative-sum difference over the flat lane — O(entries + rows),
  jit-friendly, and safe when gathered rows alias the same entry span.
"""

from __future__ import annotations

import base64
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .hashing import lane_to_u64, mix64

# Trino's default approx_distinct standard error is 2.3% -> 2048 buckets
# (error ~= 1.0414 / sqrt(m)); approx_set's own default is 1.625% ->
# 4096 buckets (ApproximateSetAggregation.DEFAULT_STANDARD_ERROR).
DEFAULT_BUCKET_BITS = 11
APPROX_SET_BUCKET_BITS = 12
MIN_BUCKET_BITS = 4
MAX_BUCKET_BITS = 16

_RANK_BITS = 6          # packed entry = bucket * 64 + rank


def bucket_bits_for_error(e: float) -> int:
    """Bucket-count exponent for a requested max standard error
    (reference: ApproximateCountDistinctAggregation.standardErrorToBuckets)."""
    import math
    if not (0.0040625 <= e <= 0.26):
        raise ValueError(
            f"standard error must be in [0.0040625, 0.26]: {e}")
    m = (1.0414 / e) ** 2
    return max(MIN_BUCKET_BITS, min(MAX_BUCKET_BITS,
                                    int(math.ceil(math.log2(m)))))


def bucket_rank_lanes(data: jax.Array, b: int) -> Tuple[jax.Array, jax.Array]:
    """Per-row (bucket, rank) lanes from a value lane.

    bucket = top b bits of the 64-bit row hash; rank = number of leading
    zeros of the remaining 64-b bits, plus one (capped at 64-b+1 when
    the remainder is all zeros).
    """
    h = mix64(lane_to_u64(data))
    bu = jnp.uint64(b)
    bucket = (h >> (jnp.uint64(64) - bu)).astype(jnp.int32)
    w = (h << bu).astype(jnp.uint64)
    clz = jax.lax.clz(w.astype(jnp.int64)).astype(jnp.int32)
    rank = jnp.where(w == 0, jnp.int32(64 - b + 1),
                     jnp.minimum(clz, 64 - b) + 1)
    return bucket, rank


def grouped_sparse_hll(vals: jax.Array, valid: jax.Array, gid: jax.Array,
                       gcap: int, b: int):
    """Per-group sparse sketches from a (group-sorted) value lane.

    Returns (start, length, entries) lanes: ``start``/``length`` are
    (gcap,) int64, ``entries`` is a (cap,) int32 packed-entry lane whose
    first sum(length) positions are the group-major entry lists.
    """
    cap = vals.shape[0]
    m = 1 << b
    bucket, rank = bucket_rank_lanes(vals, b)
    key = gid.astype(jnp.int64) * m + bucket.astype(jnp.int64)
    skey = jnp.where(valid, key, jnp.int64(gcap) * m)  # sink invalid
    order = jnp.argsort(skey)
    k2 = jnp.take(skey, order)
    r2 = jnp.take(jnp.where(valid, rank, 0), order)
    v2 = jnp.take(valid, order)
    first = jnp.arange(cap) == 0
    boundary = v2 & ((k2 != jnp.roll(k2, 1)) | first)
    runid = jnp.clip(jnp.cumsum(boundary.astype(jnp.int64)) - 1,
                     0, cap - 1).astype(jnp.int32)
    run_rank = jax.ops.segment_max(jnp.where(v2, r2, 0), runid,
                                   num_segments=cap)
    run_rank = jnp.maximum(run_rank, 0)
    run_key = jax.ops.segment_max(jnp.where(v2, k2, jnp.int64(0)), runid,
                                  num_segments=cap)
    run_key = jnp.maximum(run_key, 0)
    run_bucket = (run_key % m).astype(jnp.int32)
    entries = run_bucket * (1 << _RANK_BITS) + run_rank.astype(jnp.int32)
    egid = jnp.clip(run_key // m, 0, gcap - 1).astype(jnp.int32)
    length = jax.ops.segment_sum(
        jnp.where(boundary, jnp.int64(1), jnp.int64(0)),
        jnp.clip(k2 // m, 0, gcap - 1).astype(jnp.int32),
        num_segments=gcap)
    start = jnp.cumsum(length) - length
    # zero out entries beyond the real run count so serialization of a
    # group whose span clips into garbage stays deterministic
    nruns = jnp.sum(boundary.astype(jnp.int64))
    entries = jnp.where(jnp.arange(cap) < nruns, entries, 0)
    del egid
    return start, length, entries


def estimate_from_sparse(start: jax.Array, length: jax.Array,
                         entries: jax.Array, b: int) -> jax.Array:
    """Per-row HLL estimates from sparse entry spans (Flajolet et al.
    2007 — the estimator airlift-stats' DenseHll uses, minus its bias
    tables). Linear counting below 2.5m, using the exact zero-register
    count m - length."""
    m = 1 << b
    ranks = (jnp.asarray(entries) % (1 << _RANK_BITS)).astype(jnp.float64)
    pow2 = jnp.exp2(-ranks)
    csum = jnp.concatenate([jnp.zeros((1,), jnp.float64),
                            jnp.cumsum(pow2)])
    s = jnp.asarray(start).astype(jnp.int64)
    ln = jnp.clip(jnp.asarray(length).astype(jnp.int64), 0, m)
    e_cap = entries.shape[0]
    lo = jnp.clip(s, 0, e_cap)
    hi = jnp.clip(s + ln, 0, e_cap)
    z_entries = jnp.take(csum, hi) - jnp.take(csum, lo)
    zeros = (m - ln).astype(jnp.float64)
    z = z_entries + zeros            # absent buckets contribute 2^-0
    alpha = (0.673 if m == 16 else 0.697 if m == 32
             else 0.709 if m == 64 else 0.7213 / (1.0 + 1.079 / m))
    raw = alpha * m * m / jnp.maximum(z, 1e-300)
    lin = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    use_lin = (raw <= 2.5 * m) & (zeros > 0)
    est = jnp.where(use_lin, lin, raw)
    return jnp.round(est).astype(jnp.int64)


def merge_sparse_host(starts: np.ndarray, lens: np.ndarray,
                      entries: np.ndarray, valid: np.ndarray,
                      gid: np.ndarray, gcap: int, b: int):
    """Per-group max-union of sketch rows (host numpy; merge runs over
    small post-aggregation batches). Returns (start, length, entries)
    with the same layout contract as grouped_sparse_hll."""
    m = 1 << b
    starts = np.asarray(starts, np.int64)
    lens = np.clip(np.where(valid, np.asarray(lens, np.int64), 0), 0, m)
    e_cap = entries.shape[0]
    starts = np.clip(starts, 0, max(e_cap - 1, 0))
    lens = np.minimum(lens, e_cap - starts)
    total = int(lens.sum())
    owner = np.repeat(np.arange(starts.shape[0], dtype=np.int64), lens)
    base = np.repeat(starts, lens)
    csum = np.concatenate([[0], np.cumsum(lens)])
    within = np.arange(total, dtype=np.int64) - np.repeat(csum[:-1], lens)
    ent = entries[base + within].astype(np.int64)
    ebkt = ent >> _RANK_BITS
    ernk = ent & ((1 << _RANK_BITS) - 1)
    egid = np.asarray(gid, np.int64)[owner]
    key = egid * m + ebkt
    order = np.lexsort((-ernk, key))
    k2, r2 = key[order], ernk[order]
    boundary = np.ones(total, bool)
    boundary[1:] = k2[1:] != k2[:-1]
    out_key = k2[boundary]
    out_rank = r2[boundary]          # max rank: sorted desc within key
    out_gid = out_key // m
    out_bucket = out_key % m
    out_entries = (out_bucket << _RANK_BITS | out_rank).astype(np.int32)
    length = np.bincount(out_gid, minlength=gcap).astype(np.int64)
    start = np.cumsum(length) - length
    return start, length, out_entries


# --- wire format (cast(hll as varbinary) and client rendering) -----------

_MAGIC = b"TPUHLL1\x00"


def dense_registers(entries: np.ndarray, b: int) -> np.ndarray:
    """Dense m-register vector from one sketch's packed entries."""
    m = 1 << b
    regs = np.zeros(m, np.uint8)
    ent = np.asarray(entries, np.int64)
    regs[(ent >> _RANK_BITS) & (m - 1)] = ent & ((1 << _RANK_BITS) - 1)
    return regs


def entries_from_dense(regs: np.ndarray) -> np.ndarray:
    """Packed sparse entries (bucket-ascending) from dense registers."""
    regs = np.asarray(regs)
    nz = np.flatnonzero(regs)
    return (nz.astype(np.int64) << _RANK_BITS
            | regs[nz].astype(np.int64)).astype(np.int32)


def serialize_registers(regs: np.ndarray) -> bytes:
    """8-byte magic + 1-byte bucket bits + m dense uint8 registers."""
    regs = np.asarray(regs, dtype=np.uint8)
    m = regs.shape[-1]
    b = int(m).bit_length() - 1
    return _MAGIC + bytes([b]) + regs.tobytes()


def deserialize_registers(raw: bytes) -> np.ndarray:
    if raw[:8] != _MAGIC:
        raise ValueError("not a serialized HyperLogLog sketch")
    b = raw[8]
    m = 1 << b
    regs = np.frombuffer(raw[9:9 + m], dtype=np.uint8)
    if regs.shape[0] != m:
        raise ValueError("truncated HyperLogLog sketch")
    return regs


def sketches_to_base64(starts: np.ndarray, lens: np.ndarray,
                       entries: np.ndarray, b: int):
    """Per-row base64 wire strings for a sparse sketch column; the ONE
    rendering used by both cast(hll as varbinary) and client result
    encoding. Encodes each distinct (start, len) span once."""
    m = 1 << b
    e_cap = int(np.asarray(entries).shape[0])
    starts = np.clip(np.asarray(starts, np.int64), 0, max(e_cap, 1))
    lens = np.clip(np.asarray(lens, np.int64), 0, m)
    lens = np.minimum(lens, e_cap - starts)
    spans = np.stack([starts, lens], axis=1)
    uniq, inverse = np.unique(spans, axis=0, return_inverse=True)
    encoded = []
    for p, ln in uniq:
        regs = dense_registers(entries[int(p):int(p) + int(ln)], b)
        encoded.append(base64.b64encode(
            serialize_registers(regs)).decode())
    return [encoded[i] for i in inverse]
