"""Host-side collection aggregates: map_union, multimap_agg,
numeric_histogram.

Reference parity: operator/aggregation/MapUnionAggregation.java,
MultimapAggregationFunction.java, NumericHistogramAggregation.java +
NumericHistogram.java.

These aggregates build per-group variable-length nested structures whose
entry counts are data-dependent twice over (rows per group x entries per
row) — the capacity-planning cost of keeping them on device exceeds the
win, and like merge(hll) they typically consume small pre-aggregated
batches. They run on host numpy over fetched lanes (the hll_merge
pattern, ops/groupby.py); the chain-JIT executes aggregation nodes
eagerly so the host round-trip is legal.

Entry selection is done by INDEX into the flat element pools, then the
output pools are built with Column.gather — so nested element types
(dictionary strings, decimals, arrays) ride along without per-type host
code.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column
from ..config import capacity_for
from ..types import DOUBLE, MapType, ArrayType

__all__ = ["rows_by_group", "grouped_map_union", "grouped_multimap_agg",
           "grouped_numeric_histogram"]


def rows_by_group(order, gid, valid_s, gcap: int) -> List[np.ndarray]:
    """Original-row indices per group, in group-sorted row order.
    ``order``/``gid``/``valid_s`` are the group-sorted lanes of
    ops/groupby.py (valid_s = live & input-valid & FILTER mask)."""
    order = np.asarray(jax.device_get(order))
    gid = np.asarray(jax.device_get(gid))
    valid_s = np.asarray(jax.device_get(valid_s))
    keep = valid_s & (gid >= 0) & (gid < gcap)
    g = gid[keep]
    rows = order[keep].astype(np.int64)
    # stable sort by group keeps rows in group-sorted row order, then
    # one split at the group boundaries — no per-row python loop (an
    # 8M-capacity batch spends seconds in the interpreter otherwise)
    perm = np.argsort(g, kind="stable")
    g = g[perm]
    rows = rows[perm]
    bounds = np.searchsorted(g, np.arange(gcap + 1))
    return [rows[bounds[i]:bounds[i + 1]] for i in range(gcap)]


def _entry_key_fn(col: Column):
    """Host equality key for one flat pool position."""
    data = np.asarray(jax.device_get(col.data))
    data2 = (None if col.data2 is None
             else np.asarray(jax.device_get(col.data2)))
    valid = (None if col.valid is None
             else np.asarray(jax.device_get(col.valid)))

    def key(j: int):
        if valid is not None and not valid[j]:
            return (False, 0, 0)
        d2 = 0 if data2 is None else data2[j].item()
        return (True, data[j].item(), d2)
    return key


def _pool_gather(elem: Column, idx: np.ndarray) -> Column:
    cap = capacity_for(max(int(idx.shape[0]), 1))
    padded = np.zeros(cap, dtype=np.int64)
    padded[:idx.shape[0]] = idx
    return elem.gather(jnp.asarray(padded))


def grouped_map_union(col: Column, groups: List[np.ndarray],
                      group_valid) -> Column:
    """Per-group union of map entries; first occurrence of a key wins
    (reference MapUnionAggregation keeps the first seen value)."""
    starts = np.asarray(jax.device_get(col.data))
    lens = np.asarray(jax.device_get(col.data2))
    keyf = _entry_key_fn(col.elements)

    sel: List[int] = []
    out_start = np.zeros(len(groups), dtype=np.int64)
    out_len = np.zeros(len(groups), dtype=np.int64)
    for g, rows in enumerate(groups):
        out_start[g] = len(sel)
        seen = set()
        for r in rows:
            s, ln = int(starts[r]), int(lens[r])
            for j in range(s, s + ln):
                k = keyf(j)
                if k not in seen:
                    seen.add(k)
                    sel.append(j)
        out_len[g] = len(sel) - out_start[g]

    idx = np.asarray(sel, dtype=np.int64)
    return Column(col.type, jnp.asarray(out_start), group_valid, None,
                  jnp.asarray(out_len),
                  _pool_gather(col.elements, idx),
                  _pool_gather(col.elements2, idx))


def grouped_multimap_agg(kcol: Column, vcol: Column,
                         groups: List[np.ndarray], group_valid) -> Column:
    """multimap_agg(k, v) -> map(K, array(V)): per group, distinct keys
    in first-seen order, each mapped to the array of its values in row
    order (reference MultimapAggregationFunction; NULL values are
    collected, rows with NULL keys too — a NULL key is a key)."""
    keyf = _entry_key_fn(kcol)

    key_rows: List[int] = []      # one representative row per (g, key)
    val_rows: List[int] = []      # value pool rows, grouped by (g, key)
    arr_start: List[int] = []
    arr_len: List[int] = []
    out_start = np.zeros(len(groups), dtype=np.int64)
    out_len = np.zeros(len(groups), dtype=np.int64)
    for g, rows in enumerate(groups):
        out_start[g] = len(key_rows)
        order_keys: List[Tuple] = []
        per_key = {}
        for r in rows:
            k = keyf(int(r))
            if k not in per_key:
                per_key[k] = (int(r), [])
                order_keys.append(k)
            per_key[k][1].append(int(r))
        for k in order_keys:
            rep, vals = per_key[k]
            key_rows.append(rep)
            arr_start.append(len(val_rows))
            arr_len.append(len(vals))
            val_rows.extend(vals)
        out_len[g] = len(key_rows) - out_start[g]

    ecap = capacity_for(max(len(key_rows), 1))
    astart = np.zeros(ecap, dtype=np.int64)
    alen = np.zeros(ecap, dtype=np.int64)
    astart[:len(arr_start)] = arr_start
    alen[:len(arr_len)] = arr_len
    varr = Column(ArrayType(vcol.type), jnp.asarray(astart), None, None,
                  jnp.asarray(alen),
                  _pool_gather(vcol, np.asarray(val_rows, np.int64)))
    return Column(MapType(kcol.type, ArrayType(vcol.type)),
                  jnp.asarray(out_start), group_valid, None,
                  jnp.asarray(out_len),
                  _pool_gather(kcol, np.asarray(key_rows, np.int64)),
                  varr)


def _merge_histogram(values: np.ndarray, buckets: int,
                     weights: Optional[np.ndarray] = None):
    """Greedy adjacent-merge of sorted (x, w) pairs until <= buckets —
    the same centroid-merging idea as the reference's NumericHistogram
    (it merges the two closest buckets on overflow). Dedupe + weight
    accumulation here; the linked-list/heap merge loop is shared with
    the digest sketches (ops/digest.py _compress)."""
    from .digest import _compress
    if values.size == 0:
        return [], []
    if weights is None:
        xs, ws = np.unique(values, return_counts=True)
        ws = ws.astype(np.float64)
    else:
        xs, inv = np.unique(values, return_inverse=True)
        ws = np.zeros(xs.size, np.float64)
        np.add.at(ws, inv, weights.astype(np.float64))
    xs = xs.astype(np.float64)
    if xs.size <= buckets:
        return list(xs), list(ws)
    x, w = _compress(xs, ws, buckets)
    return list(x), list(w)


def grouped_numeric_histogram(col: Column, groups: List[np.ndarray],
                              group_valid, buckets: int,
                              scale: Optional[float] = None,
                              weight_col: Optional[Column] = None
                              ) -> Column:
    """numeric_histogram(buckets, v[, w]) -> map(double, double)."""
    data = np.asarray(jax.device_get(col.data)).astype(np.float64)
    if scale:
        data = data / scale
    wl = (None if weight_col is None
          else np.asarray(jax.device_get(weight_col.data))
          .astype(np.float64))
    keys: List[float] = []
    wts: List[float] = []
    out_start = np.zeros(len(groups), dtype=np.int64)
    out_len = np.zeros(len(groups), dtype=np.int64)
    for g, rows in enumerate(groups):
        out_start[g] = len(keys)
        xs, ws = _merge_histogram(data[rows], buckets,
                                  None if wl is None else wl[rows])
        keys.extend(xs)
        wts.extend(ws)
        out_len[g] = len(keys) - out_start[g]

    ecap = capacity_for(max(len(keys), 1))
    kd = np.zeros(ecap, dtype=np.float64)
    vd = np.zeros(ecap, dtype=np.float64)
    kd[:len(keys)] = keys
    vd[:len(wts)] = wts
    return Column(MapType(DOUBLE, DOUBLE), jnp.asarray(out_start),
                  group_valid, None, jnp.asarray(out_len),
                  Column(DOUBLE, jnp.asarray(kd)),
                  Column(DOUBLE, jnp.asarray(vd)))
