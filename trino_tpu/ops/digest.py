"""TDigest / QDigest quantile sketches.

Reference parity: operator/aggregation/TDigestAggregationFunction.java,
ApproximateLongPercentileAggregations (qdigest), operator/scalar/
{TDigestFunctions,QuantileDigestFunctions}.java; sketches live in
airlift-stats (TDigest.java, QuantileDigest.java).

Redesigned for this engine's columnar model instead of ported: a digest
column is ARRAY-shaped (``data`` = per-row start into flat centroid
lanes, ``data2`` = centroid count, ``elements`` = means, ``elements2`` =
weights). Centroids are kept sorted by mean. Building compresses by
greedy closest-pair merging (the same centroid-merge idea as t-digest,
uniform size bound rather than the quantile-dependent bound — both are
approximate sketches; accuracy is bounded by the centroid budget).
Like merge(hll), construction runs host-side: digests aggregate small
pre-reduced data and their entry counts are data-dependent twice over.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column
from ..config import capacity_for
from ..types import DOUBLE

DEFAULT_COMPRESSION = 100          # airlift TDigest default
DEFAULT_QDIGEST_BUDGET = 200       # ~ accuracy 0.01 -> 2/0.01 nodes


def _compress(means: np.ndarray, weights: np.ndarray,
              budget: int) -> Tuple[np.ndarray, np.ndarray]:
    """Merge closest adjacent centroids (weighted) until <= budget."""
    order = np.argsort(means, kind="stable")
    x = list(means[order].astype(np.float64))
    w = list(weights[order].astype(np.float64))
    n = len(x)
    if n <= budget:
        return np.asarray(x), np.asarray(w)
    prev = list(range(-1, n - 1))
    nxt = list(range(1, n + 1))
    alive = [True] * n
    heap = [(x[i + 1] - x[i], i, i + 1) for i in range(n - 1)]
    heapq.heapify(heap)
    remaining = n
    while remaining > budget and heap:
        _, i, j = heapq.heappop(heap)
        if not (alive[i] and alive[j]) or nxt[i] != j:
            continue
        tot = w[i] + w[j]
        x[i] = (x[i] * w[i] + x[j] * w[j]) / tot
        w[i] = tot
        alive[j] = False
        nxt[i] = nxt[j]
        if nxt[i] < n:
            prev[nxt[i]] = i
            heapq.heappush(heap, (x[nxt[i]] - x[i], i, nxt[i]))
        if prev[i] >= 0:
            heapq.heappush(heap, (x[i] - x[prev[i]], prev[i], i))
        remaining -= 1
    keep = [k for k in range(n) if alive[k]]
    return (np.asarray([x[k] for k in keep]),
            np.asarray([w[k] for k in keep]))


def grouped_digest(col: Column, groups: List[np.ndarray], group_valid,
                   out_type, budget: int,
                   weight_col: Optional[Column] = None,
                   scale: Optional[float] = None) -> Column:
    """Build one digest per group from a numeric lane (+ optional
    per-row weights)."""
    data = np.asarray(jax.device_get(col.data)).astype(np.float64)
    if scale:
        data = data / scale
    wl = (None if weight_col is None
          else np.asarray(jax.device_get(weight_col.data))
          .astype(np.float64))
    means: List[float] = []
    wts: List[float] = []
    start = np.zeros(len(groups), np.int64)
    length = np.zeros(len(groups), np.int64)
    for g, rows in enumerate(groups):
        start[g] = len(means)
        if rows.size:
            w = np.ones(rows.size) if wl is None else wl[rows]
            m, ww = _compress(data[rows], w, budget)
            means.extend(m)
            wts.extend(ww)
        length[g] = len(means) - start[g]
    cap = capacity_for(max(len(means), 1))
    md = np.zeros(cap, np.float64)
    wd = np.zeros(cap, np.float64)
    md[:len(means)] = means
    wd[:len(wts)] = wts
    return Column(out_type, jnp.asarray(start), group_valid, None,
                  jnp.asarray(length), Column(DOUBLE, jnp.asarray(md)),
                  Column(DOUBLE, jnp.asarray(wd)))


def grouped_digest_merge(col: Column, groups: List[np.ndarray],
                         group_valid, budget: int) -> Column:
    """merge(digest) per group: concatenate centroid runs, recompress."""
    starts = np.asarray(jax.device_get(col.data))
    lens = np.asarray(jax.device_get(col.data2))
    em = np.asarray(jax.device_get(col.elements.data)).astype(np.float64)
    ew = np.asarray(jax.device_get(col.elements2.data)).astype(np.float64)
    means: List[float] = []
    wts: List[float] = []
    start = np.zeros(len(groups), np.int64)
    length = np.zeros(len(groups), np.int64)
    for g, rows in enumerate(groups):
        start[g] = len(means)
        mm: List[float] = []
        ww: List[float] = []
        for r in rows:
            s, ln = int(starts[r]), int(lens[r])
            mm.extend(em[s:s + ln])
            ww.extend(ew[s:s + ln])
        if mm:
            m, w = _compress(np.asarray(mm), np.asarray(ww), budget)
            means.extend(m)
            wts.extend(w)
        length[g] = len(means) - start[g]
    cap = capacity_for(max(len(means), 1))
    md = np.zeros(cap, np.float64)
    wd = np.zeros(cap, np.float64)
    md[:len(means)] = means
    wd[:len(wts)] = wts
    return Column(col.type, jnp.asarray(start), group_valid, None,
                  jnp.asarray(length), Column(DOUBLE, jnp.asarray(md)),
                  Column(DOUBLE, jnp.asarray(wd)))


def digest_quantile(means: np.ndarray, weights: np.ndarray,
                    q: float) -> float:
    """Value at quantile from sorted centroids (airlift TDigest
    valueAt: piecewise over cumulative weights, midpoint convention)."""
    if means.size == 0:
        return float("nan")
    total = weights.sum()
    target = q * total
    cum = np.cumsum(weights) - weights / 2.0
    if target <= cum[0]:
        return float(means[0])
    if target >= cum[-1]:
        return float(means[-1])
    i = int(np.searchsorted(cum, target) - 1)
    span = cum[i + 1] - cum[i]
    frac = 0.0 if span <= 0 else (target - cum[i]) / span
    return float(means[i] + frac * (means[i + 1] - means[i]))


def digest_quantile_at_value(means: np.ndarray, weights: np.ndarray,
                             v: float) -> float:
    if means.size == 0:
        return float("nan")
    total = weights.sum()
    cum = np.cumsum(weights) - weights / 2.0
    if v <= means[0]:
        return 0.0
    if v >= means[-1]:
        return 1.0
    i = int(np.searchsorted(means, v) - 1)
    span = means[i + 1] - means[i]
    frac = 0.0 if span <= 0 else (v - means[i]) / span
    return float((cum[i] + frac * (cum[i + 1] - cum[i])) / total)


def sketches_to_base64(starts, lens, means, weights) -> List[str]:
    """Client rendering: base64 of a simple framing (count + f64 pairs) —
    the role of the reference's TDigest serialization."""
    import base64
    import struct
    out = []
    for i in range(len(starts)):
        s, ln = int(starts[i]), int(lens[i])
        buf = struct.pack("<q", ln)
        for j in range(s, s + ln):
            buf += struct.pack("<dd", float(means[j]), float(weights[j]))
        out.append(base64.b64encode(buf).decode())
    return out
