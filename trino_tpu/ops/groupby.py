"""Grouped aggregation — HashAggregationOperator, TPU style.

Reference parity: operator/HashAggregationOperator.java:49,381-413 with
MultiChannelGroupByHash.java:55 (open-addressing probe) and flat BigArray
accumulator state (operator/aggregation/, lib/trino-array). Redesign for
XLA (SURVEY.md §7.3): instead of a serial hash-probe loop, group rows by a
stable lexsort on the key lanes, derive segment ids from key-change
boundaries, and compute every accumulator with ``jax.ops.segment_*`` —
fully parallel, static shapes, no device hash table. Group cardinality is
data-dependent, so outputs are capacity-padded with a device num_groups.

Partial/final split (reference: AggregationNode PARTIAL/FINAL +
PushPartialAggregationThroughExchange rule) is expressed by running this
same kernel on partial states: every aggregate below declares a
``combine`` that is itself one of the supported segment ops.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import Batch, Column
from .hashing import equality_lanes

_U64MAX = jnp.uint64(0xFFFFFFFFFFFFFFFF)


@dataclass(frozen=True)
class AggInput:
    """One aggregate over one input lane (or none, for count(*))."""
    kind: str          # sum | count | count_star | min | max | any_value
                       # | argmin | argmax | count_distinct | percentile
    input: Optional[str] = None   # column name; None for count_star
    mask: Optional[str] = None    # FILTER / mask column (boolean), optional
    output: str = "agg"
    param: Optional[float] = None  # percentile fraction for 'percentile'
    input2: Optional[str] = None   # comparator lane for argmin/argmax


# kinds whose partials combine with another single-lane segment op —
# these support the PARTIAL -> exchange -> FINAL plan split (reference:
# PushPartialAggregationThroughExchange); the rest (argmin/argmax,
# count_distinct, percentile) need all rows of a group co-located, i.e.
# repartition-BEFORE-aggregate
COMBINABLE_KINDS = {"sum": "sum", "count": "sum", "count_star": "sum",
                    "min": "min", "max": "max", "any_value": "any_value",
                    "bit_and": "bit_and", "bit_or": "bit_or"}


def _key_lanes(batch: Batch, key_names: Sequence[str],
               live: Optional[jax.Array] = None) -> List[jax.Array]:
    """Exact equality-preserving lanes; a null is its own group value
    (SQL GROUP BY treats NULLs as equal), encoded via a validity lane."""
    live = batch.row_valid() if live is None else live
    lanes: List[jax.Array] = [(~live).astype(jnp.uint64)]
    for name in key_names:
        col = batch.column(name)
        col_lanes = equality_lanes(col.data)
        if col.data2 is not None and not str(col.type.name).endswith(
                "with time zone"):
            # Int128 high lane participates in key equality; a
            # TIMESTAMP WITH TIME ZONE's zone lane does NOT (equality
            # is instant-based, reference TimestampWithTimeZoneType)
            col_lanes = col_lanes + equality_lanes(col.data2)
        if col.valid is not None:
            v = jnp.asarray(col.valid)
            lanes.append((~v).astype(jnp.uint64))
            col_lanes = [jnp.where(v, u, jnp.zeros_like(u))
                         for u in col_lanes]
        col_lanes = [jnp.where(live, u, _U64MAX + jnp.zeros_like(u))
                     for u in col_lanes]
        lanes.extend(col_lanes)
    return lanes


def _string_minmax_lane(col: Column, vals: jax.Array, kind: str):
    """(rank lane, identity, decode) for MIN/MAX over a dictionary
    column: reduce over collation ranks, decode the winning rank back
    to a code (codes are insertion-ordered, not collation-ordered)."""
    ranks = col.dictionary.rank_codes()
    code_by_rank = jnp.asarray(_invert_permutation(ranks))
    rvals = jnp.take(jnp.asarray(ranks), vals, mode="clip")
    ident = jnp.asarray(len(ranks) if kind == "min" else -1, rvals.dtype)

    def decode(data):
        return jnp.take(code_by_rank, jnp.clip(data, 0, len(ranks) - 1),
                        mode="clip").astype(jnp.int32)
    return rvals, ident, decode


def _identity_for(kind: str, dtype) -> jax.Array:
    if dtype == jnp.bool_:
        return jnp.asarray(kind == "min", dtype)
    if kind == "min":
        if dtype in (jnp.float32, jnp.float64):
            return jnp.asarray(jnp.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).max, dtype)
    if kind == "max":
        if dtype in (jnp.float32, jnp.float64):
            return jnp.asarray(-jnp.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).min, dtype)
    return jnp.asarray(0, dtype)


# largest packed key-domain the unrolled masked-reduction kernel will
# take on; beyond this the lexsort path wins (graph size / compile time)
FAST_DOMAIN_LIMIT = 64

_FAST_KINDS = {"sum", "count", "count_star", "min", "max", "any_value",
               "bit_and", "bit_or"}


def _static_domain(col: Column) -> Optional[int]:
    """Statically-known value domain [0, d): dictionary code range or
    bool. None when unknown (general ints/floats)."""
    if col.dictionary is not None:
        return len(col.dictionary)
    if jnp.asarray(col.data).dtype == jnp.bool_:
        return 2
    return None


def _packed_group_aggregate(batch: Batch, key_names: Sequence[str],
                            aggs: Sequence[AggInput], gcap: int,
                            live: Optional[jax.Array] = None,
                            clamp: bool = False) -> Optional[Batch]:
    """Small-static-domain GROUP BY: one packed int32 group id per row,
    every aggregate an unrolled per-group masked reduction (VPU-friendly,
    single fused pass over HBM)."""
    doms: List[int] = []
    kcols: List[Column] = []
    if not key_names:
        return None
    for name in key_names:
        c = batch.column(name)
        d = _static_domain(c)
        if d is None or c.data2 is not None:
            return None
        doms.append(d)
        kcols.append(c)
    nseg = 1
    for d in doms:
        nseg *= d + 1          # one extra slot per key for NULL
    if nseg > FAST_DOMAIN_LIMIT or nseg > gcap:
        return None
    if any(a.kind not in _FAST_KINDS for a in aggs):
        return None
    if clamp:
        # The packed domain bounds the group count, so the output needs
        # at most nseg slots — NOT the input capacity the default gcap
        # inherits. Without this, a 6-group q1 aggregation emits 8M-row
        # output lanes and the downstream sort lexsorts 8M slots for 4
        # live rows (measured: ~20s of the sf1 engine path). Callers
        # that pass an explicit groups_capacity are asserting a shape
        # contract (distributed shard exchanges) — never clamp those.
        from ..config import capacity_for
        gcap = min(gcap, capacity_for(nseg, minimum=1))

    cap = batch.capacity
    if live is None:
        live = batch.row_valid()
    packed = jnp.zeros((cap,), jnp.int32)
    for c, d in zip(kcols, doms):
        code = jnp.asarray(c.data).astype(jnp.int32)
        code = jnp.clip(code, 0, d - 1)
        if c.valid is not None:
            code = jnp.where(jnp.asarray(c.valid), code, d)
        packed = packed * (d + 1) + code

    # Pallas fast path (TPU): one fused one-hot-matmul pass computes
    # every float sum + count; other kinds keep the masked reductions
    from . import pallas_groupby as _pg
    pmode = _pg.mode()
    pallas_res: Dict[str, Column] = {}
    rest: List[AggInput] = list(aggs)
    counts = None
    if pmode:
        pallas_res, rest, counts = _pallas_packed_aggs(
            batch, aggs, packed, live, nseg, pmode)
    gmasks = ([live & (packed == g) for g in range(nseg)]
              if (rest or counts is None) else [])
    if counts is None:
        counts = jnp.stack([jnp.sum(m.astype(jnp.int64))
                            for m in gmasks])

    out_cols: Dict[str, Column] = {}
    # key columns decoded from the group index (after compaction below)
    exists = counts > 0
    num_groups = jnp.sum(exists.astype(jnp.int64))
    gidx = jnp.nonzero(exists, size=gcap, fill_value=nseg)[0]

    rem = gidx
    for name, c, d in zip(reversed(key_names), reversed(kcols),
                          reversed(doms)):
        code = (rem % (d + 1)).astype(jnp.int32)
        rem = rem // (d + 1)
        is_null = code >= d
        data = jnp.clip(code, 0, d - 1)
        if jnp.asarray(c.data).dtype == jnp.bool_:
            data = data.astype(jnp.bool_)
        valid = ~is_null if c.valid is not None else None
        out_cols[name] = Column(c.type, data, valid, c.dictionary)
    out_cols = {k: out_cols[k] for k in key_names}

    gidx_c = jnp.clip(gidx, 0, nseg - 1)
    rest_set = {id(a) for a in rest}
    for agg in aggs:
        if id(agg) in rest_set:
            res = _masked_agg(batch, agg, gmasks, live, nseg)
        else:
            res = pallas_res[agg.output]
        out_cols[agg.output] = _compact_groups(res, gidx_c)

    return Batch(out_cols, num_groups)


def _agg_row_mask(batch: Batch, agg: AggInput,
                  live: jax.Array) -> jax.Array:
    m = live
    if agg.mask is not None:
        mcol = batch.column(agg.mask)
        mm = jnp.asarray(mcol.data).astype(bool)
        if mcol.valid is not None:
            mm = mm & jnp.asarray(mcol.valid)
        m = m & mm
    return m


def _pallas_packed_aggs(batch: Batch, aggs: Sequence[AggInput],
                        packed: jax.Array, live: jax.Array, nseg: int,
                        mode: str):
    """Route float sums and counts through the pallas grouped-sum
    kernel (ops/pallas_groupby.py). Returns (results by output name as
    [nseg] Columns, remaining aggs, per-group live counts)."""
    from ..types import BIGINT
    from . import pallas_groupby as _pg

    lanes: List[jax.Array] = [live.astype(jnp.float64)]
    plans = []          # (agg, kind, value_idx, count_idx, col)
    rest: List[AggInput] = []
    for agg in aggs:
        if agg.kind in ("count_star", "count"):
            m = _agg_row_mask(batch, agg, live)
            col = None
            if agg.kind == "count":
                col = batch.column(agg.input)
                if col.valid is not None:
                    m = m & jnp.asarray(col.valid)
            plans.append((agg, "count", len(lanes), None, col))
            lanes.append(m.astype(jnp.float64))
            continue
        if agg.kind == "sum":
            col = batch.column(agg.input)
            vals = jnp.asarray(col.data)
            if col.data2 is None and vals.dtype in (jnp.float32,
                                                    jnp.float64):
                m = _agg_row_mask(batch, agg, live)
                if col.valid is not None:
                    m = m & jnp.asarray(col.valid)
                plans.append((agg, "sum", len(lanes), len(lanes) + 1,
                              col))
                lanes.append(jnp.where(m, vals.astype(jnp.float64),
                                       0.0))
                lanes.append(m.astype(jnp.float64))
                continue
        rest.append(agg)
    if not plans:
        return {}, list(aggs), None

    gid = jnp.where(live, packed, _pg.G_PAD).astype(jnp.int32)
    outs = _pg.grouped_sums(gid, lanes, nseg,
                            interpret=(mode == "interpret"))
    counts = jnp.round(outs[0]).astype(jnp.int64)
    results: Dict[str, Column] = {}
    for agg, kind, vi, ci, col in plans:
        if kind == "count":
            results[agg.output] = Column(
                BIGINT, jnp.round(outs[vi]).astype(jnp.int64), None)
        else:
            nvalid = jnp.round(outs[ci]).astype(jnp.int64)
            data = outs[vi]
            if jnp.asarray(col.data).dtype == jnp.float32:
                data = data.astype(jnp.float32)
            results[agg.output] = Column(_sum_type(col.type), data,
                                         nvalid > 0)
    return results, rest, counts


def _compact_groups(col: Column, gidx: jax.Array) -> Column:
    from dataclasses import replace as _replace
    data = jnp.take(jnp.asarray(col.data), gidx, mode="clip")
    valid = (None if col.valid is None
             else jnp.take(jnp.asarray(col.valid), gidx, mode="clip"))
    data2 = (None if col.data2 is None
             else jnp.take(jnp.asarray(col.data2), gidx, mode="clip"))
    return _replace(col, data=data, valid=valid, data2=data2)


def _masked_agg(batch: Batch, agg: AggInput, gmasks, live,
                nseg: int) -> Column:
    """One aggregate as nseg masked reductions -> [nseg] arrays."""
    from ..types import BIGINT, is_string

    if agg.mask is not None:
        mcol = batch.column(agg.mask)
        m = jnp.asarray(mcol.data).astype(bool)
        if mcol.valid is not None:
            m = m & jnp.asarray(mcol.valid)
        gmasks = [g & m for g in gmasks]

    if agg.kind == "count_star":
        data = jnp.stack([jnp.sum(g.astype(jnp.int64)) for g in gmasks])
        return Column(BIGINT, data, None)

    col = batch.column(agg.input)
    vals = jnp.asarray(col.data)
    if col.valid is not None:
        v = jnp.asarray(col.valid)
        gmasks = [g & v for g in gmasks]

    if agg.kind == "count":
        data = jnp.stack([jnp.sum(g.astype(jnp.int64)) for g in gmasks])
        return Column(BIGINT, data, None)

    nvalid = jnp.stack([jnp.sum(g.astype(jnp.int64)) for g in gmasks])
    group_valid = nvalid > 0

    if _wide_decimal_agg(col, agg.kind):
        return _int128_masked_agg(col, agg.kind, gmasks, group_valid)

    if agg.kind == "sum":
        acc_dtype = vals.dtype if vals.dtype in (
            jnp.float32, jnp.float64) else jnp.int64
        av = vals.astype(acc_dtype)
        zero = jnp.asarray(0, acc_dtype)
        data = jnp.stack(
            [jnp.sum(jnp.where(g, av, zero)) for g in gmasks])
        return Column(_sum_type(col.type), data, group_valid)

    if agg.kind in ("bit_and", "bit_or"):
        op = jnp.bitwise_and if agg.kind == "bit_and" else jnp.bitwise_or
        ident = jnp.asarray(-1 if agg.kind == "bit_and" else 0, jnp.int64)
        work = vals.astype(jnp.int64)
        data = jnp.stack(
            [jax.lax.reduce(jnp.where(g, work, ident), ident,
                            op, (0,)) for g in gmasks])
        return Column(BIGINT, data, group_valid)

    if agg.kind in ("min", "max"):
        red = jnp.min if agg.kind == "min" else jnp.max
        if is_string(col.type):
            rvals, ident, decode = _string_minmax_lane(col, vals,
                                                       agg.kind)
            data = decode(jnp.stack(
                [red(jnp.where(g, rvals, ident)) for g in gmasks]))
            return Column(col.type, data, group_valid,
                          dictionary=col.dictionary)
        as_bool = vals.dtype == jnp.bool_
        work = vals.astype(jnp.int32) if as_bool else vals
        ident = _identity_for(agg.kind, work.dtype)
        data = jnp.stack(
            [red(jnp.where(g, work, ident)) for g in gmasks])
        if as_bool:
            data = data.astype(jnp.bool_)
        return Column(col.type, data, group_valid)

    # any_value: first valid row per group
    cap = vals.shape[0]
    pos = jnp.arange(cap, dtype=jnp.int64)
    firsts = jnp.stack(
        [jnp.min(jnp.where(g, pos, jnp.int64(cap))) for g in gmasks])
    from dataclasses import replace as _replace
    out = col.gather(jnp.clip(firsts, 0, cap - 1))
    return _replace(out, valid=group_valid)


def group_aggregate(batch: Batch, key_names: Sequence[str],
                    aggs: Sequence[AggInput],
                    groups_capacity: Optional[int] = None,
                    live: Optional[jax.Array] = None) -> Batch:
    """GROUP BY key_names with the given aggregates.

    Returns a Batch of key columns + aggregate columns, capacity-padded to
    ``groups_capacity`` (default: input capacity) with device num_groups.
    Aggregate null semantics: sum/min/max over zero non-null inputs yield
    NULL; count yields 0 (SQL standard, matching reference
    operator/aggregation/LongSumAggregation.java).

    ``live`` overrides the batch's prefix liveness with an explicit row
    mask (selection-vector execution: a fused upstream filter passes its
    mask here instead of compacting — compaction's nonzero+gather costs
    seconds at SF1 row counts on TPU).

    Two kernels (the BigintGroupByHash / MultiChannelGroupByHash split of
    the reference, re-specialized for TPU):
    - packed fast path when every key has a small STATIC domain
      (dictionary codes, bools): group id = packed key, aggregates =
      unrolled masked reductions — no sort, no gather, no scatter, which
      are all pathologically slow on TPU (measured v5e: lexsort 2.5s,
      take 5.1s, segment_sum 0.6s vs masked reduction 29ms at 8M rows).
    - general path: stable lexsort on key lanes + segment ops.
    """
    cap = batch.capacity
    gcap = groups_capacity or cap
    fast = _packed_group_aggregate(batch, key_names, aggs, gcap, live,
                                   clamp=groups_capacity is None)
    if fast is not None:
        return fast
    live = batch.row_valid() if live is None else live

    lanes = _key_lanes(batch, key_names, live)
    order = jnp.lexsort(lanes[::-1])
    live_s = jnp.take(live, order)

    # key-change boundaries over the sorted live prefix
    changed = jnp.zeros((cap,), dtype=bool)
    for lane in lanes[1:]:
        s = jnp.take(lane, order)
        changed = changed | (s != jnp.roll(s, 1))
    first = jnp.arange(cap) == 0
    boundary = (changed | first) & live_s
    gid = jnp.cumsum(boundary.astype(jnp.int64)) - 1
    num_groups = jnp.sum(boundary.astype(jnp.int64))
    gid_c = jnp.clip(gid, 0, gcap - 1).astype(jnp.int32)

    # first-row position of each group -> gather for key output
    grp_first = jnp.nonzero(boundary, size=gcap, fill_value=0)[0]
    grp_rows = jnp.take(order, grp_first)

    out_cols: Dict[str, Column] = {}
    for name in key_names:
        out_cols[name] = batch.column(name).gather(grp_rows)

    for agg in aggs:
        out_cols[agg.output] = _segment_agg(
            batch, agg, order, gid_c, live_s, gcap, lanes, live)

    return Batch(out_cols, num_groups)


def _int128_lanes(col: Column, order=None):
    lo = jnp.asarray(col.data).astype(jnp.int64)
    # a short-decimal input sign-extends into the hi lane (its sum can
    # still overflow int64 — that's why the SQL sum type is DECIMAL(38))
    hi = (jnp.asarray(col.data2).astype(jnp.int64)
          if col.data2 is not None else lo >> 63)
    if order is not None:
        lo = jnp.take(lo, order)
        hi = jnp.take(hi, order)
    return lo, hi


def _wide_decimal_agg(col: Column, kind: str) -> bool:
    """True when the aggregate must run on Int128 lanes: any long
    decimal, and a short-decimal SUM that could overflow int64
    (reference: DecimalSumAggregation accumulates in Int128). Column
    capacity is static, so capacity * 10^precision < 2^63 proves the
    single-lane int64 sum exact — keeps the hot TPC-H money sums
    (DECIMAL(12,2) at sf1) on the 1-lane kernel."""
    from ..types import DecimalType as _Dec
    if not isinstance(col.type, _Dec):
        return False
    if col.data2 is not None:
        return kind in ("sum", "min", "max")
    if kind != "sum":
        return False
    cap = int(jnp.asarray(col.data).shape[0])
    return cap * (10 ** col.type.precision) >= 2 ** 63


def _int128_masked_agg(col: Column, kind: str, gmasks, group_valid,
                       order=None) -> Column:
    """sum/min/max over DECIMAL(p>18) for the mask-per-group kernels.

    sum: each value decomposes into three int64 addend lanes
    (w0 + w1*2^32 + hi*2^64, 0 <= w0,w1 < 2^32) so per-group sums of up
    to 2^31 rows stay exact; lanes recombine with carry propagation.
    min/max: composite order (hi major signed, lo minor unsigned via
    the sign-flip trick). Reference: Int128 state of
    spi/type/Int128Math.java + DecimalSumAggregation."""
    from . import int128 as i128
    lo, hi = _int128_lanes(col, order)
    if kind == "sum":
        w0, w1, w2 = i128.sum_lanes(lo, hi)
        z = jnp.int64(0)
        s0 = jnp.stack([jnp.sum(jnp.where(g, w0, z)) for g in gmasks])
        s1 = jnp.stack([jnp.sum(jnp.where(g, w1, z)) for g in gmasks])
        s2 = jnp.stack([jnp.sum(jnp.where(g, w2, z)) for g in gmasks])
        slo, shi = i128.combine_sums(s0, s1, s2)
        return Column(_sum_type(col.type), slo, group_valid, data2=shi)
    red = jnp.min if kind == "min" else jnp.max
    ident = _identity_for(kind, jnp.int64)
    mhi = jnp.stack([red(jnp.where(g, hi, ident)) for g in gmasks])
    sbit = jnp.int64(-(2 ** 63))
    ulo = lo ^ sbit
    mlo = jnp.stack([red(jnp.where(g & (hi == mhi[k]), ulo, ident))
                     for k, g in enumerate(gmasks)]) ^ sbit
    return Column(col.type, mlo, group_valid, data2=mhi)


def _int128_segment_agg(col: Column, kind: str, valid, order, gid,
                        gcap: int, group_valid) -> Column:
    """sum/min/max over DECIMAL(p>18) for the lexsort/segment kernel
    (same lane decomposition as _int128_masked_agg)."""
    from . import int128 as i128
    lo, hi = _int128_lanes(col, order)
    if kind == "sum":
        w0, w1, w2 = i128.sum_lanes(lo, hi)
        z = jnp.int64(0)
        s0 = jax.ops.segment_sum(jnp.where(valid, w0, z), gid,
                                 num_segments=gcap)
        s1 = jax.ops.segment_sum(jnp.where(valid, w1, z), gid,
                                 num_segments=gcap)
        s2 = jax.ops.segment_sum(jnp.where(valid, w2, z), gid,
                                 num_segments=gcap)
        slo, shi = i128.combine_sums(s0, s1, s2)
        return Column(_sum_type(col.type), slo, group_valid, data2=shi)
    seg = jax.ops.segment_min if kind == "min" else jax.ops.segment_max
    ident = _identity_for(kind, jnp.int64)
    mhi = seg(jnp.where(valid, hi, ident), gid, num_segments=gcap)
    sbit = jnp.int64(-(2 ** 63))
    ulo = lo ^ sbit
    elig = valid & (hi == jnp.take(mhi, gid))
    mlo = seg(jnp.where(elig, ulo, ident), gid, num_segments=gcap) ^ sbit
    return Column(col.type, mlo, group_valid, data2=mhi)


def _segment_agg(batch: Batch, agg: AggInput, order, gid, live_s,
                 gcap: int, key_lanes=None, live_u=None) -> Column:
    from ..types import BIGINT, DOUBLE, is_string

    extra_mask = None
    if agg.mask is not None:
        mcol = batch.column(agg.mask)
        m = jnp.take(jnp.asarray(mcol.data).astype(bool), order)
        if mcol.valid is not None:
            m = m & jnp.take(jnp.asarray(mcol.valid), order)
        extra_mask = m

    if agg.kind == "count_star":
        ones = live_s.astype(jnp.int64)
        if extra_mask is not None:
            ones = jnp.where(extra_mask, ones, 0)
        data = jax.ops.segment_sum(ones, gid, num_segments=gcap)
        return Column(BIGINT, data, None)

    col = batch.column(agg.input)
    vals = jnp.take(jnp.asarray(col.data), order)
    valid = live_s if col.valid is None else (
        live_s & jnp.take(jnp.asarray(col.valid), order))
    if extra_mask is not None:
        valid = valid & extra_mask

    if agg.kind == "count":
        data = jax.ops.segment_sum(valid.astype(jnp.int64), gid,
                                   num_segments=gcap)
        return Column(BIGINT, data, None)

    nvalid = jax.ops.segment_sum(valid.astype(jnp.int64), gid,
                                 num_segments=gcap)
    group_valid = nvalid > 0

    if _wide_decimal_agg(col, agg.kind):
        return _int128_segment_agg(col, agg.kind, valid, order, gid,
                                   gcap, group_valid)

    if agg.kind == "sum":
        acc_dtype = vals.dtype if vals.dtype in (
            jnp.float32, jnp.float64) else jnp.int64
        masked = jnp.where(valid, vals.astype(acc_dtype),
                           jnp.asarray(0, acc_dtype))
        data = jax.ops.segment_sum(masked, gid, num_segments=gcap)
        return Column(_sum_type(col.type), data, group_valid)

    if agg.kind in ("bit_and", "bit_or"):
        # segmented associative scan over the group-sorted rows (AND/OR
        # have no jax.ops.segment_* primitive; they are associative and
        # commutative, so a (gid, value) scan + last-of-segment gather is
        # exact — reference: BitwiseAndAggregation/BitwiseOrAggregation)
        op = jnp.bitwise_and if agg.kind == "bit_and" else jnp.bitwise_or
        ident = jnp.asarray(-1 if agg.kind == "bit_and" else 0, jnp.int64)
        work = jnp.where(valid, vals.astype(jnp.int64), ident)
        gid64 = gid.astype(jnp.int64)

        def _comb(a, b):
            ga, va = a
            gb, vb = b
            return gb, jnp.where(ga == gb, op(va, vb), vb)

        _, scanned = jax.lax.associative_scan(_comb, (gid64, work))
        cap = order.shape[0]
        pos = jnp.arange(cap, dtype=jnp.int64)
        last = jax.ops.segment_max(
            jnp.where(live_s, pos, jnp.int64(-1)), gid, num_segments=gcap)
        data = jnp.take(scanned, jnp.clip(last, 0, cap - 1))
        return Column(BIGINT, data, group_valid)

    if agg.kind in ("min", "max"):
        seg = jax.ops.segment_min if agg.kind == "min" else \
            jax.ops.segment_max
        if is_string(col.type):
            rvals, ident, decode = _string_minmax_lane(col, vals,
                                                       agg.kind)
            data = decode(seg(jnp.where(valid, rvals, ident), gid,
                              num_segments=gcap))
            return Column(col.type, data, group_valid,
                          dictionary=col.dictionary)
        as_bool = vals.dtype == jnp.bool_
        work = vals.astype(jnp.int32) if as_bool else vals
        ident = _identity_for(agg.kind, work.dtype)
        data = seg(jnp.where(valid, work, ident), gid,
                   num_segments=gcap)
        if as_bool:
            data = data.astype(jnp.bool_)
        return Column(col.type, data, group_valid)

    if agg.kind == "any_value":
        # first VALID row of the group (respecting FILTER mask); NULL only
        # when the group has no valid value — matches global_aggregate
        cap = order.shape[0]
        pos = jnp.arange(cap, dtype=jnp.int64)
        grp_first = jax.ops.segment_min(
            jnp.where(valid, pos, jnp.int64(cap)), gid, num_segments=gcap)
        rows = jnp.take(order, jnp.clip(grp_first, 0, cap - 1))
        from dataclasses import replace as _replace
        return _replace(col.gather(rows), valid=group_valid)

    if agg.kind in ("argmin", "argmax"):
        # min_by/max_by: the value of `input` at the row where `input2`
        # is extreme (reference: operator/aggregation/
        # MinMaxByAggregationFunction.java). Two segment passes: the
        # extreme comparator, then the first row attaining it.
        from dataclasses import replace as _replace
        cap = order.shape[0]
        comp = batch.column(agg.input2)
        if comp.data2 is not None:
            raise NotImplementedError(
                f"{agg.kind} by DECIMAL(p>18) is not supported yet")
        cvalid = live_s if comp.valid is None else (
            live_s & jnp.take(jnp.asarray(comp.valid), order))
        if extra_mask is not None:
            cvalid = cvalid & extra_mask
        work, _ = _order_lane(comp, order)
        lo = agg.kind == "argmin"
        ident = _identity_for("min" if lo else "max", work.dtype)
        work = jnp.where(cvalid & ~_isnan(work), work, ident)
        seg = jax.ops.segment_min if lo else jax.ops.segment_max
        ext = seg(work, gid, num_segments=gcap)
        cand = cvalid & (work == jnp.take(ext, gid))
        pos = jnp.arange(cap, dtype=jnp.int64)
        first = jax.ops.segment_min(
            jnp.where(cand, pos, jnp.int64(cap)), gid, num_segments=gcap)
        rows = jnp.take(order, jnp.clip(first, 0, cap - 1))
        gv = jax.ops.segment_sum(cvalid.astype(jnp.int64), gid,
                                 num_segments=gcap) > 0
        out = col.gather(rows)
        ov = gv if out.valid is None else gv & jnp.asarray(out.valid)
        return _replace(out, valid=ov)

    if agg.kind == "hll":
        # approx_set: per-group sparse HLL entries, one extra sort +
        # segment pass (reference: ApproximateSetAggregation; design
        # note in ops/hll.py)
        from ..types import HyperLogLogType, INTEGER as _INT
        from .hll import DEFAULT_BUCKET_BITS, grouped_sparse_hll
        b = int(agg.param) if agg.param else DEFAULT_BUCKET_BITS
        start, length, entries = grouped_sparse_hll(vals, valid, gid,
                                                    gcap, b)
        return Column(HyperLogLogType(b), start, group_valid, None,
                      length, Column(_INT, entries))

    if agg.kind == "hll_merge":
        # merge(hll): per-group max-union of sketch rows. Host numpy —
        # merge consumes small pre-aggregated sketch batches, and the
        # chain-JIT falls back to eager execution on the host round
        # trip (reference: MergeHyperLogLogAggregation)
        from ..types import HyperLogLogType, INTEGER as _INT
        from .hll import merge_sparse_host
        b = getattr(col.type, "bucket_bits", 11)
        import numpy as _onp
        starts = _onp.asarray(jax.device_get(vals))
        lens = _onp.asarray(jax.device_get(
            jnp.take(jnp.asarray(col.data2), order)))
        ent = _onp.asarray(jax.device_get(col.elements.data))
        v_np = _onp.asarray(jax.device_get(valid))
        g_np = _onp.asarray(jax.device_get(gid))
        start, length, out_ent = merge_sparse_host(
            starts, lens, ent, v_np, g_np, gcap, b)
        cap_e = max(int(out_ent.shape[0]), 1)
        from ..config import capacity_for as _cfor
        pad = _cfor(cap_e)
        out_ent = _onp.pad(out_ent, (0, pad - out_ent.shape[0]))
        return Column(HyperLogLogType(b), jnp.asarray(start),
                      group_valid, None, jnp.asarray(length),
                      Column(_INT, jnp.asarray(out_ent)))

    if agg.kind in ("count_distinct", "percentile"):
        return _resorted_agg(batch, agg, col, gid, live_s, gcap,
                             key_lanes, extra_mask, order, live_u)

    if agg.kind == "array_agg":
        # group runs are contiguous in the sorted order: the flat
        # elements column IS the group-sorted input; each group's array
        # is (first position, included-row count). FILTER-masked rows
        # are sunk to the end of their group run by a secondary sort
        # lane so inclusion stays a prefix (reference:
        # operator/aggregation/ArrayAggregationFunction — NULL inputs
        # are collected, masked rows are not).
        from ..types import ArrayType
        from dataclasses import replace as _replace
        cap = order.shape[0]
        include = live_s if extra_mask is None else (live_s & extra_mask)
        if extra_mask is not None:
            live = (batch.row_valid() if live_u is None else live_u)
            inc_u = jnp.zeros((cap,), bool).at[order].set(include)
            use_order, use_gid, _, _, _ = _resort(
                key_lanes, [(~inc_u).astype(jnp.uint64)], live, gcap)
            use_inc = jnp.take(inc_u, use_order)
        else:
            use_order, use_gid, use_inc = order, gid, include
        pos = jnp.arange(cap, dtype=jnp.int64)
        start = jax.ops.segment_min(
            jnp.where(use_inc, pos, jnp.int64(cap)), use_gid,
            num_segments=gcap)
        length = jax.ops.segment_sum(use_inc.astype(jnp.int64), use_gid,
                                     num_segments=gcap)
        elements = col.gather(use_order)
        return Column(ArrayType(col.type),
                      jnp.clip(start, 0, cap - 1), length > 0, None,
                      length, elements)

    if agg.kind in ("map_agg", "histogram"):
        return _resorted_agg(batch, agg, col, gid, live_s, gcap,
                             key_lanes, extra_mask, order, live_u)

    if agg.kind in ("map_union", "multimap_agg", "numeric_histogram"):
        # host-side collection aggregates (hll_merge pattern; see
        # ops/collections.py module docstring for the rationale)
        from .collections import (grouped_map_union, grouped_multimap_agg,
                                  grouped_numeric_histogram, rows_by_group)
        groups = rows_by_group(order, gid, valid, gcap)
        if agg.kind == "map_union":
            return grouped_map_union(col, groups, group_valid)
        if agg.kind == "multimap_agg":
            return grouped_multimap_agg(col, batch.column(agg.input2),
                                        groups, group_valid)
        from ..types import DecimalType as _Dec
        scale = (10.0 ** col.type.scale
                 if isinstance(col.type, _Dec) else None)
        wcol = batch.column(agg.input2) if agg.input2 else None
        return grouped_numeric_histogram(col, groups, group_valid,
                                         int(agg.param or 2), scale,
                                         wcol)

    if agg.kind in ("tdigest", "qdigest", "digest_merge"):
        from .collections import rows_by_group
        from .digest import (DEFAULT_COMPRESSION, DEFAULT_QDIGEST_BUDGET,
                             grouped_digest, grouped_digest_merge)
        groups = rows_by_group(order, gid, valid, gcap)
        if agg.kind == "digest_merge":
            return grouped_digest_merge(col, groups, group_valid,
                                        _merge_budget(col))
        return _grouped_digest_build(batch, agg, col, groups,
                                     group_valid)

    raise ValueError(f"unknown aggregate kind {agg.kind}")


def _merge_budget(col: Column) -> int:
    """Recompression budget for merge(digest): qdigest sketches carry
    an accuracy budget (2/accuracy nodes) that a merge must not shrink
    — recompressing a 400-node qdigest to tdigest's 100 centroids
    would quadruple the user's requested quantile error. Honor the
    LARGEST input run so merged sketches keep their builders' budget
    (reference: QuantileDigest.merge keeps maxError)."""
    from ..types import QDigestType
    from .digest import DEFAULT_COMPRESSION, DEFAULT_QDIGEST_BUDGET
    base = (DEFAULT_QDIGEST_BUDGET if isinstance(col.type, QDigestType)
            else DEFAULT_COMPRESSION)
    if col.data2 is not None:
        import numpy as _np
        lens = _np.asarray(jax.device_get(col.data2))
        if lens.size:
            base = max(base, int(lens.max()))
    return base


def _grouped_digest_build(batch: Batch, agg: AggInput, col: Column,
                          groups, group_valid) -> Column:
    from ..types import (DecimalType as _Dec, QDigestType, T_DIGEST)
    from .digest import (DEFAULT_COMPRESSION, DEFAULT_QDIGEST_BUDGET,
                         grouped_digest)
    wcol = (batch.column(agg.input2)
            if getattr(agg, "input2", None) else None)
    scale = (10.0 ** col.type.scale
             if isinstance(col.type, _Dec) else None)
    if agg.kind == "tdigest":
        return grouped_digest(col, groups, group_valid, T_DIGEST,
                              DEFAULT_COMPRESSION, wcol, scale)
    budget = (int(2.0 / float(agg.param))
              if agg.param else DEFAULT_QDIGEST_BUDGET)
    return grouped_digest(col, groups, group_valid,
                          QDigestType(col.type), budget, wcol, scale)


def _isnan(x: jax.Array) -> jax.Array:
    if x.dtype in (jnp.float32, jnp.float64):
        return jnp.isnan(x)
    return jnp.zeros(x.shape, bool)


def _order_lane(col: Column, order=None) -> Tuple[jax.Array, object]:
    """A single lane whose numeric order == the SQL order of the column
    (collation ranks for strings, int32 for bools); second return is the
    rank->code decoder (strings only)."""
    from ..types import is_string
    d = jnp.asarray(col.data)
    decoder = None
    if is_string(col.type):
        ranks = col.dictionary.rank_codes()
        decoder = jnp.asarray(_invert_permutation(ranks))
        d = jnp.take(jnp.asarray(ranks), d, mode="clip").astype(jnp.int32)
    elif d.dtype == jnp.bool_:
        d = d.astype(jnp.int32)
    if order is not None:
        d = jnp.take(d, order)
    return d, decoder


def _resort(key_lanes, tie_lanes, live, gcap: int):
    """Re-sort rows by (key lanes, tie lanes) and recompute group ids.
    Group ids stay aligned with the primary sort of group_aggregate
    because both orders sort by the key lanes first. Returns
    (order2, gid2, live_s2, key_changed, is_first)."""
    cap = live.shape[0]
    full = list(key_lanes) + list(tie_lanes)
    order2 = jnp.lexsort(full[::-1])
    live_s2 = jnp.take(live, order2)
    changed = jnp.zeros((cap,), dtype=bool)
    for lane in key_lanes[1:]:
        s = jnp.take(lane, order2)
        changed = changed | (s != jnp.roll(s, 1))
    first = jnp.arange(cap) == 0
    boundary2 = (changed | first) & live_s2
    gid2 = jnp.clip(jnp.cumsum(boundary2.astype(jnp.int64)) - 1,
                    0, gcap - 1).astype(jnp.int32)
    return order2, gid2, live_s2, changed, first


def _resorted_agg(batch: Batch, agg: AggInput, col: Column, gid, live_s,
                  gcap: int, key_lanes, extra_mask, order,
                  live_u=None) -> Column:
    """Aggregates that need rows RE-sorted by (keys, value): exact
    count_distinct (reference approximates with HLL —
    ApproximateCountDistinctAggregation.java; exact is a superset) and
    exact percentile (reference: qdigest approx_percentile). Group ids
    stay aligned with the primary sort because both orders sort by the
    key lanes first."""
    from ..types import BIGINT
    cap = order.shape[0]
    live = batch.row_valid() if live_u is None else live_u
    valid_u = live if col.valid is None else live & jnp.asarray(col.valid)
    if agg.mask is not None:
        mcol = batch.column(agg.mask)
        m = jnp.asarray(mcol.data).astype(bool)
        if mcol.valid is not None:
            m = m & jnp.asarray(mcol.valid)
        valid_u = valid_u & m

    if agg.kind in ("count_distinct", "map_agg", "histogram"):
        vlanes = equality_lanes(col.data)
        if col.data2 is not None:
            vlanes = vlanes + equality_lanes(col.data2)
        vlanes = [jnp.where(valid_u, u, jnp.zeros_like(u))
                  for u in vlanes]
        tie = [(~valid_u).astype(jnp.uint64)] + vlanes
    else:
        if col.data2 is not None:
            raise NotImplementedError(
                "percentile over DECIMAL(p>18) is not supported yet")
        olane, _ = _order_lane(col)
        tie = [(~valid_u).astype(jnp.uint64), olane]

    order2, gid2, live_s2, changed_k, first = _resort(
        key_lanes, tie, live, gcap)
    valid2 = jnp.take(valid_u, order2)

    if agg.kind in ("count_distinct", "map_agg", "histogram"):
        changed_v = changed_k
        for lane in tie:
            s = jnp.take(lane, order2)
            changed_v = changed_v | (s != jnp.roll(s, 1))
        newval = (changed_v | first) & valid2
        if agg.kind == "count_distinct":
            data = jax.ops.segment_sum(newval.astype(jnp.int64), gid2,
                                       num_segments=gcap)
            return Column(BIGINT, data, None)
        # map_agg / histogram: each (group, distinct key) run is one
        # map entry; runs are (group, key)-major so per-group entry
        # ranges are contiguous (reference: operator/aggregation/
        # MapAggregationFunction / histogram/Histogram.java)
        from ..types import MapType
        runid = jnp.clip(jnp.cumsum(newval.astype(jnp.int64)) - 1,
                         0, cap - 1).astype(jnp.int32)
        pos = jnp.arange(cap, dtype=jnp.int64)
        run_start = jax.ops.segment_min(
            jnp.where(newval, pos, jnp.int64(cap)), runid,
            num_segments=cap)
        entry_rows = jnp.take(order2, jnp.clip(run_start, 0, cap - 1))
        keys_pool = col.gather(entry_rows)
        first_run = jax.ops.segment_min(
            jnp.where(newval, runid.astype(jnp.int64), jnp.int64(cap)),
            gid2, num_segments=gcap)
        nentries = jax.ops.segment_sum(newval.astype(jnp.int64), gid2,
                                       num_segments=gcap)
        if agg.kind == "histogram":
            counts = jax.ops.segment_sum(
                valid2.astype(jnp.int64), runid, num_segments=cap)
            vals_pool = Column(BIGINT, counts, None)
            out_t = MapType(col.type, BIGINT)
        else:
            vcol = batch.column(agg.input2)
            vals_pool = vcol.gather(entry_rows)
            out_t = MapType(col.type, vcol.type)
        return Column(out_t, jnp.clip(first_run, 0, cap - 1),
                      nentries > 0, None, nentries, keys_pool,
                      vals_pool)

    # exact percentile: valid rows of each group are a contiguous
    # ascending run starting at the group boundary (invalids sort last
    # within the group); pick the nearest-rank element
    from dataclasses import replace as _replace
    pos = jnp.arange(cap, dtype=jnp.int64)
    start = jax.ops.segment_min(
        jnp.where(live_s2, pos, jnp.int64(cap)), gid2, num_segments=gcap)
    nvalid = jax.ops.segment_sum(valid2.astype(jnp.int64), gid2,
                                 num_segments=gcap)
    q = float(agg.param if agg.param is not None else 0.5)
    k = jnp.clip(jnp.floor(q * (nvalid - 1).astype(jnp.float64) + 0.5)
                 .astype(jnp.int64), 0, jnp.maximum(nvalid - 1, 0))
    rows = jnp.take(order2, jnp.clip(start + k, 0, cap - 1))
    out = col.gather(rows)
    return _replace(out, valid=nvalid > 0)


def _invert_permutation(ranks):
    import numpy as np
    inv = np.empty(len(ranks), dtype=np.int32)
    inv[np.asarray(ranks)] = np.arange(len(ranks), dtype=np.int32)
    return inv


def _sum_type(t):
    from ..types import BIGINT, DOUBLE, REAL, DecimalType, is_integral
    if is_integral(t):
        return BIGINT
    if isinstance(t, DecimalType):
        return DecimalType(38, t.scale)
    if t.name == "real":
        return REAL
    return DOUBLE


def global_aggregate(batch: Batch, aggs: Sequence[AggInput],
                     live: Optional[jax.Array] = None) -> Batch:
    """Aggregation without GROUP BY (reference: operator/
    AggregationOperator.java) — masked full reductions, one output row.
    ``live`` as in group_aggregate (selection-vector input)."""
    from ..types import BIGINT

    live = batch.row_valid() if live is None else live
    out: Dict[str, Column] = {}
    for agg in aggs:
        extra = None
        if agg.mask is not None:
            mcol = batch.column(agg.mask)
            extra = jnp.asarray(mcol.data).astype(bool)
            if mcol.valid is not None:
                extra = extra & jnp.asarray(mcol.valid)
        if agg.kind == "count_star":
            m = live if extra is None else (live & extra)
            out[agg.output] = Column(
                BIGINT, jnp.sum(m.astype(jnp.int64))[None], None)
            continue
        col = batch.column(agg.input)
        vals = jnp.asarray(col.data)
        valid = live if col.valid is None else live & jnp.asarray(col.valid)
        if extra is not None:
            valid = valid & extra
        n = jnp.sum(valid.astype(jnp.int64))
        if agg.kind == "count":
            out[agg.output] = Column(BIGINT, n[None], None)
            continue
        has = (n > 0)[None]
        if _wide_decimal_agg(col, agg.kind):
            out[agg.output] = _int128_masked_agg(col, agg.kind, [valid],
                                                 has)
            continue
        if agg.kind == "sum":
            acc_dtype = vals.dtype if vals.dtype in (
                jnp.float32, jnp.float64) else jnp.int64
            s = jnp.sum(jnp.where(valid, vals.astype(acc_dtype),
                                  jnp.asarray(0, acc_dtype)))[None]
            out[agg.output] = Column(_sum_type(col.type), s, has)
        elif agg.kind in ("min", "max"):
            from ..types import is_string as _is_str
            if _is_str(col.type):
                rvals, ident, decode = _string_minmax_lane(
                    col, vals, agg.kind)
                masked = jnp.where(valid, rvals, ident)
                r = (jnp.min(masked) if agg.kind == "min"
                     else jnp.max(masked))
                r = decode(r)[None]
                out[agg.output] = Column(col.type, r, has,
                                         dictionary=col.dictionary)
            else:
                as_bool = vals.dtype == jnp.bool_
                work = vals.astype(jnp.int32) if as_bool else vals
                ident = _identity_for(agg.kind, work.dtype)
                masked = jnp.where(valid, work, ident)
                r = (jnp.min(masked) if agg.kind == "min"
                     else jnp.max(masked))[None]
                if as_bool:
                    r = r.astype(jnp.bool_)
                out[agg.output] = Column(col.type, r, has)
        elif agg.kind == "any_value":
            from dataclasses import replace as _replace
            idx = jnp.argmax(valid)  # first valid row (0 if none)
            out[agg.output] = _replace(col.gather(idx[None]), valid=has)
        elif agg.kind in ("bit_and", "bit_or"):
            op = (jnp.bitwise_and if agg.kind == "bit_and"
                  else jnp.bitwise_or)
            ident = jnp.asarray(-1 if agg.kind == "bit_and" else 0,
                                jnp.int64)
            masked = jnp.where(valid, vals.astype(jnp.int64), ident)
            r = jax.lax.reduce(masked, ident, op, (0,))[None]
            out[agg.output] = Column(BIGINT, r, has)
        elif agg.kind in ("argmin", "argmax"):
            from dataclasses import replace as _replace
            comp = batch.column(agg.input2)
            if comp.data2 is not None:
                raise NotImplementedError(
                    f"{agg.kind} by DECIMAL(p>18) is not supported yet")
            cvalid = live if comp.valid is None else (
                live & jnp.asarray(comp.valid))
            if extra is not None:
                cvalid = cvalid & extra
            work, _ = _order_lane(comp)
            lo = agg.kind == "argmin"
            ident = _identity_for("min" if lo else "max", work.dtype)
            work = jnp.where(cvalid & ~_isnan(work), work, ident)
            idx = jnp.argmin(work) if lo else jnp.argmax(work)
            gv = jnp.any(cvalid)[None]
            res = col.gather(idx[None])
            ov = gv if res.valid is None else gv & jnp.asarray(res.valid)
            out[agg.output] = _replace(res, valid=ov)
        elif agg.kind == "count_distinct":
            vlanes = equality_lanes(col.data)
            if col.data2 is not None:
                vlanes = vlanes + equality_lanes(col.data2)
            vlanes = [jnp.where(valid, u, jnp.zeros_like(u))
                      for u in vlanes]
            full = [(~valid).astype(jnp.uint64)] + vlanes
            order2 = jnp.lexsort(full[::-1])
            valid2 = jnp.take(valid, order2)
            changed = jnp.arange(batch.capacity) == 0
            for lane in vlanes:
                s = jnp.take(lane, order2)
                changed = changed | (s != jnp.roll(s, 1))
            cnt = jnp.sum((changed & valid2).astype(jnp.int64))
            out[agg.output] = Column(BIGINT, cnt[None], None)
        elif agg.kind == "array_agg":
            from ..types import ArrayType
            # included rows (live, FILTER-passing; NULL values stay)
            inc = live if extra is None else live & extra
            order2 = jnp.lexsort([(~inc).astype(jnp.uint64)][::-1])
            elements = col.gather(order2)
            n_inc = jnp.sum(inc.astype(jnp.int64))
            out[agg.output] = Column(
                ArrayType(col.type), jnp.zeros((1,), jnp.int64),
                (n_inc > 0)[None], None, n_inc[None], elements)
        elif agg.kind in ("map_agg", "histogram"):
            from ..types import MapType
            vlanes = equality_lanes(col.data)
            if col.data2 is not None:
                vlanes = vlanes + equality_lanes(col.data2)
            vlanes = [jnp.where(valid, u, jnp.zeros_like(u))
                      for u in vlanes]
            full = [(~valid).astype(jnp.uint64)] + vlanes
            order2 = jnp.lexsort(full[::-1])
            valid2 = jnp.take(valid, order2)
            cap = batch.capacity
            changed = jnp.arange(cap) == 0
            for lane in vlanes:
                s = jnp.take(lane, order2)
                changed = changed | (s != jnp.roll(s, 1))
            newent = (changed | (jnp.arange(cap) == 0)) & valid2
            runid = jnp.clip(jnp.cumsum(newent.astype(jnp.int64)) - 1,
                             0, cap - 1).astype(jnp.int32)
            pos = jnp.arange(cap, dtype=jnp.int64)
            run_start = jax.ops.segment_min(
                jnp.where(newent, pos, jnp.int64(cap)), runid,
                num_segments=cap)
            entry_rows = jnp.take(order2,
                                  jnp.clip(run_start, 0, cap - 1))
            keys_pool = col.gather(entry_rows)
            nent = jnp.sum(newent.astype(jnp.int64))
            if agg.kind == "histogram":
                counts = jax.ops.segment_sum(
                    valid2.astype(jnp.int64), runid, num_segments=cap)
                vals_pool = Column(BIGINT, counts, None)
                out_t = MapType(col.type, BIGINT)
            else:
                vcol = batch.column(agg.input2)
                vals_pool = vcol.gather(entry_rows)
                out_t = MapType(col.type, vcol.type)
            out[agg.output] = Column(
                out_t, jnp.zeros((1,), jnp.int64), (nent > 0)[None],
                None, nent[None], keys_pool, vals_pool)
        elif agg.kind in ("map_union", "multimap_agg",
                          "numeric_histogram"):
            from .collections import (grouped_map_union,
                                      grouped_multimap_agg,
                                      grouped_numeric_histogram,
                                      rows_by_group)
            cap = batch.capacity
            ident = jnp.arange(cap, dtype=jnp.int64)
            gid0 = jnp.zeros((cap,), jnp.int32)
            groups = rows_by_group(ident, gid0, valid, 1)
            if agg.kind == "map_union":
                out[agg.output] = grouped_map_union(col, groups, has)
            elif agg.kind == "multimap_agg":
                out[agg.output] = grouped_multimap_agg(
                    col, batch.column(agg.input2), groups, has)
            else:
                from ..types import DecimalType as _Dec
                scale = (10.0 ** col.type.scale
                         if isinstance(col.type, _Dec) else None)
                wcol = (batch.column(agg.input2) if agg.input2
                        else None)
                out[agg.output] = grouped_numeric_histogram(
                    col, groups, has, int(agg.param or 2), scale, wcol)
        elif agg.kind in ("tdigest", "qdigest", "digest_merge"):
            from .collections import rows_by_group
            from .digest import (DEFAULT_COMPRESSION,
                                 grouped_digest_merge)
            cap = batch.capacity
            ident = jnp.arange(cap, dtype=jnp.int64)
            gid0 = jnp.zeros((cap,), jnp.int32)
            groups = rows_by_group(ident, gid0, valid, 1)
            if agg.kind == "digest_merge":
                out[agg.output] = grouped_digest_merge(
                    col, groups, has, _merge_budget(col))
            else:
                out[agg.output] = _grouped_digest_build(
                    batch, agg, col, groups, has)
        elif agg.kind == "hll":
            from ..types import HyperLogLogType, INTEGER as _INT
            from .hll import DEFAULT_BUCKET_BITS, grouped_sparse_hll
            b = int(agg.param) if agg.param else DEFAULT_BUCKET_BITS
            gid0 = jnp.zeros((batch.capacity,), jnp.int32)
            start, length, entries = grouped_sparse_hll(vals, valid,
                                                        gid0, 1, b)
            out[agg.output] = Column(
                HyperLogLogType(b), start, has, None, length,
                Column(_INT, entries))
        elif agg.kind == "hll_merge":
            from ..types import HyperLogLogType, INTEGER as _INT
            from .hll import merge_sparse_host
            from ..config import capacity_for as _cfor
            b = getattr(col.type, "bucket_bits", 11)
            import numpy as _onp
            starts = _onp.asarray(jax.device_get(vals))
            lens = _onp.asarray(jax.device_get(col.data2))
            ent = _onp.asarray(jax.device_get(col.elements.data))
            v_np = _onp.asarray(jax.device_get(valid))
            g_np = _onp.zeros(batch.capacity, _onp.int64)
            start, length, out_ent = merge_sparse_host(
                starts, lens, ent, v_np, g_np, 1, b)
            pad = _cfor(max(int(out_ent.shape[0]), 1))
            out_ent = _onp.pad(out_ent, (0, pad - out_ent.shape[0]))
            out[agg.output] = Column(
                HyperLogLogType(b), jnp.asarray(start), has, None,
                jnp.asarray(length), Column(_INT, jnp.asarray(out_ent)))
        elif agg.kind == "percentile":
            from dataclasses import replace as _replace
            if col.data2 is not None:
                raise NotImplementedError(
                    "percentile over DECIMAL(p>18) is not supported yet")
            olane, _ = _order_lane(col)
            full = [(~valid).astype(jnp.uint64), olane]
            order2 = jnp.lexsort(full[::-1])
            q = float(agg.param if agg.param is not None else 0.5)
            k = jnp.clip(jnp.floor(q * (n - 1).astype(jnp.float64) + 0.5)
                         .astype(jnp.int64), 0, jnp.maximum(n - 1, 0))
            rows = jnp.take(order2, k[None])
            out[agg.output] = _replace(col.gather(rows), valid=has)
        else:
            raise ValueError(f"unknown aggregate kind {agg.kind}")
    return Batch(out, 1)
