"""Grouped aggregation — HashAggregationOperator, TPU style.

Reference parity: operator/HashAggregationOperator.java:49,381-413 with
MultiChannelGroupByHash.java:55 (open-addressing probe) and flat BigArray
accumulator state (operator/aggregation/, lib/trino-array). Redesign for
XLA (SURVEY.md §7.3): instead of a serial hash-probe loop, group rows by a
stable lexsort on the key lanes, derive segment ids from key-change
boundaries, and compute every accumulator with ``jax.ops.segment_*`` —
fully parallel, static shapes, no device hash table. Group cardinality is
data-dependent, so outputs are capacity-padded with a device num_groups.

Partial/final split (reference: AggregationNode PARTIAL/FINAL +
PushPartialAggregationThroughExchange rule) is expressed by running this
same kernel on partial states: every aggregate below declares a
``combine`` that is itself one of the supported segment ops.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import Batch, Column
from .hashing import equality_lanes

_U64MAX = jnp.uint64(0xFFFFFFFFFFFFFFFF)


@dataclass(frozen=True)
class AggInput:
    """One aggregate over one input lane (or none, for count(*))."""
    kind: str          # sum | count | count_star | min | max | any_value
                       # | argmin | argmax | count_distinct | percentile
    input: Optional[str] = None   # column name; None for count_star
    mask: Optional[str] = None    # FILTER / mask column (boolean), optional
    output: str = "agg"
    param: Optional[float] = None  # percentile fraction for 'percentile'


def _key_lanes(batch: Batch, key_names: Sequence[str]) -> List[jax.Array]:
    """Exact equality-preserving lanes; a null is its own group value
    (SQL GROUP BY treats NULLs as equal), encoded via a validity lane."""
    live = batch.row_valid()
    lanes: List[jax.Array] = [(~live).astype(jnp.uint64)]
    for name in key_names:
        col = batch.column(name)
        col_lanes = equality_lanes(col.data)
        if col.data2 is not None:
            # Int128 high lane participates in key equality
            col_lanes = col_lanes + equality_lanes(col.data2)
        if col.valid is not None:
            v = jnp.asarray(col.valid)
            lanes.append((~v).astype(jnp.uint64))
            col_lanes = [jnp.where(v, u, jnp.zeros_like(u))
                         for u in col_lanes]
        col_lanes = [jnp.where(live, u, _U64MAX + jnp.zeros_like(u))
                     for u in col_lanes]
        lanes.extend(col_lanes)
    return lanes


def _identity_for(kind: str, dtype) -> jax.Array:
    if dtype == jnp.bool_:
        return jnp.asarray(kind == "min", dtype)
    if kind == "min":
        if dtype in (jnp.float32, jnp.float64):
            return jnp.asarray(jnp.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).max, dtype)
    if kind == "max":
        if dtype in (jnp.float32, jnp.float64):
            return jnp.asarray(-jnp.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).min, dtype)
    return jnp.asarray(0, dtype)


def group_aggregate(batch: Batch, key_names: Sequence[str],
                    aggs: Sequence[AggInput],
                    groups_capacity: Optional[int] = None) -> Batch:
    """GROUP BY key_names with the given aggregates.

    Returns a Batch of key columns + aggregate columns, capacity-padded to
    ``groups_capacity`` (default: input capacity) with device num_groups.
    Aggregate null semantics: sum/min/max over zero non-null inputs yield
    NULL; count yields 0 (SQL standard, matching reference
    operator/aggregation/LongSumAggregation.java).
    """
    cap = batch.capacity
    gcap = groups_capacity or cap
    live = batch.row_valid()

    lanes = _key_lanes(batch, key_names)
    order = jnp.lexsort(lanes[::-1])
    live_s = jnp.take(live, order)

    # key-change boundaries over the sorted live prefix
    changed = jnp.zeros((cap,), dtype=bool)
    for lane in lanes[1:]:
        s = jnp.take(lane, order)
        changed = changed | (s != jnp.roll(s, 1))
    first = jnp.arange(cap) == 0
    boundary = (changed | first) & live_s
    gid = jnp.cumsum(boundary.astype(jnp.int64)) - 1
    num_groups = jnp.sum(boundary.astype(jnp.int64))
    gid_c = jnp.clip(gid, 0, gcap - 1).astype(jnp.int32)

    # first-row position of each group -> gather for key output
    grp_first = jnp.nonzero(boundary, size=gcap, fill_value=0)[0]
    grp_rows = jnp.take(order, grp_first)

    out_cols: Dict[str, Column] = {}
    for name in key_names:
        out_cols[name] = batch.column(name).gather(grp_rows)

    for agg in aggs:
        out_cols[agg.output] = _segment_agg(
            batch, agg, order, gid_c, live_s, gcap, lanes)

    return Batch(out_cols, num_groups)


def _segment_agg(batch: Batch, agg: AggInput, order, gid, live_s,
                 gcap: int, key_lanes=None) -> Column:
    from ..types import BIGINT, DOUBLE, is_string

    extra_mask = None
    if agg.mask is not None:
        mcol = batch.column(agg.mask)
        m = jnp.take(jnp.asarray(mcol.data).astype(bool), order)
        if mcol.valid is not None:
            m = m & jnp.take(jnp.asarray(mcol.valid), order)
        extra_mask = m

    if agg.kind == "count_star":
        ones = live_s.astype(jnp.int64)
        if extra_mask is not None:
            ones = jnp.where(extra_mask, ones, 0)
        data = jax.ops.segment_sum(ones, gid, num_segments=gcap)
        return Column(BIGINT, data, None)

    col = batch.column(agg.input)
    if col.data2 is not None and agg.kind in ("sum", "min", "max"):
        # Int128 lane arithmetic (carry-propagating segment sums) is not
        # implemented yet — fail loudly rather than reduce the lo lane
        # (SURVEY.md §7 hard part 4)
        raise NotImplementedError(
            f"{agg.kind} over DECIMAL(p>18) is not supported yet")
    vals = jnp.take(jnp.asarray(col.data), order)
    valid = live_s if col.valid is None else (
        live_s & jnp.take(jnp.asarray(col.valid), order))
    if extra_mask is not None:
        valid = valid & extra_mask

    if agg.kind == "count":
        data = jax.ops.segment_sum(valid.astype(jnp.int64), gid,
                                   num_segments=gcap)
        return Column(BIGINT, data, None)

    nvalid = jax.ops.segment_sum(valid.astype(jnp.int64), gid,
                                 num_segments=gcap)
    group_valid = nvalid > 0

    if agg.kind == "sum":
        acc_dtype = vals.dtype if vals.dtype in (
            jnp.float32, jnp.float64) else jnp.int64
        masked = jnp.where(valid, vals.astype(acc_dtype),
                           jnp.asarray(0, acc_dtype))
        data = jax.ops.segment_sum(masked, gid, num_segments=gcap)
        return Column(_sum_type(col.type), data, group_valid)

    if agg.kind in ("min", "max"):
        seg = jax.ops.segment_min if agg.kind == "min" else \
            jax.ops.segment_max
        if is_string(col.type):
            # min/max over collation ranks, then rank -> code
            # (codes are insertion-ordered, not collation-ordered)
            ranks = col.dictionary.rank_codes()
            code_by_rank = jnp.asarray(
                _invert_permutation(ranks))
            rvals = jnp.take(jnp.asarray(ranks), vals, mode="clip")
            ident = jnp.asarray(
                len(ranks) if agg.kind == "min" else -1, rvals.dtype)
            data = seg(jnp.where(valid, rvals, ident), gid,
                       num_segments=gcap)
            data = jnp.take(code_by_rank,
                            jnp.clip(data, 0, len(ranks) - 1),
                            mode="clip").astype(jnp.int32)
            return Column(col.type, data, group_valid,
                          dictionary=col.dictionary)
        as_bool = vals.dtype == jnp.bool_
        work = vals.astype(jnp.int32) if as_bool else vals
        ident = _identity_for(agg.kind, work.dtype)
        data = seg(jnp.where(valid, work, ident), gid,
                   num_segments=gcap)
        if as_bool:
            data = data.astype(jnp.bool_)
        return Column(col.type, data, group_valid)

    if agg.kind == "any_value":
        # first VALID row of the group (respecting FILTER mask); NULL only
        # when the group has no valid value — matches global_aggregate
        cap = order.shape[0]
        pos = jnp.arange(cap, dtype=jnp.int64)
        grp_first = jax.ops.segment_min(
            jnp.where(valid, pos, jnp.int64(cap)), gid, num_segments=gcap)
        rows = jnp.take(order, jnp.clip(grp_first, 0, cap - 1))
        from dataclasses import replace as _replace
        return _replace(col.gather(rows), valid=group_valid)

    raise ValueError(f"unknown aggregate kind {agg.kind}")


def _invert_permutation(ranks):
    import numpy as np
    inv = np.empty(len(ranks), dtype=np.int32)
    inv[np.asarray(ranks)] = np.arange(len(ranks), dtype=np.int32)
    return inv


def _sum_type(t):
    from ..types import BIGINT, DOUBLE, REAL, DecimalType, is_integral
    if is_integral(t):
        return BIGINT
    if isinstance(t, DecimalType):
        return DecimalType(38, t.scale)
    if t.name == "real":
        return REAL
    return DOUBLE


def global_aggregate(batch: Batch, aggs: Sequence[AggInput]) -> Batch:
    """Aggregation without GROUP BY (reference: operator/
    AggregationOperator.java) — masked full reductions, one output row."""
    from ..types import BIGINT

    live = batch.row_valid()
    out: Dict[str, Column] = {}
    for agg in aggs:
        extra = None
        if agg.mask is not None:
            mcol = batch.column(agg.mask)
            extra = jnp.asarray(mcol.data).astype(bool)
            if mcol.valid is not None:
                extra = extra & jnp.asarray(mcol.valid)
        if agg.kind == "count_star":
            m = live if extra is None else (live & extra)
            out[agg.output] = Column(
                BIGINT, jnp.sum(m.astype(jnp.int64))[None], None)
            continue
        col = batch.column(agg.input)
        if col.data2 is not None and agg.kind in ("sum", "min", "max"):
            raise NotImplementedError(
                f"{agg.kind} over DECIMAL(p>18) is not supported yet")
        vals = jnp.asarray(col.data)
        valid = live if col.valid is None else live & jnp.asarray(col.valid)
        if extra is not None:
            valid = valid & extra
        n = jnp.sum(valid.astype(jnp.int64))
        if agg.kind == "count":
            out[agg.output] = Column(BIGINT, n[None], None)
            continue
        has = (n > 0)[None]
        if agg.kind == "sum":
            acc_dtype = vals.dtype if vals.dtype in (
                jnp.float32, jnp.float64) else jnp.int64
            s = jnp.sum(jnp.where(valid, vals.astype(acc_dtype),
                                  jnp.asarray(0, acc_dtype)))[None]
            out[agg.output] = Column(_sum_type(col.type), s, has)
        elif agg.kind in ("min", "max"):
            from ..types import is_string as _is_str
            if _is_str(col.type):
                ranks = col.dictionary.rank_codes()
                code_by_rank = jnp.asarray(_invert_permutation(ranks))
                rvals = jnp.take(jnp.asarray(ranks), vals, mode="clip")
                ident = jnp.asarray(
                    len(ranks) if agg.kind == "min" else -1, rvals.dtype)
                masked = jnp.where(valid, rvals, ident)
                r = (jnp.min(masked) if agg.kind == "min"
                     else jnp.max(masked))
                r = jnp.take(code_by_rank,
                             jnp.clip(r, 0, len(ranks) - 1),
                             mode="clip").astype(jnp.int32)[None]
                out[agg.output] = Column(col.type, r, has,
                                         dictionary=col.dictionary)
            else:
                as_bool = vals.dtype == jnp.bool_
                work = vals.astype(jnp.int32) if as_bool else vals
                ident = _identity_for(agg.kind, work.dtype)
                masked = jnp.where(valid, work, ident)
                r = (jnp.min(masked) if agg.kind == "min"
                     else jnp.max(masked))[None]
                if as_bool:
                    r = r.astype(jnp.bool_)
                out[agg.output] = Column(col.type, r, has)
        elif agg.kind == "any_value":
            from dataclasses import replace as _replace
            idx = jnp.argmax(valid)  # first valid row (0 if none)
            out[agg.output] = _replace(col.gather(idx[None]), valid=has)
        else:
            raise ValueError(f"unknown aggregate kind {agg.kind}")
    return Batch(out, 1)
