"""Civil-date device kernels.

Reference parity: operator/scalar/DateTimeFunctions.java + the Joda-based
field extraction. On TPU, days-since-epoch int lanes are decomposed with
the branch-free civil-calendar algorithm (Howard Hinnant's
days_from_civil / civil_from_days) — pure integer VPU arithmetic, no
tables, vectorizes over the whole column.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def civil_from_days(days: jax.Array) -> Tuple[jax.Array, jax.Array,
                                              jax.Array]:
    """days since 1970-01-01 -> (year, month, day), proleptic Gregorian."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097                                   # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)          # [0, 365]
    mp = (5 * doy + 2) // 153                                # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                        # [1, 31]
    m = mp + jnp.where(mp < 10, 3, -9)                       # [1, 12]
    y = y + (m <= 2)
    return y, m, d


def days_from_civil(y: jax.Array, m: jax.Array, d: jax.Array) -> jax.Array:
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + jnp.where(m > 2, -3, 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def extract_field(days: jax.Array, field: str) -> jax.Array:
    """EXTRACT(field FROM date-as-days) -> int64 lane."""
    y, m, d = civil_from_days(days)
    if field == "year":
        return y
    if field == "month":
        return m
    if field in ("day", "day_of_month"):
        return d
    if field == "quarter":
        return (m - 1) // 3 + 1
    if field in ("day_of_week", "dow"):
        # ISO: Monday=1..Sunday=7; 1970-01-01 was a Thursday
        return (days.astype(jnp.int64) + 3) % 7 + 1
    if field in ("day_of_year", "doy"):
        return days.astype(jnp.int64) - days_from_civil(
            y, jnp.ones_like(m), jnp.ones_like(d)) + 1
    if field == "week":
        # ISO week number
        doy = days.astype(jnp.int64) - days_from_civil(
            y, jnp.ones_like(m), jnp.ones_like(d)) + 1
        dow = (days.astype(jnp.int64) + 3) % 7 + 1
        wk = (doy - dow + 10) // 7
        # weeks 0 / 53 wrap into neighbouring years; clamp approximation
        return jnp.clip(wk, 1, 53)
    raise ValueError(f"unsupported extract field for date: {field}")


def add_months(days: jax.Array, months: jax.Array) -> jax.Array:
    """date + INTERVAL month with end-of-month clamping (SQL standard;
    reference: operator/scalar/DateTimeFunctions.addFieldValueDate)."""
    y, m, d = civil_from_days(days)
    t = (y * 12 + (m - 1)) + months.astype(jnp.int64)
    ny = jnp.floor_divide(t, 12)
    nm = t - ny * 12 + 1
    # clamp day to the target month's length
    first_next = days_from_civil(
        ny + (nm == 12), jnp.where(nm == 12, 1, nm + 1),
        jnp.ones_like(nm))
    first_this = days_from_civil(ny, nm, jnp.ones_like(nm))
    month_len = first_next - first_this
    nd = jnp.minimum(d, month_len)
    return days_from_civil(ny, nm, nd)


def date_trunc_days(days: jax.Array, unit: str) -> jax.Array:
    y, m, d = civil_from_days(days)
    one = jnp.ones_like(m)
    if unit == "year":
        return days_from_civil(y, one, one)
    if unit == "quarter":
        qm = ((m - 1) // 3) * 3 + 1
        return days_from_civil(y, qm, one)
    if unit == "month":
        return days_from_civil(y, m, one)
    if unit == "week":
        dow = (days.astype(jnp.int64) + 3) % 7  # Monday=0
        return days.astype(jnp.int64) - dow
    if unit == "day":
        return days.astype(jnp.int64)
    raise ValueError(f"unsupported date_trunc unit for date: {unit}")
