"""Int128 lane arithmetic for DECIMAL(p>18).

A long decimal is a pair of int64 lanes ``(lo, hi)`` emulating a
two's-complement 128-bit integer: ``value = hi * 2^64 + u64(lo)``
(columnar.py stores ``hi`` in ``Column.data2``).

Everything here is pure jnp over int64 — TPU-safe by construction:
no uint64 (the TPU path has no native u64 compare; unsigned order uses
the sign-bit-flip trick), no float bitcasts, no data-dependent Python
control flow. Multiplication runs on 16-bit limbs so every partial
product and carry stays far below 2^63; division is a 128-step
shift-subtract ``lax.fori_loop`` (exact for any 128-bit divisor — long
division digit estimation is not worth its complexity on a lane ISA
where the loop vectorizes over all rows).

Reference behavior being matched:
core/trino-spi/src/main/java/io/trino/spi/type/Int128Math.java and
UnscaledDecimal128Arithmetic.java:42 (add/multiply/rescale with
HALF_UP), spi/type/Decimals.java for the textual forms.
Overflow beyond 128 bits wraps here rather than raising
DECIMAL_OVERFLOW — a documented divergence (a per-row raise would break
XLA tracing); results within DECIMAL(38) range are exact.
"""

from typing import Tuple

import jax
import jax.numpy as jnp

_SBIT = -(2 ** 63)
_M16 = (1 << 16) - 1


# --------------------------------------------------------------------------
# host-side constant splitting
# --------------------------------------------------------------------------

def split_const(q: int) -> Tuple[int, int]:
    """Python int -> (lo, hi) signed-int64 Python ints (two's
    complement). |q| must be < 2^127."""
    lo = q & ((1 << 64) - 1)
    if lo >= (1 << 63):
        lo -= 1 << 64
    hi = q >> 64  # Python arithmetic shift: sign-correct
    if not (-(1 << 63) <= hi < (1 << 63)):
        raise OverflowError(f"constant exceeds 128 bits: {q}")
    return lo, hi


def combine_host(lo: int, hi: int) -> int:
    """(lo, hi) int64 pair -> Python int (exact)."""
    return (int(hi) << 64) + (int(lo) & ((1 << 64) - 1))


# --------------------------------------------------------------------------
# lane primitives
# --------------------------------------------------------------------------

def sign_extend(lo: jax.Array) -> jax.Array:
    """hi lane for a value currently held in a single int64 lane."""
    return lo >> 63


def _ult(a: jax.Array, b: jax.Array) -> jax.Array:
    """unsigned a < b on int64 lanes (sign-bit flip trick)."""
    s = jnp.int64(_SBIT)
    return (a ^ s) < (b ^ s)


def add128(alo, ahi, blo, bhi):
    lo = alo + blo
    carry = _ult(lo, alo).astype(jnp.int64)
    return lo, ahi + bhi + carry


def neg128(lo, hi):
    return -lo, -hi - (lo != 0).astype(jnp.int64)


def sub128(alo, ahi, blo, bhi):
    return add128(alo, ahi, *neg128(blo, bhi))


def abs128(lo, hi):
    neg = hi < 0
    nlo, nhi = neg128(lo, hi)
    return jnp.where(neg, nlo, lo), jnp.where(neg, nhi, hi)


def eq128(alo, ahi, blo, bhi):
    return (alo == blo) & (ahi == bhi)


def lt128(alo, ahi, blo, bhi):
    """signed 128-bit a < b."""
    return (ahi < bhi) | ((ahi == bhi) & _ult(alo, blo))


def uge128(alo, ahi, blo, bhi):
    """unsigned 128-bit a >= b (for abs-value/division work)."""
    s = jnp.int64(_SBIT)
    gt = (ahi ^ s) > (bhi ^ s)
    return gt | ((ahi == bhi) & ~_ult(alo, blo))


def shl1(lo, hi):
    return lo << 1, (hi << 1) | ((lo >> 63) & 1)


# --------------------------------------------------------------------------
# multiplication (mod 2^128), 16-bit limbs
# --------------------------------------------------------------------------

def _limbs(lo, hi):
    out = []
    for w in (lo, hi):
        for i in range(4):
            out.append((w >> (16 * i)) & _M16)
    return out


def _from_limbs(l):
    packed = []
    carry = jnp.zeros_like(l[0])
    for k in range(8):
        v = l[k] + carry
        packed.append(v & _M16)
        carry = v >> 16
    lo = (packed[0] | (packed[1] << 16) | (packed[2] << 32)
          | (packed[3] << 48))
    hi = (packed[4] | (packed[5] << 16) | (packed[6] << 32)
          | (packed[7] << 48))
    return lo, hi


def mul128(alo, ahi, blo, bhi):
    """full product mod 2^128 (correct for signed two's complement)."""
    a = _limbs(alo, ahi)
    b = _limbs(blo, bhi)
    r = [jnp.zeros_like(alo) for _ in range(8)]
    for i in range(8):
        for j in range(8 - i):
            r[i + j] = r[i + j] + a[i] * b[j]
    return _from_limbs(r)


def mul_const(lo, hi, c: int):
    """multiply by a non-negative Python-int constant, mod 2^128."""
    if c < 0:
        raise ValueError("mul_const expects c >= 0")
    climbs = [(c >> (16 * i)) & _M16 for i in range(8)]
    a = _limbs(lo, hi)
    r = [jnp.zeros_like(lo) for _ in range(8)]
    for i in range(8):
        if climbs[i] == 0:
            continue
        for j in range(8 - i):
            r[i + j] = r[i + j] + a[j] * climbs[i]
    return _from_limbs(r)


# --------------------------------------------------------------------------
# division
# --------------------------------------------------------------------------

def divmod128u(vlo, vhi, dlo, dhi):
    """unsigned 128 / unsigned 128 -> (qlo, qhi, rlo, rhi).

    Shift-subtract long division, one bit per step, vectorized over all
    rows; d == 0 yields q = 0, r = v (callers guard)."""
    zero = jnp.zeros_like(vlo)
    d_zero = (dlo == 0) & (dhi == 0)
    dlo_s = jnp.where(d_zero, 1, dlo)

    def body(i, st):
        qlo, qhi, rlo, rhi = st
        k = 127 - i
        hi_k = jnp.maximum(k - 64, 0)
        lo_k = jnp.minimum(k, 63)
        bit = jnp.where(k >= 64, (vhi >> hi_k) & 1, (vlo >> lo_k) & 1)
        rlo2, rhi2 = shl1(rlo, rhi)
        rlo2 = rlo2 | bit
        ge = uge128(rlo2, rhi2, dlo_s, dhi)
        slo, shi = sub128(rlo2, rhi2, dlo_s, dhi)
        rlo3 = jnp.where(ge, slo, rlo2)
        rhi3 = jnp.where(ge, shi, rhi2)
        qb = ge.astype(jnp.int64)
        qhi2 = qhi | jnp.where(k >= 64, qb << hi_k, 0)
        qlo2 = qlo | jnp.where(k < 64, qb << lo_k, 0)
        return qlo2, qhi2, rlo3, rhi3

    qlo, qhi, rlo, rhi = jax.lax.fori_loop(
        0, 128, body, (zero, zero, zero, zero))
    qlo = jnp.where(d_zero, 0, qlo)
    qhi = jnp.where(d_zero, 0, qhi)
    rlo = jnp.where(d_zero, vlo, rlo)
    rhi = jnp.where(d_zero, vhi, rhi)
    return qlo, qhi, rlo, rhi


def div128_round_half_up(lo, hi, d: int):
    """signed (lo, hi) / positive Python-int d, HALF_UP away from zero
    (the reference's Decimals rescale rounding)."""
    if d <= 0:
        raise ValueError("divisor must be positive")
    neg = hi < 0
    alo, ahi = abs128(lo, hi)
    dlo, dhi = split_const(d)
    dlo_a = jnp.full_like(lo, dlo)
    dhi_a = jnp.full_like(hi, dhi)
    qlo, qhi, rlo, rhi = divmod128u(alo, ahi, dlo_a, dhi_a)
    r2lo, r2hi = shl1(rlo, rhi)
    up = uge128(r2lo, r2hi, dlo_a, dhi_a).astype(jnp.int64)
    qlo, qhi = add128(qlo, qhi, up, jnp.zeros_like(qhi))
    nlo, nhi = neg128(qlo, qhi)
    return jnp.where(neg, nlo, qlo), jnp.where(neg, nhi, qhi)


def div128_round_half_up_pair(alo, ahi, blo, bhi):
    """signed 128 / signed 128, HALF_UP away from zero (per-row
    divisor — the decimal division kernel)."""
    q_neg = (ahi < 0) ^ (bhi < 0)
    aal, aah = abs128(alo, ahi)
    abl, abh = abs128(blo, bhi)
    qlo, qhi, rlo, rhi = divmod128u(aal, aah, abl, abh)
    r2lo, r2hi = shl1(rlo, rhi)
    up = uge128(r2lo, r2hi, abl, abh).astype(jnp.int64)
    qlo, qhi = add128(qlo, qhi, up, jnp.zeros_like(qhi))
    nlo, nhi = neg128(qlo, qhi)
    return jnp.where(q_neg, nlo, qlo), jnp.where(q_neg, nhi, qhi)


def div128_round_half_up_scaled(lo, hi, count, pow10: int):
    """signed (lo, hi) / (count * 10^pow10) with ONE HALF_UP rounding.

    The decimal-average down-rescale path: when the result scale sits
    below the sum scale, dividing by the count and then rescaling down
    rounds twice — 0.29 / 2 at scale 2 is 14.5 -> HALF_UP 15, then
    15 / 10 -> HALF_UP 2 (0.2), while the correct single-rounded
    answer is HALF_UP(29 / 20) = 1 (0.1). Folding the 10^k into the
    divisor keeps the reference's single rounding
    (DecimalAverageAggregation rescales before the one divide).
    ``count`` lanes must be positive int64; ``count * 10^pow10`` must
    fit 128 bits (beyond that the module's documented wrap applies)."""
    if pow10 < 0:
        raise ValueError("pow10 must be non-negative")
    dlo, dhi = mul_const(count, jnp.zeros_like(count), 10 ** pow10)
    return div128_round_half_up_pair(lo, hi, dlo, dhi)


def divmod128_trunc(alo, ahi, blo, bhi):
    """signed 128/128 truncating division (SQL integer-division and %
    semantics: quotient toward zero, remainder keeps the sign of a)."""
    a_neg = ahi < 0
    b_neg = bhi < 0
    aal, aah = abs128(alo, ahi)
    abl, abh = abs128(blo, bhi)
    qlo, qhi, rlo, rhi = divmod128u(aal, aah, abl, abh)
    q_neg = a_neg ^ b_neg
    nql, nqh = neg128(qlo, qhi)
    nrl, nrh = neg128(rlo, rhi)
    return (jnp.where(q_neg, nql, qlo), jnp.where(q_neg, nqh, qhi),
            jnp.where(a_neg, nrl, rlo), jnp.where(a_neg, nrh, rhi))


# --------------------------------------------------------------------------
# rescale / conversions
# --------------------------------------------------------------------------

def rescale(lo, hi, shift: int):
    """value * 10^shift (shift > 0) or HALF_UP divide (shift < 0)."""
    if shift == 0:
        return lo, hi
    if shift > 0:
        return mul_const(lo, hi, 10 ** shift)
    return div128_round_half_up(lo, hi, 10 ** (-shift))


def to_double(lo, hi) -> jax.Array:
    # value = (hi + [lo<0])*2^64 + signed(lo): keeping lo signed avoids
    # the catastrophic cancellation of hi*2^64 + (lo+2^64) for small
    # negative values (-5 would round to 0.0)
    hi_adj = hi + (lo < 0).astype(jnp.int64)
    return hi_adj.astype(jnp.float64) * 2.0 ** 64 + lo.astype(jnp.float64)


def from_double(x: jax.Array):
    """float64 -> (lo, hi), truncating toward zero beyond float
    precision (inherent: float64 has 53 mantissa bits)."""
    neg = x < 0
    ax = jnp.abs(x)
    hi_f = jnp.floor(ax / 2.0 ** 64)
    lo_f = ax - hi_f * 2.0 ** 64
    # lo_f in [0, 2^64): map to two's-complement int64
    wrap = lo_f >= 2.0 ** 63
    lo = jnp.where(wrap, (lo_f - 2.0 ** 64), lo_f).astype(jnp.int64)
    hi = hi_f.astype(jnp.int64)
    nlo, nhi = neg128(lo, hi)
    return jnp.where(neg, nlo, lo), jnp.where(neg, nhi, hi)


# --------------------------------------------------------------------------
# segment sums (aggregation support)
# --------------------------------------------------------------------------

def sum_lanes(lo, hi):
    """Decompose (lo, hi) into three int64 addend lanes (w0, w1, hi)
    with value = w0 + w1*2^32 + hi*2^64 and 0 <= w0, w1 < 2^32, so any
    per-group segment_sum of up to 2^31 rows stays exact in int64."""
    w0 = lo & 0xFFFFFFFF
    w1 = (lo >> 32) & 0xFFFFFFFF
    return w0, w1, hi


def combine_sums(s0, s1, s2):
    """Recombine segment-summed lanes into (lo, hi):
    total = s0 + s1*2^32 + s2*2^64 (mod 2^128)."""
    lo, hi = add128(s0, jnp.zeros_like(s0), s1 << 32, s1 >> 32)
    return lo, hi + s2
