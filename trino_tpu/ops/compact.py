"""Row selection & compaction — the FilterAndProject inner loop, TPU style.

Reference parity: Trino's compiled PageFilter evaluates a predicate into a
selected-positions array and PageProjection copies survivors
(core/trino-main/.../operator/project/PageProcessor.java,
sql/gen/PageFunctionCompiler.java:101). On TPU the same is a mask +
stable-compaction gather, fused by XLA into the surrounding pipeline.
"""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from ..columnar import Batch


def mask_to_gather(mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Turn a boolean row mask into (indices, count).

    indices is capacity-length; the first ``count`` entries are the positions
    of set bits in order; the rest point at position 0 (harmless garbage —
    rows past count are dead by construction).
    """
    cap = mask.shape[0]
    idx = jnp.nonzero(mask, size=cap, fill_value=0)[0]
    count = jnp.sum(mask.astype(jnp.int64))
    return idx, count


def filter_batch(batch: Batch, mask: jax.Array) -> Batch:
    """Keep rows where mask & live; output is compacted with a device
    num_rows (data-dependent cardinality under static shapes)."""
    live = mask & batch.row_valid()
    idx, count = mask_to_gather(live)
    return batch.gather(idx, count)


def limit_batch(batch: Batch, limit: Union[int, jax.Array]) -> Batch:
    """LIMIT n without data movement (reference: operator/LimitOperator.java).
    """
    n = jnp.minimum(batch.num_rows_device(),
                    jnp.asarray(limit, dtype=jnp.int64))
    return Batch(batch.columns, n)


def offset_batch(batch: Batch, offset: Union[int, jax.Array]) -> Batch:
    """OFFSET n — shift rows down (reference: sql/planner/plan/OffsetNode)."""
    off = jnp.asarray(offset, dtype=jnp.int64)
    cap = batch.capacity
    idx = jnp.arange(cap, dtype=jnp.int64) + off
    n = jnp.maximum(batch.num_rows_device() - off, 0)
    return batch.gather(jnp.clip(idx, 0, cap - 1), n)
