"""Geospatial kernels — the trino-geospatial toolkit core, TPU-first.

Reference parity: plugin/trino-geospatial's GeoFunctions (ST_Point,
ST_X/ST_Y, ST_Distance, ST_Contains, ST_GeometryFromText/ST_AsText,
great_circle_distance). Redesign for the VPU: a POINT column is two
float64 lanes (x, y) — distance and containment are branch-free array
math over every row at once, instead of the reference's per-row ESRI
geometry objects. Polygon operands arrive as WKT text (dictionary
-coded), are parsed ONCE per distinct dictionary value host-side, and
each distinct polygon's ray-casting mask computes vectorized over all
points.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, StringDictionary
from ..types import BOOLEAN, DOUBLE, GEOMETRY, VARCHAR, is_string

EARTH_RADIUS_KM = 6371.01


def _merge_valid(*cols: Column) -> Optional[jax.Array]:
    valid = None
    for c in cols:
        if c.valid is not None:
            v = jnp.asarray(c.valid)
            valid = v if valid is None else (valid & v)
    return valid


def point_column(x: Column, y: Column) -> Column:
    return Column(GEOMETRY, jnp.asarray(x.data).astype(jnp.float64),
                  _merge_valid(x, y),
                  data2=jnp.asarray(y.data).astype(jnp.float64))


def _require_points(c: Column, what: str):
    if c.data2 is None or c.dictionary is not None:
        raise ValueError(
            f"{what} supports POINT geometries on this path "
            "(non-point shapes are WKT-backed)")


def _wkt_point_lanes(c: Column):
    """(x, y, ok) lanes for a WKT-backed geometry column: each distinct
    dictionary value parses once; rows referencing non-POINT values get
    ok=False (NULL downstream) instead of poisoning the whole column —
    a filtered column legitimately keeps dead dictionary values."""
    vals = c.dictionary.values
    xs = np.zeros(max(len(vals), 1))
    ys = np.zeros(max(len(vals), 1))
    ok = np.zeros(max(len(vals), 1), bool)
    for i, v in enumerate(vals):
        m = _POINT_RE.match(str(v))
        if m is not None:
            xs[i], ys[i], ok[i] = (float(m.group(1)),
                                   float(m.group(2)), True)
    codes = jnp.clip(jnp.asarray(c.data).astype(jnp.int32), 0,
                     max(len(vals) - 1, 0))
    return (jnp.take(jnp.asarray(xs), codes),
            jnp.take(jnp.asarray(ys), codes),
            jnp.take(jnp.asarray(ok), codes))


def _xy(c: Column, what: str):
    """(x, y, valid) from either representation."""
    if c.dictionary is not None:
        x, y, ok = _wkt_point_lanes(c)
        valid = ok if c.valid is None else (jnp.asarray(c.valid) & ok)
        return x, y, valid
    _require_points(c, what)
    return (jnp.asarray(c.data), jnp.asarray(c.data2),
            None if c.valid is None else jnp.asarray(c.valid))


def st_x(c: Column) -> Column:
    x, _y, valid = _xy(c, "ST_X")
    return Column(DOUBLE, x, valid)


def st_y(c: Column) -> Column:
    _x, y, valid = _xy(c, "ST_Y")
    return Column(DOUBLE, y, valid)


def st_distance(a: Column, b: Column) -> Column:
    """Euclidean point distance (the reference's planar ST_Distance)."""
    ax, ay, av = _xy(a, "ST_Distance")
    bx, by, bv = _xy(b, "ST_Distance")
    dx = ax - bx
    dy = ay - by
    valid = av if bv is None else (bv if av is None else av & bv)
    return Column(DOUBLE, jnp.sqrt(dx * dx + dy * dy), valid)


def great_circle_distance(lat1: Column, lon1: Column, lat2: Column,
                          lon2: Column) -> Column:
    """Haversine distance in km (reference GeoFunctions
    great_circle_distance)."""
    lanes = [jnp.radians(jnp.asarray(c.data).astype(jnp.float64))
             for c in (lat1, lon1, lat2, lon2)]
    p1, l1, p2, l2 = lanes
    dphi = p2 - p1
    dlmb = l2 - l1
    h = (jnp.sin(dphi / 2) ** 2
         + jnp.cos(p1) * jnp.cos(p2) * jnp.sin(dlmb / 2) ** 2)
    d = 2 * EARTH_RADIUS_KM * jnp.arcsin(jnp.sqrt(jnp.clip(h, 0.0,
                                                           1.0)))
    return Column(DOUBLE, d, _merge_valid(lat1, lon1, lat2, lon2))


# --------------------------------------------------------------------------
# WKT in/out
# --------------------------------------------------------------------------

_POINT_RE = re.compile(
    r"\s*POINT\s*\(\s*([-+0-9.eE]+)\s+([-+0-9.eE]+)\s*\)\s*\Z",
    re.IGNORECASE)


def geometry_from_text(c: Column) -> Column:
    """WKT varchar -> geometry. POINT text becomes (x, y) lanes;
    any other shape stays dictionary-coded WKT (parsed lazily by the
    consuming kernel)."""
    if not is_string(c.type) or c.dictionary is None:
        raise ValueError("ST_GeometryFromText expects varchar WKT")
    vals = c.dictionary.values
    xs, ys, all_points = [], [], True
    for v in vals:
        m = _POINT_RE.match(str(v))
        if m is None:
            all_points = False
            break
        xs.append(float(m.group(1)))
        ys.append(float(m.group(2)))
    if all_points and len(vals):
        codes = jnp.asarray(c.data).astype(jnp.int32)
        x = jnp.take(jnp.asarray(np.asarray(xs)), codes, mode="clip")
        y = jnp.take(jnp.asarray(np.asarray(ys)), codes, mode="clip")
        return Column(GEOMETRY, x, c.valid, data2=y)
    return Column(GEOMETRY, jnp.asarray(c.data), c.valid, c.dictionary)


def as_text(c: Column) -> Column:
    if c.dictionary is not None:      # WKT-backed shape: passthrough
        return Column(VARCHAR, jnp.asarray(c.data), c.valid,
                      c.dictionary)
    _require_points(c, "ST_AsText")
    xs = np.asarray(c.data)
    ys = np.asarray(c.data2)
    out = [f"POINT ({_fmt(xs[i])} {_fmt(ys[i])})"
           for i in range(len(xs))]
    d, codes = StringDictionary.from_strings(out)
    return Column(VARCHAR, jnp.asarray(codes), c.valid, d)


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


# --------------------------------------------------------------------------
# polygon containment
# --------------------------------------------------------------------------

_POLY_RE = re.compile(
    r"\s*POLYGON\s*\((?P<rings>.*)\)\s*\Z", re.IGNORECASE | re.DOTALL)
_RING_RE = re.compile(r"\(([^()]*)\)")


def _parse_polygon(wkt: str) -> List[Tuple[np.ndarray, np.ndarray]]:
    """ALL rings of a POLYGON — shell first, then interior rings
    (holes). The even-odd rule over the union of every ring's edges
    makes holes fall out for free: a point inside a hole crosses both
    the shell and the hole boundary an odd number of times each, XORing
    back to outside. (Dropping interior rings — the pre-fix behavior —
    reported points inside a donut hole as contained.)"""
    m = _POLY_RE.match(wkt)
    if m is None:
        raise ValueError(f"unsupported geometry for ST_Contains: "
                         f"{wkt[:40]!r}")
    rings: List[Tuple[np.ndarray, np.ndarray]] = []
    for ring in _RING_RE.findall(m.group("rings")):
        pts = []
        for pair in ring.split(","):
            xy = pair.split()
            pts.append((float(xy[0]), float(xy[1])))
        if len(pts) < 3:
            raise ValueError(
                f"degenerate polygon ring in: {wkt[:40]!r}")
        arr = np.asarray(pts, dtype=np.float64)
        rings.append((arr[:, 0], arr[:, 1]))
    if not rings:
        raise ValueError(f"unsupported geometry for ST_Contains: "
                         f"{wkt[:40]!r}")
    return rings


def _ray_cast(px: jax.Array, py: jax.Array, xs: np.ndarray,
              ys: np.ndarray) -> jax.Array:
    """Vectorized even-odd rule: one pass per polygon edge, all rows
    at once (the VPU-friendly inversion of per-row point-in-polygon)."""
    inside = jnp.zeros(px.shape, dtype=bool)
    n = len(xs)
    for i in range(n - 1):
        xi, yi, xj, yj = xs[i], ys[i], xs[i + 1], ys[i + 1]
        if yi == yj:
            continue
        crosses = ((yi > py) != (yj > py)) & (
            px < (xj - xi) * (py - yi) / (yj - yi) + xi)
        inside = inside ^ crosses
    return inside


def st_contains(shape: Column, points: Column) -> Column:
    """Polygon-contains-point, polygons dictionary-coded WKT: each
    DISTINCT polygon parses once and masks every row vectorized; rows
    pick their polygon's verdict by dictionary code."""
    _require_points(points, "ST_Contains (point argument)")
    if shape.dictionary is None:
        raise ValueError(
            "ST_Contains expects a WKT-backed shape (POLYGON) as the "
            "first argument")
    px = jnp.asarray(points.data)
    py = jnp.asarray(points.data2)
    masks = []
    parse_ok = []
    for wkt in shape.dictionary.values:
        # an unparseable dictionary value NULLs only the rows that
        # reference it — a filter legitimately strands dead values in
        # the dictionary
        try:
            rings = _parse_polygon(str(wkt))
        except ValueError:
            masks.append(jnp.zeros(px.shape, bool))
            parse_ok.append(False)
            continue
        # even-odd across ALL rings: XOR of the per-ring verdicts is
        # exactly the edge-union crossing parity (holes excluded)
        mask = jnp.zeros(px.shape, dtype=bool)
        for xs, ys in rings:
            mask = mask ^ _ray_cast(px, py, xs, ys)
        masks.append(mask)
        parse_ok.append(True)
    stacked = jnp.stack(masks) if masks else jnp.zeros(
        (1,) + px.shape, bool)
    codes = jnp.clip(jnp.asarray(shape.data).astype(jnp.int32), 0,
                     max(len(masks) - 1, 0))
    data = jnp.take_along_axis(stacked, codes[None, :], axis=0)[0]
    valid = _merge_valid(shape, points)
    if not all(parse_ok):
        ok = jnp.take(jnp.asarray(np.asarray(parse_ok, bool)), codes,
                      mode="clip")
        valid = ok if valid is None else valid & ok
    return Column(BOOLEAN, data, valid)
