"""64-bit hashing for repartitioning / hash joins / group-by.

Reference parity: Trino computes per-row raw hashes via per-type
XxHash64-based TypeOperators (core/trino-spi/.../type/TypeOperators.java,
operator/InterpretedHashGenerator.java) and combines columns with
CombineHashFunction (31*h1+h2, operator/scalar/CombineHashFunction.java).
Here we use a splitmix64-style finalizer — fully vectorizable on the VPU —
and the same multiply-combine across key columns.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

_C1 = jnp.uint64(0xBF58476D1CE4E5B9)
_C2 = jnp.uint64(0x94D049BB133111EB)
_GOLDEN = jnp.uint64(0x9E3779B97F4A7C15)


def mix64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer over a uint64 lane."""
    x = jnp.asarray(x).astype(jnp.uint64)
    x = x ^ (x >> jnp.uint64(30))
    x = x * _C1
    x = x ^ (x >> jnp.uint64(27))
    x = x * _C2
    x = x ^ (x >> jnp.uint64(31))
    return x


def float_equality_lanes(d: jax.Array):
    """Exact equality-preserving decomposition of a float lane into two
    int64 lanes (mantissa*2^53, exponent).

    The natural encoding — bitcast f64->u64 — is NOT implemented by the
    TPU backend's x64-emulation rewrite (verified on v5e), so we use
    jnp.frexp instead, which lowers fine. Canonicalizes -0.0 == 0.0 and
    all NaNs equal (SQL distinct-from semantics, reference:
    spi/type/DoubleType.java#hash)."""
    d = jnp.asarray(d).astype(jnp.float64)
    d = jnp.where(d == 0.0, 0.0, d)
    isnan = jnp.isnan(d)
    isinf = jnp.isinf(d)
    special = isnan | isinf
    safe = jnp.where(special, 0.0, d)
    m, e = jnp.frexp(safe)
    mi = (m * float(1 << 53)).astype(jnp.int64)
    ex = e.astype(jnp.int64)
    code = jnp.where(isnan, 1, jnp.where(d > 0, 2, 3))
    mi = jnp.where(special, code.astype(jnp.int64), mi)
    ex = jnp.where(special, jnp.int64(5000), ex)
    return mi, ex


def equality_lanes(data: jax.Array):
    """List of int64/uint64 lanes whose tuple-equality == SQL equality of
    the value lane. One lane for ints/bools/codes; two for floats."""
    d = jnp.asarray(data)
    if d.dtype in (jnp.float32, jnp.float64):
        mi, ex = float_equality_lanes(d)
        return [mi.astype(jnp.uint64), ex.astype(jnp.uint64)]
    if d.dtype == jnp.bool_:
        return [d.astype(jnp.uint64)]
    return [d.astype(jnp.int64).astype(jnp.uint64)]


def lane_to_u64(data: jax.Array) -> jax.Array:
    """Single uint64 lane for hashing. Exact (bijective cast) for
    ints/bools; for floats, a mix of the two equality lanes (collisions
    ~2^-64, acceptable for hashing)."""
    d = jnp.asarray(data)
    if d.dtype in (jnp.float32, jnp.float64):
        mi, ex = float_equality_lanes(d)
        return mix64(mi.astype(jnp.uint64)) + ex.astype(jnp.uint64)
    if d.dtype == jnp.bool_:
        return d.astype(jnp.uint64)
    return d.astype(jnp.int64).astype(jnp.uint64)


def hash_column(data: jax.Array, valid: Optional[jax.Array]) -> jax.Array:
    """Per-row 64-bit hash of one lane; NULL hashes to 0 (Trino convention:
    AbstractLongType.hash of null position == 0 via mayHaveNull path)."""
    h = mix64(lane_to_u64(data))
    if valid is not None:
        h = jnp.where(jnp.asarray(valid), h, jnp.uint64(0))
    return h


def combine_hashes(hashes: Sequence[jax.Array]) -> jax.Array:
    """CombineHashFunction.getHash: h = 31*h + x, vectorized."""
    acc = jnp.zeros_like(hashes[0]) + _GOLDEN
    for h in hashes:
        acc = acc * jnp.uint64(31) + h
    return mix64(acc)


def hash_columns(cols) -> jax.Array:
    """Hash a list of Columns into one uint64 lane."""
    return combine_hashes([hash_column(c.data, c.valid) for c in cols])


def partition_of(h: jax.Array, num_partitions: int) -> jax.Array:
    """Map a 64-bit hash to [0, num_partitions) — the PagePartitioner hash
    bucket (reference: operator/PartitionedOutputOperator.java:308)."""
    return (h % jnp.uint64(num_partitions)).astype(jnp.int32)
