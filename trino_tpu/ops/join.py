"""Joins — LookupJoinOperator / HashBuilderOperator, TPU style.

Reference parity: operator/HashBuilderOperator.java:51 (build side),
operator/LookupJoinOperator.java:71 + JoinProbe (probe loop),
NestedLoopJoinOperator, HashSemiJoinOperator. Redesign for XLA
(SURVEY.md §7.3): the serial open-addressing probe becomes a vectorized
sort + binary-search join:

1. build keys are reduced to a single uint64 equality lane (bijective
   splitmix64 for one integer key column — exact; multi-column and
   float keys are hash-combined, accepting a ~n^2/2^64 collision
   probability with NO re-verification — acknowledged in SURVEY.md §7
   "hard parts"; string keys are first remapped onto a dictionary
   merged across both sides so codes are comparable),
2. the build side is sorted by that lane (nulls/dead rows forced past the
   valid prefix), and
3. every probe row finds its match run via two ``searchsorted`` calls —
   O(log n) per row, all rows in parallel on the VPU.

Output cardinality is data-dependent: callers run ``match_counts`` first,
read the total on the host, pick a power-of-two capacity bucket, then run
the expansion jit with that static capacity (the two-phase analog of
Trino's incremental JoinProbe yielding pages).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import Batch, Column
from .hashing import combine_hashes, lane_to_u64, mix64

_U64MAX = jnp.uint64(0xFFFFFFFFFFFFFFFF)


def align_string_keys(probe: Batch, build: Batch,
                      probe_keys: Sequence[str],
                      build_keys: Sequence[str]) -> Tuple[Batch, Batch]:
    """Remap string key columns of both sides onto a merged dictionary so
    that code equality == string equality across batches (dictionary
    codes are only meaningful within one dictionary)."""
    pcols = dict(probe.columns)
    bcols = dict(build.columns)
    for pk, bk in zip(probe_keys, build_keys):
        pc, bc = pcols[pk], bcols[bk]
        if pc.dictionary is None or bc.dictionary is None:
            continue
        if pc.dictionary is bc.dictionary:
            continue
        merged, rs, ro = pc.dictionary.merge(bc.dictionary)
        pcols[pk] = pc.with_dictionary(merged, rs)
        bcols[bk] = bc.with_dictionary(merged, ro)
    return (Batch(pcols, probe.num_rows), Batch(bcols, build.num_rows))


def equality_lane(batch: Batch, key_names: Sequence[str]) -> Tuple[
        jax.Array, jax.Array]:
    """(lane, usable) — uint64 equality-preserving key lane; usable is
    False for dead rows and rows with any NULL key (SQL: null join keys
    never match, reference: JoinProbe skips null channels)."""
    usable = batch.row_valid()
    lanes = []
    for name in key_names:
        col = batch.column(name)
        lanes.append(lane_to_u64(col.data))
        if col.valid is not None:
            usable = usable & jnp.asarray(col.valid)
    if len(lanes) == 1:
        lane = mix64(lanes[0])  # bijective -> exact equality
    else:
        lane = combine_hashes([mix64(l) for l in lanes])
    return lane, usable


def build_side(batch: Batch, key_names: Sequence[str]):
    """Sort the build side by key lane. Returns (sorted_keys, perm, m)
    where the first m entries are usable sorted keys and the tail is
    forced to U64MAX."""
    lane, usable = equality_lane(batch, key_names)
    cap = batch.capacity
    primary = (~usable).astype(jnp.uint64)
    order = jnp.lexsort((lane, primary))
    m = jnp.sum(usable.astype(jnp.int64))
    pos = jnp.arange(cap, dtype=jnp.int64)
    sorted_lane = jnp.where(pos < m, jnp.take(lane, order), _U64MAX)
    return sorted_lane, order, m


def match_counts(probe: Batch, build: Batch,
                 probe_keys: Sequence[str], build_keys: Sequence[str]):
    """Per-probe-row (start, count) of the build match run + total rows.

    start indexes the *sorted* build order; map through perm for payload.
    """
    probe, build = align_string_keys(probe, build, probe_keys, build_keys)
    lane_p, usable_p = equality_lane(probe, probe_keys)
    sorted_lane, order, m = build_side(build, build_keys)
    left = jnp.searchsorted(sorted_lane, lane_p, side="left")
    right = jnp.searchsorted(sorted_lane, lane_p, side="right")
    left = jnp.minimum(left, m)
    right = jnp.minimum(right, m)
    count = jnp.where(usable_p, right - left, 0)
    return left, count, order


def expand_join(probe: Batch, build: Batch, start, count, order,
                out_capacity: int, join_type: str = "inner",
                build_prefix: str = "") -> Batch:
    """Materialize join output rows given per-probe match runs.

    join_type: inner | left. For 'left', probe rows with no match emit one
    row with NULL build columns (reference: LookupJoinOperator
    outer-position tracking)."""
    outer = join_type == "left"
    live_p = probe.row_valid()
    eff_count = (jnp.where(live_p, jnp.maximum(count, 1), 0)
                 if outer else count)
    no_match = count == 0

    incl = jnp.cumsum(eff_count)
    total = incl[-1]
    offs = incl - eff_count  # exclusive

    i = jnp.arange(out_capacity, dtype=jnp.int64)
    p = jnp.searchsorted(incl, i, side="right")
    p = jnp.clip(p, 0, probe.capacity - 1)
    j = i - jnp.take(offs, p)
    b_sorted = jnp.take(start, p) + j
    b = jnp.take(order, jnp.clip(b_sorted, 0, build.capacity - 1))

    pad_build = (jnp.take(no_match, p) if outer else None)

    cols = {}
    for name, col in probe.columns.items():
        cols[name] = col.gather(p)
    for name, col in build.columns.items():
        out_name = build_prefix + name
        if outer:
            cols[out_name] = col.gather(b, fill_invalid=pad_build)
        else:
            cols[out_name] = col.gather(b)
    return Batch(cols, total)


def semi_join_mask(probe: Batch, build: Batch, probe_keys: Sequence[str],
                   build_keys: Sequence[str]):
    """(matched, probe_key_null, build_has_null, build_nonempty) device
    values for IN / semi-join with full SQL three-valued semantics
    (reference: operator/HashSemiJoinOperator.java — probe null or
    build-side null yields NULL, else TRUE/FALSE)."""
    probe, build = align_string_keys(probe, build, probe_keys, build_keys)
    lane_p, usable_p = equality_lane(probe, probe_keys)
    sorted_lane, order, m = build_side(build, build_keys)
    left = jnp.minimum(jnp.searchsorted(sorted_lane, lane_p, "left"), m)
    right = jnp.minimum(jnp.searchsorted(sorted_lane, lane_p, "right"), m)
    matched = (right > left) & usable_p
    live_p = probe.row_valid()
    key_null = live_p & ~usable_p

    live_b = build.row_valid()
    any_null_key = jnp.zeros((), dtype=bool)
    for name in build_keys:
        col = build.column(name)
        if col.valid is not None:
            any_null_key = any_null_key | jnp.any(
                live_b & ~jnp.asarray(col.valid))
    nonempty = jnp.sum(live_b.astype(jnp.int64)) > 0
    return matched, key_null, any_null_key, nonempty


def cross_counts(probe: Batch, build: Batch):
    """Nested-loop cross join sizing (reference:
    operator/NestedLoopJoinOperator.java)."""
    nb = build.num_rows_device()
    count = jnp.where(probe.row_valid(), nb, 0)
    start = jnp.zeros((probe.capacity,), dtype=jnp.int64)
    order = jnp.arange(build.capacity, dtype=jnp.int64)
    return start, count, order
