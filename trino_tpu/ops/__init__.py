"""Device kernels: the engine's operator library (reference:
core/trino-main/src/main/java/io/trino/operator/ — 713 files), rebuilt as
vectorized XLA programs."""

from . import compact, groupby, hashing, join, sort  # noqa: F401
