"""Complex-type (ARRAY / MAP / ROW) and lambda (higher-order) evaluation.

Reference parity: operator/scalar/ArrayTransformFunction.java,
ArrayFilterFunction, ReduceFunction, ZipWithFunction, MapFilterFunction,
MapTransformKeys/ValuesFunction, MapFunctions, ArrayFunctions (SURVEY.md
Appendix A.10), and the SpecialForm row/field machinery.

TPU-first design note: the hot engine path (scan/filter/join/aggregate)
is device-compiled; complex-type expressions are an auxiliary SQL surface
whose per-row variable-length structure is hostile to static shapes, so
they evaluate host-side in numpy over the same flat struct-of-arrays
Column layout (offsets + lengths + flat element pools). Any chain-JIT
attempt that traces into these functions raises a concretization error
and the executor transparently re-runs the chain eagerly
(exec/executor.py:144-155).

Lambdas: a ``rex.Lambda`` carries synthetic parameter symbols; the body
is evaluated by the ordinary vectorized evaluator over a Batch whose
"rows" are the flat ELEMENTS of the canonicalized array — one eval for
all rows' elements, never a per-row python loop (except ``reduce``,
which is inherently sequential in its state and loops over element
POSITIONS, still vectorized across rows).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..columnar import Batch, Column, StringDictionary
from ..rex import Call, Lambda, input_names
from ..types import (BIGINT, BOOLEAN, ArrayType, MapType, RowType, Type,
                     VARCHAR, is_string)


class EvalError(Exception):
    # re-exported name; exec.expr defines the canonical class. Kept so
    # this module can be imported standalone in tests.
    pass


def _err():
    from .expr import EvalError as E
    return E


def _eval(e, batch):
    from .expr import eval_expr
    return eval_expr(e, batch)


def _np(x):
    return np.asarray(x)


def _host_int(x) -> int:
    """Host-sync an int; raises under jit tracing (triggering the
    executor's eager fallback)."""
    return int(x)


def _valid_np(col: Column, n: int) -> np.ndarray:
    if col.valid is None:
        return np.ones(n, dtype=bool)
    return _np(col.valid)[:n].astype(bool)


def canonicalize(col: Column, cap: Optional[int] = None,
                 valid_override: Optional[np.ndarray] = None) -> Column:
    """Re-pack an ARRAY/MAP column so offsets are the cumsum of lengths
    and the element pool contains exactly the live elements in row
    order. Gathered/sliced columns share (and may overlap) their pools;
    canonical form restores the owner[flat_idx] bijection every
    element-wise kernel needs. ``valid_override`` additionally zeroes
    rows an enclosing op has decided are NULL (so two columns packed
    with the same override stay entry-aligned)."""
    cap = col.capacity if cap is None else cap
    offs = _np(col.data)[:cap].astype(np.int64)
    lens = _np(col.data2)[:cap].astype(np.int64)
    valid = _valid_np(col, cap)
    if valid_override is not None:
        valid = valid & valid_override
    lens = np.where(valid, lens, 0)
    new_offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    total = _host_int(lens.sum())
    # flat gather indices: for element j of row i -> offs[i] + j
    owner = np.repeat(np.arange(cap, dtype=np.int64), lens)
    j = (np.arange(total, dtype=np.int64)
         - np.repeat(new_offs, lens))
    src = offs[owner] + j
    elements = _take_flat(col.elements, src)
    elements2 = (None if col.elements2 is None
                 else _take_flat(col.elements2, src))
    return Column(col.type, new_offs,
                  None if valid.all() else valid, None, lens,
                  elements, elements2)


def _take_flat(el: Column, idx: np.ndarray) -> Column:
    """Gather a flat element pool by indices (host)."""
    n = len(_np(el.data))
    safe = np.clip(idx, 0, max(n - 1, 0))
    data = _np(el.data)[safe] if n else np.zeros(0, _np(el.data).dtype)
    valid = None if el.valid is None else _np(el.valid)[safe]
    d2 = None if el.data2 is None else _np(el.data2)[safe]
    elements = None
    if el.elements is not None:
        # nested arrays: offsets lane gathered, pool shared
        elements = el.elements
    e2 = el.elements2
    children = (None if el.children is None
                else tuple(_take_flat(c, idx) for c in el.children))
    return Column(el.type, data, valid, el.dictionary, d2, elements, e2,
                  children)


def _owners(col: Column, cap: int) -> np.ndarray:
    """owner[flat_idx] for a CANONICAL column."""
    lens = np.where(_valid_np(col, cap),
                    _np(col.data2)[:cap].astype(np.int64), 0)
    return np.repeat(np.arange(cap, dtype=np.int64), lens)


def _element_batch(params_cols: Dict[str, Column], body, outer: Batch,
                   owner: np.ndarray) -> Batch:
    """Batch over flat elements: lambda params -> element pools, free
    outer references -> outer columns gathered by element owner."""
    total = len(owner)
    cols = dict(params_cols)
    free = input_names(body) - set(params_cols)
    for name in free:
        if name in outer.columns:
            cols[name] = outer.columns[name].gather(owner)
    return Batch(cols, total)


def _rebuild(arr_type: Type, canon: Column, new_elements: Column,
             elements2: Optional[Column] = None) -> Column:
    return Column(arr_type, canon.data, canon.valid, None, canon.data2,
                  new_elements, elements2)


# --------------------------------------------------------------------------
# constructors / accessors
# --------------------------------------------------------------------------

def array_ctor_complex(e: Call, items, batch: Batch) -> Column:
    """ARRAY[a, b, ...] where elements are themselves ARRAY/MAP/ROW
    columns: pools are merged host-side; the flat pool is interleaved
    row-major (row r's elements at flat positions r*k..r*k+k-1)."""
    cap = batch.capacity
    k = len(items)
    first = items[0]
    if first.children is not None:      # ROW elements
        flat_children = []
        for ci in range(len(first.children)):
            parts = [it.children[ci] for it in items]
            flat_children.append(_interleave_flat(parts, cap))
        fvalid = _interleave_valids(items, cap)
        flat = Column(first.type, np.zeros(cap * k, np.int8), fvalid,
                      children=tuple(flat_children))
    else:                               # ARRAY/MAP elements
        canons = [canonicalize(it, cap) for it in items]
        pools = [c.elements for c in canons]
        pool = _concat_flat(pools)
        bases = np.cumsum([0] + [len(_np(p.data)) for p in pools[:-1]])
        offs = np.stack([bases[i] + _np(c.data)[:cap].astype(np.int64)
                         for i, c in enumerate(canons)],
                        axis=1).reshape(-1)
        lens = np.stack([_np(c.data2)[:cap].astype(np.int64)
                         for c in canons], axis=1).reshape(-1)
        fvalid = _interleave_valids(items, cap)
        pool2 = (None if canons[0].elements2 is None
                 else _concat_flat([c.elements2 for c in canons]))
        flat = Column(first.type, offs, fvalid, None, lens, pool, pool2)
    start = np.arange(cap, dtype=np.int64) * k
    length = np.full(cap, k, np.int64)
    return Column(e.type, start, None, None, length, flat)


def _interleave_flat(parts, cap):
    """Row-interleave k row-aligned columns into one flat pool of
    length cap*k."""
    k = len(parts)
    idx = np.arange(cap * k, dtype=np.int64) // k
    gathered = [_take_flat(p, idx) for p in parts]
    # select element (i % k) from gathered[i % k]
    sel = np.arange(cap * k, dtype=np.int64) % k
    out = gathered[0]
    from dataclasses import replace as _rp
    data = _np(out.data).copy()
    valid = (None if all(g.valid is None for g in gathered)
             else np.ones(cap * k, bool))
    d2 = None if out.data2 is None else _np(out.data2).copy()
    if is_string(out.type):
        merged = gathered[0].dictionary
        remaps = [np.arange(len(merged), dtype=np.int64)]
        for g in gathered[1:]:
            merged, _, ro = merged.merge(g.dictionary)
            remaps.append(ro)
        data = data.astype(np.int64)
        for i, g in enumerate(gathered):
            m = sel == i
            data[m] = remaps[i][_np(g.data)[m].astype(np.int64)]
        data = data.astype(np.int32)
        for i, g in enumerate(gathered):
            if valid is not None:
                m = sel == i
                valid[m] = (np.ones(m.sum(), bool) if g.valid is None
                            else _np(g.valid)[m].astype(bool))
        return Column(out.type, data, valid, merged)
    for i, g in enumerate(gathered[1:], start=1):
        m = sel == i
        data[m] = _np(g.data)[m]
        if d2 is not None and g.data2 is not None:
            d2[m] = _np(g.data2)[m]
    if valid is not None:
        for i, g in enumerate(gathered):
            m = sel == i
            valid[m] = (np.ones(int(m.sum()), bool) if g.valid is None
                        else _np(g.valid)[m].astype(bool))
    return Column(out.type, data, valid, None, d2, out.elements,
                  out.elements2, out.children)


def _interleave_valids(items, cap):
    k = len(items)
    if all(it.valid is None for it in items):
        return None
    vl = [np.ones(cap, bool) if it.valid is None
          else _np(it.valid)[:cap].astype(bool) for it in items]
    return np.stack(vl, axis=1).reshape(-1)

def _map_ctor(e: Call, batch: Batch) -> Column:
    keys_arr = _eval(e.args[0], batch)
    vals_arr = _eval(e.args[1], batch)
    cap = batch.capacity
    # rows where either side is NULL produce a NULL map; packing BOTH
    # pools with the combined validity keeps them entry-aligned (a
    # keys-valid/values-NULL row must not leave orphan key entries that
    # shift every later row's value offsets)
    both = _valid_np(keys_arr, cap) & _valid_np(vals_arr, cap)
    k = canonicalize(keys_arr, cap, valid_override=both)
    v = canonicalize(vals_arr, cap, valid_override=both)
    kl = _np(k.data2)[:cap]
    vl = _np(v.data2)[:cap]
    n = batch.num_rows_host() if not isinstance(batch.num_rows, int) \
        else batch.num_rows
    live = np.arange(cap) < n
    if np.any((kl != vl) & both & live):
        raise _err()("map(): key and value arrays must have equal "
                     "lengths")
    valid = None if both.all() else both
    return Column(e.type, k.data, valid, None, k.data2, k.elements,
                  v.elements)


def _row_ctor(e: Call, batch: Batch) -> Column:
    items = tuple(_eval(a, batch) for a in e.args)
    cap = batch.capacity
    return Column(e.type, np.zeros(cap, dtype=np.int8), None,
                  children=items)


def _row_field(e: Call, batch: Batch) -> Column:
    row = _eval(e.args[0], batch)
    idx = int(e.args[1].value)
    child = row.children[idx]
    if row.valid is not None:
        v = (_np(row.valid).astype(bool)
             if child.valid is None
             else (_np(child.valid).astype(bool)
                   & _np(row.valid).astype(bool)))
        from dataclasses import replace as _rp
        child = _rp(child, valid=v)
    return child


def _map_element_at(e: Call, batch: Batch) -> Column:
    """element_at(map, key) / m[key]: per-row key lookup, NULL when
    absent. Vectorized: canonical owners + equality over the flat key
    pool, last match wins (duplicate keys keep the later entry, matching
    map_concat semantics)."""
    m = _eval(e.args[0], batch)
    probe = _eval(e.args[1], batch)
    cap = batch.capacity
    canon = canonicalize(m, cap)
    owner = _owners(canon, cap)
    keys, vals = canon.elements, canon.elements2
    kdata = _np(keys.data)
    total = len(owner)
    pd = _np(probe.data)
    if is_string(keys.type):
        # align probe codes with the key pool's dictionary
        merged, rk, rp = keys.dictionary.merge(probe.dictionary)
        kcmp = rk[kdata[:total].astype(np.int64)] if total else \
            np.zeros(0, np.int64)
        pcmp = rp[pd.astype(np.int64)]
    else:
        kcmp = kdata[:total]
        pcmp = pd
    match = kcmp == pcmp[owner] if total else np.zeros(0, bool)
    if keys.valid is not None:
        match &= _np(keys.valid)[:total].astype(bool)
    # last matching flat index per owner (scatter in ascending order)
    found = np.full(cap, -1, dtype=np.int64)
    mi = np.nonzero(match)[0]
    found[owner[mi]] = mi
    ok = found >= 0
    out = _take_flat(vals, np.where(ok, found, 0))
    valid = ok & _valid_np(m, cap) & _valid_np(probe, cap)
    if out.valid is not None:
        valid = valid & _np(out.valid).astype(bool)
    from dataclasses import replace as _rp
    return _rp(out, valid=valid)


def _map_keys(e: Call, batch: Batch) -> Column:
    m = _eval(e.args[0], batch)
    return Column(e.type, m.data, m.valid, None, m.data2, m.elements)


def _map_values(e: Call, batch: Batch) -> Column:
    m = _eval(e.args[0], batch)
    return Column(e.type, m.data, m.valid, None, m.data2, m.elements2)


def _map_entries(e: Call, batch: Batch) -> Column:
    m = _eval(e.args[0], batch)
    cap = batch.capacity
    canon = canonicalize(m, cap)
    total = len(_owners(canon, cap))
    row_el = Column(e.type.element,
                    np.zeros(total, dtype=np.int8), None,
                    children=(canon.elements, canon.elements2))
    return _rebuild(e.type, canon, row_el)


def _map_concat(e: Call, batch: Batch) -> Column:
    """map_concat(m1, m2, ...): union, later maps win on duplicate
    keys."""
    maps = [canonicalize(_eval(a, batch), batch.capacity)
            for a in e.args]
    cap = batch.capacity
    # concat pools with a source-order tag, then keep the LAST
    # occurrence of each (row, key)
    owners, flats, srcs = [], [], []
    for si, m in enumerate(maps):
        ow = _owners(m, cap)
        owners.append(ow)
        flats.append(m)
        srcs.append(np.full(len(ow), si, dtype=np.int64))
    owner = np.concatenate(owners) if owners else np.zeros(0, np.int64)
    src = np.concatenate(srcs) if srcs else np.zeros(0, np.int64)
    # global element index within its source pool
    within = np.concatenate(
        [np.arange(len(o), dtype=np.int64) for o in owners]) \
        if owners else np.zeros(0, np.int64)
    # comparable key lane across pools
    keycols = [m.elements for m in maps]
    if any(is_string(k.type) for k in keycols):
        merged = keycols[0].dictionary
        remaps = [None] * len(keycols)
        remaps[0] = np.arange(len(merged), dtype=np.int64)
        for i in range(1, len(keycols)):
            merged, _, ro = merged.merge(keycols[i].dictionary)
            remaps[i] = ro
        klanes = [remaps[i][_np(k.data)[:len(owners[i])].astype(np.int64)]
                  for i, k in enumerate(keycols)]
    else:
        klanes = [_np(k.data)[:len(owners[i])]
                  for i, k in enumerate(keycols)]
    key = np.concatenate(klanes) if klanes else np.zeros(0, np.int64)
    # sort by (owner, key, src, within); keep last per (owner, key)
    order = np.lexsort((within, src, key, owner))
    so, sk = owner[order], key[order]
    is_last = np.ones(len(order), dtype=bool)
    if len(order) > 1:
        is_last[:-1] = (so[1:] != so[:-1]) | (sk[1:] != sk[:-1])
    # order[is_last] is already owner-major (lexsort primary key), so
    # the gathered pool is row-major; entries come out key-sorted per
    # row, which is fine — map entry order is not semantic
    keep = order[is_last]
    k_owner = owner[keep]
    lens = np.bincount(k_owner, minlength=cap).astype(np.int64)[:cap]
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    # gather surviving entries: ONE vectorized _take_flat per source
    # pool, then permute the concatenated result back into keep order
    out_k = _gather_multi([m.elements for m in maps], src[keep],
                          within[keep])
    out_v = _gather_multi([m.elements2 for m in maps], src[keep],
                          within[keep])
    valids = [_valid_np(m, cap) for m in maps]
    valid = valids[0]
    for v in valids[1:]:
        valid = valid & v
    return Column(e.type, offs, None if valid.all() else valid, None,
                  lens, out_k, out_v)


def _gather_multi(pools, src: np.ndarray, within: np.ndarray) -> Column:
    """Gather flat elements scattered across several source pools:
    pools[src[i]][within[i]] for each output position i — one
    vectorized _take_flat per pool plus a permutation, never a
    per-element gather."""
    if len(src) == 0:
        return _take_flat(pools[0], np.zeros(0, np.int64))
    order_by_src = np.argsort(src, kind="stable")
    parts = []
    for i, pool in enumerate(pools):
        sel = within[src == i]
        parts.append(_take_flat(pool, sel.astype(np.int64)))
    cat = _concat_flat([p for p in parts])
    inv = np.empty(len(src), dtype=np.int64)
    inv[order_by_src] = np.arange(len(src), dtype=np.int64)
    return _take_flat(cat, inv)


def concat_columns_host(cols, counts, cap: int) -> Column:
    """Concatenate the live prefixes of columns of ANY type host-side,
    padding the row lanes to ``cap``. The pooled-column (ARRAY/MAP/ROW)
    concat point for device_concat / concat_batches — pools merge with
    rebased offsets."""
    from ..columnar import _pad
    typ = cols[0].type
    if cols[0].elements is not None:
        # every offsets+pool column concatenates the same way: ARRAY,
        # MAP, and the sketch types (hyperloglog / tdigest / qdigest)
        # share the {data=start, data2=len, elements[,elements2]} layout
        canons = [canonicalize(c, n) for c, n in zip(cols, counts)]
        pools = [c.elements for c in canons]
        pool = _concat_flat(pools)
        pool2 = None
        if canons[0].elements2 is not None:
            pool2 = _concat_flat([c.elements2 for c in canons])
        bases = np.cumsum([0] + [len(_np(p.data)) for p in pools[:-1]])
        offs = np.concatenate(
            [b + _np(c.data)[:n].astype(np.int64)
             for b, c, n in zip(bases, canons, counts)]) \
            if counts else np.zeros(0, np.int64)
        lens = np.concatenate(
            [_np(c.data2)[:n].astype(np.int64)
             for c, n in zip(canons, counts)]) \
            if counts else np.zeros(0, np.int64)
        valid = None
        if any(c.valid is not None for c in canons):
            valid = np.concatenate(
                [_valid_np(c, n) for c, n in zip(canons, counts)])
        out = Column(typ, offs, valid, None, lens, pool, pool2)
        return _pad(out, cap)
    sliced = [_take_flat(c, np.arange(n, dtype=np.int64))
              for c, n in zip(cols, counts)]
    return _pad(_concat_flat(sliced), cap)


def _concat_flat(cols):
    """Concatenate flat element pools (host)."""
    if len(cols) == 1:
        return cols[0]
    typ = cols[0].type
    if is_string(typ):
        merged = cols[0].dictionary
        remaps = [np.arange(len(merged), dtype=np.int64)]
        for c in cols[1:]:
            merged, _, ro = merged.merge(c.dictionary)
            remaps.append(ro)
        data = np.concatenate(
            [r[_np(c.data).astype(np.int64)]
             for c, r in zip(cols, remaps)]).astype(np.int32)
        valid = _concat_valid(cols)
        return Column(typ, data, valid, merged)
    data = np.concatenate([_np(c.data) for c in cols])
    valid = _concat_valid(cols)
    d2 = None
    if any(c.data2 is not None for c in cols):
        d2 = np.concatenate(
            [(_np(c.data2) if c.data2 is not None
              else np.zeros(len(_np(c.data)), np.int64)) for c in cols])
    children = None
    if cols[0].children is not None:
        children = tuple(
            _concat_flat([c.children[i] for c in cols])
            for i in range(len(cols[0].children)))
    return Column(typ, data, valid, None, d2, cols[0].elements,
                  cols[0].elements2, children)


def _concat_valid(cols):
    if all(c.valid is None for c in cols):
        return None
    return np.concatenate(
        [(np.ones(len(_np(c.data)), bool) if c.valid is None
          else _np(c.valid).astype(bool)) for c in cols])


# --------------------------------------------------------------------------
# array scalar functions
# --------------------------------------------------------------------------

def _comparable_lane(el: Column, n: int, probe: Optional[Column] = None):
    """A numpy lane where == is value equality (and < is collation order
    for strings); optionally aligns a probe column into the same code
    space. Returns (lane, probe_lane|None)."""
    data = _np(el.data)[:n]
    if is_string(el.type):
        ranks = el.dictionary.rank_codes()
        if probe is not None:
            merged, rk, rp = el.dictionary.merge(probe.dictionary)
            mranks = merged.rank_codes()
            lane = mranks[rk[data.astype(np.int64)]] if n else data
            pl = mranks[rp[_np(probe.data).astype(np.int64)]]
            return lane, pl
        return ranks[data.astype(np.int64)] if n else data, None
    pl = None if probe is None else _np(probe.data)
    return data, pl


def _contains(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    probe = _eval(e.args[1], batch)
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    owner = _owners(canon, cap)
    total = len(owner)
    lane, pl = _comparable_lane(canon.elements, total, probe)
    match = lane == pl[owner] if total else np.zeros(0, bool)
    if canon.elements.valid is not None:
        match &= _np(canon.elements.valid)[:total].astype(bool)
    out = np.zeros(cap, dtype=bool)
    np.logical_or.at(out, owner, match)
    valid = _valid_np(arr, cap) & _valid_np(probe, cap)
    return Column(BOOLEAN, out, None if valid.all() else valid)


def _array_position(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    probe = _eval(e.args[1], batch)
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    owner = _owners(canon, cap)
    total = len(owner)
    lane, pl = _comparable_lane(canon.elements, total, probe)
    match = lane == pl[owner] if total else np.zeros(0, bool)
    if canon.elements.valid is not None:
        match &= _np(canon.elements.valid)[:total].astype(bool)
    offs = _np(canon.data).astype(np.int64)
    pos = np.zeros(cap, dtype=np.int64)
    mi = np.nonzero(match)[0][::-1]  # reversed: first match wins
    pos[owner[mi]] = mi - offs[owner[mi]] + 1
    valid = _valid_np(arr, cap) & _valid_np(probe, cap)
    return Column(BIGINT, pos, None if valid.all() else valid)


def _array_minmax(kind: str):
    def f(e: Call, batch: Batch) -> Column:
        arr = _eval(e.args[0], batch)
        cap = batch.capacity
        canon = canonicalize(arr, cap)
        owner = _owners(canon, cap)
        total = len(owner)
        el = canon.elements
        lane, _ = _comparable_lane(el, total)
        evalid = (np.ones(total, bool) if el.valid is None
                  else _np(el.valid)[:total].astype(bool))
        # NULL element -> result NULL (reference array_min/max)
        has_null = np.zeros(cap, dtype=bool)
        np.logical_or.at(has_null, owner, ~evalid)
        if total and np.issubdtype(lane.dtype, np.floating):
            sent = np.inf if kind == "min" else -np.inf
        else:
            ii = np.iinfo(lane.dtype if total else np.int64)
            sent = ii.max if kind == "min" else ii.min
        best = np.full(cap, sent, dtype=lane.dtype if total
                       else np.int64)
        op = np.minimum if kind == "min" else np.maximum
        if total:
            op.at(best, owner, np.where(evalid, lane, sent))
        lens = np.where(_valid_np(canon, cap),
                        _np(canon.data2)[:cap].astype(np.int64), 0)
        valid = _valid_np(arr, cap) & (lens > 0) & ~has_null
        if is_string(el.type):
            # map collation rank back to a code: pick the element whose
            # rank equals best via position trick
            ranks = el.dictionary.rank_codes()
            inv = np.argsort(ranks)
            codes = inv[np.clip(best, 0, len(inv) - 1)].astype(np.int32) \
                if len(inv) else best.astype(np.int32)
            return Column(el.type, codes,
                          None if valid.all() else valid, el.dictionary)
        return Column(el.type, best.astype(_np(el.data).dtype),
                      None if valid.all() else valid)
    return f


def _array_distinct(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    owner = _owners(canon, cap)
    total = len(owner)
    el = canon.elements
    lane, _ = _comparable_lane(el, total)
    evalid = (np.ones(total, bool) if el.valid is None
              else _np(el.valid)[:total].astype(bool))
    # keep the FIRST occurrence of each (owner, value); NULLs collapse
    # to one
    vkey = np.where(evalid, lane.astype(np.int64), np.int64(0))
    order = np.lexsort((np.arange(total), vkey, ~evalid, owner))
    so, sk, sv = owner[order], vkey[order], evalid[order]
    first = np.ones(total, dtype=bool)
    if total > 1:
        first[1:] = (so[1:] != so[:-1]) | (sk[1:] != sk[:-1]) \
            | (sv[1:] != sv[:-1])
    keep = np.sort(order[first])
    k_owner = owner[keep]
    lens = np.bincount(k_owner, minlength=cap).astype(np.int64)[:cap]
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    new_el = _take_flat(el, keep)
    return Column(e.type, offs, arr.valid if arr.valid is None else
                  _valid_np(arr, cap), None, lens, new_el)


def _array_sort(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    owner = _owners(canon, cap)
    total = len(owner)
    el = canon.elements
    lane, _ = _comparable_lane(el, total)
    evalid = (np.ones(total, bool) if el.valid is None
              else _np(el.valid)[:total].astype(bool))
    # ascending, NULLs last (reference array_sort)
    order = np.lexsort((lane, np.where(evalid, 0, 1), owner))
    new_el = _take_flat(el, order)
    return Column(e.type, canon.data, canon.valid, None, canon.data2,
                  new_el)


def _slice(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    start = _eval(e.args[1], batch)
    length = _eval(e.args[2], batch)
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    lens = np.where(_valid_np(canon, cap),
                    _np(canon.data2)[:cap].astype(np.int64), 0)
    offs = _np(canon.data)[:cap].astype(np.int64)
    s = _np(start.data)[:cap].astype(np.int64)
    ln = np.maximum(_np(length.data)[:cap].astype(np.int64), 0)
    begin = np.where(s > 0, s - 1, lens + s)  # 1-based / from-end
    begin_c = np.clip(begin, 0, lens)
    new_lens = np.clip(np.minimum(ln, lens - begin_c), 0, None)
    new_lens = np.where((s == 0) | (begin < 0) | (begin >= lens), 0,
                        new_lens)
    new_offs = np.concatenate([[0],
                               np.cumsum(new_lens)[:-1]]).astype(np.int64)
    owner = np.repeat(np.arange(cap, dtype=np.int64), new_lens)
    j = (np.arange(int(new_lens.sum()), dtype=np.int64)
         - np.repeat(new_offs, new_lens))
    src = offs[owner] + begin_c[owner] + j
    new_el = _take_flat(canon.elements, src)
    valid = _valid_np(arr, cap) & _valid_np(start, cap) \
        & _valid_np(length, cap)
    return Column(e.type, new_offs, None if valid.all() else valid,
                  None, new_lens, new_el)


def _repeat(e: Call, batch: Batch) -> Column:
    val = _eval(e.args[0], batch)
    cnt = _eval(e.args[1], batch)
    cap = batch.capacity
    n = np.clip(_np(cnt.data)[:cap].astype(np.int64), 0, None)
    offs = np.concatenate([[0], np.cumsum(n)[:-1]]).astype(np.int64)
    owner = np.repeat(np.arange(cap, dtype=np.int64), n)
    el = _take_flat(val, owner)
    valid = _valid_np(cnt, cap)
    return Column(e.type, offs, None if valid.all() else valid, None,
                  n, el)


def _sequence(e: Call, batch: Batch) -> Column:
    lo = _eval(e.args[0], batch)
    hi = _eval(e.args[1], batch)
    cap = batch.capacity
    valid = _valid_np(lo, cap) & _valid_np(hi, cap)
    if len(e.args) > 2:
        stepc = _eval(e.args[2], batch)
        step = _np(stepc.data)[:cap].astype(np.int64)
        valid = valid & _valid_np(stepc, cap)
    else:
        step = np.ones(cap, dtype=np.int64)
    a = _np(lo.data)[:cap].astype(np.int64)
    b = _np(hi.data)[:cap].astype(np.int64)
    n = batch.num_rows_host() if not isinstance(batch.num_rows, int) \
        else batch.num_rows
    live = np.arange(cap) < n
    if np.any((step == 0) & valid & live):
        raise _err()("sequence step must not be zero")
    safe_step = np.where(step == 0, 1, step)
    lens = np.maximum((b - a) // safe_step + 1, 0)
    lens = np.where(valid & live, lens, 0)
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    owner = np.repeat(np.arange(cap, dtype=np.int64), lens)
    j = (np.arange(int(lens.sum()), dtype=np.int64)
         - np.repeat(offs, lens))
    flat = a[owner] + j * safe_step[owner]
    el = Column(e.type.element, flat.astype(np.int64), None)
    return Column(e.type, offs, None if valid.all() else valid, None,
                  lens, el)


def _flatten(e: Call, batch: Batch) -> Column:
    """flatten(array(array(E))) -> array(E)."""
    arr = _eval(e.args[0], batch)
    cap = batch.capacity
    canon = canonicalize(arr, cap)   # outer arrays canonical
    owner = _owners(canon, cap)
    inner = canon.elements           # ARRAY-typed flat pool
    total = len(owner)
    ioffs = _np(inner.data)[:total].astype(np.int64)
    ilens = _np(inner.data2)[:total].astype(np.int64)
    if inner.valid is not None:
        ilens = np.where(_np(inner.valid)[:total].astype(bool), ilens, 0)
    out_lens = np.zeros(cap, dtype=np.int64)
    np.add.at(out_lens, owner, ilens)
    out_offs = np.concatenate([[0],
                               np.cumsum(out_lens)[:-1]]).astype(np.int64)
    # expand: for each inner array, its elements in order
    rep_inner = np.repeat(np.arange(total, dtype=np.int64), ilens)
    grand = int(ilens.sum())
    j = (np.arange(grand, dtype=np.int64)
         - np.repeat(np.concatenate([[0], np.cumsum(ilens)[:-1]]),
                     ilens))
    src = ioffs[rep_inner] + j
    el = _take_flat(inner.elements, src)
    return Column(e.type, out_offs, arr.valid, None, out_lens, el)


def _array_setop(kind: str):
    """array_union / array_intersect / array_except, fully vectorized:
    sort combined (owner, value, source) entries, derive distinct-value
    groups + per-source presence, keep groups per set semantics, emit
    each kept group's first entry."""
    def f(e: Call, batch: Batch) -> Column:
        a1 = _eval(e.args[0], batch)
        a2 = _eval(e.args[1], batch)
        cap = batch.capacity
        c1 = canonicalize(a1, cap)
        c2 = canonicalize(a2, cap)
        o1, o2 = _owners(c1, cap), _owners(c2, cap)
        t1, t2 = len(o1), len(o2)
        e1, e2 = c1.elements, c2.elements
        if is_string(e1.type) or is_string(e2.type):
            merged, r1, r2 = e1.dictionary.merge(e2.dictionary)
            ranks = merged.rank_codes()
            l1 = ranks[r1[_np(e1.data)[:t1].astype(np.int64)]] if t1 \
                else np.zeros(0, np.int64)
            l2 = ranks[r2[_np(e2.data)[:t2].astype(np.int64)]] if t2 \
                else np.zeros(0, np.int64)
        else:
            l1, l2 = _np(e1.data)[:t1], _np(e2.data)[:t2]
        v1 = (np.ones(t1, bool) if e1.valid is None
              else _np(e1.valid)[:t1].astype(bool))
        v2 = (np.ones(t2, bool) if e2.valid is None
              else _np(e2.valid)[:t2].astype(bool))
        owner = np.concatenate([o1, o2])
        nl = np.concatenate([~v1, ~v2])
        lk = np.where(~nl,
                      np.concatenate([l1, l2]).astype(np.int64), 0)
        srcarr = np.concatenate([np.zeros(t1, np.int64),
                                 np.ones(t2, np.int64)])
        within = np.concatenate([np.arange(t1, dtype=np.int64),
                                 np.arange(t2, dtype=np.int64)])
        total = len(owner)
        order = np.lexsort((within, srcarr, lk, nl, owner))
        so = owner[order]
        sn, sk = nl[order], lk[order]
        is_first = np.ones(total, bool)
        if total > 1:
            is_first[1:] = ((so[1:] != so[:-1]) | (sn[1:] != sn[:-1])
                            | (sk[1:] != sk[:-1]))
        gidv = np.cumsum(is_first) - 1
        ngroups = int(gidv[-1]) + 1 if total else 0
        pres = np.zeros((2, max(ngroups, 1)), bool)
        ss = srcarr[order]
        np.logical_or.at(pres[0], gidv[ss == 0], True)
        np.logical_or.at(pres[1], gidv[ss == 1], True)
        if kind == "union":
            keep_grp = np.ones(max(ngroups, 1), bool)
        elif kind == "intersect":
            keep_grp = pres[0] & pres[1]
        else:
            keep_grp = pres[0] & ~pres[1]
        rep = order[is_first]            # first entry of each group
        sel = keep_grp[:ngroups] if ngroups else np.zeros(0, bool)
        rep_keep = rep[sel]
        k_owner = owner[rep_keep]
        lens = np.bincount(k_owner, minlength=cap).astype(np.int64)[:cap]
        offs = np.concatenate([[0],
                               np.cumsum(lens)[:-1]]).astype(np.int64)
        el = _gather_multi([e1, e2], srcarr[rep_keep],
                           within[rep_keep])
        valid = _valid_np(a1, cap) & _valid_np(a2, cap)
        return Column(e.type, offs, None if valid.all() else valid,
                      None, lens, el)
    return f


def _arrays_overlap(e: Call, batch: Batch) -> Column:
    inter = _array_setop("intersect")(
        Call("array_intersect", e.args,
             _eval(e.args[0], batch).type), batch)
    lens = _np(inter.data2).astype(np.int64)
    return Column(BOOLEAN, lens > 0, inter.valid)


# --------------------------------------------------------------------------
# higher-order functions
# --------------------------------------------------------------------------

def _transform(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    lam: Lambda = e.args[1]
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    owner = _owners(canon, cap)
    eb = _element_batch({lam.params[0]: canon.elements}, lam.body,
                        batch, owner)
    out_el = _eval(lam.body, eb)
    return _rebuild(e.type, canon, out_el)


def _filter_arr(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    lam: Lambda = e.args[1]
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    owner = _owners(canon, cap)
    total = len(owner)
    eb = _element_batch({lam.params[0]: canon.elements}, lam.body,
                        batch, owner)
    pred = _eval(lam.body, eb)
    keepm = _np(pred.data)[:total].astype(bool)
    if pred.valid is not None:
        keepm &= _np(pred.valid)[:total].astype(bool)
    keep = np.nonzero(keepm)[0]
    k_owner = owner[keep]
    lens = np.bincount(k_owner, minlength=cap).astype(np.int64)[:cap]
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    el = _take_flat(canon.elements, keep)
    return Column(e.type, offs, canon.valid, None, lens, el)


def _match(kind: str):
    def f(e: Call, batch: Batch) -> Column:
        arr = _eval(e.args[0], batch)
        lam: Lambda = e.args[1]
        cap = batch.capacity
        canon = canonicalize(arr, cap)
        owner = _owners(canon, cap)
        total = len(owner)
        eb = _element_batch({lam.params[0]: canon.elements}, lam.body,
                            batch, owner)
        pred = _eval(lam.body, eb)
        pv = _np(pred.data)[:total].astype(bool)
        pnull = (~_np(pred.valid)[:total].astype(bool)
                 if pred.valid is not None else np.zeros(total, bool))
        any_true = np.zeros(cap, bool)
        any_false = np.zeros(cap, bool)
        any_null = np.zeros(cap, bool)
        np.logical_or.at(any_true, owner, pv & ~pnull)
        np.logical_or.at(any_false, owner, ~pv & ~pnull)
        np.logical_or.at(any_null, owner, pnull)
        valid = _valid_np(arr, cap)
        if kind == "any":
            # TRUE if any true; NULL if none true but a null; else FALSE
            out = any_true
            nul = ~any_true & any_null
        elif kind == "all":
            # FALSE if any false; NULL if no false but a null; else TRUE
            out = ~any_false & ~any_null
            nul = ~any_false & any_null
        else:  # none
            out = ~any_true & ~any_null
            nul = ~any_true & any_null
        valid = valid & ~nul
        return Column(BOOLEAN, out, None if valid.all() else valid)
    return f


def _reduce(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    init = _eval(e.args[1], batch)
    step: Lambda = e.args[2]
    outfn: Lambda = e.args[3]
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    offs = _np(canon.data)[:cap].astype(np.int64)
    lens = np.where(_valid_np(canon, cap),
                    _np(canon.data2)[:cap].astype(np.int64), 0)
    maxlen = int(lens.max()) if cap else 0
    state = init
    ssym, esym = step.params
    from dataclasses import replace as _rp
    for j in range(maxlen):
        idx = offs + j
        live = j < lens
        elem = _take_flat(canon.elements, np.where(live, idx, 0))
        eb_cols = {ssym: state, esym: elem}
        free = input_names(step.body) - set(step.params)
        for name in free:
            if name in batch.columns:
                eb_cols[name] = batch.columns[name]
        nb = Batch(eb_cols, cap)
        new_state = _eval(step.body, nb)
        # rows whose array is exhausted keep their state
        sv = _valid_np(state, cap)
        nv = _valid_np(new_state, cap)
        valid = np.where(live, nv, sv)
        if is_string(new_state.type):
            # codes from the two states live in different dictionaries:
            # unify before selecting per-row
            merged, rs, rn = state.dictionary.merge(
                new_state.dictionary)
            sd = rs[_np(state.data)[:cap].astype(np.int64)]
            nd = rn[_np(new_state.data)[:cap].astype(np.int64)]
            data = np.where(live, nd, sd).astype(np.int32)
            state = Column(new_state.type, data,
                           None if valid.all() else valid, merged)
        else:
            data = np.where(live, _np(new_state.data)[:cap],
                            _np(state.data)[:cap])
            d2 = None
            if new_state.data2 is not None or state.data2 is not None:
                zero = np.zeros(cap, np.int64)
                d2 = np.where(
                    live,
                    (_np(new_state.data2)[:cap]
                     if new_state.data2 is not None else zero),
                    (_np(state.data2)[:cap]
                     if state.data2 is not None else zero))
            state = Column(new_state.type, data,
                           None if valid.all() else valid, None, d2)
    ob = Batch({outfn.params[0]: state, **{
        n: batch.columns[n]
        for n in (input_names(outfn.body) - set(outfn.params))
        if n in batch.columns}}, cap)
    out = _eval(outfn.body, ob)
    av = _valid_np(arr, cap)
    ov = _valid_np(out, cap) & av
    return _rp(out, valid=None if ov.all() else ov)


def _zip_with(e: Call, batch: Batch) -> Column:
    a1 = _eval(e.args[0], batch)
    a2 = _eval(e.args[1], batch)
    lam: Lambda = e.args[2]
    cap = batch.capacity
    c1, c2 = canonicalize(a1, cap), canonicalize(a2, cap)
    l1 = np.where(_valid_np(c1, cap),
                  _np(c1.data2)[:cap].astype(np.int64), 0)
    l2 = np.where(_valid_np(c2, cap),
                  _np(c2.data2)[:cap].astype(np.int64), 0)
    lens = np.maximum(l1, l2)
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    total = int(lens.sum())
    owner = np.repeat(np.arange(cap, dtype=np.int64), lens)
    j = np.arange(total, dtype=np.int64) - np.repeat(offs, lens)
    from dataclasses import replace as _rp

    def pad_el(c, ln):
        src = _np(c.data)[:cap].astype(np.int64)[owner] + j
        inb = j < ln[owner]
        el = _take_flat(c.elements, np.where(inb, src, 0))
        v = inb if el.valid is None else \
            (_np(el.valid).astype(bool) & inb)
        return _rp(el, valid=v)

    e1, e2 = pad_el(c1, l1), pad_el(c2, l2)
    eb = _element_batch({lam.params[0]: e1, lam.params[1]: e2},
                        lam.body, batch, owner)
    out_el = _eval(lam.body, eb)
    valid = _valid_np(a1, cap) & _valid_np(a2, cap)
    return Column(e.type, offs, None if valid.all() else valid, None,
                  lens, out_el)


def _map_lambda(which: str):
    """map_filter / transform_keys / transform_values."""
    def f(e: Call, batch: Batch) -> Column:
        m = _eval(e.args[0], batch)
        lam: Lambda = e.args[1]
        cap = batch.capacity
        canon = canonicalize(m, cap)
        owner = _owners(canon, cap)
        total = len(owner)
        eb = _element_batch({lam.params[0]: canon.elements,
                             lam.params[1]: canon.elements2},
                            lam.body, batch, owner)
        out = _eval(lam.body, eb)
        if which == "filter":
            keepm = _np(out.data)[:total].astype(bool)
            if out.valid is not None:
                keepm &= _np(out.valid)[:total].astype(bool)
            keep = np.nonzero(keepm)[0]
            k_owner = owner[keep]
            lens = np.bincount(k_owner,
                               minlength=cap).astype(np.int64)[:cap]
            offs = np.concatenate(
                [[0], np.cumsum(lens)[:-1]]).astype(np.int64)
            return Column(e.type, offs, canon.valid, None, lens,
                          _take_flat(canon.elements, keep),
                          _take_flat(canon.elements2, keep))
        if which == "keys":
            return Column(e.type, canon.data, canon.valid, None,
                          canon.data2, out, canon.elements2)
        return Column(e.type, canon.data, canon.valid, None,
                      canon.data2, canon.elements, out)
    return f


def top_k_map_entries(col: Column, k: int) -> Column:
    """Keep each row's k highest-valued entries (value lane descending,
    key ascending on ties) — the output step of approx_most_frequent
    (reference: operator/aggregation/approxmostfrequent/)."""
    cap = col.capacity
    canon = canonicalize(col, cap)
    owner = _owners(canon, cap)
    total = len(owner)
    counts = _np(canon.elements2.data)[:total].astype(np.int64)
    klane, _ = _comparable_lane(canon.elements, total)
    order = np.lexsort((klane, -counts, owner))
    rank = np.empty(total, np.int64)
    # rank within owner group over the sorted order
    so = owner[order]
    first = np.ones(total, bool)
    if total > 1:
        first[1:] = so[1:] != so[:-1]
    gstart = np.maximum.accumulate(
        np.where(first, np.arange(total), 0))
    rank[order] = np.arange(total) - gstart
    keep = np.sort(order[rank[order] < k])
    k_owner = owner[keep]
    lens = np.bincount(k_owner, minlength=cap).astype(np.int64)[:cap]
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    return Column(col.type, offs, canon.valid, None, lens,
                  _take_flat(canon.elements, keep),
                  _take_flat(canon.elements2, keep))


def _map_zip_with(e: Call, batch: Batch) -> Column:
    """map_zip_with(m1, m2, (k, v1, v2) -> ...): key union per row;
    a key absent from one side binds its value parameter to NULL
    (reference: operator/scalar/MapZipWithFunction.java)."""
    m1 = canonicalize(_eval(e.args[0], batch), batch.capacity)
    m2 = canonicalize(_eval(e.args[1], batch), batch.capacity)
    lam: Lambda = e.args[2]
    cap = batch.capacity
    o1, o2 = _owners(m1, cap), _owners(m2, cap)
    t1, t2 = len(o1), len(o2)
    k1, k2 = m1.elements, m2.elements
    if is_string(k1.type) or is_string(k2.type):
        merged, r1, r2 = k1.dictionary.merge(k2.dictionary)
        l1 = r1[_np(k1.data)[:t1].astype(np.int64)] if t1 else \
            np.zeros(0, np.int64)
        l2 = r2[_np(k2.data)[:t2].astype(np.int64)] if t2 else \
            np.zeros(0, np.int64)
    else:
        l1 = _np(k1.data)[:t1].astype(np.int64)
        l2 = _np(k2.data)[:t2].astype(np.int64)
    owner = np.concatenate([o1, o2])
    keyl = np.concatenate([l1, l2])
    srcarr = np.concatenate([np.zeros(t1, np.int64),
                             np.ones(t2, np.int64)])
    within = np.concatenate([np.arange(t1, dtype=np.int64),
                             np.arange(t2, dtype=np.int64)])
    total = len(owner)
    order = np.lexsort((within, srcarr, keyl, owner))
    so, sk = owner[order], keyl[order]
    is_first = np.ones(total, bool)
    if total > 1:
        is_first[1:] = (so[1:] != so[:-1]) | (sk[1:] != sk[:-1])
    gidv = np.cumsum(is_first) - 1
    ngroups = int(gidv[-1]) + 1 if total else 0
    ss = srcarr[order]
    # first entry per (owner,key) group from EACH source (-1 = absent);
    # reversed scatter so the earliest sorted position wins
    src_idx = [np.full(max(ngroups, 1), -1, np.int64) for _ in (0, 1)]
    for s in (0, 1):
        selpos = np.nonzero(ss == s)[0][::-1]
        src_idx[s][gidv[selpos]] = order[selpos]
    ue = order[is_first]           # union entries, owner-major
    u_owner = owner[ue]
    ug = gidv[is_first]
    keys_pool = _gather_multi([k1, k2], srcarr[ue], within[ue])
    from dataclasses import replace as _rp

    def side_values(s, pool):
        idx = src_idx[s][ug]
        present = idx >= 0
        w = np.where(present, within[np.clip(idx, 0, max(total - 1, 0))]
                     if total else 0, 0)
        col = _take_flat(pool, np.asarray(w, np.int64))
        v = present if col.valid is None else \
            (_np(col.valid).astype(bool) & present)
        return _rp(col, valid=v)

    v1 = side_values(0, m1.elements2)
    v2 = side_values(1, m2.elements2)
    eb = _element_batch({lam.params[0]: keys_pool,
                         lam.params[1]: v1, lam.params[2]: v2},
                        lam.body, batch, u_owner)
    out_vals = _eval(lam.body, eb)
    lens = np.bincount(u_owner, minlength=cap).astype(np.int64)[:cap]
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    valid = _valid_np(m1, cap) & _valid_np(m2, cap)
    return Column(e.type, offs, None if valid.all() else valid, None,
                  lens, keys_pool, out_vals)


# --------------------------------------------------------------------------
# string -> array functions (SplitFunction, JoniRegexpFunctions'
# regexp_extract_all / regexp_split, SplitToMapFunction, ArrayJoin)
# --------------------------------------------------------------------------

def _mat_strings(col: Column, n: int):
    from .expr import _materialize_strings
    return _materialize_strings(col, n)


def _strings_array(e: Call, rows) -> Column:
    """Build an array(varchar) column from per-row python lists
    (None list -> NULL row; None element -> NULL entry)."""
    lens = np.asarray([0 if r is None else len(r) for r in rows],
                      np.int64)
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    flat = [p for r in rows if r is not None for p in r]
    dct, codes = StringDictionary.from_strings(flat)
    evalid = np.asarray([p is not None for p in flat], bool)
    el = Column(VARCHAR, codes,
                None if evalid.all() else evalid, dct)
    valid = np.asarray([r is not None for r in rows], bool)
    return Column(e.type, offs, None if valid.all() else valid, None,
                  lens, el)


def _const_arg(e: Call, i: int, what: str):
    from ..rex import Const as _C
    if not isinstance(e.args[i], _C):
        raise _err()(f"{e.name}: {what} must be constant")
    return e.args[i].value


def _split(e: Call, batch: Batch) -> Column:
    a = _eval(e.args[0], batch)
    delim = _const_arg(e, 1, "delimiter")
    limit = (int(_const_arg(e, 2, "limit")) if len(e.args) > 2
             else None)
    strs = _mat_strings(a, batch.capacity)
    rows = []
    for v in strs:
        if v is None:
            rows.append(None)
        elif limit is not None:
            rows.append(v.split(delim, limit - 1))
        else:
            rows.append(v.split(delim))
    return _strings_array(e, rows)


def _regexp_extract_all(e: Call, batch: Batch) -> Column:
    import re as _re
    a = _eval(e.args[0], batch)
    pat = _re.compile(_const_arg(e, 1, "pattern"))
    group = int(_const_arg(e, 2, "group")) if len(e.args) > 2 else 0
    strs = _mat_strings(a, batch.capacity)
    rows = [None if v is None
            else [m.group(group) for m in pat.finditer(v)]
            for v in strs]
    return _strings_array(e, rows)


def _regexp_split(e: Call, batch: Batch) -> Column:
    import re as _re
    a = _eval(e.args[0], batch)
    pat = _re.compile(_const_arg(e, 1, "pattern"))
    strs = _mat_strings(a, batch.capacity)
    rows = [None if v is None else pat.split(v) for v in strs]
    return _strings_array(e, rows)


def _split_to_map(e: Call, batch: Batch) -> Column:
    a = _eval(e.args[0], batch)
    entry_d = _const_arg(e, 1, "entryDelimiter")
    kv_d = _const_arg(e, 2, "keyValueDelimiter")
    strs = _mat_strings(a, batch.capacity)
    keys, vals = [], []
    for v in strs:
        if v is None:
            keys.append(None)
            vals.append(None)
            continue
        k_row, v_row = [], []
        for entry in v.split(entry_d):
            if not entry:
                continue
            if kv_d not in entry:
                raise _err()(
                    "split_to_map: entry without key-value delimiter")
            k, val = entry.split(kv_d, 1)
            if k in k_row:
                raise _err()(f"split_to_map: duplicate key {k!r}")
            k_row.append(k)
            v_row.append(val)
        keys.append(k_row)
        vals.append(v_row)
    karr = _strings_array(e, keys)
    varr = _strings_array(e, vals)
    return Column(e.type, karr.data, karr.valid, None, karr.data2,
                  karr.elements, varr.elements)


def _array_join(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    delim = _const_arg(e, 1, "delimiter")
    null_repl = (_const_arg(e, 2, "null replacement")
                 if len(e.args) > 2 else None)
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    total = _host_int(np.asarray(canon.data2)[:cap].sum())
    el = canon.elements
    if is_string(el.type):
        flat = _mat_strings(el, total)
    else:
        d = _np(el.data)[:total]
        ev = _valid_np(el, total)
        flat = []
        for i in range(total):
            if not ev[i]:
                flat.append(None)
            elif d.dtype.kind == "b":
                flat.append("true" if d[i] else "false")
            elif d.dtype.kind == "f":
                flat.append(repr(float(d[i])))
            else:
                flat.append(str(int(d[i])))
    offs = _np(canon.data)[:cap].astype(np.int64)
    lens = _np(canon.data2)[:cap].astype(np.int64)
    valid = _valid_np(canon, cap)
    out = []
    for i in range(cap):
        if not valid[i]:
            out.append(None)
            continue
        parts = []
        for j in range(int(lens[i])):
            v = flat[int(offs[i]) + j]
            if v is None:
                if null_repl is not None:
                    parts.append(null_repl)
            else:
                parts.append(v)
        out.append(delim.join(parts))
    dct, codes = StringDictionary.from_strings(out)
    ovalid = np.asarray([o is not None for o in out], bool)
    return Column(e.type, codes,
                  None if ovalid.all() else ovalid, dct)


DISPATCH = {
    "split": _split,
    "regexp_extract_all": _regexp_extract_all,
    "regexp_split": _regexp_split,
    "split_to_map": _split_to_map,
    "array_join": _array_join,
    "$map": _map_ctor,
    "$row": _row_ctor,
    "$field": _row_field,
    "map": _map_ctor,
    "map_keys": _map_keys,
    "map_values": _map_values,
    "map_entries": _map_entries,
    "map_concat": _map_concat,
    "contains": _contains,
    "array_position": _array_position,
    "array_min": _array_minmax("min"),
    "array_max": _array_minmax("max"),
    "array_distinct": _array_distinct,
    "array_sort": _array_sort,
    "slice": _slice,
    "repeat": _repeat,
    "sequence": _sequence,
    "flatten": _flatten,
    "array_union": _array_setop("union"),
    "array_intersect": _array_setop("intersect"),
    "array_except": _array_setop("except"),
    "arrays_overlap": _arrays_overlap,
    "transform": _transform,
    "filter": _filter_arr,
    "any_match": _match("any"),
    "all_match": _match("all"),
    "none_match": _match("none"),
    "reduce": _reduce,
    "zip_with": _zip_with,
    "map_filter": _map_lambda("filter"),
    "transform_keys": _map_lambda("keys"),
    "transform_values": _map_lambda("values"),
    "map_zip_with": _map_zip_with,
}


# --------------------------------------------------------------------------
# round-4 additions: zip / ngrams / combinations / array_remove /
# map_from_entries / multimap_from_entries / split_to_multimap /
# cosine_similarity (reference: operator/scalar/{ZipFunction,
# ArrayNgramsFunction,CombinationsFunction,ArrayRemoveFunction,
# MapFromEntriesFunction,MultimapFromEntriesFunction,StringFunctions,
# MathFunctions}.java)
# --------------------------------------------------------------------------

from dataclasses import replace as _dc_replace  # noqa: E402


def _array_remove(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    probe = _eval(e.args[1], batch)
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    owner = _owners(canon, cap)
    total = len(owner)
    el = canon.elements
    lane, pl = _comparable_lane(el, total, probe)
    # drop where element == probe; NULL probe or NULL element: keep
    drop = lane == pl[owner] if total else np.zeros(0, bool)
    if el.valid is not None:
        drop &= _np(el.valid)[:total].astype(bool)
    drop &= _valid_np(probe, cap)[owner]
    keep = np.nonzero(~drop)[0]
    k_owner = owner[keep]
    lens = np.bincount(k_owner, minlength=cap).astype(np.int64)[:cap]
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    return Column(e.type, offs, None if arr.valid is None
                  else _valid_np(arr, cap), None, lens,
                  _take_flat(el, keep))


def _zip_fn(e: Call, batch: Batch) -> Column:
    cap = batch.capacity
    arrs = [canonicalize(_eval(a, batch), cap) for a in e.args]
    lens = [np.where(_valid_np(a, cap),
                     _np(a.data2)[:cap].astype(np.int64), 0)
            for a in arrs]
    valid = np.ones(cap, bool)
    for a in arrs:
        valid &= _valid_np(a, cap)
    out_len = np.where(valid, np.maximum.reduce(lens), 0)
    offs = np.concatenate([[0], np.cumsum(out_len)[:-1]]).astype(np.int64)
    total = _host_int(out_len.sum())
    owner = np.repeat(np.arange(cap, dtype=np.int64), out_len)
    j = np.arange(total, dtype=np.int64) - np.repeat(offs, out_len)
    children = []
    for a, ln in zip(arrs, lens):
        src = _np(a.data)[:cap].astype(np.int64)[owner] + j
        present = j < ln[owner]
        ch = _take_flat(a.elements, np.where(present, src, 0))
        chv = (present if ch.valid is None
               else (np.asarray(ch.valid, bool) & present))
        children.append(_dc_replace(ch, valid=chv))
    row_el = Column(e.type.element, np.zeros(total, np.int8), None,
                    children=tuple(children))
    return Column(e.type, offs, None if valid.all() else valid, None,
                  out_len, row_el)


def _ngrams(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    ne = e.args[1]
    from .expr import Const as _Const
    if not isinstance(ne, _Const) or ne.value is None or int(ne.value) < 1:
        raise _err()("ngrams: n must be a positive constant")
    n = int(ne.value)
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    valid = _valid_np(arr, cap)
    lens = np.where(valid, _np(canon.data2)[:cap].astype(np.int64), 0)
    offs = _np(canon.data)[:cap].astype(np.int64)
    cnt = np.where(valid, np.maximum(lens - n + 1, 1), 0)
    out_offs = np.concatenate([[0], np.cumsum(cnt)[:-1]]).astype(np.int64)
    total = _host_int(cnt.sum())
    owner = np.repeat(np.arange(cap, dtype=np.int64), cnt)
    j = np.arange(total, dtype=np.int64) - np.repeat(out_offs, cnt)
    in_offs = offs[owner] + j
    in_lens = np.minimum(n, lens[owner] - j)
    inner = Column(e.type.element, in_offs, None, None,
                   np.maximum(in_lens, 0), canon.elements)
    return Column(e.type, out_offs, None if valid.all() else valid,
                  None, cnt, inner)


def _combinations(e: Call, batch: Batch) -> Column:
    import itertools
    arr = _eval(e.args[0], batch)
    ne = e.args[1]
    from .expr import Const as _Const
    if not isinstance(ne, _Const) or ne.value is None:
        raise _err()("combinations: n must be a constant")
    n = int(ne.value)
    if n < 0 or n > 5:
        raise _err()("combinations: n must be in [0, 5]")
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    valid = _valid_np(arr, cap)
    lens = np.where(valid, _np(canon.data2)[:cap].astype(np.int64), 0)
    offs = _np(canon.data)[:cap].astype(np.int64)
    pool_idx = []
    cnt = np.zeros(cap, np.int64)
    inner_offs = []
    inner_lens = []
    for r in range(cap):
        if not valid[r]:
            continue
        k = 0
        for combo in itertools.combinations(range(int(lens[r])), n):
            inner_offs.append(len(pool_idx))
            inner_lens.append(n)
            pool_idx.extend(offs[r] + i for i in combo)
            k += 1
        cnt[r] = k
    out_offs = np.concatenate([[0], np.cumsum(cnt)[:-1]]).astype(np.int64)
    total = _host_int(cnt.sum())
    io = np.zeros(max(total, 1), np.int64)
    il = np.zeros(max(total, 1), np.int64)
    io[:total] = inner_offs
    il[:total] = inner_lens
    pool = _take_flat(canon.elements,
                      np.asarray(pool_idx, dtype=np.int64))
    inner = Column(e.type.element, io[:max(total, 1)], None, None,
                   il[:max(total, 1)], pool)
    return Column(e.type, out_offs, None if valid.all() else valid,
                  None, cnt, inner)


def _array_end(which: str):
    def f(e: Call, batch: Batch) -> Column:
        arr = _eval(e.args[0], batch)
        cap = batch.capacity
        canon = canonicalize(arr, cap)
        valid = _valid_np(arr, cap)
        lens = _np(canon.data2)[:cap].astype(np.int64)
        offs = _np(canon.data)[:cap].astype(np.int64)
        nonempty = valid & (lens > 0)
        idx = np.where(which == "first", offs, offs + lens - 1)
        el = _take_flat(canon.elements,
                        np.where(nonempty, idx, 0))
        ev = (nonempty if el.valid is None
              else np.asarray(el.valid, bool) & nonempty)
        return _dc_replace(el, valid=ev)
    return f


def _entry_children(canon: Column, total: int):
    row_el = canon.elements
    if row_el.children is None or len(row_el.children) != 2:
        raise _err()("map_from_entries requires array(row(K, V))")
    if row_el.valid is not None \
            and not np.asarray(row_el.valid, bool)[:total].all():
        raise _err()("map entry cannot be null")
    return row_el.children


def _map_from_entries(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    owner = _owners(canon, cap)
    total = len(owner)
    kcol, vcol = _entry_children(canon, total)
    lane, _ = _comparable_lane(kcol, total)
    kv = (np.ones(total, bool) if kcol.valid is None
          else np.asarray(kcol.valid, bool)[:total])
    if not kv.all():
        raise _err()("map key cannot be null")
    pairs = set()
    for i in range(total):
        key = (int(owner[i]), int(lane[i]))
        if key in pairs:
            raise _err()("Duplicate map keys are not allowed")
        pairs.add(key)
    return Column(e.type, canon.data, canon.valid, None, canon.data2,
                  _take_flat(kcol, np.arange(total, dtype=np.int64)),
                  _take_flat(vcol, np.arange(total, dtype=np.int64)))


def _multimap_from_entries(e: Call, batch: Batch) -> Column:
    arr = _eval(e.args[0], batch)
    cap = batch.capacity
    canon = canonicalize(arr, cap)
    owner = _owners(canon, cap)
    total = len(owner)
    kcol, vcol = _entry_children(canon, total)
    lane, _ = _comparable_lane(kcol, total)
    out_len = np.zeros(cap, np.int64)
    key_rows = []
    val_rows = []
    arr_offs = []
    arr_lens = []
    i = 0
    while i < total:
        r = owner[i]
        per = {}
        order_k = []
        while i < total and owner[i] == r:
            k = int(lane[i])
            if k not in per:
                per[k] = (i, [])
                order_k.append(k)
            per[k][1].append(i)
            i += 1
        for k in order_k:
            rep, rows = per[k]
            key_rows.append(rep)
            arr_offs.append(len(val_rows))
            arr_lens.append(len(rows))
            val_rows.extend(rows)
        out_len[r] = len(order_k)
    offs = np.concatenate([[0], np.cumsum(out_len)[:-1]]).astype(np.int64)
    nk = max(len(key_rows), 1)
    io = np.zeros(nk, np.int64)
    il = np.zeros(nk, np.int64)
    io[:len(arr_offs)] = arr_offs
    il[:len(arr_lens)] = arr_lens
    varr = Column(e.type.value, io, None, None, il,
                  _take_flat(vcol, np.asarray(val_rows, np.int64)))
    return Column(e.type, offs, canon.valid, None, out_len,
                  _take_flat(kcol, np.asarray(key_rows, np.int64)), varr)


def _split_to_multimap(e: Call, batch: Batch) -> Column:
    from .expr import _materialize_strings, Const as _Const
    s = _eval(e.args[0], batch)
    d1, d2 = e.args[1], e.args[2]
    if not isinstance(d1, _Const) or not isinstance(d2, _Const):
        raise _err()("split_to_multimap: delimiters must be constants")
    ed, kd = str(d1.value), str(d2.value)
    cap = batch.capacity
    mats = _materialize_strings(s)
    valid = np.asarray([m is not None for m in mats], bool)
    keys = []
    vals = []
    out_len = np.zeros(cap, np.int64)
    arr_offs = []
    arr_lens = []
    flat_vals = []
    for r, m in enumerate(mats):
        if m is None:
            continue
        per = {}
        order_k = []
        if m:
            for entry in m.split(ed):
                k, _, v = entry.partition(kd)
                if k not in per:
                    per[k] = []
                    order_k.append(k)
                per[k].append(v)
        for k in order_k:
            keys.append(k)
            arr_offs.append(len(flat_vals))
            arr_lens.append(len(per[k]))
            flat_vals.extend(per[k])
        out_len[r] = len(order_k)
    offs = np.concatenate([[0], np.cumsum(out_len)[:-1]]).astype(np.int64)
    kd_, kcodes = StringDictionary.from_strings(keys)
    vd_, vcodes = StringDictionary.from_strings(flat_vals)
    nk = max(len(keys), 1)
    nv = max(len(flat_vals), 1)
    kc = np.zeros(nk, np.int32)
    kc[:len(keys)] = kcodes
    vc = np.zeros(nv, np.int32)
    vc[:len(flat_vals)] = vcodes
    io = np.zeros(nk, np.int64)
    il = np.zeros(nk, np.int64)
    io[:len(arr_offs)] = arr_offs
    il[:len(arr_lens)] = arr_lens
    varr = Column(e.type.value, io, None, None, il,
                  Column(VARCHAR, vc, None, vd_))
    return Column(e.type, offs, None if valid.all() else valid, None,
                  out_len, Column(VARCHAR, kc, None, kd_), varr)


def _cosine_similarity(e: Call, batch: Batch) -> Column:
    import math
    cap = batch.capacity
    m1 = canonicalize(_eval(e.args[0], batch), cap)
    m2 = canonicalize(_eval(e.args[1], batch), cap)
    valid = _valid_np(m1, cap) & _valid_np(m2, cap)

    def rowmaps(m):
        offs = _np(m.data)[:cap].astype(np.int64)
        lens = _np(m.data2)[:cap].astype(np.int64)
        kl = m.elements
        kd = kl.dictionary.values if kl.dictionary is not None else None
        kdata = _np(kl.data)
        vdata = _np(m.elements2.data).astype(np.float64)
        out = []
        for r in range(cap):
            d = {}
            for j in range(int(offs[r]), int(offs[r] + lens[r])):
                key = (str(kd[int(kdata[j])]) if kd is not None
                       else kdata[j].item())
                d[key] = float(vdata[j])
            out.append(d)
        return out
    a, b = rowmaps(m1), rowmaps(m2)
    out = np.zeros(cap, np.float64)
    ok = valid.copy()
    for r in range(cap):
        if not valid[r]:
            continue
        na = math.sqrt(sum(v * v for v in a[r].values()))
        nb = math.sqrt(sum(v * v for v in b[r].values()))
        if na == 0.0 or nb == 0.0:
            ok[r] = False
            continue
        dot = sum(v * b[r].get(k, 0.0) for k, v in a[r].items())
        out[r] = dot / (na * nb)
    from ..types import DOUBLE as _DOUBLE
    return Column(_DOUBLE, out, None if ok.all() else ok)


DISPATCH.update({
    "array_remove": _array_remove,
    "zip": _zip_fn,
    "ngrams": _ngrams,
    "combinations": _combinations,
    "array_first": _array_end("first"),
    "array_last": _array_end("last"),
    "map_from_entries": _map_from_entries,
    "multimap_from_entries": _multimap_from_entries,
    "split_to_multimap": _split_to_multimap,
    "cosine_similarity": _cosine_similarity,
})
