"""Coordinator hot-shape registry: ranked LRU of compiled program
shapes, the feed for worker pre-warm.

Reference parity: there is no direct Trino analog — the closest is the
coordinator's global (cross-query) dynamic-filter/statistics state —
because the JVM pays its bytecode-generation cost in milliseconds. On
a tensor runtime the equivalent cost is 30-90s of XLA compile per
fragment shape (ROADMAP item 1), so WHICH shapes a cluster runs is
operationally precious state: the registry records every structural
program the process compiles (canonical key from exec/progkey.py +
capacity-bucketed aval spec), ranks entries by hit count with LRU
recency as the tiebreak/eviction order, and serves the top-K at
``GET /v1/hotshapes`` on the coordinator. A joining worker pulls the
list during its announce handshake and AOT-compiles the top-K on a
background thread BEFORE advertising itself warm (exec/aot.py,
server/task_worker.py) — so a fresh worker's first fragment of a hot
query executes at device speed instead of trace speed.

Workers feed their locally-recorded shapes back to the coordinator in
task status payloads (``hotShapes``), so the coordinator's registry
covers every DISPATCHED fragment's shapes, not only what its own
combine stage compiled.

Recorded kinds span the FULL warm path (exec/aot.py dispatches on
``payload["kind"]``): ``chain`` / ``stream`` / ``stream_full``
(canonical fragment programs), ``streamjoin`` (the streamed-probe
chunk kernel), ``join`` (the materialized hash join's count + expand
program pair), ``window`` (execute_window over one canonical
WindowNode), and ``repartition`` (the exchange bucketing kernel —
signature-only, no fragment).

Shared-runtime code: the registry is mutated by query executor
threads, task threads, and HTTP handler threads concurrently — every
method takes the registry lock (and the module is on the race-lint
cross-module allowlist, analysis/lint.py)."""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

import numpy as np

from ..config import CONFIG
from ..obs.metrics import METRICS

_M_RECORDS = METRICS.counter(
    "trino_tpu_hot_shapes_recorded_total",
    "Hot-shape registry records by outcome",
    ("outcome",))           # new | hit | merged | unsupported
_M_SIZE = METRICS.gauge(
    "trino_tpu_hot_shapes",
    "Program shapes currently tracked by the hot-shape registry")

# registry entries a pathological query may create: past this budget a
# query keeps HITTING existing entries but registers no new ones (a
# generated-SQL storm of one-off shapes must not evict the fleet's
# genuinely hot programs). Session-gated per query (prewarm_enabled /
# hot_shape_top_k, session.py).
_BUDGET_ATTR = "_hot_shapes_recorded"


class HotShapeRegistry:
    """Ranked LRU of (canonical key -> AOT-able payload) entries."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        import uuid
        self._lock = threading.Lock()
        self._capacity = (capacity if capacity is not None
                          else CONFIG.hot_shape_entries)
        # key -> entry dict; OrderedDict end == most recently touched
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._seq = 0
        # identity stamped on exported deltas: when a worker shares
        # the process (and therefore THIS registry) with the scheduler
        # — single-host runners, tests, the bench fault/mpp legs —
        # merging its status delta back in would double-count every
        # worker-side sighting. merge() drops self-originated entries.
        self.origin = uuid.uuid4().hex[:12]

    # -- write side ----------------------------------------------------
    def record(self, kind: str, key: str,
               payload_fn: Callable[[], Optional[dict]],
               hits: int = 1) -> Optional[str]:
        """Count a sighting of ``key``; on first sight materialize the
        AOT payload (``payload_fn`` returns None for shapes the AOT
        path cannot rebuild — oversized dictionaries, nested columns —
        which are not registered at all). Returns "new" when this call
        created the entry, "hit" when it re-ranked an existing one,
        None when the shape is unsupported."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                ent["hits"] += hits
                self._seq += 1
                ent["seq"] = self._seq
                self._entries.move_to_end(key)
                _M_RECORDS.inc(outcome="hit")
                return "hit"
        # payload built OUTSIDE the lock: serde encoding walks the
        # whole canonical fragment
        payload = payload_fn()
        if payload is None:
            _M_RECORDS.inc(outcome="unsupported")
            return None
        with self._lock:
            ent = self._entries.get(key)
            self._seq += 1
            if ent is not None:         # raced another recorder
                ent["hits"] += hits
                ent["seq"] = self._seq
                self._entries.move_to_end(key)
                _M_RECORDS.inc(outcome="hit")
                return "hit"
            new_ent = {"kind": kind, "key": key,
                       "hits": hits, "seq": self._seq,
                       "payload": payload}
            self._entries[key] = new_ent
            while len(self._entries) > max(self._capacity, 1):
                # rank-aware eviction: coldest (fewest hits), oldest-
                # touched among ties — never the entry just admitted
                # (every newcomer starts at 1 hit and would otherwise
                # evict itself, starving the registry of fresh shapes)
                victims = [e for e in self._entries.values()
                           if e is not new_ent]
                if not victims:
                    break
                v = min(victims, key=lambda e: (e["hits"], e["seq"]))
                del self._entries[v["key"]]
            _M_RECORDS.inc(outcome="new")
            _M_SIZE.set(len(self._entries))
            return "new"

    def merge(self, entries: List[dict]) -> int:
        """Absorb entries exported by another process (worker task
        status riding back to the coordinator). Defensive: a malformed
        entry is skipped, never raises into the status path."""
        n = 0
        for e in entries or ():
            try:
                if e.get("origin") == self.origin:
                    # exported from THIS registry (in-process worker):
                    # the sighting is already counted here
                    continue
                kind = str(e["kind"])
                key = str(e["key"])
                hits = max(int(e.get("hits") or 1), 1)
                payload = e["payload"]
                if not isinstance(payload, dict):
                    continue
            except (KeyError, TypeError, ValueError):
                continue
            if self.record(kind, key, lambda p=payload: p, hits=hits):
                _M_RECORDS.inc(outcome="merged")
                n += 1
        return n

    # -- read side -----------------------------------------------------
    def top(self, k: int) -> List[dict]:
        """The k hottest shapes: hit count desc, recency desc as the
        tiebreak — what a joining worker should compile first."""
        with self._lock:
            ranked = sorted(self._entries.values(),
                            key=lambda e: (-e["hits"], -e["seq"]))
            return [dict(e) for e in ranked[:max(int(k), 0)]]

    def hit_counts(self) -> Dict[str, int]:
        """Per-key hit snapshot — the baseline for ``export_delta``."""
        with self._lock:
            return {k: e["hits"] for k, e in self._entries.items()}

    def export_delta(self, before: Dict[str, int]) -> List[dict]:
        """Entries whose hit count GREW since the ``before`` snapshot,
        carrying only the growth as their ``hits`` — the worker-side
        delta a task status ships back. Shipping deltas (not
        cumulative counts) keeps the coordinator's ranking additive:
        N statuses each reporting the same entry contribute exactly
        the sightings that happened, never re-count earlier ones."""
        with self._lock:
            out = []
            for k, e in self._entries.items():
                grown = e["hits"] - before.get(k, 0)
                if grown > 0:
                    ent = dict(e)
                    ent["hits"] = grown
                    ent["origin"] = self.origin
                    out.append(ent)
            return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            _M_SIZE.set(0)


# the process-wide registry (coordinator and worker alike: a worker
# records what it compiles and exports deltas via task status; the
# coordinator records its combine-stage programs directly and merges
# worker deltas)
HOT_SHAPES = HotShapeRegistry()


def _session_allows(session) -> bool:
    try:
        return bool(session.get("prewarm_enabled")) \
            if session is not None else True
    except KeyError:
        return True


def _session_budget(session) -> int:
    try:
        return int(session.get("hot_shape_top_k")) \
            if session is not None else CONFIG.prewarm_top_k
    except KeyError:
        return CONFIG.prewarm_top_k


def record_program(kind: str, cache_key, canon, batch,
                   session, payload_fn=None) -> None:
    """Executor hook: count a structural-program sighting and (first
    time) capture its AOT payload from the canonical input batch.
    ``cache_key`` is the in-process jit-cache key object — the AOT
    compiler re-derives the same key from the decoded fragment, which
    is what lets a pre-warmed program land in the exact slot the
    executor will probe. Gated per query by the ``prewarm_enabled``
    session property, with ``hot_shape_top_k`` as the query's
    new-entry budget. ``payload_fn`` overrides the default chain/
    stream payload builder for kinds with their own transport form
    (the streamed-join probe programs of exec/streamjoin.py)."""
    if not _session_allows(session):
        return
    # the budget is PER QUERY: keyed by the session's current query id
    # (runner/coordinator stamp one per execution), so a long-lived
    # session keeps contributing new shapes query after query instead
    # of going silent once its first queries spent the counter
    used = 0
    qid = None
    if session is not None:
        qid = getattr(session, "query_id", "") or ""
        state = getattr(session, _BUDGET_ATTR, None)
        if isinstance(state, tuple) and state[0] == qid:
            used = state[1]
    budget = _session_budget(session)

    def build() -> Optional[dict]:
        if session is not None and used >= budget:
            return None         # budget spent: hit-count only
        if payload_fn is not None:
            return payload_fn()
        return build_payload(kind, canon, batch)

    outcome = HOT_SHAPES.record(kind, repr(cache_key), build)
    if outcome == "new" and session is not None:
        try:
            setattr(session, _BUDGET_ATTR, (qid, used + 1))
        except AttributeError:      # frozen/foreign session object
            pass


# dictionaries above this entry count are not serialized into the
# registry (the payload would ship a whole string pool per shape);
# such shapes stay un-prewarmable rather than bloating the feed
MAX_DICT_ENTRIES = 64


def build_payload(kind: str, canon, batch) -> Optional[dict]:
    """The AOT transport form of one compiled shape: the canonical
    fragment (plan/serde wire JSON) + the observed input lane spec at
    its capacity bucket. None when the input contains lanes the AOT
    rebuilder cannot fabricate faithfully (nested ARRAY/MAP/ROW
    columns, large dictionaries)."""
    cols = []
    schema = {}
    for name, c in batch.columns.items():
        if c.elements is not None or c.elements2 is not None \
                or c.children is not None:
            return None
        ent: Dict[str, object] = {
            "name": name,
            "dtype": str(np.dtype(c.data.dtype)),
            "valid": c.valid is not None,
            "data2": (None if c.data2 is None
                      else str(np.dtype(c.data2.dtype))),
        }
        if c.dictionary is not None:
            vals = list(c.dictionary.values)
            if len(vals) > MAX_DICT_ENTRIES:
                return None
            ent["dict"] = [None if v is None else str(v)
                           for v in vals]
        cols.append(ent)
        schema[name] = c.type
    num_rows = ("int" if isinstance(batch.num_rows, int)
                else str(np.dtype(batch.num_rows.dtype)))
    return {"kind": kind,
            "fragment": canon.wire_fragment(schema),
            "cols": cols,
            "capacity": int(batch.capacity),
            "num_rows": num_rows}
