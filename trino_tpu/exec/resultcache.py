"""Coordinator-side result cache: identical deterministic point
queries short-circuit BEFORE dispatch — zero planned fragments, zero
worker tasks, zero kernel launches.

Reference parity: the reference has no engine result cache (clients
layer one on), but its bytecode caches establish the identity
discipline this module reuses: results are keyed on the CANONICAL
program (exec/progkey.py — the same key the jit caches and the
hot-shape registry share), not on SQL text, so renamed-but-identical
dashboard queries hit one entry. The split fingerprint (table handle +
accepted pushdowns) pins WHICH data the program ran over, and every
scanned connector's ``data_version()`` pins WHEN — a version bump
(memory-connector INSERT, DDL) invalidates on the next lookup instead
of serving stale rows.

Cacheability is conservative: every scanned connector must report a
data version (unversioned sources — jdbc, localfile — can mutate
invisibly), the plan must be serde-encodable, and no expression may be
volatile (now(), rand()). Everything else passes through untouched.

Thread-safety: the cache is mutated by concurrent query threads
(lookups/fills) and by the memory-pressure ladder (exec/executor.py
evict_cache_pressure) — every traversal holds ``_lock``.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict
from dataclasses import fields as dc_fields, is_dataclass
from typing import Dict, List, Optional, Tuple

from ..config import CONFIG
from ..obs.metrics import METRICS
from ..plan.nodes import OutputNode, PlanNode, TableScanNode
from ..rex import RowExpr, expr_volatile

RESULT_CACHE_LOOKUPS = METRICS.counter(
    "trino_tpu_result_cache_lookups_total",
    "Result-cache lookups by outcome", labelnames=("result",))
RESULT_CACHE_EVICTIONS = METRICS.counter(
    "trino_tpu_result_cache_evictions_total",
    "Result-cache entries dropped, by reason "
    "(lru | pressure | invalidated)", labelnames=("reason",))
RESULT_CACHE_BYTES = METRICS.gauge(
    "trino_tpu_result_cache_bytes", "Bytes held by the result cache")
RESULT_CACHE_ENTRIES = METRICS.gauge(
    "trino_tpu_result_cache_entries", "Entries in the result cache")


def _result_nbytes(columns: List[str], rows: List[list]) -> int:
    """Cheap host-side size estimate: per-cell overhead + string
    payloads (rows are plain python lists bound for JSON anyway)."""
    n = 64 + 16 * len(columns)
    for row in rows:
        n += 24 + 16 * len(row)
        for v in row:
            if isinstance(v, str):
                n += len(v)
    return n


class _Entry:
    __slots__ = ("columns", "types", "rows", "nbytes", "versions",
                 "created")

    def __init__(self, columns, types, rows, nbytes, versions):
        self.columns = columns
        self.types = types
        self.rows = rows
        self.nbytes = nbytes
        self.versions = versions     # ((catalog, data_version), ...)
        self.created = time.time()


class ResultCache:
    """Byte-capped LRU over final query results. ``get`` re-validates
    the captured connector versions against the caller's current ones:
    a mismatch drops the entry (counted ``invalidated``) and misses."""

    def __init__(self, capacity_bytes: int) -> None:
        self.capacity = capacity_bytes
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._bytes = 0

    # -- stats ---------------------------------------------------------
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- core ----------------------------------------------------------
    def get(self, key: tuple, current_versions: tuple
            ) -> Optional[Tuple[List[str], list, List[list]]]:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                RESULT_CACHE_LOOKUPS.inc(result="miss")
                return None
            if e.versions != current_versions:
                self._drop(key, e, "invalidated")
                RESULT_CACHE_LOOKUPS.inc(result="miss")
                return None
            self._entries.move_to_end(key)
            RESULT_CACHE_LOOKUPS.inc(result="hit")
            # rows are handed to clients that may mutate them: return
            # a per-row copy, keep the cached master pristine
            return (list(e.columns), list(e.types),
                    [list(r) for r in e.rows])

    def put(self, key: tuple, columns: List[str], types: list,
            rows: List[list], versions: tuple) -> bool:
        nbytes = _result_nbytes(columns, rows)
        # one entry may not monopolize the cache
        if self.capacity <= 0 or nbytes > max(self.capacity // 4, 1):
            return False
        snap = [list(r) for r in rows]
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = _Entry(list(columns), list(types),
                                        snap, nbytes, versions)
            self._bytes += nbytes
            while self._bytes > self.capacity and len(self._entries) > 1:
                k, e = next(iter(self._entries.items()))
                self._drop(k, e, "lru")
            self._publish()
        return True

    def evict(self, need_bytes: int) -> int:
        """Memory-pressure hook (exec/executor.py evict_cache_pressure):
        drop oldest entries until ``need_bytes`` are freed or the cache
        is empty. Returns bytes freed."""
        freed = 0
        with self._lock:
            while self._entries and freed < need_bytes:
                k, e = next(iter(self._entries.items()))
                self._drop(k, e, "pressure")
                freed += e.nbytes
            self._publish()
        return freed

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._publish()

    # -- internals (lock held) -----------------------------------------
    def _drop(self, key: tuple, e: _Entry, reason: str) -> None:
        self._entries.pop(key, None)
        self._bytes -= e.nbytes
        RESULT_CACHE_EVICTIONS.inc(reason=reason)
        self._publish()

    def _publish(self) -> None:
        RESULT_CACHE_BYTES.set(float(self._bytes))
        RESULT_CACHE_ENTRIES.set(float(len(self._entries)))


RESULT_CACHE = ResultCache(CONFIG.result_cache_bytes)


# ---- cache key ------------------------------------------------------

def _walk_nodes(nd: PlanNode):
    yield nd
    for s in nd.sources:
        yield from _walk_nodes(s)


def _any_volatile(nd: PlanNode) -> bool:
    def vol(v) -> bool:
        if isinstance(v, RowExpr):
            return expr_volatile(v)
        if isinstance(v, dict):
            return any(vol(x) for x in v.values())
        if isinstance(v, (list, tuple)):
            return any(vol(x) for x in v)
        return False
    for n in _walk_nodes(nd):
        if any(vol(getattr(n, f.name)) for f in dc_fields(n)
               if f.name != "source"):
            return True
    return False


def _scan_fingerprint(scan: TableScanNode) -> tuple:
    h = scan.handle
    return (h.catalog, h.schema, h.table, repr(h.constraint), h.limit)


def result_cache_key(plan: OutputNode, catalogs
                     ) -> Optional[Tuple[tuple, tuple]]:
    """(key, versions) for a cacheable plan, None for uncacheable.

    Fast path: an Output over a canonicalizable Filter/Project chain
    over one scan keys on the CANONICAL program (rename-invariant —
    the same identity the jit caches and ragged batcher share).
    General path: sha256 of the serde-encoded plan. Both carry the
    split fingerprints; versions ride separately so ``get`` can
    distinguish invalidation from plain miss."""
    if not is_dataclass(plan) or not isinstance(plan, OutputNode):
        return None
    scans = [n for n in _walk_nodes(plan)
             if isinstance(n, TableScanNode)]
    if not scans:
        # catalog-less SELECT 1 etc.: cheap anyway, and caching them
        # would pin the no-scan fast path's identity semantics
        return None
    versions = []
    for s in scans:
        try:
            ver = catalogs.connector(s.handle.catalog).data_version()
        except KeyError:
            return None
        if ver is None:
            return None
        versions.append((s.handle.catalog, ver))
    if _any_volatile(plan):
        return None
    fps = tuple(sorted(set(_scan_fingerprint(s) for s in scans)))
    key = _chain_key(plan)
    if key is None:
        try:
            from ..plan.serde import to_jsonable
            blob = json.dumps(to_jsonable(plan), sort_keys=True,
                              default=str)
        except Exception:          # noqa: BLE001 — unencodable plan
            return None
        key = ("plan", hashlib.sha256(blob.encode()).hexdigest())
    return key + (fps,), tuple(sorted(set(versions)))


def _chain_key(plan: OutputNode) -> Optional[tuple]:
    """Canonical identity for the point-lookup shape: Output ->
    [canonicalizable chain] -> TableScan. The io signature maps
    canonical input names to CONNECTOR columns (through the scan's
    assignments) and client column names to their producing symbols'
    canonical names — so two plans differing only in planner symbol
    numbering share one entry."""
    from .progkey import canonicalize_nodes
    from ..plan.nodes import (FilterNode, LimitNode, OffsetNode,
                              ProjectNode, SampleNode, SortNode,
                              TopNNode)
    chain: List[PlanNode] = []
    cur = plan.source
    while isinstance(cur, (FilterNode, ProjectNode, LimitNode,
                           OffsetNode, SortNode, TopNNode, SampleNode)):
        chain.append(cur)
        cur = cur.source
    if not isinstance(cur, TableScanNode):
        return None
    canon = canonicalize_nodes(chain)
    if canon is None:
        return None
    ins = tuple(sorted(
        (cn, cur.assignments[orig])
        for orig, cn in canon.mapping.items()
        if orig in cur.assignments))
    outs = tuple(
        (name, canon.mapping.get(sym, cur.assignments.get(sym, sym)))
        for name, sym in zip(plan.names, plan.symbols))
    return ("chain", canon.key, ins, outs)


# ---- runner wrapper --------------------------------------------------

class CachingQueryRunner:
    """Transparent cache layer the coordinator's runner factory wraps
    around BOTH runner kinds (local and distributed). A hit returns a
    synthesized QueryResult without touching the inner runner — no
    planning against workers, no dispatched tasks. A miss double-plans
    (once here for the key, once inside the inner runner); point
    queries plan in microseconds, so key cost is noise next to one
    dispatch round-trip. Everything non-SELECT, non-deterministic or
    unkeyable passes straight through."""

    def __init__(self, inner, session, catalogs) -> None:
        self._inner = inner
        self._session = session
        self._catalogs = catalogs

    def __getattr__(self, name):
        # .resume / .session / .catalogs / anything else the
        # coordinator pokes at — behave like the wrapped runner
        return getattr(self._inner, name)

    def execute(self, sql: str):
        session = self._session
        try:
            enabled = bool(session.get("result_cache_enabled"))
        except KeyError:
            enabled = False
        if not enabled or CONFIG.result_cache_bytes <= 0:
            return self._inner.execute(sql)
        keyver = self._key_for(sql)
        if keyver is None:
            return self._inner.execute(sql)
        key, versions = keyver
        hit = RESULT_CACHE.get(key, versions)
        if hit is not None:
            return self._synthesize(hit)
        res = self._inner.execute(sql)
        # only successful plain SELECT results are cacheable (DDL/DML
        # mutate; a raised QueryError never reaches here)
        if getattr(res, "update_type", None) is None:
            RESULT_CACHE.put(key, res.columns, res.types, res.rows,
                             versions)
        return res

    # -- internals -----------------------------------------------------
    def _key_for(self, sql: str):
        from ..sql import ast as A
        from ..sql.parser import parse_statement
        try:
            stmt = parse_statement(sql)
            if not isinstance(stmt, A.QueryStatement):
                return None
            from ..planner import LogicalPlanner
            from ..planner.optimizer import optimize
            planner = LogicalPlanner(self._catalogs, self._session)
            plan = optimize(planner.plan(stmt), self._catalogs,
                            self._session)
            return result_cache_key(plan, self._catalogs)
        except Exception:           # noqa: BLE001 — any planning
            return None             # failure: let the inner runner
                                    # produce the real error/result

    def _synthesize(self, hit):
        from ..runner import QueryResult
        columns, types, rows = hit
        t0 = time.perf_counter()
        session = self._session
        # mirror LocalQueryRunner's id discipline: a coordinator-
        # stamped id wins and is consumed; standalone use mints one
        qid = session.query_id or session.next_query_id()
        session.query_id = ""
        return QueryResult(columns=columns, types=types, rows=rows,
                           query_id=qid,
                           wall_s=time.perf_counter() - t0)
