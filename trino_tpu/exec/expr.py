"""Row-expression evaluation over Batches.

Reference parity: the compiled PageProcessor loop — sql/gen/
PageFunctionCompiler.java:101 + ExpressionInterpreter.java. Here every
rex node lowers to jnp ops over whole column lanes; jax.jit traces the
enclosing pipeline into one fused XLA program (SURVEY.md §7.2), which is
the TPU analog of Trino generating one bytecode class per expression.

String strategy ("strings on TPU", SURVEY.md §7 hard part 2): scalar
string functions evaluate host-side over the column's *dictionary values*
(small), producing a device gather table; per-row work on the TPU is just
integer code gathers. Functions of multiple string columns fall back to
host row materialization.

Three-valued logic: every eval returns a Column (value lane + validity
lane); AND/OR implement Kleene truth tables explicitly.
"""

from __future__ import annotations

import re
from dataclasses import replace as dc_replace
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Batch, Column, StringDictionary
from ..ops.datetime import (add_months, date_trunc_days, extract_field)
from ..rex import Call, CaseExpr, Cast, Const, InputRef, RowExpr
from ..types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, REAL, UNKNOWN,
                     VARCHAR, CharType, DecimalType, IntervalDayTime,
                     IntervalYearMonth, TimestampType, Type, VarcharType,
                     is_integral, is_numeric, is_string)


class EvalError(Exception):
    pass


def eval_expr(e: RowExpr, batch: Batch) -> Column:
    if isinstance(e, InputRef):
        return batch.column(e.name)
    if isinstance(e, Const):
        return _const_column(e, batch.capacity)
    if isinstance(e, Cast):
        return _eval_cast(e, batch)
    if isinstance(e, CaseExpr):
        return _eval_case(e, batch)
    if isinstance(e, Call):
        return _eval_call(e, batch)
    raise EvalError(f"cannot evaluate {type(e).__name__}")


def eval_predicate(e: RowExpr, batch: Batch) -> jax.Array:
    """Boolean mask: TRUE rows only (NULL -> excluded), ANDed with
    liveness."""
    col = eval_expr(e, batch)
    m = jnp.asarray(col.data).astype(bool)
    if col.valid is not None:
        m = m & jnp.asarray(col.valid)
    return m & batch.row_valid()


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

def _const_column(e: Const, cap: int) -> Column:
    t = e.type
    if e.value is None:
        from ..types import ArrayType, MapType, RowType
        if isinstance(t, (ArrayType, MapType, RowType)):
            from ..columnar import column_from_pylist, pad_batch
            col = column_from_pylist([None], t)
            return pad_batch(Batch({"c": col}, 1), cap).column("c")
        if is_string(t):
            d, _ = StringDictionary.from_strings([])
            return Column(t, jnp.zeros((cap,), jnp.int32),
                          jnp.zeros((cap,), dtype=bool), d)
        base = t if t != UNKNOWN else BOOLEAN
        dt = base.np_dtype or np.dtype(np.int64)
        return Column(t, jnp.zeros((cap,), dtype=dt),
                      jnp.zeros((cap,), dtype=bool))
    if is_string(t):
        d = StringDictionary(np.asarray([e.value], dtype=object))
        return Column(t, jnp.zeros((cap,), dtype=jnp.int32), None, d)
    from ..types import TimestampTZType
    if isinstance(t, TimestampTZType):
        ms, off = (e.value if isinstance(e.value, tuple)
                   else (e.value, 0))
        return Column(t, jnp.full((cap,), ms, jnp.int64), None,
                      data2=jnp.full((cap,), off, jnp.int64))
    if isinstance(t, DecimalType):
        v = e.value
        if isinstance(v, int):
            q = v * 10 ** t.scale
        elif isinstance(v, str):
            # exact: a float round-trip would corrupt literals beyond
            # 2^53 (q34-style wide-decimal comparisons); prec=80 because
            # the default 28-digit context rounds DECIMAL(38) magnitudes
            from decimal import (Context as _DC, Decimal as _D,
                                 ROUND_HALF_UP as _RHU)
            q = int(_D(v).scaleb(t.scale, _DC(prec=80))
                    .to_integral_value(rounding=_RHU))
        else:
            q = int(round(float(v) * (10 ** t.scale)))
        if not t.is_short:
            lo = q & ((1 << 64) - 1)
            lo = lo - (1 << 64) if lo >= (1 << 63) else lo
            return Column(t, jnp.full((cap,), lo, jnp.int64), None,
                          data2=jnp.full((cap,), q >> 64, jnp.int64))
        return Column(t, jnp.full((cap,), q, dtype=jnp.int64), None)
    dt = t.np_dtype
    return Column(t, jnp.full((cap,), e.value, dtype=dt), None)


def _lane(col: Column) -> jax.Array:
    return jnp.asarray(col.data)


def _merge_valid(*cols: Column) -> Optional[jax.Array]:
    v = None
    for c in cols:
        if c.valid is None:
            continue
        cv = jnp.asarray(c.valid)
        v = cv if v is None else (v & cv)
    return v


def _dict_transform(col: Column, fn: Callable[[str], object],
                    out_type: Type) -> Column:
    """Host-evaluate fn over dictionary values; device code lanes are
    reused (possibly remapped through a new dictionary)."""
    vals = col.dictionary.values
    out = [fn(str(v)) for v in vals]
    if is_string(out_type) \
            or getattr(out_type, "name", "") == "varbinary":
        # varbinary rides the dictionary-string lanes (latin-1-decoded
        # raw bytes), same as varchar
        d, codes = StringDictionary.from_strings(out)
        table = jnp.asarray(codes.astype(np.int32))
        data = jnp.take(table, _lane(col), mode="clip")
        valid = col.valid
        nulls = np.asarray([v is None for v in out], dtype=bool)
        if nulls.any():
            nv = ~jnp.take(jnp.asarray(nulls), _lane(col), mode="clip")
            valid = nv if valid is None else (jnp.asarray(valid) & nv)
        return Column(out_type, data, valid, d)
    # numeric/boolean result: value table gather
    nulls = np.asarray([v is None for v in out], dtype=bool)
    dt = out_type.np_dtype
    tbl = np.asarray([0 if v is None else v for v in out], dtype=dt)
    data = jnp.take(jnp.asarray(tbl), _lane(col), mode="clip")
    valid = col.valid
    if nulls.any():
        nv = ~jnp.take(jnp.asarray(nulls), _lane(col), mode="clip")
        valid = nv if valid is None else (jnp.asarray(valid) & nv)
    return Column(out_type, data, valid)


def _parse_long_decimal_dict(col: Column, t, safe: bool) -> Column:
    """varchar -> DECIMAL(p>18): parse the dictionary host-side into
    128-bit quantized values, emit (lo, hi) gather tables. The single
    -lane _dict_transform overflows int64 here (round-4 verdict repro).
    Reference: spi/type/Decimals.java parse + Int128 representation."""
    from decimal import (Context as _DC, Decimal as _D, InvalidOperation,
                         ROUND_HALF_UP as _RHU)
    from ..ops.int128 import split_const
    ctx = _DC(prec=80)
    los, his, nulls = [], [], []
    for v in col.dictionary.values:
        try:
            q = int(_D(str(v).strip()).scaleb(t.scale, ctx)
                    .to_integral_value(rounding=_RHU))
            lo, hi = split_const(q)
            los.append(lo)
            his.append(hi)
            nulls.append(False)
        except (InvalidOperation, ValueError, OverflowError):
            if not safe:
                raise EvalError(f"Cannot cast '{v}' to {t}") from None
            los.append(0)
            his.append(0)
            nulls.append(True)
    codes = _lane(col)
    lo = jnp.take(jnp.asarray(np.asarray(los, np.int64)), codes,
                  mode="clip")
    hi = jnp.take(jnp.asarray(np.asarray(his, np.int64)), codes,
                  mode="clip")
    valid = col.valid
    nulls = np.asarray(nulls, dtype=bool)
    if nulls.any():
        nv = ~jnp.take(jnp.asarray(nulls), codes, mode="clip")
        valid = nv if valid is None else (jnp.asarray(valid) & nv)
    return Column(t, lo, valid, data2=hi)


def _materialize_strings(col: Column, n: Optional[int] = None) -> List:
    codes = np.asarray(col.data)
    valid = (None if col.valid is None else np.asarray(col.valid))
    out = []
    if col.dictionary is None:
        # dictionary-less (e.g. an all-NULL UNKNOWN constant): only
        # invalid rows are representable as strings -> None
        for i in range(len(codes) if n is None else n):
            out.append(None if valid is None or not valid[i]
                       else str(codes[i]))
        return out
    vals = col.dictionary.values
    for i in range(len(codes) if n is None else n):
        if valid is not None and not valid[i]:
            out.append(None)
        else:
            out.append(str(vals[int(codes[i])]))
    return out


def _row_string_fn(cols: List[Column], fn, out_type: Type) -> Column:
    """Host row-wise fallback for multi-string-column functions."""
    mats = [_materialize_strings(c) for c in cols]
    out = []
    for row in zip(*mats):
        out.append(None if any(v is None for v in row) else fn(*row))
    d, codes = StringDictionary.from_strings(out)
    valid = np.asarray([o is not None for o in out], dtype=bool)
    return Column(out_type, jnp.asarray(codes), None
                  if valid.all() else jnp.asarray(valid), d)


# --------------------------------------------------------------------------
# CASE
# --------------------------------------------------------------------------

def _eval_case(e: CaseExpr, batch: Batch) -> Column:
    branches = [(eval_expr(c, batch), eval_expr(v, batch))
                for c, v in e.whens]
    default = (eval_expr(e.default, batch) if e.default is not None
               else _const_column(Const(None, e.type), batch.capacity))
    if is_string(e.type):
        # unify dictionaries across branches
        cols = [v for _, v in branches] + [default]
        merged = None
        remaps = []
        for c in cols:
            if merged is None:
                merged = c.dictionary
                remaps.append(np.arange(len(merged), dtype=np.int32))
            else:
                merged, _, ro = merged.merge(c.dictionary)
                remaps.append(ro)
        cols = [dc_replace(c, data=jnp.take(jnp.asarray(rm), _lane(c),
                                            mode="clip"),
                           dictionary=merged)
                for c, rm in zip(cols, remaps)]
        branches = [(b[0], c) for b, c in zip(branches, cols[:-1])]
        default = cols[-1]
    taken = jnp.zeros((batch.capacity,), dtype=bool)
    data = _lane(default)
    valid = (jnp.ones((batch.capacity,), bool) if default.valid is None
             else jnp.asarray(default.valid))
    for cond, val in branches:
        c_true = _lane(cond).astype(bool)
        if cond.valid is not None:
            c_true = c_true & jnp.asarray(cond.valid)
        sel = c_true & ~taken
        data = jnp.where(sel, _lane(val).astype(data.dtype), data)
        v = (jnp.ones_like(valid) if val.valid is None
             else jnp.asarray(val.valid))
        valid = jnp.where(sel, v, valid)
        taken = taken | c_true
    return Column(e.type, data, None if _always_true(valid) else valid,
                  default.dictionary if is_string(e.type) else None)


def _always_true(v) -> bool:
    return False  # device value; keep the lane (cheap)


# --------------------------------------------------------------------------
# casts
# --------------------------------------------------------------------------

def _eval_cast(e: Cast, batch: Batch) -> Column:
    src = eval_expr(e.arg, batch)
    return cast_column(src, e.type, e.safe)


def cast_column(src: Column, t: Type, safe: bool = False) -> Column:
    s = src.type
    if s == t:
        return src
    if s == UNKNOWN:
        out = _const_column(Const(None, t), src.capacity)
        return out
    from ..types import ArrayType, MapType, RowType
    if isinstance(t, RowType) and isinstance(s, RowType):
        if len(t.fields) != len(s.fields):
            raise EvalError(f"cannot cast {s} to {t}")
        kids = tuple(cast_column(c, ft, safe)
                     for c, (_, ft) in zip(src.children, t.fields))
        return dc_replace(src, type=t, children=kids)
    if isinstance(t, ArrayType) and isinstance(s, ArrayType):
        return dc_replace(src, type=t,
                          elements=cast_column(src.elements, t.element,
                                               safe))
    if isinstance(t, MapType) and isinstance(s, MapType):
        return dc_replace(
            src, type=t,
            elements=cast_column(src.elements, t.key, safe),
            elements2=cast_column(src.elements2, t.value, safe))
    from ..types import HyperLogLogType, VARBINARY as _VB

    def _stringy(x):
        return is_string(x) or x is _VB or x.name == "varbinary"
    if isinstance(s, HyperLogLogType) and _stringy(t):
        # cast(hll as varbinary/varchar): base64 of this engine's dense
        # framing (ops/hll.py — shared with client result encoding)
        from ..ops.hll import sketches_to_base64
        out = sketches_to_base64(jax.device_get(src.data),
                                 jax.device_get(src.data2),
                                 np.asarray(
                                     jax.device_get(src.elements.data)),
                                 s.bucket_bits)
        dct, codes = StringDictionary.from_strings(out)
        return Column(t, jnp.asarray(codes), src.valid, dct)
    if isinstance(t, HyperLogLogType) and _stringy(s):
        import base64 as _b64
        from ..ops.hll import deserialize_registers, entries_from_dense
        from ..types import INTEGER as _INT
        pool, pool_b, bad = [], [], np.zeros(
            len(src.dictionary.values), bool)
        for i, v in enumerate(src.dictionary.values):
            try:
                regs = deserialize_registers(_b64.b64decode(v))
                pool.append(entries_from_dense(regs))
                pool_b.append(int(regs.shape[0]).bit_length() - 1)
            except Exception as ex:
                if not safe:
                    raise EvalError(
                        f"cannot cast to hyperloglog: {ex}")
                pool.append(np.zeros((0,), np.int32))
                pool_b.append(-1)
                bad[i] = True
        real_b = sorted({b for b in pool_b if b >= 0})
        if len(real_b) > 1:
            raise EvalError(
                "cannot cast a column mixing HyperLogLog precisions "
                f"(bucket bits {real_b})")
        bbits = real_b[0] if real_b else t.bucket_bits
        lens = np.asarray([p.shape[0] for p in pool], np.int64)
        offs = np.cumsum(lens) - lens
        flat = (np.concatenate(pool) if pool
                else np.zeros((0,), np.int32))
        from ..config import capacity_for as _cfor
        pad = _cfor(max(int(flat.shape[0]), 1))
        flat = np.pad(flat, (0, pad - flat.shape[0]))
        codes = jnp.asarray(src.data).astype(jnp.int64)
        starts = jnp.take(jnp.asarray(offs), codes, mode="clip")
        lns = jnp.take(jnp.asarray(lens), codes, mode="clip")
        valid = src.valid
        if bad.any():
            ok = jnp.take(jnp.asarray(~bad), codes, mode="clip")
            valid = ok if valid is None else jnp.asarray(valid) & ok
        return Column(HyperLogLogType(bbits), starts, valid, None,
                      lns, Column(_INT, jnp.asarray(flat)))
    # string source -> parse host-side over dictionary
    if is_string(s) and not is_string(t):
        if isinstance(t, DecimalType) and not t.is_short:
            return _parse_long_decimal_dict(src, t, safe)
        return _dict_transform(src, _parser_for(t, safe), t)
    if is_string(t):
        if is_string(s):
            return dc_replace(src, type=t)
        return _to_varchar(src, t)
    d = _lane(src)
    if isinstance(s, DecimalType):
        if src.data2 is not None:
            # fold the Int128 hi lane in: value = hi*2^64 + u64(lo)
            # (float64 rounding is inherent in a cast to double)
            lo = d.astype(jnp.float64)
            lo = jnp.where(d < 0, lo + 2.0 ** 64, lo)
            sv = (jnp.asarray(src.data2).astype(jnp.float64)
                  * 2.0 ** 64 + lo) / (10.0 ** s.scale)
        else:
            sv = d.astype(jnp.float64) / (10.0 ** s.scale)
        if t.name == "double":
            return Column(t, sv, src.valid)
        if t.name == "real":
            return Column(t, sv.astype(jnp.float32), src.valid)
        if is_integral(t):
            if src.data2 is not None:
                from ..ops import int128 as i128
                lo, _hi = i128.rescale(d.astype(jnp.int64),
                                       jnp.asarray(src.data2)
                                       .astype(jnp.int64), -s.scale)
                return Column(t, lo.astype(t.np_dtype), src.valid)
            return Column(t, _round_half_up(sv).astype(t.np_dtype),
                          src.valid)
        if isinstance(t, DecimalType):
            shift = t.scale - s.scale
            if shift == 0 and t.is_short == s.is_short:
                # precision-only change: keep both Int128 lanes intact
                return dc_replace(src, type=t)
            if src.data2 is not None or not t.is_short:
                from ..ops import int128 as i128
                lo = d.astype(jnp.int64)
                hi = (jnp.asarray(src.data2).astype(jnp.int64)
                      if src.data2 is not None else i128.sign_extend(lo))
                lo, hi = i128.rescale(lo, hi, shift)
                if t.is_short:
                    # in-range values fit the low lane exactly; the
                    # reference raises on overflow, we wrap (documented
                    # in ops/int128.py)
                    return Column(t, lo, src.valid)
                return Column(t, lo, src.valid, data2=hi)
            if shift >= 0:
                nd = d * (10 ** shift)
            else:
                nd = _div_round_half_up(d, 10 ** (-shift))
            return Column(t, nd, src.valid)
        if t is BOOLEAN:
            return Column(t, d != 0, src.valid)
    if isinstance(t, DecimalType):
        if is_integral(s) or s is BOOLEAN:
            if not t.is_short:
                from ..ops import int128 as i128
                lo = d.astype(jnp.int64)
                lo, hi = i128.rescale(lo, i128.sign_extend(lo), t.scale)
                return Column(t, lo, src.valid, data2=hi)
            return Column(t, d.astype(jnp.int64) * (10 ** t.scale),
                          src.valid)
        # float -> decimal, HALF_UP
        scaled = d.astype(jnp.float64) * (10.0 ** t.scale)
        if not t.is_short:
            from ..ops import int128 as i128
            rounded = (jnp.sign(scaled)
                       * jnp.floor(jnp.abs(scaled) + 0.5))
            lo, hi = i128.from_double(rounded)
            return Column(t, lo, src.valid, data2=hi)
        return Column(t, _round_half_up(scaled), src.valid)
    if t.name in ("double", "real"):
        return Column(t, d.astype(t.np_dtype), src.valid)
    if is_integral(t):
        if s.name in ("double", "real"):
            return Column(t, _round_half_up(d.astype(jnp.float64))
                          .astype(t.np_dtype), src.valid)
        return Column(t, d.astype(t.np_dtype), src.valid)
    if t is BOOLEAN:
        return Column(t, d.astype(bool), src.valid)
    if t is DATE and isinstance(s, TimestampType):
        unit = 10 ** (3 - 0) if s.precision == 3 else 10 ** 3
        ms = d  # millis
        return Column(t, jnp.floor_divide(ms, 86400000).astype(jnp.int32),
                      src.valid)
    if isinstance(t, TimestampType) and s is DATE:
        return Column(t, d.astype(jnp.int64) * 86400000, src.valid)
    from ..types import TimestampTZType
    if isinstance(t, TimestampTZType):
        if isinstance(s, TimestampType):       # UTC interpretation
            return Column(t, d.astype(jnp.int64), src.valid,
                          data2=jnp.zeros((src.capacity,), jnp.int64))
        if s is DATE:
            return Column(t, d.astype(jnp.int64) * 86400000, src.valid,
                          data2=jnp.zeros((src.capacity,), jnp.int64))
        if isinstance(s, TimestampTZType):
            return dc_replace(src, type=t)
    if isinstance(s, TimestampTZType):
        local = _tz_local_millis(src)
        if isinstance(t, TimestampType):
            return Column(t, local, src.valid)
        if t is DATE:
            return Column(t, jnp.floor_divide(local, 86400000),
                          src.valid)
    raise EvalError(f"unsupported cast {s} -> {t}")


def _round_half_up(x: jax.Array) -> jax.Array:
    return (jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)).astype(jnp.int64)


def _div_round_half_up(x: jax.Array, q: int) -> jax.Array:
    sign = jnp.sign(x)
    ax = jnp.abs(x)
    return (sign * ((ax + q // 2) // q)).astype(jnp.int64)


def _parser_for(t: Type, safe: bool):
    import datetime

    def parse(v: str):
        try:
            if t is DATE:
                d = datetime.date.fromisoformat(v.strip())
                return d.toordinal() - datetime.date(1970, 1, 1).toordinal()
            if is_integral(t):
                return int(v.strip())
            if t.name in ("double", "real"):
                return float(v)
            if t is BOOLEAN:
                return v.strip().lower() in ("true", "t", "1")
            if isinstance(t, DecimalType):
                from decimal import Decimal
                q = Decimal(v.strip()).scaleb(t.scale)
                return int(q.to_integral_value())
            if isinstance(t, TimestampType):
                from ..types import iso_timestamp_millis
                return iso_timestamp_millis(v)
            from ..types import TimestampTZType as _TTZ
            if isinstance(t, _TTZ):
                from ..types import iso_timestamp_tz
                ms, off = iso_timestamp_tz(v)
                # single int lane from _dict_transform: encode the
                # UTC instant (offset recovered as 0 — fixed-offset
                # display is normalized to UTC on this path)
                return ms if off is None else ms
            from ..types import TimeType as _TT
            if isinstance(t, _TT):
                from ..types import iso_time_millis
                return iso_time_millis(v)
        except (ValueError, ArithmeticError):
            if safe:
                return None
            raise EvalError(f"Cannot cast '{v}' to {t}") from None
        raise EvalError(f"unsupported cast varchar -> {t}")

    return parse


def _to_varchar(src: Column, t: Type) -> Column:
    s = src.type
    n = src.capacity
    data = np.asarray(src.data)
    valid = None if src.valid is None else np.asarray(src.valid)
    hi_arr = (np.asarray(src.data2)
              if src.data2 is not None and isinstance(s, DecimalType)
              else None)
    out = []
    for i in range(n):
        if valid is not None and not valid[i]:
            out.append(None)
            continue
        v = data[i]
        if s is DATE:
            import datetime
            out.append(str(datetime.date.fromordinal(
                int(v) + datetime.date(1970, 1, 1).toordinal())))
        elif isinstance(s, DecimalType):
            q = int(v)
            if hi_arr is not None:
                q = (int(hi_arr[i]) << 64) + (q & ((1 << 64) - 1))
            if s.scale:
                sign = "-" if q < 0 else ""
                q = abs(q)
                out.append(f"{sign}{q // 10**s.scale}."
                           f"{q % 10**s.scale:0{s.scale}d}")
            else:
                out.append(str(q))
        elif s is BOOLEAN or s.name == "boolean":
            out.append("true" if v else "false")
        elif s.name in ("double", "real"):
            out.append(repr(float(v)))
        elif s.name.endswith("with time zone"):
            import datetime
            off = (int(np.asarray(src.data2)[i])
                   if src.data2 is not None else 0)
            local = (datetime.datetime(1970, 1, 1)
                     + datetime.timedelta(
                         milliseconds=int(v) + off * 60000))
            sign = "+" if off >= 0 else "-"
            out.append(local.strftime("%Y-%m-%d %H:%M:%S.%f")[:-3]
                       + f" {sign}{abs(off) // 60:02d}:"
                         f"{abs(off) % 60:02d}")
        elif s.name.startswith("timestamp"):
            import datetime
            local = (datetime.datetime(1970, 1, 1)
                     + datetime.timedelta(milliseconds=int(v)))
            out.append(local.strftime("%Y-%m-%d %H:%M:%S.%f")[:-3])
        elif s.name.startswith("time("):
            ms = int(v) % 86400000
            out.append(f"{ms // 3600000:02d}:{(ms // 60000) % 60:02d}"
                       f":{(ms // 1000) % 60:02d}.{ms % 1000:03d}")
        else:
            out.append(str(int(v)))
    d, codes = StringDictionary.from_strings(out)
    nv = np.asarray([o is not None for o in out], dtype=bool)
    return Column(t, jnp.asarray(codes),
                  None if nv.all() else jnp.asarray(nv), d)


# --------------------------------------------------------------------------
# calls
# --------------------------------------------------------------------------

def _eval_call(e: Call, batch: Batch) -> Column:
    fn = e.fn
    h = _DISPATCH.get(fn)
    if h is not None:
        return h(e, batch)
    raise EvalError(f"no evaluator for function '{fn}'")


# ---- boolean logic (Kleene) ----------------------------------------------

def _bool_parts(c: Column):
    d = _lane(c).astype(bool)
    v = (jnp.ones_like(d) if c.valid is None else jnp.asarray(c.valid))
    return d, v


def _and(e, batch):
    a, b = (eval_expr(x, batch) for x in e.args)
    ad, av = _bool_parts(a)
    bd, bv = _bool_parts(b)
    data = ad & bd
    # NULL unless either side is definite FALSE
    false_a = av & ~ad
    false_b = bv & ~bd
    valid = (av & bv) | false_a | false_b
    return Column(BOOLEAN, data & valid, valid)


def _or(e, batch):
    a, b = (eval_expr(x, batch) for x in e.args)
    ad, av = _bool_parts(a)
    bd, bv = _bool_parts(b)
    true_a = av & ad
    true_b = bv & bd
    data = true_a | true_b
    valid = (av & bv) | true_a | true_b
    return Column(BOOLEAN, data, valid)


def _not(e, batch):
    a = eval_expr(e.args[0], batch)
    return Column(BOOLEAN, ~_lane(a).astype(bool), a.valid)


def _is_null(e, batch):
    a = eval_expr(e.args[0], batch)
    live = batch.row_valid()
    if a.valid is None:
        return Column(BOOLEAN, jnp.zeros((batch.capacity,), bool), None)
    return Column(BOOLEAN, ~jnp.asarray(a.valid) & live, None)


# ---- comparisons ---------------------------------------------------------

def _align_string_codes(a: Column, b: Column):
    if a.dictionary is b.dictionary:
        return _lane(a), _lane(b), a.dictionary
    merged, ra, rb = a.dictionary.merge(b.dictionary)
    da = jnp.take(jnp.asarray(ra), _lane(a), mode="clip")
    db = jnp.take(jnp.asarray(rb), _lane(b), mode="clip")
    return da, db, merged


def _cmp(op: str):
    def h(e, batch):
        a = eval_expr(e.args[0], batch)
        b = eval_expr(e.args[1], batch)
        valid = _merge_valid(a, b)
        if is_string(a.type):
            if op in ("=", "<>"):
                da, db, _ = _align_string_codes(a, b)
                eq = da == db
                data = eq if op == "=" else ~eq
            else:
                ra = a.dictionary.rank_codes()
                if b.dictionary is a.dictionary:
                    rb_t = ra
                else:
                    merged, ma, mb = a.dictionary.merge(b.dictionary)
                    ranks = merged.rank_codes()
                    da = jnp.take(jnp.asarray(ranks[ma]), _lane(a),
                                  mode="clip")
                    db = jnp.take(jnp.asarray(ranks[mb]), _lane(b),
                                  mode="clip")
                    data = _cmp_lanes(op, da, db)
                    return Column(BOOLEAN, data, valid)
                da = jnp.take(jnp.asarray(ra), _lane(a), mode="clip")
                db = jnp.take(jnp.asarray(ra), _lane(b), mode="clip")
                data = _cmp_lanes(op, da, db)
            return Column(BOOLEAN, data, valid)
        da, db = _lane(a), _lane(b)
        if isinstance(a.type, DecimalType) and (a.data2 is not None
                                                or b.data2 is not None):
            data = _cmp_int128(op, a, b)
        else:
            data = _cmp_lanes(op, da, db)
        return Column(BOOLEAN, data, valid)

    return h


def _cmp_int128(op, a: Column, b: Column):
    """Two's-complement 128-bit comparison over (hi, lo) lanes: signed
    on the high word, unsigned on the low (the sign-bit-flip trick
    turns int64 order into uint64 order — the TPU path has no native
    u64 compare). A side without a hi lane sign-extends its low word.
    Reference: Int128Math/Decimal comparisons in spi/type/Decimals."""
    lo_a = jnp.asarray(a.data).astype(jnp.int64)
    lo_b = jnp.asarray(b.data).astype(jnp.int64)
    hi_a = (jnp.asarray(a.data2).astype(jnp.int64)
            if a.data2 is not None else lo_a >> 63)
    hi_b = (jnp.asarray(b.data2).astype(jnp.int64)
            if b.data2 is not None else lo_b >> 63)
    sbit = jnp.int64(-(2 ** 63))
    ua, ub = lo_a ^ sbit, lo_b ^ sbit
    if op in ("=", "<>"):
        eq = (hi_a == hi_b) & (lo_a == lo_b)
        return eq if op == "=" else ~eq
    lt = (hi_a < hi_b) | ((hi_a == hi_b) & (ua < ub))
    if op == "<":
        return lt
    if op == ">=":
        return ~lt
    gt = (hi_a > hi_b) | ((hi_a == hi_b) & (ua > ub))
    return gt if op == ">" else ~gt


def _cmp_lanes(op, da, db):
    if op == "=":
        return da == db
    if op == "<>":
        return da != db
    if op == "<":
        return da < db
    if op == "<=":
        return da <= db
    if op == ">":
        return da > db
    return da >= db


def _is_distinct_from(e, batch):
    a = eval_expr(e.args[0], batch)
    b = eval_expr(e.args[1], batch)
    live = batch.row_valid()
    av = (live if a.valid is None else jnp.asarray(a.valid) & live)
    bv = (live if b.valid is None else jnp.asarray(b.valid) & live)
    if is_string(a.type):
        da, db, _ = _align_string_codes(a, b)
    else:
        da, db = _lane(a), _lane(b)
    neq = da != db
    data = (av != bv) | (av & bv & neq)
    return Column(BOOLEAN, data, None)


# ---- arithmetic ----------------------------------------------------------

def _arith(op: str):
    def h(e, batch):
        a = eval_expr(e.args[0], batch)
        b = eval_expr(e.args[1], batch)
        valid = _merge_valid(a, b)
        da, db = _lane(a), _lane(b)
        if op == "+":
            data = da + db
        elif op == "-":
            data = da - db
        elif op == "*":
            data = da * db
        elif op == "/":
            if is_integral(e.type):
                sign = jnp.sign(da) * jnp.sign(db)
                data = sign * (jnp.abs(da) //
                               jnp.maximum(jnp.abs(db), 1))
                data = data.astype(da.dtype)
            else:
                data = da / db
        elif op == "%":
            if is_integral(e.type):
                m = jnp.abs(da) % jnp.maximum(jnp.abs(db), 1)
                data = (jnp.sign(da) * m).astype(da.dtype)
            else:
                data = jnp.where(db != 0, jnp.fmod(da, db), jnp.nan)
        return Column(e.type, data.astype(e.type.np_dtype), valid)

    return h


def _decimal_arith(op: str):
    def h(e, batch):
        a = eval_expr(e.args[0], batch)
        b = eval_expr(e.args[1], batch)
        t: DecimalType = e.type
        if (a.data2 is not None) or (b.data2 is not None) or not t.is_short:
            return _decimal_arith_128(op, a, b, t)
        sa = a.type.scale if isinstance(a.type, DecimalType) else 0
        sb = b.type.scale if isinstance(b.type, DecimalType) else 0
        da = _lane(a).astype(jnp.int64)
        db = _lane(b).astype(jnp.int64)
        valid = _merge_valid(a, b)
        if op in ("+", "-"):
            da = da * (10 ** (t.scale - sa))
            db = db * (10 ** (t.scale - sb))
            data = da + db if op == "+" else da - db
        elif op == "*":
            data = da * db
            shift = sa + sb - t.scale
            if shift > 0:
                data = _div_round_half_up(data, 10 ** shift)
        elif op == "/":
            # result scale t.scale: (a/b) * 10^ts = a*10^(ts - sa + sb) / b
            shift = t.scale - sa + sb
            num = da * (10 ** max(shift, 0))
            den = jnp.where(db == 0, 1, db)
            q = num.astype(jnp.float64) / den.astype(jnp.float64)
            if shift < 0:
                q = q / (10 ** (-shift))
            data = _round_half_up(q)
        elif op == "%":
            data = jnp.where(db != 0, da % jnp.where(db == 0, 1, db), 0)
        return Column(t, data, valid)

    return h


def _decimal_arith_128(op: str, a: Column, b: Column,
                       t: "DecimalType") -> Column:
    """Exact Int128 decimal arithmetic over (lo, hi) lanes.
    Reference: spi/type/UnscaledDecimal128Arithmetic.java:42 (add /
    multiply / rescale on Int128, HALF_UP rounding)."""
    from ..ops import int128 as i128
    sa = a.type.scale if isinstance(a.type, DecimalType) else 0
    sb = b.type.scale if isinstance(b.type, DecimalType) else 0
    valid = _merge_valid(a, b)

    def lanes(c):
        lo = _lane(c).astype(jnp.int64)
        hi = (jnp.asarray(c.data2).astype(jnp.int64)
              if c.data2 is not None else i128.sign_extend(lo))
        return lo, hi

    alo, ahi = lanes(a)
    blo, bhi = lanes(b)
    if op in ("+", "-"):
        alo, ahi = i128.rescale(alo, ahi, t.scale - sa)
        blo, bhi = i128.rescale(blo, bhi, t.scale - sb)
        lo, hi = (i128.add128(alo, ahi, blo, bhi) if op == "+"
                  else i128.sub128(alo, ahi, blo, bhi))
    elif op == "*":
        lo, hi = i128.mul128(alo, ahi, blo, bhi)
        lo, hi = i128.rescale(lo, hi, t.scale - sa - sb)
    elif op == "/":
        # (a/b) at scale t.scale: round(a * 10^(t.scale - sa + sb) / b)
        shift = t.scale - sa + sb
        alo, ahi = i128.rescale(alo, ahi, max(shift, 0))
        blo, bhi = i128.rescale(blo, bhi, max(-shift, 0))
        zero = (blo == 0) & (bhi == 0)
        blo_s = jnp.where(zero, 1, blo)
        lo, hi = i128.div128_round_half_up_pair(alo, ahi, blo_s, bhi)
        valid = (~zero if valid is None else valid & ~zero)
    else:  # %
        # operands must agree on the result scale before the divmod
        # (150@s2 mod 30@s1 is 0.20, not the dimensionally-true 2.00)
        alo, ahi = i128.rescale(alo, ahi, t.scale - sa)
        blo, bhi = i128.rescale(blo, bhi, t.scale - sb)
        zero = (blo == 0) & (bhi == 0)
        blo_s = jnp.where(zero, 1, blo)
        _, _, lo, hi = i128.divmod128_trunc(alo, ahi, blo_s, bhi)
        valid = (~zero if valid is None else valid & ~zero)
    if t.is_short:
        return Column(t, lo, valid)
    return Column(t, lo, valid, data2=hi)


def _negate(e, batch):
    a = eval_expr(e.args[0], batch)
    if a.data2 is not None and isinstance(a.type, DecimalType):
        from ..ops import int128 as i128
        lo, hi = i128.neg128(_lane(a).astype(jnp.int64),
                             jnp.asarray(a.data2).astype(jnp.int64))
        return dc_replace(a, data=lo, data2=hi, type=e.type)
    return dc_replace(a, data=-_lane(a), type=e.type)


# ---- scalar math ---------------------------------------------------------

def _unary_np(fn):
    def h(e, batch):
        a = eval_expr(e.args[0], batch)
        return Column(e.type, fn(_lane(a).astype(jnp.float64))
                      .astype(e.type.np_dtype), a.valid)
    return h


def _abs(e, batch):
    a = eval_expr(e.args[0], batch)
    if a.data2 is not None and isinstance(a.type, DecimalType):
        from ..ops import int128 as i128
        lo, hi = i128.abs128(_lane(a).astype(jnp.int64),
                             jnp.asarray(a.data2).astype(jnp.int64))
        return dc_replace(a, data=lo, data2=hi)
    return dc_replace(a, data=jnp.abs(_lane(a)))


def _round(e, batch):
    a = eval_expr(e.args[0], batch)
    t = a.type
    if isinstance(t, DecimalType):
        if a.data2 is not None:
            if len(e.args) == 2:
                arg1 = e.args[1]
                if not isinstance(arg1, Const) or arg1.value is None:
                    raise EvalError(
                        "round(decimal, n) requires a literal n")
                n = int(arg1.value)
            else:
                n = 0
            if n >= t.scale:
                return a
            if t.scale - n > 38:
                # 10^(scale-n) exceeds 128 bits: every value rounds to 0
                z = jnp.zeros_like(_lane(a).astype(jnp.int64))
                return Column(t, z, a.valid, data2=z)
            from ..ops import int128 as i128
            lo = _lane(a).astype(jnp.int64)
            hi = jnp.asarray(a.data2).astype(jnp.int64)
            lo, hi = i128.rescale(lo, hi, -(t.scale - n))
            lo, hi = i128.rescale(lo, hi, t.scale - n)
            return Column(t, lo, a.valid, data2=hi)
        # digits must be a constant for a static result scale
        # (reference: round(decimal, n) with literal n — the common
        # SQL shape; a per-row digit lane has no fixed output type)
        if len(e.args) == 2:
            arg1 = e.args[1]
            if not isinstance(arg1, Const) or arg1.value is None:
                raise EvalError(
                    "round(decimal, n) requires a literal n")
            n = int(arg1.value)
        else:
            n = 0
        d = _lane(a).astype(jnp.int64)
        if n >= t.scale:
            return a
        if t.scale - n > 18:
            # divisor would overflow int64; every int64-lane value
            # rounds to 0 at that magnitude (Trino returns 0 here)
            return Column(t, jnp.zeros_like(d), a.valid)
        div = 10 ** (t.scale - n)
        rounded = _div_round_half_up(d, div) * div
        return Column(t, rounded, a.valid)
    if is_integral(t):
        return a
    if len(e.args) == 2:
        dcol = eval_expr(e.args[1], batch)
        dd = _lane(dcol).astype(jnp.int64)
        scale = jnp.power(10.0, dd.astype(jnp.float64))
    else:
        scale = 1.0
    d = _lane(a).astype(jnp.float64)
    data = jnp.sign(d) * jnp.floor(jnp.abs(d) * scale + 0.5) / scale
    return Column(t, data.astype(t.np_dtype), a.valid)


def _floorceil(which):
    def h(e, batch):
        a = eval_expr(e.args[0], batch)
        t = a.type
        if is_integral(t):
            return a
        d = _lane(a).astype(jnp.float64)
        data = jnp.floor(d) if which == "floor" else jnp.ceil(d)
        return Column(t, data.astype(t.np_dtype), a.valid)
    return h


def _truncate(e, batch):
    a = eval_expr(e.args[0], batch)
    d = _lane(a).astype(jnp.float64)
    return Column(a.type, jnp.trunc(d).astype(a.type.np_dtype), a.valid)


def _sign(e, batch):
    a = eval_expr(e.args[0], batch)
    return Column(a.type, jnp.sign(_lane(a)).astype(a.type.np_dtype),
                  a.valid)


def _power(e, batch):
    a = eval_expr(e.args[0], batch)
    b = eval_expr(e.args[1], batch)
    return Column(DOUBLE, jnp.power(_lane(a).astype(jnp.float64),
                                    _lane(b).astype(jnp.float64)),
                  _merge_valid(a, b))


def _mod(e, batch):
    return _arith("%")(e, batch)


def _greatest_least(which):
    def h(e, batch):
        cols = [eval_expr(a, batch) for a in e.args]
        data = _lane(cols[0])
        for c in cols[1:]:
            d = _lane(c)
            data = jnp.maximum(data, d) if which == "greatest" \
                else jnp.minimum(data, d)
        return Column(e.type, data, _merge_valid(*cols))
    return h


# ---- conditionals --------------------------------------------------------

def _coalesce(e, batch):
    cols = [eval_expr(a, batch) for a in e.args]
    if is_string(e.type):
        merged = None
        remapped = []
        for c in cols:
            if merged is None:
                merged = c.dictionary
                remapped.append(_lane(c))
            else:
                merged, _, ro = merged.merge(c.dictionary)
                remapped.append(jnp.take(jnp.asarray(ro), _lane(c),
                                         mode="clip"))
        data = remapped[-1]
        valid = (jnp.ones((batch.capacity,), bool)
                 if cols[-1].valid is None else jnp.asarray(cols[-1].valid))
        for c, d in zip(reversed(cols[:-1]), reversed(remapped[:-1])):
            v = (jnp.ones_like(valid) if c.valid is None
                 else jnp.asarray(c.valid))
            data = jnp.where(v, d, data)
            valid = v | valid
        return Column(e.type, data, valid, merged)
    data = _lane(cols[-1])
    valid = (jnp.ones((batch.capacity,), bool) if cols[-1].valid is None
             else jnp.asarray(cols[-1].valid))
    for c in reversed(cols[:-1]):
        v = (jnp.ones((batch.capacity,), bool) if c.valid is None
             else jnp.asarray(c.valid))
        data = jnp.where(v, _lane(c).astype(data.dtype), data)
        valid = v | valid
    return Column(e.type, data, valid)


def _nullif(e, batch):
    a = eval_expr(e.args[0], batch)
    b = eval_expr(e.args[1], batch)
    if is_string(a.type):
        da, db, _ = _align_string_codes(a, b)
    else:
        da, db = _lane(a), _lane(b)
    both = _merge_valid(a, b)
    eq = (da == db) if both is None else ((da == db) & both)
    av = (jnp.ones((batch.capacity,), bool) if a.valid is None
          else jnp.asarray(a.valid))
    return dc_replace(a, valid=av & ~eq)


def _if(e, batch):
    c = eval_expr(e.args[0], batch)
    case = CaseExpr(((e.args[0], e.args[1]),), e.args[2], e.type)
    return _eval_case(case, batch)


def _try(e, batch):
    try:
        return eval_expr(e.args[0], batch)
    except EvalError:
        return _const_column(Const(None, e.type), batch.capacity)


# ---- strings -------------------------------------------------------------

def like_to_regex(pattern: str, escape: Optional[str] = None) -> str:
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if escape and ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return "".join(out)


def _like(e, batch):
    a = eval_expr(e.args[0], batch)
    pat = e.args[1]
    if not isinstance(pat, Const):
        raise EvalError("LIKE pattern must be constant")
    esc = None
    if len(e.args) > 2:
        if not isinstance(e.args[2], Const):
            raise EvalError("LIKE escape must be constant")
        esc = e.args[2].value
    rx = re.compile(like_to_regex(str(pat.value), esc), re.DOTALL)
    return _dict_transform(a, lambda v: rx.fullmatch(v) is not None,
                           BOOLEAN)


def _regexp_like(e, batch):
    a = eval_expr(e.args[0], batch)
    pat = e.args[1]
    if not isinstance(pat, Const):
        raise EvalError("regexp pattern must be constant")
    rx = re.compile(str(pat.value))
    return _dict_transform(a, lambda v: rx.search(v) is not None, BOOLEAN)


def _string_unary(fn):
    def h(e, batch):
        a = eval_expr(e.args[0], batch)
        return _dict_transform(a, fn, e.type)
    return h


def _length(e, batch):
    a = eval_expr(e.args[0], batch)
    if isinstance(a.type, CharType):
        return _dict_transform(a, lambda v: a.type.length, BIGINT)
    return _dict_transform(a, len, BIGINT)


def _substr(e, batch):
    a = eval_expr(e.args[0], batch)
    rest = [eval_expr(x, batch) for x in e.args[1:]]
    if all(isinstance(x, Const) for x in e.args[1:]):
        start = int(e.args[1].value)
        ln = int(e.args[2].value) if len(e.args) > 2 else None

        def f(v: str):
            i = start - 1 if start > 0 else len(v) + start
            return v[i:] if ln is None else v[i:i + ln]
        return _dict_transform(a, f, e.type)
    # dynamic start/length: host row fallback
    starts = np.asarray(rest[0].data)
    lens = np.asarray(rest[1].data) if len(rest) > 1 else None
    mats = _materialize_strings(a)
    out = []
    for i, v in enumerate(mats):
        if v is None:
            out.append(None)
            continue
        st = int(starts[i])
        j = st - 1 if st > 0 else len(v) + st
        out.append(v[j:] if lens is None else v[j:j + int(lens[i])])
    d, codes = StringDictionary.from_strings(out)
    nv = np.asarray([o is not None for o in out], dtype=bool)
    return Column(e.type, jnp.asarray(codes),
                  None if nv.all() else jnp.asarray(nv), d)


def _concat(e, batch):
    cols = [eval_expr(a, batch) for a in e.args]
    n_dyn = sum(1 for c, a in zip(cols, e.args)
                if not isinstance(a, Const))
    if n_dyn <= 1:
        # single dynamic column: dictionary transform with const parts
        parts = [(c if isinstance(a, Const) else None, a)
                 for c, a in zip(cols, e.args)]
        dyn_idx = next((i for i, a in enumerate(e.args)
                        if not isinstance(a, Const)), None)
        if dyn_idx is None:
            s = "".join(str(a.value) for a in e.args)
            return _const_column(Const(s, VARCHAR), batch.capacity)
        pre = "".join(str(a.value) for a in e.args[:dyn_idx])
        post = "".join(str(a.value) for a in e.args[dyn_idx + 1:])
        return _dict_transform(cols[dyn_idx],
                               lambda v: pre + v + post, e.type)
    return _row_string_fn(cols, lambda *vs: "".join(vs), e.type)


def _strpos(e, batch):
    a = eval_expr(e.args[0], batch)
    pat = e.args[1]
    if not isinstance(pat, Const):
        raise EvalError("strpos needle must be constant")
    needle = str(pat.value)
    return _dict_transform(a, lambda v: v.find(needle) + 1, BIGINT)


def _replace(e, batch):
    a = eval_expr(e.args[0], batch)
    if not all(isinstance(x, Const) for x in e.args[1:]):
        raise EvalError("replace search/replacement must be constant")
    search = str(e.args[1].value)
    repl = str(e.args[2].value) if len(e.args) > 2 else ""
    return _dict_transform(a, lambda v: v.replace(search, repl), e.type)


def _starts_with(e, batch):
    a = eval_expr(e.args[0], batch)
    pat = e.args[1]
    if not isinstance(pat, Const):
        raise EvalError("starts_with prefix must be constant")
    p = str(pat.value)
    return _dict_transform(a, lambda v: v.startswith(p), BOOLEAN)


def _split_part(e, batch):
    a = eval_expr(e.args[0], batch)
    if not all(isinstance(x, Const) for x in e.args[1:]):
        raise EvalError("split_part arguments must be constant")
    delim = str(e.args[1].value)
    idx = int(e.args[2].value)

    def f(v: str):
        parts = v.split(delim)
        return parts[idx - 1] if 1 <= idx <= len(parts) else None
    return _dict_transform(a, f, e.type)


def _pad(which):
    def h(e, batch):
        a = eval_expr(e.args[0], batch)
        size = int(e.args[1].value)
        fill = str(e.args[2].value) if len(e.args) > 2 else " "

        def f(v: str):
            if len(v) >= size:
                return v[:size]
            padn = size - len(v)
            p = (fill * padn)[:padn]
            return p + v if which == "lpad" else v + p
        return _dict_transform(a, f, e.type)
    return h


# ---- datetime ------------------------------------------------------------

def _tz_local_millis(a: Column) -> jax.Array:
    """UTC instant lane + per-value offset minutes -> local millis."""
    ms = _lane(a).astype(jnp.int64)
    if a.data2 is not None:
        ms = ms + jnp.asarray(a.data2).astype(jnp.int64) * 60000
    return ms


def _extract(field: str):
    def h(e, batch):
        from ..types import TimestampTZType
        a = eval_expr(e.args[0], batch)
        if a.type is DATE:
            days = _lane(a).astype(jnp.int64)
        elif isinstance(a.type, TimestampType):
            days = jnp.floor_divide(_lane(a), 86400000)
        elif isinstance(a.type, TimestampTZType):
            days = jnp.floor_divide(_tz_local_millis(a), 86400000)
        else:
            raise EvalError(f"{field}() requires date/timestamp")
        return Column(BIGINT, extract_field(days, field), a.valid)
    return h


def _time_field(field: str):
    def h(e, batch):
        from ..types import TimeType, TimestampTZType
        a = eval_expr(e.args[0], batch)
        if isinstance(a.type, TimestampTZType):
            ms = jnp.mod(_tz_local_millis(a), 86400000)
        elif not isinstance(a.type, (TimestampType, TimeType)):
            return Column(BIGINT, jnp.zeros((batch.capacity,), jnp.int64),
                          a.valid)
        else:
            ms = jnp.mod(_lane(a), 86400000)
        if field == "hour":
            v = ms // 3600000
        elif field == "minute":
            v = (ms // 60000) % 60
        elif field == "second":
            v = (ms // 1000) % 60
        else:
            v = ms % 1000
        return Column(BIGINT, v.astype(jnp.int64), a.valid)
    return h


def _date_interval(op: str):
    def h(e, batch):
        a = eval_expr(e.args[0], batch)
        b = eval_expr(e.args[1], batch)
        days = _lane(a).astype(jnp.int64)
        valid = _merge_valid(a, b)
        iv = _lane(b).astype(jnp.int64)
        if op == "-":
            iv = -iv
        if e.args[1].type is IntervalYearMonth:
            data = add_months(days, iv)
        else:
            data = days + jnp.floor_divide(iv, 86400000)
        return Column(DATE, data.astype(jnp.int32), valid)
    return h


def _ts_interval(op: str):
    def h(e, batch):
        a = eval_expr(e.args[0], batch)
        b = eval_expr(e.args[1], batch)
        ms = _lane(a).astype(jnp.int64)
        iv = _lane(b).astype(jnp.int64)
        if op == "-":
            iv = -iv
        valid = _merge_valid(a, b)
        if e.args[1].type is IntervalYearMonth:
            days = jnp.floor_divide(ms, 86400000)
            tod = ms - days * 86400000
            data = add_months(days, iv) * 86400000 + tod
        else:
            data = ms + iv
        return Column(e.type, data, valid)
    return h


def _date_diff_days(e, batch):
    a = eval_expr(e.args[0], batch)
    b = eval_expr(e.args[1], batch)
    return Column(BIGINT, _lane(a).astype(jnp.int64)
                  - _lane(b).astype(jnp.int64), _merge_valid(a, b))


def _date_trunc(e, batch):
    unit = e.args[0]
    if not isinstance(unit, Const):
        raise EvalError("date_trunc unit must be constant")
    a = eval_expr(e.args[1], batch)
    u = str(unit.value).lower()
    if a.type is DATE:
        return Column(DATE, date_trunc_days(
            _lane(a).astype(jnp.int64), u).astype(jnp.int32), a.valid)
    if isinstance(a.type, TimestampType):
        ms = _lane(a).astype(jnp.int64)
        if u in ("year", "quarter", "month", "week", "day"):
            days = jnp.floor_divide(ms, 86400000)
            return Column(a.type,
                          date_trunc_days(days, u) * 86400000, a.valid)
        q = {"hour": 3600000, "minute": 60000, "second": 1000}[u]
        return Column(a.type, (ms // q) * q, a.valid)
    raise EvalError("date_trunc requires date/timestamp")


def _date_diff(e, batch):
    unit = e.args[0]
    if not isinstance(unit, Const):
        raise EvalError("date_diff unit must be constant")
    u = str(unit.value).lower()
    a = eval_expr(e.args[1], batch)
    b = eval_expr(e.args[2], batch)
    valid = _merge_valid(a, b)

    def days_of(c):
        if c.type is DATE:
            return _lane(c).astype(jnp.int64)
        return jnp.floor_divide(_lane(c), 86400000)

    if u == "day":
        return Column(BIGINT, days_of(b) - days_of(a), valid)
    if u in ("month", "year", "quarter", "week"):
        from ..ops.datetime import civil_from_days
        ya, ma, da_ = civil_from_days(days_of(a))
        yb, mb, db_ = civil_of = civil_from_days(days_of(b))
        months = (yb * 12 + mb) - (ya * 12 + ma)
        months = months - (db_ < da_)
        if u == "month":
            return Column(BIGINT, months, valid)
        if u == "quarter":
            return Column(BIGINT, months // 3, valid)
        if u == "year":
            return Column(BIGINT, months // 12, valid)
        return Column(BIGINT, (days_of(b) - days_of(a)) // 7, valid)
    q = {"hour": 3600000, "minute": 60000, "second": 1000,
         "millisecond": 1}[u]
    return Column(BIGINT, (_lane(b) - _lane(a)) // q, valid)


def _date_add(e, batch):
    unit = e.args[0]
    if not isinstance(unit, Const):
        raise EvalError("date_add unit must be constant")
    u = str(unit.value).lower()
    n = eval_expr(e.args[1], batch)
    a = eval_expr(e.args[2], batch)
    valid = _merge_valid(n, a)
    nn = _lane(n).astype(jnp.int64)
    if a.type is DATE:
        days = _lane(a).astype(jnp.int64)
        if u == "day":
            out = days + nn
        elif u == "week":
            out = days + nn * 7
        elif u in ("month", "quarter", "year"):
            mult = {"month": 1, "quarter": 3, "year": 12}[u]
            out = add_months(days, nn * mult)
        else:
            raise EvalError(f"date_add('{u}') on date not supported")
        return Column(DATE, out.astype(jnp.int32), valid)
    ms = _lane(a).astype(jnp.int64)
    q = {"day": 86400000, "hour": 3600000, "minute": 60000,
         "second": 1000, "millisecond": 1, "week": 7 * 86400000}.get(u)
    if q is not None:
        return Column(a.type, ms + nn * q, valid)
    days = jnp.floor_divide(ms, 86400000)
    tod = ms - days * 86400000
    mult = {"month": 1, "quarter": 3, "year": 12}[u]
    return Column(a.type, add_months(days, nn * mult) * 86400000 + tod,
                  valid)


# ---- float predicates ----------------------------------------------------

def _geo_call(which):
    """Geospatial dispatch into ops/geo.py (vectorized point lanes).
    Numeric arguments (coordinates) coerce to DOUBLE — a DECIMAL
    literal's scaled-integer lane must not leak into geometry math."""
    def h(e, batch):
        from ..ops import geo
        from ..types import GEOMETRY as _G, is_numeric as _isnum
        args = [eval_expr(a, batch) for a in e.args]
        args = [cast_column(a, DOUBLE)
                if a.type is not _G and _isnum(a.type)
                and a.type is not DOUBLE else a
                for a in args]
        try:
            if which == "point":
                return geo.point_column(*args)
            if which == "x":
                return geo.st_x(args[0])
            if which == "y":
                return geo.st_y(args[0])
            if which == "distance":
                return geo.st_distance(*args)
            if which == "fromtext":
                return geo.geometry_from_text(args[0])
            if which == "astext":
                return geo.as_text(args[0])
            if which == "contains":
                return geo.st_contains(*args)
            return geo.great_circle_distance(*args)
        except ValueError as ex:
            raise EvalError(str(ex)) from ex
    return h


def _float_pred(fn):
    def h(e, batch):
        a = eval_expr(e.args[0], batch)
        return Column(BOOLEAN, fn(_lane(a).astype(jnp.float64)), a.valid)
    return h


# ---- unix time + MySQL-style datetime formatting -------------------------
# (operator/scalar/DateTimeFunctions.java: from_unixtime, to_unixtime,
# date_format, date_parse — format codes are the MySQL set)

_MYSQL_FMT = {"Y": "%Y", "y": "%y", "m": "%m", "c": "%m", "d": "%d",
              "e": "%d", "H": "%H", "k": "%H", "h": "%I", "I": "%I",
              "i": "%M", "s": "%S", "S": "%S", "f": "%f", "p": "%p",
              "W": "%A", "a": "%a", "b": "%b", "M": "%B", "j": "%j",
              "T": "%H:%M:%S", "%": "%%"}


def _mysql_to_py_format(fmt: str) -> str:
    out = []
    i = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch == "%" and i + 1 < len(fmt):
            code = fmt[i + 1]
            if code not in _MYSQL_FMT:
                # fail loudly rather than emit plausible wrong output
                raise EvalError(
                    f"unsupported datetime format code '%{code}'")
            out.append(_MYSQL_FMT[code])
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _const_str(e) -> str:
    from ..rex import Const as _Const
    if not isinstance(e, _Const) or e.value is None:
        raise EvalError("format string must be a constant")
    return str(e.value)


def _from_unixtime(e, batch):
    a = eval_expr(e.args[0], batch)
    ms = jnp.round(_lane(a).astype(jnp.float64) * 1000.0) \
        .astype(jnp.int64)
    return Column(e.type, ms, a.valid)


def _to_unixtime(e, batch):
    a = eval_expr(e.args[0], batch)
    return Column(DOUBLE, _lane(a).astype(jnp.float64) / 1000.0, a.valid)


def _date_format(e, batch):
    import datetime as _dt
    a = eval_expr(e.args[0], batch)
    pyfmt = _mysql_to_py_format(_const_str(e.args[1]))
    ms = np.asarray(a.data).astype(np.int64)   # host materialization
    if a.type is DATE or a.type.name == "date":
        ms = ms * 86400000
    # skip invalid slots: they hold arbitrary sentinels (e.g. the
    # int64 min/max identities of window aggregates) that overflow
    # timedelta
    ok = (np.ones(ms.shape, bool) if a.valid is None
          else np.asarray(a.valid))
    epoch = _dt.datetime(1970, 1, 1)
    out = [(epoch + _dt.timedelta(milliseconds=int(v))).strftime(pyfmt)
           if k else "" for v, k in zip(ms, ok)]
    dic, codes = StringDictionary.from_strings(out)
    return Column(e.type, jnp.asarray(codes), a.valid, dic)


def _date_parse(e, batch):
    import datetime as _dt
    a = eval_expr(e.args[0], batch)
    pyfmt = _mysql_to_py_format(_const_str(e.args[1]))
    epoch = _dt.datetime(1970, 1, 1)

    def parse(v: str):
        try:
            dt = _dt.datetime.strptime(v, pyfmt)
        except ValueError:
            return None
        return int((dt - epoch).total_seconds() * 1000)

    return _dict_transform(a, parse, e.type)


# ---- JSON (operator/scalar/JsonFunctions.java; JSON values travel as
# varchar — the reference's JSON type is a thin wrapper over a slice) ---

_JSON_TOKEN = None


def _json_path_tokens(path: str):
    """Tokenize a JSONPath subset: $.field, $.a.b, $[0], $.a[2].b —
    the shapes JsonExtract.java's generated extractors cover. Raises
    on anything else (the reference's INVALID_FUNCTION_ARGUMENT for
    unsupported paths, never silent misreads)."""
    import re as _re
    tok_re = _re.compile(r"\.([A-Za-z_][A-Za-z0-9_]*)|\[(\d+)\]")
    toks = []
    i = 0
    while i < len(path):
        m = tok_re.match(path, i)
        if m is None:
            raise EvalError(f"invalid JSON path: '${path}'")
        toks.append(m.groups())
        i = m.end()
    return toks


def _json_path_get(doc, toks):
    cur = doc
    for name, idx in toks:
        if name:
            if not isinstance(cur, dict) or name not in cur:
                return None
            cur = cur[name]
        else:
            i = int(idx)
            if not isinstance(cur, list) or i >= len(cur):
                return None
            cur = cur[i]
    return cur


def _json_fn(kind: str):
    def h(e, batch):
        import json as _json
        a = eval_expr(e.args[0], batch)
        path = _const_str(e.args[1]) if len(e.args) > 1 else "$"
        if not path.startswith("$"):
            raise EvalError(f"invalid JSON path: {path}")
        toks = _json_path_tokens(path[1:])

        def f(v: str):
            try:
                doc = _json.loads(v)
            except ValueError:
                return None
            got = _json_path_get(doc, toks)
            if kind == "scalar":
                if got is None or isinstance(got, (dict, list)):
                    return None
                if isinstance(got, bool):
                    return "true" if got else "false"
                return str(got)
            if kind == "extract":
                return None if got is None else _json.dumps(got)
            if kind == "array_length":
                return len(got) if isinstance(got, list) else None
            if kind == "size":
                if got is None:
                    return None
                return len(got) if isinstance(got, (list, dict)) else 0
            return None
        return _dict_transform(a, f, e.type)
    return h


# ---- arrays --------------------------------------------------------------
# spi/block/ArrayBlock redesigned: per-row (start, length) lanes over a
# flat elements Column (columnar.py Column.elements)

def _array_ctor(e, batch):
    from ..types import is_string as _isstr
    items = [eval_expr(a, batch) for a in e.args]
    if items[0].elements is not None or items[0].children is not None:
        # nested ARRAY/MAP/ROW elements: pools merged host-side
        from .complex import array_ctor_complex
        return array_ctor_complex(e, items, batch)
    k = len(items)
    cap = batch.capacity
    dic = None
    if _isstr(items[0].type):
        dic = items[0].dictionary
        remaps = []
        for it in items:
            dic, _, ro = dic.merge(it.dictionary)
            remaps.append(ro)
        # earlier codes stay stable under later merges (merge appends)
        lanes = [jnp.take(jnp.asarray(rm),
                          jnp.asarray(it.data).astype(jnp.int32),
                          mode="clip")
                 for it, rm in zip(items, remaps)]
    else:
        lanes = [jnp.asarray(it.data) for it in items]
    flat = jnp.stack(lanes, axis=1).reshape(-1)
    valid_flat = None
    if any(it.valid is not None for it in items):
        vl = [jnp.ones((cap,), bool) if it.valid is None
              else jnp.asarray(it.valid) for it in items]
        valid_flat = jnp.stack(vl, axis=1).reshape(-1)
    d2 = None
    if any(it.data2 is not None for it in items):
        l2 = [jnp.zeros((cap,), jnp.int64) if it.data2 is None
              else jnp.asarray(it.data2) for it in items]
        d2 = jnp.stack(l2, axis=1).reshape(-1)
    elements = Column(e.type.element, flat, valid_flat, dic, d2)
    start = jnp.arange(cap, dtype=jnp.int64) * k
    length = jnp.full((cap,), k, jnp.int64)
    return Column(e.type, start, None, None, length, elements)


def _cardinality(e, batch):
    a = eval_expr(e.args[0], batch)
    from ..types import HyperLogLogType
    if isinstance(a.type, HyperLogLogType):
        # cardinality(hll): the HLL estimator over each row's sparse
        # entries (reference: operator/scalar/HyperLogLogFunctions.java)
        from ..ops.hll import estimate_from_sparse
        est = estimate_from_sparse(jnp.asarray(a.data),
                                   jnp.asarray(a.data2),
                                   jnp.asarray(a.elements.data),
                                   a.type.bucket_bits)
        return Column(BIGINT, est, a.valid)
    if a.elements is None:
        raise EvalError("cardinality requires an array or map")
    return Column(BIGINT, jnp.asarray(a.data2).astype(jnp.int64),
                  a.valid)


def _empty_approx_set(e, batch):
    """Constant empty HLL sketch per row (HyperLogLogFunctions.java):
    zero sparse entries. Bucket bits match approx_set's default so
    merge(coalesce(approx_set(x), empty_approx_set())) type-checks."""
    from ..ops.hll import APPROX_SET_BUCKET_BITS
    from ..types import HyperLogLogType, INTEGER
    cap = batch.capacity
    empty = Column(INTEGER, jnp.zeros((8,), jnp.int32))
    return Column(HyperLogLogType(APPROX_SET_BUCKET_BITS),
                  jnp.zeros((cap,), jnp.int64), None,
                  None, jnp.zeros((cap,), jnp.int64), empty)


def _element_at(e, batch):
    from ..types import MapType
    if isinstance(e.args[0].type, MapType):
        from .complex import _map_element_at
        return _map_element_at(e, batch)
    a = eval_expr(e.args[0], batch)
    i = eval_expr(e.args[1], batch)
    if a.elements is None:
        raise EvalError("element_at requires an array")
    idx = jnp.asarray(i.data).astype(jnp.int64)
    length = jnp.asarray(a.data2).astype(jnp.int64)
    # 1-based; negative indexes from the end (reference element_at);
    # out of range -> NULL
    pos = jnp.where(idx < 0, length + idx, idx - 1)
    inrange = (pos >= 0) & (pos < length)
    flat_idx = jnp.asarray(a.data).astype(jnp.int64) + \
        jnp.clip(pos, 0, jnp.maximum(length - 1, 0))
    el = a.elements
    edata = jnp.take(jnp.asarray(el.data), flat_idx, mode="clip")
    valid = inrange
    for v in (a.valid, i.valid):
        if v is not None:
            valid = valid & jnp.asarray(v)
    if el.valid is not None:
        valid = valid & jnp.take(jnp.asarray(el.valid), flat_idx,
                                 mode="clip")
    d2 = (None if el.data2 is None
          else jnp.take(jnp.asarray(el.data2), flat_idx, mode="clip"))
    return Column(el.type, edata, valid, el.dictionary, d2)


# ---- dispatch table ------------------------------------------------------

_DISPATCH: Dict[str, Callable] = {
    "and": _and, "or": _or, "not": _not, "is_null": _is_null,
    "is_distinct_from": _is_distinct_from,
    "=": _cmp("="), "<>": _cmp("<>"), "<": _cmp("<"), "<=": _cmp("<="),
    ">": _cmp(">"), ">=": _cmp(">="),
    "+": _arith("+"), "-": _arith("-"), "*": _arith("*"),
    "/": _arith("/"), "%": _arith("%"),
    "decimal_+": _decimal_arith("+"), "decimal_-": _decimal_arith("-"),
    "decimal_*": _decimal_arith("*"), "decimal_/": _decimal_arith("/"),
    "decimal_%": _decimal_arith("%"),
    "negate": _negate, "abs": _abs, "round": _round,
    "floor": _floorceil("floor"), "ceil": _floorceil("ceil"),
    "ceiling": _floorceil("ceil"), "truncate": _truncate, "sign": _sign,
    "sqrt": _unary_np(jnp.sqrt), "cbrt": _unary_np(jnp.cbrt),
    "exp": _unary_np(jnp.exp), "ln": _unary_np(jnp.log),
    "log2": _unary_np(jnp.log2), "log10": _unary_np(jnp.log10),
    "sin": _unary_np(jnp.sin), "cos": _unary_np(jnp.cos),
    "tan": _unary_np(jnp.tan), "asin": _unary_np(jnp.arcsin),
    "acos": _unary_np(jnp.arccos), "atan": _unary_np(jnp.arctan),
    "sinh": _unary_np(jnp.sinh), "cosh": _unary_np(jnp.cosh),
    "tanh": _unary_np(jnp.tanh),
    "degrees": _unary_np(jnp.degrees), "radians": _unary_np(jnp.radians),
    "power": _power, "pow": _power, "mod": _mod,
    "greatest": _greatest_least("greatest"),
    "least": _greatest_least("least"),
    "is_nan": _float_pred(jnp.isnan),
    "st_point": _geo_call("point"), "st_x": _geo_call("x"),
    "st_y": _geo_call("y"), "st_distance": _geo_call("distance"),
    "st_geometryfromtext": _geo_call("fromtext"),
    "st_astext": _geo_call("astext"),
    "st_contains": _geo_call("contains"),
    "great_circle_distance": _geo_call("gcd"),
    "is_finite": _float_pred(jnp.isfinite),
    "is_infinite": _float_pred(jnp.isinf),
    "coalesce": _coalesce, "nullif": _nullif, "if": _if, "try": _try,
    "like": _like, "regexp_like": _regexp_like,
    "lower": _string_unary(str.lower), "upper": _string_unary(str.upper),
    "trim": _string_unary(str.strip), "ltrim": _string_unary(str.lstrip),
    "rtrim": _string_unary(str.rstrip),
    "reverse": _string_unary(lambda v: v[::-1]),
    "length": _length, "substring": _substr, "substr": _substr,
    "concat": _concat, "strpos": _strpos, "position": _strpos,
    "replace": _replace, "starts_with": _starts_with,
    "split_part": _split_part, "lpad": _pad("lpad"), "rpad": _pad("rpad"),
    "year": _extract("year"), "month": _extract("month"),
    "quarter": _extract("quarter"), "week": _extract("week"),
    "day": _extract("day"), "day_of_month": _extract("day"),
    "day_of_week": _extract("day_of_week"), "dow": _extract("day_of_week"),
    "day_of_year": _extract("day_of_year"), "doy": _extract("day_of_year"),
    "hour": _time_field("hour"), "minute": _time_field("minute"),
    "second": _time_field("second"), "millisecond":
        _time_field("millisecond"),
    "date_add_interval": _date_interval("+"),
    "date_sub_interval": _date_interval("-"),
    "ts_add_interval": _ts_interval("+"),
    "ts_sub_interval": _ts_interval("-"),
    "date_diff_days": _date_diff_days,
    "date_trunc": _date_trunc, "date_diff": _date_diff,
    "date_add": _date_add,
    "$array": _array_ctor, "cardinality": _cardinality,
    "empty_approx_set": _empty_approx_set,
    "element_at": _element_at,
    "from_unixtime": _from_unixtime, "to_unixtime": _to_unixtime,
    "date_format": _date_format, "date_parse": _date_parse,
    "json_extract_scalar": _json_fn("scalar"),
    "json_extract": _json_fn("extract"),
    "json_array_length": _json_fn("array_length"),
    "json_size": _json_fn("size"),
}

# --------------------------------------------------------------------------
# bitwise / crypto / URL / misc scalar breadth
# (operator/scalar/BitwiseFunctions.java, VarbinaryFunctions.java
#  digests, UrlFunctions.java, MathFunctions 2-arg forms)
# --------------------------------------------------------------------------

def _bitwise(op):
    def f(e, batch):
        a = eval_expr(e.args[0], batch)
        b = eval_expr(e.args[1], batch)
        x = _lane(a).astype(jnp.int64)
        y = _lane(b).astype(jnp.int64)
        if op == "and":
            d = x & y
        elif op == "or":
            d = x | y
        elif op == "xor":
            d = x ^ y
        elif op == "lshift":
            d = x << y
        else:
            d = x >> y
        return Column(BIGINT, d, _merge_valid(a, b))
    return f


def _bitwise_not(e, batch):
    a = eval_expr(e.args[0], batch)
    return Column(BIGINT, ~_lane(a).astype(jnp.int64), a.valid)


def _bit_count(e, batch):
    a = eval_expr(e.args[0], batch)
    bits = eval_expr(e.args[1], batch) if len(e.args) > 1 else None
    x = _lane(a).astype(jnp.int64).view(jnp.uint64)
    nbits = (jnp.asarray(bits.data).astype(jnp.int64)
             if bits is not None else jnp.int64(64))
    # mask to the low n bits (sign extension counts for negatives)
    mask = jnp.where(nbits >= 64, jnp.uint64(0xFFFFFFFFFFFFFFFF),
                     (jnp.uint64(1) << nbits.astype(jnp.uint64))
                     - jnp.uint64(1))
    v = x & mask
    cnt = jnp.zeros(v.shape, jnp.int64)
    for shift in range(0, 64, 8):
        byte = ((v >> jnp.uint64(shift)) &
                jnp.uint64(0xFF)).astype(jnp.int32)
        tbl = jnp.asarray([bin(i).count("1") for i in range(256)],
                          jnp.int64)
        cnt = cnt + jnp.take(tbl, byte)
    valid = a.valid
    if bits is not None:
        valid = _merge_valid(a, bits)
    return Column(BIGINT, cnt, valid)


def _xxh64_py(data: bytes, seed: int = 0) -> int:
    """Reference xxHash64 (public domain algorithm), used when the
    native serde library is absent."""
    P1, P2, P3 = (0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F,
                  0x165667B19E3779F9)
    P4, P5 = 0x85EBCA77C2B2AE63, 0x27D4EB2F165667C5
    M = 0xFFFFFFFFFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M
    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + P1 + P2) & M
        v2 = (seed + P2) & M
        v3 = seed & M
        v4 = (seed - P1) & M
        while i + 32 <= n:
            for j, vv in enumerate((v1, v2, v3, v4)):
                lane = int.from_bytes(data[i + j * 8:i + j * 8 + 8],
                                      "little")
                vv = (vv + lane * P2) & M
                vv = (rotl(vv, 31) * P1) & M
                if j == 0:
                    v1 = vv
                elif j == 1:
                    v2 = vv
                elif j == 2:
                    v3 = vv
                else:
                    v4 = vv
            i += 32
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12)
             + rotl(v4, 18)) & M
        for vv in (v1, v2, v3, v4):
            vv = (rotl((vv * P2) & M, 31) * P1) & M
            h = (((h ^ vv) * P1) + P4) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while i + 8 <= n:
        lane = int.from_bytes(data[i:i + 8], "little")
        k = (rotl((lane * P2) & M, 31) * P1) & M
        h = ((rotl(h ^ k, 27) * P1) + P4) & M
        i += 8
    if i + 4 <= n:
        lane = int.from_bytes(data[i:i + 4], "little")
        h = ((rotl(h ^ ((lane * P1) & M), 23) * P2) + P3) & M
        i += 4
    while i < n:
        h = (rotl(h ^ ((data[i] * P5) & M), 11) * P1) & M
        i += 1
    h ^= h >> 33
    h = (h * P2) & M
    h ^= h >> 29
    h = (h * P3) & M
    h ^= h >> 32
    return h


def _digest(algo):
    def f(e, batch):
        import hashlib
        a = eval_expr(e.args[0], batch)
        return _dict_transform(
            a, lambda v: hashlib.new(algo, v.encode()).hexdigest(),
            e.type)
    return f


def _crc32(e, batch):
    import zlib
    a = eval_expr(e.args[0], batch)
    return _dict_transform(a, lambda v: zlib.crc32(v.encode()), BIGINT)


def _xxhash64_fn(e, batch):
    a = eval_expr(e.args[0], batch)
    from ..serde import _load_native
    lib = _load_native()

    def h(v: str) -> int:
        raw = v.encode()
        u = (int(lib.tt_xxh64(raw, len(raw), 0)) if lib is not None
             else _xxh64_py(raw))
        return u - (1 << 64) if u >= (1 << 63) else u
    return _dict_transform(a, h, BIGINT)


def _to_hex(e, batch):
    a = eval_expr(e.args[0], batch)
    from ..types import is_string as _iss
    if _iss(a.type):
        return _dict_transform(
            a, lambda v: v.encode().hex().upper(), e.type)
    d = _lane(a).astype(jnp.int64)
    # bigint -> 16-digit hex via host transform on unique-ish lanes is
    # wasteful; do it columnar on host
    vals = np.asarray(d)
    out = [format(int(v) & ((1 << 64) - 1), "X") for v in vals]
    dct, codes = StringDictionary.from_strings(out)
    return Column(e.type, jnp.asarray(codes), a.valid, dct)


def _from_hex(e, batch):
    a = eval_expr(e.args[0], batch)
    return _dict_transform(
        a, lambda v: bytes.fromhex(v).decode("utf-8", "replace"),
        e.type)


def _url_part(which):
    def f(e, batch):
        from urllib.parse import urlsplit
        a = eval_expr(e.args[0], batch)

        def g(v: str):
            try:
                u = urlsplit(v)
            except ValueError:
                return None
            if which == "protocol":
                return u.scheme or None
            if which == "host":
                return u.hostname
            if which == "port":
                return u.port
            if which == "path":
                return u.path
            if which == "query":
                return u.query or None
            return u.fragment or None
        return _dict_transform(a, g, e.type)
    return f


def _url_extract_parameter(e, batch):
    from urllib.parse import parse_qs, urlsplit
    if not isinstance(e.args[1], Const):
        raise EvalError("url_extract_parameter: name must be constant")
    a = eval_expr(e.args[0], batch)
    name = e.args[1].value

    def g(v: str):
        try:
            qs = parse_qs(urlsplit(v).query,
                          keep_blank_values=True)
        except ValueError:
            return None
        vals = qs.get(name)
        return vals[0] if vals else None
    return _dict_transform(a, g, e.type)


def _url_codec(which):
    def f(e, batch):
        from urllib.parse import quote_plus, unquote_plus
        a = eval_expr(e.args[0], batch)
        fn = quote_plus if which == "encode" else unquote_plus
        return _dict_transform(a, fn, e.type)
    return f


def _translate(e, batch):
    if not (isinstance(e.args[1], Const) and isinstance(e.args[2],
                                                        Const)):
        raise EvalError("translate: from/to must be constants")
    a = eval_expr(e.args[0], batch)
    table = {}
    f_s, t_s = e.args[1].value, e.args[2].value
    for i, ch in enumerate(f_s):
        table[ord(ch)] = t_s[i] if i < len(t_s) else None
    return _dict_transform(a, lambda v: v.translate(table), e.type)


def _log_b(e, batch):
    a = eval_expr(e.args[0], batch)
    b = eval_expr(e.args[1], batch)
    d = jnp.log(_lane(b).astype(jnp.float64)) / \
        jnp.log(_lane(a).astype(jnp.float64))
    return Column(DOUBLE, d, _merge_valid(a, b))


def _const_double(val):
    def f(e, batch):
        return Column(DOUBLE, jnp.full((batch.capacity,), val,
                                       jnp.float64), None)
    return f


def _random_fn(e, batch):
    cap = batch.capacity
    if e.args:
        n = eval_expr(e.args[0], batch)
        bound = np.asarray(_lane(n))
        vals = np.random.randint(
            0, np.maximum(bound.astype(np.int64), 1))
        return Column(BIGINT, jnp.asarray(vals), n.valid)
    return Column(DOUBLE, jnp.asarray(np.random.uniform(size=cap)), None)


def _atan2(e, batch):
    a = eval_expr(e.args[0], batch)
    b = eval_expr(e.args[1], batch)
    d = jnp.arctan2(_lane(a).astype(jnp.float64),
                    _lane(b).astype(jnp.float64))
    return Column(DOUBLE, d, _merge_valid(a, b))


def _chr(e, batch):
    a = eval_expr(e.args[0], batch)
    vals = np.asarray(_lane(a)).astype(np.int64)
    out = [chr(int(v)) if 0 <= v < 0x110000 else "" for v in vals]
    dct, codes = StringDictionary.from_strings(out)
    return Column(e.type, jnp.asarray(codes), a.valid, dct)


def _codepoint(e, batch):
    a = eval_expr(e.args[0], batch)
    return _dict_transform(
        a, lambda v: ord(v[0]) if v else None, BIGINT)


def _concat_ws(e, batch):
    """concat_ws(sep, s1, s2, ...): NULL args are skipped; a NULL
    separator yields NULL (reference: ConcatWsFunction.java)."""
    cols = [eval_expr(a, batch) for a in e.args]
    mats = [_materialize_strings(c) for c in cols]
    out = []
    for row in zip(*mats):
        sep = row[0]
        out.append(None if sep is None
                   else sep.join(v for v in row[1:] if v is not None))
    dct, codes = StringDictionary.from_strings(out)
    valid = np.asarray([o is not None for o in out], dtype=bool)
    return Column(e.type, jnp.asarray(codes),
                  None if valid.all() else jnp.asarray(valid), dct)


def _java_format_value(spec: str, conv: str, v):
    """One %-directive of Java String.format, via Python's format
    mini-language (subset: flags - 0 ,  width, precision; conversions
    s d f e x o b)."""
    grouping = "," in spec
    spec = spec.replace(",", "")
    align = ""
    if spec.startswith("-"):
        align = "<"
        spec = spec[1:]
    py = align + spec
    if conv in ("d", "x", "o"):
        if conv == "d":
            return format(int(v), py + (",d" if grouping else "d"))
        return format(int(v), py + conv)
    if conv in ("f", "e", "g"):
        return format(float(v), py + ("," if grouping else "") + conv)
    if conv == "b":
        return "true" if v else "false"
    return format(str(v), py + "s")


def _format_fn(e, batch):
    if not isinstance(e.args[0], Const):
        raise EvalError("format: the format string must be constant")
    fmt = e.args[0].value
    import re as _re
    parts = _re.split(r"(%[-,0-9.]*[a-zA-Z]|%%)", fmt)
    cols = [eval_expr(a, batch) for a in e.args[1:]]
    from ..types import is_string as _iss
    mats = []
    for c in cols:
        if _iss(c.type):
            mats.append(_materialize_strings(c))
        else:
            d = np.asarray(c.data)
            valid = (np.ones(len(d), bool) if c.valid is None
                     else np.asarray(c.valid))
            if isinstance(c.type, DecimalType):
                hi = (None if c.data2 is None
                      else np.asarray(c.data2))
                scale = 10 ** c.type.scale

                def unscale(i):
                    v = int(d[i])
                    if hi is not None:
                        v = (int(hi[i]) << 64) | (v & ((1 << 64) - 1))
                    return v / scale
                mats.append([unscale(i) if valid[i] else None
                             for i in range(len(d))])
            else:
                mats.append([d[i].item() if valid[i] else None
                             for i in range(len(d))])
    out = []
    for row in zip(*mats) if mats else [()] * batch.capacity:
        ai = 0
        pieces = []
        bad = False
        for p in parts:
            if p == "%%":
                pieces.append("%")
            elif p.startswith("%") and len(p) > 1:
                v = row[ai] if ai < len(row) else None
                ai += 1
                if v is None:
                    bad = True
                    break
                pieces.append(_java_format_value(p[1:-1], p[-1], v))
            else:
                pieces.append(p)
        out.append(None if bad else "".join(pieces))
    dct, codes = StringDictionary.from_strings(out)
    valid = np.asarray([o is not None for o in out], dtype=bool)
    return Column(e.type, jnp.asarray(codes),
                  None if valid.all() else jnp.asarray(valid), dct)


def _levenshtein(a: str, b: str) -> int:
    if len(a) < len(b):
        a, b = b, a
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                           prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


def _str_distance(kind):
    def f(e, batch):
        a = eval_expr(e.args[0], batch)
        b = eval_expr(e.args[1], batch)
        ma, mb = _materialize_strings(a), _materialize_strings(b)
        out = np.zeros(len(ma), np.int64)
        valid = np.ones(len(ma), bool)
        for i, (x, y) in enumerate(zip(ma, mb)):
            if x is None or y is None:
                valid[i] = False
            elif kind == "hamming":
                if len(x) != len(y):
                    raise EvalError("hamming_distance: strings must "
                                    "have the same length")
                out[i] = sum(c1 != c2 for c1, c2 in zip(x, y))
            else:
                out[i] = _levenshtein(x, y)
        return Column(BIGINT, jnp.asarray(out),
                      None if valid.all() else jnp.asarray(valid))
    return f


def _regexp_pattern(e, idx=1):
    if not isinstance(e.args[idx], Const):
        raise EvalError("regexp pattern must be constant")
    import re as _re
    return _re.compile(e.args[idx].value)


def _regexp_extract(e, batch):
    a = eval_expr(e.args[0], batch)
    pat = _regexp_pattern(e)
    group = 0
    if len(e.args) > 2:
        if not isinstance(e.args[2], Const):
            raise EvalError("regexp_extract: group must be constant")
        group = int(e.args[2].value)

    def g(v: str):
        m = pat.search(v)
        return None if m is None else m.group(group)
    return _dict_transform(a, g, e.type)


def _regexp_replace(e, batch):
    import re as _re
    a = eval_expr(e.args[0], batch)
    pat = _regexp_pattern(e)
    repl = ""
    if len(e.args) > 2:
        if not isinstance(e.args[2], Const):
            raise EvalError("regexp_replace: replacement must be "
                            "constant")
        # Java replacement syntax: $1 / ${name} -> Python \1 / \g<name>
        repl = _re.sub(r"\$\{(\w+)\}", r"\\g<\1>",
                       _re.sub(r"\$(\d+)", r"\\\1", e.args[2].value))
    return _dict_transform(a, lambda v: pat.sub(repl, v), e.type)


def _typeof(e, batch):
    t = str(e.args[0].type)
    dct, codes = StringDictionary.from_strings([t] * batch.capacity)
    return Column(e.type, jnp.asarray(codes), None, dct)


def _width_bucket(e, batch):
    x = eval_expr(e.args[0], batch)
    lo = eval_expr(e.args[1], batch)
    hi = eval_expr(e.args[2], batch)
    n = eval_expr(e.args[3], batch)
    xd = _lane(x).astype(jnp.float64)
    lod = _lane(lo).astype(jnp.float64)
    hid = _lane(hi).astype(jnp.float64)
    nd = _lane(n).astype(jnp.int64)
    width = (hid - lod) / nd
    fwd = jnp.clip(jnp.floor((xd - lod) / width).astype(jnp.int64) + 1,
                   0, nd + 1)
    rev = jnp.clip(jnp.floor((lod - xd) /
                             ((lod - hid) / nd)).astype(jnp.int64) + 1,
                   0, nd + 1)
    out = jnp.where(hid >= lod, fwd, rev)
    return Column(BIGINT, out, _merge_valid(x, lo, hi, n))


def _year_of_week(e, batch):
    """ISO 8601 week-year: the calendar year of the week's Thursday."""
    a = eval_expr(e.args[0], batch)
    if a.type is DATE:
        days = _lane(a).astype(jnp.int64)
    elif isinstance(a.type, TimestampType):
        days = jnp.floor_divide(_lane(a), 86400000)
    else:
        raise EvalError("year_of_week() requires date/timestamp")
    monday_idx = jnp.mod(days + 3, 7)          # 0 = Monday
    thursday = days - monday_idx + 3
    return Column(BIGINT, extract_field(thursday, "year"), a.valid)


def _current_date(e, batch):
    import time as _time
    days = int(_time.time() // 86400)
    return Column(e.type, jnp.full((batch.capacity,), days, jnp.int64),
                  None)


def _now_fn(e, batch):
    import time as _time
    ms = int(_time.time() * 1000)
    return Column(e.type, jnp.full((batch.capacity,), ms, jnp.int64),
                  None)


def _current_time_fn(e, batch):
    import time as _time
    ms = int(_time.time() * 1000) % 86400000
    return Column(e.type, jnp.full((batch.capacity,), ms, jnp.int64),
                  None)


def _date_fn(e, batch):
    a = eval_expr(e.args[0], batch)
    return cast_column(a, e.type)


def _normalize_fn(e, batch):
    import unicodedata
    a = eval_expr(e.args[0], batch)
    form = "NFC"
    if len(e.args) > 1:
        if not isinstance(e.args[1], Const):
            raise EvalError("normalize: form must be constant")
        form = str(e.args[1].value).upper()
    if form not in ("NFC", "NFD", "NFKC", "NFKD"):
        raise EvalError(f"normalize: invalid form {form}")
    return _dict_transform(
        a, lambda v: unicodedata.normalize(form, v), e.type)


_BASE_DIGITS = "0123456789abcdefghijklmnopqrstuvwxyz"


def _to_base(e, batch):
    a = eval_expr(e.args[0], batch)
    r = eval_expr(e.args[1], batch)
    vals = np.asarray(_lane(a)).astype(np.int64)
    radix = np.asarray(_lane(r)).astype(np.int64)
    out = []
    for v, rx in zip(vals, radix):
        rx = int(rx)
        if not 2 <= rx <= 36:
            raise EvalError("to_base: radix must be in [2, 36]")
        v = int(v)
        neg, v = v < 0, abs(v)
        digits = ""
        while True:
            digits = _BASE_DIGITS[v % rx] + digits
            v //= rx
            if v == 0:
                break
        out.append(("-" if neg else "") + digits)
    dct, codes = StringDictionary.from_strings(out)
    return Column(e.type, jnp.asarray(codes), _merge_valid(a, r), dct)


def _from_base(e, batch):
    a = eval_expr(e.args[0], batch)
    r = eval_expr(e.args[1], batch)
    if not isinstance(e.args[1], Const):
        raise EvalError("from_base: radix must be constant")
    radix = int(e.args[1].value)
    if not 2 <= radix <= 36:
        raise EvalError("from_base: radix must be in [2, 36]")
    return _dict_transform(a, lambda v: int(v, radix), BIGINT)


def _zone_offsets_for(zone: str, instants: np.ndarray) -> np.ndarray:
    """Per-value offset minutes for a zone string; IANA names resolve
    per instant (DST-correct), fixed offsets are constant."""
    from ..types import zone_offset_minutes
    z = zone.strip()
    if "/" not in z:
        return np.full(instants.shape, zone_offset_minutes(z), np.int64)
    import datetime
    from zoneinfo import ZoneInfo
    zi = ZoneInfo(z)
    epoch = datetime.datetime(1970, 1, 1,
                              tzinfo=datetime.timezone.utc)
    out = np.empty(instants.shape, np.int64)
    for i, v in enumerate(instants):
        off = (epoch + datetime.timedelta(milliseconds=int(v))
               ).astimezone(zi).utcoffset()
        out[i] = int(off.total_seconds() // 60)
    return out


def _at_timezone(e, batch):
    """AT TIME ZONE: same instant, new display zone (reference:
    operator/scalar/AtTimeZone.java)."""
    from ..types import TimestampTZType, TimestampType as _TT
    a = eval_expr(e.args[0], batch)
    if not isinstance(e.args[1], Const):
        raise EvalError("AT TIME ZONE: zone must be constant")
    zone = str(e.args[1].value)
    if isinstance(a.type, _TT):
        # plain timestamp: interpret as UTC instant
        a = dc_replace(a, type=TimestampTZType(a.type.precision),
                       data2=jnp.zeros((a.capacity,), jnp.int64))
    instants = np.asarray(a.data)
    offs = _zone_offsets_for(zone, instants)
    return dc_replace(a, data2=jnp.asarray(offs))


def _with_timezone(e, batch):
    """with_timezone(timestamp, zone): the wall-clock value read in
    that zone (instant shifts)."""
    from ..types import TimestampTZType
    a = eval_expr(e.args[0], batch)
    if not isinstance(e.args[1], Const):
        raise EvalError("with_timezone: zone must be constant")
    zone = str(e.args[1].value)
    local = np.asarray(a.data)
    offs = _zone_offsets_for(zone, local)  # approx for DST edges
    instant = local - offs * 60000
    return Column(TimestampTZType(getattr(a.type, "precision", 3)),
                  jnp.asarray(instant), a.valid,
                  data2=jnp.asarray(offs))


def _to_iso8601(e, batch):
    from ..types import TimestampTZType
    a = eval_expr(e.args[0], batch)
    import datetime
    epoch = datetime.datetime(1970, 1, 1)
    vals = np.asarray(a.data)
    out = []
    if a.type is DATE:
        d0 = datetime.date(1970, 1, 1).toordinal()
        for v in vals:
            out.append(datetime.date.fromordinal(int(v) + d0)
                       .isoformat())
    elif isinstance(a.type, TimestampTZType):
        offs = (np.asarray(a.data2) if a.data2 is not None
                else np.zeros(len(vals), np.int64))
        for v, o in zip(vals, offs):
            local = epoch + datetime.timedelta(
                milliseconds=int(v) + int(o) * 60000)
            sign = "+" if o >= 0 else "-"
            out.append(local.isoformat(timespec="milliseconds")
                       + f"{sign}{abs(int(o)) // 60:02d}:"
                         f"{abs(int(o)) % 60:02d}")
    else:
        for v in vals:
            out.append((epoch + datetime.timedelta(milliseconds=int(v))
                        ).isoformat(timespec="milliseconds"))
    dct, codes = StringDictionary.from_strings(out)
    return Column(VARCHAR, jnp.asarray(codes), a.valid, dct)


_DISPATCH_EXTRA = {
    "at_timezone": _at_timezone,
    "with_timezone": _with_timezone,
    "to_iso8601": _to_iso8601,
    "pi": _const_double(float(np.pi)),
    "e": _const_double(float(np.e)),
    "nan": _const_double(float("nan")),
    "infinity": _const_double(float("inf")),
    "random": _random_fn, "rand": _random_fn,
    "atan2": _atan2,
    "chr": _chr, "codepoint": _codepoint,
    "concat_ws": _concat_ws,
    "format": _format_fn,
    "hamming_distance": _str_distance("hamming"),
    "levenshtein_distance": _str_distance("levenshtein"),
    "regexp_extract": _regexp_extract,
    "regexp_replace": _regexp_replace,
    "typeof": _typeof,
    "width_bucket": _width_bucket,
    "year_of_week": _year_of_week, "yow": _year_of_week,
    "current_date": _current_date,
    "now": _now_fn, "current_timestamp": _now_fn,
    "localtimestamp": _now_fn,
    "current_time": _current_time_fn, "localtime": _current_time_fn,
    "date": _date_fn,
    "normalize": _normalize_fn,
    "to_base": _to_base, "from_base": _from_base,
    "bitwise_and": _bitwise("and"), "bitwise_or": _bitwise("or"),
    "bitwise_xor": _bitwise("xor"),
    "bitwise_left_shift": _bitwise("lshift"),
    "bitwise_right_shift": _bitwise("rshift"),
    "bitwise_not": _bitwise_not, "bit_count": _bit_count,
    "md5": _digest("md5"), "sha1": _digest("sha1"),
    "sha256": _digest("sha256"), "sha512": _digest("sha512"),
    "crc32": _crc32, "xxhash64": _xxhash64_fn,
    "to_hex": _to_hex, "from_hex": _from_hex,
    "url_extract_protocol": _url_part("protocol"),
    "url_extract_host": _url_part("host"),
    "url_extract_port": _url_part("port"),
    "url_extract_path": _url_part("path"),
    "url_extract_query": _url_part("query"),
    "url_extract_fragment": _url_part("fragment"),
    "url_extract_parameter": _url_extract_parameter,
    "url_encode": _url_codec("encode"),
    "url_decode": _url_codec("decode"),
    "translate": _translate,
    "log": _log_b,
}
_DISPATCH.update(_DISPATCH_EXTRA)


# complex-type (ARRAY/MAP/ROW) + higher-order functions evaluate
# host-side (see exec/complex.py module docstring for why)
from . import complex as _complex  # noqa: E402

for _name, _fn in _complex.DISPATCH.items():
    _DISPATCH.setdefault(_name, _fn)


# --------------------------------------------------------------------------
# round-4 scalar breadth: HMAC, binary codecs, joda datetime, bar charts,
# porter stemmer (reference: operator/scalar/{HmacFunctions,
# VarbinaryFunctions,DateTimeFunctions,ColorFunctions,WordStemFunction}.java)
# --------------------------------------------------------------------------

def _carried_bytes(typ) -> Callable[[str], bytes]:
    """varbinary values are carried as latin-1-decoded strings
    (_num_to_binary); varchar is real text -> utf-8."""
    if getattr(typ, "name", "") == "varbinary":
        return lambda s: s.encode("latin-1")
    return lambda s: s.encode()


def _hmac(algo):
    def f(e, batch):
        import hashlib
        import hmac as _hm
        a = eval_expr(e.args[0], batch)
        k = eval_expr(e.args[1], batch)
        vb = _carried_bytes(a.type)
        kb = _carried_bytes(k.type)
        return _row_string_fn(
            [a, k],
            lambda v, key: _hm.new(kb(key), vb(v),
                                   getattr(hashlib, algo)).hexdigest(),
            e.type)
    return f


def _retype_string(e, batch):
    """json_format / color / render: identity on the carried string,
    retyped (varbinary is a dictionary column like varchar)."""
    a = eval_expr(e.args[0], batch)
    if a.dictionary is None:
        return dc_replace(a, type=e.type)
    return Column(e.type, a.data, a.valid, a.dictionary)


def _to_utf8(e, batch):
    """varchar -> varbinary holding the text's REAL utf-8 bytes in the
    latin-1-decoded carried-string convention of _num_to_binary (so
    hmac_*/md5/length over the result see the actual byte sequence,
    including for non-latin-1 text)."""
    a = eval_expr(e.args[0], batch)
    if a.dictionary is None:      # all-NULL UNKNOWN constant
        return dc_replace(a, type=e.type)
    return _dict_transform(
        a, lambda s: s.encode("utf-8").decode("latin-1"), e.type)


def _from_utf8(e, batch):
    """varbinary (latin-1-carried raw bytes) -> varchar text, invalid
    sequences replaced with U+FFFD (reference
    VarbinaryFunctions.fromUtf8 default behavior)."""
    a = eval_expr(e.args[0], batch)
    if a.dictionary is None:      # all-NULL UNKNOWN constant
        return dc_replace(a, type=e.type)
    return _dict_transform(
        a, lambda s: s.encode("latin-1", errors="replace")
                      .decode("utf-8", errors="replace"), e.type)


def _json_parse(e, batch):
    import json as _json
    a = eval_expr(e.args[0], batch)

    def canon(v: str):
        try:
            return _json.dumps(_json.loads(v), separators=(",", ":"),
                               sort_keys=False)
        except ValueError:
            raise EvalError(f"Cannot convert value to JSON: '{v}'")
    return _dict_transform(a, canon, e.type)


def _num_to_binary(pack):
    def f(e, batch):
        a = eval_expr(e.args[0], batch)
        vals = np.asarray(a.data)
        valid = None if a.valid is None else np.asarray(a.valid)
        out = []
        for i in range(vals.shape[0]):
            if valid is not None and not valid[i]:
                out.append(None)
            else:
                out.append(pack(vals[i]).decode("latin-1"))
        d, codes = StringDictionary.from_strings(out)
        v = np.asarray([o is not None for o in out], bool)
        return Column(e.type, jnp.asarray(codes),
                      None if v.all() else jnp.asarray(v), d)
    return f


def _binary_to_num(unpack):
    def f(e, batch):
        a = eval_expr(e.args[0], batch)
        return _dict_transform(
            a, lambda s: unpack(s.encode("latin-1")), e.type)
    return f


def _bar_fn(e, batch):
    """bar(x, width): unicode block bar (reference renders ANSI color
    ramps; the bar geometry matches, color is omitted)."""
    a = eval_expr(e.args[0], batch)
    w = e.args[1]
    if not isinstance(w, Const) or w.value is None:
        raise EvalError("bar: width must be a constant")
    width = int(w.value)
    vals = np.asarray(a.data).astype(np.float64)
    valid = None if a.valid is None else np.asarray(a.valid)
    out = []
    for i in range(vals.shape[0]):
        if valid is not None and not valid[i]:
            out.append(None)
            continue
        x = min(max(float(vals[i]), 0.0), 1.0)
        n = int(round(x * width))
        out.append("█" * n + " " * (width - n))
    d, codes = StringDictionary.from_strings(out)
    v = np.asarray([o is not None for o in out], bool)
    return Column(e.type, jnp.asarray(codes),
                  None if v.all() else jnp.asarray(v), d)


_JODA_TOKENS = [
    ("yyyy", "%Y"), ("yyy", "%Y"), ("yy", "%y"), ("y", "%Y"),
    ("MMMM", "%B"), ("MMM", "%b"), ("MM", "%m"), ("M", "%m"),
    ("dd", "%d"), ("d", "%d"), ("EEEE", "%A"), ("EEE", "%a"),
    ("HH", "%H"), ("H", "%H"), ("hh", "%I"), ("h", "%I"),
    ("mm", "%M"), ("m", "%M"), ("ss", "%S"), ("s", "%S"),
    ("SSS", "%f"), ("a", "%p"), ("ZZ", "%z"), ("Z", "%z"),
]


def _joda_to_strptime(fmt: str) -> str:
    out = []
    i = 0
    while i < len(fmt):
        if fmt[i] == "'":
            j = fmt.find("'", i + 1)
            if j < 0:
                out.append(fmt[i + 1:])
                break
            out.append(fmt[i + 1:j].replace("%", "%%"))
            i = j + 1
            continue
        for tok, rep in _JODA_TOKENS:
            if fmt.startswith(tok, i):
                out.append(rep)
                i += len(tok)
                break
        else:
            out.append(fmt[i].replace("%", "%%"))
            i += 1
    return "".join(out)


def _parse_datetime(e, batch):
    import datetime as _dt
    a = eval_expr(e.args[0], batch)
    fe = e.args[1]
    if not isinstance(fe, Const) or fe.value is None:
        raise EvalError("parse_datetime: format must be a constant")
    fmt = _joda_to_strptime(str(fe.value))
    codes = np.asarray(a.data)
    valid = None if a.valid is None else np.asarray(a.valid)
    vals = a.dictionary.values if a.dictionary is not None else None
    data = np.zeros(codes.shape[0], np.int64)
    data2 = np.zeros(codes.shape[0], np.int64)
    ok = np.ones(codes.shape[0], bool)
    for i in range(codes.shape[0]):
        if valid is not None and not valid[i]:
            ok[i] = False
            continue
        s = str(vals[int(codes[i])]) if vals is not None else str(codes[i])
        # %f expects microseconds; joda SSS is millis — normalize
        try:
            t = _dt.datetime.strptime(s, fmt)
        except ValueError as ex:
            raise EvalError(f"parse_datetime: {ex}")
        off = t.utcoffset()
        offm = 0 if off is None else int(off.total_seconds() // 60)
        naive = t.replace(tzinfo=None)
        ms = int((naive - _dt.datetime(1970, 1, 1)).total_seconds()
                 * 1000)
        data[i] = ms - offm * 60000
        data2[i] = offm
    return Column(e.type, jnp.asarray(data),
                  None if ok.all() else jnp.asarray(ok), None,
                  jnp.asarray(data2))


def _format_datetime(e, batch):
    import datetime as _dt
    a = eval_expr(e.args[0], batch)
    fe = e.args[1]
    if not isinstance(fe, Const) or fe.value is None:
        raise EvalError("format_datetime: format must be a constant")
    fmt = _joda_to_strptime(str(fe.value))
    vals = np.asarray(a.data)
    offs = (np.asarray(a.data2) if a.data2 is not None
            else np.zeros(vals.shape[0], np.int64))
    valid = None if a.valid is None else np.asarray(a.valid)
    epoch = _dt.datetime(1970, 1, 1)
    from ..types import DATE as _DATE
    out = []
    for i in range(vals.shape[0]):
        if valid is not None and not valid[i]:
            out.append(None)
            continue
        if a.type is _DATE:
            t = _dt.datetime.fromordinal(
                int(vals[i]) + _dt.date(1970, 1, 1).toordinal())
        else:
            t = epoch + _dt.timedelta(
                milliseconds=int(vals[i]) + int(offs[i]) * 60000)
        # strftime %f prints micros; joda SSS is millis — substitute
        # into the FORMAT (digits only, cannot collide with other
        # directives) rather than find/replace on the formatted string
        row_fmt = fmt.replace("%f", f"{t.microsecond // 1000:03d}")
        out.append(t.strftime(row_fmt))
    d, codes = StringDictionary.from_strings(out)
    v = np.asarray([o is not None for o in out], bool)
    return Column(e.type, jnp.asarray(codes),
                  None if v.all() else jnp.asarray(v), d)


def _from_iso8601_date(e, batch):
    import datetime as _dt
    a = eval_expr(e.args[0], batch)
    d0 = _dt.date(1970, 1, 1).toordinal()
    return _dict_transform(
        a, lambda s: _dt.date.fromisoformat(s[:10]).toordinal() - d0,
        e.type)


def _from_iso8601_timestamp(e, batch):
    import datetime as _dt
    a = eval_expr(e.args[0], batch)
    codes = np.asarray(a.data)
    valid = None if a.valid is None else np.asarray(a.valid)
    vals = a.dictionary.values if a.dictionary is not None else None
    data = np.zeros(codes.shape[0], np.int64)
    data2 = np.zeros(codes.shape[0], np.int64)
    ok = np.ones(codes.shape[0], bool)
    for i in range(codes.shape[0]):
        if valid is not None and not valid[i]:
            ok[i] = False
            continue
        s = str(vals[int(codes[i])]) if vals is not None else str(codes[i])
        t = _dt.datetime.fromisoformat(s.replace("Z", "+00:00"))
        off = t.utcoffset()
        offm = 0 if off is None else int(off.total_seconds() // 60)
        naive = t.replace(tzinfo=None)
        data[i] = int((naive - _dt.datetime(1970, 1, 1)).total_seconds()
                      * 1000) - offm * 60000
        data2[i] = offm
    return Column(e.type, jnp.asarray(data),
                  None if ok.all() else jnp.asarray(ok), None,
                  jnp.asarray(data2))


def _last_day_of_month(e, batch):
    import calendar
    import datetime as _dt
    a = eval_expr(e.args[0], batch)
    vals = np.asarray(a.data)
    valid = None if a.valid is None else np.asarray(a.valid)
    d0 = _dt.date(1970, 1, 1).toordinal()
    from ..types import DATE as _DATE
    out = np.zeros(vals.shape[0], np.int64)
    for i in range(vals.shape[0]):
        if valid is not None and not valid[i]:
            continue
        if a.type is _DATE:
            d = _dt.date.fromordinal(int(vals[i]) + d0)
        else:
            d = (_dt.datetime(1970, 1, 1)
                 + _dt.timedelta(milliseconds=int(vals[i]))).date()
        last = calendar.monthrange(d.year, d.month)[1]
        out[i] = _dt.date(d.year, d.month, last).toordinal() - d0
    return Column(e.type, jnp.asarray(out), a.valid)


def _timezone_part(which):
    def f(e, batch):
        a = eval_expr(e.args[0], batch)
        offs = (jnp.asarray(a.data2) if a.data2 is not None
                else jnp.zeros(np.asarray(a.data).shape[0], jnp.int64))
        if which == "hour":
            data = jnp.sign(offs) * (jnp.abs(offs) // 60)
        else:
            data = jnp.sign(offs) * (jnp.abs(offs) % 60)
        return Column(BIGINT, data.astype(jnp.int64), a.valid)
    return f


_PORTER_V = "aeiou"


def _porter_stem(w: str) -> str:
    """Compact Porter stemmer (step 1 + common suffixes) — covers the
    usual analytics cases (plurals, -ing/-ed, -ation)."""
    if len(w) <= 2:
        return w
    w = w.lower()

    def meas(s):
        m, prev_v = 0, False
        for ch in s:
            v = ch in _PORTER_V
            if prev_v and not v:
                m += 1
            prev_v = v
        return m

    def has_vowel(s):
        return any(c in _PORTER_V for c in s)

    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]
    # step 1b
    if w.endswith("eed"):
        if meas(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed") and has_vowel(w[:-2]):
        w = w[:-2]
        w = _porter_fixup(w)
    elif w.endswith("ing") and has_vowel(w[:-3]):
        w = w[:-3]
        w = _porter_fixup(w)
    # step 1c
    if w.endswith("y") and has_vowel(w[:-1]):
        w = w[:-1] + "i"
    for suf, rep in (("ational", "ate"), ("tional", "tion"),
                     ("ization", "ize"), ("fulness", "ful"),
                     ("ousness", "ous"), ("iveness", "ive"),
                     ("biliti", "ble"), ("entli", "ent"),
                     ("ousli", "ous"), ("alli", "al"), ("eli", "e")):
        if w.endswith(suf) and meas(w[:-len(suf)]) > 0:
            w = w[:-len(suf)] + rep
            break
    return w


def _porter_fixup(w: str) -> str:
    if w.endswith(("at", "bl", "iz")):
        return w + "e"
    if (len(w) >= 2 and w[-1] == w[-2]
            and w[-1] not in "lsz" and w[-1] not in _PORTER_V):
        return w[:-1]
    return w


def _word_stem(e, batch):
    a = eval_expr(e.args[0], batch)
    return _dict_transform(a, _porter_stem, e.type)


def _unpack_be(nbytes, signed=True):
    def f(b: bytes):
        b = b[:nbytes].rjust(nbytes, b"\x00")
        return int.from_bytes(b, "big", signed=signed)
    return f


def _unpack_ieee(fmt):
    import struct

    def f(b: bytes):
        return struct.unpack(fmt, b[:8 if fmt == ">d" else 4])[0]
    return f


def _pack_fns():
    import struct
    return {
        "to_big_endian_64": lambda v: struct.pack(">q", int(v)),
        "to_big_endian_32": lambda v: struct.pack(">i", int(v)),
        "to_ieee754_64": lambda v: struct.pack(">d", float(v)),
        "to_ieee754_32": lambda v: struct.pack(">f", float(v)),
    }


_DISPATCH_R4 = {
    "hmac_md5": _hmac("md5"), "hmac_sha1": _hmac("sha1"),
    "hmac_sha256": _hmac("sha256"), "hmac_sha512": _hmac("sha512"),
    "to_utf8": _to_utf8, "from_utf8": _from_utf8,
    "json_format": _retype_string, "json_parse": _json_parse,
    "bar": _bar_fn,
    "color": _retype_string, "render": _retype_string,
    "parse_datetime": _parse_datetime,
    "format_datetime": _format_datetime,
    "from_iso8601_date": _from_iso8601_date,
    "from_iso8601_timestamp": _from_iso8601_timestamp,
    "last_day_of_month": _last_day_of_month,
    "timezone_hour": _timezone_part("hour"),
    "timezone_minute": _timezone_part("minute"),
    "word_stem": _word_stem,
    "from_big_endian_64": _binary_to_num(_unpack_be(8)),
    "from_big_endian_32": _binary_to_num(_unpack_be(4)),
    "from_ieee754_64": _binary_to_num(_unpack_ieee(">d")),
    "from_ieee754_32": _binary_to_num(_unpack_ieee(">f")),
}
for _n, _f in _pack_fns().items():
    _DISPATCH_R4[_n] = _num_to_binary(_f)
_DISPATCH.update(_DISPATCH_R4)


# --- quantile sketch accessors (TDigestFunctions/QuantileDigestFunctions) --

def _digest_lanes(col: Column):
    starts = np.asarray(col.data).astype(np.int64)
    lens = (np.zeros_like(starts) if col.data2 is None
            else np.asarray(col.data2).astype(np.int64))
    means = np.asarray(col.elements.data).astype(np.float64)
    weights = np.asarray(col.elements2.data).astype(np.float64)
    return starts, lens, means, weights


def _digest_result(col: Column, vals: np.ndarray, ok: np.ndarray,
                   out_type):
    from ..types import QDigestType, is_integral
    vt = (col.type.value_type
          if isinstance(col.type, QDigestType) else None)
    if vt is not None and is_integral(vt):
        data = np.round(vals).astype(np.int64)
        return Column(out_type, jnp.asarray(data),
                      None if ok.all() else jnp.asarray(ok))
    return Column(out_type, jnp.asarray(vals),
                  None if ok.all() else jnp.asarray(ok))


def _value_at_quantile(e, batch):
    from ..ops.digest import digest_quantile
    col = eval_expr(e.args[0], batch)
    qc = eval_expr(e.args[1], batch)
    starts, lens, means, weights = _digest_lanes(col)
    qs = np.asarray(qc.data).astype(np.float64)
    n = starts.shape[0]
    out = np.zeros(n, np.float64)
    ok = np.ones(n, bool)
    cvalid = None if col.valid is None else np.asarray(col.valid)
    for i in range(n):
        if (cvalid is not None and not cvalid[i]) or lens[i] == 0:
            ok[i] = False
            continue
        s, ln = starts[i], lens[i]
        out[i] = digest_quantile(means[s:s + ln], weights[s:s + ln],
                                 float(qs[i % qs.shape[0]]))
    return _digest_result(col, out, ok, e.type)


def _values_at_quantiles(e, batch):
    from ..ops.digest import digest_quantile
    from ..types import ArrayType
    col = eval_expr(e.args[0], batch)
    qarr = eval_expr(e.args[1], batch)
    starts, lens, means, weights = _digest_lanes(col)
    qoffs = np.asarray(qarr.data).astype(np.int64)
    qlens = np.asarray(qarr.data2).astype(np.int64)
    qvals = np.asarray(qarr.elements.data).astype(np.float64)
    n = starts.shape[0]
    cvalid = None if col.valid is None else np.asarray(col.valid)
    flat = []
    out_offs = np.zeros(n, np.int64)
    out_lens = np.zeros(n, np.int64)
    ok = np.ones(n, bool)
    for i in range(n):
        out_offs[i] = len(flat)
        if (cvalid is not None and not cvalid[i]) or lens[i] == 0:
            ok[i] = False
            continue
        s, ln = starts[i], lens[i]
        for j in range(int(qoffs[i]), int(qoffs[i] + qlens[i])):
            flat.append(digest_quantile(means[s:s + ln],
                                        weights[s:s + ln],
                                        float(qvals[j])))
        out_lens[i] = len(flat) - out_offs[i]
    cap = max(len(flat), 1)
    fd = np.zeros(cap, np.float64)
    fd[:len(flat)] = flat
    elem_t = e.type.element
    inner = _digest_result(col, fd, np.ones(cap, bool), elem_t)
    return Column(e.type, jnp.asarray(out_offs),
                  None if ok.all() else jnp.asarray(ok), None,
                  jnp.asarray(out_lens), inner)


def _quantile_at_value(e, batch):
    from ..ops.digest import digest_quantile_at_value
    col = eval_expr(e.args[0], batch)
    vc = eval_expr(e.args[1], batch)
    starts, lens, means, weights = _digest_lanes(col)
    vs = np.asarray(vc.data).astype(np.float64)
    n = starts.shape[0]
    out = np.zeros(n, np.float64)
    ok = np.ones(n, bool)
    cvalid = None if col.valid is None else np.asarray(col.valid)
    for i in range(n):
        if (cvalid is not None and not cvalid[i]) or lens[i] == 0:
            ok[i] = False
            continue
        s, ln = starts[i], lens[i]
        out[i] = digest_quantile_at_value(
            means[s:s + ln], weights[s:s + ln],
            float(vs[i % vs.shape[0]]))
    return Column(DOUBLE, jnp.asarray(out),
                  None if ok.all() else jnp.asarray(ok))


_DISPATCH.update({
    "value_at_quantile": _value_at_quantile,
    "values_at_quantiles": _values_at_quantiles,
    "quantile_at_value": _quantile_at_value,
})
