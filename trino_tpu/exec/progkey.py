"""Canonical program keys: one shared canonicalizer for every
compiled-program cache.

Reference parity: the reference keys its generated-bytecode caches on
RowExpression trees (sql/gen/ExpressionCompiler.java:56) — two queries
whose expressions are structurally equal share one compiled class no
matter what the analyzer named their symbols. Here the compiled unit
is an XLA program and the cache has THREE layers that must agree on
identity:

1. the in-process structural caches (``exec/executor.py``
   ``_CHAIN_JIT_CACHE`` / ``_STREAM_JIT_CACHE``),
2. jax's own per-callable trace cache (keyed on the pytree treedef —
   which includes Batch COLUMN NAMES and their order, columnar.py
   ``_batch_flatten``),
3. jax's persistent compilation cache on disk (config.py), keyed on
   the serialized HLO.

Plain structural fingerprints (the old ``_node_fingerprint`` keys)
miss on all three layers whenever the planner renames a symbol
(``l_quantity$3`` vs ``l_quantity$7`` for the same scan) or emits the
same projection with a different column order — identical programs,
full re-trace, full XLA recompile. This module fixes identity at the
root: a traceable node chain is REWRITTEN over canonical symbol names
(``c0, c1, ...`` in execution-order first use), producing

- a canonical **key** (the fingerprint of the canonicalized nodes) for
  the in-process caches and the hot-shape registry,
- canonical **nodes** the cached closure actually executes, so the
  traced jaxpr/HLO — and with it layers 2 and 3 — is byte-identical
  across renamed plans (the persistent cache is thereby effectively
  keyed on the canonical program too), and
- a per-plan **binding** that renames input batch columns to canonical
  names before the call and the output back after it.

Capacity buckets are deliberately ABSENT from the key: jax
specializes per input shape under one callable, and the power-of-two
bucketing of config.capacity_for already collapses minor cardinality
changes onto the same shapes. Constant literals are canonicalized to
their typed planner values (``DATE '1998-09-02'`` and its int form
key identically) but never erased — a constant is baked into the
compiled program, so erasing it would alias genuinely different
programs.
"""

from __future__ import annotations

from dataclasses import replace as dc_replace
from typing import Dict, List, Optional, Sequence, Tuple

from ..columnar import Batch
from ..plan.nodes import (Aggregate, AggregationNode, AssignUniqueIdNode,
                          FilterNode, LimitNode, MarkDistinctNode,
                          OffsetNode, PlanNode, ProjectNode,
                          RemoteSourceNode, SampleNode, SortKey,
                          SortNode, TopNNode, WindowFunction, WindowNode)
from ..rex import (VOLATILE_FNS, Call, CaseExpr, Cast, Const, InputRef,
                   Lambda, RowExpr)


class _NotCanonical(Exception):
    """Node/expression outside the canonicalizable subset (volatile
    calls, unknown node kinds): callers fall back to identity keys."""


class _SymbolMap:
    """Deterministic symbol renaming: first use (in execution order)
    wins ``c<i>``. The map is a bijection — two distinct source
    symbols can never alias one canonical name."""

    __slots__ = ("names",)

    def __init__(self) -> None:
        self.names: Dict[str, str] = {}

    def sym(self, name: str) -> str:
        got = self.names.get(name)
        if got is None:
            got = f"c{len(self.names)}"
            self.names[name] = got
        return got


def _canon_expr(e: RowExpr, m: _SymbolMap) -> RowExpr:
    if isinstance(e, InputRef):
        return InputRef(m.sym(e.name), e.type)
    if isinstance(e, Const):
        return e
    if isinstance(e, Call):
        if e.fn in VOLATILE_FNS:
            raise _NotCanonical(e.fn)
        return Call(e.fn, tuple(_canon_expr(a, m) for a in e.args),
                    e.type)
    if isinstance(e, Cast):
        return Cast(_canon_expr(e.arg, m), e.type, e.safe)
    if isinstance(e, CaseExpr):
        return CaseExpr(tuple((_canon_expr(c, m), _canon_expr(v, m))
                              for c, v in e.whens),
                        None if e.default is None
                        else _canon_expr(e.default, m), e.type)
    if isinstance(e, Lambda):
        # lambda params are fresh symbols referenced via InputRef in
        # the body — they rename through the same map
        return Lambda(tuple(m.sym(p) for p in e.params),
                      _canon_expr(e.body, m), e.type)
    raise _NotCanonical(type(e).__name__)


def _canon_aggregate(a: Aggregate, m: _SymbolMap) -> Aggregate:
    return Aggregate(
        a.kind,
        None if a.argument is None else m.sym(a.argument),
        a.type, a.distinct,
        None if a.mask is None else m.sym(a.mask),
        None if a.argument2 is None else m.sym(a.argument2),
        a.param)


def _canon_node(nd: PlanNode, m: _SymbolMap) -> PlanNode:
    """Rebuild one chain node over canonical symbols (source link left
    untouched — chain execution dispatches per node, never through
    ``.source``)."""
    if isinstance(nd, FilterNode):
        return dc_replace(nd, predicate=_canon_expr(nd.predicate, m))
    if isinstance(nd, ProjectNode):
        # input symbols rename before output symbols: every InputRef of
        # every assignment maps first, THEN the assignment targets —
        # keeps pass-through projections (x -> x) idempotent
        exprs = {s: _canon_expr(e, m) for s, e in nd.assignments.items()}
        return dc_replace(nd, assignments={m.sym(s): e
                                           for s, e in exprs.items()})
    if isinstance(nd, (SampleNode, LimitNode, OffsetNode)):
        return nd
    if isinstance(nd, SortNode):
        return dc_replace(nd, keys=tuple(
            SortKey(m.sym(k.symbol), k.ascending, k.nulls_first)
            for k in nd.keys))
    if isinstance(nd, TopNNode):
        return dc_replace(nd, keys=tuple(
            SortKey(m.sym(k.symbol), k.ascending, k.nulls_first)
            for k in nd.keys))
    if isinstance(nd, AssignUniqueIdNode):
        return dc_replace(nd, symbol=m.sym(nd.symbol))
    if isinstance(nd, MarkDistinctNode):
        return dc_replace(nd, keys=tuple(m.sym(k) for k in nd.keys),
                          marker=m.sym(nd.marker))
    if isinstance(nd, AggregationNode):
        if nd.group_id_symbol is not None:
            raise _NotCanonical("grouping-set aggregation")
        return dc_replace(
            nd,
            group_keys=tuple(m.sym(k) for k in nd.group_keys),
            aggregates={m.sym(out): _canon_aggregate(a, m)
                        for out, a in nd.aggregates.items()})
    if isinstance(nd, WindowNode):
        # inputs before outputs (same discipline as ProjectNode):
        # partition/order keys and per-function argument symbols map
        # first, then the function output symbols
        part = tuple(m.sym(s) for s in nd.partition_by)
        order = tuple(SortKey(m.sym(k.symbol), k.ascending,
                              k.nulls_first) for k in nd.order_by)
        fns = {out: _canon_window_fn(f, m)
               for out, f in nd.functions.items()}
        return dc_replace(nd, partition_by=part, order_by=order,
                          functions={m.sym(out): f
                                     for out, f in fns.items()})
    raise _NotCanonical(type(nd).__name__)


def _canon_window_fn(f: WindowFunction, m: _SymbolMap) -> WindowFunction:
    return dc_replace(
        f,
        argument=None if f.argument is None else m.sym(f.argument),
        offset=None if f.offset is None else m.sym(f.offset),
        default=None if f.default is None else m.sym(f.default))


def node_fingerprint(nd: PlanNode) -> Optional[tuple]:
    """Serialize every field a jitted evaluation of this node depends
    on (row expressions are frozen dataclasses — repr() is total).
    Returns None for node types outside the whitelist or volatile
    expressions; callers fall back to per-query identity keys. A
    collision between genuinely different plans would reuse the wrong
    program, so any new field on these nodes MUST be added here."""
    from ..rex import expr_volatile
    if isinstance(nd, FilterNode):
        if expr_volatile(nd.predicate):
            return None
        return ("F", repr(nd.predicate))
    if isinstance(nd, ProjectNode):
        if any(expr_volatile(e) for e in nd.assignments.values()):
            return None
        return ("P", tuple((s, repr(e))
                           for s, e in nd.assignments.items()))
    if isinstance(nd, SampleNode):
        return ("S", nd.method, nd.ratio)
    if isinstance(nd, LimitNode):
        return ("L", nd.count, nd.partial)
    if isinstance(nd, OffsetNode):
        return ("O", nd.count)
    if isinstance(nd, SortNode):
        return ("So", nd.keys)
    if isinstance(nd, TopNNode):
        return ("T", nd.count, nd.keys, nd.step)
    if isinstance(nd, AssignUniqueIdNode):
        return ("U", nd.symbol)
    if isinstance(nd, MarkDistinctNode):
        return ("M", nd.marker, nd.keys)
    if isinstance(nd, AggregationNode):
        return ("A", tuple(nd.group_keys), nd.step, nd.group_id_symbol,
                tuple((out, a.kind, a.argument, a.argument2, a.mask,
                       a.distinct, a.param, repr(a.type))
                      for out, a in nd.aggregates.items()))
    if isinstance(nd, WindowNode):
        return ("W", tuple(nd.partition_by), nd.order_by,
                tuple((out, f.kind, f.argument, repr(f.type),
                       f.frame_unit, f.frame_start, f.frame_end,
                       f.offset, f.default, f.frame_start_value,
                       f.frame_end_value)
                      for out, f in nd.functions.items()))
    return None


class Binding:
    """Per-plan rename shim around one canonical program: actual input
    columns -> canonical names before the call, canonical output names
    -> this plan's names after it. Columns the chain never references
    (pass-through lanes under a filter) extend the map in sorted
    original-name order — deterministic for a given input schema, so
    every split of one scan binds identically."""

    __slots__ = ("fwd", "inv")

    def __init__(self, mapping: Dict[str, str],
                 columns: Sequence[str]) -> None:
        self.fwd = dict(mapping)
        for name in sorted(c for c in columns if c not in self.fwd):
            self.fwd[name] = f"x{len(self.fwd)}"
        self.inv = {v: k for k, v in self.fwd.items()}

    def rename_in(self, b: Batch) -> Batch:
        cols = sorted(b.columns, key=lambda c: self.fwd[c])
        return Batch({self.fwd[c]: b.columns[c] for c in cols},
                     b.num_rows)

    def rename_out(self, b: Batch) -> Batch:
        return Batch({self.inv.get(s, s): c
                      for s, c in b.columns.items()}, b.num_rows)


class CanonicalProgram:
    """A canonicalized traceable node stack (top-down order) + its
    cache key and the plan's symbol map."""

    __slots__ = ("key", "nodes", "mapping")

    def __init__(self, key: tuple, nodes: List[PlanNode],
                 mapping: Dict[str, str]) -> None:
        self.key = key
        self.nodes = nodes          # top-down, like the executor chain
        self.mapping = mapping      # original symbol -> canonical

    def binding(self, b: Batch) -> Binding:
        return Binding(self.mapping, list(b.columns))

    def wire_fragment(self, input_schema: Dict[str, object]) -> dict:
        """Serialize the canonical stack as a plan fragment rooted in
        its top node over a schema-carrying RemoteSourceNode leaf —
        the hot-shape registry's transport form (plan/serde.py), which
        a pre-warming worker decodes back into the exact closure the
        executor would build (exec/aot.py)."""
        from ..plan.serde import to_jsonable
        body: PlanNode = RemoteSourceNode((), dict(input_schema),
                                          "gather")
        for nd in reversed(self.nodes):
            body = dc_replace(nd, source=body)
        return to_jsonable(body)


# ---- ragged multi-query batching (exec/taskexec.py RaggedBatcher +
# exec/executor.py _try_ragged_chain) ---------------------------------

# per-row provenance lane of a ragged batch: which co-batched query
# (by part index) owns the row. Prefixed so it can never collide with
# a canonical (c<i>) or extension (x<i>) symbol.
RAGGED_LANE = "__rq"


def ragged_nodes(nodes_top_down: Sequence[PlanNode]) -> List[PlanNode]:
    """Thread the provenance lane through a canonical chain: the lane
    column rides every FilterNode for free (filter_batch gathers ALL
    columns), but a ProjectNode drops unreferenced columns — so each
    one re-emits the lane as a pass-through assignment. Callers gate
    batchability to Filter/Project chains (Limit/Sort/TopN/Sample have
    per-query cross-row semantics that break under concatenation)."""
    from ..types import BIGINT
    out: List[PlanNode] = []
    for nd in nodes_top_down:
        if isinstance(nd, ProjectNode):
            out.append(dc_replace(nd, assignments={
                **nd.assignments,
                RAGGED_LANE: InputRef(RAGGED_LANE, BIGINT)}))
        else:
            out.append(nd)
    return out


def peel_wire_fragment(root: PlanNode) -> Tuple[List[PlanNode], Dict]:
    """Inverse of ``wire_fragment``: (top-down node stack, input
    schema) from a decoded fragment."""
    nodes: List[PlanNode] = []
    nd = root
    while not isinstance(nd, RemoteSourceNode):
        nodes.append(nd)
        nd = nd.source
    return nodes, dict(nd.schema)


def canonicalize_nodes(nodes_top_down: Sequence[PlanNode]
                       ) -> Optional[CanonicalProgram]:
    """Canonicalize a traceable node stack (top-down, the executor's
    chain order — for the streaming-aggregation program the
    AggregationNode leads). Returns None when any node or expression
    falls outside the canonical subset; callers keep per-query
    identity keys for those."""
    m = _SymbolMap()
    canon: List[PlanNode] = []
    try:
        # execution order (bottom-up): input symbols take the low
        # canonical indices, so the data-flow reading of c0.. matches
        # what the program consumes first
        for nd in reversed(list(nodes_top_down)):
            canon.append(_canon_node(nd, m))
    except _NotCanonical:
        return None
    canon.reverse()
    fps = tuple(node_fingerprint(n) for n in canon)
    if any(f is None for f in fps):
        return None
    return CanonicalProgram(fps, canon, dict(m.names))
