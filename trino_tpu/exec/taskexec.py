"""Worker-side multi-query task scheduler: the shared split runner pool.

Reference parity: execution/executor/TaskExecutor.java — a fixed pool
of runner threads time-slices ALL concurrent queries' drivers on 1s
quanta through a MultilevelSplitQueue (TaskExecutor.java:79,172-217,
456-484; PrioritizedSplitRunner.java:35), so a worker serving many
queries interleaves them instead of letting the first arrival own the
node. The tensor-runtime execution model (arXiv 2203.01877) maps the
quantum onto chunk-granularity yield points, which this engine already
has: every split read (Executor._read_split) and every streamed chunk
(exec/streamjoin.py run_streamed) is a natural boundary.

Redesigned cooperative: each task keeps its own thread (the worker's
existing model), but only ``runners`` of them EXECUTE at any moment —
the rest wait at split/chunk boundaries for a slot grant. A quantum is
therefore "the work between two checkpoints" (one split or one chunk),
and preemption is a priority comparison at each boundary:

- **multilevel feedback**: priority is keyed on the QUERY's accumulated
  scheduled seconds on this worker. ``LEVEL_THRESHOLDS_S`` bucket
  queries into levels (the reference's 0s/1s/10s/60s/300s ladder);
  a long-running query decays to higher levels and any younger query's
  splits preempt it at the next boundary — short queries finish fast.
- **fair share by resource group**: within a level, groups drain by
  weighted virtual time (stride scheduling: each accounted second
  advances the group's virtual clock by ``elapsed / weight``, and the
  group with the SMALLEST virtual time runs next), so a group with
  scheduling_weight=3 drains ~3x the split quanta of a weight-1 group
  under contention REGARDLESS of how many queries each group runs —
  share follows weight, not query count (the WeightedFairQueue
  analog, applied at the worker instead of only at admission). A
  group re-activating after idling has its virtual clock clamped up
  to the busiest-waiting floor, so banked idle time cannot starve
  everyone else. Within a group, the query with the least scheduled
  time runs first.
- **blocked tasks release their slot**: a pipelined consumer waiting on
  an upstream exchange commit holds no runner slot (``blocked()``), so
  bounded runners can never deadlock a producer behind its consumer.

Thread model: task threads + HTTP status threads touch the shared
queue; ONE lock guards every mutation, and each handle carries its own
grant event so a wakeup never requires broadcast. Grant decisions
happen under the lock; waiting happens outside it.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_right
from typing import Dict, List, Optional, Tuple

from ..obs.metrics import (EXCHANGE_WAIT_SECONDS, TASK_QUANTUM_SECONDS,
                           TASK_SCHED_LEVEL_SECONDS, TASK_SCHED_QUANTA,
                           TASK_SCHED_QUEUE_DEPTH, TASK_SCHED_RUNNABLE,
                           TASK_SCHED_YIELDS)

# per-query scheduled-seconds thresholds for the feedback levels
# (reference: MultilevelSplitQueue.LEVEL_THRESHOLD_SECONDS)
LEVEL_THRESHOLDS_S = (1.0, 10.0, 60.0, 300.0)


class TaskCanceledError(Exception):
    """Raised out of a slot wait when the task's cancel event fires —
    the task thread unwinds like any cooperative cancellation instead
    of waiting forever for a grant it can no longer use."""


class TaskHandle:
    """One task's scheduling state. The owning task thread calls
    ``acquire()`` once before executing, ``checkpoint()`` at every
    split/chunk boundary, ``blocked()`` around off-CPU waits, and
    ``close()`` (or the context-manager exit) when done."""

    __slots__ = ("ex", "query_id", "task_id", "group", "weight",
                 "cancel", "seq", "state", "_grant_ev", "_since",
                 "quanta", "cpu_s", "_cpu_since")

    def __init__(self, ex: "TaskExecutor", query_id: str, task_id: str,
                 group: str, weight: float, cancel, seq: int):
        self.ex = ex
        self.query_id = query_id
        self.task_id = task_id
        self.group = group
        self.weight = max(float(weight), 1e-9)
        self.cancel = cancel
        self.seq = seq
        self.state = "new"          # new|waiting|running|blocked|closed
        self._grant_ev = threading.Event()
        self._since: float = 0.0    # clock() at the last grant/account
        self.quanta = 0
        # scheduler CPU attribution: per-thread CPU seconds
        # (time.thread_time) accumulated quantum by quantum — every
        # stamp happens ON the task's own thread (checkpoint / blocked
        # / close run there; grants re-stamp in _wait_grant after the
        # waiting thread wakes), so the delta is exactly this task's
        # thread CPU between checkpoints, per (query, task, split)
        self.cpu_s = 0.0
        self._cpu_since: float = time.thread_time()

    # -- the lifecycle entry points -----------------------------------
    def __enter__(self) -> "TaskHandle":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def acquire(self) -> None:
        """Block until this task is granted a runner slot."""
        ex = self.ex
        with ex._lock:
            if self.state == "closed":
                raise TaskCanceledError(
                    f"task {self.task_id} already closed")
            self._grant_ev.clear()
            self.state = "waiting"
            ex._waiting.append(self)
            ex._dispatch_locked()
        self._wait_grant()

    def checkpoint(self) -> None:
        """A split/chunk finished: account the quantum and, if any
        waiter now outranks this task, hand over the slot and wait for
        the next grant. O(waiters) under one lock — called per split/
        chunk, never per row."""
        ex = self.ex
        yielded = False
        with ex._lock:
            if self.state != "running":
                return              # blocked/closed callers are no-ops
            self._account_locked()
            best = ex._best_waiter_locked()
            if best is not None \
                    and ex._key_locked(best) < ex._key_locked(self):
                # the waiter outranks us: yield the slot (it may also
                # outrank every OTHER waiter, which _dispatch settles)
                self._grant_ev.clear()
                self.state = "waiting"
                ex._running.discard(self)
                ex._waiting.append(self)
                ex._dispatch_locked()
                yielded = True
        if yielded:
            TASK_SCHED_YIELDS.inc()
            self._wait_grant()

    def blocked(self) -> "_BlockedScope":
        """Context manager for off-CPU waits (exchange pulls): the
        slot is released on entry and re-acquired on exit, so bounded
        runners cannot deadlock a producer behind its blocked
        consumer."""
        return _BlockedScope(self)

    def run_blocked(self, fn, *args, **kwargs):
        """Run ``fn`` with the slot released (the exchange-reader
        wrapper: server/task_worker.py wires a consumer task's pulls
        through this)."""
        with self.blocked():
            return fn(*args, **kwargs)

    def close(self) -> None:
        ex = self.ex
        with ex._lock:
            if self.state == "closed":
                return
            if self.state == "running":
                self._account_locked()
                ex._running.discard(self)
            elif self.state == "waiting":
                try:
                    ex._waiting.remove(self)
                except ValueError:
                    pass
            self.state = "closed"
            ex._close_locked(self)
            ex._dispatch_locked()

    # -- internals ----------------------------------------------------
    def _account_locked(self) -> None:
        now = self.ex._clock()
        elapsed = max(now - self._since, 0.0)
        self._since = now
        # CPU stamp on the owning thread (every _account_locked caller
        # runs on the task thread): the quantum's thread-CPU seconds
        cpu_now = time.thread_time()
        cpu = max(cpu_now - self._cpu_since, 0.0)
        self._cpu_since = cpu_now
        self.cpu_s += cpu
        self.ex._charge_locked(self, elapsed, cpu)

    def _wait_grant(self) -> None:
        ex = self.ex
        try:
            while not self._grant_ev.wait(0.05):
                if self.cancel is not None and self.cancel.is_set():
                    with ex._lock:
                        if self.state == "running":
                            return  # granted while we checked cancel
                        try:
                            ex._waiting.remove(self)
                        except ValueError:
                            pass
                        ex._publish_depth_locked()
                        self.state = "closed"
                        ex._close_locked(self)
                    raise TaskCanceledError(
                        f"task {self.task_id} canceled while waiting "
                        "for a runner slot")
        finally:
            # CPU accounting restarts at the grant: time burned off-CPU
            # waiting for the slot must not charge the next quantum
            self._cpu_since = time.thread_time()  # tt-lint: ignore[race-attr-write] owning-task-thread-private: every _cpu_since reader/writer runs on the handle's own thread (thread_time is per-thread by definition)


class _BlockedScope:
    __slots__ = ("h", "_t0", "_released")

    def __init__(self, h: TaskHandle):
        self.h = h
        self._t0: float = 0.0
        self._released = False

    def __enter__(self):
        h, ex = self.h, self.h.ex
        self._t0 = time.perf_counter()
        with ex._lock:
            if h.state == "running":
                h._account_locked()
                h.state = "blocked"
                ex._running.discard(h)
                ex._dispatch_locked()
                self._released = True
        return self

    def __exit__(self, *exc):
        h, ex = self.h, self.h.ex
        if self._released:
            # the exchange-wait observable: how long this consumer sat
            # off-CPU with its runner slot RELEASED waiting for
            # upstream commits (a no-op enter — closed/canceled handle
            # — held no slot and must not skew the histogram)
            EXCHANGE_WAIT_SECONDS.observe(
                max(time.perf_counter() - self._t0, 0.0))
        with ex._lock:
            if h.state != "blocked":
                return              # closed while blocked
            h._grant_ev.clear()
            h.state = "waiting"
            ex._waiting.append(h)
            ex._dispatch_locked()
        h._wait_grant()


class TaskExecutor:
    """The shared runner pool + multilevel fair-share queue for one
    worker process. ``runners`` bounds concurrently EXECUTING tasks;
    registration is unbounded (admission/shedding is the caller's
    concern — server/task_worker.py)."""

    def __init__(self, runners: int, clock=time.perf_counter,
                 ema_tau_s: Optional[float] = None):
        self.runners = max(1, int(runners))
        self._clock = clock
        self._lock = threading.Lock()
        self._running: set = set()
        self._waiting: List[TaskHandle] = []
        # per-query accumulated scheduled seconds + open-handle count
        # (time drops with the query's last handle — qids are unique
        # per dispatch, so the table stays bounded by live queries)
        self._query_time: Dict[str, float] = {}
        self._query_cpu: Dict[str, float] = {}
        self._query_handles: Dict[str, int] = {}
        self._group_time: Dict[str, float] = {}
        # time-decayed EMA of the open-task count (the busy-shed
        # signal, server/task_worker.py _shed_reason): a dispatch
        # burst decays in, sustained overload saturates. tau from
        # config (TRINO_TPU_BUSY_SHED_EMA_S); 0 tracks the spot value.
        if ema_tau_s is None:
            from ..config import CONFIG
            ema_tau_s = CONFIG.busy_shed_ema_s
        self.ema_tau_s = max(float(ema_tau_s), 0.0)
        self._ema = 0.0
        self._ema_t = self._clock()
        # stride scheduling per group: virtual time advances by
        # elapsed/weight; the smallest virtual time drains next, so a
        # group's share follows its WEIGHT, not its query count. The
        # open-handle count drives re-activation clamping (an idle
        # group must not bank virtual time and then starve everyone).
        self._group_vtime: Dict[str, float] = {}
        self._group_handles: Dict[str, int] = {}
        self._open = 0
        self._seq = 0

    # -- registration -------------------------------------------------
    def register(self, query_id: str, task_id: str,
                 group: str = "global", weight: float = 1.0,
                 cancel=None) -> TaskHandle:
        with self._lock:
            self._seq += 1
            h = TaskHandle(self, query_id, task_id, group, weight,
                           cancel, self._seq)
            self._ema_update_locked()   # decay over the quiet window,
            #                             THEN admit the new task
            self._query_handles[query_id] = \
                self._query_handles.get(query_id, 0) + 1
            self._query_time.setdefault(query_id, 0.0)
            self._query_cpu.setdefault(query_id, 0.0)
            if self._group_handles.get(group, 0) == 0:
                # (re-)activation clamp: an idle group's virtual
                # clock catches up to the floor of currently-active
                # groups — fair share is over contention windows, not
                # all history
                active = [v for g, v in self._group_vtime.items()
                          if self._group_handles.get(g, 0) > 0]
                floor = min(active) if active else 0.0
                self._group_vtime[group] = max(
                    self._group_vtime.get(group, 0.0), floor)
            self._group_handles[group] = \
                self._group_handles.get(group, 0) + 1
            self._open += 1
            TASK_SCHED_RUNNABLE.set(self._open)
        return h

    # -- introspection ------------------------------------------------
    def open_tasks(self) -> int:
        with self._lock:
            return self._open

    def scheduled_seconds(self, group: Optional[str] = None) -> float:
        with self._lock:
            if group is None:
                return sum(self._group_time.values())
            return self._group_time.get(group, 0.0)

    def query_seconds(self, query_id: str) -> float:
        with self._lock:
            return self._query_time.get(query_id, 0.0)

    def query_cpu_seconds(self, query_id: str) -> float:
        """Accumulated thread-CPU seconds the scheduler accounted for
        this query's quanta on this worker (the figure task status
        reports back to the coordinator)."""
        with self._lock:
            return self._query_cpu.get(query_id, 0.0)

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._waiting)

    def open_tasks_ema(self) -> float:
        """Time-decayed EMA of the open-task count — the smoothed
        busy-shed signal (reads also advance the decay, so a worker
        going quiet recovers without waiting for the next event)."""
        with self._lock:
            self._ema_update_locked()
            return self._ema

    def set_query_seconds(self, query_id: str, seconds: float) -> None:
        """Test hook: pin a query's accumulated scheduled time (drives
        the level/priority logic deterministically)."""
        with self._lock:
            self._query_time[query_id] = float(seconds)

    def set_group_vtime(self, group: str, vtime: float) -> None:
        """Test hook: pin a group's virtual clock (drives the
        weighted fair-share ordering deterministically)."""
        with self._lock:
            self._group_vtime[group] = float(vtime)

    # -- internals (all called under self._lock) ----------------------
    def _ema_update_locked(self) -> None:
        now = self._clock()
        dt = max(now - self._ema_t, 0.0)
        self._ema_t = now
        if self.ema_tau_s <= 0:
            self._ema = float(self._open)
            return
        import math
        alpha = 1.0 - math.exp(-dt / self.ema_tau_s)
        self._ema += alpha * (float(self._open) - self._ema)

    def _publish_depth_locked(self) -> None:
        TASK_SCHED_QUEUE_DEPTH.set(len(self._waiting))

    def _key_locked(self, h: TaskHandle
                    ) -> Tuple[int, float, float, int]:
        qtime = self._query_time.get(h.query_id, 0.0)
        level = bisect_right(LEVEL_THRESHOLDS_S, qtime)
        # level (short queries finish fast) dominates; then the
        # group's weighted virtual time (fair share follows WEIGHT,
        # not query count); then the least-served query; then arrival
        return (level, self._group_vtime.get(h.group, 0.0), qtime,
                h.seq)

    def _best_waiter_locked(self) -> Optional[TaskHandle]:
        if not self._waiting:
            return None
        return min(self._waiting, key=self._key_locked)

    def _dispatch_locked(self) -> None:
        while len(self._running) < self.runners and self._waiting:
            h = min(self._waiting, key=self._key_locked)
            self._waiting.remove(h)
            h.state = "running"
            h._since = self._clock()
            self._running.add(h)
            h._grant_ev.set()
        self._publish_depth_locked()

    def _charge_locked(self, h: TaskHandle, elapsed: float,
                       cpu: float = 0.0) -> None:
        # the level the quantum RAN at (pre-charge accumulated time):
        # the per-level scheduled-seconds counter is the decay ladder's
        # observable face
        level = bisect_right(LEVEL_THRESHOLDS_S,
                             self._query_time.get(h.query_id, 0.0))
        self._query_time[h.query_id] = \
            self._query_time.get(h.query_id, 0.0) + elapsed
        self._query_cpu[h.query_id] = \
            self._query_cpu.get(h.query_id, 0.0) + cpu
        self._group_time[h.group] = \
            self._group_time.get(h.group, 0.0) + elapsed
        self._group_vtime[h.group] = \
            self._group_vtime.get(h.group, 0.0) + elapsed / h.weight
        h.quanta += 1
        TASK_SCHED_QUANTA.inc(group=h.group)
        TASK_QUANTUM_SECONDS.observe(elapsed)
        TASK_SCHED_LEVEL_SECONDS.inc(elapsed, level=str(level))

    def _close_locked(self, h: TaskHandle) -> None:
        self._ema_update_locked()   # decay over the lived window,
        #                             THEN retire the task
        n = self._query_handles.get(h.query_id, 1) - 1
        if n <= 0:
            self._query_handles.pop(h.query_id, None)
            self._query_time.pop(h.query_id, None)
            self._query_cpu.pop(h.query_id, None)
        else:
            self._query_handles[h.query_id] = n
        self._group_handles[h.group] = \
            max(self._group_handles.get(h.group, 1) - 1, 0)
        self._open -= 1
        TASK_SCHED_RUNNABLE.set(self._open)


# ---------------------------------------------------------------------
# Ragged multi-query batching: coalesce compatible small fragments from
# CONCURRENT queries into one batch executed by a single compiled
# program (the LLM-serving playbook — ragged per-request rows through
# one kernel — applied to point-lookup storms). The batcher only
# groups; combining inputs, running the program and demuxing rows back
# per query is the caller's ``run_group`` closure (exec/executor.py
# _try_ragged_chain), so this module stays import-cycle-free.

from ..obs.metrics import METRICS  # noqa: E402

RAGGED_BATCHES = METRICS.counter(
    "trino_tpu_ragged_batch_batches_total",
    "Ragged batches executed (>= 2 co-batched fragments each)")
RAGGED_QUERIES = METRICS.counter(
    "trino_tpu_ragged_batch_queries_total",
    "Fragments served through a ragged batch")
RAGGED_ROWS = METRICS.counter(
    "trino_tpu_ragged_batch_rows_total",
    "Live input rows through ragged batches")
RAGGED_FALLBACKS = METRICS.counter(
    "trino_tpu_ragged_batch_fallbacks_total",
    "Fragments that fell back to solo execution, by reason "
    "(solo_window | capacity | error | timeout)",
    labelnames=("reason",))
RAGGED_BATCH_SIZE = METRICS.histogram(  # tt-lint: ignore[metric-naming] count-valued distribution — fragments per batch have no time/byte unit
    "trino_tpu_ragged_batch_size",
    "Co-batched fragments per executed ragged batch",
    buckets=(2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0))


class _RaggedGroup:
    __slots__ = ("sig", "items", "rows", "open", "done", "results")

    def __init__(self, sig: tuple, item, rows: int):
        self.sig = sig
        self.items = [item]
        self.rows = rows
        self.open = True
        self.done = threading.Event()
        self.results: Optional[list] = None


def _wait_inline(fn, *args, **kwargs):
    return fn(*args, **kwargs)


class RaggedBatcher:
    """Batch formation at the quantum boundary. The FIRST fragment of
    a signature becomes the batch LEADER: it parks for the formation
    window (slot released through ``wait``), then closes the group and
    executes all members' inputs as one batch. Joiners park until the
    leader publishes results. Every wait routes through the caller's
    ``wait`` hook (TaskHandle.run_blocked on a scheduled worker) —
    members holding every runner slot would otherwise deadlock the
    leader's re-acquire.

    Failure isolation: ``run_group`` raising fails NO ONE here — the
    group publishes no results and every member (leader included)
    falls back to solo execution on its own thread, where the actual
    offender re-raises its own error and innocents succeed."""

    def __init__(self, window_s: float, max_rows: int) -> None:
        self.window_s = max(float(window_s), 0.0)
        self.max_rows = max(int(max_rows), 1)
        self._lock = threading.Lock()
        self._groups: Dict[tuple, _RaggedGroup] = {}

    def submit(self, sig: tuple, rows: int, item, run_group,
               wait=None, max_rows: Optional[int] = None,
               member_timeout_s: float = 600.0):
        """Offer one fragment for co-batching.

        ``sig``   canonical-program compatibility signature
        ``rows``  the fragment's live row count
        ``item``  opaque payload handed to ``run_group``
        ``run_group(items) -> [result, ...]`` executes a closed group
                  (leader's thread) and returns per-item results
        ``wait``  slot-releasing call hook (session.slot_wait); None
                  waits inline

        Returns ``(True, result)`` when the fragment was served by a
        ragged batch, ``(False, None)`` when the caller must run solo.
        """
        cap = min(self.max_rows, max_rows or self.max_rows)
        if rows > cap:
            RAGGED_FALLBACKS.inc(reason="capacity")
            return False, None
        waiter = wait or _wait_inline
        with self._lock:
            g = self._groups.get(sig)
            if g is not None and g.open and g.rows + rows <= cap:
                idx = len(g.items)
                g.items.append(item)
                g.rows += rows
                joined = g
            elif g is not None:
                # a same-sig group exists but is closed/full: joining
                # would race its execution — run solo
                RAGGED_FALLBACKS.inc(reason="capacity")
                return False, None
            else:
                joined = None
                g = _RaggedGroup(sig, item, rows)
                self._groups[sig] = g
        if joined is not None:
            # member: park (slot released) until the leader publishes
            ok = waiter(g.done.wait, member_timeout_s)
            if not ok:
                RAGGED_FALLBACKS.inc(reason="timeout")
                return False, None
            if g.results is None:
                RAGGED_FALLBACKS.inc(reason="error")
                return False, None
            return True, g.results[idx]
        # leader: formation window with the slot released, then close
        if self.window_s > 0:
            waiter(time.sleep, self.window_s)
        with self._lock:
            g.open = False
            self._groups.pop(sig, None)
        if len(g.items) == 1:
            # nobody showed up: run solo, no demux overhead
            g.done.set()
            RAGGED_FALLBACKS.inc(reason="solo_window")
            return False, None
        try:
            results = run_group(list(g.items))
            if results is None or len(results) != len(g.items):
                raise RuntimeError(
                    f"ragged run_group returned "
                    f"{0 if results is None else len(results)} results "
                    f"for {len(g.items)} items")
            g.results = results
        except Exception:           # noqa: BLE001 — isolation: the
            g.results = None        # whole group degrades to solo
            RAGGED_FALLBACKS.inc(reason="error")
            return False, None
        finally:
            g.done.set()
        RAGGED_BATCHES.inc()
        RAGGED_QUERIES.inc(len(g.items))
        RAGGED_ROWS.inc(g.rows)
        RAGGED_BATCH_SIZE.observe(float(len(g.items)))
        return True, g.results[0]


_RAGGED: Optional[RaggedBatcher] = None
_RAGGED_INIT_LOCK = threading.Lock()


def ragged_batcher() -> RaggedBatcher:
    """Process-wide batcher (config-sized): every executor in the
    process offers through one instance, so fragments of DIFFERENT
    queries can meet."""
    global _RAGGED
    if _RAGGED is None:
        with _RAGGED_INIT_LOCK:
            if _RAGGED is None:
                from ..config import CONFIG
                _RAGGED = RaggedBatcher(
                    CONFIG.ragged_window_ms / 1000.0,
                    CONFIG.ragged_batch_rows)
    return _RAGGED
