"""Coordinator -> remote-worker query execution (the multi-host spine).

Reference parity: the coordinator drives worker JVMs through
  server/remotetask/HttpRemoteTask.java:103 (POST /v1/task with a
  serialized fragment + split assignment),
  execution/SqlTaskManager.java:370-403 (worker-side task execution),
  operator/ExchangeClient.java:149 (token-acknowledged page pulls),
and SqlQueryScheduler/SqlStageExecution stitch the stages together.

TPU-first shape: a leaf fragment (scan -> filter -> project, plus a
partial aggregation / partial TopN / partial limit when the parent
combines) is shipped as JSON (plan/serde.py) to every worker with a
(part, nparts) split share; workers execute it on their own backend and
serve serde page frames; the coordinator concatenates the partials,
substitutes them into the plan as preloaded batches, and runs the
remaining (combine) plan locally. Exchanges inside a TPU slice stay XLA
collectives (parallel/spmd.py) — this module is the DCN leg between
hosts.
"""

from __future__ import annotations

import json
import threading
import uuid
from dataclasses import replace as dc_replace
from typing import Dict, List, Optional, Tuple

from ..catalog import CatalogManager
from ..columnar import Batch
from ..plan.nodes import (Aggregate, AggregationNode, FilterNode,
                          LimitNode, OutputNode, PlanNode, ProjectNode,
                          TableScanNode, TopNNode)
from ..plan.serde import to_jsonable
from ..rex import InputRef
from ..session import Session
from .executor import (Executor, NodeStats, QueryError, _Pre,
                       device_concat, merge_node_stats)

# aggregate kinds a PARTIAL/FINAL split supports host-side, mapping to
# the FINAL combine kind (reference: AggregationNode PARTIAL->FINAL +
# InternalAggregationFunction combine; avg splits into sum+count)
_COMBINE = {"sum": "sum", "count": "sum", "count_star": "sum",
            "min": "min", "max": "max", "any_value": "any_value",
            "bool_and": "bool_and", "bool_or": "bool_or", "every":
            "bool_and"}


class _Fragment:
    """One leaf fragment: a plan subtree rooted in a single table scan
    chain, executed by every worker over its split share."""

    def __init__(self, fid: int, plan: PlanNode,
                 final_builder) -> None:
        self.fid = fid
        self.plan = plan
        # final_builder(preloaded) -> PlanNode: rebuilds the
        # coordinator-side combine step over the gathered partials
        self.final_builder = final_builder


def _is_chain(node: PlanNode) -> bool:
    """scan | filter(chain) | project(chain) — independently executable
    per split share."""
    if isinstance(node, TableScanNode):
        return True
    if isinstance(node, (FilterNode, ProjectNode)):
        return _is_chain(node.source)
    return False


def _chain_scan(node: PlanNode) -> TableScanNode:
    while not isinstance(node, TableScanNode):
        node = node.source
    return node


def _splittable_agg(node: AggregationNode) -> bool:
    if node.step != "SINGLE" or node.group_id_symbol is not None:
        return False
    for a in node.aggregates.values():
        if a.distinct:
            return False
        if a.kind == "avg":
            continue
        if a.kind not in _COMBINE:
            return False
    return True


class RemoteScheduler:
    """Fragment a plan, dispatch leaf fragments to workers, stitch the
    results back (SqlQueryScheduler, collapsed to leaf stages +
    coordinator combine)."""

    def __init__(self, worker_uris: List[str],
                 catalogs: CatalogManager, session: Session,
                 collect_stats: bool = False):
        if not worker_uris:
            raise ValueError("RemoteScheduler needs at least one worker")
        from ..server.task_worker import RemoteTaskClient
        self.workers = [RemoteTaskClient(u) for u in worker_uris]
        self.catalogs = catalogs
        self.session = session
        # distributed stats rollup: workers report per-node stats in
        # task results; after execute_plan, fragment_stats[fid] holds
        # the per-stage merge and self.stats the full rollup (fragment
        # stages + the coordinator combine), powering EXPLAIN ANALYZE
        self.collect_stats = collect_stats
        self.fragment_stats: Dict[int, List[NodeStats]] = {}
        self.fragment_workers: Dict[int, int] = {}
        self.fragment_expected: int = 0     # tasks dispatched per frag
        self.stats: List[NodeStats] = []
        # cluster-wide resource figures: max of worker peaks (tasks run
        # concurrently) + the coordinator combine; spill sums
        self.peak_memory_bytes = 0
        self.spill_bytes = 0

    # -- fragmentation -------------------------------------------------
    def _remotable(self, node: PlanNode) -> bool:
        """Only pure-generator scans may execute on a remote worker;
        coordinator-state-backed catalogs (system.runtime, memory
        tables, information_schema) must read THIS process (reference:
        system tables run on the coordinator via
        SystemPartitioningHandle.COORDINATOR_ONLY)."""
        scan = _chain_scan(node)
        try:
            conn = self.catalogs.connector(scan.handle.catalog)
        except Exception:       # noqa: BLE001
            return False
        return bool(getattr(conn, "remote_scan_ok",
                            getattr(conn, "scan_cache_ok", False)))

    def _cut(self, node: PlanNode, frags: List[_Fragment]) -> PlanNode:
        # parent-combinable shapes first: partial agg / topN / limit
        if isinstance(node, AggregationNode) and _is_chain(node.source) \
                and self._remotable(node.source) \
                and _splittable_agg(node):
            return self._cut_aggregation(node, frags)
        if isinstance(node, TopNNode) and _is_chain(node.source) \
                and self._remotable(node.source):
            fid = len(frags)
            if node.step == "SINGLE":
                part = dc_replace(node, step="PARTIAL")
                frags.append(_Fragment(
                    fid, part,
                    lambda pre, n=node: dc_replace(n, source=pre,
                                                   step="FINAL")))
            elif node.step == "PARTIAL":
                # an optimizer-created partial (CreatePartialTopN over
                # a union branch) ships whole; its FINAL stays above
                frags.append(_Fragment(fid, node, lambda pre: pre))
            else:
                frags.append(_Fragment(fid, node.source,
                                       lambda pre, n=node: dc_replace(
                                           n, source=pre)))
                return _Placeholder(fid, node.source.output_schema())
            return _Placeholder(fid, node.output_schema())
        if isinstance(node, LimitNode) and _is_chain(node.source) \
                and self._remotable(node.source):
            fid = len(frags)
            part = (node if node.partial
                    else dc_replace(node, partial=True))
            frags.append(_Fragment(
                fid, part,
                (lambda pre: pre) if node.partial
                else (lambda pre, n=node: dc_replace(n, source=pre))))
            return _Placeholder(fid, node.output_schema())
        if _is_chain(node) and not isinstance(node, TableScanNode) \
                and self._remotable(node):
            # a bare chain (scan+filter+project) below a non-combinable
            # parent: ship the chain, gather rows
            fid = len(frags)
            frags.append(_Fragment(fid, node, lambda pre: pre))
            return _Placeholder(fid, node.output_schema())
        if isinstance(node, TableScanNode) and self._remotable(node):
            fid = len(frags)
            frags.append(_Fragment(fid, node, lambda pre: pre))
            return _Placeholder(fid, node.output_schema())
        # recurse
        srcs = node.sources
        if not srcs:
            return node
        new = [self._cut(s, frags) for s in srcs]
        if all(a is b for a, b in zip(new, srcs)):
            return node
        return _replace_sources(node, new)

    def _cut_aggregation(self, node: AggregationNode,
                         frags: List[_Fragment]) -> PlanNode:
        """PARTIAL on workers, FINAL combine + avg reconstruction at the
        coordinator (PushPartialAggregationThroughExchange, host leg)."""
        partial_aggs: Dict[str, Aggregate] = {}
        final_aggs: Dict[str, Aggregate] = {}
        avg_posts: Dict[str, Tuple[str, str]] = {}
        from ..types import BIGINT
        src_schema = node.source.output_schema()
        for sym, a in node.aggregates.items():
            if a.kind == "avg":
                ssym, csym = sym + "$rsum", sym + "$rcnt"
                from ..functions import aggregate_result_type
                sum_t = aggregate_result_type("sum",
                                              [src_schema[a.argument]])
                partial_aggs[ssym] = Aggregate("sum", a.argument, sum_t,
                                               mask=a.mask)
                partial_aggs[csym] = Aggregate("count", a.argument,
                                               BIGINT, mask=a.mask)
                final_aggs[ssym] = Aggregate("sum", ssym, sum_t)
                final_aggs[csym] = Aggregate("sum", csym, BIGINT)
                avg_posts[sym] = (ssym, csym)
            else:
                kind = a.kind
                out_t = a.type
                partial_aggs[sym] = a
                final_aggs[sym] = Aggregate(_COMBINE[kind], sym, out_t)
        part = AggregationNode(node.source, node.group_keys,
                               partial_aggs, step="SINGLE")
        fid = len(frags)

        def build_final(pre, n=node, finals=final_aggs, posts=avg_posts):
            out: PlanNode = AggregationNode(pre, n.group_keys, finals,
                                            step="SINGLE")
            if posts:
                from ..rex import Call
                assigns = {}
                schema = out.output_schema()
                from ..types import DecimalType
                for s in n.output_schema():
                    if s in posts:
                        ssym, csym = posts[s]
                        a = n.aggregates[s]
                        num = InputRef(ssym, schema[ssym])
                        den = InputRef(csym, schema[csym])
                        # decimal division must hit the exact Int128
                        # kernel (the planner's op naming —
                        # "decimal_/" — not the float _arith path)
                        op = ("decimal_/"
                              if isinstance(a.type, DecimalType)
                              else "/")
                        assigns[s] = Call(op, (num, den), a.type)
                    else:
                        assigns[s] = InputRef(s, schema[s])
                out = ProjectNode(out, assigns)
            return out

        frags.append(_Fragment(fid, part, build_final))
        return _Placeholder(fid, node.output_schema())

    # -- dispatch ------------------------------------------------------
    def execute_plan(self, plan: PlanNode) -> Batch:
        from ..obs.trace import null_span
        trace = getattr(self.session, "trace", None)
        sp = trace.span if trace is not None else null_span
        frags: List[_Fragment] = []
        with sp("schedule"):
            rewritten = self._cut(plan, frags)
        if not frags:
            ex = Executor(self.catalogs, self.session,
                          self.collect_stats)
            out = ex.execute(plan)
            self.stats = list(ex.stats)
            self.peak_memory_bytes = ex.peak_reserved_bytes
            self.spill_bytes = ex.spilled_bytes
            return out
        gathered = self._run_fragments(frags)
        final = _substitute(rewritten, {
            f.fid: f.final_builder(_Pre(gathered[f.fid]))
            for f in frags})
        ex = Executor(self.catalogs, self.session, self.collect_stats)
        out = ex.execute(final)
        self.peak_memory_bytes = max(self.peak_memory_bytes,
                                     ex.peak_reserved_bytes)
        self.spill_bytes += ex.spilled_bytes
        if self.collect_stats:
            # full rollup: fragment stages first (leaf-to-root order),
            # annotated with their stage, then the coordinator combine
            self.stats = []
            for fid in sorted(self.fragment_stats):
                nw = self.fragment_workers.get(fid, 0)
                # a worker whose (best-effort) status fetch failed is
                # missing from the merge: say so, or an under-counted
                # rollup reads as a complete one
                tag = (f"fragment {fid} x{nw} workers"
                       if nw == self.fragment_expected else
                       f"fragment {fid} x{nw}/"
                       f"{self.fragment_expected} workers reported")
                for s in self.fragment_stats[fid]:
                    s.detail = f"{s.detail} {tag}".strip() \
                        if s.detail else tag
                    self.stats.append(s)
            self.stats.extend(ex.stats)
        return out

    def _run_fragments(self, frags: List[_Fragment]) -> Dict[int, Batch]:
        qid = uuid.uuid4().hex[:12]
        nparts = len(self.workers)
        session = self.session
        # hash_partition_count caps the remote fan-out
        # (SystemSessionProperties HASH_PARTITION_COUNT)
        hpc = int(session.get("hash_partition_count"))
        if hpc > 0:
            nparts = min(nparts, hpc)
        results: Dict[int, List[Optional[Batch]]] = {
            f.fid: [None] * nparts for f in frags}
        worker_stats: Dict[int, List[List[NodeStats]]] = {
            f.fid: [] for f in frags}
        worker_resources: List[Tuple[int, int]] = []  # (peak, spill)
        errors: List[str] = []
        trace = getattr(session, "trace", None)
        trace_parent = trace.current() if trace is not None else None
        events = getattr(session, "events", None)

        payloads = {f.fid: to_jsonable(f.plan) for f in frags}

        def run_one(f: _Fragment, wi: int):
            import time as _time
            t0 = _time.perf_counter()
            try:
                client = self.workers[wi]
                tid = f"{qid}.{f.fid}.{wi}"
                client.submit_fragment(
                    tid, payloads[f.fid],
                    catalog=session.catalog, schema=session.schema,
                    part=wi, nparts=nparts,
                    properties=dict(session.properties),
                    collect_stats=self.collect_stats)
                pages = client.pages(
                    tid, cancel=getattr(session, "cancel", None))
                results[f.fid][wi] = (device_concat(pages)
                                      if len(pages) > 1 else
                                      pages[0] if pages else None)
                t1 = _time.perf_counter()
                # telemetry is best-effort: the result pages are
                # already in hand, so a failed stats fetch (transient
                # status GET error, graft bug) must never fail the
                # query that produced them
                try:
                    if self.collect_stats:
                        status = client.status(tid)
                        reported = [NodeStats.from_dict(d) for d in
                                    status.get("nodeStats") or []]
                        if reported:
                            worker_stats[f.fid].append(reported)
                        # list.append is atomic; sums happen after join
                        worker_resources.append((
                            int(status.get("peakMemoryBytes") or 0),
                            int(status.get("spillBytes") or 0)))
                        if trace is not None:
                            sp = trace.record(
                                f"fragment_{f.fid}_execute", t0, t1,
                                parent=trace_parent, worker=wi,
                                task=tid)
                            trace.graft(sp, status.get("spans") or [])
                    # a remote task IS this engine's split of work: its
                    # completion is the SplitCompleted lifecycle event
                    if events is not None:
                        from ..server.events import SplitCompletedEvent
                        events.split_completed(SplitCompletedEvent(
                            getattr(session, "query_id", "") or qid,
                            f"task:{tid}", t1 - t0))
                except Exception:      # noqa: BLE001
                    pass
            except Exception as e:     # noqa: BLE001
                errors.append(f"task {f.fid}@worker{wi}: "
                              f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=run_one, args=(f, wi))
                   for f in frags for wi in range(nparts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise QueryError("remote task failed: "
                             + "; ".join(errors[:3]))
        if self.collect_stats:
            self.fragment_expected = nparts
            for f in frags:
                self.fragment_stats[f.fid] = merge_node_stats(
                    worker_stats[f.fid])
                self.fragment_workers[f.fid] = len(worker_stats[f.fid])
            for peak, spill in worker_resources:
                self.peak_memory_bytes = max(self.peak_memory_bytes,
                                             peak)
                self.spill_bytes += spill
        out: Dict[int, Batch] = {}
        for f in frags:
            parts = [b for b in results[f.fid] if b is not None]
            if not parts:
                raise QueryError(f"fragment {f.fid} returned no pages")
            out[f.fid] = (device_concat(parts) if len(parts) > 1
                          else parts[0])
        return out


class _Placeholder(PlanNode):
    """Marks a cut point until the gathered batch replaces it."""

    __slots__ = ("fid", "_schema")

    def __init__(self, fid: int, schema):
        self.fid = fid
        self._schema = dict(schema)

    def output_schema(self):
        return dict(self._schema)


def _replace_sources(node: PlanNode, new_sources) -> PlanNode:
    import dataclasses
    src_fields = [f.name for f in dataclasses.fields(node)
                  if f.name in ("source", "left", "right", "children",
                                "filtering_source")]
    updates = {}
    i = 0
    for fname in src_fields:
        cur = getattr(node, fname)
        if isinstance(cur, PlanNode):
            updates[fname] = new_sources[i]
            i += 1
        elif isinstance(cur, tuple):
            updates[fname] = tuple(new_sources[i:i + len(cur)])
            i += len(cur)
    return dc_replace(node, **updates)


class DistributedHostQueryRunner:
    """DistributedQueryRunner analog: parse/plan/optimize at the
    coordinator, leaf fragments on remote worker processes, combine
    locally (reference: testing/trino-testing's DistributedQueryRunner
    booting a coordinator + N workers on ephemeral ports)."""

    def __init__(self, worker_uris: List[str],
                 session: Optional[Session] = None, catalogs=None,
                 collect_node_stats: bool = False):
        from ..runner import LocalQueryRunner
        self._local = LocalQueryRunner(session=session,
                                       catalogs=catalogs)
        self.session = self._local.session
        self.catalogs = self._local.catalogs
        self.worker_uris = list(worker_uris)
        self.collect_node_stats = collect_node_stats

    def execute(self, sql: str):
        import time as _time
        from ..obs.metrics import QUERY_WALL_SECONDS
        from ..obs.trace import QueryTrace, null_span
        from ..planner.logical import LogicalPlanner
        from ..planner.optimizer import optimize
        from ..plan.nodes import plan_tree_lines
        from ..runner import QueryResult
        from ..sql import ast as A
        from ..sql.parser import parse_statement
        from ..types import VARCHAR
        t0 = _time.perf_counter()
        stmt = parse_statement(sql)
        analyze = False
        if isinstance(stmt, A.Explain):
            if not stmt.analyze \
                    or not isinstance(stmt.statement, A.QueryStatement):
                return self._local.execute(sql)
            # distributed EXPLAIN ANALYZE: run the inner query over the
            # workers WITH stats so the rendering shows real per-
            # fragment numbers, not coordinator-only timings
            analyze = True
            stmt = stmt.statement
        if not isinstance(stmt, A.QueryStatement):
            return self._local.execute(sql)   # DDL etc: coordinator-only
        collect = self.collect_node_stats or analyze
        trace = (QueryTrace(getattr(self.session, "query_id", ""))
                 if collect else None)
        sp = trace.span if trace is not None else null_span
        prev_trace = self.session.trace
        self.session.trace = trace
        try:
            with sp("plan"):
                planner = LogicalPlanner(self.catalogs, self.session)
                plan = planner.plan(stmt)
            with sp("optimize"):
                plan = optimize(plan, self.catalogs, self.session)
            sched = RemoteScheduler(
                self.worker_uris, self.catalogs, self.session,
                collect_stats=collect)
            with sp("execute"):
                batch = sched.execute_plan(plan)
        finally:
            self.session.trace = prev_trace
            # same latency histogram LocalQueryRunner feeds, in the
            # finally for the same reason: failed/timed-out queries
            # must not vanish from the SLO dashboards
            QUERY_WALL_SECONDS.observe(_time.perf_counter() - t0)
        if analyze:
            from .executor import render_analyze_lines
            lines = render_analyze_lines(plan_tree_lines(plan),
                                         sched.stats, trace)
            res = QueryResult(["Query Plan"], [VARCHAR],
                              [[l] for l in lines])
            res.stats = sched.stats
            res.trace = trace
            return res
        schema = batch.schema()
        types = [schema[s] for s in plan.symbols]
        res = QueryResult(list(plan.names), types, batch.to_pylist())
        res.plan_lines = plan_tree_lines(plan)
        res.trace = trace
        res.peak_memory_bytes = sched.peak_memory_bytes
        res.spill_bytes = sched.spill_bytes
        if self.collect_node_stats:
            res.stats = sched.stats
        return res


def _substitute(node: PlanNode, repl: Dict[int, PlanNode]) -> PlanNode:
    if isinstance(node, _Placeholder):
        return repl[node.fid]
    srcs = node.sources
    if not srcs:
        return node
    new = [_substitute(s, repl) for s in srcs]
    if all(a is b for a, b in zip(new, srcs)):
        return node
    return _replace_sources(node, new)
