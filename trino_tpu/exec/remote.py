"""Coordinator -> remote-worker query execution (the multi-host spine).

Reference parity: the coordinator drives worker JVMs through
  server/remotetask/HttpRemoteTask.java:103 (POST /v1/task with a
  serialized fragment + split assignment),
  execution/SqlTaskManager.java:370-403 (worker-side task execution),
  operator/ExchangeClient.java:149 (token-acknowledged page pulls),
and SqlQueryScheduler/SqlStageExecution stitch the stages together.

TPU-first shape, two dispatch modes:

- **stage-DAG MPP** (``multistage_execution``; trino_tpu/stage/): the
  plan is cut at exchange points into a DAG of stages — joins, final
  aggregations, and windows execute ON WORKERS over a
  hash-partitioned worker-to-worker exchange riding the FTE spool,
  and the coordinator executes only the root stage (the reference's
  SqlQueryScheduler -> SqlStageExecution -> PartitionedOutputOperator
  shape). Plans the stage fragmenter declines fall back to:
- **flat leaf fragments**: a leaf fragment (scan -> filter -> project,
  plus a partial aggregation / partial TopN / partial limit when the
  parent combines) is shipped as JSON (plan/serde.py) to every worker
  with a (part, nparts) split share; workers execute it on their own
  backend and serve serde page frames; the coordinator concatenates
  the partials, substitutes them into the plan as preloaded batches,
  and runs the remaining (combine) plan locally.

Exchanges inside a TPU slice stay XLA collectives (parallel/spmd.py)
— this module is the DCN leg between hosts.
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from dataclasses import replace as dc_replace
from typing import Callable, Dict, List, Optional, Tuple

from ..catalog import CatalogManager
from ..columnar import Batch
from ..fte.retry import (COMBINE_RETRIES, TASK_RETRIES, RetryController,
                         RetryPolicy, backoff_delay, pick_worker)
from ..fte.speculate import (SPECULATIVE_TASKS, SPECULATIVE_WINS,
                             StragglerDetector)
from ..plan.nodes import (AggregationNode, FilterNode, LimitNode,
                          PlanNode, ProjectNode, TableScanNode,
                          TopNNode)
from ..plan.serde import to_jsonable
from ..session import Session
from .executor import (Executor, NodeStats, QueryError, _Pre,
                       device_concat, merge_node_stats)

# the PARTIAL/FINAL aggregation split lives in stage/fragmenter.py now
# (shared by this flat fragmenter and the stage-DAG fragmenter — one
# combine table, zero drift)
from ..stage.fragmenter import (build_final_aggregation,
                                split_aggregates,
                                splittable_aggregates)


class _Fragment:
    """One leaf fragment: a plan subtree rooted in a single table scan
    chain, executed by every worker over its split share."""

    def __init__(self, fid: int, plan: PlanNode,
                 final_builder) -> None:
        self.fid = fid
        self.plan = plan
        # final_builder(preloaded) -> PlanNode: rebuilds the
        # coordinator-side combine step over the gathered partials
        self.final_builder = final_builder


def _is_chain(node: PlanNode) -> bool:
    """scan | filter(chain) | project(chain) — independently executable
    per split share."""
    if isinstance(node, TableScanNode):
        return True
    if isinstance(node, (FilterNode, ProjectNode)):
        return _is_chain(node.source)
    return False


def _chain_scan(node: PlanNode) -> TableScanNode:
    while not isinstance(node, TableScanNode):
        node = node.source
    return node


def _splittable_agg(node: AggregationNode) -> bool:
    if node.step != "SINGLE" or node.group_id_symbol is not None:
        return False
    return splittable_aggregates(node)


class RemoteScheduler:
    """Dispatch a plan over remote workers. Under
    ``multistage_execution`` the stage fragmenter (stage/fragmenter.py)
    cuts a multi-stage DAG and the stage scheduler
    (stage/scheduler.py) runs joins/aggregations ON the workers with a
    partitioned worker-to-worker exchange; otherwise — or when the
    fragmenter declines the plan shape — the flat path ships leaf
    fragments and combines on the coordinator (SqlQueryScheduler,
    collapsed to leaf stages + coordinator combine)."""

    def __init__(self, worker_uris: List[str],
                 catalogs: CatalogManager, session: Session,
                 collect_stats: bool = False,
                 failure_detector=None, spool=None,
                 worker_supplier: Optional[
                     Callable[[], List[str]]] = None,
                 manifest_store=None, manifest_meta=None):
        if not worker_uris:
            raise ValueError("RemoteScheduler needs at least one worker")
        from ..server.task_worker import RemoteTaskClient
        self.workers = [RemoteTaskClient(u) for u in worker_uris]
        self.catalogs = catalogs
        self.session = session
        # mid-flight failover (fte/recovery.py ExecutionManifestStore):
        # when both are wired and the retry policy allows resumption,
        # the stage path persists an execution manifest BEFORE
        # dispatching any task. ``manifest_meta`` carries the
        # coordinator-side identity/admission facts (query id, slug,
        # SQL, user, resource group, original submit epoch) the
        # scheduler itself does not know.
        self.manifest_store = manifest_store
        self.manifest_meta = manifest_meta
        # failover-resume accounting for the most recent stage run
        self.failover_resumed = 0
        self.failover_replayed = 0
        # distributed stats rollup: workers report per-node stats in
        # task results; after execute_plan, fragment_stats[fid] holds
        # the per-stage merge and self.stats the full rollup (fragment
        # stages + the coordinator combine), powering EXPLAIN ANALYZE
        self.collect_stats = collect_stats
        self.fragment_stats: Dict[int, List[NodeStats]] = {}
        self.fragment_workers: Dict[int, int] = {}
        self.fragment_expected: int = 0     # tasks dispatched per frag
        self.stats: List[NodeStats] = []
        # cluster-wide resource figures: max of worker peaks (tasks run
        # concurrently) + the coordinator combine; spill sums, as do
        # the morsel-streaming rollups (chunks + h2d bytes across
        # every worker task and the coordinator stages)
        self.peak_memory_bytes = 0
        self.spill_bytes = 0
        self.stream_chunks = 0
        self.stream_h2d_bytes = 0
        # scheduler/device attribution rollup (ISSUE 15): thread-CPU
        # seconds the workers' split schedulers accounted to this
        # query's tasks and device seconds their jitted dispatches
        # measured — summed per fragment/stage for the EXPLAIN ANALYZE
        # rollup and query-wide for the result
        self.cpu_seconds = 0.0
        self.device_seconds = 0.0
        # ragged batching: chain dispatches this query's tasks served
        # through co-batched programs (worker status raggedBatched)
        self.ragged_batched = 0
        self.fragment_cpu: Dict[int, float] = {}
        self.fragment_device: Dict[int, float] = {}
        # fault-tolerant execution (trino_tpu/fte/): the heartbeat
        # detector receives observed task failures and is consulted
        # when picking a replacement worker; the spool receives every
        # completed attempt's page frames (first-commit-wins) and is
        # what the combine reads. Workers observed failing a task this
        # query join ``excluded`` and are avoided for re-dispatch.
        self.failure_detector = failure_detector
        self.spool = spool
        self.excluded: set = set()
        self._excl_lock = threading.Lock()
        # attempt counters are written by dispatch threads + the
        # speculation monitor concurrently; += is read-modify-write, so
        # they share a dedicated lock (found by analysis/lint.py's
        # race-attr-write rule — lost increments would undercount
        # retries in EXPLAIN ANALYZE and the bench fault leg)
        self._stats_lock = threading.Lock()
        self.task_retries = 0
        self.combine_retries = 0
        self.speculative_launches = 0
        self.speculative_wins = 0
        # live membership (server/coordinator.py announce endpoint):
        # when a supplier is wired, every retry/speculation dispatch
        # first syncs the worker list, so a worker that JOINS mid-query
        # becomes eligible for replacement attempts and speculative
        # duplicates (the initial split fan-out stays fixed — only
        # extra attempts land on late joiners). Leaves need no sync:
        # the failure detector's liveness verdict already sidelines
        # departed workers.
        self.worker_supplier = worker_supplier
        self._members_lock = threading.Lock()
        self._known_uris = {c.base_uri for c in self.workers}
        self.workers_joined = 0
        # stage-DAG execution artifacts (multistage_execution): the cut
        # DAG and its text rendering for EXPLAIN ANALYZE's stage section
        self.stage_dag = None
        self.stage_lines: List[str] = []

    # -- deadline propagation ------------------------------------------
    def _remaining_s(self) -> Optional[float]:
        """Seconds left in this query's wall-clock budget (None = no
        deadline). The deadline is ABSOLUTE (session.deadline, set by
        the tracker/runner from query_max_run_time) so every dispatch,
        retry backoff, and page pull shares one shrinking budget —
        one computation, owned by Session.remaining_time."""
        rem = getattr(self.session, "remaining_time", None)
        return rem() if callable(rem) else None

    def _attempt_budget_s(self, default_s: float) -> float:
        """Per-attempt timeout bounded by the remaining query budget —
        an attempt must never outlive its query's deadline."""
        rem = self._remaining_s()
        if rem is None:
            return default_s
        return max(0.05, min(default_s, rem))

    def _check_deadline(self, where: str) -> None:
        """Raise EXCEEDED_TIME_LIMIT once the budget is spent; records
        a ``deadline_cancel`` span so the trace shows WHERE the breach
        cut execution (schedule, retry, combine...)."""
        import time as _time
        rem = self._remaining_s()
        if rem is None or rem > 0:
            return
        trace = getattr(self.session, "trace", None)
        if trace is not None:
            now = _time.perf_counter()
            trace.record("deadline_cancel", now, now, where=where)
        raise QueryError(
            f"Query exceeded the maximum run time "
            f"(query_max_run_time) during {where}",
            error_name="EXCEEDED_TIME_LIMIT")

    # -- live memory feedback ------------------------------------------
    def _live_memory_hook(self, task_id: str):
        """Per-task beat callback folding a worker's LIVE reservation
        into the cluster pool (server/memory.py reserve_remote) while
        the task runs — the low-memory killer then acts on live worker
        bytes, not completion-time peaks. None when no pool context
        governs this query or live_memory_feedback is off."""
        mem = getattr(self.session, "memory", None)
        feed = getattr(mem, "reserve_remote", None)
        if feed is None:
            return None
        try:
            if not bool(self.session.get("live_memory_feedback")):
                return None
        except KeyError:        # foreign session without the knob
            pass

        def beat(nbytes) -> None:
            n = int(nbytes or 0)
            if n > 0:
                feed(task_id, n)

        rel = getattr(mem, "release_remote", None)

        def release() -> None:
            # the attempt is terminal: its worker memory is free, so
            # the pool stops charging this query for it — without
            # this, retried attempts and sequential stage tasks
            # ACCUMULATE dead high-water marks until the killer fires
            # on a query that never held that much at once
            if rel is not None:
                try:
                    rel(task_id)
                except Exception:   # noqa: BLE001 — best-effort
                    pass
        beat.release = release
        return beat

    def _sync_workers(self) -> None:
        """Append clients for workers that joined since dispatch.
        Append-only: positions of known workers never move (attempt
        rotation in fte/retry.py is positional), and a departed URI
        keeps its slot for the detector to veto."""
        if self.worker_supplier is None:
            return
        try:
            uris = list(self.worker_supplier())
        except Exception:       # noqa: BLE001 — membership is advisory
            return
        from ..server.task_worker import RemoteTaskClient
        with self._members_lock:
            for u in uris:
                u = str(u).rstrip("/")
                if u in self._known_uris:
                    continue
                self._known_uris.add(u)
                self.workers.append(RemoteTaskClient(u))
                self.workers_joined += 1
                if self.failure_detector is not None:
                    self.failure_detector.add_service(u)

    # -- fragmentation -------------------------------------------------
    def _remotable(self, node: PlanNode) -> bool:
        """Only pure-generator scans may execute on a remote worker;
        coordinator-state-backed catalogs (system.runtime, memory
        tables, information_schema) must read THIS process (reference:
        system tables run on the coordinator via
        SystemPartitioningHandle.COORDINATOR_ONLY)."""
        scan = _chain_scan(node)
        try:
            conn = self.catalogs.connector(scan.handle.catalog)
        except Exception:       # noqa: BLE001
            return False
        return bool(getattr(conn, "remote_scan_ok",
                            getattr(conn, "scan_cache_ok", False)))

    def _cut(self, node: PlanNode, frags: List[_Fragment]) -> PlanNode:
        # parent-combinable shapes first: partial agg / topN / limit
        if isinstance(node, AggregationNode) and _is_chain(node.source) \
                and self._remotable(node.source) \
                and _splittable_agg(node):
            return self._cut_aggregation(node, frags)
        if isinstance(node, TopNNode) and _is_chain(node.source) \
                and self._remotable(node.source):
            fid = len(frags)
            if node.step == "SINGLE":
                part = dc_replace(node, step="PARTIAL")
                frags.append(_Fragment(
                    fid, part,
                    lambda pre, n=node: dc_replace(n, source=pre,
                                                   step="FINAL")))
            elif node.step == "PARTIAL":
                # an optimizer-created partial (CreatePartialTopN over
                # a union branch) ships whole; its FINAL stays above
                frags.append(_Fragment(fid, node, lambda pre: pre))
            else:
                frags.append(_Fragment(fid, node.source,
                                       lambda pre, n=node: dc_replace(
                                           n, source=pre)))
                return _Placeholder(fid, node.source.output_schema())
            return _Placeholder(fid, node.output_schema())
        if isinstance(node, LimitNode) and _is_chain(node.source) \
                and self._remotable(node.source):
            fid = len(frags)
            part = (node if node.partial
                    else dc_replace(node, partial=True))
            frags.append(_Fragment(
                fid, part,
                (lambda pre: pre) if node.partial
                else (lambda pre, n=node: dc_replace(n, source=pre))))
            return _Placeholder(fid, node.output_schema())
        if _is_chain(node) and not isinstance(node, TableScanNode) \
                and self._remotable(node):
            # a bare chain (scan+filter+project) below a non-combinable
            # parent: ship the chain, gather rows
            fid = len(frags)
            frags.append(_Fragment(fid, node, lambda pre: pre))
            return _Placeholder(fid, node.output_schema())
        if isinstance(node, TableScanNode) and self._remotable(node):
            fid = len(frags)
            frags.append(_Fragment(fid, node, lambda pre: pre))
            return _Placeholder(fid, node.output_schema())
        # recurse
        srcs = node.sources
        if not srcs:
            return node
        new = [self._cut(s, frags) for s in srcs]
        if all(a is b for a, b in zip(new, srcs)):
            return node
        return _replace_sources(node, new)

    def _cut_aggregation(self, node: AggregationNode,
                         frags: List[_Fragment]) -> PlanNode:
        """PARTIAL on workers, FINAL combine + avg reconstruction at
        the coordinator (PushPartialAggregationThroughExchange, host
        leg). The split itself is shared with the stage-DAG fragmenter
        (stage/fragmenter.py split_aggregates)."""
        partial_aggs, final_aggs, avg_posts = split_aggregates(
            node.aggregates, node.source.output_schema())
        part = AggregationNode(node.source, node.group_keys,
                               partial_aggs, step="SINGLE")
        fid = len(frags)

        def build_final(pre, n=node, finals=final_aggs,
                        posts=avg_posts):
            return build_final_aggregation(pre, n, finals, posts)

        frags.append(_Fragment(fid, part, build_final))
        return _Placeholder(fid, node.output_schema())

    # -- dispatch ------------------------------------------------------
    def execute_plan(self, plan: PlanNode) -> Batch:
        from ..analysis.sanity import PlanSanityChecker
        from ..obs.trace import null_span
        trace = getattr(self.session, "trace", None)
        sp = trace.span if trace is not None else null_span
        # ALWAYS validated before fragmentation (not only in the
        # plan_validation debug mode): a malformed plan crossing the
        # dispatch boundary costs a fleet-wide fan-out plus 30-90s of
        # XLA compile per worker before it fails — the checker costs a
        # plan walk. Fragments additionally prove serde round-trip
        # stability, because their wire form IS what workers execute.
        checker = PlanSanityChecker()
        frags: List[_Fragment] = []
        payloads: Dict[int, dict] = {}
        dag = stage_payloads = None
        with sp("schedule"):
            self._check_deadline("schedule")
            checker.validate(plan, "pre-dispatch")
            if self._multistage_enabled():
                from ..stage.fragmenter import StageFragmenter
                dag = StageFragmenter(self.catalogs,
                                      self.session).fragment(plan)
            if dag is not None:
                # always-on pre-dispatch battery, stage flavor: every
                # stage plan runs the fragment validators (its wire
                # form IS what workers execute) PLUS the stage-boundary
                # checks — partitioning-key closure and schema/type
                # agreement across every PartitionedOutput/RemoteSource
                # pair (analysis/sanity.py StageBoundaryChecker)
                from ..analysis.sanity import validate_stage_dag
                stage_payloads = validate_stage_dag(dag, checker)
            else:
                rewritten = self._cut(plan, frags)
                for f in frags:
                    # the round-trip-proven encoding IS the wire
                    # payload: ship the exact bytes that were validated
                    # instead of encoding the fragment a second time
                    payloads[f.fid] = checker.validate_fragment(
                        f.plan, "fragmenter")
        if dag is not None:
            return self._execute_stages(dag, stage_payloads)
        if not frags:
            ex = Executor(self.catalogs, self.session,
                          self.collect_stats)
            out = ex.execute(plan)
            self.stats = list(ex.stats)
            self.peak_memory_bytes = ex.peak_reserved_bytes
            self.spill_bytes = ex.spilled_bytes
            self.stream_chunks = ex.stream_chunks
            self.stream_h2d_bytes = ex.stream_h2d_bytes
            return out
        gathered = self._run_fragments(frags, payloads)
        final = _substitute(rewritten, {
            f.fid: f.final_builder(_Pre(gathered[f.fid]))
            for f in frags})
        out, ex = self._execute_combine(final)
        self.peak_memory_bytes = max(self.peak_memory_bytes,
                                     ex.peak_reserved_bytes)
        self.spill_bytes += ex.spilled_bytes
        self.stream_chunks += ex.stream_chunks
        self.stream_h2d_bytes += ex.stream_h2d_bytes
        if self.collect_stats:
            # full rollup: fragment stages first (leaf-to-root order),
            # annotated with their stage, then the coordinator combine
            self.stats = []
            for fid in sorted(self.fragment_stats):
                nw = self.fragment_workers.get(fid, 0)
                # a worker whose (best-effort) status fetch failed is
                # missing from the merge: say so, or an under-counted
                # rollup reads as a complete one
                tag = (f"fragment {fid} x{nw} workers"
                       if nw == self.fragment_expected else
                       f"fragment {fid} x{nw}/"
                       f"{self.fragment_expected} workers reported")
                # the per-fragment attribution rollup: scheduler-
                # accounted CPU and device seconds, distinct from wall
                tag += (f" [cpu {self.fragment_cpu.get(fid, 0.0):.3f}s"
                        f", device "
                        f"{self.fragment_device.get(fid, 0.0) * 1000:.2f}"
                        "ms]")
                for s in self.fragment_stats[fid]:
                    s.detail = f"{s.detail} {tag}".strip() \
                        if s.detail else tag
                    self.stats.append(s)
            self.stats.extend(ex.stats)
        return out

    def _multistage_enabled(self) -> bool:
        try:
            return bool(self.session.get("multistage_execution"))
        except KeyError:        # foreign session without the knob
            return False

    def _execute_stages(self, dag, payloads: Dict[int, dict],
                        resume: Optional[dict] = None) -> Batch:
        """Stage-DAG execution: every worker stage runs through the
        topological stage scheduler (stage/scheduler.py) with the
        partitioned exchange riding the workers' spools; the
        coordinator then executes ONLY the root plan, pulling the
        final gather partition from the last stage's tasks — under
        the same combine retry loop as the flat path.

        ``resume`` (coordinator failover, fte/recovery.py): a dict of
        ``{"exec_qid", "ntasks", "spool"}`` reconstructed from a
        spooled execution manifest — the stage scheduler then reuses
        the ORIGINAL execution id (exchange keys must match the
        partitions earlier attempts committed), pins the original
        fan-out, and dispatches only the partitions whose exchange
        keys carry no COMMITTED marker."""
        from ..stage.exchange import ExchangePuller
        from ..stage.scheduler import StageExecution
        from ..fte.faultpoints import fault_point
        self.stage_dag = dag
        self.stage_lines = dag.lines()
        if resume is not None:
            sx = StageExecution(
                self, dag, payloads, qid=str(resume["exec_qid"]),
                ntasks_override={int(k): int(v) for k, v in
                                 (resume.get("ntasks") or {}).items()},
                resume_spool=resume.get("spool"))
        else:
            sx = StageExecution(self, dag, payloads)
            self._persist_manifest(dag, payloads, sx)
        # deterministic chaos site: the manifest (when one was written)
        # is durable, no task has been dispatched — a crash here leaves
        # a fully-replayable query
        fault_point("coordinator.pre_dispatch")
        sources = sx.run()
        self.failover_resumed = sx.resumed_parts
        self.failover_replayed = sx.replayed_parts
        timeout_s = float(self.session.get("remote_task_timeout"))
        # spool-first root gather: on a shared local spool base the
        # coordinator reads the final stage's committed partitions
        # directly off the workers' spool dir — a worker dying AFTER
        # its last task committed costs nothing (the HTTP pull from
        # the winner URI stays as the cross-host fallback)
        root_spool = None
        try:
            from ..config import CONFIG
            from ..fte.spool import make_spool, worker_spool_base
            if (CONFIG.spool_backend or "local").lower() in (
                    "local", "filesystem", ""):
                root_spool = make_spool(
                    "local", local_base_dir=worker_spool_base())
        except Exception:       # noqa: BLE001 — HTTP path remains
            root_spool = None

        def setup(ex):
            ex.exchange_reader = ExchangePuller(
                sources, part=0, spool=root_spool,
                timeout_s=timeout_s,
                cancel=getattr(self.session, "cancel",
                               None)).read_fragment

        out, ex = self._execute_combine(dag.root_plan, setup=setup)
        self.peak_memory_bytes = max(self.peak_memory_bytes,
                                     ex.peak_reserved_bytes)
        self.spill_bytes += ex.spilled_bytes
        self.stream_chunks += ex.stream_chunks
        self.stream_h2d_bytes += ex.stream_h2d_bytes
        for peak, spill in sx.resources:
            self.peak_memory_bytes = max(self.peak_memory_bytes, peak)
            self.spill_bytes += spill
        if self.collect_stats:
            # per-stage rollup, leaf-to-root, then the coordinator's
            # root stage — EXPLAIN ANALYZE proves WHERE each operator
            # ran (the acceptance question: joins and final
            # aggregations tagged with worker stages, the coordinator
            # carrying only the root stream)
            self.stats = []
            for sid in sorted(sx.stage_stats):
                ntasks = sx.ntasks.get(sid, 0)
                nrep = sx.stage_reported.get(sid, 0)
                tag = (f"stage {sid} x{nrep} tasks"
                       if nrep == ntasks else
                       f"stage {sid} x{nrep}/{ntasks} tasks reported")
                # per-stage attribution (the acceptance rollup):
                # worker-side scheduler CPU + device seconds, distinct
                # from the wall column
                tag += (f" [cpu {sx.stage_cpu.get(sid, 0.0):.3f}s, "
                        f"device "
                        f"{sx.stage_device.get(sid, 0.0) * 1000:.2f}ms]")
                for s in sx.stage_stats[sid]:
                    s.detail = f"{s.detail} {tag}".strip() \
                        if s.detail else tag
                    self.stats.append(s)
            for s in ex.stats:
                s.detail = (f"{s.detail} stage root (coordinator)"
                            .strip() if s.detail
                            else "stage root (coordinator)")
            self.stats.extend(ex.stats)
        return out

    def _persist_manifest(self, dag, payloads: Dict[int, dict],
                          sx) -> None:
        """Spool the execution manifest for mid-flight failover —
        everything a coordinator that never saw this query needs to
        finish it (fte/recovery.py ExecutionManifestStore). Gated the
        same way spooling itself is: retry_policy=NONE queries are not
        resumable, exactly as they get no task retries. Best-effort by
        contract — a failed persist costs only resumability."""
        if self.manifest_store is None or not self.manifest_meta:
            return
        if not RetryPolicy.from_session(self.session).enabled:
            return
        try:
            doc = dict(self.manifest_meta)
            doc.update({
                "execId": sx.qid,
                "catalog": self.session.catalog,
                "schema": self.session.schema,
                "properties": dict(self.session.properties),
                "ntasks": {str(k): int(v)
                           for k, v in sx.ntasks.items()},
                "stages": [{
                    "sid": st.sid,
                    "inputs": list(st.inputs),
                    "consumer": st.consumer,
                    "maxTasks": st.max_tasks,
                    # the serde-proven wire encoding the scheduler
                    # ships (analysis/sanity.py validate_fragment
                    # round-trip-checked these exact bytes)
                    "payload": payloads[st.sid],
                } for st in dag.stages],
                "rootPlan": to_jsonable(dag.root_plan),
            })
            self.manifest_store.persist(doc)
        except Exception:       # noqa: BLE001 — resumability is
            pass                # opportunistic, never a query failure

    def _execute_combine(self, final: PlanNode, setup=None):
        """The root (combine) stage with its own retry loop: under
        retry_policy=TASK the combine re-executes on the coordinator
        up to the per-task attempt budget — the fragment output it
        consumes is already gathered (and, when spooled, durable), so
        re-running the root costs only coordinator compute. Until PR 6
        this was the one unretried single point of failure (ROADMAP
        item 5). ``setup`` configures each attempt's Executor (the
        stage path wires the exchange reader for the root gather — a
        failed pull retries with a fresh executor the same way). A
        user cancel or a deterministic ``QueryError`` is never
        retried."""
        import time as _time
        from ..fte.faultpoints import fault_point
        # deterministic chaos site: every input the combine needs is
        # durable (stage output committed / fragments gathered), only
        # the root execution and result publication remain — fired
        # BEFORE the retry loop so an injected raise is a coordinator
        # failure, not a retriable combine error
        fault_point("coordinator.mid_combine")
        policy = RetryPolicy.from_session(self.session)
        attempts = (max(policy.task_retry_attempts, 1)
                    if policy.enabled else 1)
        trace = getattr(self.session, "trace", None)
        for attempt in range(attempts):
            # the deadline bounds the combine retry loop too: a root
            # re-execution past the budget answers nobody
            self._check_deadline("combine" if attempt == 0
                                 else "combine retry")
            ex = Executor(self.catalogs, self.session,
                          self.collect_stats)
            if setup is not None:
                setup(ex)
            t0 = _time.perf_counter()
            try:
                return ex.execute(final), ex
            except Exception as e:      # noqa: BLE001
                cancel = getattr(self.session, "cancel", None)
                if cancel is not None and cancel.is_set():
                    raise
                if isinstance(e, QueryError):
                    # deterministic engine/user errors (memory limit,
                    # bad data at the root) fail identically on every
                    # attempt — re-running only delays the answer
                    raise
                if attempt + 1 >= attempts:
                    raise
                self.combine_retries += 1
                COMBINE_RETRIES.inc()
                if trace is not None:
                    trace.record("combine_retry", t0,
                                 _time.perf_counter(), attempt=attempt,
                                 error=f"{type(e).__name__}: {e}"[-160:])
                delay = backoff_delay(policy, attempt + 1, "combine")
                rem = self._remaining_s()
                if rem is not None:
                    delay = min(delay, max(rem, 0.0))
                _time.sleep(delay)
        raise AssertionError("unreachable")  # loop returns or raises

    def _run_fragments(self, frags: List[_Fragment],
                       payloads: Optional[Dict[int, dict]] = None
                       ) -> Dict[int, Batch]:
        """Attempt-aware dispatch: every (fragment, part) task runs a
        retry loop (fte/retry.py budgets + backoff, replacement worker
        per attempt), completed attempts commit their page frames to
        the spool (first-commit-wins; fte/spool.py), and a speculation
        monitor re-dispatches stragglers (fte/speculate.py). The old
        single-shot path is the degenerate case: retry_policy=NONE, no
        spool, zero extra attempts."""
        import time as _time
        from ..serde import deserialize_batch
        qid = uuid.uuid4().hex[:12]
        nparts = len(self.workers)
        session = self.session
        # hash_partition_count caps the remote fan-out
        # (SystemSessionProperties HASH_PARTITION_COUNT)
        hpc = int(session.get("hash_partition_count"))
        if hpc > 0:
            nparts = min(nparts, hpc)
        policy = RetryPolicy.from_session(session)
        speculation_on = bool(session.get("speculation_enabled")) \
            and len(self.workers) > 1
        # spooling engages only when a duplicate attempt is possible
        # (retry or speculation): retry_policy=NONE stays the legacy
        # in-memory path with zero disk traffic
        use_spool = policy.enabled or speculation_on
        if use_spool and self.spool is None:
            from ..fte.spool import default_spool
            self.spool = default_spool(
                str(session.get("spool_backend")) or None)
        spool = self.spool if use_spool else None
        if spool is not None:
            try:        # ride-along TTL sweep (time-gated internally)
                spool.maybe_cleanup()
            except Exception:   # noqa: BLE001
                pass
        controller = RetryController(policy)
        straggler = StragglerDetector(
            multiplier=float(session.get("speculation_multiplier")),
            min_runtime_s=int(
                session.get("speculation_min_runtime_ms")) / 1000.0)
        worker_stats: Dict[int, List[List[NodeStats]]] = {
            f.fid: [] for f in frags}
        worker_resources: List[Tuple[int, int]] = []  # (peak, spill)
        trace = getattr(session, "trace", None)
        trace_parent = trace.current() if trace is not None else None
        events = getattr(session, "events", None)

        if payloads is None:
            payloads = {f.fid: to_jsonable(f.plan) for f in frags}
        tasks = [_TaskRun(f, part)
                 for f in frags for part in range(nparts)]

        def alive(wi: int) -> bool:
            det = self.failure_detector
            return det is None or det.is_alive(self.workers[wi].base_uri)

        def run_attempt(st: _TaskRun, attempt: int, wi: int,
                        speculative: bool = False) -> Optional[str]:
            """One attempt of task ``st`` on worker ``wi``; returns an
            error string on failure, None on success OR benign loss to
            a sibling attempt."""
            f = st.fragment
            tid = f"{qid}.{f.fid}.{st.part}.a{attempt}"
            client = self.workers[wi]
            t0 = _time.perf_counter()
            if not speculative:
                with st.lock:
                    st.running_since = t0
                    st.running_worker = wi
            beat = self._live_memory_hook(tid)
            # distributed tracing: pre-mint THIS attempt's span id and
            # ship it W3C-style — the worker's spans are born with the
            # query's trace id and this id as their parent, so the
            # post-completion graft is an id-preserving merge
            span_id = tp = None
            if trace is not None:
                span_id = trace.new_span_id()
                tp = trace.traceparent(span_id)
            try:
                client.submit_fragment(
                    tid, payloads[f.fid],
                    catalog=session.catalog, schema=session.schema,
                    part=st.part, nparts=nparts,
                    properties=dict(session.properties),
                    collect_stats=self.collect_stats,
                    attempt=attempt, spool=spool is not None,
                    # the worker re-derives an absolute deadline from
                    # the remaining budget: its own executor stops
                    # between plan nodes instead of computing a result
                    # nobody will wait for
                    deadline_s=self._remaining_s(),
                    # the admitting group rides into the worker's
                    # shared split scheduler (fair-share by group)
                    resource_group=getattr(session, "resource_group",
                                           None),
                    group_weight=getattr(session,
                                         "resource_group_weight",
                                         None),
                    traceparent=tp)
                # the watch event aborts this attempt's page pull the
                # moment a sibling attempt wins (or the user cancels)
                watch = _MultiEvent(getattr(session, "cancel", None),
                                    st.done)
                meta: Dict[str, str] = {}
                frames = client.pages_raw(
                    tid, cancel=watch,
                    timeout_s=self._attempt_budget_s(
                        float(session.get("remote_task_timeout"))),
                    meta_out=meta,
                    # 202 polls carry the running task's live
                    # reservation into the cluster pool
                    on_beat=beat,
                    traceparent=tp)
            except Exception as e:     # noqa: BLE001
                st.last_window = (t0, _time.perf_counter())
                if not speculative:
                    with st.lock:
                        st.running_since = None  # not running anywhere:
                        # the speculation monitor must not read a retry
                        # backoff as a straggling attempt
                if st.done.is_set():
                    if not st.failed:
                        return None     # a sibling attempt already won
                    # the task already failed permanently elsewhere and
                    # this pull was watch-aborted: not evidence against
                    # THIS worker — no detector demerit, no exclusion
                    return (f"fragment {f.fid} task {tid}: aborted "
                            "(task already failed)")
                cancel = getattr(session, "cancel", None)
                if cancel is not None and cancel.is_set():
                    # a user cancel is not the worker's failure: no
                    # detector demerit, no exclusion
                    return (f"fragment {f.fid} task {tid}: canceled")
                if _busy_decline(e):
                    # retryable BUSY shed (worker 503): the worker is
                    # healthy, just loaded — rotate to another worker
                    # WITHOUT a detector demerit or per-query
                    # exclusion (it stays eligible for later attempts)
                    return (f"{BUSY_MARK} fragment {f.fid} task {tid} "
                            f"on worker {client.base_uri}: busy "
                            "(load shed)")
                if self.failure_detector is not None:
                    self.failure_detector.record_task_failure(
                        client.base_uri, f"{type(e).__name__}: {e}")
                with self._excl_lock:
                    self.excluded.add(wi)
                return (f"fragment {f.fid} task {tid} on worker "
                        f"{client.base_uri}: {type(e).__name__}: {e}")
            finally:
                if beat is not None:
                    beat.release()  # terminal attempt: stop charging
            t1 = _time.perf_counter()
            st.last_window = (t0, t1)
            if self.failure_detector is not None:
                self.failure_detector.record_task_success(
                    client.base_uri)
            straggler.record(f.fid, t1 - t0)
            batches = None
            if spool is None:
                # decode in the attempt thread so N pullers overlap
                # deserialization (the pre-FTE path's concurrency); a
                # bad frame is a retriable attempt failure
                try:
                    batches = [deserialize_batch(fr) for fr in frames]
                except Exception as e:     # noqa: BLE001
                    return (f"fragment {f.fid} task {tid}: "
                            f"deserialize failed: "
                            f"{type(e).__name__}: {e}")
            # first-commit-wins: with a spool the COMMITTED marker is
            # the arbiter (a late duplicate is discarded on disk);
            # without one the in-memory winner slot is
            winner_attempt = attempt
            if spool is not None:
                try:
                    # single-host double-write coalescing (PR 5
                    # follow-on): when the worker already committed
                    # these exact frames to ITS spool and that
                    # directory is visible on this host (shared spool
                    # root), hard-link instead of rewriting the bytes
                    src_dir = meta.get("spool_dir")
                    linker = getattr(spool, "commit_linked", None)
                    winner_attempt = None
                    if src_dir and linker is not None \
                            and os.path.isdir(src_dir):
                        try:
                            # expect_frames: the header is worker-
                            # supplied, so the linked bytes must match
                            # the pulled pages before they can become
                            # the authoritative spooled output
                            winner_attempt = linker(
                                qid, f.fid, st.part, attempt, src_dir,
                                expect_frames=frames)
                        except Exception:  # noqa: BLE001
                            # coalescing is strictly best-effort: a
                            # reaped source dir or a content mismatch
                            # falls through to the byte commit of the
                            # frames actually pulled, instead of
                            # failing a finished attempt
                            winner_attempt = None
                    if winner_attempt is None:
                        winner_attempt = spool.commit(
                            qid, f.fid, st.part, attempt, frames)
                except Exception as e:     # noqa: BLE001 — ENOSPC etc
                    # an unwritable spool is a retriable attempt
                    # failure, not a hung query
                    return (f"fragment {f.fid} task {tid}: spool "
                            f"commit failed: {type(e).__name__}: {e}")
            won = False
            with st.lock:
                if st.winner is None and winner_attempt == attempt:
                    st.winner = (attempt, wi, speculative)
                    if spool is None:
                        st.batches = batches
                    won = True
            if not won:
                return None     # duplicate output discarded
            # from here on the winner MUST set st.done (finally below):
            # a crash between winner-set and done-set would strand the
            # main thread's untimed wait
            try:
                if speculative:
                    with self._stats_lock:
                        self.speculative_wins += 1
                    SPECULATIVE_WINS.inc()
                # telemetry is best-effort: the result pages are
                # already committed, so a failed stats fetch (transient
                # status GET error, graft bug) must never fail the
                # query
                if self.collect_stats:
                    status = client.status(tid, traceparent=tp)
                    # the worker's compiled-shape delta feeds the
                    # coordinator's hot-shape registry: DISPATCHED
                    # fragments' programs become pre-warmable even
                    # though the coordinator never compiled them
                    # (exec/hotshapes.py)
                    from .hotshapes import HOT_SHAPES
                    HOT_SHAPES.merge(status.get("hotShapes") or [])
                    # same transport, same dedup: the worker's observed
                    # per-operator rows/walls feed the coordinator's
                    # learned-stats registry (exec/learnedstats.py)
                    from .learnedstats import LEARNED_STATS
                    LEARNED_STATS.merge(status.get("learnedStats")
                                        or [])
                    reported = [NodeStats.from_dict(d) for d in
                                status.get("nodeStats") or []]
                    if reported:
                        worker_stats[f.fid].append(reported)
                    # list.append is atomic; sums happen after the wait
                    worker_resources.append((
                        int(status.get("peakMemoryBytes") or 0),
                        int(status.get("spillBytes") or 0)))
                    cpu_s = float(status.get("cpuSeconds") or 0.0)
                    dev_s = float(status.get("deviceSeconds") or 0.0)
                    with self._stats_lock:
                        self.stream_chunks += int(
                            status.get("streamChunks") or 0)
                        self.stream_h2d_bytes += int(
                            status.get("streamH2dBytes") or 0)
                        self.cpu_seconds += cpu_s
                        self.device_seconds += dev_s
                        self.ragged_batched += int(
                            status.get("raggedBatched") or 0)
                        self.fragment_cpu[f.fid] = \
                            self.fragment_cpu.get(f.fid, 0.0) + cpu_s
                        self.fragment_device[f.fid] = \
                            self.fragment_device.get(f.fid, 0.0) + dev_s
                    if trace is not None:
                        # the pre-minted id becomes the span the
                        # worker's subtree already points at
                        sp = trace.record(
                            f"fragment_{f.fid}_execute", t0, t1,
                            parent=trace_parent, span_id=span_id,
                            worker=wi, task=tid, attempt=attempt,
                            speculative=speculative,
                            cpu_s=round(cpu_s, 6),
                            device_ms=round(dev_s * 1000, 3))
                        trace.graft(sp, status.get("spans") or [])
                # a remote task IS this engine's split of work: its
                # completion is the SplitCompleted lifecycle event
                if events is not None:
                    from ..server.events import SplitCompletedEvent
                    events.split_completed(SplitCompletedEvent(
                        getattr(session, "query_id", "") or qid,
                        f"task:{tid}", t1 - t0))
            except Exception:      # noqa: BLE001
                pass
            finally:
                st.done.set()
            return None

        def run_task(st: _TaskRun):
            """Primary attempt loop: dispatch, and on failure consult
            the retry budgets, pick a replacement worker, back off,
            go again."""
            failures = 0
            busy_declines = 0
            attempt = st.next_attempt()
            while True:
                if attempt > 0:
                    # a replacement attempt may land on a worker that
                    # joined after dispatch (live membership)
                    self._sync_workers()
                with self._excl_lock:
                    banned = frozenset(self.excluded)
                wi = pick_worker(len(self.workers), st.part, attempt,
                                 banned, alive)
                try:
                    err = run_attempt(st, attempt, wi)
                except Exception as e:   # noqa: BLE001 — a bug in the
                    # attempt path must surface as a task failure, not
                    # kill this daemon thread with st.done forever
                    # unset (the main wait has no timeout)
                    err = (f"fragment {st.fragment.fid} attempt "
                           f"{attempt}: internal: "
                           f"{type(e).__name__}: {e}")
                if err is None:
                    return
                failures += 1
                st.errors.append(err)
                cancel = getattr(session, "cancel", None)
                canceled = cancel is not None and cancel.is_set()
                rem = self._remaining_s()
                if rem is not None and rem <= 0:
                    # the deadline outranks the retry budget: a retry
                    # past it would only burn worker time the client
                    # has already given up on
                    canceled = True
                if err.startswith(BUSY_MARK) and not canceled:
                    # a BUSY decline is not a task failure — the
                    # dispatch never started. Back off and rotate
                    # WITHOUT consuming the retry budget (bounded so
                    # a permanently wedged fleet still fails): this is
                    # how the existing machinery "absorbs" load shed
                    busy_declines += 1
                    if busy_declines <= BUSY_RETRY_LIMIT:
                        delay = backoff_delay(
                            policy, failures,
                            f"{qid}.{st.fragment.fid}.{st.part}")
                        if rem is not None:
                            delay = min(delay, max(rem, 0.0))
                        if st.done.wait(delay):
                            return
                        attempt = st.next_attempt()
                        continue
                if canceled or not controller.record_failure(
                        (st.fragment.fid, st.part)):
                    # out of attempts — but first-completion-wins cuts
                    # both ways: a healthy speculative duplicate still
                    # in flight decides the task's fate, not this
                    # exhausted primary (setting done now would abort
                    # its page pull via the _MultiEvent watch)
                    with st.lock:
                        spec_pending = (st.speculated
                                        and st.winner is None)
                    if spec_pending and not canceled:
                        st.spec_done.wait()
                    with st.lock:
                        if st.winner is None:
                            st.failed = True
                    st.done.set()
                    return
                with self._stats_lock:
                    self.task_retries += 1
                TASK_RETRIES.inc()
                if trace is not None:
                    t0, t1 = st.last_window
                    trace.record(
                        f"fragment_{st.fragment.fid}_retry", t0, t1,
                        parent=trace_parent, part=st.part,
                        worker=wi, attempt=attempt, error=err[-160:])
                delay = backoff_delay(
                    policy, failures,
                    f"{qid}.{st.fragment.fid}.{st.part}")
                if rem is not None:
                    delay = min(delay, max(rem, 0.0))
                if st.done.wait(delay):
                    return   # a speculative sibling won during backoff
                attempt = st.next_attempt()

        def run_speculative(st: _TaskRun, attempt: int, wi: int):
            try:
                err = run_attempt(st, attempt, wi, speculative=True)
                if err is not None:
                    st.errors.append("[speculative] " + err)
            except Exception as e:       # noqa: BLE001
                st.errors.append("[speculative] internal: "
                                 f"{type(e).__name__}: {e}")
            finally:
                # the retry loop may be blocked on this duplicate's
                # outcome before declaring the task failed
                st.spec_done.set()

        def monitor(stop_ev: threading.Event):
            """Straggler watch: poll running tasks' elapsed time
            against the fragment's completed-runtime median; launch at
            most one speculative duplicate per task on a different
            worker."""
            while not stop_ev.wait(0.05):
                pending = [st for st in tasks if not st.done.is_set()]
                if not pending:
                    return
                for st in pending:
                    if st.speculated:
                        continue
                    with st.lock:
                        t0 = st.running_since
                        wi_cur = st.running_worker
                        settled = st.winner is not None
                    # winner set but done not yet (the winner thread is
                    # in its best-effort telemetry block): the task is
                    # finished — duplicating it would only burn query
                    # retry budget
                    if settled or t0 is None:
                        continue
                    elapsed = _time.perf_counter() - t0
                    if not straggler.is_straggler(st.fragment.fid,
                                                  elapsed):
                        continue
                    rem = self._remaining_s()
                    if rem is not None and rem <= 0:
                        continue     # past the deadline: no new work
                    if not controller.grant_speculation(
                            (st.fragment.fid, st.part)):
                        continue
                    st.speculated = True
                    attempt = st.next_attempt()
                    # a freshly joined worker is the ideal speculation
                    # target: idle by definition
                    self._sync_workers()
                    with self._excl_lock:
                        banned = frozenset(
                            self.excluded
                            | ({wi_cur} if wi_cur is not None
                               else set()))
                    wi = pick_worker(len(self.workers), st.part,
                                     attempt, banned, alive)
                    if wi == wi_cur:
                        # every other worker is banned or dead: a
                        # duplicate on the straggler itself cannot
                        # help — skip the launch (the consumed budget
                        # slot is the degenerate fleet's toll). The
                        # no-op duplicate is resolved immediately so
                        # the retry loop never waits on it
                        st.spec_done.set()
                        continue
                    with self._stats_lock:
                        self.speculative_launches += 1
                    SPECULATIVE_TASKS.inc()
                    if trace is not None:
                        trace.record(
                            f"fragment_{st.fragment.fid}_speculate",
                            t0, _time.perf_counter(),
                            parent=trace_parent, part=st.part,
                            attempt=attempt, worker=wi,
                            straggler_worker=wi_cur)
                    threading.Thread(target=run_speculative,
                                     args=(st, attempt, wi),
                                     daemon=True).start()

        # daemon threads + event-based completion: first-completion-
        # wins must not block on joining a loser thread stuck in a
        # page pull on a wedged worker (its watch event unblocks it at
        # the next poll; a fully hung socket times out on its own)
        for st in tasks:
            threading.Thread(target=run_task, args=(st,),
                             daemon=True).start()
        stop_ev = threading.Event()
        if speculation_on:
            threading.Thread(target=monitor, args=(stop_ev,),
                             daemon=True).start()
        try:
            for st in tasks:
                st.done.wait()
        finally:
            stop_ev.set()
        failed = [st for st in tasks if st.failed]
        if failed:
            if spool is not None:
                spool.release(qid)
            raise QueryError(
                "remote task failed: " + "; ".join(
                    "; ".join(st.errors[-2:]) for st in failed[:3]))
        if self.collect_stats:
            self.fragment_expected = nparts
            for f in frags:
                self.fragment_stats[f.fid] = merge_node_stats(
                    worker_stats[f.fid])
                self.fragment_workers[f.fid] = len(worker_stats[f.fid])
            for peak, spill in worker_resources:
                self.peak_memory_bytes = max(self.peak_memory_bytes,
                                             peak)
                self.spill_bytes += spill
        # gather: the combine input comes OFF THE SPOOL (when one is
        # configured) — completed fragment output survives outside the
        # dispatch threads' memory, which is what makes a late retry
        # of the combine (or a restarted coordinator reading a shared
        # spool dir) possible at all
        out: Dict[int, Batch] = {}
        try:
            for f in frags:
                batches: List[Batch] = []
                for st in tasks:
                    if st.fragment is not f:
                        continue
                    if spool is None:
                        part_batches = st.batches
                    else:
                        frames = spool.read(qid, f.fid, st.part)
                        part_batches = (None if frames is None else
                                        [deserialize_batch(fr)
                                         for fr in frames])
                    if part_batches is None:
                        # the task WON, so its output must be readable
                        # — silently skipping a part would return an
                        # answer missing a whole shard's rows
                        raise QueryError(
                            f"fragment {f.fid} part {st.part}: "
                            "committed output missing from spool")
                    batches.extend(part_batches)
                if not batches:
                    raise QueryError(
                        f"fragment {f.fid} returned no pages")
                out[f.fid] = (device_concat(batches)
                              if len(batches) > 1 else batches[0])
        finally:
            if spool is not None:
                spool.release(qid)
        return out


# error-string marker for a worker's retryable BUSY shed, and the
# bound on budget-free re-dispatches per task (a permanently wedged
# fleet must still fail the query through the normal budget machinery
# instead of spinning forever)
BUSY_MARK = "[busy]"
BUSY_RETRY_LIMIT = 64


def _busy_decline(e: BaseException) -> bool:
    """True for a worker's retryable BUSY shed (HTTP 503 from
    server/task_worker.py WorkerBusyError): the dispatch was DECLINED,
    not failed — the retry machinery rotates to another worker and the
    shedding worker keeps its health record clean."""
    import urllib.error
    return isinstance(e, urllib.error.HTTPError) and e.code == 503


class _TaskRun:
    """One (fragment, part) task's dispatch state across attempts
    (the reference's per-task attempt bookkeeping in
    EventDrivenFaultTolerantQueryScheduler, collapsed)."""

    __slots__ = ("fragment", "part", "done", "spec_done", "lock",
                 "failed", "errors", "batches", "winner", "_attempts",
                 "running_since", "running_worker", "speculated",
                 "last_window")

    def __init__(self, fragment: _Fragment, part: int):
        self.fragment = fragment
        self.part = part
        self.done = threading.Event()
        # resolved outcome of the (at most one) speculative duplicate
        self.spec_done = threading.Event()
        self.lock = threading.Lock()
        self.failed = False
        self.errors: List[str] = []
        self.batches: Optional[List[Batch]] = None  # no-spool result
        self.winner: Optional[Tuple[int, int, bool]] = None
        self._attempts = 0
        self.running_since: Optional[float] = None
        self.running_worker: Optional[int] = None
        self.speculated = False
        self.last_window: Tuple[float, float] = (0.0, 0.0)

    def next_attempt(self) -> int:
        """Allocate a unique attempt id (shared by the retry loop and
        the speculation monitor — task ids must never collide)."""
        with self.lock:
            attempt = self._attempts
            self._attempts += 1
            return attempt


class _MultiEvent:
    """``is_set()`` ORs several events — the page pull's cancel hook
    combines user cancellation with sibling-attempt-won abort."""

    __slots__ = ("_events",)

    def __init__(self, *events):
        self._events = [e for e in events if e is not None]

    def is_set(self) -> bool:
        return any(e.is_set() for e in self._events)


class _Placeholder(PlanNode):
    """Marks a cut point until the gathered batch replaces it."""

    __slots__ = ("fid", "_schema")

    def __init__(self, fid: int, schema):
        self.fid = fid
        self._schema = dict(schema)

    def output_schema(self):
        return dict(self._schema)


def _replace_sources(node: PlanNode, new_sources) -> PlanNode:
    import dataclasses
    src_fields = [f.name for f in dataclasses.fields(node)
                  if f.name in ("source", "left", "right", "children",
                                "filtering_source")]
    updates = {}
    i = 0
    for fname in src_fields:
        cur = getattr(node, fname)
        if isinstance(cur, PlanNode):
            updates[fname] = new_sources[i]
            i += 1
        elif isinstance(cur, tuple):
            updates[fname] = tuple(new_sources[i:i + len(cur)])
            i += len(cur)
    return dc_replace(node, **updates)


class DistributedHostQueryRunner:
    """DistributedQueryRunner analog: parse/plan/optimize at the
    coordinator, execution on remote worker processes — multi-stage
    with a worker-to-worker partitioned exchange under
    ``multistage_execution``, flat leaf fragments + coordinator
    combine otherwise (reference: testing/trino-testing's
    DistributedQueryRunner booting a coordinator + N workers on
    ephemeral ports)."""

    def __init__(self, worker_uris: List[str],
                 session: Optional[Session] = None, catalogs=None,
                 collect_node_stats: bool = False,
                 failure_detector=None, spool=None,
                 worker_supplier: Optional[
                     Callable[[], List[str]]] = None,
                 manifest_store=None, manifest_meta=None):
        from ..runner import LocalQueryRunner
        self._local = LocalQueryRunner(session=session,
                                       catalogs=catalogs)
        self.session = self._local.session
        self.catalogs = self._local.catalogs
        self.worker_uris = list(worker_uris)
        self.collect_node_stats = collect_node_stats
        # fault-tolerant execution plumbing (trino_tpu/fte/): both are
        # optional — the scheduler creates a default spool (config/
        # session-selected backend) when the session asks for
        # retry_policy=TASK and none was given. ``worker_supplier``
        # enables live membership: re-polled at retry/speculation time
        # so late-joining workers receive attempts mid-query.
        self.failure_detector = failure_detector
        self.spool = spool
        self.worker_supplier = worker_supplier
        # mid-flight failover plumbing (fte/recovery.py): when wired,
        # stage-DAG dispatches spool an execution manifest first
        self.manifest_store = manifest_store
        self.manifest_meta = manifest_meta
        # failover-resume accounting of the last execute()/resume()
        self.failover_resumed = 0
        self.failover_replayed = 0

    def execute(self, sql: str):
        import time as _time
        from ..obs.metrics import (QUERY_PEAK_MEMORY_BYTES,
                                   QUERY_WALL_SECONDS)
        from ..obs.trace import QueryTrace, null_span
        from ..planner.logical import LogicalPlanner
        from ..planner.optimizer import optimize
        from ..plan.nodes import plan_tree_lines
        from ..runner import QueryResult
        from ..sql import ast as A
        from ..sql.parser import parse_statement
        from ..types import VARCHAR
        t0 = _time.perf_counter()
        stmt = parse_statement(sql)
        analyze = False
        if isinstance(stmt, A.Explain):
            if not stmt.analyze \
                    or not isinstance(stmt.statement, A.QueryStatement):
                return self._local.execute(sql)
            # distributed EXPLAIN ANALYZE: run the inner query over the
            # workers WITH stats so the rendering shows real per-
            # fragment numbers, not coordinator-only timings
            analyze = True
            stmt = stmt.statement
        if not isinstance(stmt, A.QueryStatement):
            return self._local.execute(sql)   # DDL etc: coordinator-only
        collect = self.collect_node_stats or analyze
        trace = (QueryTrace(getattr(self.session, "query_id", ""))
                 if collect else None)
        sp = trace.span if trace is not None else null_span
        prev_trace = self.session.trace
        self.session.trace = trace
        try:
            with sp("plan"):
                planner = LogicalPlanner(self.catalogs, self.session)
                plan = planner.plan(stmt)
            with sp("optimize"):
                plan = optimize(plan, self.catalogs, self.session)
            sched = RemoteScheduler(
                self.worker_uris, self.catalogs, self.session,
                collect_stats=collect,
                failure_detector=self.failure_detector,
                spool=self.spool,
                worker_supplier=self.worker_supplier,
                manifest_store=self.manifest_store,
                manifest_meta=self.manifest_meta)
            with sp("execute"):
                batch = sched.execute_plan(plan)
            self.failover_resumed = sched.failover_resumed
            self.failover_replayed = sched.failover_replayed
        finally:
            self.session.trace = prev_trace
            # same latency histogram LocalQueryRunner feeds, in the
            # finally for the same reason: failed/timed-out queries
            # must not vanish from the SLO dashboards
            QUERY_WALL_SECONDS.observe(_time.perf_counter() - t0)
            # OTLP export (obs/otlp.py): the finished distributed
            # trace — worker spans included, ids intact — leaves
            # through the configured sinks; in the finally so failed
            # queries' traces export too (they are the ones worth
            # reading)
            if trace is not None and trace.roots:
                from ..obs.otlp import maybe_export
                maybe_export(trace, session=self.session)
        if collect:
            # sched.peak_memory_bytes is only populated when worker
            # stats were fetched; a non-stats query must not clobber
            # the gauge's last real sample with 0
            QUERY_PEAK_MEMORY_BYTES.set(sched.peak_memory_bytes)
        if analyze:
            from .executor import render_analyze_lines
            plan_lines = plan_tree_lines(plan)
            if sched.stage_lines:
                # the stage DAG the fragmenter actually dispatched —
                # EXPLAIN ANALYZE's proof of WHERE operators ran
                plan_lines = plan_lines + [""] + sched.stage_lines
            lines = render_analyze_lines(plan_lines,
                                         sched.stats, trace)
            res = QueryResult(["Query Plan"], [VARCHAR],
                              [[l] for l in lines])
            res.stats = sched.stats
            res.trace = trace
            return res
        schema = batch.schema()
        types = [schema[s] for s in plan.symbols]
        res = QueryResult(list(plan.names), types, batch.to_pylist())
        res.plan_lines = plan_tree_lines(plan)
        res.trace = trace
        res.peak_memory_bytes = sched.peak_memory_bytes
        res.spill_bytes = sched.spill_bytes
        res.stream_chunks = sched.stream_chunks
        res.stream_h2d_bytes = sched.stream_h2d_bytes
        res.cpu_seconds = sched.cpu_seconds
        res.device_seconds = sched.device_seconds
        res.ragged_batched = sched.ragged_batched
        res.speculative_wins = sched.speculative_wins
        # canonical plan key for the history record / learned stats
        # (exec/learnedstats.py): computed from the OPTIMIZED root
        # plan, the same identity a local run of this query would get
        from .learnedstats import plan_key_for
        res.plan_key = plan_key_for(plan)
        if self.collect_node_stats:
            res.stats = sched.stats
        return res

    def resume(self, manifest: dict, resume_spool=None):
        """Finish a RUNNING query from its spooled execution manifest
        (coordinator failover; fte/recovery.py). The stage DAG is
        rebuilt from the manifest's serde-proven wire encodings, the
        ORIGINAL execution id and fan-out are pinned (exchange keys
        must address the partitions earlier attempts committed), and
        only partitions without a COMMITTED marker are dispatched —
        then the combine re-runs and the result is assembled exactly
        like a first-run query's.

        ``resume_spool`` is the spool the WORKERS committed exchange
        output to; defaults to the shared local worker spool base."""
        import time as _time
        from ..obs.metrics import QUERY_WALL_SECONDS
        from ..plan.serde import from_jsonable
        from ..runner import QueryResult
        from ..stage.fragmenter import Stage, StageDAG
        t0 = _time.perf_counter()
        stages = []
        payloads: Dict[int, dict] = {}
        for rec in manifest.get("stages") or []:
            sid = int(rec["sid"])
            payloads[sid] = rec["payload"]
            stages.append(Stage(
                sid=sid, plan=from_jsonable(rec["payload"]),
                inputs=tuple(int(i) for i in (rec.get("inputs") or ())),
                consumer=(None if rec.get("consumer") is None
                          else int(rec["consumer"])),
                max_tasks=(None if rec.get("maxTasks") is None
                           else int(rec["maxTasks"]))))
        if not stages:
            raise QueryError("execution manifest carries no stages")
        stages.sort(key=lambda st: st.sid)
        root = from_jsonable(manifest["rootPlan"])
        dag = StageDAG(stages, root)
        if resume_spool is None:
            from ..fte.spool import make_spool, worker_spool_base
            resume_spool = make_spool(
                "local", local_base_dir=worker_spool_base())
        sched = RemoteScheduler(
            self.worker_uris, self.catalogs, self.session,
            collect_stats=self.collect_node_stats,
            failure_detector=self.failure_detector,
            spool=self.spool,
            worker_supplier=self.worker_supplier)
        try:
            batch = sched._execute_stages(
                dag, payloads,
                resume={"exec_qid": manifest["execId"],
                        "ntasks": manifest.get("ntasks") or {},
                        "spool": resume_spool})
        finally:
            QUERY_WALL_SECONDS.observe(_time.perf_counter() - t0)
        self.failover_resumed = sched.failover_resumed
        self.failover_replayed = sched.failover_replayed
        schema = batch.schema()
        types = [schema[s] for s in root.symbols]
        res = QueryResult(list(root.names), types, batch.to_pylist())
        res.peak_memory_bytes = sched.peak_memory_bytes
        res.spill_bytes = sched.spill_bytes
        res.stream_chunks = sched.stream_chunks
        res.stream_h2d_bytes = sched.stream_h2d_bytes
        res.cpu_seconds = sched.cpu_seconds
        res.device_seconds = sched.device_seconds
        res.ragged_batched = sched.ragged_batched
        return res


def _substitute(node: PlanNode, repl: Dict[int, PlanNode]) -> PlanNode:
    if isinstance(node, _Placeholder):
        return repl[node.fid]
    srcs = node.sources
    if not srcs:
        return node
    new = [_substitute(s, repl) for s in srcs]
    if all(a is b for a, b in zip(new, srcs)):
        return node
    return _replace_sources(node, new)
