from .executor import Executor, QueryError  # noqa: F401
