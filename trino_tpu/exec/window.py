"""Window function execution.

Reference parity: operator/WindowOperator.java + operator/window/ (21
files: FrameInfo, WindowPartition, rank/value functions — SURVEY.md
Appendix A.6). TPU redesign: one lexsort by (partition, order) keys, then
every function is segment arithmetic over the sorted order — partition
boundaries from key-change detection, ranks from order-key-change
cumsums, running aggregates from cumsum minus the partition-start prefix.
Results scatter back to input row order, so WindowNode preserves row
positions (like the reference's PagesIndex approach).
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from ..columnar import Batch, Column
from ..ops import sort as sort_ops
from ..ops.groupby import _key_lanes
from ..plan.nodes import SortKey, WindowFunction, WindowNode
from ..types import BIGINT, DOUBLE, DecimalType, REAL


def execute_window(src: Batch, node: WindowNode) -> Batch:
    cap = src.capacity
    live = src.row_valid()

    skeys = [sort_ops.SortKey(s, True, False) for s in node.partition_by]
    skeys += [sort_ops.SortKey(k.symbol, k.ascending, k.nulls_first)
              for k in node.order_by]
    order = (sort_ops.sort_order(src, skeys) if skeys
             else jnp.arange(cap, dtype=jnp.int64))
    live_s = jnp.take(live, order)
    pos = jnp.arange(cap, dtype=jnp.int64)

    # partition boundaries over sorted order
    if node.partition_by:
        plane = _key_lanes(src, list(node.partition_by))
        p_changed = jnp.zeros((cap,), bool)
        for lane in plane[1:]:
            s = jnp.take(lane, order)
            p_changed = p_changed | (s != jnp.roll(s, 1))
        p_boundary = (p_changed | (pos == 0)) & live_s
    else:
        p_boundary = (pos == 0) & live_s
    pid = jnp.cumsum(p_boundary.astype(jnp.int64)) - 1
    pid_c = jnp.clip(pid, 0, cap - 1).astype(jnp.int32)
    part_start = jax.ops.segment_min(
        jnp.where(live_s, pos, jnp.int64(cap)), pid_c, num_segments=cap)
    part_size = jax.ops.segment_sum(live_s.astype(jnp.int64), pid_c,
                                    num_segments=cap)

    # peer (order-key) boundaries for rank/dense_rank
    if node.order_by:
        olane = _key_lanes(src, [k.symbol for k in node.order_by])
        o_changed = jnp.zeros((cap,), bool)
        for lane in olane[1:]:
            s = jnp.take(lane, order)
            o_changed = o_changed | (s != jnp.roll(s, 1))
        peer_boundary = (o_changed | p_boundary) & live_s
    else:
        peer_boundary = p_boundary

    row_in_part = pos - jnp.take(part_start, pid_c)

    out_cols: Dict[str, Column] = dict(src.columns)
    for sym, fn in node.functions.items():
        vals_s = _eval_fn(fn, src, order, live_s, pid_c, pos, part_start,
                          part_size, peer_boundary, row_in_part, node)
        data, valid = vals_s[0], vals_s[1]
        # lag/lead may return a merged dictionary as a third element
        fn_dict = vals_s[2] if len(vals_s) > 2 else None
        # scatter back to input row order
        inv = jnp.zeros((cap,), jnp.int64).at[order].set(pos)
        out_data = jnp.take(data, inv)
        out_valid = None if valid is None else jnp.take(valid, inv)
        if fn_dict is None and fn.argument is not None and \
                fn.kind in ("min", "max", "any_value", "first_value",
                            "last_value", "nth_value"):
            fn_dict = src.column(fn.argument).dictionary
        if fn_dict is not None:
            col = Column(fn.type, out_data.astype(jnp.int32),
                         out_valid, fn_dict)
        else:
            col = Column(fn.type, out_data, out_valid)
        out_cols[sym] = col
    return Batch(out_cols, src.num_rows)


def _eval_fn(fn: WindowFunction, src: Batch, order, live_s, pid, pos,
             part_start, part_size, peer_boundary, row_in_part, node):
    cap = src.capacity
    k = fn.kind
    if k == "row_number":
        return row_in_part + 1, None
    if k == "rank":
        # rank = position of the peer-group start within the partition
        peer_start = _running_last_where(pos, peer_boundary)
        return peer_start - jnp.take(part_start, pid) + 1, None
    if k == "dense_rank":
        dr = jnp.cumsum(peer_boundary.astype(jnp.int64))
        part_first_dr = jax.ops.segment_min(
            jnp.where(live_s, dr, jnp.int64(cap + 1)), pid,
            num_segments=cap)
        return dr - jnp.take(part_first_dr, pid) + 1, None
    if k == "percent_rank":
        peer_start = _running_last_where(pos, peer_boundary)
        r = (peer_start - jnp.take(part_start, pid)).astype(jnp.float64)
        n = jnp.take(part_size, pid).astype(jnp.float64)
        return jnp.where(n > 1, r / jnp.maximum(n - 1.0, 1.0), 0.0), None
    if k == "cume_dist":
        # count of rows <= current peer group end
        peer_id = jnp.cumsum(peer_boundary.astype(jnp.int64)) - 1
        peer_id_c = jnp.clip(peer_id, 0, cap - 1).astype(jnp.int32)
        peer_end = jax.ops.segment_max(
            jnp.where(live_s, pos, jnp.int64(-1)), peer_id_c,
            num_segments=cap)
        ends = jnp.take(peer_end, peer_id_c)
        n = jnp.take(part_size, pid).astype(jnp.float64)
        rel = (ends - jnp.take(part_start, pid) + 1).astype(jnp.float64)
        return rel / jnp.maximum(n, 1.0), None
    if k == "ntile":
        # ntile(b): first (n % b) buckets get ceil(n/b) rows, filled
        # consecutively (operator/window/NTileFunction.java) — also
        # correct when b > n, where each row gets its own bucket
        n = jnp.take(part_size, pid)
        if fn.offset is None:
            raise ValueError("ntile() requires a bucket-count argument")
        bcol = src.column(fn.offset)
        b = jnp.maximum(
            jnp.take(jnp.asarray(bcol.data).astype(jnp.int64), order), 1)
        b_valid = (None if bcol.valid is None
                   else jnp.take(jnp.asarray(bcol.valid), order))
        q, rem = n // b, n % b
        thresh = rem * (q + 1)
        r = row_in_part
        bucket = jnp.where(
            r < thresh, r // jnp.maximum(q + 1, 1),
            rem + (r - thresh) // jnp.maximum(q, 1))
        return bucket + 1, b_valid

    # value / aggregate functions need the argument lane in sorted order
    col = src.column(fn.argument) if fn.argument else None
    if col is not None:
        vals = jnp.take(jnp.asarray(col.data), order)
        valid_lane = (live_s if col.valid is None
                      else live_s & jnp.take(jnp.asarray(col.valid), order))
    else:
        vals = live_s.astype(jnp.int64)
        valid_lane = live_s

    if _explicit_frame(fn) and k not in ("lag", "lead"):
        return _framed_eval(fn, src, order, live_s, pid, pos,
                            part_start, part_size, peer_boundary, node,
                            vals, valid_lane)

    unbounded_end = (fn.frame_end in ("unbounded_following",)
                     or not node.order_by)
    # default RANGE frame ends at the CURRENT ROW'S PEER GROUP end (SQL
    # standard; operator/window/WindowPartition peer handling): running
    # values are read at the peer-group-end position. ROWS frames end
    # at the row itself.
    peer_id = jnp.clip(jnp.cumsum(peer_boundary.astype(jnp.int64)) - 1,
                       0, cap - 1).astype(jnp.int32)
    peer_end = jax.ops.segment_max(
        jnp.where(live_s, pos, jnp.int64(-1)), peer_id,
        num_segments=cap)
    frame_pos = (pos if fn.frame_unit == "rows"
                 else jnp.clip(jnp.take(peer_end, peer_id), 0, cap - 1))

    if k in ("first_value",):
        first_pos = jnp.take(part_start, pid)
        return jnp.take(vals, first_pos), jnp.take(valid_lane, first_pos)
    if k in ("last_value",):
        if unbounded_end:
            last_pos = jnp.take(part_start, pid) + \
                jnp.take(part_size, pid) - 1
        else:
            last_pos = frame_pos  # frame end (peers for RANGE)
        last_pos = jnp.clip(last_pos, 0, cap - 1)
        return jnp.take(vals, last_pos), jnp.take(valid_lane, last_pos)
    if k == "nth_value":
        # value at the n-th row of the frame (operator/window/
        # NthValueFunction.java): NULL when n exceeds the frame
        if fn.offset is None:
            raise ValueError("nth_value() requires a position argument")
        ocol = src.column(fn.offset)
        nth = jnp.take(jnp.asarray(ocol.data).astype(jnp.int64), order)
        start = jnp.take(part_start, pid)
        tgt = start + nth - 1
        frame_end = (start + jnp.take(part_size, pid) - 1
                     if unbounded_end else pos)
        in_frame = (nth >= 1) & (tgt <= frame_end)
        tgt_c = jnp.clip(tgt, 0, cap - 1)
        data = jnp.take(vals, tgt_c)
        valid = in_frame & jnp.take(valid_lane, tgt_c)
        if ocol.valid is not None:
            valid = valid & jnp.take(jnp.asarray(ocol.valid), order)
        return data, valid
    if k in ("lag", "lead"):
        off_valid = None
        if fn.offset is not None:
            ocol = src.column(fn.offset)
            off = jnp.take(
                jnp.asarray(ocol.data).astype(jnp.int64), order)
            if ocol.valid is not None:
                # NULL offset -> NULL result (LagFunction.java semantics)
                off_valid = jnp.take(jnp.asarray(ocol.valid), order)
        else:
            off = jnp.int64(1)
        tgt = pos - off if k == "lag" else pos + off
        same_part = (tgt >= jnp.take(part_start, pid)) & \
            (tgt < jnp.take(part_start, pid) + jnp.take(part_size, pid))
        tgt_c = jnp.clip(tgt, 0, cap - 1)
        data = jnp.take(vals, tgt_c)
        valid = jnp.take(valid_lane, tgt_c) & same_part
        out_dict = col.dictionary if col is not None else None
        if fn.default is not None:
            dcol = src.column(fn.default)
            dvals = jnp.asarray(dcol.data)
            if out_dict is not None:
                # codes from two pools: remap the default lane into a
                # merged dictionary (DictionaryBlock id remapping)
                if dcol.dictionary is None:
                    raise ValueError(
                        "lag/lead default for a dictionary column must "
                        "be a string")
                merged, _, remap_other = out_dict.merge(dcol.dictionary)
                dvals = jnp.take(jnp.asarray(remap_other),
                                 dvals.astype(jnp.int32))
                out_dict = merged
            dvals = jnp.take(dvals.astype(vals.dtype), order)
            dvalid = (live_s if dcol.valid is None else
                      live_s & jnp.take(jnp.asarray(dcol.valid), order))
            data = jnp.where(same_part, data, dvals)
            valid = jnp.where(same_part, valid, dvalid)
        if off_valid is not None:
            valid = valid & off_valid
        return data, valid, out_dict

    # aggregates over the partition (or running when ordered)
    masked = jnp.where(valid_lane, vals, 0)
    if k in ("count", "count_star"):
        lane = valid_lane.astype(jnp.int64)
        total = jax.ops.segment_sum(lane, pid, num_segments=cap)
        if unbounded_end:
            return jnp.take(total, pid), None
        run = jnp.cumsum(lane)
        base = _part_base(run, lane, part_start, pid)
        return jnp.take(run, frame_pos) - base, None
    if k == "sum":
        acc = masked.astype(
            jnp.float64 if vals.dtype in (jnp.float32, jnp.float64)
            else jnp.int64)
        nval = jax.ops.segment_sum(valid_lane.astype(jnp.int64), pid,
                                   num_segments=cap)
        if unbounded_end:
            tot = jax.ops.segment_sum(acc, pid, num_segments=cap)
            return (jnp.take(tot, pid).astype(vals.dtype),
                    jnp.take(nval, pid) > 0)
        run = jnp.cumsum(acc)
        base = _part_base(run, acc, part_start, pid)
        runv = jnp.cumsum(valid_lane.astype(jnp.int64))
        vbase = _part_base(runv, valid_lane.astype(jnp.int64),
                           part_start, pid)
        return ((jnp.take(run, frame_pos) - base).astype(vals.dtype),
                (jnp.take(runv, frame_pos) - vbase) > 0)
    if k == "avg":
        acc = masked.astype(jnp.float64)
        cnt = valid_lane.astype(jnp.int64)
        if unbounded_end:
            s = jax.ops.segment_sum(acc, pid, num_segments=cap)
            n = jax.ops.segment_sum(cnt, pid, num_segments=cap)
            s, n = jnp.take(s, pid), jnp.take(n, pid)
        else:
            rs, rn = jnp.cumsum(acc), jnp.cumsum(cnt)
            s = jnp.take(rs, frame_pos) - _part_base(rs, acc,
                                                     part_start, pid)
            n = jnp.take(rn, frame_pos) - _part_base(rn, cnt,
                                                     part_start, pid)
        return s / jnp.maximum(n.astype(jnp.float64), 1.0), n > 0
    if k in ("min", "max"):
        seg = jax.ops.segment_min if k == "min" else jax.ops.segment_max
        if vals.dtype in (jnp.float32, jnp.float64):
            ident = jnp.asarray(jnp.inf if k == "min" else -jnp.inf,
                                vals.dtype)
        else:
            info = jnp.iinfo(vals.dtype if vals.dtype != jnp.bool_
                             else jnp.int32)
            ident = jnp.asarray(info.max if k == "min" else info.min)
        w = jnp.where(valid_lane, vals, ident)
        nval = jax.ops.segment_sum(valid_lane.astype(jnp.int64), pid,
                                   num_segments=cap)
        tot = seg(w, pid, num_segments=cap)
        if unbounded_end:
            return jnp.take(tot, pid), jnp.take(nval, pid) > 0
        # running min/max via associative scan within partitions
        op = jnp.minimum if k == "min" else jnp.maximum
        run = jax.lax.associative_scan(
            lambda a, b: op(a, b), jnp.where(peer_boundary | True, w, w))
        # reset at partition starts: recompute with segmented scan
        run = _segmented_scan(w, pid, op)
        runv = jnp.cumsum(valid_lane.astype(jnp.int64))
        vbase = _part_base(runv, valid_lane.astype(jnp.int64),
                           part_start, pid)
        return (jnp.take(run, frame_pos),
                (jnp.take(runv, frame_pos) - vbase) > 0)
    raise ValueError(f"window function '{k}' not implemented")


#: function kinds that return before the explicit-frame dispatch in
#: ``_eval_fn`` — an explicit frame on these never reaches the host
#: ``_framed_eval`` path, so they stay jit-traceable regardless.
_PRE_FRAME_KINDS = frozenset((
    "row_number", "rank", "dense_rank", "percent_rank", "cume_dist",
    "ntile", "lag", "lead"))


def window_traceable(node: WindowNode) -> bool:
    """True when ``execute_window(src, node)`` is a pure jnp program
    for this node — the gate for the structural window jit cache
    (exec/executor.py). Explicit-frame aggregates evaluate through
    ``_framed_eval``, which is host numpy (sparse-table RMQ, per-
    partition searchsorted loops) and cannot trace."""
    return not any(_explicit_frame(f) and f.kind not in _PRE_FRAME_KINDS
                   for f in node.functions.values())


def _explicit_frame(fn) -> bool:
    """True when the function carries a frame the default running/
    whole-partition paths can't express: offset bounds, GROUPS unit, or
    non-default start/end (operator/window/FrameInfo.java)."""
    return (fn.frame_start_value is not None
            or fn.frame_end_value is not None
            or fn.frame_unit == "groups"
            or fn.frame_start not in ("unbounded_preceding",)
            or fn.frame_end == "following")


def _framed_eval(fn, src, order, live_s, pid, pos, part_start,
                 part_size, peer_boundary, node, vals, valid_lane):
    """Explicit-frame evaluation (ROWS/RANGE/GROUPS BETWEEN ... ):
    compute inclusive [lo, hi] sorted-position bounds per row, then
    every aggregate is a prefix-sum difference (min/max: a host sparse
    table — windows evaluate eagerly, WindowNode is not chain-jitted).
    Reference: operator/window/WindowPartition.updateFrame +
    AggregateWindowFunction."""
    import numpy as np
    cap = int(pos.shape[0])
    posn = np.arange(cap, dtype=np.int64)
    pidn = np.asarray(pid)
    ps = np.asarray(jnp.take(part_start, pid))
    pe = ps + np.asarray(jnp.take(part_size, pid)) - 1
    unit = fn.frame_unit
    k = fn.kind

    if unit != "rows":
        peerb = np.asarray(peer_boundary)
        gidx = np.cumsum(peerb.astype(np.int64)) - 1
        gidx = np.clip(gidx, 0, cap - 1)
        g_start = np.full(cap, cap, np.int64)
        np.minimum.at(g_start, gidx, posn)
        g_end = np.full(cap, -1, np.int64)
        np.maximum.at(g_end, gidx, posn)

    def group_of_offset(delta):
        """peer-group index shifted by delta, clamped to the
        partition's group range."""
        first_g = gidx[np.clip(ps, 0, cap - 1)]
        last_g = gidx[np.clip(pe, 0, cap - 1)]
        return np.clip(gidx + delta, first_g, last_g)

    def bound(which):
        btype = fn.frame_start if which == "start" else fn.frame_end
        bval = fn.frame_start_value if which == "start" \
            else fn.frame_end_value
        if btype == "unbounded_preceding":
            return ps.copy()
        if btype == "unbounded_following":
            return pe.copy()
        if btype == "current":
            if unit == "rows":
                return posn.copy()
            # RANGE/GROUPS: current row means the whole peer group
            return g_start[gidx] if which == "start" else g_end[gidx]
        sign = -1 if btype == "preceding" else 1
        n = int(bval or 0)
        if unit == "rows":
            return posn + sign * n
        if unit == "groups":
            g = group_of_offset(sign * n)
            return g_start[g] if which == "start" else g_end[g]
        # RANGE with a value offset: per-partition searchsorted over
        # the (single, numeric) order key. Descending keys are negated
        # so the sorted lane is ascending — in that mirrored space
        # "preceding" is STILL the smaller side, so the offset sign
        # does not flip. NULL keys sort into their own contiguous run;
        # they are excluded from the search segment and a NULL-key
        # row's frame is its null peer group (SQL: NULL is peer only
        # with NULL).
        if len(node.order_by) != 1:
            raise ValueError(
                "RANGE offset frames require exactly one ORDER BY key")
        key = node.order_by[0]
        kcol = src.column(key.symbol)
        lane = np.asarray(jnp.take(jnp.asarray(kcol.data), order))
        if lane.dtype == np.bool_ or kcol.dictionary is not None:
            raise ValueError(
                "RANGE offset frames require a numeric ORDER BY key")
        kvalid = (np.ones(cap, bool) if kcol.valid is None
                  else np.asarray(jnp.take(jnp.asarray(kcol.valid),
                                           order)))
        if not key.ascending:
            lane = -lane
        target = lane + sign * n
        out = np.empty(cap, np.int64)
        starts = np.unique(np.asarray(ps))
        for s in starts:
            sel = np.nonzero((np.asarray(ps) == s))[0]
            if len(sel) == 0:
                continue
            e = int(pe[sel[0]])
            vpos = np.nonzero(kvalid[s:e + 1])[0]
            if len(vpos):
                vs, ve = s + vpos[0], s + vpos[-1]
                seg = lane[vs:ve + 1]
                vsel = sel[kvalid[sel]]
                t = target[vsel]
                if which == "start":
                    out[vsel] = vs + np.searchsorted(seg, t,
                                                     side="left")
                else:
                    out[vsel] = vs + np.searchsorted(
                        seg, t, side="right") - 1
            nsel = sel[~kvalid[sel]]
            if len(nsel):
                out[nsel] = (g_start[gidx[nsel]] if which == "start"
                             else g_end[gidx[nsel]])
        return out

    lo = np.maximum(bound("start"), ps)
    hi = np.minimum(bound("end"), pe)
    empty = lo > hi
    lo_c = np.clip(lo, 0, cap - 1)
    hi_c = np.clip(hi, 0, cap - 1)

    valid_n = np.asarray(valid_lane)
    vals_n = np.asarray(vals)

    if k in ("first_value", "last_value", "nth_value"):
        if k == "first_value":
            idx = lo_c
            ok = ~empty
        elif k == "last_value":
            idx = hi_c
            ok = ~empty
        else:
            ocol = src.column(fn.offset)
            nth = np.asarray(jnp.take(
                jnp.asarray(ocol.data).astype(jnp.int64), order))
            idx = np.clip(lo_c + nth - 1, 0, cap - 1)
            ok = ~empty & (nth >= 1) & (lo + nth - 1 <= hi)
            if ocol.valid is not None:
                ok &= np.asarray(jnp.take(jnp.asarray(ocol.valid),
                                          order))
        data = vals_n[idx]
        return jnp.asarray(data), jnp.asarray(ok & valid_n[idx])

    if k in ("count", "count_star"):
        C = np.concatenate([[0], np.cumsum(valid_n.astype(np.int64))])
        cnt = C[hi_c + 1] - C[lo_c]
        cnt = np.where(empty, 0, cnt)
        return jnp.asarray(cnt), None

    if k in ("sum", "avg"):
        acc_dt = np.float64 if vals_n.dtype.kind == "f" else np.int64
        masked = np.where(valid_n, vals_n.astype(acc_dt), 0)
        S = np.concatenate([[0], np.cumsum(masked)])
        C = np.concatenate([[0], np.cumsum(valid_n.astype(np.int64))])
        s = S[hi_c + 1] - S[lo_c]
        c = C[hi_c + 1] - C[lo_c]
        ok = ~empty & (c > 0)
        if k == "avg":
            return (jnp.asarray(s / np.maximum(c, 1).astype(np.float64)),
                    jnp.asarray(ok))
        return jnp.asarray(np.where(ok, s, 0).astype(vals_n.dtype)), \
            jnp.asarray(ok)

    if k in ("min", "max"):
        if vals_n.dtype.kind == "f":
            ident = np.inf if k == "min" else -np.inf
        elif vals_n.dtype == np.bool_:
            vals_n = vals_n.astype(np.int32)
            ident = 2 if k == "min" else -1
        else:
            info = np.iinfo(vals_n.dtype)
            ident = info.max if k == "min" else info.min
        w = np.where(valid_n, vals_n, ident)
        # sparse-table RMQ: O(n log n) build, O(1) per query
        levels = [w]
        span = 1
        op = np.minimum if k == "min" else np.maximum
        while span * 2 <= cap:
            prev = levels[-1]
            levels.append(op(prev[:len(prev) - span], prev[span:]))
            span *= 2
        length = hi_c - lo_c + 1
        lvl = np.maximum(
            np.int64(np.floor(np.log2(np.maximum(length, 1)))), 0)
        span_of = (1 << lvl).astype(np.int64)
        out = np.empty(cap, dtype=w.dtype)
        for li, tbl in enumerate(levels):
            m = lvl == li
            if not m.any():
                continue
            a = lo_c[m]
            b = np.clip(hi_c[m] - span_of[m] + 1, 0, len(tbl) - 1)
            out[m] = op(tbl[np.clip(a, 0, len(tbl) - 1)], tbl[b])
        C = np.concatenate([[0], np.cumsum(valid_n.astype(np.int64))])
        c = C[hi_c + 1] - C[lo_c]
        ok = ~empty & (c > 0)
        return jnp.asarray(out), jnp.asarray(ok)

    raise ValueError(
        f"window function '{k}' does not support explicit frames")


def _running_last_where(pos, flag):
    """For each position, the most recent position where flag was True."""
    marked = jnp.where(flag, pos, jnp.int64(-1))
    return jax.lax.associative_scan(jnp.maximum, marked)


def _part_base(running, lane, part_start, pid):
    """Value of the running sum just before each partition start."""
    start_pos = jnp.take(part_start, pid)
    start_val = jnp.take(running, jnp.clip(start_pos, 0, len(running) - 1))
    start_lane = jnp.take(lane, jnp.clip(start_pos, 0, len(lane) - 1))
    return start_val - start_lane


def _segmented_scan(vals, pid, op):
    """Inclusive segmented scan: restart accumulation at pid changes."""
    pairs = (vals, pid.astype(jnp.int64))

    def combine(a, b):
        av, ai = a
        bv, bi = b
        take_b = bi != ai
        return jnp.where(take_b, bv, op(av, bv)), bi

    out, _ = jax.lax.associative_scan(combine, pairs)
    return out
