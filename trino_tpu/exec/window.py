"""Window function execution.

Reference parity: operator/WindowOperator.java + operator/window/ (21
files: FrameInfo, WindowPartition, rank/value functions — SURVEY.md
Appendix A.6). TPU redesign: one lexsort by (partition, order) keys, then
every function is segment arithmetic over the sorted order — partition
boundaries from key-change detection, ranks from order-key-change
cumsums, running aggregates from cumsum minus the partition-start prefix.
Results scatter back to input row order, so WindowNode preserves row
positions (like the reference's PagesIndex approach).
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from ..columnar import Batch, Column
from ..ops import sort as sort_ops
from ..ops.groupby import _key_lanes
from ..plan.nodes import SortKey, WindowFunction, WindowNode
from ..types import BIGINT, DOUBLE, DecimalType, REAL


def execute_window(src: Batch, node: WindowNode) -> Batch:
    cap = src.capacity
    live = src.row_valid()

    skeys = [sort_ops.SortKey(s, True, False) for s in node.partition_by]
    skeys += [sort_ops.SortKey(k.symbol, k.ascending, k.nulls_first)
              for k in node.order_by]
    order = (sort_ops.sort_order(src, skeys) if skeys
             else jnp.arange(cap, dtype=jnp.int64))
    live_s = jnp.take(live, order)
    pos = jnp.arange(cap, dtype=jnp.int64)

    # partition boundaries over sorted order
    if node.partition_by:
        plane = _key_lanes(src, list(node.partition_by))
        p_changed = jnp.zeros((cap,), bool)
        for lane in plane[1:]:
            s = jnp.take(lane, order)
            p_changed = p_changed | (s != jnp.roll(s, 1))
        p_boundary = (p_changed | (pos == 0)) & live_s
    else:
        p_boundary = (pos == 0) & live_s
    pid = jnp.cumsum(p_boundary.astype(jnp.int64)) - 1
    pid_c = jnp.clip(pid, 0, cap - 1).astype(jnp.int32)
    part_start = jax.ops.segment_min(
        jnp.where(live_s, pos, jnp.int64(cap)), pid_c, num_segments=cap)
    part_size = jax.ops.segment_sum(live_s.astype(jnp.int64), pid_c,
                                    num_segments=cap)

    # peer (order-key) boundaries for rank/dense_rank
    if node.order_by:
        olane = _key_lanes(src, [k.symbol for k in node.order_by])
        o_changed = jnp.zeros((cap,), bool)
        for lane in olane[1:]:
            s = jnp.take(lane, order)
            o_changed = o_changed | (s != jnp.roll(s, 1))
        peer_boundary = (o_changed | p_boundary) & live_s
    else:
        peer_boundary = p_boundary

    row_in_part = pos - jnp.take(part_start, pid_c)

    out_cols: Dict[str, Column] = dict(src.columns)
    for sym, fn in node.functions.items():
        vals_s = _eval_fn(fn, src, order, live_s, pid_c, pos, part_start,
                          part_size, peer_boundary, row_in_part, node)
        data, valid = vals_s[0], vals_s[1]
        # lag/lead may return a merged dictionary as a third element
        fn_dict = vals_s[2] if len(vals_s) > 2 else None
        # scatter back to input row order
        inv = jnp.zeros((cap,), jnp.int64).at[order].set(pos)
        out_data = jnp.take(data, inv)
        out_valid = None if valid is None else jnp.take(valid, inv)
        if fn_dict is None and fn.argument is not None and \
                fn.kind in ("min", "max", "any_value", "first_value",
                            "last_value", "nth_value"):
            fn_dict = src.column(fn.argument).dictionary
        if fn_dict is not None:
            col = Column(fn.type, out_data.astype(jnp.int32),
                         out_valid, fn_dict)
        else:
            col = Column(fn.type, out_data, out_valid)
        out_cols[sym] = col
    return Batch(out_cols, src.num_rows)


def _eval_fn(fn: WindowFunction, src: Batch, order, live_s, pid, pos,
             part_start, part_size, peer_boundary, row_in_part, node):
    cap = src.capacity
    k = fn.kind
    if k == "row_number":
        return row_in_part + 1, None
    if k == "rank":
        # rank = position of the peer-group start within the partition
        peer_start = _running_last_where(pos, peer_boundary)
        return peer_start - jnp.take(part_start, pid) + 1, None
    if k == "dense_rank":
        dr = jnp.cumsum(peer_boundary.astype(jnp.int64))
        part_first_dr = jax.ops.segment_min(
            jnp.where(live_s, dr, jnp.int64(cap + 1)), pid,
            num_segments=cap)
        return dr - jnp.take(part_first_dr, pid) + 1, None
    if k == "percent_rank":
        peer_start = _running_last_where(pos, peer_boundary)
        r = (peer_start - jnp.take(part_start, pid)).astype(jnp.float64)
        n = jnp.take(part_size, pid).astype(jnp.float64)
        return jnp.where(n > 1, r / jnp.maximum(n - 1.0, 1.0), 0.0), None
    if k == "cume_dist":
        # count of rows <= current peer group end
        peer_id = jnp.cumsum(peer_boundary.astype(jnp.int64)) - 1
        peer_id_c = jnp.clip(peer_id, 0, cap - 1).astype(jnp.int32)
        peer_end = jax.ops.segment_max(
            jnp.where(live_s, pos, jnp.int64(-1)), peer_id_c,
            num_segments=cap)
        ends = jnp.take(peer_end, peer_id_c)
        n = jnp.take(part_size, pid).astype(jnp.float64)
        rel = (ends - jnp.take(part_start, pid) + 1).astype(jnp.float64)
        return rel / jnp.maximum(n, 1.0), None
    if k == "ntile":
        # ntile(b): first (n % b) buckets get ceil(n/b) rows, filled
        # consecutively (operator/window/NTileFunction.java) — also
        # correct when b > n, where each row gets its own bucket
        n = jnp.take(part_size, pid)
        if fn.offset is None:
            raise ValueError("ntile() requires a bucket-count argument")
        bcol = src.column(fn.offset)
        b = jnp.maximum(
            jnp.take(jnp.asarray(bcol.data).astype(jnp.int64), order), 1)
        b_valid = (None if bcol.valid is None
                   else jnp.take(jnp.asarray(bcol.valid), order))
        q, rem = n // b, n % b
        thresh = rem * (q + 1)
        r = row_in_part
        bucket = jnp.where(
            r < thresh, r // jnp.maximum(q + 1, 1),
            rem + (r - thresh) // jnp.maximum(q, 1))
        return bucket + 1, b_valid

    # value / aggregate functions need the argument lane in sorted order
    col = src.column(fn.argument) if fn.argument else None
    if col is not None:
        vals = jnp.take(jnp.asarray(col.data), order)
        valid_lane = (live_s if col.valid is None
                      else live_s & jnp.take(jnp.asarray(col.valid), order))
    else:
        vals = live_s.astype(jnp.int64)
        valid_lane = live_s

    unbounded_end = (fn.frame_end in ("unbounded_following",)
                     or not node.order_by)

    if k in ("first_value",):
        first_pos = jnp.take(part_start, pid)
        return jnp.take(vals, first_pos), jnp.take(valid_lane, first_pos)
    if k in ("last_value",):
        if unbounded_end:
            last_pos = jnp.take(part_start, pid) + \
                jnp.take(part_size, pid) - 1
        else:
            last_pos = pos  # running frame: current row
        last_pos = jnp.clip(last_pos, 0, cap - 1)
        return jnp.take(vals, last_pos), jnp.take(valid_lane, last_pos)
    if k == "nth_value":
        # value at the n-th row of the frame (operator/window/
        # NthValueFunction.java): NULL when n exceeds the frame
        if fn.offset is None:
            raise ValueError("nth_value() requires a position argument")
        ocol = src.column(fn.offset)
        nth = jnp.take(jnp.asarray(ocol.data).astype(jnp.int64), order)
        start = jnp.take(part_start, pid)
        tgt = start + nth - 1
        frame_end = (start + jnp.take(part_size, pid) - 1
                     if unbounded_end else pos)
        in_frame = (nth >= 1) & (tgt <= frame_end)
        tgt_c = jnp.clip(tgt, 0, cap - 1)
        data = jnp.take(vals, tgt_c)
        valid = in_frame & jnp.take(valid_lane, tgt_c)
        if ocol.valid is not None:
            valid = valid & jnp.take(jnp.asarray(ocol.valid), order)
        return data, valid
    if k in ("lag", "lead"):
        off_valid = None
        if fn.offset is not None:
            ocol = src.column(fn.offset)
            off = jnp.take(
                jnp.asarray(ocol.data).astype(jnp.int64), order)
            if ocol.valid is not None:
                # NULL offset -> NULL result (LagFunction.java semantics)
                off_valid = jnp.take(jnp.asarray(ocol.valid), order)
        else:
            off = jnp.int64(1)
        tgt = pos - off if k == "lag" else pos + off
        same_part = (tgt >= jnp.take(part_start, pid)) & \
            (tgt < jnp.take(part_start, pid) + jnp.take(part_size, pid))
        tgt_c = jnp.clip(tgt, 0, cap - 1)
        data = jnp.take(vals, tgt_c)
        valid = jnp.take(valid_lane, tgt_c) & same_part
        out_dict = col.dictionary if col is not None else None
        if fn.default is not None:
            dcol = src.column(fn.default)
            dvals = jnp.asarray(dcol.data)
            if out_dict is not None:
                # codes from two pools: remap the default lane into a
                # merged dictionary (DictionaryBlock id remapping)
                if dcol.dictionary is None:
                    raise ValueError(
                        "lag/lead default for a dictionary column must "
                        "be a string")
                merged, _, remap_other = out_dict.merge(dcol.dictionary)
                dvals = jnp.take(jnp.asarray(remap_other),
                                 dvals.astype(jnp.int32))
                out_dict = merged
            dvals = jnp.take(dvals.astype(vals.dtype), order)
            dvalid = (live_s if dcol.valid is None else
                      live_s & jnp.take(jnp.asarray(dcol.valid), order))
            data = jnp.where(same_part, data, dvals)
            valid = jnp.where(same_part, valid, dvalid)
        if off_valid is not None:
            valid = valid & off_valid
        return data, valid, out_dict

    # aggregates over the partition (or running when ordered)
    masked = jnp.where(valid_lane, vals, 0)
    if k in ("count", "count_star"):
        lane = valid_lane.astype(jnp.int64)
        total = jax.ops.segment_sum(lane, pid, num_segments=cap)
        if unbounded_end:
            return jnp.take(total, pid), None
        run = jnp.cumsum(lane)
        base = _part_base(run, lane, part_start, pid)
        return run - base, None
    if k == "sum":
        acc = masked.astype(
            jnp.float64 if vals.dtype in (jnp.float32, jnp.float64)
            else jnp.int64)
        nval = jax.ops.segment_sum(valid_lane.astype(jnp.int64), pid,
                                   num_segments=cap)
        if unbounded_end:
            tot = jax.ops.segment_sum(acc, pid, num_segments=cap)
            return (jnp.take(tot, pid).astype(vals.dtype),
                    jnp.take(nval, pid) > 0)
        run = jnp.cumsum(acc)
        base = _part_base(run, acc, part_start, pid)
        runv = jnp.cumsum(valid_lane.astype(jnp.int64))
        vbase = _part_base(runv, valid_lane.astype(jnp.int64),
                           part_start, pid)
        return ((run - base).astype(vals.dtype), (runv - vbase) > 0)
    if k == "avg":
        acc = masked.astype(jnp.float64)
        cnt = valid_lane.astype(jnp.int64)
        if unbounded_end:
            s = jax.ops.segment_sum(acc, pid, num_segments=cap)
            n = jax.ops.segment_sum(cnt, pid, num_segments=cap)
            s, n = jnp.take(s, pid), jnp.take(n, pid)
        else:
            rs, rn = jnp.cumsum(acc), jnp.cumsum(cnt)
            s = rs - _part_base(rs, acc, part_start, pid)
            n = rn - _part_base(rn, cnt, part_start, pid)
        return s / jnp.maximum(n.astype(jnp.float64), 1.0), n > 0
    if k in ("min", "max"):
        seg = jax.ops.segment_min if k == "min" else jax.ops.segment_max
        if vals.dtype in (jnp.float32, jnp.float64):
            ident = jnp.asarray(jnp.inf if k == "min" else -jnp.inf,
                                vals.dtype)
        else:
            info = jnp.iinfo(vals.dtype if vals.dtype != jnp.bool_
                             else jnp.int32)
            ident = jnp.asarray(info.max if k == "min" else info.min)
        w = jnp.where(valid_lane, vals, ident)
        nval = jax.ops.segment_sum(valid_lane.astype(jnp.int64), pid,
                                   num_segments=cap)
        tot = seg(w, pid, num_segments=cap)
        if unbounded_end:
            return jnp.take(tot, pid), jnp.take(nval, pid) > 0
        # running min/max via associative scan within partitions
        op = jnp.minimum if k == "min" else jnp.maximum
        run = jax.lax.associative_scan(
            lambda a, b: op(a, b), jnp.where(peer_boundary | True, w, w))
        # reset at partition starts: recompute with segmented scan
        run = _segmented_scan(w, pid, op)
        runv = jnp.cumsum(valid_lane.astype(jnp.int64))
        vbase = _part_base(runv, valid_lane.astype(jnp.int64),
                           part_start, pid)
        return run, (runv - vbase) > 0
    raise ValueError(f"window function '{k}' not implemented")


def _running_last_where(pos, flag):
    """For each position, the most recent position where flag was True."""
    marked = jnp.where(flag, pos, jnp.int64(-1))
    return jax.lax.associative_scan(jnp.maximum, marked)


def _part_base(running, lane, part_start, pid):
    """Value of the running sum just before each partition start."""
    start_pos = jnp.take(part_start, pid)
    start_val = jnp.take(running, jnp.clip(start_pos, 0, len(running) - 1))
    start_lane = jnp.take(lane, jnp.clip(start_pos, 0, len(lane) - 1))
    return start_val - start_lane


def _segmented_scan(vals, pid, op):
    """Inclusive segmented scan: restart accumulation at pid changes."""
    pairs = (vals, pid.astype(jnp.int64))

    def combine(a, b):
        av, ai = a
        bv, bi = b
        take_b = bi != ai
        return jnp.where(take_b, bv, op(av, bv)), bi

    out, _ = jax.lax.associative_scan(combine, pairs)
    return out
