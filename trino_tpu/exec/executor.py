"""Single-process plan executor.

Reference parity: the worker execution stack — LocalExecutionPlanner
(sql/planner/LocalExecutionPlanner.java:307) + Driver loop
(operator/Driver.java:355-440) + the operator set (SURVEY.md §2.1).
TPU-first redesign (SURVEY.md §7.2): there is no operator pull-loop; the
executor walks the plan bottom-up, evaluating each node as whole-column
jnp transformations over capacity-padded Batches. XLA fuses chains of
filter/project/aggregate into single device programs; data-dependent
cardinalities (filter/join output sizes) are the only host syncs — the
two-phase "count, pick bucket, expand" pattern of ops/join.py.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field, replace as dc_replace
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..catalog import CatalogManager
from ..columnar import (Batch, Column, StringDictionary, batch_from_pylist,
                        empty_batch, pad_batch)
from ..config import (CONFIG, MemoryLimitExceeded, capacity_for,
                      reserve_bytes)
from ..ops import compact, join as join_ops, sort as sort_ops
from ..ops.groupby import AggInput, global_aggregate, group_aggregate
from ..ops.hashing import hash_columns, partition_of
from ..plan.nodes import (AggregationNode, Aggregate, AssignUniqueIdNode,
                          EnforceSingleRowNode, ExchangeNode, FilterNode,
                          JoinNode, LimitNode, MarkDistinctNode, OffsetNode,
                          OutputNode, PartitionedOutputNode, PlanNode,
                          ProjectNode, RemoteSourceNode, SampleNode,
                          SemiJoinNode, SetOpNode, SortNode, TableScanNode,
                          TopNNode, UnionNode, ValuesNode, WindowNode)
from ..planner.logical import SemiJoinMultiNode
from ..rex import Const, InputRef
from ..session import Session
from ..types import (BIGINT, BOOLEAN, DOUBLE, REAL, DecimalType, Type,
                     is_integral, is_string)
from .expr import EvalError, eval_expr, eval_predicate


class QueryError(Exception):
    """Engine/user-facing failure. ``error_name`` (when set) pins the
    StandardErrorCode name for errors.classify — governance errors
    (memory kills, deadline breaches) must reach the client with their
    Trino identity, not a message-sniffed guess."""

    def __init__(self, message: str,
                 error_name: "Optional[str]" = None):
        super().__init__(message)
        if error_name is not None:
            self.error_name = error_name


class _Pre(PlanNode):
    """Wraps an already-computed Batch so handlers can recurse through
    self.execute() transparently (used by the distributed executors to
    pre-materialize sources and by the remote scheduler to substitute
    gathered fragments). Lives here — NOT in exec/distributed.py — so
    the host-worker dispatch path (exec/remote.py) stays importable
    when the mesh stack (parallel/spmd.py) is unavailable."""

    __slots__ = ("batch",)

    def __init__(self, batch):
        self.batch = batch

    @property
    def sources(self):
        return ()

    def output_schema(self):
        return self.batch.schema()


@dataclass
class NodeStats:
    """OperatorStats analog (operator/OperatorStats.java): per-plan-node
    wall time, row/byte flow, compile (jit-trace) wall, device time
    (jitted-dispatch completion, distinct from wall — the tensor-
    runtime headline split), thread-CPU time, and cache-hit flags,
    powering EXPLAIN ANALYZE, /v1/query/{id}, and the distributed
    stats rollup (workers serialize these in task results; the
    coordinator merges them per stage — see merge_node_stats)."""
    name: str
    detail: str = ""
    wall_s: float = 0.0
    output_rows: int = -1
    input_rows: int = -1
    input_bytes: int = -1
    output_bytes: int = -1
    compile_s: float = 0.0
    cache_hit: Optional[bool] = None
    # device seconds this node's jitted dispatches spent (block-until-
    # ready deltas, exec/executor.py _jit_call) — own dispatches only,
    # NOT children's (unlike wall, which nests)
    device_s: float = 0.0
    # thread-CPU seconds across this node's execution (includes
    # children, like wall — the two are directly comparable)
    cpu_s: float = 0.0

    def to_dict(self) -> dict:
        return {"name": self.name, "detail": self.detail,
                "wall_s": self.wall_s, "output_rows": self.output_rows,
                "input_rows": self.input_rows,
                "input_bytes": self.input_bytes,
                "output_bytes": self.output_bytes,
                "compile_s": self.compile_s,
                "cache_hit": self.cache_hit,
                "device_s": self.device_s,
                "cpu_s": self.cpu_s}

    @staticmethod
    def from_dict(d: dict) -> "NodeStats":
        return NodeStats(
            d.get("name", "?"), d.get("detail", ""),
            float(d.get("wall_s", 0.0)), int(d.get("output_rows", -1)),
            int(d.get("input_rows", -1)), int(d.get("input_bytes", -1)),
            int(d.get("output_bytes", -1)),
            float(d.get("compile_s", 0.0)), d.get("cache_hit"),
            float(d.get("device_s", 0.0)), float(d.get("cpu_s", 0.0)))


def _sum_counts(vals: Sequence[int]) -> int:
    known = [v for v in vals if v is not None and v >= 0]
    return sum(known) if known else -1


def merge_node_stats(
        per_worker: Sequence[Sequence["NodeStats"]]) -> List["NodeStats"]:
    """Roll worker-reported per-node stats up into one per-stage list.
    Every worker executed the SAME fragment plan, but fast paths
    (streaming aggregation fuses scan+agg into one entry; an empty
    split share takes the generic path) mean the lists need not align
    positionally — entries merge by (node name, occurrence index),
    ordered by the most detailed worker's list. Rows/bytes sum across
    workers (they partition the input); wall and compile take the max
    (the stage's critical path — tasks run concurrently); cache_hit
    ANDs (one cold worker means the stage paid a compile)."""
    lists = [list(l) for l in per_worker if l]
    if not lists:
        return []

    def keyed(l: Sequence["NodeStats"]):
        seen: Dict[str, int] = {}
        out = []
        for s in l:
            i = seen.get(s.name, 0)
            seen[s.name] = i + 1
            out.append(((s.name, i), s))
        return out

    base = max(lists, key=len)
    by_key: Dict[tuple, List[NodeStats]] = {}
    extras: List[tuple] = []
    # base is first in the stable descending sort, so keys discovered
    # in OTHER lists are by construction not base keys
    for l in sorted(lists, key=len, reverse=True):
        for k, s in keyed(l):
            if k not in by_key:
                by_key[k] = []
                if l is not base:
                    extras.append(k)  # go after base's order
            by_key[k].append(s)
    order = [k for k, _ in keyed(base)] + extras
    merged: List[NodeStats] = []
    for k in order:
        same = by_key[k]
        hits = [s.cache_hit for s in same if s.cache_hit is not None]
        merged.append(NodeStats(
            same[0].name, same[0].detail,
            max(s.wall_s for s in same),
            _sum_counts([s.output_rows for s in same]),
            _sum_counts([s.input_rows for s in same]),
            _sum_counts([s.input_bytes for s in same]),
            _sum_counts([s.output_bytes for s in same]),
            max(s.compile_s for s in same),
            all(hits) if hits else None,
            # device/CPU are RESOURCE totals: tasks run concurrently
            # on different devices/cores, so the stage consumed the
            # SUM (wall takes the max — the critical path)
            sum(s.device_s for s in same),
            sum(s.cpu_s for s in same)))
    return merged


def render_analyze_lines(plan_lines, stats, trace) -> List[str]:
    """The EXPLAIN ANALYZE text body: plan tree, per-node stats, and
    the span-tree section — one renderer shared by the local runner
    and the distributed host runner so the formats cannot drift."""
    lines = list(plan_lines or [])
    lines.append("")
    lines.extend(stats_lines(stats or []))
    if trace is not None and trace.roots:
        lines.append("")
        lines.append("Trace:")
        lines.extend(trace.lines())
    return lines


def stats_lines(stats: Sequence["NodeStats"]) -> List[str]:
    """EXPLAIN ANALYZE text rendering of a NodeStats list (reference:
    planprinter/PlanPrinter's textDistributedPlan stats columns)."""
    out = []
    for s in stats:
        parts = [f"{s.name}: {s.wall_s * 1000:.2f}ms"]
        if s.input_rows >= 0:
            parts.append(f"in {s.input_rows} rows"
                         + (f"/{s.input_bytes}B"
                            if s.input_bytes >= 0 else ""))
        parts.append(f"out {s.output_rows} rows"
                     + (f"/{s.output_bytes}B"
                        if s.output_bytes >= 0 else ""))
        if s.compile_s > 0:
            parts.append(f"compile {s.compile_s * 1000:.2f}ms")
        if s.device_s > 0:
            # device time ≠ wall: the jitted dispatches' completion
            # wait, the number that explains tensor-engine latency
            parts.append(f"device {s.device_s * 1000:.2f}ms")
        if s.cpu_s > 0:
            parts.append(f"cpu {s.cpu_s * 1000:.2f}ms")
        if s.cache_hit is not None:
            parts.append("cache hit" if s.cache_hit else "cache miss")
        if s.detail:
            parts.append(s.detail)
        out.append(", ".join(parts))
    return out


# plan nodes whose _apply_ is pure jnp (traceable): a chain of these over
# one source compiles into a single XLA program — the reference's
# "one bytecode class per pipeline" (ExpressionCompiler) as jax.jit
# (SURVEY.md §7.2)
_TRACEABLE = ()  # filled after class definition
_PPOS, _BPOS = "__probe_pos$", "__build_pos$"

# cross-query caches of jitted plan programs, keyed by canonical
# program key (exec/progkey.py); deny-lists for plans whose chains
# touch host-only evaluation paths. Reference analog: the generated-
# class caches of sql/gen/ExpressionCompiler.java (keyed on
# RowExpression trees) — re-tracing an identical plan costs ~2s/query
# through the persistent-compilation-cache path on a tunneled chip.
_STREAM_JIT_CACHE: Dict[tuple, object] = {}
_STREAM_JIT_DENY: set = set()
_CHAIN_JIT_CACHE: Dict[tuple, object] = {}
_CHAIN_JIT_DENY: set = set()
# ragged multi-query batch programs (canonical chain + the __rq
# provenance lane threaded through, exec/progkey.py ragged_nodes):
# keyed on the canonical chain key — jax specializes per combined
# capacity under one callable, same as the solo chain cache
_RAGGED_JIT_CACHE: Dict[tuple, object] = {}
# window programs (execute_window over one canonical WindowNode) and
# the two-phase materialized hash-join programs (count + expand over
# ops/join.py) — the "window" and "join" AOT kinds of exec/aot.py
_WINDOW_JIT_CACHE: Dict[tuple, object] = {}
_WINDOW_JIT_DENY: set = set()
_MJOIN_JIT_CACHE: Dict[tuple, object] = {}
_MJOIN_JIT_DENY: set = set()

# process metrics (obs/metrics.py; scraped at GET /metrics). These are
# per-query-phase increments, never per-row — the lock cost is noise.
from ..obs.metrics import METRICS as _METRICS
# the jit-cache family is defined ONCE in obs/metrics.py (streamjoin's
# probe-program cache feeds the same family — a second registration
# here would trip the metrics-hygiene lint)
from ..obs.metrics import JIT_CACHE_LOOKUPS as _M_JIT
_M_SCAN = _METRICS.counter(
    "trino_tpu_scan_cache_total",
    "HBM-resident scan cache lookups by granularity and outcome",
    ("cache", "result"))
_M_SCAN_BYTES = _METRICS.gauge(
    "trino_tpu_scan_cache_bytes",
    "Bytes of table lanes resident in the scan cache", ("connector",))
_M_SPILL = _METRICS.counter(
    "trino_tpu_spill_bytes_total",
    "Bytes written to host RAM by oversized-join spill")
_M_SPLITS = _METRICS.counter(
    "trino_tpu_splits_read_total", "Table splits read by the executor")


# volatility lives in rex (a property of expressions, shared with the
# planner); these aliases keep the executor-local names working
from ..rex import VOLATILE_FNS as _VOLATILE_FNS, \
    expr_volatile as _expr_volatile


# structural node fingerprints + the canonical program keys built on
# them live in exec/progkey.py — ONE canonicalizer shared by the
# in-process caches here, the hot-shape registry (exec/hotshapes.py),
# and the AOT compiler (exec/aot.py)

import threading as _jit_threading

_JIT_CACHE_LOCK = _jit_threading.Lock()

_M_JIT_EVICT = _METRICS.counter(
    "trino_tpu_jit_cache_evictions_total",
    "Structural jitted-program cache entries evicted at capacity "
    "(TRINO_TPU_JIT_CACHE_ENTRIES)")


def _cache_put(cache: Dict[tuple, object], key: tuple, val) -> None:
    # the coordinator runs one thread per query (server/coordinator.py)
    # — insert-with-eviction must not race another thread's eviction
    with _JIT_CACHE_LOCK:
        limit = max(int(CONFIG.jit_cache_entries), 1)
        while len(cache) >= limit:
            try:
                cache.pop(next(iter(cache)))
                _M_JIT_EVICT.inc()
            except (KeyError, StopIteration):
                break
        cache[key] = val


def _keys_inexact(cols, keys) -> bool:
    """True when the uint64 equality lane of ops/join.py cannot be
    bijective for these keys: multi-column (hash-combined), float
    (hash-converted), or Int128 decimal (only the low lane is hashed)."""
    if len(keys) > 1:
        return True
    c = cols[keys[0]]
    return c.data2 is not None or np.asarray(c.data).dtype.kind == "f"


def join_verify_filter(left_cols, right_cols, pkeys, bkeys, filt):
    """Hash-collision re-verification (reference: JoinProbe verifies
    candidate positions by real key equality, never by hash alone).
    When the key lane is inexact, append key-equality conjuncts to the
    residual filter; the residual join path then drops collision rows
    and repairs outer rows from the surviving match set."""
    if not (_keys_inexact(left_cols, pkeys)
            or _keys_inexact(right_cols, bkeys)):
        return filt
    from ..rex import Call as _RCall, and_all
    eqs = [
        _RCall("=", (InputRef(pk, left_cols[pk].type),
                     InputRef(bk, right_cols[bk].type)), BOOLEAN)
        for pk, bk in zip(pkeys, bkeys)]
    return and_all(([filt] if filt is not None else []) + eqs)


class Executor:
    def __init__(self, catalogs: CatalogManager, session: Session,
                 collect_stats: bool = False,
                 fragment_jit: Optional[bool] = None):
        self.catalogs = catalogs
        self.session = session
        self.collect_stats = collect_stats
        self.stats: List[NodeStats] = []
        if fragment_jit is None:
            # eager dispatch through the device tunnel is the bottleneck
            # on TPU; on CPU the compile cost dominates short queries.
            # TRINO_TPU_FRAGMENT_JIT=1|0 overrides the backend default
            # (a CPU fleet serving REPEATED shapes amortizes compiles
            # through the canonical-key caches + persistent cache, and
            # the warm-path tests exercise exactly that)
            env = os.environ.get("TRINO_TPU_FRAGMENT_JIT", "")
            if env in ("0", "1"):
                fragment_jit = env == "1"
            else:
                fragment_jit = jax.default_backend() not in ("cpu",)
        self.fragment_jit = fragment_jit
        self._no_jit_chains: set = set()
        self._jit_chains: dict = {}
        # per-query telemetry accumulators (obs/): stat frames track
        # each node's input flow (children add their output on exit);
        # peak/spill feed the enriched QueryCompletedEvent
        self._frames: List[dict] = []
        self.peak_reserved_bytes: int = 0
        self.spilled_bytes: int = 0
        # morsel streaming (exec/streamjoin.py): chunks processed and
        # host->device bytes moved by streamed operators this query —
        # exported in worker task status (streamChunks/streamH2dBytes)
        # and rolled up by the remote/stage schedulers
        self.stream_chunks: int = 0
        self.stream_h2d_bytes: int = 0
        # ragged multi-query batching (exec/taskexec.py RaggedBatcher):
        # chain dispatches this query served through a co-batched
        # ragged program — exported in worker task status
        # (raggedBatched) and rolled up by the remote/stage schedulers
        self.ragged_batched: int = 0
        # device-time attribution (ISSUE 15): seconds this executor's
        # jitted dispatches spent to data-ready (_jit_call block-until-
        # ready deltas), exported as deviceSeconds in worker task
        # status and rolled up per stage — the number distinct from
        # wall that explains tensor-engine latency
        self.device_s: float = 0.0
        # > 0 while a morsel-streamed chunk loop is driving dispatches
        # (exec/streamjoin.py run_streamed): device timing's block-
        # until-ready would serialize the double-buffered overlap, so
        # streamed chunks forgo device attribution — the overlap
        # contract outranks it
        self._stream_depth: int = 0
        # remote-task split addressing: (part, nparts) makes every scan
        # read only splits with index % nparts == part (the worker's
        # share of a fragment — server/task_worker.py fragment payloads;
        # reference: SqlStageExecution assigning splits to tasks)
        self.scan_partition: Optional[Tuple[int, int]] = None
        # stage-DAG exchange input (trino_tpu/stage/): fid -> batches
        # of this task's partition of upstream stage ``fid``'s output
        # (the ExchangeOperator hook; wired by server/task_worker.py
        # for worker stage tasks and by exec/remote.py for the
        # coordinator's root stage)
        self.exchange_reader = None

    def _detached(self) -> "Executor":
        """Lightweight clone captured by closures that outlive this
        query in the structural JIT caches: shares catalogs/session but
        carries no per-query jit/stats state, so a cached program does
        not pin its first query's executor object graph."""
        return Executor(self.catalogs, self.session)

    @property
    def trace(self):
        """The current query's span tree (obs/trace.py), carried on the
        Session by the runner; None outside a traced query."""
        return getattr(self.session, "trace", None)

    # ------------------------------------------------------------------
    def execute(self, node: PlanNode) -> Batch:
        cancel = getattr(self.session, "cancel", None)
        if cancel is not None and cancel.is_set():
            # cooperative cancellation between plan nodes (reference:
            # Driver loop checks the yield/termination signal)
            raise QueryError("Query was canceled")
        deadline = getattr(self.session, "deadline", None)
        if deadline is not None and time.monotonic() > deadline:
            # deadline enforcement at the same granularity as cancel:
            # a breach stops execution between plan nodes instead of
            # waiting for the coordinator's next poll
            raise QueryError(
                "Query exceeded the maximum run time "
                "(query_max_run_time)", error_name="EXCEEDED_TIME_LIMIT")
        yld = getattr(self.session, "split_yield", None)
        if yld is not None:
            # shared split scheduler (exec/taskexec.py): a plan-node
            # boundary is a yield point too — operators without split
            # or chunk loops (exchange-fed joins, sorts) still hand
            # the runner slot to a higher-priority query's task here
            yld()
        if not self.collect_stats:
            return self._execute_inner(node)
        return self._stats_wrap(node, lambda: self._execute_inner(node))

    def _stats_wrap(self, node: PlanNode, fn):
        """Time one node's execution and record a NodeStats entry.
        A frame on the stack accumulates this node's input flow: every
        child node adds its own output rows/bytes to the parent frame
        on exit, and split reads add the scanned rows directly."""
        frame = {"rows": 0, "bytes": 0, "compile_s": 0.0, "cache": None,
                 "device_s": 0.0}
        self._frames.append(frame)
        t0 = time.perf_counter()
        cpu0 = time.thread_time()
        try:
            out = fn()
        finally:
            self._frames.pop()
        # CPU before the blocking row read below: the host decode of
        # the output is accounting overhead, not the operator's work
        cpu_s = max(time.thread_time() - cpu0, 0.0)
        # blocking read for accurate per-node timing
        n = (out.total_rows_host() if hasattr(out, "total_rows_host")
             else out.num_rows_host())
        obytes = sum(_col_bytes(c) for c in out.columns.values())
        name = type(node).__name__.replace("Node", "")
        if not name.startswith("_"):
            # internal wrappers (_Pre preloaded batches) are plumbing,
            # not operators — they feed the parent's input, no entry
            detail = ""
            if frame.get("stream_chunks"):
                # morsel streaming: chunk count + transfer volume per
                # operator, the EXPLAIN ANALYZE face of streamjoin.py
                detail = (f"streamed {frame['stream_chunks']} chunks, "
                          f"{frame.get('stream_h2d', 0)}B h2d")
            self.stats.append(NodeStats(
                name, detail, wall_s=time.perf_counter() - t0,
                output_rows=n,
                input_rows=frame["rows"], input_bytes=frame["bytes"],
                output_bytes=obytes, compile_s=frame["compile_s"],
                cache_hit=frame["cache"],
                device_s=frame["device_s"], cpu_s=cpu_s))
        if self._frames:
            parent = self._frames[-1]
            parent["rows"] += n
            parent["bytes"] += obytes
        return out

    def _jit_call(self, jitted, args: tuple, cache: str, hit: bool):
        """Invoke a jitted program, separating jit_trace (first, cache-
        miss call: trace + XLA compile + execute) from device_execute
        (steady state) in the query trace, attributing compile wall to
        the current node's stats frame, and measuring DEVICE time
        distinct from wall: jax dispatch is async, so the delta from
        dispatch return to ``jax.block_until_ready`` is the device's
        completion wait (the fallback ISSUE 15 names; a real XLA-
        profiler hook would refine, not replace, this number). On a
        sync backend the dispatch itself runs the program, so a
        cache-hit call's whole span is device work. The extra sync
        only happens under telemetry — the stats fence next to it
        already syncs per node, so the no-telemetry path keeps jax's
        async pipeline untouched. AOT-compiled programs (exec/aot.py)
        additionally surface XLA's cost analysis (flops) on the
        span."""
        tr = self.trace
        if tr is None and not self.collect_stats:
            return jitted(*args)
        t0 = time.perf_counter()
        t1 = dev_s = None
        try:
            out = jitted(*args)
            t1 = time.perf_counter()
            if self._stream_depth == 0:
                # device attribution syncs — inside a streamed chunk
                # loop that sync would serialize the double-buffered
                # transfer/compute overlap, so streamed dispatches
                # skip it (their chunks report wall only)
                try:
                    jax.block_until_ready(out)
                except Exception:   # noqa: BLE001 — non-array outputs
                    pass
                t2 = time.perf_counter()
                # hit: the whole dispatch-to-ready window is device
                # work; miss: only the post-trace completion wait is
                # (the trace+compile share lands in compile_s below)
                dev_s = (t2 - t0) if hit else (t2 - t1)
            return out
        finally:
            tend = time.perf_counter()
            if t1 is None:
                t1 = tend
            if tr is not None:
                attrs = {"cache": cache}
                if dev_s is not None:
                    attrs["device_ms"] = round(dev_s * 1000, 3)
                if not hit:
                    try:    # AOT Compiled objects carry cost analysis
                        ca = getattr(jitted, "cost_analysis", None)
                        if ca is not None:
                            c = ca()
                            c = c[0] if isinstance(c, (list, tuple)) \
                                else c
                            if c and c.get("flops"):
                                attrs["flops"] = float(c["flops"])
                    except Exception:   # noqa: BLE001 — advisory
                        pass
                tr.record("device_execute" if hit else "jit_trace",
                          t0, tend, **attrs)
            if dev_s:
                self.device_s += dev_s
                if self._frames:
                    self._frames[-1]["device_s"] += dev_s
            if not hit and self._frames:
                self._frames[-1]["compile_s"] += t1 - t0
                if self._frames[-1]["cache"] is None:
                    self._frames[-1]["cache"] = False
            elif hit and self._frames \
                    and self._frames[-1]["cache"] is None:
                self._frames[-1]["cache"] = True

    def _read_split(self, conn, split, columns) -> Batch:
        """Split read with telemetry: wall-timed for the
        SplitCompletedEvent (fired when the session carries an event
        manager — the task/split completion path), counted into the
        metrics registry, and charged to the current node's input."""
        t0 = time.perf_counter()
        b = read_split_cached(conn, split, columns)
        wall = time.perf_counter() - t0
        _M_SPLITS.inc()
        if self.collect_stats and self._frames:
            self._frames[-1]["rows"] += b.num_rows_host()
            self._frames[-1]["bytes"] += sum(
                _col_bytes(c) for c in b.columns.values())
        events = getattr(self.session, "events", None)
        if events is not None:
            from ..server.events import SplitCompletedEvent
            h = split.handle
            events.split_completed(SplitCompletedEvent(
                getattr(self.session, "query_id", "") or "",
                f"{h.catalog}.{h.schema}.{h.table}"
                f"[{split.part}/{split.part_count}]", wall))
        yld = getattr(self.session, "split_yield", None)
        if yld is not None:
            # a completed split IS the scheduler quantum (exec/
            # taskexec.py): account it and maybe hand the runner slot
            # to a higher-priority query's task before the next split
            yld()
        return b

    def _execute_inner(self, node: PlanNode) -> Batch:
        if isinstance(node, (FilterNode, ProjectNode)):
            # beyond-HBM morsel streaming (exec/streamjoin.py): a
            # Filter/Project chain over a scan whose materialization
            # estimate exceeds the memory budget streams fixed-capacity
            # chunks through the (one) compiled chain program instead
            # of raising the memory error
            from .streamjoin import maybe_stream_chain
            streamed = maybe_stream_chain(self, node)
            if streamed is not None:
                return streamed
        if isinstance(node, AggregationNode):
            streamed = self._try_streaming_aggregation(node)
            if streamed is not None:
                return streamed
            masked = self._try_masked_filter_aggregation(node)
            if masked is not None:
                return masked
        if self.fragment_jit and isinstance(node, _TRACEABLE):
            chain = []
            cur = node
            # aggregations are a chain BARRIER, not a link: executing
            # them through _execute_inner gives them their own fused
            # program with selection-vector filter->aggregate fusion
            # (no 8M-row compaction gather) + the whole-table fast path;
            # the chain above jits over the small aggregated output
            while isinstance(cur, _TRACEABLE) \
                    and not isinstance(cur, AggregationNode):
                chain.append(cur)
                cur = cur.source
            if chain:
                # canonical program key (exec/progkey.py): renamed
                # symbols and reordered columns land on ONE cached
                # program; plans outside the canonical subset keep
                # per-query identity keys
                from .progkey import canonicalize_nodes
                canon = canonicalize_nodes(chain)
                structural = canon is not None
                key = canon.key if structural \
                    else tuple(id(n) for n in chain)
                base = self.execute(cur)
                if key not in self._no_jit_chains \
                        and key not in _CHAIN_JIT_DENY:
                    try:
                        return self._run_chain_jit(key, chain, base,
                                                   structural, canon)
                    except (jax.errors.TracerArrayConversionError,
                            jax.errors.ConcretizationTypeError):
                        # chain touches host-only paths (row-
                        # materializing string fns); run it eagerly
                        # from here on
                        self._no_jit_chains.add(key)
                        if structural:
                            _CHAIN_JIT_CACHE.pop(key, None)
                            _CHAIN_JIT_DENY.add(key)
                b = base
                for nd in reversed(chain):
                    b = self._dispatch_apply(nd, b)
                return b
        method = getattr(self, "_exec_" + type(node).__name__, None)
        if method is None:
            raise QueryError(
                f"no executor for plan node {type(node).__name__}")
        try:
            return method(node)
        except EvalError as e:
            raise QueryError(str(e)) from e

    # ------------------------------------------------------------------
    # streaming aggregation over scan splits (grouped execution analog:
    # execution/Lifespan.java + SpillableHashAggregationBuilder — bound
    # memory by aggregating split-by-split with one compiled program,
    # then combining partials)
    # ------------------------------------------------------------------
    _STREAM_CHAIN = None   # set after class body

    _NONSTREAMABLE = {"min_by", "max_by", "approx_distinct",
                      "approx_percentile", "array_agg", "map_agg",
                      "histogram", "approx_most_frequent",
                      "approx_set", "merge", "map_union", "multimap_agg",
                      "numeric_histogram", "tdigest_agg", "qdigest_agg"}

    def _try_streaming_aggregation(self, node: AggregationNode):
        # kinds whose partials don't combine with a single-lane segment
        # op need all rows at once — no split-streaming for them
        if any(a.distinct or a.kind in self._NONSTREAMABLE
               for a in node.aggregates.values()):
            return None
        chain = []
        cur = node.source
        while isinstance(cur, self._STREAM_CHAIN):
            chain.append(cur)
            cur = cur.source
        if not isinstance(cur, TableScanNode):
            return None
        conn = self.catalogs.connector(cur.handle.catalog)
        par = int(self.session.get("task_concurrency")) or 1
        columns = sorted(set(cur.assignments.values()))
        # beyond-HBM chunking (exec/streamjoin.py): when the scan's
        # materialization estimate exceeds the memory budget (or
        # stream_chunk_rows forces it), split batches are further cut
        # into fixed-capacity chunks streamed through double-buffered
        # transfers, with periodic partial folding so the accumulated
        # partial set stays bounded too
        from .streamjoin import agg_chunk_capacity
        stream_cap = agg_chunk_capacity(self, cur)
        # whole-table fast path: when the table is (or fits) HBM-
        # resident, the filter->project->aggregate chain runs as ONE
        # device program over all rows — the hand-fused micro's shape —
        # instead of one dispatch per split through the tunnel
        whole = (None if self.scan_partition is not None
                 or stream_cap is not None
                 else read_table_cached(conn, cur.handle, columns, par))
        raws: Optional[List[Batch]] = None
        if whole is not None:
            raws = [whole]
        elif stream_cap is None:
            # the chunked branch never reads this split list —
            # host_scan_chunks enumerates (and share-filters) its own,
            # and an empty share simply yields zero partials below
            splits = conn.get_splits(cur.handle, par)
            if self.scan_partition is not None:
                part, nparts = self.scan_partition
                splits = [s for i, s in enumerate(splits)
                          if i % nparts == part]
                if not splits:
                    return None    # generic path emits the empty batch
            if len(splits) < 2 and self.scan_partition is None:
                return None
        partials: List[Batch] = []
        phys = post = None
        helper = self._detached()   # closures below are cached

        # canonical program (exec/progkey.py): under fragment_jit the
        # closures execute the CANONICAL node stack — renamed symbols /
        # reordered columns across queries land on one cached program
        # and one persistent-cache entry — with the input batches
        # renamed through the plan's binding and the output renamed
        # back once at the end. Plans outside the canonical subset
        # keep the original nodes and a per-execution program.
        canon = binding = None
        node_x, chain_x = node, chain
        if self.fragment_jit:
            from .progkey import canonicalize_nodes
            canon = canonicalize_nodes([node] + chain)
            if canon is not None:
                node_x, chain_x = canon.nodes[0], canon.nodes[1:]
        fkey = canon.key if canon is not None else None

        run, run_full = make_stream_runners(helper, chain_x, node_x)

        def bind(b: Batch) -> Batch:
            nonlocal binding
            if canon is None:
                return b
            if binding is None:
                binding = canon.binding(b)
            return binding.rename_in(b)

        def unbind(b: Batch) -> Batch:
            return b if binding is None else binding.rename_out(b)

        if raws is not None and len(raws) == 1 and self.fragment_jit:
            fullkey = None if fkey is None else (fkey, "full")
            if fullkey not in _STREAM_JIT_DENY:
                full_jit = (_STREAM_JIT_CACHE.get(fullkey)
                            if fullkey is not None else None)
                full_hit = full_jit is not None
                if fullkey is not None:
                    # only real cache lookups count — an uncacheable
                    # plan (no structural key) is not a miss
                    _M_JIT.inc(cache="stream",
                               result="hit" if full_hit else "miss")
                if full_jit is None:
                    full_jit = jax.jit(run_full)
                    if fullkey is not None:
                        _cache_put(_STREAM_JIT_CACHE, fullkey, full_jit)
                batch = bind(Batch(
                    {sym: raws[0].column(col)
                     for sym, col in cur.assignments.items()},
                    raws[0].num_rows))
                if fullkey is not None:
                    from .hotshapes import record_program
                    record_program("stream_full", fullkey, canon,
                                   batch, self.session)
                try:
                    return unbind(self._jit_call(
                        full_jit, (batch,), "stream", full_hit))
                except (jax.errors.TracerArrayConversionError,
                        jax.errors.ConcretizationTypeError):
                    if fullkey is not None:
                        _STREAM_JIT_CACHE.pop(fullkey, None)
                        _STREAM_JIT_DENY.add(fullkey)

        # one jitted program serves every split (uniform capacities);
        # the program is cached across QUERIES by canonical program
        # key so a repeated query skips re-trace + executable reload
        # (~2s/query through the persistent-cache path, measured on
        # the tunnel)
        run_jit = None
        jit_hit = False
        recorded = False
        if self.fragment_jit:
            if fkey is not None and fkey not in _STREAM_JIT_DENY:
                run_jit = _STREAM_JIT_CACHE.get(fkey)
                jit_hit = run_jit is not None
                _M_JIT.inc(cache="stream",
                           result="hit" if jit_hit else "miss")
            if run_jit is None and fkey not in _STREAM_JIT_DENY:
                run_jit = jax.jit(run)
                if fkey is not None:
                    _cache_put(_STREAM_JIT_CACHE, fkey, run_jit)
        def consume(batch: Batch) -> Batch:
            nonlocal phys, post, recorded, run_jit, jit_hit
            batch = bind(batch)
            if fkey is not None and not recorded \
                    and fkey not in _STREAM_JIT_DENY:
                # deny-listed programs must not climb the pre-warm
                # ranking: every joining worker would burn a top-K
                # slot AOT-compiling a shape that cannot trace
                from .hotshapes import record_program
                record_program("stream", fkey, canon, batch,
                               self.session)
                recorded = True
            if phys is None:
                phys, post, _ = _lower_aggregates(node_x.aggregates,
                                                  batch)
            if run_jit is not None:
                try:
                    out = self._jit_call(run_jit, (batch,), "stream",
                                         jit_hit)
                    jit_hit = True   # later splits reuse the program
                except (jax.errors.TracerArrayConversionError,
                        jax.errors.ConcretizationTypeError):
                    run_jit = None
                    if fkey is not None:
                        _STREAM_JIT_CACHE.pop(fkey, None)
                        _STREAM_JIT_DENY.add(fkey)
                    out = run(batch)
            else:
                out = run(batch)
            return out

        from ..ops.groupby import COMBINABLE_KINDS

        def make_finals():
            return [AggInput(COMBINABLE_KINDS[a.kind], a.output, None,
                             a.output) for a in phys]

        if stream_cap is not None:
            from .streamjoin import (_row_bytes, host_scan_chunks,
                                     run_streamed)
            # streamed peak: 2 in-flight chunk buffers + the bounded
            # partial set the fold keeps (<= 8 chunk-capacity partials)
            self._reserve_streamed(
                10 * stream_cap * _row_bytes(cur.schema),
                f"chunk-streamed aggregation over {cur.handle.table} "
                f"(chunk capacity {stream_cap})")

            def fold() -> None:
                # re-combine the accumulated partials into one batch
                # (combine kinds are idempotent under re-combination:
                # sum/min/max/any) so memory stays bounded by the
                # fold window, not the chunk count
                nonlocal partials
                m = device_concat(partials)
                fin = make_finals()
                if node_x.group_keys:
                    g = group_aggregate(m, list(node_x.group_keys),
                                        fin)
                else:
                    g = _pad_partial(global_aggregate(m, fin))
                partials = [g]

            def collect(out: Batch, i: int) -> None:
                partials.append(out)
                if len(partials) >= 8:
                    fold()

            run_streamed(self, "agg",
                         host_scan_chunks(self, cur, stream_cap),
                         lambda chunk, i: consume(chunk), collect)
            if not partials:
                return None    # empty scan: generic path emits empty
        else:
            for raw in (raws if raws is not None else
                        (self._read_split(conn, sp, columns)
                         for sp in splits)):
                partials.append(consume(Batch(
                    {sym: raw.column(col)
                     for sym, col in cur.assignments.items()},
                    raw.num_rows)))
        merged = device_concat(partials)
        finals = make_finals()
        if node_x.group_keys:
            out = group_aggregate(merged, list(node_x.group_keys),
                                  finals)
        else:
            out = global_aggregate(merged, finals)
        if post:
            cols = dict(out.columns)
            for sym, fn in post.items():
                cols[sym] = fn(out)
            keep = set(node_x.group_keys) | set(node_x.aggregates)
            cols = {s: c for s, c in cols.items() if s in keep}
            out = Batch(cols, out.num_rows)
        return unbind(out)

    # ------------------------------------------------------------------
    # masked (selection-vector) filter -> aggregation fusion: filters
    # below an aggregation become a liveness mask consumed directly by
    # the aggregation kernels instead of a nonzero+gather compaction
    # (reference keeps selected-positions arrays inside PageProcessor for
    # the same reason — operator/project/PageProcessor.java; on TPU the
    # compaction gather costs seconds at SF1 row counts, the mask is
    # free)
    # ------------------------------------------------------------------
    def _masked_chain_eval(self, chain, b: Batch):
        """Evaluate a Filter/Project/Sample chain over ``b`` WITHOUT
        compacting: returns (columns, live-mask). Dead rows compute
        garbage values that the downstream mask consumer ignores."""
        live = b.row_valid()
        cols = dict(b.columns)
        cap = b.capacity
        for nd in reversed(chain):
            # num_rows=cap -> row_valid() is all-true inside expression
            # eval; the real liveness is tracked in `live`
            bb = Batch(cols, cap)
            if isinstance(nd, FilterNode):
                live = live & eval_predicate(nd.predicate, bb)
            elif isinstance(nd, SampleNode):
                from ..ops.hashing import mix64
                h = mix64(jnp.arange(cap, dtype=jnp.uint64))
                u = (h >> jnp.uint64(11)).astype(jnp.float64) \
                    / float(1 << 53)
                live = live & (u < nd.ratio)
            else:
                cols = {s: eval_expr(e, bb)
                        for s, e in nd.assignments.items()}
        return cols, live

    def _try_masked_filter_aggregation(self, node: AggregationNode):
        chain: List[PlanNode] = []
        cur = node.source
        while isinstance(cur, (FilterNode, ProjectNode, SampleNode)):
            chain.append(cur)
            cur = cur.source
        if not any(isinstance(n, (FilterNode, SampleNode))
                   for n in chain):
            return None
        base = self.execute(cur)

        def run(b: Batch) -> Batch:
            cols, live = self._masked_chain_eval(chain, b)
            nlive = jnp.sum(live.astype(jnp.int64))
            src = Batch(cols, nlive)
            phys, post, extra_cols = _lower_aggregates(
                node.aggregates, src)
            if extra_cols:
                c2 = dict(src.columns)
                c2.update(extra_cols)
                src = Batch(c2, nlive)
            if node.group_keys:
                out = group_aggregate(src, list(node.group_keys), phys,
                                      live=live)
            elif phys:
                out = global_aggregate(src, phys, live=live)
            else:
                return _single_row(src)
            if post:
                oc = dict(out.columns)
                for sym, fn in post.items():
                    oc[sym] = fn(out)
                keep = set(node.group_keys) | set(node.aggregates)
                oc = {s: c for s, c in oc.items() if s in keep}
                out = Batch(oc, out.num_rows)
            return out

        if not self.fragment_jit:
            try:
                return run(base)
            except EvalError as e:
                raise QueryError(str(e)) from e
        key = ("masked", id(node))
        if key in self._no_jit_chains:
            return run(base)
        jitted = self._jit_chains.get(key)
        hit = jitted is not None
        _M_JIT.inc(cache="masked", result="hit" if hit else "miss")
        if jitted is None:
            jitted = jax.jit(run)
            self._jit_chains[key] = jitted
        try:
            return self._jit_call(jitted, (base,), "masked", hit)
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            # host-materializing expressions in the chain: run eagerly
            self._no_jit_chains.add(key)
            return run(base)
        except EvalError as e:
            raise QueryError(str(e)) from e

    def _dispatch_apply(self, node: PlanNode, src: Batch) -> Batch:
        try:
            return getattr(self, "_apply_" + type(node).__name__)(
                node, src)
        except EvalError as e:
            raise QueryError(str(e)) from e

    def _run_chain_jit(self, key, chain, base: Batch,
                       structural: bool = False, canon=None) -> Batch:
        # cache the jitted callable per chain so repeated executions of
        # the same plan reuse the compiled XLA program (jax.jit's cache
        # is keyed on function identity). Structural keys live in a
        # module-level cache shared ACROSS queries; identity keys stay
        # per-executor (they can't outlive their plan objects safely).
        # Structural programs execute the CANONICAL node stack with the
        # input/output columns renamed through the plan's binding
        # (exec/progkey.py) — the traced jaxpr is identical across
        # renamed plans, so jax's persistent compilation cache is
        # effectively keyed on the canonical program too.
        cache = _CHAIN_JIT_CACHE if structural else self._jit_chains
        jitted = cache.get(key)
        hit = jitted is not None
        _M_JIT.inc(cache="chain", result="hit" if hit else "miss")
        if jitted is None:
            helper = self._detached() if structural else self
            nodes = canon.nodes if structural else chain

            def fn(b):
                for nd in reversed(nodes):
                    b = helper._dispatch_apply(nd, b)
                return b
            jitted = jax.jit(fn)
            if structural:
                _cache_put(_CHAIN_JIT_CACHE, key, jitted)
            else:
                cache[key] = jitted
        if structural:
            binding = canon.binding(base)
            cb = binding.rename_in(base)
            from .hotshapes import record_program
            # record the SOLO canonical program: the hot shape the
            # fleet pre-warms is the chain itself, not the ragged
            # variant (whose capacity depends on who co-arrives)
            record_program("chain", key, canon, cb, self.session)
            out = self._try_ragged_chain(key, canon, cb)
            if out is None:
                out = self._jit_call(jitted, (cb,), "chain", hit)
            return binding.rename_out(out)
        return self._jit_call(jitted, (base,), "chain", hit)

    # ------------------------------------------------------------------
    # ragged multi-query batching (tentpole, ISSUE 18): compatible
    # small canonical fragments from CONCURRENT queries coalesce into
    # one combined batch run by a single compiled program, with a
    # per-row provenance lane (__rq) demuxing result rows back to each
    # owning query. Telemetry stays per-query: each participant records
    # its own ragged_batch trace span and bumps its own counter; the
    # leader's executor carries the batch's device seconds and memory
    # reservation (an over-budget batch fails formation for everyone,
    # who then run solo under their own budgets).
    # ------------------------------------------------------------------
    def _try_ragged_chain(self, key: tuple, canon, cb: Batch
                          ) -> Optional[Batch]:
        """Offer a canonical chain dispatch for co-batching. Returns
        this query's demuxed output (canonical names — the caller's
        binding renames out), or None to run solo."""
        session = self.session
        try:
            if not bool(session.get("ragged_batching")):
                return None
            max_rows = int(session.get("ragged_batch_max_rows")) \
                or CONFIG.ragged_batch_rows
        except (KeyError, TypeError, ValueError):
            return None
        # only pure Filter/Project chains batch: Limit/Sort/TopN/
        # Sample/MarkDistinct have per-query cross-row semantics that
        # break under concatenation
        if not canon.nodes or not all(
                isinstance(nd, (FilterNode, ProjectNode))
                for nd in canon.nodes):
            return None
        n = cb.num_rows
        if not isinstance(n, int):
            return None     # device-resident count: syncing to form
            #                 a batch would stall the async pipeline
        # leave room for at least one batch-mate
        if n <= 0 or n * 2 > max_rows:
            return None
        if any(c.elements is not None or c.children is not None
               for c in cb.columns.values()):
            return None     # array/map/row lanes: concat delegates to
            #                 host-side complex merge — not worth it
        # compatibility signature: canonical program + column layout
        # (same canonical key from DIFFERENT tables can carry different
        # types) + catalog (one connector per batch)
        sig = (key, session.catalog,
               tuple((name, repr(c.type))
                     for name, c in cb.columns.items()))
        from .taskexec import ragged_batcher

        def run_group(items):
            return self._run_ragged_group(key, canon, items)

        t0 = time.perf_counter()
        ok, out = ragged_batcher().submit(
            sig, n, cb, run_group,
            wait=getattr(session, "slot_wait", None),
            max_rows=max_rows)
        if not ok:
            return None
        self.ragged_batched += 1
        tr = self.trace
        if tr is not None:
            tr.record("ragged_batch", t0, time.perf_counter(),
                      rows=n)
        return out

    def _run_ragged_group(self, key: tuple, canon,
                          items: List[Batch]) -> List[Batch]:
        """Leader-side group execution: combine members' canonical
        batches (+ provenance lane), run ONE compiled ragged program,
        demux rows back per member by lane value."""
        import numpy as np
        from ..columnar import Column, concat_batches
        from ..types import BIGINT
        from .progkey import RAGGED_LANE, ragged_nodes
        ns = [b.num_rows_host() for b in items]
        total = sum(ns)
        combined = concat_batches(items)
        cap = combined.capacity
        # reserve-before-allocate on the LEADER (the thread that
        # executes): a batch the leader's query cannot afford fails
        # formation — every member then runs solo under its own budget
        self._reserve(cap, len(combined.columns) + 1, "ragged batch")
        lane = np.concatenate([
            np.repeat(np.arange(len(items), dtype=np.int64),
                      np.asarray(ns, dtype=np.int64)),
            # padding rows carry the sentinel len(items): no member's
            # demux selector can ever match them
            np.full(cap - total, len(items), dtype=np.int64)])
        ragged = Batch(
            {**combined.columns,
             RAGGED_LANE: Column(BIGINT, jnp.asarray(lane))}, total)
        rkey = ("ragged",) + tuple(key)
        jitted = _RAGGED_JIT_CACHE.get(rkey)
        hit = jitted is not None
        _M_JIT.inc(cache="ragged", result="hit" if hit else "miss")
        if jitted is None:
            helper = self._detached()
            nodes = ragged_nodes(canon.nodes)

            def fn(b):
                for nd in reversed(nodes):
                    b = helper._dispatch_apply(nd, b)
                return b
            jitted = jax.jit(fn)
            _cache_put(_RAGGED_JIT_CACHE, rkey, jitted)
        out = self._jit_call(jitted, (ragged,), "ragged", hit)
        # demux: ONE host sync for the lane, then a per-member row
        # gather (the engine's own compaction primitive — dictionaries,
        # Int128 lanes and validity all route through Column.gather).
        # filter compaction is STABLE (mask_to_gather's nonzero is
        # ascending) and members' input rows are contiguous, so each
        # member's relative row order matches its solo run exactly.
        n_out = out.num_rows_host()
        lane_out = np.asarray(
            jax.device_get(out.column(RAGGED_LANE).data))[:n_out]
        bare = Batch({k: c for k, c in out.columns.items()
                      if k != RAGGED_LANE}, out.num_rows)
        results = []
        for i in range(len(items)):
            sel = np.nonzero(lane_out == i)[0]
            k = len(sel)
            cap_i = capacity_for(k, minimum=8)
            idx = np.zeros(cap_i, dtype=np.int64)
            idx[:k] = sel
            results.append(bare.gather(jnp.asarray(idx), k))
        return results

    # ------------------------------------------------------------------
    # leaves
    # ------------------------------------------------------------------
    def _exec_TableScanNode(self, node: TableScanNode) -> Batch:
        conn = self.catalogs.connector(node.handle.catalog)
        columns = sorted(set(node.assignments.values()))
        par = int(self.session.get("task_concurrency")) or 1
        if self.scan_partition is not None:
            part, nparts = self.scan_partition
            splits = conn.get_splits(node.handle, par)
            mine = [s for i, s in enumerate(splits)
                    if i % nparts == part]
            if not mine:
                from ..columnar import batch_from_pylist
                return batch_from_pylist(
                    {s: [] for s in node.schema}, dict(node.schema))
            # reserve-before-allocate for the WORKER's split share too
            # (same discipline as the whole-table path below): an
            # oversized fragment fails with the actionable
            # EXCEEDED_LOCAL_MEMORY_LIMIT error instead of a raw HBM
            # OOM mid-concat
            if node.handle.constraint is None \
                    and node.handle.limit is None \
                    and hasattr(conn, "table_row_count"):
                total = conn.table_row_count(node.handle)
                if total:
                    share = -(-int(total) * len(mine) // len(splits))
                    self._reserve(share, len(columns),
                                  f"worker split share of "
                                  f"{node.handle.table} "
                                  f"(part {part}/{nparts})")
            batches = [self._read_split(conn, s, columns)
                       for s in mine]
            whole = (device_concat(batches) if len(batches) > 1
                     else batches[0])
            cols = {sym: whole.column(col)
                    for sym, col in node.assignments.items()}
            return Batch(cols, whole.num_rows)
        whole = read_table_cached(conn, node.handle, columns, par)
        if whole is None:
            # materializing the table for a downstream operator: check
            # the memory guard FIRST so an over-limit table fails with
            # the actionable EXCEEDED_LOCAL_MEMORY_LIMIT error instead
            # of exhausting HBM mid-concat (memory/MemoryPool.java's
            # reserve-before-allocate discipline)
            est = None
            if node.handle.constraint is None \
                    and node.handle.limit is None \
                    and hasattr(conn, "table_row_count"):
                # pushed-down constraints/limits shrink the result below
                # the table row count by an unknown factor — reserving
                # the full-table estimate would spuriously reject
                # selective scans (q6@sf100 keeps ~2% of rows)
                est = conn.table_row_count(node.handle)
            if est:
                self._reserve(int(est), len(columns),
                              f"table scan of {node.handle.table}")
            splits = conn.get_splits(node.handle, par)
            batches = [self._read_split(conn, s, columns)
                       for s in splits]
            whole = (device_concat(batches) if len(batches) > 1
                     else batches[0])
        cols = {sym: whole.column(col)
                for sym, col in node.assignments.items()}
        return Batch(cols, whole.num_rows)

    def _exec_ValuesNode(self, node: ValuesNode) -> Batch:
        data = {s: [row[i] for row in node.rows]
                for i, s in enumerate(node.schema)}
        return batch_from_pylist(data, dict(node.schema))

    # ------------------------------------------------------------------
    # row transforms
    # ------------------------------------------------------------------
    def _exec_FilterNode(self, node: FilterNode) -> Batch:
        return self._apply_FilterNode(node, self.execute(node.source))

    def _apply_FilterNode(self, node: FilterNode, src: Batch) -> Batch:
        mask = eval_predicate(node.predicate, src)
        return compact.filter_batch(src, mask)

    def _exec_ProjectNode(self, node: ProjectNode) -> Batch:
        return self._apply_ProjectNode(node, self.execute(node.source))

    def _apply_ProjectNode(self, node: ProjectNode, src: Batch) -> Batch:
        cols = {s: eval_expr(e, src)
                for s, e in node.assignments.items()}
        return Batch(cols, src.num_rows)

    def _exec_OutputNode(self, node: OutputNode) -> Batch:
        src = self.execute(node.source)
        return Batch({s: src.column(s) for s in node.symbols},
                     src.num_rows)

    def _exec_LimitNode(self, node: LimitNode) -> Batch:
        return self._apply_LimitNode(node, self.execute(node.source))

    def _apply_LimitNode(self, node: LimitNode, src: Batch) -> Batch:
        return compact.limit_batch(src, node.count)

    def _exec_OffsetNode(self, node: OffsetNode) -> Batch:
        return self._apply_OffsetNode(node, self.execute(node.source))

    def _apply_OffsetNode(self, node: OffsetNode, src: Batch) -> Batch:
        return compact.offset_batch(src, node.count)

    def _exec_SortNode(self, node: SortNode) -> Batch:
        return self._apply_SortNode(node, self.execute(node.source))

    def _apply_SortNode(self, node: SortNode, src: Batch) -> Batch:
        keys = [sort_ops.SortKey(k.symbol, k.ascending, k.nulls_first)
                for k in node.keys]
        return sort_ops.sort_batch(src, keys)

    def _exec_TopNNode(self, node: TopNNode) -> Batch:
        return self._apply_TopNNode(node, self.execute(node.source))

    def _apply_TopNNode(self, node: TopNNode, src: Batch) -> Batch:
        keys = [sort_ops.SortKey(k.symbol, k.ascending, k.nulls_first)
                for k in node.keys]
        return sort_ops.topn_batch(src, keys, node.count)

    def _exec_SampleNode(self, node: SampleNode) -> Batch:
        return self._apply_SampleNode(node, self.execute(node.source))

    def _apply_SampleNode(self, node: SampleNode, src: Batch) -> Batch:
        from ..ops.hashing import mix64
        h = mix64(jnp.arange(src.capacity, dtype=jnp.uint64))
        u = (h >> jnp.uint64(11)).astype(jnp.float64) / float(1 << 53)
        return compact.filter_batch(src, u < node.ratio)

    def _exec_AssignUniqueIdNode(self, node: AssignUniqueIdNode) -> Batch:
        return self._apply_AssignUniqueIdNode(
            node, self.execute(node.source))

    def _apply_AssignUniqueIdNode(self, node, src: Batch) -> Batch:
        cols = dict(src.columns)
        cols[node.symbol] = Column(
            BIGINT, jnp.arange(src.capacity, dtype=jnp.int64), None)
        return Batch(cols, src.num_rows)

    def _exec_EnforceSingleRowNode(self, node) -> Batch:
        src = self.execute(node.source)
        n = src.num_rows_host()
        if n > 1:
            raise QueryError(
                "Scalar sub-query has returned multiple rows")
        if n == 0:
            # one all-NULL row
            cols = {}
            for s, c in src.columns.items():
                cols[s] = dc_replace(
                    c, valid=jnp.zeros((c.capacity,), bool))
            return Batch(cols, 1)
        return src

    # ------------------------------------------------------------------
    # aggregation
    # ------------------------------------------------------------------
    def _exec_AggregationNode(self, node: AggregationNode) -> Batch:
        return self._apply_AggregationNode(
            node, self.execute(node.source))

    def _apply_AggregationNode(self, node: AggregationNode,
                               src: Batch) -> Batch:
        phys, post, extra_cols = _lower_aggregates(node.aggregates, src)
        if extra_cols:
            cols = dict(src.columns)
            cols.update(extra_cols)
            src = Batch(cols, src.num_rows)
        if node.group_keys:
            out = group_aggregate(src, list(node.group_keys), phys)
        else:
            out = global_aggregate(src, phys) if phys else \
                _single_row(src)
        if post:
            cols = dict(out.columns)
            for sym, fn in post.items():
                cols[sym] = fn(out)
            # drop intermediate lanes
            keep = set(node.group_keys) | set(node.aggregates)
            cols = {s: c for s, c in cols.items() if s in keep}
            out = Batch(cols, out.num_rows)
        return out

    def _exec_MarkDistinctNode(self, node: MarkDistinctNode) -> Batch:
        return self._apply_MarkDistinctNode(
            node, self.execute(node.source))

    def _apply_MarkDistinctNode(self, node: MarkDistinctNode,
                                src: Batch) -> Batch:
        from ..ops.groupby import _key_lanes
        lanes = _key_lanes(src, list(node.keys))
        order = jnp.lexsort(lanes[::-1])
        live_s = jnp.take(src.row_valid(), order)
        changed = jnp.zeros((src.capacity,), dtype=bool)
        for lane in lanes[1:]:
            s = jnp.take(lane, order)
            changed = changed | (s != jnp.roll(s, 1))
        first = jnp.arange(src.capacity) == 0
        boundary = (changed | first) & live_s
        marker = jnp.zeros((src.capacity,), bool).at[order].set(boundary)
        cols = dict(src.columns)
        cols[node.marker] = Column(BOOLEAN, marker, None)
        return Batch(cols, src.num_rows)

    def _exec_GroupIdNode(self, node) -> Batch:
        """plan/GroupIdNode.java: one copy of the input per grouping set;
        keys absent from a set become NULL; id column tags the set."""
        src = self.execute(node.source)
        copies = []
        for i, keys in enumerate(node.grouping_sets):
            keep = set(keys)
            cols = {}
            for s, c in src.columns.items():
                if s in node.all_keys and s not in keep:
                    cols[s] = dc_replace(
                        c, valid=jnp.zeros((c.capacity,), bool))
                else:
                    cols[s] = c
            cols[node.id_symbol] = Column(
                BIGINT, jnp.full((src.capacity,), i, jnp.int64), None)
            copies.append(Batch(cols, src.num_rows))
        return device_concat(copies)

    # ------------------------------------------------------------------
    def _exec_UnnestNode(self, node) -> Batch:
        """UNNEST: expand array rows into element rows (reference:
        operator/unnest/UnnestOperator.java). The expansion is the same
        searchsorted pattern as join output materialization — per-row
        emit count = max array length, two-phase capacity."""
        src = self.execute(node.source)
        cap = src.capacity
        live = src.row_valid()
        arrs = {o: src.column(i) for o, i in node.unnest.items()}
        lens = {}
        for o, c in arrs.items():
            ln = jnp.asarray(c.data2).astype(jnp.int64)
            if c.valid is not None:
                ln = jnp.where(jnp.asarray(c.valid), ln, 0)
            lens[o] = ln
        count = None
        for ln in lens.values():
            count = ln if count is None else jnp.maximum(count, ln)
        count = jnp.where(live, count, 0)
        total = int(jnp.sum(count))
        out_cap = capacity_for(max(total, 1))
        self._reserve(out_cap, len(node.replicate) + len(arrs) + 1,
                      "unnest output")
        incl = jnp.cumsum(count)
        offs = incl - count
        i = jnp.arange(out_cap, dtype=jnp.int64)
        p = jnp.clip(jnp.searchsorted(incl, i, side="right"), 0, cap - 1)
        j = i - jnp.take(offs, p)
        cols: Dict[str, Column] = {}
        for s in node.replicate:
            cols[s] = src.column(s).gather(p)
        for o, c in arrs.items():
            el = c.elements
            ecap = int(jnp.asarray(el.data).shape[0])
            flat = jnp.take(jnp.asarray(c.data).astype(jnp.int64), p) + j
            flat = jnp.clip(flat, 0, ecap - 1)
            in_arr = j < jnp.take(lens[o], p)
            data = jnp.take(jnp.asarray(el.data), flat)
            valid = in_arr
            if el.valid is not None:
                valid = valid & jnp.take(jnp.asarray(el.valid), flat)
            d2 = (None if el.data2 is None
                  else jnp.take(jnp.asarray(el.data2), flat))
            cols[o] = Column(el.type, data, valid, el.dictionary, d2,
                             el.elements)
        if node.ordinality:
            cols[node.ordinality] = Column(BIGINT, j + 1, None)
        return Batch(cols, total)

    # ------------------------------------------------------------------
    # joins
    # ------------------------------------------------------------------
    def _mjoin_program(self, key: tuple, builder):
        """Lookup-or-build one jitted materialized-join program in the
        cross-query cache. None when the key is denied (a prior trace
        hit host-only evaluation); the caller falls back to the eager
        two-phase path."""
        if key in _MJOIN_JIT_DENY:
            return None
        jitted = _MJOIN_JIT_CACHE.get(key)
        hit = jitted is not None
        _M_JIT.inc(cache="join", result="hit" if hit else "miss")
        if jitted is None:
            jitted = jax.jit(builder())
            _cache_put(_MJOIN_JIT_CACHE, key, jitted)
        return jitted, hit

    @staticmethod
    def _mjoin_jittable(probe: Batch, build: Batch) -> bool:
        # nested ARRAY/MAP/ROW lanes keep the eager path (their AOT
        # payload cannot be rebuilt, and the win is in the flat TPC-H
        # lanes anyway)
        return not any(
            c.elements is not None or c.children is not None
            for c in list(probe.columns.values())
            + list(build.columns.values()))

    def _mjoin_counts(self, probe: Batch, build: Batch, pkeys, bkeys,
                      outer: bool):
        """Jitted count phase of the materialized join. Returns
        (start, count, order, total) device arrays, or None on decline
        — the caller runs ops/join.py eagerly."""
        if not (self.fragment_jit
                and self._mjoin_jittable(probe, build)):
            return None
        from .streamjoin import _lane_spec
        key = mjoin_count_key(outer, pkeys, bkeys, _lane_spec(probe),
                              _lane_spec(build), probe.capacity,
                              build.capacity)
        got = self._mjoin_program(
            key, lambda: make_mjoin_count_program(pkeys, bkeys, outer))
        if got is None:
            return None
        jitted, hit = got
        try:
            return self._jit_call(jitted, (probe, build), "join", hit)
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            _MJOIN_JIT_CACHE.pop(key, None)
            _MJOIN_JIT_DENY.add(key)
            return None

    def _mjoin_expand(self, probe: Batch, build: Batch, start, count,
                      order, jt: str, residual, out_cap: int,
                      criteria=None) -> Optional[Batch]:
        """Jitted expand phase (+ fused residual filter). On first
        success the join's full two-program shape is recorded into the
        hot-shape registry (exec/hotshapes.py) so exec/aot.py can
        pre-compile BOTH phases into these same cache slots."""
        if not (self.fragment_jit
                and self._mjoin_jittable(probe, build)):
            return None
        from .streamjoin import _join_payload, _lane_spec
        key = mjoin_expand_key(jt, repr(residual), _lane_spec(probe),
                               _lane_spec(build), probe.capacity,
                               build.capacity, out_cap)
        got = self._mjoin_program(
            key, lambda: make_mjoin_expand_program(jt, residual,
                                                   out_cap))
        if got is None:
            return None
        jitted, hit = got
        args = (probe, build, jnp.asarray(start, jnp.int64),
                jnp.asarray(count, jnp.int64),
                jnp.asarray(order, jnp.int64))
        try:
            out = self._jit_call(jitted, args, "join", hit)
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            _MJOIN_JIT_CACHE.pop(key, None)
            _MJOIN_JIT_DENY.add(key)
            return None
        if criteria is not None:
            from .hotshapes import record_program

            def build_pl():
                return _join_payload(jt, criteria, residual, probe,
                                     build, out_cap, kind="join")
            # the registry key carries the join keys too: two joins
            # sharing lane specs share the expand program but each
            # needs its own count program compiled
            record_program(
                "join",
                ("mjoin", tuple(c.left for c in criteria),
                 tuple(c.right for c in criteria), key),
                None, None, self.session, payload_fn=build_pl)
        return out

    def _exec_JoinNode(self, node: JoinNode) -> Batch:
        jt = node.join_type
        if jt == "right":
            flipped = JoinNode(node.right, node.left, "left",
                               tuple(join_ops and
                                     _flip_clause(c)
                                     for c in node.criteria),
                               node.filter)
            return self._exec_JoinNode(flipped)
        # beyond-HBM probe streaming (exec/streamjoin.py): when the
        # probe side is a scan chain whose working set exceeds the
        # memory budget, build the hash table once and stream probe
        # chunks through double-buffered host->device transfers
        # instead of materializing the probe (BENCH_r05's q18@sf100
        # "exceeds single-chip HBM" gap)
        from .streamjoin import maybe_stream_join
        streamed, pre_built = maybe_stream_join(self, node)
        if streamed is not None:
            return streamed
        left = self.execute(node.left)
        # a declined stream decision may have materialized the build
        # side already (the remaining-after-build check needs it):
        # reuse that batch instead of executing node.right twice
        right = (pre_built if pre_built is not None
                 else self.execute(node.right))

        if jt == "cross" or not node.criteria:
            return self._cross_join(left, right, node.filter, jt)

        pkeys = [c.left for c in node.criteria]
        bkeys = [c.right for c in node.criteria]
        filt = join_verify_filter(left.columns, right.columns,
                                  pkeys, bkeys, node.filter)
        if filt is None:
            outer = jt in ("left", "full")
            counted = self._mjoin_counts(left, right, pkeys, bkeys,
                                         outer)
            if counted is not None:
                start, count, order, total_dev = counted
                eff = None      # only the oversized path needs it
                total = int(total_dev)
            else:
                start, count, order = join_ops.match_counts(
                    left, right, pkeys, bkeys)
                live_p = left.row_valid()
                eff = jnp.where(live_p, jnp.maximum(count, 1), 0) \
                    if outer else count
                total = int(jnp.sum(eff))
            width = len(left.columns) + len(right.columns)
            if total > CONFIG.max_batch_rows:
                if eff is None:
                    eff = jnp.where(left.row_valid(),
                                    jnp.maximum(count, 1), 0) \
                        if outer else count
                out = self._oversized_join(
                    left, right, start, count, eff, order, total,
                    width, "left" if outer else "inner")
            else:
                self._reserve(total, width, "join output")
                cap = capacity_for(total)
                out = None
                if counted is not None:
                    out = self._mjoin_expand(
                        left, right, start, count, order,
                        "left" if outer else "inner", None, cap,
                        criteria=node.criteria)
                if out is None:
                    out = join_ops.expand_join(
                        left, right, start, count, order, cap,
                        "left" if outer else "inner")
            if jt == "full":
                out = self._append_right_unmatched(
                    out, left, right, pkeys, bkeys)
            return out
        # residual filter: expand as inner candidates with probe+build
        # position tracks, filter, then repair unmatched outer rows from
        # the *surviving* match sets (key-only counts are not enough —
        # a key match rejected by the filter must still null-extend)
        probe = self._with_pos(left, _PPOS) if jt in ("left", "full") \
            else left
        build = self._with_pos(right, _BPOS) if jt == "full" else right
        counted = self._mjoin_counts(probe, build, pkeys, bkeys, False)
        if counted is not None:
            start, count, order, total_dev = counted
            total = int(total_dev)
        else:
            start, count, order = join_ops.match_counts(
                probe, build, pkeys, bkeys)
            total = int(jnp.sum(count))
        width = len(probe.columns) + len(build.columns)
        if total > CONFIG.max_batch_rows and jt == "inner":
            out = self._oversized_join(probe, build, start, count, count,
                                       order, total, width, "inner",
                                       residual=filt)
            return self._repair_outer(out, left, right, jt)
        self._reserve(total, width, "join candidates")
        cap = capacity_for(total)
        out = None
        if counted is not None:
            out = self._mjoin_expand(probe, build, start, count, order,
                                     "inner", filt, cap,
                                     criteria=node.criteria)
        if out is None:
            cand = join_ops.expand_join(probe, build, start, count,
                                        order, cap, "inner")
            mask = eval_predicate(filt, cand)
            out = compact.filter_batch(cand, mask)
        return self._repair_outer(out, left, right, jt)

    def _reserve(self, rows: int, n_lanes: int, what: str) -> None:
        limit = int(self.session.get("query_max_memory_per_node"))
        try:
            est = reserve_bytes(rows, n_lanes, limit, what)
        except MemoryLimitExceeded as e:
            raise QueryError(str(e)) from e
        self._account(est)

    def _reserve_streamed(self, nbytes: int, what: str) -> None:
        """Reserve a streamed operator's REAL footprint (build state +
        2 chunk buffers + 1 output chunk — exec/streamjoin.py), not
        the full-materialization estimate streaming exists to avoid.
        The cluster pool sees this figure too, so the low-memory
        killer judges streamed queries by what they actually hold."""
        limit = int(self.session.get("query_max_memory_per_node"))
        if nbytes > limit:
            raise QueryError(
                f"Query exceeded per-node memory limit of {limit} "
                f"bytes ({what} needs ~{nbytes} bytes even streamed); "
                "raise query_max_memory_per_node or lower "
                "stream_chunk_rows")
        self._account(int(nbytes))

    def _account(self, est: int) -> None:
        # largest single reservation = the query's peak-memory figure
        # reported in QueryCompletedEvent (capacity planning is the one
        # allocation decision point in this engine — config.py)
        self.peak_reserved_bytes = max(self.peak_reserved_bytes, est)
        mem = getattr(self.session, "memory", None)
        if mem is not None:
            # cluster memory governance (server/memory.py): the same
            # estimate feeds the coordinator's pool ledger; a per-query
            # cap breach or a low-memory kill of THIS query raises
            # here, in the reserving thread, with its Trino error name.
            # ONLY governance errors are rewrapped — an internal bug in
            # the manager must surface as an internal error, not
            # masquerade as a memory-limit breach
            from ..server.memory import MemoryGovernanceError
            try:
                mem.reserve(est)
            except MemoryGovernanceError as e:
                raise QueryError(str(e),
                                 error_name=e.error_name) from e

    def _oversized_join(self, probe: Batch, build: Batch, start, count,
                        eff, order, total: int, width: int,
                        jt: str, residual=None) -> Batch:
        """Join whose output exceeds the per-batch device budget:
        expand probe-row chunks device-side and accumulate the results
        in HOST memory (the spiller role — reference:
        operator/HashBuilderOperator.java:155-170 spill state machine /
        spiller/GenericPartitioningSpiller; on TPU the spill target is
        host RAM, the first rung of the HBM->host->disk ladder,
        SURVEY.md §5 checkpoint/resume). Requires spill_enabled, else
        the memory guard fires."""
        if not bool(self.session.get("spill_enabled")):
            self._reserve(total, width, "join output (spill disabled)")
        eff_np = np.asarray(eff)
        cum = np.cumsum(eff_np)
        budget = CONFIG.max_batch_rows
        n_live = probe.num_rows_host()
        chunks: List[Batch] = []
        lo = 0
        consumed = 0
        pcap = probe.capacity
        while lo < pcap and consumed < total:
            hi = int(np.searchsorted(cum, consumed + budget, "right"))
            hi = max(hi, lo + 1)
            chunk_rows = int(cum[hi - 1] - consumed)
            if chunk_rows == 0:
                lo = hi
                continue
            sel = jnp.arange(lo, hi, dtype=jnp.int64)
            # gathered rows are live iff their original position was in
            # the live prefix — gathered liveness is again a prefix
            sub_probe = probe.gather(sel, max(min(n_live, hi) - lo, 0))
            sub_start = jnp.take(jnp.asarray(start), sel)
            sub_count = jnp.take(jnp.asarray(count), sel)
            cap = capacity_for(max(chunk_rows, 1))
            out = join_ops.expand_join(
                sub_probe, build, sub_start, sub_count, order, cap, jt)
            consumed += chunk_rows
            lo = hi
            if residual is not None:
                # filter each chunk on device BEFORE spilling so only
                # survivors reach host RAM
                mask = eval_predicate(residual, out)
                out = compact.filter_batch(out, mask)
                chunk_rows = out.num_rows_host()
                if chunk_rows == 0:
                    continue
            spilled = _to_host(out, chunk_rows)
            nbytes = sum(_col_bytes(c) for c in spilled.columns.values())
            self.spilled_bytes += nbytes
            _M_SPILL.inc(nbytes)
            chunks.append(spilled)
        if not chunks:
            return _to_host(join_ops.expand_join(
                probe, build, jnp.asarray(start),
                jnp.zeros_like(jnp.asarray(count)), order, 8, jt), 0)
        return _host_concat(chunks, sum(c.num_rows for c in chunks))

    def _cross_join(self, left: Batch, right: Batch, filt,
                    jt: str = "inner") -> Batch:
        """Cross / non-equi join (no equi criteria). For left/full outer
        variants, probe/build positions are tracked through the filter so
        unmatched rows null-extend (JoinNode with empty criteria in
        sql/planner/plan/JoinNode.java; NestedLoopJoinOperator.java)."""
        nl, nr = left.num_rows_host(), right.num_rows_host()
        total = nl * nr
        self._reserve(total, len(left.columns) + len(right.columns),
                      "cross join output")
        cap = capacity_for(max(total, 1))
        probe = self._with_pos(left, _PPOS) if jt in ("left", "full") \
            else left
        build = self._with_pos(right, _BPOS) if jt == "full" else right
        start, count, order = join_ops.cross_counts(probe, build)
        out = join_ops.expand_join(probe, build, start, count, order,
                                   cap, "inner")
        if filt is not None:
            mask = eval_predicate(filt, out)
            out = compact.filter_batch(out, mask)
        return self._repair_outer(out, left, right, jt)

    def _with_pos(self, b: Batch, name: str) -> Batch:
        cols = dict(b.columns)
        cols[name] = Column(
            BIGINT, jnp.arange(b.capacity, dtype=jnp.int64), None)
        return Batch(cols, b.num_rows)

    def _repair_outer(self, out: Batch, left: Batch, right: Batch,
                      jt: str) -> Batch:
        """Strip position lanes; null-extend outer rows whose matches
        all died in the filter (surviving-match repair)."""
        live_out = out.row_valid()
        pp = (jnp.asarray(out.column(_PPOS).data)
              if jt in ("left", "full") else None)
        bb = (jnp.asarray(out.column(_BPOS).data)
              if jt == "full" else None)
        if pp is not None or bb is not None:
            out = Batch({s: c for s, c in out.columns.items()
                         if s not in (_PPOS, _BPOS)}, out.num_rows)
        if pp is not None:
            matched = jnp.zeros((left.capacity,), bool).at[
                jnp.where(live_out, pp, 0)].max(live_out)
            unmatched = left.row_valid() & ~matched
            out = device_concat(
                [out, self._null_extend(left, right, unmatched)])
        if bb is not None:
            matched_b = jnp.zeros((right.capacity,), bool).at[
                jnp.where(live_out, bb, 0)].max(live_out)
            unmatched_b = right.row_valid() & ~matched_b
            out = device_concat(
                [out, self._null_extend_right(left, right, unmatched_b)])
        return out

    def _null_extend(self, left: Batch, right: Batch,
                     row_mask) -> Batch:
        """Rows of ``left`` where mask, with all-NULL right columns."""
        sub = compact.filter_batch(left, row_mask)
        cols = dict(sub.columns)
        for s, c in right.columns.items():
            z = jnp.zeros((sub.capacity,), dtype=np.asarray(c.data).dtype)
            cols[s] = Column(c.type, z,
                             jnp.zeros((sub.capacity,), bool),
                             c.dictionary,
                             None if c.data2 is None else
                             jnp.zeros((sub.capacity,), jnp.int64))
        return Batch(cols, sub.num_rows)

    def _null_extend_right(self, left: Batch, right: Batch,
                           row_mask) -> Batch:
        """Rows of ``right`` where mask, with all-NULL left columns."""
        sub = compact.filter_batch(right, row_mask)
        cols = {}
        for s, c in left.columns.items():
            z = jnp.zeros((sub.capacity,), dtype=np.asarray(c.data).dtype)
            cols[s] = Column(c.type, z, jnp.zeros((sub.capacity,), bool),
                             c.dictionary,
                             None if c.data2 is None else
                             jnp.zeros((sub.capacity,), jnp.int64))
        cols.update(sub.columns)
        return Batch(cols, sub.num_rows)

    def _append_right_unmatched(self, out: Batch, left: Batch,
                                right: Batch, pkeys, bkeys) -> Batch:
        # FULL JOIN tail (no residual filter): right rows with no key
        # match, null-extended
        start, count, order = join_ops.match_counts(
            right, left, bkeys, pkeys)
        unmatched = right.row_valid() & (count == 0)
        pad = self._null_extend_right(left, right, unmatched)
        return device_concat([out, pad])

    def _exec_SemiJoinNode(self, node: SemiJoinNode) -> Batch:
        src = self.execute(node.source)
        filt = self.execute(node.filtering_source)
        matched, key_null, build_null, nonempty = join_ops.semi_join_mask(
            src, filt, [node.source_key], [node.filtering_key])
        # x IN (...): TRUE if matched; FALSE if build empty; NULL if the
        # probe key is NULL or the build side contains NULLs; else FALSE
        data = matched
        valid = matched | ~nonempty | (~key_null & ~build_null)
        cols = dict(src.columns)
        cols[node.output] = Column(BOOLEAN, data, valid)
        return Batch(cols, src.num_rows)

    def _exec_SemiJoinMultiNode(self, node: SemiJoinMultiNode) -> Batch:
        src = self.execute(node.source)
        filt = self.execute(node.filtering_source)
        skeys = list(node.source_keys)
        fkeys = list(node.filtering_keys)
        residual = (join_verify_filter(src.columns, filt.columns,
                                       skeys, fkeys, node.filter)
                    if skeys else node.filter)
        if residual is None and skeys:
            matched, _, _, _ = join_ops.semi_join_mask(
                src, filt, skeys, fkeys)
            cols = dict(src.columns)
            cols[node.output] = Column(BOOLEAN, matched, None)
            return Batch(cols, src.num_rows)
        node = dc_replace(node, filter=residual)
        # residual filter path: expand candidate matches, filter, then
        # mark probe rows with surviving matches
        ppos = "__probe_pos$"
        scols = dict(src.columns)
        scols[ppos] = Column(BIGINT,
                             jnp.arange(src.capacity, dtype=jnp.int64),
                             None)
        probe = Batch(scols, src.num_rows)
        if skeys:
            start, count, order = join_ops.match_counts(
                probe, filt, skeys, fkeys)
        else:
            start, count, order = join_ops.cross_counts(probe, filt)
        total = int(jnp.sum(count))
        cap = capacity_for(total)
        cand = join_ops.expand_join(probe, filt, start, count, order,
                                    cap, "inner")
        if node.filter is not None:
            mask = eval_predicate(node.filter, cand)
        else:
            mask = cand.row_valid()
        pp = jnp.asarray(cand.column(ppos).data)
        live = cand.row_valid() & mask
        matched = jnp.zeros((src.capacity,), bool).at[
            jnp.where(live, pp, 0)].max(live)
        cols = dict(src.columns)
        cols[node.output] = Column(BOOLEAN, matched, None)
        return Batch(cols, src.num_rows)

    # ------------------------------------------------------------------
    # set operations
    # ------------------------------------------------------------------
    def _exec_UnionNode(self, node: UnionNode) -> Batch:
        parts = []
        for child, smap in zip(node.children, node.symbol_maps):
            b = self.execute(child)
            parts.append(Batch(
                {out: b.column(inner) for out, inner in smap.items()},
                b.num_rows))
        return device_concat(parts)

    def _exec_SetOpNode(self, node: SetOpNode) -> Batch:
        left = self.execute(node.left)
        right = self.execute(node.right)
        lb = Batch({o: left.column(i) for o, i in node.left_map.items()},
                   left.num_rows)
        rb = Batch({o: right.column(i)
                    for o, i in node.right_map.items()}, right.num_rows)
        return setop_batches(lb, rb, node.op, node.distinct,
                             list(node.schema))

    # ------------------------------------------------------------------
    # windows
    # ------------------------------------------------------------------
    def _exec_WindowNode(self, node: WindowNode) -> Batch:
        from .window import execute_window, window_traceable
        src = self.execute(node.source)
        if not (self.fragment_jit and window_traceable(node)):
            return execute_window(src, node)
        from .progkey import canonicalize_nodes
        canon = canonicalize_nodes([node])
        if canon is None or canon.key in _WINDOW_JIT_DENY:
            return execute_window(src, node)
        key = canon.key
        jitted = _WINDOW_JIT_CACHE.get(key)
        hit = jitted is not None
        _M_JIT.inc(cache="window", result="hit" if hit else "miss")
        if jitted is None:
            wnode = canon.nodes[0]

            def fn(b: Batch) -> Batch:
                return execute_window(b, wnode)
            jitted = jax.jit(fn)
            _cache_put(_WINDOW_JIT_CACHE, key, jitted)
        binding = canon.binding(src)
        cb = binding.rename_in(src)
        from .hotshapes import record_program
        record_program("window", key, canon, cb, self.session)
        try:
            out = self._jit_call(jitted, (cb,), "window", hit)
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            # a lane/function combination that materializes on host
            # despite the traceability gate: run eagerly ever after
            _WINDOW_JIT_CACHE.pop(key, None)
            _WINDOW_JIT_DENY.add(key)
            return execute_window(src, node)
        return binding.rename_out(out)

    # ------------------------------------------------------------------
    def _exec_ExchangeNode(self, node: ExchangeNode) -> Batch:
        # single-process execution: exchanges are identity (M3 replaces
        # this with all_to_all / all_gather over the device mesh)
        return self.execute(node.source)

    def _exec_PartitionedOutputNode(self,
                                    node: PartitionedOutputNode) -> Batch:
        # the partitioning itself happens at the page boundary
        # (server/task_worker.py cuts the result into partition frames
        # with stage/repartition.py); executed directly — the
        # coordinator running a stage plan locally, a test harness —
        # the node is identity
        return self.execute(node.source)

    def _exec_RemoteSourceNode(self, node: RemoteSourceNode) -> Batch:
        """Reads this task's partition of every upstream stage task
        through the exchange hook (stage/exchange.py ExchangePuller).
        A pull failure is a retriable attempt failure — the stage
        scheduler re-dispatches the task, which re-pulls the committed
        upstream frames off the spool."""
        reader = self.exchange_reader
        if reader is None:
            raise QueryError(
                "RemoteSourceNode executed outside a stage exchange "
                "context (no exchange reader wired)")
        batches: List[Batch] = []
        for fid in node.fragment_ids:
            batches.extend(reader(int(fid)))
        if not batches:
            from ..columnar import empty_batch
            return empty_batch(node.schema)
        out = (device_concat(batches) if len(batches) > 1
               else batches[0])
        return out

    def _exec__Pre(self, node: "_Pre") -> Batch:
        return node.batch

    def _single_row(self, src: Batch) -> Batch:
        return _single_row(src)


def make_stream_runners(helper: "Executor", chain, node):
    """Build the streaming-aggregation programs over a chain +
    AggregationNode: ``run`` (per-split partial aggregation) and
    ``run_full`` (whole-table partial + final combine + post-processing
    fused into ONE XLA computation — the shape of the hand-fused
    micro). Module-level so the AOT compiler (exec/aot.py) rebuilds
    the EXACT closures the executor caches — a pre-warmed program and
    a live query trace the same jaxpr."""

    def partial(b: Batch):
        # selection-vector execution: the filter chain becomes a
        # live mask consumed by the aggregation (no compaction).
        # Aggregates lower against the CHAIN OUTPUT columns
        # (projection-created symbols like checksum's arg live there,
        # not on the raw scan batch).
        cols, live = helper._masked_chain_eval(chain, b)
        src = Batch(cols, jnp.sum(live.astype(jnp.int64)))
        _p, _post, extra = _lower_aggregates(node.aggregates, src)
        if extra:
            c2 = dict(src.columns)
            c2.update(extra)
            src = Batch(c2, src.num_rows)
        if node.group_keys:
            out = group_aggregate(src, list(node.group_keys), _p,
                                  live=live)
        else:
            out = _pad_partial(global_aggregate(src, _p, live=live))
        return out, _p, _post

    def run(b: Batch) -> Batch:
        return partial(b)[0]

    def run_full(b: Batch) -> Batch:
        out, _p, _post = partial(b)
        from ..ops.groupby import COMBINABLE_KINDS
        fin = [AggInput(COMBINABLE_KINDS[a.kind], a.output, None,
                        a.output) for a in _p]
        if node.group_keys:
            out = group_aggregate(out, list(node.group_keys), fin)
        else:
            out = global_aggregate(out, fin)
        if _post:
            cols = dict(out.columns)
            for sym, fn in _post.items():
                cols[sym] = fn(out)
            keep = set(node.group_keys) | set(node.aggregates)
            cols = {s: c for s, c in cols.items() if s in keep}
            out = Batch(cols, out.num_rows)
        return out

    return run, run_full


# --------------------------------------------------------------------------
# materialized hash-join programs (the "join" AOT kind)
# --------------------------------------------------------------------------
# The eager join in _exec_JoinNode is already two-phase ("count, pick
# bucket, expand" — ops/join.py): the count phase is the only host
# sync, the expansion runs at a static capacity bucket. Each phase is
# therefore one traceable program; jitting them separately keeps the
# host-side total/bucket decision OUT of the traced code while every
# device op (lane hashing, searchsorted, gather expansion, residual
# filtering) fuses. Builders are module-level so exec/aot.py rebuilds
# the EXACT closures the executor caches (progkey doctrine: one key
# per program, shared by the live path and the pre-warmer).

def mjoin_count_key(outer: bool, pkeys, bkeys, probe_spec, build_spec,
                    probe_cap: int, build_cap: int) -> tuple:
    return ("mjoin_count", bool(outer), tuple(pkeys), tuple(bkeys),
            probe_spec, build_spec, int(probe_cap), int(build_cap))


def mjoin_expand_key(jt: str, residual_repr: str, probe_spec,
                     build_spec, probe_cap: int, build_cap: int,
                     out_cap: int) -> tuple:
    return ("mjoin_expand", jt, residual_repr, probe_spec, build_spec,
            int(probe_cap), int(build_cap), int(out_cap))


def make_mjoin_count_program(pkeys, bkeys, outer: bool):
    """Phase 1: build-side sort + probe match counts + the effective
    output total. Everything downstream of the total is host policy
    (bucket choice, memory reserve, oversized spill), so the program
    ends exactly at the host-sync boundary. Output dtypes are pinned
    int64 — they cross into the separately-jitted expand program."""
    pkeys, bkeys = list(pkeys), list(bkeys)

    def fn(probe: Batch, build: Batch):
        start, count, order = join_ops.match_counts(
            probe, build, pkeys, bkeys)
        if outer:
            eff = jnp.where(probe.row_valid(),
                            jnp.maximum(count, 1), 0)
        else:
            eff = count
        return (start.astype(jnp.int64), count.astype(jnp.int64),
                order.astype(jnp.int64), jnp.sum(eff))

    return fn


def make_mjoin_expand_program(jt: str, residual, out_cap: int):
    """Phase 2: gather-expand the match set at the chosen capacity
    bucket; with a residual, the candidate expansion, predicate and
    compaction fuse into the same program (the streamed-join probe
    kernel's shape, minus the chunk loop)."""

    def fn(probe: Batch, build: Batch, start, count, order):
        out = join_ops.expand_join(probe, build, start, count, order,
                                   out_cap, "inner" if residual is not None
                                   else jt)
        if residual is None:
            return out
        mask = eval_predicate(residual, out)
        return compact.filter_batch(out, mask)

    return fn


def setop_tag(lb: Batch, rb: Batch):
    """Tag each side with per-side counters for the group-by counting
    kernel (reference rules: ImplementIntersectDistinctAsUnion,
    ImplementExceptAll). Shared by the local and distributed paths."""
    tagged = []
    for b, (lc, rc) in ((lb, (1, 0)), (rb, (0, 1))):
        cols = dict(b.columns)
        cols["__l$"] = Column(
            BIGINT, jnp.full((b.capacity,), lc, jnp.int64), None)
        cols["__r$"] = Column(
            BIGINT, jnp.full((b.capacity,), rc, jnp.int64), None)
        tagged.append(Batch(cols, b.num_rows))
    return tagged


SETOP_AGGS = (AggInput("sum", "__l$", output="__nl$"),
              AggInput("sum", "__r$", output="__nr$"))


def setop_keep_times(nl, nr, op: str, distinct: bool):
    """(keep-mask, replication-times|None) from the per-side counts —
    the set-op semantics in one place (EXCEPT ALL keeps rows with
    nl > nr replicated nl-nr times; INTERSECT ALL min(nl, nr))."""
    if op == "intersect":
        keep = (nl > 0) & (nr > 0)
    elif distinct:
        keep = (nl > 0) & (nr == 0)
    else:
        keep = nl > nr
    if distinct:
        return keep, None
    times = (jnp.minimum(nl, nr) if op == "intersect"
             else jnp.maximum(nl - nr, 0))
    return keep, times


def setop_batches(lb: Batch, rb: Batch, op: str, distinct: bool,
                  out_syms) -> Batch:
    """INTERSECT/EXCEPT [ALL] over two schema-aligned batches.
    Batch-level so the distributed executor can run the same kernel per
    shard after a hash repartition on all columns (its traced twin in
    exec/distributed.py differs only in concat + host syncs)."""
    both = device_concat(setop_tag(lb, rb))
    g = group_aggregate(both, out_syms, list(SETOP_AGGS))
    nl = jnp.asarray(g.column("__nl$").data)
    nr = jnp.asarray(g.column("__nr$").data)
    keep, times = setop_keep_times(nl, nr, op, distinct)
    out = compact.filter_batch(g, keep)
    if times is not None:
        times = jnp.take(times, compact.mask_to_gather(keep)[0])
        total = int(jnp.sum(jnp.where(out.row_valid(), times, 0)))
        cap = capacity_for(max(total, 1))
        incl = jnp.cumsum(jnp.where(out.row_valid(), times, 0))
        i = jnp.arange(cap, dtype=jnp.int64)
        p = jnp.searchsorted(incl, i, side="right")
        p = jnp.clip(p, 0, out.capacity - 1)
        out = out.gather(p, total)
    return Batch({s: out.column(s) for s in out_syms}, out.num_rows)


_TRACEABLE = (FilterNode, ProjectNode, LimitNode, OffsetNode, SortNode,
              TopNNode, SampleNode, AssignUniqueIdNode, MarkDistinctNode,
              AggregationNode)
Executor._STREAM_CHAIN = (FilterNode, ProjectNode, SampleNode)


def _pad_partial(b: Batch) -> Batch:
    """Pad a 1-row global-aggregate partial to capacity 8 so partials
    from every split concatenate uniformly."""
    cols = {}
    for s, c in b.columns.items():
        data = jnp.pad(jnp.asarray(c.data), (0, 8 - c.capacity))
        valid = (None if c.valid is None
                 else jnp.pad(jnp.asarray(c.valid), (0, 8 - c.capacity)))
        cols[s] = Column(c.type, data, valid, c.dictionary,
                         None if c.data2 is None else
                         jnp.pad(jnp.asarray(c.data2),
                                 (0, 8 - c.capacity)))
    return Batch(cols, b.num_rows)


def _flip_clause(c):
    from ..plan.nodes import JoinClause
    return JoinClause(c.right, c.left)


# --------------------------------------------------------------------------
# HBM-resident scan cache for immutable generator connectors: the
# "storage layer" of tpch/tpcds is deterministic, so table columns can
# live in device memory across queries — on TPU this removes the
# host->HBM re-upload (the dominant engine-path cost through a tunneled
# chip; repeated scans become compute-only like the reference's
# OS-page-cached table files). Keyed per connector object; bounded by
# CONFIG.scan_cache_bytes, insertion-order eviction.
# --------------------------------------------------------------------------

import threading as _threading  # noqa: E402
import weakref as _weakref  # noqa: E402

_SCAN_CACHES: "_weakref.WeakKeyDictionary" = _weakref.WeakKeyDictionary()
_SCAN_CACHE_LOCK = _threading.Lock()


def _col_bytes(c: Column) -> int:
    total = 0
    for lane in (c.data, c.valid, c.data2):
        if lane is not None:
            total += int(np.asarray(lane).nbytes) \
                if isinstance(lane, np.ndarray) else int(lane.nbytes)
    return total


def read_split_cached(conn, split, columns) -> Batch:
    """Split read through the per-connector HBM cache. Lanes are
    cached per (split, COLUMN), so overlapping projections of the same
    split share one device copy per column. The lock covers all state
    mutation — the coordinator runs one executor thread per query."""
    if not getattr(conn, "scan_cache_ok", False) \
            or CONFIG.scan_cache_bytes <= 0:
        return conn.read_split(split, columns)
    h = split.handle
    skey = (h.schema, h.table, split.part, split.part_count,
            h.constraint, h.limit)
    with _SCAN_CACHE_LOCK:
        state = _SCAN_CACHES.get(conn)
        if state is None:
            state = {"entries": {}, "order": [], "bytes": 0}
            _SCAN_CACHES[conn] = state
        entry = state["entries"].get(skey)
        missing = [c for c in columns
                   if entry is None or c not in entry["cols"]]
    if not missing:
        _M_SCAN.inc(cache="split", result="hit")
        with _SCAN_CACHE_LOCK:
            return Batch({c: entry["cols"][c] for c in columns},
                         entry["num_rows"])
    _M_SCAN.inc(cache="split", result="miss")
    raw = conn.read_split(split, missing)
    on_dev = jax.default_backend() != "cpu"
    if on_dev:
        raw = raw.on_device()          # pin the lanes in HBM
    size = sum(_col_bytes(c) for c in raw.columns.values())
    with _SCAN_CACHE_LOCK:
        state = _SCAN_CACHES.get(conn)
        if state is None:
            state = {"entries": {}, "order": [], "bytes": 0}
            _SCAN_CACHES[conn] = state
        if size <= CONFIG.scan_cache_bytes:
            while state["bytes"] + size > CONFIG.scan_cache_bytes \
                    and state["order"]:
                old_key = state["order"].pop(0)
                old = state["entries"].pop(old_key, None)
                if old is not None:
                    state["bytes"] -= sum(_col_bytes(c)
                                          for c in old["cols"].values())
            entry = state["entries"].get(skey)
            if entry is None:
                entry = {"cols": {}, "num_rows": raw.num_rows}
                state["entries"][skey] = entry
                state["order"].append(skey)
            for name, col in raw.columns.items():
                if name not in entry["cols"]:
                    entry["cols"][name] = col
                    state["bytes"] += _col_bytes(col)
        entry = state["entries"].get(skey)
        _M_SCAN_BYTES.set(state["bytes"],
                          connector=getattr(conn, "name",
                                            type(conn).__name__))
        if entry is not None and all(c in entry["cols"]
                                     for c in columns):
            return Batch({c: entry["cols"][c] for c in columns},
                         entry["num_rows"])
    # cache too small for this split: serve the direct read (fill any
    # columns the raw read didn't cover)
    if all(c in raw.columns for c in columns):
        return Batch({c: raw.columns[c] for c in columns},
                     raw.num_rows)
    rest = conn.read_split(split, columns)
    return rest.on_device() if on_dev else rest


def cache_memory_bytes() -> int:
    """Bytes held by the shared HBM scan caches across every connector
    — the figure cross-query memory governance (server/memory.py +
    server/task_worker.py) folds into its pressure arithmetic: cached
    table lanes share the same device/host memory as query working
    sets, so a pool sized to the hardware must see them."""
    with _SCAN_CACHE_LOCK:
        scan = sum(int(state["bytes"])
                   for state in _SCAN_CACHES.values())
    # the result cache holds host-side rows, not HBM lanes, but it is
    # process memory the pressure ladder can shed — governance must
    # see it or it silently erodes the pool headroom
    try:
        from .resultcache import RESULT_CACHE
        return scan + RESULT_CACHE.bytes()
    except Exception:       # noqa: BLE001 — import cycles in teardown
        return scan


from ..obs.metrics import CACHE_PRESSURE_EVICTS as _M_CACHE_PRESSURE


def evict_cache_pressure(need_bytes: int) -> int:
    """Shed shared-cache memory under pressure, oldest entries first:
    the scan caches (byte-accounted) go first; if they cannot cover
    the deficit the structural jit-program caches drop their oldest
    half (entry sizes are opaque — compiled closures — so the jit
    relief is entry-counted, backed by the persistent XLA cache for
    recompiles) and the replicate fetch-once cache is cleared. Returns
    the scan-cache bytes actually freed. This is what makes the caches
    GOVERNED resources: a cache full of one query's programs/tables is
    evicted before the low-memory killer considers killing a neighbor
    query (ISSUE 14 tentpole part 3)."""
    need = max(int(need_bytes), 0)
    freed = 0
    with _SCAN_CACHE_LOCK:
        for conn, state in list(_SCAN_CACHES.items()):
            while state["order"] and freed < need:
                old_key = state["order"].pop(0)
                old = state["entries"].pop(old_key, None)
                if old is None:
                    continue
                sz = sum(_col_bytes(c) for c in old["cols"].values())
                state["bytes"] -= sz
                freed += sz
                _M_CACHE_PRESSURE.inc(cache="scan")
            _M_SCAN_BYTES.set(state["bytes"],
                              connector=getattr(conn, "name",
                                                type(conn).__name__))
            if freed >= need:
                break
    if freed < need:
        # byte-accounted caches first: the replicate fetch-once cache
        # frees measurable bytes before the opaque jit closures go
        try:
            from ..stage.exchange import evict_replicate_cache
            freed += evict_replicate_cache(need - freed)
        except Exception:       # noqa: BLE001 — relief is best-effort
            pass
    if freed < need:
        # the result cache sheds BEFORE the jit caches: cached rows
        # are merely saved latency, compiled programs are saved
        # compile storms — drop the cheaper-to-rebuild tier first
        try:
            from .resultcache import RESULT_CACHE
            before = len(RESULT_CACHE)
            freed += RESULT_CACHE.evict(need - freed)
            for _ in range(before - len(RESULT_CACHE)):
                _M_CACHE_PRESSURE.inc(cache="result")
        except Exception:   # noqa: BLE001 — relief is best-effort
            pass
    if freed < need:
        with _JIT_CACHE_LOCK:
            for cache in (_CHAIN_JIT_CACHE, _STREAM_JIT_CACHE,
                          _RAGGED_JIT_CACHE):
                for _ in range(len(cache) // 2):
                    try:
                        cache.pop(next(iter(cache)))
                    except (KeyError, StopIteration):
                        break
                    _M_CACHE_PRESSURE.inc(cache="jit")
    return freed


def _whole_table_mode() -> bool:
    """Whole-table HBM residency: on by default on device backends,
    where per-split dispatch latency through the tunnel dominates the
    engine path (measured: 46 splits of sf1 lineitem cost ~20s of
    dispatch for ~0.6s of compute). On CPU, split streaming keeps the
    working set cache-sized — the reference's page-at-a-time pipeline
    (operator/Driver.java) — so it stays the default there."""
    mode = os.environ.get("TRINO_TPU_WHOLE_TABLE", "auto")
    if mode == "auto":
        return jax.default_backend() != "cpu"
    return mode == "1"


def read_table_cached(conn, handle, columns, par) -> Optional[Batch]:
    """Whole-table read through the HBM cache: all splits concatenated
    ONCE into a single device-resident Batch cached under part=-1, so
    every later scan of the table is a dictionary lookup — no per-split
    dispatch, no per-query re-concat. The whole-table entry supersedes
    the table's per-split entries (the concat copies the lanes, so
    keeping both would double-count the budget). Returns None when the
    mode is off or the table exceeds the cache budget; callers fall
    back to split streaming."""
    if not columns or not getattr(conn, "scan_cache_ok", False) \
            or CONFIG.scan_cache_bytes <= 0 or not _whole_table_mode():
        return None
    h = handle
    wkey = (h.schema, h.table, -1, 0, h.constraint, h.limit)
    with _SCAN_CACHE_LOCK:
        state = _SCAN_CACHES.get(conn)
        entry = state["entries"].get(wkey) if state else None
        missing = [c for c in columns
                   if entry is None or c not in entry["cols"]]
        if not missing:
            _M_SCAN.inc(cache="table", result="hit")
            return Batch({c: entry["cols"][c] for c in columns},
                         entry["num_rows"])
    _M_SCAN.inc(cache="table", result="miss")
    # cheap pre-check from the handle's row estimate so an over-budget
    # table (inventory@sf10 is ~4GB of lanes) is never transiently
    # materialized whole in HBM just to discover it doesn't fit. Sized
    # on the MISSING columns only — an almost-fully-cached wide table
    # must stay admissible for its last few columns.
    est_rows = None
    if hasattr(conn, "table_row_count"):
        est_rows = conn.table_row_count(h)
    if est_rows:
        est = int(est_rows) * max(len(missing), 1) * 9  # data8+valid1
        if 2 * est > CONFIG.scan_cache_bytes:
            return None
    splits = conn.get_splits(h, par)
    if len(splits) == 1:
        return read_split_cached(conn, splits[0], columns)
    parts = [read_split_cached(conn, s, missing) for s in splits]
    total_bytes = sum(_col_bytes(c) for b in parts
                      for c in b.columns.values())
    # concat pads up to the next capacity bucket: budget 2x the raw size
    if 2 * total_bytes > CONFIG.scan_cache_bytes:
        return None
    whole = device_concat(parts)
    with _SCAN_CACHE_LOCK:
        state = _SCAN_CACHES.get(conn)
        if state is None:
            state = {"entries": {}, "order": [], "bytes": 0}
            _SCAN_CACHES[conn] = state
        for k in [k for k in state["order"]
                  if k[:2] == (h.schema, h.table) and k[2] >= 0]:
            old = state["entries"].pop(k, None)
            state["order"].remove(k)
            if old is not None:
                state["bytes"] -= sum(_col_bytes(c)
                                      for c in old["cols"].values())
        size = sum(_col_bytes(c) for c in whole.columns.values())
        while state["bytes"] + size > CONFIG.scan_cache_bytes \
                and state["order"]:
            old_key = state["order"].pop(0)
            old = state["entries"].pop(old_key, None)
            if old is not None:
                state["bytes"] -= sum(_col_bytes(c)
                                      for c in old["cols"].values())
        entry = state["entries"].get(wkey)
        if entry is None:
            entry = {"cols": {}, "num_rows": whole.num_rows}
            state["entries"][wkey] = entry
            state["order"].append(wkey)
        for name, col in whole.columns.items():
            if name not in entry["cols"]:
                entry["cols"][name] = col
                state["bytes"] += _col_bytes(col)
        _M_SCAN_BYTES.set(state["bytes"],
                          connector=getattr(conn, "name",
                                            type(conn).__name__))
        entry = state["entries"].get(wkey)
        if entry is not None and all(c in entry["cols"]
                                     for c in columns):
            return Batch({c: entry["cols"][c] for c in columns},
                         entry["num_rows"])
    # the budget evicted our own entry mid-insert: stream instead
    return None


def _amf_post(sym: str, k: int):
    def post(out: Batch) -> Column:
        from .complex import top_k_map_entries
        return top_k_map_entries(out.column(sym), k)
    return post


def _single_row(src: Batch) -> Batch:
    return Batch({"__one$": Column(
        BIGINT, jnp.zeros((8,), jnp.int64), None)}, 1)


# --------------------------------------------------------------------------
# aggregate lowering (avg & friends -> segment-op primitives)
# --------------------------------------------------------------------------

def _lower_aggregates(aggregates: Dict[str, Aggregate], src: Batch):
    """Map logical aggregates onto the kernel-supported kinds
    (sum/count/count_star/min/max/any_value), returning
    (phys_aggs, post_fns, extra_columns). The decomposition mirrors the
    reference's accumulator states (e.g. avg = LongAndDoubleState,
    variance = CentralMomentsState —
    operator/aggregation/AverageAggregations.java, CentralMomentsState)."""
    phys: List[AggInput] = []
    post = {}
    extra: Dict[str, Column] = {}

    for sym, a in aggregates.items():
        kind = a.kind
        if kind == "count" and a.distinct:
            phys.append(AggInput("count_distinct", a.argument, a.mask,
                                 sym))
        elif kind in ("sum", "min", "max", "count", "count_star"):
            phys.append(AggInput(kind, a.argument, a.mask, sym))
        elif kind in ("any_value", "arbitrary"):
            phys.append(AggInput("any_value", a.argument, a.mask, sym))
        elif kind == "avg":
            ssym, csym = sym + "$sum", sym + "$cnt"
            phys.append(AggInput("sum", a.argument, a.mask, ssym))
            phys.append(AggInput("count", a.argument, a.mask, csym))
            post[sym] = _avg_post(ssym, csym, a.type)
        elif kind == "count_if":
            msym = sym + "$mask"
            arg = src.column(a.argument)
            m = jnp.asarray(arg.data).astype(bool)
            if arg.valid is not None:
                m = m & jnp.asarray(arg.valid)
            if a.mask is not None:
                mc = src.column(a.mask)
                mm = jnp.asarray(mc.data).astype(bool)
                if mc.valid is not None:
                    mm = mm & jnp.asarray(mc.valid)
                m = m & mm
            extra[msym] = Column(BOOLEAN, m, None)
            phys.append(AggInput("count_star", None, msym, sym))
        elif kind in ("bool_and", "every", "bool_or"):
            op = "min" if kind in ("bool_and", "every") else "max"
            phys.append(AggInput(op, a.argument, a.mask, sym))
        elif kind in ("stddev", "stddev_samp", "stddev_pop", "variance",
                      "var_samp", "var_pop"):
            bsym, d, bvalid = _stat_lane(src, a.argument, extra,
                                         sym + "$f")
            sqsym = sym + "$sq"
            extra[sqsym] = Column(DOUBLE, d * d, bvalid)
            ssym, csym, s2sym = sym + "$s", sym + "$c", sym + "$s2"
            phys.append(AggInput("sum", bsym, a.mask, ssym))
            phys.append(AggInput("count", bsym, a.mask, csym))
            phys.append(AggInput("sum", sqsym, a.mask, s2sym))
            pop = kind.endswith("_pop")
            sqrt = kind.startswith("stddev")
            post[sym] = _variance_post(ssym, csym, s2sym, pop, sqrt)
        elif kind == "geometric_mean":
            lsym = sym + "$ln"
            _, d, bvalid = _stat_lane(src, a.argument, extra, sym + "$f")
            extra[lsym] = Column(DOUBLE, jnp.log(d), bvalid)
            ssym, csym = sym + "$s", sym + "$c"
            phys.append(AggInput("sum", lsym, a.mask, ssym))
            phys.append(AggInput("count", lsym, a.mask, csym))
            post[sym] = _geomean_post(ssym, csym)
        elif kind in ("bitwise_and_agg", "bitwise_or_agg"):
            phys.append(AggInput(
                "bit_and" if kind == "bitwise_and_agg" else "bit_or",
                a.argument, a.mask, sym))
        elif kind in ("min_by", "max_by"):
            phys.append(AggInput(
                "argmin" if kind == "min_by" else "argmax",
                a.argument, a.mask, sym, input2=a.argument2))
        elif kind == "approx_distinct":
            phys.append(AggInput("count_distinct", a.argument, a.mask,
                                 sym))
        elif kind == "approx_set":
            # param (if present) is the requested max standard error;
            # translate to a bucket-count exponent once at plan time
            from ..ops.hll import (APPROX_SET_BUCKET_BITS,
                                   bucket_bits_for_error)
            b = (bucket_bits_for_error(float(a.param))
                 if a.param is not None else APPROX_SET_BUCKET_BITS)
            phys.append(AggInput("hll", a.argument, a.mask, sym,
                                 param=float(b)))
        elif kind == "merge":
            from ..types import QDigestType, TDigestType
            argt = src.column(a.argument).type
            mk = ("digest_merge"
                  if isinstance(argt, (TDigestType, QDigestType))
                  else "hll_merge")
            phys.append(AggInput(mk, a.argument, a.mask, sym))
        elif kind in ("tdigest_agg", "qdigest_agg"):
            phys.append(AggInput(
                "tdigest" if kind == "tdigest_agg" else "qdigest",
                a.argument, a.mask, sym, input2=a.argument2,
                param=a.param))
        elif kind == "array_agg":
            phys.append(AggInput("array_agg", a.argument, a.mask, sym))
        elif kind == "map_agg":
            phys.append(AggInput("map_agg", a.argument, a.mask, sym,
                                 input2=a.argument2))
        elif kind == "map_union":
            phys.append(AggInput("map_union", a.argument, a.mask, sym))
        elif kind == "multimap_agg":
            phys.append(AggInput("multimap_agg", a.argument, a.mask, sym,
                                 input2=a.argument2))
        elif kind == "numeric_histogram":
            phys.append(AggInput("numeric_histogram", a.argument, a.mask,
                                 sym, input2=a.argument2, param=a.param))
        elif kind == "histogram":
            phys.append(AggInput("histogram", a.argument, a.mask, sym))
        elif kind == "approx_most_frequent":
            # exact histogram then keep the k most frequent entries
            # (reference approximates with a stream summary —
            # operator/aggregation/approxmostfrequent/; exact is a
            # correct superset)
            phys.append(AggInput("histogram", a.argument, a.mask, sym))
            k = int(a.param) if a.param is not None else 3
            post[sym] = _amf_post(sym, k)
        elif kind == "approx_percentile":
            phys.append(AggInput("percentile", a.argument, a.mask, sym,
                                 param=a.param))
        elif kind == "checksum":
            # order-independent multiset hash: wraparound int64 sum of
            # per-row hashes; NULL contributes a fixed odd constant
            # (reference: operator/aggregation/ChecksumAggregation —
            # xxhash64-based, ours is the engine hash of ops/hashing.py)
            from ..ops.hashing import hash_column as _hcol, mix64 as _mix
            arg = src.column(a.argument)
            hsym = sym + "$h"
            h = _hcol(arg.data, arg.valid)
            if arg.data2 is not None:
                h = h * jnp.uint64(31) + _hcol(arg.data2, arg.valid)
            valid_row = (jnp.ones((h.shape[0],), bool)
                         if arg.valid is None else jnp.asarray(arg.valid))
            h = jnp.where(valid_row, h,
                          jnp.uint64(0x9E3779B97F4A7C15))
            extra[hsym] = Column(BIGINT, h.astype(jnp.int64), None)
            phys.append(AggInput("sum", hsym, a.mask, sym))
        elif kind in ("corr", "covar_samp", "covar_pop", "regr_slope",
                      "regr_intercept"):
            # sum-of-products lowering over PAIRWISE-valid rows
            # (reference: CovarianceAggregation / CorrelationAggregation
            # / RegressionAggregation states)
            _, yd, yv = _stat_lane(src, a.argument, extra, sym + "$fy")
            _, xd, xv = _stat_lane(src, a.argument2, extra, sym + "$fx")
            pv = None
            for v in (yv, xv):
                if v is not None:
                    v = jnp.asarray(v)
                    pv = v if pv is None else pv & v
            names = {}
            lanes = {"y": yd, "x": xd, "xy": xd * yd, "xx": xd * xd}
            if kind == "corr":
                lanes["yy"] = yd * yd
            for tag, d in lanes.items():
                lsym = f"{sym}${tag}"
                extra[lsym] = Column(DOUBLE, d, pv)
                ssym = f"{sym}$s{tag}"
                phys.append(AggInput("sum", lsym, a.mask, ssym))
                names[tag] = ssym
            csym = sym + "$n"
            phys.append(AggInput("count", f"{sym}$x", a.mask, csym))
            post[sym] = _bivariate_post(kind, names, csym)
        elif kind in ("skewness", "kurtosis"):
            bsym, d, bvalid = _stat_lane(src, a.argument, extra,
                                         sym + "$f")
            names = {}
            for p, tag in ((2, "2"), (3, "3"), (4, "4")):
                if p == 4 and kind != "kurtosis":
                    continue
                lsym = f"{sym}$p{tag}"
                extra[lsym] = Column(DOUBLE, d ** p, bvalid)
                ssym = f"{sym}$s{tag}"
                phys.append(AggInput("sum", lsym, a.mask, ssym))
                names[tag] = ssym
            ssym, csym = sym + "$s1", sym + "$n"
            phys.append(AggInput("sum", bsym, a.mask, ssym))
            phys.append(AggInput("count", bsym, a.mask, csym))
            post[sym] = _moments_post(kind, ssym, names, csym)
        else:
            raise QueryError(f"aggregate '{kind}' not implemented")
    return phys, post, extra


def _stat_lane(src: Batch, name: str, extra: Dict[str, Column],
               tag: str):
    """(symbol, f64 lane, validity) of a numeric input for the
    statistical aggregates — DECIMAL lanes are unscaled to doubles
    (their storage is the scaled integer)."""
    col = src.column(name)
    d = jnp.asarray(col.data).astype(jnp.float64)
    if isinstance(col.type, DecimalType):
        if col.data2 is not None:
            raise QueryError(
                "statistical aggregates over DECIMAL(p>18) are not "
                "supported")
        d = d / (10.0 ** col.type.scale)
        extra[tag] = Column(DOUBLE, d, col.valid)
        return tag, d, col.valid
    return name, d, col.valid


def _bivariate_post(kind: str, s: Dict[str, str], csym: str):
    """corr/covar/regr finishers from pairwise sums. Formulas match the
    reference accumulator states (CovarianceState etc.)."""
    def fn(out: Batch) -> Column:
        n = jnp.asarray(out.column(csym).data).astype(jnp.float64)
        sy = jnp.asarray(out.column(s["y"]).data).astype(jnp.float64)
        sx = jnp.asarray(out.column(s["x"]).data).astype(jnp.float64)
        sxy = jnp.asarray(out.column(s["xy"]).data).astype(jnp.float64)
        sxx = jnp.asarray(out.column(s["xx"]).data).astype(jnp.float64)
        nn = jnp.maximum(n, 1.0)
        co = sxy - sx * sy / nn          # n * cov_pop
        mxx = sxx - sx * sx / nn         # n * var_pop(x)
        if kind == "covar_pop":
            data, valid = co / nn, n > 0
        elif kind == "covar_samp":
            data, valid = co / jnp.maximum(n - 1.0, 1.0), n > 1
        elif kind == "corr":
            syy = jnp.asarray(out.column(s["yy"]).data).astype(
                jnp.float64)
            myy = syy - sy * sy / nn
            denom = jnp.sqrt(mxx * myy)
            data = co / jnp.where(denom > 0.0, denom, 1.0)
            valid = (n > 1) & (denom > 0.0)
        elif kind == "regr_slope":
            data = co / jnp.where(mxx > 0.0, mxx, 1.0)
            valid = (n > 0) & (mxx > 0.0)
        else:  # regr_intercept
            slope = co / jnp.where(mxx > 0.0, mxx, 1.0)
            data = (sy - slope * sx) / nn
            valid = (n > 0) & (mxx > 0.0)
        return Column(DOUBLE, data, valid)
    return fn


def _moments_post(kind: str, ssym: str, s: Dict[str, str], csym: str):
    """skewness/kurtosis from raw power sums via central moments
    (reference: CentralMomentsState + DoubleSkewness/Kurtosis)."""
    def fn(out: Batch) -> Column:
        n = jnp.asarray(out.column(csym).data).astype(jnp.float64)
        s1 = jnp.asarray(out.column(ssym).data).astype(jnp.float64)
        s2 = jnp.asarray(out.column(s["2"]).data).astype(jnp.float64)
        s3 = jnp.asarray(out.column(s["3"]).data).astype(jnp.float64)
        nn = jnp.maximum(n, 1.0)
        m2 = s2 - s1 * s1 / nn
        m3 = s3 - 3.0 * s1 * s2 / nn + 2.0 * s1 ** 3 / (nn * nn)
        if kind == "skewness":
            denom = jnp.where(m2 > 0.0, m2, 1.0) ** 1.5
            data = jnp.sqrt(nn) * m3 / denom
            valid = (n > 2) & (m2 > 0.0)
        else:
            s4 = jnp.asarray(out.column(s["4"]).data).astype(jnp.float64)
            m4 = (s4 - 4.0 * s1 * s3 / nn + 6.0 * s1 * s1 * s2 / (nn * nn)
                  - 3.0 * s1 ** 4 / (nn ** 3))
            m2s = jnp.where(m2 > 0.0, m2, 1.0)
            data = (nn * (nn + 1.0) / jnp.maximum(
                (nn - 1.0) * (nn - 2.0) * (nn - 3.0), 1.0)
                * (nn * m4 / (m2s * m2s))
                - 3.0 * (nn - 1.0) ** 2 / jnp.maximum(
                    (nn - 2.0) * (nn - 3.0), 1.0))
            valid = (n > 3) & (m2 > 0.0)
        return Column(DOUBLE, data, valid)
    return fn


def _avg_post(ssym, csym, rtype):
    def fn(out: Batch) -> Column:
        s = out.column(ssym)
        c = out.column(csym)
        cnt = jnp.asarray(c.data).astype(jnp.float64)
        valid = cnt > 0
        if isinstance(rtype, DecimalType) and s.data2 is not None:
            # Int128 sum: rescale sum-scale -> result-scale, then one
            # exact HALF_UP division by the count. A result scale
            # BELOW the sum scale folds the 10^k into the divisor so
            # the value rounds ONCE (divide-then-rescale rounded
            # twice, off by one ulp at .x45 boundaries — round-5
            # advisor nit). Reference: DecimalAverageAggregation.java
            from ..ops import int128 as i128
            lo = jnp.asarray(s.data).astype(jnp.int64)
            hi = jnp.asarray(s.data2).astype(jnp.int64)
            shift = rtype.scale - s.type.scale
            lo, hi = i128.rescale(lo, hi, max(shift, 0))
            cn = jnp.maximum(jnp.asarray(c.data).astype(jnp.int64), 1)
            if shift < 0:
                lo, hi = i128.div128_round_half_up_scaled(
                    lo, hi, cn, -shift)
            else:
                lo, hi = i128.div128_round_half_up_pair(
                    lo, hi, cn, jnp.zeros_like(cn))
            if rtype.is_short:
                return Column(rtype, lo, valid)
            return Column(rtype, lo, valid, data2=hi)
        num = jnp.asarray(s.data).astype(jnp.float64)
        if isinstance(s.type, DecimalType):
            num = num / (10.0 ** s.type.scale)
        data = num / jnp.maximum(cnt, 1.0)
        if isinstance(rtype, DecimalType):
            q = (jnp.sign(data) *
                 jnp.floor(jnp.abs(data) * 10.0 ** rtype.scale + 0.5))
            return Column(rtype, q.astype(jnp.int64), valid)
        if rtype is REAL:
            return Column(rtype, data.astype(jnp.float32), valid)
        return Column(rtype, data, valid)
    return fn


def _variance_post(ssym, csym, s2sym, pop: bool, sqrt: bool):
    def fn(out: Batch) -> Column:
        s = jnp.asarray(out.column(ssym).data).astype(jnp.float64)
        n = jnp.asarray(out.column(csym).data).astype(jnp.float64)
        s2 = jnp.asarray(out.column(s2sym).data).astype(jnp.float64)
        m2 = s2 - s * s / jnp.maximum(n, 1.0)
        denom = jnp.maximum(n if pop else n - 1.0, 1.0)
        v = m2 / denom
        v = jnp.maximum(v, 0.0)
        data = jnp.sqrt(v) if sqrt else v
        valid = n > (0.0 if pop else 1.0)
        return Column(DOUBLE, data, valid)
    return fn


def _geomean_post(ssym, csym):
    def fn(out: Batch) -> Column:
        s = jnp.asarray(out.column(ssym).data).astype(jnp.float64)
        n = jnp.asarray(out.column(csym).data).astype(jnp.float64)
        return Column(DOUBLE, jnp.exp(s / jnp.maximum(n, 1.0)), n > 0)
    return fn


# --------------------------------------------------------------------------
# host spill helpers (HBM -> host RAM accumulation for oversized joins)
# --------------------------------------------------------------------------

def _to_host(b: Batch, n: int) -> Batch:
    """Materialize the live prefix of ``b`` on host (numpy lanes) —
    the spill write. LazyBlock in reverse: device memory is released,
    re-upload happens lazily when a kernel touches the column."""
    cols = {}
    for s, c in b.columns.items():
        data = np.asarray(c.data)[:n].copy()
        valid = None if c.valid is None else np.asarray(c.valid)[:n].copy()
        d2 = None if c.data2 is None else np.asarray(c.data2)[:n].copy()
        cols[s] = Column(c.type, data, valid, c.dictionary, d2)
    return Batch(cols, n)


def _host_concat(chunks: Sequence[Batch], total: int) -> Batch:
    """Concatenate host-resident chunks into one host Batch."""
    cap = capacity_for(max(total, 1), minimum=8)
    names = chunks[0].names
    cols: Dict[str, Column] = {}
    for name in names:
        cs = [c.column(name) for c in chunks]
        typ = cs[0].type
        dic = cs[0].dictionary
        if dic is not None and any(c.dictionary is not dic
                                   for c in cs[1:]):
            merged = dic
            remaps = [np.arange(len(merged), dtype=np.int32)]
            for c in cs[1:]:
                merged, _, ro = merged.merge(c.dictionary)
                remaps.append(ro)
            lanes = [np.take(rm, np.asarray(c.data).astype(np.int32))
                     for c, rm in zip(cs, remaps)]
            dic = merged
        else:
            lanes = [np.asarray(c.data) for c in cs]
        data = np.concatenate(lanes)
        data = np.pad(data, (0, cap - len(data)))
        valid = None
        if any(c.valid is not None for c in cs):
            vl = [np.ones(len(np.asarray(c.data)), bool)
                  if c.valid is None else np.asarray(c.valid)
                  for c in cs]
            valid = np.pad(np.concatenate(vl), (0, cap - total))
        d2 = None
        if any(c.data2 is not None for c in cs):
            l2 = [np.zeros(len(np.asarray(c.data)), np.int64)
                  if c.data2 is None else np.asarray(c.data2)
                  for c in cs]
            d2 = np.pad(np.concatenate(l2), (0, cap - total))
        cols[name] = Column(typ, data, valid, dic, d2)
    return Batch(cols, total)


# --------------------------------------------------------------------------
# device concat (local exchange merge)
# --------------------------------------------------------------------------

def device_concat(parts: Sequence[Batch]) -> Batch:
    """Concatenate live prefixes of Batches on device.

    The gather indices are host-computed from (host) row counts — this is
    the local-exchange merge point (reference: operator/exchange/
    LocalExchange.java), a natural host sync."""
    parts = list(parts)
    if len(parts) == 1:
        return parts[0]
    counts = [p.num_rows_host() for p in parts]
    total = sum(counts)
    if total > CONFIG.max_batch_rows and any(
            isinstance(next(iter(p.columns.values())).data, np.ndarray)
            for p in parts):
        # an oversized part already spilled to host: keep the merge on
        # host RAM instead of re-materializing everything on device
        return _host_concat([_to_host(p, n)
                             for p, n in zip(parts, counts)], total)
    cap = capacity_for(max(total, 1))
    names = parts[0].names
    out_cols: Dict[str, Column] = {}
    for name in names:
        cols = [p.column(name) for p in parts]
        typ = cols[0].type
        if cols[0].elements is not None or cols[0].children is not None:
            # pooled (ARRAY/MAP/ROW) columns merge host-side with
            # rebased offsets (exec/complex.py)
            from .complex import concat_columns_host
            out_cols[name] = concat_columns_host(cols, counts, cap)
            continue
        if is_string(typ):
            merged = cols[0].dictionary
            remaps = [np.arange(len(merged), dtype=np.int32)]
            for c in cols[1:]:
                merged, _, ro = merged.merge(c.dictionary)
                remaps.append(ro)
            lanes = [jnp.take(jnp.asarray(rm),
                              jnp.asarray(c.data).astype(jnp.int32),
                              mode="clip")
                     for c, rm in zip(cols, remaps)]
        else:
            dt = np.asarray(cols[0].data).dtype
            lanes = [jnp.asarray(c.data).astype(dt) for c in cols]
        glued = jnp.concatenate(lanes)
        # host-computed index of each part's live prefix
        idx_parts = []
        offset = 0
        for c, n in zip(cols, counts):
            idx_parts.append(np.arange(n, dtype=np.int64) + offset)
            offset += c.capacity
        idx = np.concatenate(idx_parts) if idx_parts else \
            np.zeros(0, np.int64)
        idx = np.pad(idx, (0, cap - len(idx)))
        data = jnp.take(glued, jnp.asarray(idx), mode="clip")
        any_valid = any(c.valid is not None for c in cols)
        valid = None
        if any_valid:
            vlanes = [jnp.ones((c.capacity,), bool) if c.valid is None
                      else jnp.asarray(c.valid) for c in cols]
            valid = jnp.take(jnp.concatenate(vlanes), jnp.asarray(idx),
                             mode="clip")
        d2 = None
        if any(c.data2 is not None for c in cols):
            from ..columnar import hi_lane_or_fill
            d2 = jnp.take(
                jnp.concatenate([hi_lane_or_fill(c) for c in cols]),
                jnp.asarray(idx), mode="clip")
        out_cols[name] = Column(typ, data, valid,
                                merged if is_string(typ) else None, d2)
    return Batch(out_cols, total)
