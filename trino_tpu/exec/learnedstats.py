"""Learned per-operator statistics keyed by canonical program key —
the seed store for the adaptive-execution cost model (ROADMAP item 3a).

Reference parity: Trino's history-based statistics / the coordinator's
CachingCostCalculator inputs. The reference estimates selectivities
statically from connector stats; a tensor runtime can do better — it
already OBSERVES every operator's rows-in/rows-out and wall time per
execution (exec/executor.py NodeStats), so this registry turns that
exhaust into reusable priors: per (canonical program key, operator,
occurrence) an EMA of selectivity (rows_out/rows_in) and throughput
(rows_out/wall_s).

Transport mirrors the hot-shape registry (exec/hotshapes.py), which
already ships exactly these program identities: workers observe into
their process-local singleton during task execution and export
origin-stamped observation DELTAS in task status (``learnedStats``);
the coordinator's schedulers merge them at the same two sites that
merge ``hotShapes``. ``merge`` skips self-originated observations so a
worker sharing the coordinator's process (single-host runners, tests,
the bench legs) never double-counts.

Persistence: ``save``/``load`` round-trip the EMAs through a JSON file
under the coordinator's spool/history directory, so learned priors
survive coordinator restarts (served at ``GET /v1/stats`` and scanned
as ``system.runtime.operator_stats``).

Shared-runtime code: observed by executor/task threads, merged by
scheduler threads, snapshotted by HTTP handler threads — every method
takes the registry lock (the module is on the race-lint cross-module
allowlist, analysis/lint.py)."""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from ..config import CONFIG
from ..obs.metrics import LEARNED_STATS_OBSERVATIONS, LEARNED_STATS_SIZE


def plan_key_for(root) -> str:
    """Stable canonical key for a plan (sub)tree: the progkey
    structural fingerprint when the plan canonicalizes (renamed /
    reordered plans share one key — the identity the hot-shape
    registry transports), else a digest of the rendered plan tree so
    EVERY plan gets a non-empty, deterministic key."""
    try:
        from .progkey import node_fingerprint
        fp = node_fingerprint(root)
    except Exception:           # noqa: BLE001 — keying is best-effort
        fp = None
    if fp is not None:
        raw = repr(fp)
    else:
        try:
            from ..plan.nodes import plan_tree_lines
            raw = "\n".join(plan_tree_lines(root))
        except Exception:       # noqa: BLE001
            raw = repr(type(root).__name__)
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


class LearnedStatsRegistry:
    """EMA store of observed operator behavior, LRU-bounded per
    (program key, operator name, occurrence index)."""

    def __init__(self, capacity: Optional[int] = None,
                 alpha: Optional[float] = None) -> None:
        import uuid
        self._lock = threading.Lock()
        self._capacity = (capacity if capacity is not None
                          else CONFIG.learned_stats_entries)
        self._alpha = (alpha if alpha is not None
                       else CONFIG.learned_stats_alpha)
        # (key, op, idx) -> entry dict; OrderedDict end == most
        # recently observed (the LRU eviction order)
        self._ops: "OrderedDict[Tuple[str, str, int], dict]" = \
            OrderedDict()
        # observation ring for delta export: each observe()/merge()
        # appends one compact record; export_delta ships the suffix
        # recorded after the caller's seq snapshot
        self._pending: "deque[dict]" = deque(maxlen=4096)
        self._seq = 0
        # identity stamped on exported observations — merge() drops
        # self-originated ones (in-process worker dedup, same contract
        # as HotShapeRegistry.origin)
        self.origin = uuid.uuid4().hex[:12]

    # -- write side ----------------------------------------------------
    def observe(self, key: str, op: str, idx: int, rows_in: int,
                rows_out: int, wall_s: float,
                origin: Optional[str] = None,
                _outcome: str = "observed") -> None:
        """Fold one observed operator execution into the EMAs. Rows
        may be -1 (unknown); selectivity only updates when both sides
        are known, throughput when wall is non-zero."""
        now = time.time()
        sel = (rows_out / rows_in
               if rows_in is not None and rows_out is not None
               and rows_in > 0 and rows_out >= 0 else None)
        rate = (rows_out / wall_s
                if rows_out is not None and rows_out >= 0
                and wall_s and wall_s > 0 else None)
        with self._lock:
            k = (key, str(op), int(idx))
            ent = self._ops.get(k)
            if ent is None:
                ent = {"key": key, "op": str(op), "idx": int(idx),
                       "n": 0, "selectivity": None, "rows_per_s": None,
                       "rows_in": 0, "rows_out": 0, "wall_s": 0.0,
                       "updated": now}
                self._ops[k] = ent
                while len(self._ops) > max(self._capacity, 1):
                    self._ops.popitem(last=False)
            a = self._alpha
            if sel is not None:
                ent["selectivity"] = (sel if ent["selectivity"] is None
                                      else (1 - a) * ent["selectivity"]
                                      + a * sel)
            if rate is not None:
                ent["rows_per_s"] = (rate if ent["rows_per_s"] is None
                                     else (1 - a) * ent["rows_per_s"]
                                     + a * rate)
            ent["n"] += 1
            ent["rows_in"] += max(int(rows_in or 0), 0)
            ent["rows_out"] += max(int(rows_out or 0), 0)
            ent["wall_s"] += max(float(wall_s or 0.0), 0.0)
            ent["updated"] = now
            self._ops.move_to_end(k)
            self._seq += 1
            self._pending.append({
                "seq": self._seq, "key": key, "op": str(op),
                "idx": int(idx), "rows_in": int(rows_in or 0),
                "rows_out": int(rows_out or 0),
                "wall_s": float(wall_s or 0.0),
                "origin": origin or self.origin})
            LEARNED_STATS_SIZE.set(len(self._ops))
        LEARNED_STATS_OBSERVATIONS.inc(outcome=_outcome)

    def merge(self, observations: List[dict]) -> int:
        """Absorb observations exported by another process (worker
        task status riding back to the coordinator). Defensive: a
        malformed entry is skipped, never raises into the status
        path. Original origins are preserved in the pending ring, so
        a re-export through a shared-process relay still dedups at
        the true source."""
        n = 0
        for o in observations or ():
            try:
                if o.get("origin") == self.origin:
                    continue    # recorded by THIS registry already
                self.observe(str(o["key"]), str(o["op"]),
                             int(o.get("idx") or 0),
                             int(o.get("rows_in") or 0),
                             int(o.get("rows_out") or 0),
                             float(o.get("wall_s") or 0.0),
                             origin=str(o.get("origin") or ""),
                             _outcome="merged")
                n += 1
            except (KeyError, TypeError, ValueError):
                continue
        return n

    # -- delta transport -----------------------------------------------
    def seq(self) -> int:
        """Current observation sequence — the ``export_delta``
        baseline a worker snapshots before running a task."""
        with self._lock:
            return self._seq

    def export_delta(self, since: int) -> List[dict]:
        """Observations recorded after the ``since`` snapshot — the
        worker-side delta a task status ships back. Raw observations
        (not EMAs) keep the coordinator's merge additive: N statuses
        each contribute exactly the executions that happened, and the
        receiving registry applies its OWN smoothing."""
        with self._lock:
            return [dict(o) for o in self._pending
                    if o["seq"] > since]

    # -- read side -----------------------------------------------------
    def snapshot(self) -> List[dict]:
        """Every tracked operator's learned stats, most-recently
        observed first — the /v1/stats and
        system.runtime.operator_stats payload."""
        with self._lock:
            out = [dict(e) for e in self._ops.values()]
        out.reverse()
        return out

    def lookup(self, key: str, op: str, idx: int = 0) -> Optional[dict]:
        with self._lock:
            ent = self._ops.get((key, str(op), int(idx)))
            return dict(ent) if ent is not None else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._ops)

    def clear(self) -> None:
        with self._lock:
            self._ops.clear()
            self._pending.clear()
            LEARNED_STATS_SIZE.set(0)

    # -- persistence ---------------------------------------------------
    def save(self, path: str) -> bool:
        """Persist the EMAs as JSON (atomic rename). Best-effort: an
        unwritable directory must never fail a query's terminal
        bookkeeping."""
        with self._lock:
            entries = [dict(e) for e in self._ops.values()]
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"entries": entries}, f)
            os.replace(tmp, path)
            return True
        except OSError:
            return False

    def load(self, path: str) -> int:
        """Absorb a saved snapshot: absent keys adopt the persisted
        EMAs wholesale (they ARE this registry's own prior state from
        before a restart); keys already live keep their fresher
        in-memory values."""
        try:
            with open(path) as f:
                entries = (json.load(f) or {}).get("entries") or []
        except (OSError, ValueError):
            return 0
        n = 0
        now = time.time()
        with self._lock:
            for e in entries:
                try:
                    k = (str(e["key"]), str(e["op"]),
                         int(e.get("idx") or 0))
                except (KeyError, TypeError, ValueError):
                    continue
                if k in self._ops:
                    continue
                self._ops[k] = {
                    "key": k[0], "op": k[1], "idx": k[2],
                    "n": max(int(e.get("n") or 0), 0),
                    "selectivity": e.get("selectivity"),
                    "rows_per_s": e.get("rows_per_s"),
                    "rows_in": max(int(e.get("rows_in") or 0), 0),
                    "rows_out": max(int(e.get("rows_out") or 0), 0),
                    "wall_s": max(float(e.get("wall_s") or 0.0), 0.0),
                    "updated": float(e.get("updated") or now)}
                n += 1
            while len(self._ops) > max(self._capacity, 1):
                self._ops.popitem(last=False)
            LEARNED_STATS_SIZE.set(len(self._ops))
        return n


# the process-wide registry (coordinator and worker alike: a worker
# observes what it executes and exports deltas via task status; the
# coordinator observes its local executions directly and merges
# worker deltas)
LEARNED_STATS = LearnedStatsRegistry()


def _session_allows(session) -> bool:
    try:
        return bool(session.get("learned_stats_enabled")) \
            if session is not None else True
    except KeyError:
        return True


def record_node_stats(plan_key: str, stats, session=None) -> int:
    """Executor-completion hook: fold one execution's per-operator
    NodeStats into the registry under ``plan_key``. Occurrence index
    disambiguates repeated operator names within one plan (same
    convention as exec/executor.py merge_node_stats). Gated per query
    by the ``learned_stats_enabled`` session property."""
    if not plan_key or not stats or not _session_allows(session):
        return 0
    seen: Dict[str, int] = {}
    n = 0
    for s in stats:
        name = getattr(s, "name", None)
        if name is None and isinstance(s, dict):
            name = s.get("name")
        if not name:
            continue
        idx = seen.get(name, 0)
        seen[name] = idx + 1
        if isinstance(s, dict):
            rows_in = int(s.get("input_rows", -1))
            rows_out = int(s.get("output_rows", -1))
            wall = float(s.get("wall_s", 0.0))
        else:
            rows_in = int(getattr(s, "input_rows", -1))
            rows_out = int(getattr(s, "output_rows", -1))
            wall = float(getattr(s, "wall_s", 0.0))
        LEARNED_STATS.observe(plan_key, str(name), idx,
                              rows_in, rows_out, wall)
        n += 1
    return n
