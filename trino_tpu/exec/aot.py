"""Ahead-of-time fragment compilation: lower + compile without data.

Reference parity: the paper's codegen layer maps to full AOT
compilation of query programs (PAPERS: Julia-to-TPU, arxiv 1810.09868)
over canonicalized operator-as-tensor-program shapes (arxiv
2203.01877). The JVM reference needs nothing like this — bytecode
generation is milliseconds — but XLA compile is 30-90s per fragment
shape, so decoupling compilation from first execution is the
difference between a worker that serves its first query at device
speed and one that stalls a fleet.

Mechanics: a hot-shape payload (exec/hotshapes.py) carries the
CANONICAL fragment (exec/progkey.py wire form) plus the observed input
lane spec at its capacity bucket. ``compile_entry`` rebuilds the exact
closure the executor would build for that program, fabricates an
argument Batch of ``jax.ShapeDtypeStruct`` avals — no real data — and
runs ``jax.jit(fn).lower(batch).compile()``. The compile:

- inserts the jitted callable into the in-process structural cache
  under the SAME canonical key the executor probes
  (``_CHAIN_JIT_CACHE`` / ``_STREAM_JIT_CACHE``), and
- writes the compiled program into jax's persistent compilation cache
  (config.py), so even a later signature variation (a different
  capacity bucket, a fresh dictionary identity) pays only a re-trace,
  never the XLA compile.

AOT purity contract: functions lowered here must be data-independent
Python — no ``if x.item()`` / ``int(arr)`` branches on traced values
(there is no data to branch on). ``analysis/lint.py`` enforces this
statically (the ``aot-unsafe`` rule)."""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..catalog import CatalogManager
from ..obs.metrics import METRICS
from ..session import Session

_M_AOT = METRICS.counter(
    "trino_tpu_aot_compiles_total",
    "AOT fragment compilations by outcome",
    ("kind", "result"))     # result: compiled | cached | error
_M_AOT_WALL = METRICS.histogram(
    "trino_tpu_aot_compile_seconds",
    "Per-shape AOT compile wall (lower + XLA compile)",
    ("kind",))


def _aval_batch(payload: dict, schema):
    """Fabricate the argument Batch: ShapeDtypeStruct lanes at the
    recorded capacity bucket, real (small) dictionaries — everything
    jax needs to trace and compile, nothing touching real data."""
    import jax
    from ..columnar import Batch, Column, StringDictionary
    cap = int(payload["capacity"])
    cols = {}
    for ent in payload["cols"]:
        name = ent["name"]
        data = jax.ShapeDtypeStruct((cap,), np.dtype(ent["dtype"]))
        valid = (jax.ShapeDtypeStruct((cap,), np.dtype(bool))
                 if ent.get("valid") else None)
        d2 = (jax.ShapeDtypeStruct((cap,), np.dtype(ent["data2"]))
              if ent.get("data2") else None)
        dictionary = None
        if ent.get("dict") is not None:
            dictionary = StringDictionary(
                np.asarray(ent["dict"], dtype=object))
        cols[name] = Column(schema[name], data, valid, dictionary, d2)
    if payload["num_rows"] == "int":
        num_rows = cap
    else:
        import jax as _jax
        num_rows = _jax.ShapeDtypeStruct(
            (), np.dtype(payload["num_rows"]))
    return Batch(cols, num_rows)


def _peeled_fragment(payload: dict):
    """(top-down canonical nodes, fps key, input schema) from a
    fragment-carrying payload — the chain/stream/window transport."""
    from .progkey import node_fingerprint, peel_wire_fragment
    from ..plan.serde import from_jsonable
    root = from_jsonable(payload["fragment"])
    nodes, schema = peel_wire_fragment(root)
    fps = tuple(node_fingerprint(n) for n in nodes)
    if any(f is None for f in fps):
        raise ValueError("hot-shape fragment is not canonicalizable")
    return nodes, fps, schema


def _mjoin_programs(payload: dict) -> list:
    """The TWO programs of one materialized hash join (count + expand,
    exec/executor.py) from their shared payload — a join pre-warm is
    incomplete unless both phases land in the cache."""
    import jax
    from . import executor as ex
    from ..plan.serde import from_jsonable
    from .streamjoin import _spec_from_payload
    frag = from_jsonable(payload["fragment"])
    pschema, bschema = dict(frag.left.schema), dict(frag.right.schema)
    pkeys = [c.left for c in frag.criteria]
    bkeys = [c.right for c in frag.criteria]
    pcap = int(payload["chunk_capacity"])
    bcap = int(payload["build_capacity"])
    out_cap = int(payload["out_capacity"])
    pspec = _spec_from_payload(payload["probe_cols"])
    bspec = _spec_from_payload(payload["build_cols"])
    outer = frag.join_type == "left"
    probe = _aval_batch(
        {"cols": payload["probe_cols"], "capacity": pcap,
         "num_rows": payload.get("probe_num_rows", "int")}, pschema)
    build = _aval_batch(
        {"cols": payload["build_cols"], "capacity": bcap,
         "num_rows": payload.get("build_num_rows", "int")}, bschema)

    def i64(n: int):
        return jax.ShapeDtypeStruct((n,), np.dtype(np.int64))

    ckey = ex.mjoin_count_key(outer, pkeys, bkeys, pspec, bspec,
                              pcap, bcap)
    ekey = ex.mjoin_expand_key(frag.join_type, repr(frag.filter),
                               pspec, bspec, pcap, bcap, out_cap)
    return [
        (ckey, ex.make_mjoin_count_program(pkeys, bkeys, outer),
         (probe, build), ex._MJOIN_JIT_CACHE),
        (ekey, ex.make_mjoin_expand_program(frag.join_type,
                                            frag.filter, out_cap),
         (probe, build, i64(pcap), i64(pcap), i64(bcap)),
         ex._MJOIN_JIT_CACHE)]


def _repartition_program(payload: dict) -> tuple:
    import jax
    from ..stage import repartition as rp
    nkeys = int(payload["nkeys"])
    cap = int(payload["capacity"])
    nparts = int(payload["nparts"])
    lanes = tuple(jax.ShapeDtypeStruct((cap,), np.dtype(np.uint64))
                  for _ in range(nkeys))
    valids = tuple(jax.ShapeDtypeStruct((cap,), np.dtype(bool))
                   for _ in range(nkeys))
    return (rp.bucket_program_key(nkeys, cap, nparts),
            rp.make_bucket_program(nkeys, nparts), (lanes, valids),
            rp._BUCKET_JIT_CACHE)


def compile_entry(entry: dict) -> Optional[float]:
    """AOT-compile one hot-shape registry entry — every jitted program
    the entry's shape needs (a materialized join carries two: count +
    expand). Returns the total compile wall in seconds, or None when
    all programs were already resident in their in-process caches (a
    hit — nothing to do). Raises on a broken payload; callers treat
    per-entry failures as skippable."""
    import jax
    from . import executor as ex

    payload = entry["payload"] if "payload" in entry else entry
    kind = str(payload["kind"])
    if kind == "streamjoin":
        # streamed-join probe programs (exec/streamjoin.py) carry
        # their own transport form: a JoinNode over two schema-
        # carrying RemoteSource leaves + both sides' lane specs, so a
        # pre-warming worker compiles the chunk kernel at its
        # canonical chunk capacity too
        from .streamjoin import _JOIN_JIT_CACHE, aot_entry
        key, fn, args = aot_entry(payload)
        programs = [(key, fn, args, _JOIN_JIT_CACHE)]
    elif kind == "join":
        # materialized hash join: same wire form as streamjoin, two
        # programs (exec/executor.py mjoin count/expand)
        programs = _mjoin_programs(payload)
    elif kind == "repartition":
        # the exchange bucketing kernel (stage/repartition.py) — no
        # fragment, just the (key count, capacity, nparts) signature
        programs = [_repartition_program(payload)]
    elif kind == "window":
        from .window import execute_window
        nodes, fps, schema = _peeled_fragment(payload)
        wnode = nodes[0]

        def wfn(b):
            return execute_window(b, wnode)
        programs = [(fps, wfn, (_aval_batch(payload, schema),),
                     ex._WINDOW_JIT_CACHE)]
    else:
        nodes, fps, schema = _peeled_fragment(payload)

        # the same helper shape the executor's structural closures
        # capture: detached (no per-query state), catalogs untouched
        # by chain evaluation
        helper = ex.Executor(CatalogManager(), Session())

        if kind == "chain":
            key = fps
            cache = ex._CHAIN_JIT_CACHE
            chain = nodes

            def fn(b):
                for nd in reversed(chain):
                    b = helper._dispatch_apply(nd, b)
                return b
        elif kind in ("stream", "stream_full"):
            # stream node stacks lead with the AggregationNode
            # (progkey.canonicalize_nodes order)
            agg, chain = nodes[0], nodes[1:]
            run, run_full = ex.make_stream_runners(helper, chain, agg)
            key = fps if kind == "stream" else (fps, "full")
            cache = ex._STREAM_JIT_CACHE
            fn = run if kind == "stream" else run_full
        else:
            raise ValueError(f"unknown hot-shape kind {kind!r}")
        programs = [(key, fn, (_aval_batch(payload, schema),), cache)]

    wall = 0.0
    compiled = False
    for key, fn, args, cache in programs:
        with ex._JIT_CACHE_LOCK:
            resident = key in cache
        if resident:
            continue
        t0 = time.perf_counter()
        try:
            jitted = jax.jit(fn)
            jitted.lower(*args).compile()
        except Exception:
            _M_AOT.inc(kind=kind, result="error")
            raise
        wall += time.perf_counter() - t0
        # the jitted callable (now holding the compiled program in its
        # own cache) lands under the executor's key: the first real
        # query with this shape is an in-process cache hit
        ex._cache_put(cache, key, jitted)
        compiled = True
    if not compiled:
        _M_AOT.inc(kind=kind, result="cached")
        return None
    _M_AOT.inc(kind=kind, result="compiled")
    _M_AOT_WALL.observe(wall, kind=kind)
    return wall


def compile_entries(entries: List[dict]) -> dict:
    """Compile a hot-shape list (best-effort, per-entry isolation):
    returns {"compiled": n, "cached": n, "errors": n, "wall_s": total}
    — the pre-warm loop's summary (server/task_worker.py)."""
    out = {"compiled": 0, "cached": 0, "errors": 0, "wall_s": 0.0}
    for e in entries or ():
        try:
            wall = compile_entry(e)
        except Exception:       # noqa: BLE001 — one bad shape must
            # not abort the warm-up of the rest
            out["errors"] += 1
            continue
        if wall is None:
            out["cached"] += 1
        else:
            out["compiled"] += 1
            out["wall_s"] += wall
    return out
