"""Distributed plan executor: SQL over the device mesh.

Reference parity: the distributed scheduler + worker stack
(SqlQueryScheduler.java:112, SqlStageExecution, the exchange layer) —
TPU-first redesign (SURVEY.md §7.4): a stage's tasks are the shards of
one SPMD program; exchanges are collectives:

- table scans: splits round-robin onto shards (SourcePartitionedScheduler
  → shard_parts)
- filter/project/partial-agg: per-shard shard_map segments
- grouped aggregation: partial → all_to_all repartition → final
  (PushPartialAggregationThroughExchange shape)
- joins: REPLICATED (broadcast build via all_gather, two-phase size probe
  — the DetermineJoinDistributionType REPLICATED branch); the
  PARTITIONED branch (repartition both sides) applies to large
  equi-inner joins
- semi joins: replicated filtering source + per-shard mask
- TopN: per-shard TopN, gather, final TopN; Sort/Window/SetOps gather to
  the coordinator shard (single-node fallback)

Data-dependent output capacities use the two-phase pattern: a counts
shard_map, a host max, then the expansion shard_map with static shapes.
"""

from __future__ import annotations

from dataclasses import replace as dc_replace
from typing import Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..catalog import CatalogManager
from ..columnar import Batch, Column
from ..config import capacity_for
from ..ops import compact, join as join_ops, sort as sort_ops
from ..ops.groupby import (COMBINABLE_KINDS as _COMBINABLE, AggInput,
                           global_aggregate, group_aggregate)
from ..parallel.mesh import (AXIS, ShardedBatch, get_mesh, shard_parts,
                             unshard_batch)
from ..parallel.spmd import (broadcast_sharded,
                             distributed_group_aggregate,
                             repartition_by_hash, repartition_dest_counts,
                             shard_apply, shard_apply2, shard_apply2s,
                             shard_totals, shard_totals2, shard_totals2s)
from ..plan.nodes import (AggregationNode, FilterNode, JoinNode, LimitNode,
                          OutputNode, PlanNode, ProjectNode, SemiJoinNode,
                          TableScanNode, TopNNode)
from ..planner.logical import SemiJoinMultiNode
from ..session import Session
from ..types import BOOLEAN, BIGINT, is_string
from .executor import (Executor, QueryError, _Pre, _lower_aggregates,
                       device_concat, join_verify_filter)
from .expr import eval_expr, eval_predicate

Value = Union[Batch, ShardedBatch]

# below this estimated build-side row count a join build is broadcast
# (DetermineJoinDistributionType's size heuristic)
BROADCAST_LIMIT = 1 << 20
# a relation smaller than this isn't worth sharding at all
MIN_SHARD_ROWS = 1 << 12


class DistributedExecutor(Executor):
    """Executor whose intermediate values may be row-sharded across the
    mesh. Nodes without a distributed strategy gather to the host and
    reuse the local implementation (COORDINATOR_ONLY fallback)."""

    def __init__(self, catalogs: CatalogManager, session: Session,
                 mesh=None, collect_stats: bool = False):
        super().__init__(catalogs, session, collect_stats)
        self.mesh = mesh or get_mesh()
        # ICI-native stage execution (stage/ici.py): the ROOT execute
        # call tries to cut the plan into the same StageDAG the remote
        # scheduler runs and execute it here with device-collective
        # exchanges; stage bodies then recurse through this executor
        # with RemoteSource leaves resolving in _ici_values
        self._ici_tried = False
        self._ici_values = None

    # -- helpers ---------------------------------------------------------
    def _host(self, v: Value) -> Batch:
        return unshard_batch(v) if isinstance(v, ShardedBatch) else v

    def execute_host(self, node: PlanNode) -> Batch:
        return self._host(self.execute(node))

    def execute(self, node: PlanNode):  # type: ignore[override]
        cancel = getattr(self.session, "cancel", None)
        if cancel is not None and cancel.is_set():
            raise QueryError("Query was canceled")
        if not self._ici_tried:
            # one attempt, at the root plan only: recursive execute
            # calls (stage bodies included) take the node path below
            self._ici_tried = True  # tt-lint: ignore[race-attr-write] an executor instance is owned by ONE query/task thread for its lifetime
            out = self._try_ici_stages(node)
            if out is not None:
                return out

        def inner():
            method = getattr(self, "_dexec_" + type(node).__name__,
                             None)
            if method is not None:
                return method(node)
            # local fallback: materialize sharded sources on host
            return self._exec_local(node)

        if not self.collect_stats:
            return inner()
        # same per-node stats discipline as the local executor
        # (previously the mesh path silently collected nothing)
        return self._stats_wrap(node, inner)

    def _try_ici_stages(self, plan: PlanNode) -> Optional[Batch]:
        """Route the plan through the stage DAG with ICI-native
        exchange (stage/ici.py) when the fragmenter admits it — the
        unification of this mesh executor with the stage scheduler:
        one fragmenter, one DAG shape, collectives instead of
        spool+HTTP for every in-slice edge. Declined plans (None) keep
        the node-at-a-time distributed path below."""
        try:
            if not (bool(self.session.get("multistage_execution"))
                    and bool(self.session.get("ici_exchange"))):
                return None
        except KeyError:        # foreign session without the knobs
            return None
        if self.mesh.devices.size < 2:
            return None
        from ..stage.fragmenter import StageFragmenter
        dag = StageFragmenter(self.catalogs, self.session).fragment(plan)
        if dag is None:
            return None
        from ..stage.ici import IciStageExecution
        return IciStageExecution(self, dag).run()

    def _dexec_RemoteSourceNode(self, node) -> Value:
        """In-slice exchange: a stage body's RemoteSource resolves to
        the producer stage's device-resident value (stage/ici.py) —
        no frames, no wire. Outside an ICI stage run this node has no
        mesh meaning and takes the local (exchange reader) path."""
        if self._ici_values is None:
            return self._exec_local(node)
        vals = [self._ici_values[int(fid)]
                for fid in node.fragment_ids]
        if len(vals) == 1:
            return vals[0]
        hosts = [self._host(v) for v in vals]
        return device_concat(hosts)

    def _exec_local(self, node: PlanNode) -> Batch:
        method = getattr(super(), "_exec_" + type(node).__name__, None)
        if method is None:
            raise QueryError(
                f"no executor for plan node {type(node).__name__}")
        # parent handlers recurse via self.execute(source) and expect
        # host Batches; pre-materialize every source (COORDINATOR_ONLY
        # gather) so sharded values never leak into local operators
        import dataclasses
        if node.sources and dataclasses.is_dataclass(node):
            updates = {}
            for f in dataclasses.fields(node):
                v = getattr(node, f.name)
                if isinstance(v, PlanNode):
                    updates[f.name] = _Pre(self.execute_host(v))
                elif isinstance(v, tuple) and v and all(
                        isinstance(x, PlanNode) for x in v):
                    updates[f.name] = tuple(
                        _Pre(self.execute_host(x)) for x in v)
            if updates:
                node = dc_replace(node, **updates)
        return method(node)

    # make the parent's recursive self.execute(source) calls transparent:
    # any source executed through the parent class must come back as a
    # host Batch
    def _exec_lifted(self, node: PlanNode) -> Batch:
        return self.execute_host(node)

    # -- leaves ----------------------------------------------------------
    def _dexec_TableScanNode(self, node: TableScanNode) -> Value:
        conn = self.catalogs.connector(node.handle.catalog)
        columns = sorted(set(node.assignments.values()))
        n = self.mesh.devices.size
        splits = conn.get_splits(node.handle, n)
        est = conn.table_row_count(node.handle) or 0
        if len(splits) == 1 and est < MIN_SHARD_ROWS:
            return self._exec_local(node)
        per_dev = [[] for _ in range(n)]
        for i, s in enumerate(splits):
            per_dev[i % n].append(s)
        parts = []
        for d in range(n):
            # _read_split = read_split_cached + telemetry (split
            # counter, SplitCompletedEvent, input-flow accounting)
            batches = [self._read_split(conn, s, columns)
                       for s in per_dev[d]]
            if not batches:
                from ..columnar import empty_batch
                meta = conn.get_table_metadata(node.handle.schema,
                                               node.handle.table)
                batches = [empty_batch(
                    {c.name: c.type for c in meta.columns
                     if c.name in set(columns)})]
            parts.append(device_concat(batches)
                         if len(batches) > 1 else batches[0])
        sb = shard_parts(parts, self.mesh)
        # rename connector columns to plan symbols
        cols = {sym: sb.columns[col]
                for sym, col in node.assignments.items()}
        return ShardedBatch(cols, sb.num_rows, sb.mesh, sb.per_shard_cap)

    # -- per-shard pipeline segments ------------------------------------
    def _dexec_FilterNode(self, node: FilterNode) -> Value:
        src = self.execute(node.source)
        if not isinstance(src, ShardedBatch):
            return super()._exec_FilterNode(
                dc_replace(node, source=_Pre(src)))
        return shard_apply(
            src, lambda b: compact.filter_batch(
                b, eval_predicate(node.predicate, b)))

    def _dexec_ProjectNode(self, node: ProjectNode) -> Value:
        src = self.execute(node.source)
        if not isinstance(src, ShardedBatch):
            return super()._exec_ProjectNode(
                dc_replace(node, source=_Pre(src)))
        return shard_apply(
            src, lambda b: Batch({s: eval_expr(e, b)
                                  for s, e in node.assignments.items()},
                                 b.num_rows))

    def _dexec_OutputNode(self, node: OutputNode) -> Batch:
        src = self._host(self.execute(node.source))
        return Batch({s: src.column(s) for s in node.symbols},
                     src.num_rows)

    def _dexec_LimitNode(self, node: LimitNode) -> Batch:
        src = self.execute(node.source)
        if isinstance(src, ShardedBatch):
            # per-shard pre-limit bounds the gather to n * count rows
            src = shard_apply(
                src, lambda b: compact.limit_batch(b, node.count))
            src = unshard_batch(src)
        return compact.limit_batch(src, node.count)

    def _dexec_TopNNode(self, node: TopNNode) -> Value:
        src = self.execute(node.source)
        keys = [sort_ops.SortKey(k.symbol, k.ascending, k.nulls_first)
                for k in node.keys]
        if isinstance(src, ShardedBatch):
            # per-shard partial TopN, gather, final TopN
            src = shard_apply(
                src, lambda b: sort_ops.topn_batch(b, keys, node.count))
            src = unshard_batch(src)
        return sort_ops.topn_batch(src, keys, node.count)

    def _dexec_SortNode(self, node) -> Value:
        """Distributed sort (distributed_sort session property): sampled
        range exchange + per-shard sort, replacing the gather-to-
        coordinator fallback. Reference: operator/MergeOperator.java
        (sorted merge exchange) — TPU-first: shard i receives the i-th
        ORDER BY slice via an all_to_all range repartition, sorts it
        locally, and shard-major gather order IS the global order."""
        src = self.execute(node.source)
        if not isinstance(src, ShardedBatch):
            return super()._exec_SortNode(
                dc_replace(node, source=_Pre(src)))
        keys = [sort_ops.SortKey(k.symbol, k.ascending, k.nulls_first)
                for k in node.keys]
        key_cols = [src.columns[k.column] for k in keys
                    if k.column in src.columns]
        distributable = (
            bool(self.session.get("distributed_sort"))
            and src.n_shards > 1
            and src.total_rows_host() >= MIN_SHARD_ROWS
            and all(c.elements is None for c in src.columns.values())
            and all(c.data2 is None for c in key_cols))
        if not distributable:
            return super()._exec_SortNode(
                dc_replace(node, source=_Pre(self._host(src))))
        from ..parallel.spmd import (range_dest_counts,
                                     repartition_by_range,
                                     sample_range_splitters)
        splitters = sample_range_splitters(src, keys)
        if splitters is None:  # empty relation
            return super()._exec_SortNode(
                dc_replace(node, source=_Pre(self._host(src))))
        counts = range_dest_counts(src, keys, splitters)
        cap = capacity_for(max(int(jnp.max(counts)), 1))
        rp = repartition_by_range(src, keys, splitters, out_cap=cap)
        return shard_apply(
            rp, lambda b: sort_ops.sort_batch(b, keys), cap)

    # -- window ----------------------------------------------------------
    def _dexec_WindowNode(self, node) -> Value:
        """Distributed window: hash-repartition on the PARTITION BY
        keys, run the window kernel per shard — every partition is
        wholly on one shard, so per-shard evaluation is exact.
        Reference: operator/WindowOperator.java downstream of a
        partitioned exchange (AddExchanges window rule); replaces the
        gather-to-coordinator fallback (round-4 verdict weak #6)."""
        src = self.execute(node.source)
        if not isinstance(src, ShardedBatch):
            return super()._exec_WindowNode(
                dc_replace(node, source=_Pre(src)))
        pkeys = list(node.partition_by)
        distributable = (
            bool(pkeys)
            and all(k in src.columns for k in pkeys)
            and src.n_shards > 1
            and src.total_rows_host() >= MIN_SHARD_ROWS
            and all(c.elements is None for c in src.columns.values())
            and all(src.columns[k].data2 is None for k in pkeys))
        if not distributable:
            return super()._exec_WindowNode(
                dc_replace(node, source=_Pre(self._host(src))))
        from ..parallel.spmd import (repartition_by_hash,
                                     repartition_dest_counts)
        from .window import execute_window
        counts = repartition_dest_counts(src, pkeys)
        cap = capacity_for(max(int(jnp.max(counts)), 1))
        rp = repartition_by_hash(src, pkeys, out_cap=cap)
        try:
            return shard_apply(rp, lambda b: execute_window(b, node),
                               cap)
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            # a window shape the kernel can't trace (host-side frame
            # math): correctness first, gather and run locally
            return super()._exec_WindowNode(
                dc_replace(node, source=_Pre(self._host(src))))

    # -- set operations --------------------------------------------------
    def _dexec_SetOpNode(self, node) -> Value:
        """Distributed INTERSECT/EXCEPT: schema-align both sides, hash
        -repartition each on ALL output columns (equal rows co-locate),
        then run the tag+group+filter kernel per shard. Reference:
        SetOperationNodeUtils + partitioned exchange; replaces the
        gather fallback (round-4 verdict weak #6)."""
        from .executor import setop_batches
        left = self.execute(node.left)
        right = self.execute(node.right)
        out_syms = list(node.schema)

        def align(v: Value, m: Dict[str, str]) -> Value:
            # pure rename/subset: no device pass needed either way
            if isinstance(v, ShardedBatch):
                return ShardedBatch(
                    {o: v.columns[i] for o, i in m.items()},
                    v.num_rows, v.mesh, v.per_shard_cap)
            return Batch({o: v.column(i) for o, i in m.items()},
                         v.num_rows)

        lb = align(left, node.left_map)
        rb = align(right, node.right_map)
        distributable = (
            isinstance(lb, ShardedBatch) and isinstance(rb, ShardedBatch)
            and lb.n_shards > 1
            and (lb.total_rows_host() + rb.total_rows_host()
                 >= MIN_SHARD_ROWS)
            and all(c.elements is None and c.data2 is None
                    for v in (lb, rb) for c in v.columns.values()))
        if not distributable:
            hb_l = self._host(lb) if isinstance(lb, ShardedBatch) else lb
            hb_r = self._host(rb) if isinstance(rb, ShardedBatch) else rb
            return setop_batches(hb_l, hb_r, node.op, node.distinct,
                                 out_syms)
        from ..parallel.spmd import (repartition_by_hash,
                                     repartition_dest_counts)
        lb, rb = _align_setop_dicts(lb, rb, out_syms)
        lc = repartition_dest_counts(lb, out_syms)
        rc = repartition_dest_counts(rb, out_syms)
        lcap = capacity_for(max(int(jnp.max(lc)), 1))
        rcap = capacity_for(max(int(jnp.max(rc)), 1))
        lrp = repartition_by_hash(lb, out_syms, out_cap=lcap)
        rrp = repartition_by_hash(rb, out_syms, out_cap=rcap)
        out_cap = capacity_for(lcap + rcap)
        return shard_apply2s(
            lrp, rrp,
            lambda a, b: _setop_traced(a, b, node.op, node.distinct,
                                       out_syms, out_cap),
            out_cap)

    # -- aggregation -----------------------------------------------------
    def _dexec_AggregationNode(self, node: AggregationNode) -> Value:
        src = self.execute(node.source)
        if not isinstance(src, ShardedBatch):
            return super()._exec_AggregationNode(
                dc_replace(node, source=_Pre(src)))
        if any(a.kind in ("array_agg", "map_agg", "histogram",
                          "approx_most_frequent", "map_union",
                          "multimap_agg", "numeric_histogram",
                          "tdigest_agg", "qdigest_agg",
                          "approx_set", "merge")
               for a in node.aggregates.values()):
            # array/map offsets don't survive shard-local numbering;
            # gather to the coordinator shard and aggregate locally
            return super()._exec_AggregationNode(
                dc_replace(node, source=_Pre(self._host(src))))
        # lower avg & friends against the global sharded lanes (extra
        # columns are elementwise — they stay sharded)
        glob = Batch(src.columns, 0)
        phys, post, extra = _lower_aggregates(node.aggregates, glob)
        if extra:
            cols = dict(src.columns)
            cols.update(extra)
            src = ShardedBatch(cols, src.num_rows, src.mesh,
                               src.per_shard_cap)
        if node.group_keys:
            out = distributed_group_aggregate(src, list(node.group_keys),
                                              phys)
            if post:
                cols = dict(out.columns)
                host_view = Batch(out.columns, 0)
                for sym, fn in post.items():
                    cols[sym] = fn(host_view)
                keep = set(node.group_keys) | set(node.aggregates)
                cols = {s: c for s, c in cols.items() if s in keep}
                out = ShardedBatch(cols, out.num_rows, out.mesh,
                                   out.per_shard_cap)
            return out
        # global aggregation: per-shard partials -> gather -> combine
        if not phys:
            return self._single_row(None)
        if any(a.kind not in _COMBINABLE for a in phys):
            # non-decomposable kinds: gather rows, aggregate exactly
            return super()._exec_AggregationNode(
                dc_replace(node, source=_Pre(self._host(src))))
        partial = shard_apply(
            src, lambda b: _pad_one(global_aggregate(b, phys)),
            out_cap=8)
        gathered = unshard_batch(partial)
        finals = [AggInput(_combine_kind(a.kind), a.output, None,
                           a.output) for a in phys]
        out = global_aggregate(gathered, finals)
        if post:
            cols = dict(out.columns)
            for sym, fn in post.items():
                cols[sym] = fn(out)
            keep = set(node.aggregates)
            cols = {s: c for s, c in cols.items() if s in keep}
            out = Batch(cols, 1)
        return out

    # -- joins -----------------------------------------------------------
    def _dexec_JoinNode(self, node: JoinNode) -> Value:
        jt = node.join_type
        if jt == "right":
            # swap before executing children so subtrees run only once
            from ..plan.nodes import JoinClause
            return self._dexec_JoinNode(JoinNode(
                node.right, node.left, "left",
                tuple(JoinClause(c.right, c.left) for c in node.criteria),
                node.filter))
        left = self.execute(node.left)
        right = self.execute(node.right)
        if not isinstance(left, ShardedBatch) and \
                not isinstance(right, ShardedBatch):
            return super()._exec_JoinNode(
                dc_replace(node, left=_Pre(left), right=_Pre(right)))
        if jt == "full" or not node.criteria or jt == "cross":
            # rare shapes: host fallback
            return super()._exec_JoinNode(
                dc_replace(node, left=_Pre(self._host(left)),
                           right=_Pre(self._host(right))))

        pkeys = [c.left for c in node.criteria]
        bkeys = [c.right for c in node.criteria]
        probe = left if isinstance(left, ShardedBatch) else None
        if probe is None:
            # probe on host, build sharded: gather build, local join
            return super()._exec_JoinNode(
                dc_replace(node, left=_Pre(left),
                           right=_Pre(self._host(right))))

        # hash-collision re-verification for inexact key lanes
        # (JoinProbe real-equality semantics; see executor.py)
        node = dc_replace(node, filter=join_verify_filter(
            left.columns, right.columns, pkeys, bkeys, node.filter))

        # dynamic filtering: build-side key ranges prune probe rows
        # BEFORE any exchange (reference: DynamicFilterService.java:95 +
        # DynamicFilterSourceOperator — collect on the build, push to
        # the probe; here collection is a host reduction over the build
        # key lanes and the push is a per-shard pre-filter)
        probe = self._dynamic_filter_probe(probe, right, pkeys, bkeys,
                                           jt)

        # PARTITIONED distribution (DetermineJoinDistributionType's
        # PARTITIONED branch): hash-repartition BOTH sides on the join
        # keys so matching rows co-locate, then per-shard join — the
        # build side is never replicated (VERDICT weak #7)
        if (str(node.distribution or "").lower() == "partitioned"
                and isinstance(right, ShardedBatch)
                and jt in ("inner", "left")):
            return self._partitioned_join(node, probe, right,
                                          pkeys, bkeys, jt)

        # REPLICATED distribution: broadcast the build side
        build_host = self._host(right)
        build_host = _align_sharded_strings(probe, build_host,
                                            pkeys, bkeys)
        outer = jt == "left"

        def phase1(pb: Batch, bb: Batch):
            start, count, order = join_ops.match_counts(
                pb, bb, pkeys, bkeys)
            live = pb.row_valid()
            eff = jnp.where(live, jnp.maximum(count, 1), 0) if (
                outer and node.filter is None) else count
            return jnp.sum(eff)

        totals = shard_totals2(probe, build_host, phase1)
        out_cap = capacity_for(max(int(jnp.max(totals)), 1))
        pad_cap = probe.per_shard_cap if (outer and
                                          node.filter is not None) else 0

        def phase2(pb: Batch, bb: Batch) -> Batch:
            return _shard_join(pb, bb, pkeys, bkeys, jt, node.filter,
                               out_cap, pad_cap)

        return shard_apply2(probe, build_host, phase2, out_cap + pad_cap)

    def _dynamic_filter_probe(self, probe: ShardedBatch, build: Value,
                              pkeys, bkeys, jt: str) -> ShardedBatch:
        """Pre-exchange probe pruning from build-side key min/max
        (enable_dynamic_filtering session property). INNER joins only —
        outer probe rows must survive. Dictionary keys are skipped
        (codes are shard-local). Records rows_in/rows_kept on the
        executor for EXPLAIN/verification."""
        if jt != "inner" or not isinstance(probe, ShardedBatch):
            return probe
        if not bool(self.session.get("enable_dynamic_filtering")):
            return probe
        bounds = []
        for pk, bk in zip(pkeys, bkeys):
            pc = probe.columns[pk]
            bc = build.columns[bk]
            if pc.dictionary is not None or bc.dictionary is not None \
                    or bc.data2 is not None:
                continue
            data = np.asarray(bc.data)
            if isinstance(build, ShardedBatch):
                per = build.per_shard_cap
                counts = np.asarray(build.num_rows)
                live = (np.arange(per)[None, :]
                        < counts[:, None]).reshape(-1)
            else:
                n = build.num_rows_host()
                live = np.arange(data.shape[0]) < n
            if bc.valid is not None:
                live = live & np.asarray(bc.valid)
            vals = data[live]
            if vals.size == 0:
                bounds.append((pk, 1, 0, None, False))  # drop all
            else:
                # small-domain exact set beats min/max by orders of
                # magnitude on sparse keys (the reference's
                # discrete-values DynamicFilter domain)
                uniq = np.unique(vals)
                exact = (jnp.asarray(uniq)
                         if uniq.size <= 100_000 and
                         uniq.dtype.kind in "iu" else None)
                has_nan = (vals.dtype.kind == "f"
                           and bool(np.isnan(vals).any()))
                with np.errstate(invalid="ignore"):
                    mn = (np.nanmin(vals) if has_nan else vals.min())
                    mx = (np.nanmax(vals) if has_nan else vals.max())
                bounds.append((pk, mn, mx, exact, has_nan))
        if not bounds:
            return probe

        def f(b: Batch) -> Batch:
            mask = b.row_valid()
            for pk, mn, mx, exact, has_nan in bounds:
                c = b.column(pk)
                d = jnp.asarray(c.data)
                if exact is not None:
                    pos = jnp.searchsorted(exact, d)
                    hit = jnp.take(exact, jnp.clip(pos, 0,
                                                   exact.shape[0] - 1),
                                   mode="clip") == d
                    m = hit & (pos < exact.shape[0])
                else:
                    m = (d >= mn) & (d <= mx)
                    if has_nan:
                        # engine equality treats all NaNs as equal
                        # (ops/hashing.py), so NaN probes can match a
                        # NaN build key and must survive the filter
                        m = m | jnp.isnan(d)
                if c.valid is not None:
                    # NULL keys never match an inner join
                    m = m & jnp.asarray(c.valid)
                mask = mask & m
            return compact.filter_batch(b, mask)

        if self.collect_stats:
            before = probe.total_rows_host()
            kept = shard_apply(probe, f, probe.per_shard_cap)
            self.dynamic_filter_rows = (before,
                                        kept.total_rows_host())
            return kept
        return shard_apply(probe, f, probe.per_shard_cap)

    def _partitioned_join(self, node: JoinNode, probe: ShardedBatch,
                          build: ShardedBatch, pkeys, bkeys,
                          jt: str) -> Value:
        """Repartition both inputs by join-key hash (AddExchanges.java's
        FIXED_HASH on both children), then join shard-locally. Exchange
        capacities come from real per-destination counts (two-phase)."""
        build = _align_sharded_dicts(probe, build, pkeys, bkeys)
        pc = repartition_dest_counts(probe, pkeys)
        bc = repartition_dest_counts(build, bkeys)
        pcap = capacity_for(max(int(jnp.max(pc)), 1))
        bcap = capacity_for(max(int(jnp.max(bc)), 1))
        probe = repartition_by_hash(probe, pkeys, out_cap=pcap)
        build = repartition_by_hash(build, bkeys, out_cap=bcap)
        outer = jt == "left"

        def phase1(pb: Batch, bb: Batch):
            start, count, order = join_ops.match_counts(
                pb, bb, pkeys, bkeys)
            live = pb.row_valid()
            eff = jnp.where(live, jnp.maximum(count, 1), 0) if (
                outer and node.filter is None) else count
            return jnp.sum(eff)

        totals = shard_totals2s(probe, build, phase1)
        out_cap = capacity_for(max(int(jnp.max(totals)), 1))
        pad_cap = probe.per_shard_cap if (outer and
                                          node.filter is not None) else 0

        def phase2(pb: Batch, bb: Batch) -> Batch:
            return _shard_join(pb, bb, pkeys, bkeys, jt, node.filter,
                               out_cap, pad_cap)

        return shard_apply2s(probe, build, phase2, out_cap + pad_cap)

    def _dexec_SemiJoinNode(self, node: SemiJoinNode) -> Value:
        src = self.execute(node.source)
        if not isinstance(src, ShardedBatch):
            return super()._exec_SemiJoinNode(
                dc_replace(node, source=_Pre(src),
                           filtering_source=_Pre(self.execute_host(
                               node.filtering_source))))
        filt = self.execute_host(node.filtering_source)
        filt = _align_sharded_strings(src, filt, [node.source_key],
                                      [node.filtering_key])

        def f(b: Batch, fb: Batch) -> Batch:
            matched, key_null, build_null, nonempty = \
                join_ops.semi_join_mask(b, fb, [node.source_key],
                                        [node.filtering_key])
            valid = matched | ~nonempty | (~key_null & ~build_null)
            cols = dict(b.columns)
            cols[node.output] = Column(BOOLEAN, matched, valid)
            return Batch(cols, b.num_rows)

        return shard_apply2(src, filt, f, src.per_shard_cap)

    def _dexec_SemiJoinMultiNode(self, node: SemiJoinMultiNode) -> Value:
        src = self.execute(node.source)
        if not isinstance(src, ShardedBatch):
            return super()._exec_SemiJoinMultiNode(
                dc_replace(node, source=_Pre(src),
                           filtering_source=_Pre(self.execute_host(
                               node.filtering_source))))
        filt = self.execute_host(node.filtering_source)
        skeys = list(node.source_keys)
        fkeys = list(node.filtering_keys)
        filt = _align_sharded_strings(src, filt, skeys, fkeys)
        if skeys:
            node = dc_replace(node, filter=join_verify_filter(
                src.columns, filt.columns, skeys, fkeys, node.filter))
        if node.filter is None and skeys:
            def f(b: Batch, fb: Batch) -> Batch:
                matched, _, _, _ = join_ops.semi_join_mask(
                    b, fb, skeys, fkeys)
                cols = dict(b.columns)
                cols[node.output] = Column(BOOLEAN, matched, None)
                return Batch(cols, b.num_rows)
            return shard_apply2(src, filt, f, src.per_shard_cap)

        def phase1(pb: Batch, fb: Batch):
            if skeys:
                _, count, _ = join_ops.match_counts(pb, fb, skeys, fkeys)
                return jnp.sum(count)
            return pb.num_rows_device() * fb.num_rows_device()

        totals = shard_totals2(src, filt, phase1)
        cand_cap = capacity_for(max(int(jnp.max(totals)), 1))

        def phase2(pb: Batch, fb: Batch) -> Batch:
            ppos = "__probe_pos$"
            pcols = dict(pb.columns)
            pcols[ppos] = Column(
                BIGINT, jnp.arange(pb.capacity, dtype=jnp.int64), None)
            probe2 = Batch(pcols, pb.num_rows)
            if skeys:
                start, count, order = join_ops.match_counts(
                    probe2, fb, skeys, fkeys)
            else:
                start, count, order = join_ops.cross_counts(probe2, fb)
            cand = join_ops.expand_join(probe2, fb, start, count, order,
                                        cand_cap, "inner")
            mask = (eval_predicate(node.filter, cand)
                    if node.filter is not None else cand.row_valid())
            pp = jnp.asarray(cand.column(ppos).data)
            live = cand.row_valid() & mask
            matched = jnp.zeros((pb.capacity,), bool).at[
                jnp.where(live, pp, 0)].max(live)
            cols = dict(pb.columns)
            cols[node.output] = Column(BOOLEAN, matched, None)
            return Batch(cols, pb.num_rows)

        return shard_apply2(src, filt, phase2, src.per_shard_cap)


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

def _combine_kind(kind: str) -> str:
    return _COMBINABLE[kind]


def _align_setop_dicts(lb: ShardedBatch, rb: ShardedBatch,
                       syms) -> Tuple[ShardedBatch, ShardedBatch]:
    """Put both set-op sides' string columns on ONE merged dictionary
    (merge keeps left codes stable; right codes remap), so hash
    repartition co-locates equal strings and the per-shard group-by
    compares codes directly."""
    lcols = dict(lb.columns)
    rcols = dict(rb.columns)
    changed = False
    for s in syms:
        lc, rc = lcols.get(s), rcols.get(s)
        if lc is None or rc is None or lc.dictionary is None \
                or rc.dictionary is None \
                or lc.dictionary is rc.dictionary:
            continue
        merged, _, ro = lc.dictionary.merge(rc.dictionary)
        rcols[s] = dc_replace(
            rc, data=jnp.take(jnp.asarray(ro), jnp.asarray(rc.data),
                              mode="clip"), dictionary=merged)
        lcols[s] = dc_replace(lc, dictionary=merged)
        changed = True
    if not changed:
        return lb, rb
    return (ShardedBatch(lcols, lb.num_rows, lb.mesh, lb.per_shard_cap),
            ShardedBatch(rcols, rb.num_rows, rb.mesh, rb.per_shard_cap))


def _setop_traced(lb: Batch, rb: Batch, op: str, distinct: bool,
                  out_syms, out_cap: int) -> Batch:
    """setop_batches' shard_map-traceable twin: same tagging and
    semantics (exec/executor.py setop_tag/setop_keep_times), but a
    traced concat, a static groups capacity, and a device-scalar total
    (no host syncs inside shard_map)."""
    from .executor import SETOP_AGGS, setop_keep_times, setop_tag
    tagged = setop_tag(lb, rb)
    both = _trace_concat(tagged[0], tagged[1], out_cap)
    g = group_aggregate(both, out_syms, list(SETOP_AGGS),
                        groups_capacity=out_cap)
    nl = jnp.asarray(g.column("__nl$").data)
    nr = jnp.asarray(g.column("__nr$").data)
    keep, times = setop_keep_times(nl, nr, op, distinct)
    out = compact.filter_batch(g, keep)
    if times is not None:
        times = jnp.take(times, compact.mask_to_gather(keep)[0])
        live_times = jnp.where(out.row_valid(), times, 0)
        total = jnp.sum(live_times)           # device scalar
        incl = jnp.cumsum(live_times)
        i = jnp.arange(out_cap, dtype=jnp.int64)
        p = jnp.searchsorted(incl, i, side="right")
        p = jnp.clip(p, 0, out.capacity - 1)
        out = out.gather(p, total)
    return Batch({s: out.column(s) for s in out_syms}, out.num_rows)


def _pad_one(b: Batch) -> Batch:
    """Pad a 1-row aggregate result to capacity 8 for shard transport."""
    cols = {}
    for s, c in b.columns.items():
        data = jnp.pad(jnp.asarray(c.data), (0, 8 - c.capacity))
        valid = (None if c.valid is None
                 else jnp.pad(jnp.asarray(c.valid), (0, 8 - c.capacity)))
        d2 = (None if c.data2 is None
              else jnp.pad(jnp.asarray(c.data2), (0, 8 - c.capacity)))
        cols[s] = Column(c.type, data, valid, c.dictionary, data2=d2)
    return Batch(cols, b.num_rows)


def _align_sharded_dicts(probe: ShardedBatch, build: ShardedBatch,
                         pkeys, bkeys) -> ShardedBatch:
    """Remap the build side's string-key code lanes onto the probe
    side's dictionaries (both sharded). The remap table is tiny and
    replicated; the gather is elementwise over the sharded lane."""
    cols = dict(build.columns)
    changed = False
    for pk, bk in zip(pkeys, bkeys):
        pc = probe.columns.get(pk)
        bc = cols.get(bk)
        if pc is None or bc is None or pc.dictionary is None \
                or bc.dictionary is None or pc.dictionary is bc.dictionary:
            continue
        merged, _, ro = pc.dictionary.merge(bc.dictionary)
        remap = jnp.asarray(ro)
        cols[bk] = dc_replace(
            bc, data=jnp.take(remap, jnp.asarray(bc.data), mode="clip"),
            dictionary=merged)
        changed = True
    if not changed:
        return build
    return ShardedBatch(cols, build.num_rows, build.mesh,
                        build.per_shard_cap)


def _align_sharded_strings(sb: ShardedBatch, host: Batch, skeys, hkeys
                           ) -> Batch:
    """Remap the host/build side's string key columns onto the sharded
    side's dictionaries so code equality == string equality. The sharded
    side's codes are left untouched (remapping them is also possible but
    costs a device pass per shard)."""
    cols = dict(host.columns)
    for sk, hk in zip(skeys, hkeys):
        sc = sb.columns.get(sk)
        hc = cols.get(hk)
        if sc is None or hc is None or sc.dictionary is None \
                or hc.dictionary is None:
            continue
        if sc.dictionary is hc.dictionary:
            continue
        # build-side strings unseen on the probe side get codes beyond
        # the probe dictionary — they can never equal a probe code,
        # which is exactly the join semantics required
        merged, rs, ro = sc.dictionary.merge(hc.dictionary)
        remap = jnp.asarray(ro)
        cols[hk] = dc_replace(
            hc, data=jnp.take(remap, jnp.asarray(hc.data), mode="clip"),
            dictionary=merged)
    return Batch(cols, host.num_rows)


def _trace_concat(a: Batch, b: Batch, out_cap: int) -> Batch:
    """Concatenate two batches' live prefixes inside a trace (static
    capacities; counts are device scalars)."""
    na = a.num_rows_device()
    nb = b.num_rows_device()
    live = jnp.concatenate([
        jnp.arange(a.capacity, dtype=jnp.int64) < na,
        jnp.arange(b.capacity, dtype=jnp.int64) < nb])
    idx = jnp.nonzero(live, size=out_cap, fill_value=0)[0]
    cols = {}
    for name in a.names:
        ca, cb = a.column(name), b.column(name)
        data = jnp.take(jnp.concatenate(
            [jnp.asarray(ca.data),
             # jnp dtype read: np.asarray here would host-sync a traced
             # array inside shard_map
             jnp.asarray(cb.data).astype(jnp.asarray(ca.data).dtype)]),
            idx, mode="clip")
        valid = None
        if ca.valid is not None or cb.valid is not None:
            va = (jnp.ones((ca.capacity,), bool) if ca.valid is None
                  else jnp.asarray(ca.valid))
            vb = (jnp.ones((cb.capacity,), bool) if cb.valid is None
                  else jnp.asarray(cb.valid))
            valid = jnp.take(jnp.concatenate([va, vb]), idx, mode="clip")
        d2 = None
        if ca.data2 is not None or cb.data2 is not None:
            from ..columnar import hi_lane_or_fill
            d2 = jnp.take(jnp.concatenate(
                [hi_lane_or_fill(ca), hi_lane_or_fill(cb)]), idx,
                mode="clip")
        cols[name] = Column(ca.type, data, valid, ca.dictionary,
                            data2=d2)
    return Batch(cols, na + nb)


def _shard_join(pb: Batch, bb: Batch, pkeys, bkeys, jt: str, filt,
                out_cap: int, pad_cap: int) -> Batch:
    """Trace-safe single-shard join against a replicated build side
    (the per-shard body of a REPLICATED-distribution join)."""
    outer = jt == "left"
    if filt is None:
        start, count, order = join_ops.match_counts(pb, bb, pkeys, bkeys)
        return join_ops.expand_join(pb, bb, start, count, order, out_cap,
                                    "left" if outer else "inner")
    ppos = "__probe_pos$"
    pcols = dict(pb.columns)
    pcols[ppos] = Column(BIGINT,
                         jnp.arange(pb.capacity, dtype=jnp.int64), None)
    probe2 = Batch(pcols, pb.num_rows)
    start, count, order = join_ops.match_counts(probe2, bb, pkeys, bkeys)
    cand = join_ops.expand_join(probe2, bb, start, count, order, out_cap,
                                "inner")
    mask = eval_predicate(filt, cand)
    out = compact.filter_batch(cand, mask)
    if not outer:
        return Batch({s: c for s, c in out.columns.items() if s != ppos},
                     out.num_rows)
    pp = jnp.asarray(out.column(ppos).data)
    live_out = out.row_valid()
    matched = jnp.zeros((pb.capacity,), bool).at[
        jnp.where(live_out, pp, 0)].max(live_out)
    unmatched = pb.row_valid() & ~matched
    pad_src = compact.filter_batch(pb, unmatched)
    pad_cols = dict(pad_src.columns)
    for s, c in bb.columns.items():
        z = jnp.zeros((pad_src.capacity,),
                      dtype=np.asarray(c.data).dtype)
        pad_cols[s] = Column(c.type, z,
                             jnp.zeros((pad_src.capacity,), bool),
                             c.dictionary)
    pad = Batch(pad_cols, pad_src.num_rows)
    out = Batch({s: c for s, c in out.columns.items() if s != ppos},
                out.num_rows)
    return _trace_concat(out, pad, out_cap + pad_cap)


