"""Beyond-HBM execution: morsel-streamed operators with double-buffered
host->device transfer.

Reference parity: the reference streams pages through operators by
construction (operator/Driver.java's pull loop never materializes a
table), so "working set exceeds memory" is a spill concern there, not
an executor-mode concern. This engine's whole-column execution model
(columnar.py) materializes an operator's entire input in device
memory — which caps query scale at one chip's HBM (BENCH_r05: q18@sf100
"not attempted: ~34GB of q18 lanes exceeds single-chip HBM").

This module is the morsel-driven answer (tensor-runtime query
processing, PAPERS arxiv 2203.01877: operator-as-tensor-program chunk
streaming): when a probe/scan side's full-materialization estimate
exceeds the memory budget, the operator streams fixed-capacity chunks
instead of materializing —

- **hash join**: the build side is materialized and sorted ONCE in
  device memory (ops/join.py build_side — the engine's "hash table");
  probe-side chunks then stream through one jitted
  count-and-expand program per canonical chunk capacity, with
  ``jax.device_put`` on chunk N+1 issued while the program runs on
  chunk N (the async-copy double-buffering of SNIPPETS [1]/[3], on the
  host->HBM edge). Match outputs spill to host per chunk (the existing
  oversized-join discipline).
- **scan -> filter -> project chains**: chunks stream through the
  canonical chain program (exec/progkey.py — the same program the
  unstreamed chain path compiles), outputs host-concatenated.
- **streaming aggregation** (exec/executor.py
  ``_try_streaming_aggregation``) reuses the chunk source + the
  double-buffered loop here, with periodic partial folding so the
  accumulated partial set stays bounded.

Every chunk shares ONE canonical capacity, so every chunk hits the same
compiled program (jax specializes per shape under one callable; the
first chunk traces, the rest are device_execute). Chunk capacity comes
from ``stream_chunk_rows`` (session) / ``TRINO_TPU_STREAM_CHUNK_ROWS``,
or is auto-derived from the memory budget when 0.

Memory governance: a streamed operator reserves its **streamed peak**
(build state + 2 chunk buffers + 1 output chunk) instead of the
full-materialization estimate — the PR 10 cluster pool sees what the
operator actually holds, so the low-memory killer stops shooting
queries streaming can serve.

Limits (fall back to the materialized path): FULL joins, string
columns CREATED by the probe chain (a chain-minted dictionary per
chunk would re-trace every chunk; strings read off the scan stream
through the per-stream canonical layout of ``_StreamDictEncoder``),
nested (ARRAY/MAP/ROW) scan columns, and semi joins.

Shared-runtime code: the jitted-program caches here are mutated by
query executor threads and the worker pre-warm thread concurrently —
mutations go through exec/executor.py's ``_cache_put`` under its cache
lock (this module is on the race-lint cross-module allowlist,
analysis/lint.py)."""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Optional, Sequence, \
    Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Batch, Column, StringDictionary, empty_batch
from ..config import CONFIG, capacity_for
from ..obs.metrics import (JIT_CACHE_LOOKUPS as _M_JIT, METRICS,
                           STREAM_CHUNKS, STREAM_H2D_BYTES,
                           STREAM_OVERLAPPED)
from ..plan.nodes import (FilterNode, JoinNode, PlanNode, ProjectNode,
                          RemoteSourceNode, TableScanNode)
from ..rex import Call as _RCall, InputRef, and_all
from ..types import BOOLEAN, DecimalType

# cross-query cache of jitted streamed-join probe programs, keyed by
# (probe/build lane specs, keys, join type, residual, capacities);
# populated by live queries AND by worker pre-warm (exec/aot.py
# "streamjoin" entries). Deny set for programs that refuse to trace.
_JOIN_JIT_CACHE: Dict[tuple, object] = {}
_JOIN_JIT_DENY: set = set()


# --------------------------------------------------------------------------
# engagement: when does an operator stream?
# --------------------------------------------------------------------------

def chunk_rows_setting(session) -> int:
    """``stream_chunk_rows``: > 0 forces streaming at that chunk size
    (tests/bench pin the capacity); 0 = auto-engage on budget breach;
    < 0 disables streaming entirely (the operator escape hatch — the
    engine falls back to the materialized path and its memory
    errors)."""
    try:
        return int(session.get("stream_chunk_rows"))
    except KeyError:
        return 0


def memory_budget(ex) -> int:
    """The effective streaming budget: the per-node limit, tightened by
    whatever cluster governance binds this query (query_max_memory /
    group soft limit / pool size via QueryMemoryContext.budget_bytes).
    A query that would breach the POOL un-streamed must engage
    streaming too — the pool killer only sees reservations, and the
    whole point is to reserve the streamed peak instead."""
    limit = int(ex.session.get("query_max_memory_per_node"))
    mem = getattr(ex.session, "memory", None)
    fn = getattr(mem, "budget_bytes", None)
    if callable(fn):
        try:
            b = fn()
            if b:
                limit = min(limit, int(b))
        except Exception:       # noqa: BLE001 — governance is advisory
            pass
    return limit


def scan_chain(node: PlanNode):
    """(chain, scan) when ``node`` heads a Filter/Project-only chain
    over a TableScanNode — the streamable shape (row-local operators
    only: Sample is position-dependent, Limit/Sort are global)."""
    chain: List[PlanNode] = []
    cur = node
    while isinstance(cur, (FilterNode, ProjectNode)):
        chain.append(cur)
        cur = cur.source
    if not isinstance(cur, TableScanNode):
        return None
    return chain, cur


def _col_streamable(t) -> bool:
    name = str(t.name)
    return not (name.startswith("array(") or name.startswith("map(")
                or name.startswith("row("))


def _split_connector(ex, scan: TableScanNode):
    """The scan's connector when it supports split iteration (the
    chunk source needs get_splits/read_split); None otherwise —
    coordinator-state catalogs (system.runtime, information_schema)
    never stream."""
    try:
        conn = ex.catalogs.connector(scan.handle.catalog)
    except Exception:           # noqa: BLE001
        return None
    if not hasattr(conn, "get_splits") \
            or not hasattr(conn, "read_split"):
        return None
    return conn


def stream_gate(ex, scan: TableScanNode):
    """The engagement preconditions every streamed operator shares:
    None when streaming is impossible for this scan (no split-capable
    connector, unstreamable column types, streaming disabled); else
    (forced chunk rows, memory budget, scan estimate). Operator-
    specific rules (the join's remaining-after-build check, the
    chain/agg est-vs-budget comparison) layer on top — ONE gate, so
    the three streamed operators cannot drift."""
    if _split_connector(ex, scan) is None:
        return None
    if not all(_col_streamable(t) for t in scan.schema.values()):
        return None
    forced = chunk_rows_setting(ex.session)
    if forced < 0:
        return None             # streaming disabled for this session
    return forced, memory_budget(ex), scan_estimate(ex, scan)


def scan_estimate(ex, scan: TableScanNode) -> Optional[int]:
    """Full-materialization estimate of the scan in bytes — the SAME
    rows x lanes x 8 figure ``_exec_TableScanNode`` would reserve, so
    streaming engages exactly where the reserve would raise. None when
    the connector cannot estimate (pushed-down constraint/limit)."""
    try:
        conn = ex.catalogs.connector(scan.handle.catalog)
    except Exception:           # noqa: BLE001
        return None
    if scan.handle.constraint is not None or scan.handle.limit is not None:
        return None
    if not hasattr(conn, "table_row_count") \
            or not hasattr(conn, "get_splits"):
        return None
    rows = conn.table_row_count(scan.handle)
    if not rows:
        return None
    return int(rows) * max(len(set(scan.assignments.values())), 1) * 8


def _row_bytes(schema: Dict[str, object]) -> int:
    """Per-row device bytes of one chunk of this schema (data lane +
    validity + the Int128/tz hi lane where the type carries one)."""
    total = 0
    for t in schema.values():
        total += 9              # 8B data + 1B validity
        if (isinstance(t, DecimalType) and not t.is_short) \
                or str(t.name).endswith("with time zone"):
            total += 8
    return max(total, 9)


def _pick_chunk_capacity(forced: int, avail_bytes: int,
                         per_row: int) -> Optional[int]:
    """Canonical chunk capacity: the forced setting, or the largest
    power of two whose streamed footprint fits ``avail_bytes``.
    None when not even the minimum chunk fits."""
    if forced > 0:
        return capacity_for(min(forced, CONFIG.max_batch_rows),
                            minimum=8)
    cap = 8
    while cap * 2 * per_row <= avail_bytes \
            and cap * 2 <= CONFIG.max_batch_rows:
        cap *= 2
    if cap * per_row > avail_bytes:
        return None
    return cap


# --------------------------------------------------------------------------
# chunk source: host-resident fixed-capacity morsels off the scan
# --------------------------------------------------------------------------

def _slice_chunk(raw: Batch, assignments: Dict[str, str], lo: int,
                 hi: int, cap: int) -> Batch:
    """Rows [lo, hi) of the split, padded to the canonical chunk
    capacity, renamed to the scan's output symbols. Lanes land as host
    numpy (np.asarray on a device lane downloads — the streamed path
    deliberately stages through host RAM, that is the point)."""
    cols: Dict[str, Column] = {}
    n = hi - lo
    for sym, col in assignments.items():
        c = raw.column(col)

        def cut(lane):
            a = np.asarray(lane)[lo:hi]
            if n < cap:
                a = np.concatenate(
                    [a, np.zeros(cap - n, dtype=a.dtype)])
            return a

        cols[sym] = Column(
            c.type, cut(c.data),
            None if c.valid is None else cut(c.valid),
            c.dictionary,
            None if c.data2 is None else cut(c.data2))
    return Batch(cols, n)


def host_scan_chunks(ex, scan: TableScanNode, chunk_cap: int
                     ) -> Iterator[Batch]:
    """Yield host chunks of the scan at the canonical capacity,
    respecting the worker's split share (``ex.scan_partition``)."""
    conn = ex.catalogs.connector(scan.handle.catalog)
    columns = sorted(set(scan.assignments.values()))
    par = int(ex.session.get("task_concurrency")) or 1
    splits = conn.get_splits(scan.handle, par)
    if ex.scan_partition is not None:
        part, nparts = ex.scan_partition
        splits = [s for i, s in enumerate(splits)
                  if i % nparts == part]
    for sp in splits:
        raw = ex._read_split(conn, sp, columns)
        n = raw.num_rows_host()
        # stage the split on HOST once: np.asarray per chunk over a
        # device-resident lane would re-download the whole split per
        # chunk. The split staging buffer lives in host RAM (the spill
        # medium — exempt from the device budget); device-side
        # generator connectors that materialize splits directly in HBM
        # remain the device round's open item (ROADMAP item 2)
        raw = Batch(
            {name: Column(
                c.type, np.asarray(c.data),
                None if c.valid is None else np.asarray(c.valid),
                c.dictionary,
                None if c.data2 is None else np.asarray(c.data2))
             for name, c in raw.columns.items()}, n)
        for lo in range(0, n, chunk_cap):
            yield _slice_chunk(raw, scan.assignments, lo,
                               min(lo + chunk_cap, n), chunk_cap)


def _batch_nbytes(b: Batch) -> int:
    total = 0
    for c in b.columns.values():
        for lane in (c.data, c.valid, c.data2):
            if lane is not None:
                total += int(np.asarray(lane).nbytes)
    return total


def _h2d(b: Batch) -> Batch:
    """Upload one chunk's lanes (jax.device_put is asynchronous — the
    DMA overlaps whatever the device is already running)."""
    cols = {}
    for s, c in b.columns.items():
        cols[s] = Column(
            c.type, jax.device_put(c.data),
            None if c.valid is None else jax.device_put(c.valid),
            c.dictionary,
            None if c.data2 is None else jax.device_put(c.data2))
    return Batch(cols, b.num_rows)


# per-streamed-operator cap on stream_chunk trace spans (the tail is
# summarized): span trees ride worker task-status JSON, so unbounded
# per-chunk spans would make status size linear in chunk count
_MAX_CHUNK_SPANS = 32


def run_streamed(ex, op: str, host_iter: Iterable[Batch],
                 dispatch, collect) -> Tuple[int, int]:
    """The double-buffered chunk loop shared by every streamed
    operator. Per chunk: ``dispatch(device_chunk, i)`` launches the
    compute (async under jax dispatch), then chunk i+1's host prep +
    ``jax.device_put`` are issued while that compute is in flight, and
    only then ``collect(result, i)`` host-syncs chunk i's output — the
    transfer for the NEXT chunk rides under the CURRENT chunk's
    compute (the double-buffer contract). Returns (chunks, h2d bytes)
    and records them in the stream metrics + the executor's per-query
    counters + the current stats frame."""
    it = iter(host_iter)
    # device-timing suppression: _jit_call's block-until-ready device
    # attribution would serialize this loop's double-buffered overlap
    # — streamed dispatches run unsynced (wall-only spans)
    ex._stream_depth += 1
    try:
        return _stream_loop(ex, op, it, dispatch, collect)
    finally:
        ex._stream_depth -= 1


def _stream_loop(ex, op: str, it, dispatch,
                 collect) -> Tuple[int, int]:
    """The body of ``run_streamed`` (split out so the device-timing
    suppression wraps it in one try/finally)."""
    import time as _time
    from contextlib import nullcontext
    trace = ex.trace
    host = next(it, None)
    nchunks = h2d = overlapped = 0
    cur = None
    if host is not None:
        h2d += _batch_nbytes(host)
        cur = _h2d(host)
    while cur is not None:
        # cooperative cancellation/deadline at CHUNK granularity: a
        # streamed operator is one plan node running for thousands of
        # chunks, so the between-plan-nodes check in Executor.execute
        # alone would let a killed/deadlined query stream to the end
        cancel = getattr(ex.session, "cancel", None)
        if cancel is not None and cancel.is_set():
            from .executor import QueryError
            raise QueryError("Query was canceled")
        deadline = getattr(ex.session, "deadline", None)
        if deadline is not None and _time.monotonic() > deadline:
            from .executor import QueryError
            raise QueryError(
                "Query exceeded the maximum run time "
                "(query_max_run_time)",
                error_name="EXCEEDED_TIME_LIMIT")
        yld = getattr(ex.session, "split_yield", None)
        if yld is not None:
            # shared split scheduler (exec/taskexec.py): a streamed
            # chunk is the quantum — a thousand-chunk stream yields
            # its runner slot to higher-priority queries per chunk
            # instead of owning the worker to completion
            yld()
        # per-chunk spans are capped: a million-chunk stream must not
        # hold (and ship, via worker task status) a Span per chunk —
        # the tail is summarized in one stream_tail span below
        cm = (trace.span("stream_chunk", op=op, chunk=nchunks)
              if trace is not None and nchunks < _MAX_CHUNK_SPANS
              else nullcontext())
        with cm:
            out = dispatch(cur, nchunks)
            nxt_host = next(it, None)
            nxt = None
            if nxt_host is not None:
                h2d += _batch_nbytes(nxt_host)
                nxt = _h2d(nxt_host)        # overlaps chunk N's compute
                overlapped += 1
            collect(out, nchunks)
        cur = nxt
        nchunks += 1
    if trace is not None and nchunks > _MAX_CHUNK_SPANS:
        now = _time.perf_counter()
        trace.record("stream_tail", now, now, op=op,
                     elided_chunks=nchunks - _MAX_CHUNK_SPANS)
    if nchunks:
        STREAM_CHUNKS.inc(nchunks, op=op)
        STREAM_H2D_BYTES.inc(h2d)
        if overlapped:
            STREAM_OVERLAPPED.inc(overlapped)
        ex.stream_chunks += nchunks
        ex.stream_h2d_bytes += h2d
        if ex.collect_stats and ex._frames:
            frame = ex._frames[-1]
            frame["stream_chunks"] = \
                frame.get("stream_chunks", 0) + nchunks
            frame["stream_h2d"] = frame.get("stream_h2d", 0) + h2d
    return nchunks, h2d


def agg_chunk_capacity(ex, scan: TableScanNode) -> Optional[int]:
    """Chunk capacity for the streaming-aggregation path
    (exec/executor.py ``_try_streaming_aggregation``), or None when
    chunking should not engage (fits the budget, unstreamable
    columns, or not even a minimal chunk fits)."""
    gate = stream_gate(ex, scan)
    if gate is None:
        return None
    forced, budget, est = gate
    if forced <= 0 and (est is None or est <= budget):
        return None
    # 2 in-flight chunks + the bounded partial fold window (~8 chunk-
    # capacity partials of at most the input's lane width)
    per_row = 10 * _row_bytes(scan.schema)
    return _pick_chunk_capacity(forced, budget, per_row)


# --------------------------------------------------------------------------
# the chain program (shared by streamed chains and streamed join probes)
# --------------------------------------------------------------------------

def make_chain_runner(ex, chain: Sequence[PlanNode]):
    """callable(Batch) -> Batch applying the Filter/Project chain
    bottom-up over one chunk. Under fragment_jit the closure executes
    the CANONICAL node stack through the cross-query chain cache
    (exec/progkey.py — the same program, and the same cache slot, the
    unstreamed chain path compiles), so streamed chunks amortize with
    everything else; otherwise eager per chunk. Also returns a
    recorder that registers the chunk shape with the hot-shape
    registry once (so pre-warming workers AOT-compile the chunk-sized
    chain program too)."""
    if not chain:
        return (lambda b: b), (lambda b: None)
    chain = list(chain)

    def eager(b: Batch) -> Batch:
        for nd in reversed(chain):
            b = ex._dispatch_apply(nd, b)
        return b

    if not ex.fragment_jit:
        return eager, (lambda b: None)
    from . import executor as _ex
    from .progkey import canonicalize_nodes
    canon = canonicalize_nodes(chain)
    if canon is None:
        return eager, (lambda b: None)
    key = canon.key
    state = {"binding": None, "hit": None}

    def run(b: Batch) -> Batch:
        if key in _ex._CHAIN_JIT_DENY:
            return eager(b)
        if state["binding"] is None:
            state["binding"] = canon.binding(b)
        binding = state["binding"]
        jitted = _ex._CHAIN_JIT_CACHE.get(key)
        if state["hit"] is None:        # count the lookup once per op
            state["hit"] = jitted is not None
            _M_JIT.inc(cache="chain",
                       result="hit" if state["hit"] else "miss")
        if jitted is None:
            helper = ex._detached()
            nodes = canon.nodes

            def fn(cb):
                for nd in reversed(nodes):
                    cb = helper._dispatch_apply(nd, cb)
                return cb
            jitted = jax.jit(fn)
            _ex._cache_put(_ex._CHAIN_JIT_CACHE, key, jitted)
        try:
            out = ex._jit_call(jitted, (binding.rename_in(b),),
                               "chain", bool(state["hit"]))
            state["hit"] = True         # later chunks ride the program
            return binding.rename_out(out)
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            _ex._CHAIN_JIT_CACHE.pop(key, None)
            _ex._CHAIN_JIT_DENY.add(key)
            return eager(b)

    def record(b: Batch) -> None:
        if key in _ex._CHAIN_JIT_DENY:
            return
        from .hotshapes import record_program
        if state["binding"] is None:
            state["binding"] = canon.binding(b)
        record_program("chain", key, canon,
                       state["binding"].rename_in(b), ex.session)

    return run, record


# --------------------------------------------------------------------------
# streamed scan -> filter -> project chains
# --------------------------------------------------------------------------

def maybe_stream_chain(ex, node: PlanNode) -> Optional[Batch]:
    """Chunk-stream a Filter/Project chain whose scan's
    full-materialization estimate exceeds the budget (or when
    ``stream_chunk_rows`` forces chunking). Returns the chain output
    (host-resident), or None when streaming does not engage."""
    sc = scan_chain(node)
    if sc is None:
        return None
    chain, scan = sc
    if not chain:
        return None
    gate = stream_gate(ex, scan)
    if gate is None:
        return None
    forced, budget, est = gate
    if forced <= 0 and (est is None or est <= budget):
        return None
    # 2 in-flight input chunks + 1 retained output chunk — the output
    # carries the CHAIN's schema, which a projection can widen beyond
    # the scan's
    per_row = 2 * _row_bytes(scan.schema) \
        + _row_bytes(chain[0].output_schema())
    chunk_cap = _pick_chunk_capacity(forced, budget, per_row)
    if chunk_cap is None:
        return None                 # not even a minimal chunk fits
    ex._reserve_streamed(
        chunk_cap * per_row,
        f"streamed scan chain over {scan.handle.table} "
        f"(chunk capacity {chunk_cap})")
    run, record = make_chain_runner(ex, chain)
    from .executor import _host_concat, _to_host
    outs: List[Batch] = []
    total = 0

    def dispatch(chunk: Batch, i: int):
        if i == 0:
            record(chunk)
        return run(chunk)

    def collect(out: Batch, i: int):
        nonlocal total
        n = out.num_rows_host()
        if n:
            outs.append(_to_host(out, n))
            total += n

    run_streamed(ex, "chain", host_scan_chunks(ex, scan, chunk_cap),
                 dispatch, collect)
    if not outs:
        return empty_batch(chain[0].output_schema())
    return _host_concat(outs, total)


# --------------------------------------------------------------------------
# streamed hash probe join
# --------------------------------------------------------------------------

def _type_inexact(t) -> bool:
    """Type-level twin of executor._keys_inexact: True when the uint64
    equality lane cannot be bijective for a key of this type (float,
    Int128 decimal low lane, tz hi lane)."""
    if isinstance(t, DecimalType):
        return not t.is_short
    if str(t.name).endswith("with time zone"):
        return True
    try:
        return np.dtype(t.np_dtype).kind == "f"
    except Exception:           # noqa: BLE001
        return True


def _verify_filter_types(pschema, bschema, pkeys, bkeys, filt):
    """join_verify_filter from plan types (no batches yet): append
    key-equality conjuncts when the hash lane is inexact."""
    inexact = len(pkeys) > 1 or any(
        _type_inexact(pschema[k]) for k in pkeys) or any(
        _type_inexact(bschema[k]) for k in bkeys)
    if not inexact:
        return filt
    eqs = [_RCall("=", (InputRef(pk, pschema[pk]),
                        InputRef(bk, bschema[bk])), BOOLEAN)
           for pk, bk in zip(pkeys, bkeys)]
    return and_all(([filt] if filt is not None else []) + eqs)


def _lane_spec(b: Batch) -> tuple:
    """Hashable description of a batch's lanes — the part of the jit
    signature the in-process cache key must capture (names/order =
    treedef, dtypes, validity/hi-lane presence, dictionary-ness)."""
    out = []
    for s, c in b.columns.items():
        out.append((s, str(np.dtype(c.data.dtype)),
                    c.valid is not None,
                    None if c.data2 is None
                    else str(np.dtype(c.data2.dtype)),
                    c.dictionary is not None))
    return tuple(out)


def _spec_from_payload(cols: List[dict]) -> tuple:
    return tuple((str(e["name"]), str(e["dtype"]), bool(e.get("valid")),
                  (None if not e.get("data2") else str(e["data2"])),
                  e.get("dict") is not None) for e in cols)


def join_program_key(jt: str, pkeys, bkeys, residual_repr: str,
                     probe_spec: tuple, build_spec: tuple,
                     chunk_cap: int, build_cap: int,
                     out_cap: int) -> tuple:
    return ("streamjoin", jt, tuple(pkeys), tuple(bkeys),
            residual_repr, probe_spec, build_spec,
            int(chunk_cap), int(build_cap), int(out_cap))


_PPOS = "__probe_pos$"


def make_probe_program(jt: str, pkeys: Sequence[str],
                       bkeys: Sequence[str], residual, out_cap: int):
    """The per-chunk probe kernel: searchsorted match counts against
    the prebuilt sorted build lane + output expansion at a STATIC
    capacity, fused into one traceable function -> every chunk of one
    streamed join runs the same compiled program. Returns
    (out_batch, total_matches) — the total is the overflow signal the
    host checks (a chunk whose matches exceed ``out_cap`` reruns
    through a grown program). Module-level so exec/aot.py rebuilds the
    EXACT closure for worker pre-warm."""
    from ..ops import compact, join as join_ops
    from .expr import eval_predicate
    pkeys = list(pkeys)
    outer = jt == "left"

    def fn(chunk: Batch, build: Batch, sorted_lane, order, m):
        lane_p, usable_p = join_ops.equality_lane(chunk, pkeys)
        left = jnp.minimum(
            jnp.searchsorted(sorted_lane, lane_p, side="left"), m)
        right = jnp.minimum(
            jnp.searchsorted(sorted_lane, lane_p, side="right"), m)
        count = jnp.where(usable_p, right - left, 0)
        if residual is None:
            live_p = chunk.row_valid()
            eff = (jnp.where(live_p, jnp.maximum(count, 1), 0)
                   if outer else count)
            total = jnp.sum(eff)
            out = join_ops.expand_join(
                chunk, build, left, count, order, out_cap,
                "left" if outer else "inner")
            return out, total
        probe = chunk
        if outer:
            cols = dict(chunk.columns)
            from ..types import BIGINT
            cols[_PPOS] = Column(
                BIGINT, jnp.arange(chunk.capacity, dtype=jnp.int64),
                None)
            probe = Batch(cols, chunk.num_rows)
        total = jnp.sum(count)
        cand = join_ops.expand_join(probe, build, left, count, order,
                                    out_cap, "inner")
        mask = eval_predicate(residual, cand)
        out = compact.filter_batch(cand, mask)
        return out, total

    return fn


def _join_payload(jt, criteria, residual, chunk: Batch, build: Batch,
                  out_cap: int, kind: str = "streamjoin"
                  ) -> Optional[dict]:
    """AOT transport form of one hash-join program set: the join shape
    as a wire fragment (JoinNode over two schema-carrying RemoteSource
    leaves, ``filter`` holding the FULL residual incl. hash-verify
    conjuncts) + both sides' lane specs at their capacities. Shared by
    the streamed probe program (kind="streamjoin") and the
    materialized two-phase programs (kind="join" — exec/executor.py);
    for the latter ``chunk`` is the whole probe batch. None when a
    side carries lanes the AOT rebuilder cannot fabricate (nested
    columns, large dictionaries)."""
    from ..plan.serde import to_jsonable
    from .hotshapes import MAX_DICT_ENTRIES

    def side(b: Batch):
        cols = []
        schema = {}
        for name, c in b.columns.items():
            if c.elements is not None or c.children is not None:
                return None, None
            ent: Dict[str, object] = {
                "name": name,
                "dtype": str(np.dtype(c.data.dtype)),
                "valid": c.valid is not None,
                "data2": (None if c.data2 is None
                          else str(np.dtype(c.data2.dtype)))}
            if c.dictionary is not None:
                vals = list(c.dictionary.values)
                if len(vals) > MAX_DICT_ENTRIES:
                    return None, None
                ent["dict"] = [None if v is None else str(v)
                               for v in vals]
            cols.append(ent)
            schema[name] = c.type
        return cols, schema

    pcols, pschema = side(chunk)
    bcols, bschema = side(build)
    if pcols is None or bcols is None:
        return None
    frag = JoinNode(RemoteSourceNode((), pschema, "gather"),
                    RemoteSourceNode((), bschema, "gather"),
                    jt, tuple(criteria), residual)
    def nrows_kind(b: Batch) -> str:
        return ("int" if isinstance(b.num_rows, int)
                else str(np.dtype(b.num_rows.dtype)))

    return {"kind": kind,
            "fragment": to_jsonable(frag),
            "probe_cols": pcols, "build_cols": bcols,
            "chunk_capacity": int(chunk.capacity),
            "build_capacity": int(build.capacity),
            "probe_num_rows": nrows_kind(chunk),
            "build_num_rows": nrows_kind(build),
            "out_capacity": int(out_cap)}


def aot_entry(payload: dict):
    """(cache key, probe fn, aval args) for exec/aot.py: rebuild the
    exact probe program a streamed join would run from a hot-shape
    payload, with ShapeDtypeStruct avals standing in for the chunk,
    the build side, and the sorted build state."""
    from ..plan.serde import from_jsonable
    from .aot import _aval_batch

    frag = from_jsonable(payload["fragment"])
    if not isinstance(frag, JoinNode):
        raise ValueError("streamjoin payload fragment is not a join")
    pschema = dict(frag.left.schema)
    bschema = dict(frag.right.schema)
    pkeys = [c.left for c in frag.criteria]
    bkeys = [c.right for c in frag.criteria]
    chunk_cap = int(payload["chunk_capacity"])
    build_cap = int(payload["build_capacity"])
    out_cap = int(payload["out_capacity"])
    key = join_program_key(
        frag.join_type, pkeys, bkeys, repr(frag.filter),
        _spec_from_payload(payload["probe_cols"]),
        _spec_from_payload(payload["build_cols"]),
        chunk_cap, build_cap, out_cap)
    fn = make_probe_program(frag.join_type, pkeys, bkeys, frag.filter,
                            out_cap)
    chunk = _aval_batch({"cols": payload["probe_cols"],
                         "capacity": chunk_cap,
                         "num_rows": payload.get("probe_num_rows",
                                                 "int")}, pschema)
    build = _aval_batch({"cols": payload["build_cols"],
                         "capacity": build_cap,
                         "num_rows": payload.get("build_num_rows",
                                                 "int")}, bschema)
    sorted_lane = jax.ShapeDtypeStruct((build_cap,), np.dtype(np.uint64))
    order = jax.ShapeDtypeStruct((build_cap,), np.dtype(np.int64))
    m = jax.ShapeDtypeStruct((), np.dtype(np.int64))
    return key, fn, (chunk, build, sorted_lane, order, m)


class _StreamDictEncoder:
    """Canonical per-stream code layout for probe-side string columns.

    Every split/chunk read off the connector carries its own
    StringDictionary — a STATIC aux of the Batch pytree, so a fresh
    identity per chunk would re-trace the chain and probe programs on
    every chunk. The encoder fixes ONE stream-level dictionary per
    column (join-key columns are seeded with the BUILD side's
    dictionary, so remapped probe codes compare directly against the
    prebuilt sorted key lane — the per-chunk align_string_keys merge
    of the materialized path, hoisted to stream setup) and host-remaps
    each chunk's codes into that layout inside the double-buffer
    window. Chunks introducing genuinely new values extend the layout
    append-only: existing codes never move, ONE re-trace per extension
    instead of one per chunk, and values absent from the build
    dictionary get codes past its length — codes the sorted build
    lane cannot contain, so they match nothing, exactly what string
    equality requires."""

    def __init__(self, seeds: Dict[str, StringDictionary]):
        self._dicts: Dict[str, StringDictionary] = dict(seeds)

    def encode(self, chunk: Batch) -> Batch:
        cols = dict(chunk.columns)
        changed = False
        for name, c in chunk.columns.items():
            if c.dictionary is None:
                continue
            d = self._dicts.get(name)
            if d is None:
                self._dicts[name] = c.dictionary
                continue
            if c.dictionary is d:
                continue
            idx = d.index
            vals = c.dictionary.values
            remap = np.empty(len(vals), dtype=np.int32)
            fresh = []
            for i, s in enumerate(vals):
                code = idx.get(s)
                if code is None:
                    fresh.append((i, s))
                else:
                    remap[i] = code
            if fresh:
                ext = list(d.values)
                nidx = dict(idx)
                for i, s in fresh:
                    remap[i] = len(ext)
                    nidx[s] = len(ext)
                    ext.append(s)
                d = StringDictionary(np.asarray(ext, dtype=object),
                                     nidx)
                self._dicts[name] = d
            codes = np.take(remap,
                            np.asarray(c.data).astype(np.int32))
            cols[name] = Column(c.type, codes, c.valid, d, c.data2)
            changed = True
        return Batch(cols, chunk.num_rows) if changed else chunk


def maybe_stream_join(ex, node: JoinNode
                      ) -> Tuple[Optional[Batch], Optional[Batch]]:
    """Chunk-stream the probe side of a hash join whose probe scan
    does not fit the budget REMAINING after the build side: build
    once, stream probe chunks through double-buffered transfers and
    ONE compiled probe program, accumulate match output on host.
    Returns (streamed result, None) on engagement; on decline,
    (None, build batch) when the decision required materializing the
    build side (the caller reuses it instead of re-executing), else
    (None, None)."""
    jt = node.join_type
    if jt not in ("inner", "left") or not node.criteria:
        return None, None
    sc = scan_chain(node.left)
    if sc is None:
        return None, None
    chain, scan = sc
    gate = stream_gate(ex, scan)
    if gate is None:
        return None, None
    pschema = chain[0].output_schema() if chain \
        else scan.output_schema()
    bschema = node.right.output_schema()
    # nested columns cannot chunk-slice; string columns stream through
    # the per-stream canonical dictionary layout (_StreamDictEncoder)
    # — but only when read off the SCAN: a string column the chain
    # creates would mint a fresh dictionary per chunk (a re-trace per
    # chunk), so those decline to the materialized path. The BUILD
    # side may carry dictionaries freely: it is materialized once,
    # its identity is stable.
    from ..types import is_string
    if not all(_col_streamable(t) for t in pschema.values()):
        return None, None
    if not all(_col_streamable(t) for t in scan.schema.values()):
        return None, None
    if any(is_string(t) and s not in scan.schema
           for s, t in pschema.items()):
        return None, None
    pkeys = [c.left for c in node.criteria]
    bkeys = [c.right for c in node.criteria]
    if any(k not in pschema for k in pkeys) \
            or any(k not in bschema for k in bkeys):
        return None, None
    forced, budget, est = gate
    if forced <= 0 and (est is None or 4 * est <= budget):
        # heuristic pre-decline: the exact remaining-after-build rule
        # below requires materializing the build FIRST, which reorders
        # execution for every join — so probes under a quarter of the
        # budget skip it. The corner this concedes: a build consuming
        # >3/4 of the budget next to a fitting probe materializes both
        # (per-reservation accounting, same as the pre-streaming
        # engine) instead of streaming
        return None, None
    residual = _verify_filter_types(pschema, bschema, pkeys, bkeys,
                                    node.filter)

    # build once: the engine's hash table is the sorted key lane +
    # permutation of ops/join.py (HashBuilderOperator's table, HBM-
    # resident for the whole stream)
    from ..ops import join as join_ops
    from .executor import _col_bytes, _host_concat, _to_host
    build = ex.execute(node.right)
    build_bytes = sum(_col_bytes(c) for c in build.columns.values()) \
        + 2 * build.capacity * 8
    # the exact engagement rule: stream iff the probe does not fit in
    # what the budget leaves after the (capacity-rounded) build state
    # — the materialized path would hold probe + build concurrently
    if forced <= 0 and (est is None
                        or est <= max(budget - build_bytes, 0)):
        return None, build
    sorted_lane, order, m = join_ops.build_side(build, bkeys)
    order = order.astype(jnp.int64)
    m = m.astype(jnp.int64)
    # probe-side canonical dictionaries: key columns seed from the
    # BUILD dictionary so remapped probe codes compare directly
    # against the sorted build key lane just computed
    enc = _StreamDictEncoder(
        {pk: build.column(bk).dictionary
         for pk, bk in zip(pkeys, bkeys)
         if build.column(bk).dictionary is not None})

    probe_row = _row_bytes(pschema) + _row_bytes(scan.schema)
    out_row = _row_bytes(pschema) + _row_bytes(bschema) + 8
    per_row = 2 * probe_row + out_row
    chunk_cap = _pick_chunk_capacity(
        forced, max(budget - build_bytes, 0), per_row)
    if chunk_cap is None:
        return None, build      # build alone exhausts the budget
    state = {"out_cap": chunk_cap, "prog": None, "prog_cap": None,
             "probe_spec": None, "hit": None, "eager": False,
             "recorded": False}
    ex._reserve_streamed(
        build_bytes + chunk_cap * per_row,
        f"streamed join (build {build_bytes}B + chunk capacity "
        f"{chunk_cap})")

    chain_run, chain_record = make_chain_runner(ex, chain)
    outs: List[Batch] = []
    total_rows = 0

    def program():
        """(callable, key, eager?) for the current output capacity —
        rebuilt ONLY when the capacity grows: the key derivation
        (residual repr, lane-spec walks) is host work sitting in the
        double-buffer window, so it must not repeat per chunk. Jitted
        programs live in the cross-query cache, keyed like every
        structural cache (exec/progkey.py doctrine: one key per
        program, shared across queries)."""
        from . import executor as _ex
        if state["prog"] is not None \
                and state["prog_cap"] == state["out_cap"]:
            return state["prog"]
        key = join_program_key(
            jt, pkeys, bkeys, repr(residual), state["probe_spec"],
            _lane_spec(build), chunk_cap, build.capacity,
            state["out_cap"])
        fn = make_probe_program(jt, pkeys, bkeys, residual,
                                state["out_cap"])
        if state["eager"] or key in _JOIN_JIT_DENY:
            entry = (fn, key, True)
        else:
            jitted = _JOIN_JIT_CACHE.get(key)
            state["hit"] = jitted is not None
            _M_JIT.inc(cache="streamjoin",
                       result="hit" if state["hit"] else "miss")
            if jitted is None:
                jitted = jax.jit(fn)
                _ex._cache_put(_JOIN_JIT_CACHE, key, jitted)
            entry = (jitted, key, False)
        state["prog"], state["prog_cap"] = entry, state["out_cap"]
        return entry

    def run_chunk(probe_chunk: Batch):
        if state["probe_spec"] is None:
            state["probe_spec"] = _lane_spec(probe_chunk)
        jitted, key, eager = program()
        args = (probe_chunk, build, sorted_lane, order, m)
        if eager:                   # deny/fallback path
            return jitted(*args)
        try:
            out = ex._jit_call(jitted, args, "streamjoin",
                               bool(state["hit"]))
            state["hit"] = True     # later chunks ride the program
            if not state["recorded"]:
                state["recorded"] = True
                from .hotshapes import record_program

                def build_pl():
                    return _join_payload(jt, node.criteria, residual,
                                         probe_chunk, build,
                                         state["out_cap"])
                record_program("streamjoin", key, None, None,
                               ex.session, payload_fn=build_pl)
            return out
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            _JOIN_JIT_CACHE.pop(key, None)
            _JOIN_JIT_DENY.add(key)
            state["eager"] = True
            state["prog"] = None
            fn = make_probe_program(jt, pkeys, bkeys, residual,
                                    state["out_cap"])
            return fn(*args)

    def dispatch(chunk: Batch, i: int):
        if i == 0:
            chain_record(chunk)
        b = chain_run(chunk)
        out, total = run_chunk(b)
        return b, out, total

    def collect(res, i: int):
        nonlocal total_rows
        b, out, total = res
        total = int(total)
        if total > state["out_cap"]:
            # a hot probe chunk overflowed the output bucket: grow the
            # capacity (monotone — later chunks keep the larger
            # program) and re-expand this chunk. The grown buffer is
            # REAL device residency, so it goes through the same
            # reserve discipline as the initial streamed peak — an
            # ungoverned regrow would be exactly the invisible OOM
            # streaming exists to prevent
            grown = capacity_for(total)
            ex._reserve_streamed(
                build_bytes + 2 * chunk_cap * probe_row
                + grown * out_row,
                f"streamed join output growth to {grown} rows "
                "(one probe chunk matched more build rows than the "
                "output bucket holds; lower stream_chunk_rows)")
            state["out_cap"] = grown
            out, total = run_chunk(b)
        if residual is not None:
            out = ex._repair_outer(out, b, build, jt)
        n = out.num_rows_host()
        if n:
            outs.append(_to_host(out, n))
            total_rows += n

    run_streamed(ex, "join",
                 (enc.encode(c)
                  for c in host_scan_chunks(ex, scan, chunk_cap)),
                 dispatch, collect)
    if not outs:
        # zero matches / empty probe: synthesize the joined schema
        # with an honest zero-row expansion
        chunk0 = chain_run(_h2d(empty_batch(
            {s: scan.schema[s] for s in scan.assignments})))
        z = jnp.zeros((chunk0.capacity,), jnp.int64)
        out = join_ops.expand_join(chunk0, build, z, z, order,
                                   8, "inner")
        return _to_host(out, 0), None
    return _host_concat(outs, total_rows), None
