"""Global configuration for the TPU-native engine.

The engine requires 64-bit types: SQL BIGINT is int64 and DOUBLE is float64
(XLA emulates both on TPU; verified supported on v5e). This module must be
imported before any jax.numpy use, so every entry point imports trino_tpu
first.

Reference parity: plays the role of Trino's FeaturesConfig / TaskManagerConfig
(reference: core/trino-main/.../sql/analyzer/FeaturesConfig.java,
execution/TaskManagerConfig.java) — a process-wide knob registry, with
per-session overrides layered on top by ``trino_tpu.session.Session``.
"""

from __future__ import annotations

import dataclasses
import os

import jax

jax.config.update("jax_enable_x64", True)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


@dataclasses.dataclass
class EngineConfig:
    """Process-wide engine configuration (Trino: etc/config.properties)."""

    # Max rows per Batch flowing through a pipeline. Static-shape buckets are
    # powers of two up to this; larger inputs are processed in chunks.
    max_batch_rows: int = _env_int("TRINO_TPU_MAX_BATCH_ROWS", 1 << 22)
    # Minimum physical capacity bucket, to bound the number of distinct
    # compiled shapes (each bucket is a separate XLA compilation).
    min_capacity: int = 1 << 10
    # Per-query memory limit in bytes (Trino: query.max-memory-per-node).
    max_query_memory_per_node: int = _env_int(
        "TRINO_TPU_QUERY_MAX_MEMORY", 16 << 30
    )
    # Enable host spill when device memory is exhausted.
    spill_enabled: bool = os.environ.get("TRINO_TPU_SPILL", "1") == "1"
    # HBM-resident scan cache budget for immutable generator connectors
    # (tpch/tpcds): table columns live in device memory across queries
    # — the "storage layer in HBM" design of README.md. 0 disables.
    scan_cache_bytes: int = _env_int("TRINO_TPU_SCAN_CACHE",
                                     4 << 30)


CONFIG = EngineConfig()


class MemoryLimitExceeded(Exception):
    """EXCEEDED_LOCAL_MEMORY_LIMIT (spi/StandardErrorCode.java analog):
    a capacity decision would allocate more device memory than the
    query_max_memory_per_node session property allows."""


def reserve_bytes(rows: int, n_lanes: int, limit_bytes: int,
                  what: str) -> int:
    """Check an allocation of rows x n_lanes 8-byte device lanes against
    the per-node query memory limit (memory/ ClusterMemoryManager +
    LocalMemoryContext reservation, collapsed to the single decision
    point that matters in this engine: capacity planning)."""
    est = rows * max(n_lanes, 1) * 8
    if est > limit_bytes:
        raise MemoryLimitExceeded(
            f"Query exceeded per-node memory limit of {limit_bytes} "
            f"bytes ({what} needs ~{est} bytes for {rows} rows x "
            f"{n_lanes} lanes); raise query_max_memory_per_node or "
            "enable spill")
    return est


def capacity_for(n: int, minimum: int | None = None) -> int:
    """Round ``n`` up to a power-of-two capacity bucket.

    Static shapes are mandatory under jit; bucketing keeps the number of
    compiled variants logarithmic in data size (the analog of Trino compiling
    one bytecode class per expression shape, ExpressionCompiler.java:56).
    """
    floor = CONFIG.min_capacity if minimum is None else minimum
    cap = max(int(n), 1)
    bucket = max(floor, 1)
    while bucket < cap:
        bucket <<= 1
    return bucket
