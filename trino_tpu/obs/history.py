"""Coordinator query history: every terminal query leaves a compact,
durable record.

Reference parity: Trino's system.runtime.queries + the query-history
surface of the web UI (execution/QueryTracker holds BasicQueryInfo for
finished queries; dedicated history connectors persist them). Here the
store is bounded and TTL'd in memory, and append-only JSONL on disk
under the spool/history directory, so records survive coordinator
restarts (``GET /v1/history``, ``system.runtime.queries``).

Also hosts the two companion rings the observability endpoints serve:

* ``TraceRing`` — recent trace ids + root-span summaries, so a bare
  ``GET /v1/trace`` lists what ``/v1/trace/{query_id}`` can expand.
* ``MetricsRing`` — periodic whole-registry snapshots (per process,
  rolled up cluster-wide by the coordinator's provider), the ring
  behind ``system.runtime.metrics``.

Shared-runtime code: records are appended by per-query tracker
threads while HTTP handler threads and system-table scans read — every
method takes the store lock (the module is on the race-lint
cross-module allowlist, analysis/lint.py)."""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..config import CONFIG
from .metrics import HISTORY_RECORDS, SLOW_QUERY_LOGS

# compact-record caps: history is a bounded diagnostic surface, not an
# archive — full SQL text and stack traces belong to /v1/query/{id}
_SQL_CAP = 512
_MSG_CAP = 300
_OPS_CAP = 64


def sql_digest(sql: str) -> str:
    """Stable identity of the query TEXT (the plan key is the
    identity of its canonical program — both ride the record)."""
    return hashlib.sha256((sql or "").encode()).hexdigest()[:16]


def record_from_query(q, plan_key: str = "") -> dict:
    """Build one history record from a terminal coordinator query
    (server/coordinator.py _Query, duck-typed). Everything numeric is
    defensive — a FAILED query may carry no result at all."""
    r = getattr(q, "result", None)
    err = getattr(q, "error", None) or {}
    stats = (getattr(r, "stats", None) or []) if r is not None else []
    created = float(getattr(q, "created", 0.0) or 0.0)
    started = getattr(q, "started", None)
    ended = float(getattr(q, "ended", None) or time.time())
    queued_s = max(((started if started is not None else ended)
                    - created), 0.0)
    cpu_s = float(getattr(r, "cpu_seconds", 0.0) or 0.0) if r else 0.0
    device_s = float(getattr(r, "device_seconds", 0.0) or 0.0) \
        if r else 0.0
    if cpu_s == 0.0 and stats:
        # local (non-dispatched) execution: the scheduler rollup never
        # ran, so attribute from the per-node stats directly
        cpu_s = sum(max(getattr(s, "cpu_s", 0.0), 0.0) for s in stats)
    if device_s == 0.0 and stats:
        device_s = sum(max(getattr(s, "device_s", 0.0), 0.0)
                       for s in stats)
    ops = []
    for s in stats[:_OPS_CAP]:
        ops.append({"name": getattr(s, "name", "?"),
                    "rows_in": int(getattr(s, "input_rows", -1)),
                    "rows_out": int(getattr(s, "output_rows", -1)),
                    "wall_s": round(getattr(s, "wall_s", 0.0), 6)})
    trace = getattr(r, "trace", None) if r is not None else None
    sess = getattr(q, "session", None)
    sql = str(getattr(q, "sql", "") or "")
    return {
        "query_id": getattr(q, "query_id", ""),
        "state": getattr(q, "state", ""),
        "user": getattr(sess, "user", "") if sess is not None else "",
        "source": getattr(q, "source", ""),
        "sql": sql[:_SQL_CAP],
        "sql_digest": sql_digest(sql),
        "plan_key": plan_key or str(getattr(r, "plan_key", "") or ""),
        "error_name": err.get("errorName"),
        "error_type": err.get("errorType"),
        "error_message": (str(err.get("message"))[:_MSG_CAP]
                          if err.get("message") else None),
        "created": created,
        "queued_s": round(queued_s, 6),
        "wall_s": round(max(ended - created, 0.0), 6),
        "cpu_s": round(cpu_s, 6),
        "device_s": round(device_s, 6),
        "rows": len(getattr(r, "rows", ()) or ()) if r else 0,
        "peak_memory_bytes": int(getattr(r, "peak_memory_bytes", 0)
                                 or 0) if r else 0,
        "spill_bytes": int(getattr(r, "spill_bytes", 0) or 0)
        if r else 0,
        "stream_chunks": int(getattr(r, "stream_chunks", 0) or 0)
        if r else 0,
        "stream_h2d_bytes": int(getattr(r, "stream_h2d_bytes", 0)
                                or 0) if r else 0,
        "ragged_batched": int(getattr(r, "ragged_batched", 0) or 0)
        if r else 0,
        "retries": int(getattr(r, "speculative_wins", 0) or 0)
        if r else 0,
        "trace_id": getattr(trace, "trace_id", None),
        "operators": ops,
    }


class QueryHistoryStore:
    """Bounded, TTL'd, JSONL-persisted record store. One instance per
    coordinator; the file outlives the process."""

    def __init__(self, path: str, capacity: Optional[int] = None,
                 ttl_s: Optional[float] = None) -> None:
        self.path = path
        self.capacity = max(int(capacity if capacity is not None
                                else CONFIG.history_capacity), 1)
        self.ttl_s = float(ttl_s if ttl_s is not None
                           else CONFIG.history_ttl_s)
        self._lock = threading.Lock()
        self._records: "deque[dict]" = deque(maxlen=self.capacity)
        self._appends_since_compact = 0
        self._load()

    # -- persistence ---------------------------------------------------
    def _load(self) -> None:
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            return
        now = time.time()
        recs = []
        for line in lines[-self.capacity * 2:]:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and self._fresh(rec, now):
                recs.append(rec)
        with self._lock:
            for rec in recs[-self.capacity:]:
                self._records.append(rec)

    def _fresh(self, rec: dict, now: float) -> bool:
        if self.ttl_s <= 0:
            return True
        ts = float(rec.get("recorded_at") or rec.get("created") or 0.0)
        return (now - ts) <= self.ttl_s

    def _append_line(self, rec: dict) -> None:
        try:
            os.makedirs(os.path.dirname(self.path) or ".",
                        exist_ok=True)
            with open(self.path, "a") as f:
                f.write(json.dumps(rec, default=str) + "\n")
        except (OSError, TypeError, ValueError):
            pass            # durable history is best-effort

    def _maybe_compact(self) -> None:
        """Rewrite the JSONL once appends exceed 4x capacity since the
        last compaction, so an immortal coordinator's history file
        stays O(capacity), not O(queries ever run)."""
        if self._appends_since_compact < self.capacity * 4:
            return
        self._appends_since_compact = 0
        snap = list(self._records)
        try:
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                for rec in snap:
                    f.write(json.dumps(rec, default=str) + "\n")
            os.replace(tmp, self.path)
        except (OSError, TypeError, ValueError):
            pass

    # -- write side ----------------------------------------------------
    def record(self, rec: dict) -> dict:
        """Append one terminal-query record (stamped, TTL-pruned,
        persisted). Returns the stamped record."""
        rec = dict(rec)
        rec.setdefault("recorded_at", time.time())
        now = rec["recorded_at"]
        with self._lock:
            while self._records and not self._fresh(self._records[0],
                                                    now):
                self._records.popleft()
            self._records.append(rec)
            self._appends_since_compact += 1
            self._append_line(rec)
            self._maybe_compact()
        HISTORY_RECORDS.inc(state=str(rec.get("state") or "UNKNOWN"))
        return rec

    def slow_log(self, rec: dict, threshold_ms: float) -> None:
        """Emit one full trace-linked slow-query record to the
        side-channel JSONL (``slow_queries.jsonl`` next to the history
        file) — the outlier log the slow_query_log_ms session property
        arms."""
        entry = dict(rec)
        entry["slow_query_threshold_ms"] = threshold_ms
        path = os.path.join(os.path.dirname(self.path) or ".",
                            "slow_queries.jsonl")
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "a") as f:
                f.write(json.dumps(entry, default=str) + "\n")
        except (OSError, TypeError, ValueError):
            pass
        SLOW_QUERY_LOGS.inc()

    # -- read side -----------------------------------------------------
    def records(self, limit: Optional[int] = None,
                state: Optional[str] = None) -> List[dict]:
        """Newest-first TTL-pruned snapshot."""
        now = time.time()
        with self._lock:
            while self._records and not self._fresh(self._records[0],
                                                    now):
                self._records.popleft()
            out = [dict(r) for r in self._records]
        out.reverse()
        if state:
            out = [r for r in out if r.get("state") == state]
        if limit is not None and limit >= 0:
            out = out[:limit]
        return out

    def get(self, query_id: str) -> Optional[dict]:
        with self._lock:
            for r in reversed(self._records):
                if r.get("query_id") == query_id:
                    return dict(r)
        return None

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


class TraceRing:
    """Bounded ring of recent trace summaries — what a bare
    ``GET /v1/trace`` lists (trace id, query id, root spans), each
    expandable at ``/v1/trace/{query_id}``."""

    def __init__(self, capacity: int = 64) -> None:
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=max(capacity, 1))

    def append(self, query_id: str, state: str, trace) -> None:
        """Summarize a finished query's trace into the ring (no-op
        for traceless queries)."""
        if trace is None or not getattr(trace, "roots", None):
            return
        roots = [{"name": sp.name,
                  "wall_ms": round(sp.wall_s * 1000, 3),
                  "children": len(sp.children)}
                 for sp in trace.roots[:8]]
        with self._lock:
            self._ring.append({
                "traceId": getattr(trace, "trace_id", ""),
                "queryId": query_id,
                "state": state,
                "recordedAt": time.time(),
                "rootSpans": roots})

    def list(self) -> List[dict]:
        with self._lock:
            out = [dict(e) for e in self._ring]
        out.reverse()
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


class MetricsRing:
    """Periodic whole-registry snapshots, ring-bounded. ``sample`` is
    lazy — the first reader past the interval takes the snapshot, so
    an idle cluster pays nothing."""

    def __init__(self, capacity: Optional[int] = None,
                 interval_s: Optional[float] = None) -> None:
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(
            maxlen=max(int(capacity if capacity is not None
                           else CONFIG.metrics_ring_slots), 1))
        self.interval_s = float(
            interval_s if interval_s is not None
            else CONFIG.metrics_ring_interval_s)
        self._last = 0.0

    def maybe_sample(self, collect_fn) -> None:
        """Take a snapshot if the interval elapsed. ``collect_fn``
        returns {node: {metric: {labels_tuple: value}}} (the parsed
        exposition shape of obs/metrics.py parse_exposition)."""
        now = time.time()
        with self._lock:
            if now - self._last < self.interval_s:
                return
            self._last = now
        try:
            snap = collect_fn()
        except Exception:       # noqa: BLE001 — sampling best-effort
            return
        with self._lock:
            self._ring.append({"ts": now, "nodes": snap})

    def snapshots(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._ring]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
