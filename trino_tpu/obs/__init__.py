"""Telemetry subsystem: metrics registry + query trace spans.

Reference parity: the reference treats observability as a first-class
subsystem — always-on QueryStats/OperatorStats
(operator/OperatorStats.java), JMX metrics exported per component
(io.airlift.stats), and the /v1/query detail API feeding the web UI.
Here the same three layers exist TPU-first:

- ``obs.metrics``: process-wide counters/gauges/histograms with
  Prometheus text exposition (GET /metrics on the coordinator and the
  task worker) — the JMX/MBean analog.
- ``obs.trace``: a per-query DISTRIBUTED span tree (parse -> plan ->
  optimize -> execute, with jit_trace vs device_execute children) —
  every span carries a real 128-bit-trace/64-bit-span identity, W3C
  ``traceparent`` context propagates into worker task payloads, and
  worker subtrees merge back id-preserving. On a tensor runtime
  compilation/dispatch overheads dominate (PAPERS.md "Query
  Processing on Tensor Computation Runtimes"), so trace-vs-execute
  separation (and device_ms vs wall) is the single most important
  measurement the JVM engine never needed.
- ``obs.otlp``: stdlib-only OTLP/JSON export of finished traces
  (ResourceSpans shape; file + HTTP sinks, plus the coordinator's
  GET /v1/trace/{query_id} pull surface).
- rich ``NodeStats`` + the distributed rollup live with the executor
  (exec/executor.py, exec/remote.py): workers report per-node stats in
  task results and the coordinator merges them per stage.
"""

from .metrics import METRICS, MetricsRegistry
from .trace import QueryTrace, Span

__all__ = ["METRICS", "MetricsRegistry", "QueryTrace", "Span"]
