"""Per-query trace spans: a tree of timed phases.

Reference parity: the reference records queryStats stage timings
(QueryStateMachine's queued/analysis/planning/execution durations) and
exposes them in /v1/query; OpenTelemetry spans landed on the same
boundaries (io.opentelemetry.api wiring in DispatchManager /
SqlQueryExecution). Here a ``QueryTrace`` rides on the Session: the
runner opens parse/plan/optimize/execute spans, the executor nests
jit_trace vs device_execute children under execute, and the remote
scheduler grafts per-fragment subtrees reported by workers. On a tensor
runtime this split is the headline number — compilation/dispatch
dominates latency (PAPERS.md "Query Processing on Tensor Computation
Runtimes"), and a wall-clock total cannot show it.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Span:
    name: str
    start_s: float                      # perf_counter at open
    end_s: Optional[float] = None       # perf_counter at close
    attrs: Dict[str, object] = field(default_factory=dict)
    children: List["Span"] = field(default_factory=list)

    @property
    def wall_s(self) -> float:
        return (self.end_s or self.start_s) - self.start_s

    def to_dict(self, origin_s: float) -> dict:
        d = {"name": self.name,
             "startMillis": round((self.start_s - origin_s) * 1000, 3),
             "wallMillis": round(self.wall_s * 1000, 3)}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict(origin_s) for c in self.children]
        return d

    @classmethod
    def from_dict(cls, d: dict, origin_s: float = 0.0) -> "Span":
        start = origin_s + d.get("startMillis", 0.0) / 1000.0
        sp = cls(d.get("name", "?"), start,
                 start + d.get("wallMillis", 0.0) / 1000.0,
                 dict(d.get("attrs", {})))
        sp.children = [cls.from_dict(c, origin_s)
                       for c in d.get("children", [])]
        return sp


class QueryTrace:
    """The span tree of one query. ``span(name)`` is a context manager
    nesting under the innermost open span; ``record``/``graft`` attach
    pre-timed spans (worker-reported subtrees arrive whole). The open-
    span stack is owned by the query's executor thread; the lock only
    guards child-list appends, which fragment-dispatch threads hit
    concurrently."""

    def __init__(self, query_id: str = ""):
        self.query_id = query_id
        self.origin_s = time.perf_counter()
        self.roots: List[Span] = []
        self._stack: List[Span] = []
        self._lock = threading.Lock()

    # -- structured construction --------------------------------------
    def span(self, name: str, **attrs) -> "_SpanCtx":
        return _SpanCtx(self, name, attrs)

    def _open(self, name: str, attrs: Dict[str, object]) -> Span:
        sp = Span(name, time.perf_counter(), attrs=dict(attrs))
        with self._lock:
            parent = self._stack[-1] if self._stack else None
            (parent.children if parent else self.roots).append(sp)
            self._stack.append(sp)
        return sp

    def _close(self, sp: Span) -> None:
        sp.end_s = time.perf_counter()
        with self._lock:
            if self._stack and self._stack[-1] is sp:
                self._stack.pop()

    def current(self) -> Optional[Span]:
        with self._lock:
            return self._stack[-1] if self._stack else None

    def record(self, name: str, start_s: float, end_s: float,
               parent: Optional[Span] = None, **attrs) -> Span:
        """Attach an already-timed span under ``parent`` (or the
        innermost open span). Safe from fragment-dispatch threads."""
        sp = Span(name, start_s, end_s, dict(attrs))
        with self._lock:
            if parent is None:
                parent = self._stack[-1] if self._stack else None
            (parent.children if parent else self.roots).append(sp)
        return sp

    def graft(self, parent: Optional[Span], spans: List[dict],
              base_s: Optional[float] = None) -> None:
        """Attach worker-reported span dicts (their clocks are not ours:
        rebase the subtree at ``base_s``, default = parent start)."""
        if parent is not None and base_s is None:
            base_s = parent.start_s
        for d in spans:
            sp = Span.from_dict(d, base_s if base_s is not None
                                else self.origin_s)
            with self._lock:
                (parent.children if parent is not None
                 else self.roots).append(sp)

    # -- rendering ------------------------------------------------------
    def to_dicts(self) -> List[dict]:
        return [r.to_dict(self.origin_s) for r in self.roots]

    def lines(self) -> List[str]:
        """Indented text rendering for EXPLAIN ANALYZE."""
        out: List[str] = []

        def walk(sp: Span, depth: int) -> None:
            attrs = ""
            if sp.attrs:
                attrs = " " + ", ".join(
                    f"{k}={v}" for k, v in sorted(sp.attrs.items()))
            out.append(f"{'   ' * depth}- {sp.name}: "
                       f"{sp.wall_s * 1000:.2f}ms{attrs}")
            for c in sp.children:
                walk(c, depth + 1)

        for r in self.roots:
            walk(r, 0)
        return out


def null_span(name: str, **attrs):
    """Drop-in for ``QueryTrace.span`` when no trace is installed —
    callers write ``sp = trace.span if trace else null_span`` and keep
    one code path."""
    from contextlib import nullcontext
    return nullcontext()


class _SpanCtx:
    __slots__ = ("_trace", "_name", "_attrs", "span")

    def __init__(self, trace: QueryTrace, name: str, attrs):
        self._trace = trace
        self._name = name
        self._attrs = attrs
        self.span: Optional[Span] = None

    def __enter__(self) -> Span:
        self.span = self._trace._open(self._name, self._attrs)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None and self.span is not None:
            self.span.attrs.setdefault("error", exc_type.__name__)
        self._trace._close(self.span)
