"""Per-query distributed trace: a span tree with real span identity.

Reference parity: the reference records queryStats stage timings
(QueryStateMachine's queued/analysis/planning/execution durations) and
exposes them in /v1/query; OpenTelemetry spans landed on the same
boundaries (io.opentelemetry.api wiring in DispatchManager /
SqlQueryExecution) with W3C ``traceparent`` context propagation into
the task protocol. Here a ``QueryTrace`` rides on the Session: the
runner opens parse/plan/optimize/execute spans, the executor nests
jit_trace vs device_execute children under execute, and the remote/
stage schedulers pre-mint a span id per dispatched task, ship it as a
``traceparent`` (header + task-payload field), and merge the worker's
reported subtree back ID-PRESERVING — a worker span is born with the
query's 128-bit trace id and its true 64-bit parent span id, so the
merged tree is one distributed trace, not a clock-rebased collage.
On a tensor runtime the jit_trace/device_execute split is the headline
number — compilation/dispatch dominates latency (PAPERS.md "Query
Processing on Tensor Computation Runtimes"), and a wall-clock total
cannot show it; ``device_ms`` attribution on those spans is what
EXPLAIN ANALYZE rolls up per stage.

Concurrency: the open-span stack is a per-thread structure
(``threading.local``), so a span opened on a fragment-dispatch thread
can never nest under whatever the executor thread happens to have open
— the pre-identity implementation shared one stack across threads and
had exactly that race. Cross-thread attachment is explicit: pass
``parent=`` to ``span()``/``record()``. The lock only guards child-
list appends, which concurrent threads do hit.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


def new_trace_id() -> str:
    """128-bit W3C trace id (32 lowercase hex chars)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit W3C span id (16 lowercase hex chars)."""
    return os.urandom(8).hex()


def format_traceparent(trace_id: str, span_id: str) -> str:
    """W3C Trace Context header value (version 00, sampled)."""
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value: object) -> Optional[Tuple[str, str]]:
    """(trace_id, parent_span_id) from a ``traceparent`` value, or
    None when malformed — propagation is best-effort, a corrupt header
    must never fail a task."""
    if not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, _ = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    return trace_id, span_id


@dataclass
class Span:
    name: str
    start_s: float                      # perf_counter at open
    end_s: Optional[float] = None       # perf_counter at close
    attrs: Dict[str, object] = field(default_factory=dict)
    children: List["Span"] = field(default_factory=list)
    # identity (the distributed half): 64-bit span id, minted at
    # creation or preserved off the wire; parent_id is only stored for
    # REMOTE parents (a local parent is the tree edge itself)
    span_id: str = field(default_factory=new_span_id)
    parent_id: Optional[str] = None
    # absolute wall-clock anchors (unix nanos), preserved across the
    # wire so a worker span keeps ITS host's clock instead of being
    # rebased onto the coordinator's — the id-preserving merge is also
    # a clock-preserving one
    start_unix_ns: Optional[int] = None
    end_unix_ns: Optional[int] = None

    @property
    def wall_s(self) -> float:
        return (self.end_s or self.start_s) - self.start_s

    def to_dict(self, origin_s: float,
                origin_unix_ns: Optional[int] = None) -> dict:
        d = {"name": self.name,
             "startMillis": round((self.start_s - origin_s) * 1000, 3),
             "wallMillis": round(self.wall_s * 1000, 3),
             "spanId": self.span_id}
        if self.parent_id:
            d["parentSpanId"] = self.parent_id
        start_ns = self.start_unix_ns
        if start_ns is None and origin_unix_ns is not None:
            start_ns = origin_unix_ns + int(
                (self.start_s - origin_s) * 1e9)
        if start_ns is not None:
            d["startUnixNanos"] = int(start_ns)
            end_ns = self.end_unix_ns
            if end_ns is None:
                end_ns = start_ns + int(self.wall_s * 1e9)
            d["endUnixNanos"] = int(end_ns)
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict(origin_s, origin_unix_ns)
                             for c in self.children]
        return d

    @classmethod
    def from_dict(cls, d: dict, origin_s: float = 0.0) -> "Span":
        start = origin_s + d.get("startMillis", 0.0) / 1000.0
        sp = cls(d.get("name", "?"), start,
                 start + d.get("wallMillis", 0.0) / 1000.0,
                 dict(d.get("attrs", {})))
        sid = d.get("spanId")
        if sid:
            sp.span_id = str(sid)
        pid = d.get("parentSpanId")
        if pid:
            sp.parent_id = str(pid)
        if d.get("startUnixNanos") is not None:
            sp.start_unix_ns = int(d["startUnixNanos"])
        if d.get("endUnixNanos") is not None:
            sp.end_unix_ns = int(d["endUnixNanos"])
        sp.children = [cls.from_dict(c, origin_s)
                       for c in d.get("children", [])]
        return sp


class QueryTrace:
    """The span tree of one query. ``span(name)`` is a context manager
    nesting under the calling THREAD's innermost open span (explicit
    ``parent=`` overrides); ``record``/``graft`` attach pre-timed
    spans (worker-reported subtrees arrive whole, ids intact). Born
    with a 128-bit trace id — or, on a worker, with the QUERY's trace
    id and the dispatching span's id from the ``traceparent`` the task
    payload carried, so every span this trace mints already belongs to
    the distributed trace."""

    def __init__(self, query_id: str = "",
                 trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None):
        self.query_id = query_id
        self.trace_id = trace_id or new_trace_id()
        # the REMOTE parent: root spans opened here carry it as their
        # parentSpanId, which is what makes the coordinator-side merge
        # id-preserving instead of positional
        self.parent_span_id = parent_span_id
        self.origin_s = time.perf_counter()
        self.origin_unix_ns = time.time_ns()
        self.roots: List[Span] = []
        self._tls = threading.local()   # per-thread open-span stack
        self._lock = threading.Lock()

    # -- clock mapping -------------------------------------------------
    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []  # tt-lint: ignore[race-attr-write] threading.local attribute: each thread writes its OWN slot by construction — thread isolation is the whole point
        return st

    def perf_from_unix_ns(self, ns: int) -> float:
        """Map an absolute unix-nanos timestamp onto this trace's
        perf_counter timebase (the rendering clock)."""
        return self.origin_s + (ns - self.origin_unix_ns) / 1e9

    # -- W3C context ---------------------------------------------------
    def traceparent(self, span_id: Optional[str] = None) -> str:
        """The ``traceparent`` value naming ``span_id`` (default: the
        calling thread's innermost open span) as the remote parent."""
        if span_id is None:
            cur = self.current()
            span_id = cur.span_id if cur is not None else new_span_id()
        return format_traceparent(self.trace_id, span_id)

    parse_traceparent = staticmethod(parse_traceparent)
    new_span_id = staticmethod(new_span_id)

    # -- structured construction --------------------------------------
    def span(self, name: str, parent: Optional[Span] = None,
             **attrs) -> "_SpanCtx":
        return _SpanCtx(self, name, attrs, parent)

    def _open(self, name: str, attrs: Dict[str, object],
              parent: Optional[Span] = None) -> Span:
        sp = Span(name, time.perf_counter(), attrs=dict(attrs))
        sp.start_unix_ns = time.time_ns()
        stack = self._stack()
        if parent is None:
            parent = stack[-1] if stack else None
        if parent is None and self.parent_span_id:
            sp.parent_id = self.parent_span_id
        with self._lock:
            (parent.children if parent is not None
             else self.roots).append(sp)
        stack.append(sp)
        return sp

    def _close(self, sp: Span) -> None:
        sp.end_s = time.perf_counter()
        sp.end_unix_ns = time.time_ns()
        stack = self._stack()
        if stack and stack[-1] is sp:
            stack.pop()

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def record(self, name: str, start_s: float, end_s: float,
               parent: Optional[Span] = None,
               span_id: Optional[str] = None, **attrs) -> Span:
        """Attach an already-timed span under ``parent`` (or the
        calling thread's innermost open span). ``span_id`` installs a
        PRE-MINTED id — the dispatch path mints the id before task
        submit so the worker's spans can be born pointing at it.
        Safe from fragment-dispatch threads."""
        sp = Span(name, start_s, end_s, dict(attrs))
        if span_id:
            sp.span_id = span_id
        sp.start_unix_ns = self.origin_unix_ns + int(
            (start_s - self.origin_s) * 1e9)
        sp.end_unix_ns = self.origin_unix_ns + int(
            (end_s - self.origin_s) * 1e9)
        if parent is None:
            parent = self.current()
        if parent is None and self.parent_span_id:
            sp.parent_id = self.parent_span_id
        with self._lock:
            (parent.children if parent is not None
             else self.roots).append(sp)
        return sp

    def graft(self, parent: Optional[Span], spans: List[dict],
              base_s: Optional[float] = None) -> None:
        """Attach worker-reported span dicts — the ID-PRESERVING
        merge: span/parent ids survive the wire, and spans carrying
        absolute unix-nanos anchors keep their own host's clock
        (mapped onto this trace's timebase for rendering). Legacy
        dicts without anchors fall back to rebasing the subtree at
        ``base_s`` (default = parent start)."""
        if parent is not None and base_s is None:
            base_s = parent.start_s
        for d in spans:
            sp = Span.from_dict(d, base_s if base_s is not None
                                else self.origin_s)
            self._realign(sp)
            if sp.parent_id is None and parent is not None:
                sp.parent_id = parent.span_id
            with self._lock:
                (parent.children if parent is not None
                 else self.roots).append(sp)

    def _realign(self, sp: Span) -> None:
        if sp.start_unix_ns is not None:
            start = self.perf_from_unix_ns(sp.start_unix_ns)
            end = (self.perf_from_unix_ns(sp.end_unix_ns)
                   if sp.end_unix_ns is not None
                   else start + sp.wall_s)
            sp.start_s, sp.end_s = start, end
        for c in sp.children:
            self._realign(c)

    # -- rendering ------------------------------------------------------
    def to_dicts(self) -> List[dict]:
        return [r.to_dict(self.origin_s, self.origin_unix_ns)
                for r in self.roots]

    def all_spans(self) -> List[Span]:
        """Depth-first flattening of the whole tree (the OTLP
        exporter's input — OTLP spans are a flat list linked by
        parentSpanId)."""
        out: List[Span] = []

        def walk(sp: Span) -> None:
            out.append(sp)
            for c in sp.children:
                walk(c)

        for r in self.roots:
            walk(r)
        return out

    def lines(self) -> List[str]:
        """Indented text rendering for EXPLAIN ANALYZE."""
        out: List[str] = []

        def walk(sp: Span, depth: int) -> None:
            attrs = ""
            if sp.attrs:
                attrs = " " + ", ".join(
                    f"{k}={v}" for k, v in sorted(sp.attrs.items()))
            out.append(f"{'   ' * depth}- {sp.name}: "
                       f"{sp.wall_s * 1000:.2f}ms{attrs}")
            for c in sp.children:
                walk(c, depth + 1)

        for r in self.roots:
            walk(r, 0)
        return out


def null_span(name: str, **attrs):
    """Drop-in for ``QueryTrace.span`` when no trace is installed —
    callers write ``sp = trace.span if trace else null_span`` and keep
    one code path."""
    from contextlib import nullcontext
    return nullcontext()


class _SpanCtx:
    __slots__ = ("_trace", "_name", "_attrs", "_parent", "span")

    def __init__(self, trace: QueryTrace, name: str, attrs,
                 parent: Optional[Span] = None):
        self._trace = trace
        self._name = name
        self._attrs = attrs
        self._parent = parent
        self.span: Optional[Span] = None

    def __enter__(self) -> Span:
        self.span = self._trace._open(self._name, self._attrs,
                                      self._parent)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None and self.span is not None:
            self.span.attrs.setdefault("error", exc_type.__name__)
        self._trace._close(self.span)
