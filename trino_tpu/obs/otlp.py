"""OTLP/JSON trace export: ResourceSpans over a file or HTTP sink.

Reference parity: the reference wires io.opentelemetry SDK exporters
(OTLP over HTTP) onto the DispatchManager / SqlQueryExecution span
boundaries; any OTel collector ingests the result. This module is the
stdlib-only analog: a finished ``QueryTrace`` (obs/trace.py — spans
already carry 128-bit trace ids, 64-bit span ids, parent links, and
absolute unix-nanos timestamps) serializes into the OTLP/JSON
``resourceSpans`` shape that ``POST {endpoint}/v1/traces`` accepts
and any collector file-reader understands.

Sinks (both best-effort — telemetry export must never fail a query):

- **file** (``TRINO_TPU_OTLP_FILE``): one JSON document per line
  (JSONL), the zero-dependency audit sink; rotate externally.
- **HTTP** (``TRINO_TPU_OTLP_ENDPOINT``): ``POST`` the document to an
  OTLP/HTTP collector; ``/v1/traces`` is appended when the endpoint
  does not already name it.

The coordinator additionally serves ``GET /v1/trace/{query_id}``
(server/coordinator.py) with the same document for a finished query —
the pull-side of the export, no collector required.

Outcomes are counted in ``trino_tpu_otlp_exports_total{sink,result}``.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

from .metrics import OTLP_EXPORTS

# OTLP enum: SPAN_KIND_INTERNAL (engine phases are internal spans;
# the task-dispatch HTTP hop is modeled by parent links, not by
# client/server kind pairs)
SPAN_KIND_INTERNAL = 1

# serializes appends so concurrent queries' documents interleave at
# line (not byte) granularity in the file sink
_FILE_LOCK = threading.Lock()


def _any_value(v: object) -> dict:
    """A typed OTLP AnyValue."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _attributes(attrs: Dict[str, object]) -> List[dict]:
    return [{"key": str(k), "value": _any_value(v)}
            for k, v in sorted(attrs.items(), key=lambda kv: str(kv[0]))]


def _span_to_otlp(span, trace, parent_id: Optional[str]) -> dict:
    start_ns = span.start_unix_ns
    if start_ns is None:
        start_ns = trace.origin_unix_ns + int(
            (span.start_s - trace.origin_s) * 1e9)
    end_ns = span.end_unix_ns
    if end_ns is None:
        end_ns = start_ns + int(span.wall_s * 1e9)
    out = {
        "traceId": trace.trace_id,
        "spanId": span.span_id,
        "name": span.name,
        "kind": SPAN_KIND_INTERNAL,
        "startTimeUnixNano": str(int(start_ns)),
        "endTimeUnixNano": str(int(end_ns)),
    }
    if parent_id:
        out["parentSpanId"] = parent_id
    if span.attrs:
        out["attributes"] = _attributes(span.attrs)
    return out


def trace_to_resource_spans(trace, resource: Optional[dict] = None
                            ) -> dict:
    """The OTLP/JSON document for one query's trace. ``resource``
    attributes identify the producing process (service.name, query id)
    — the ResourceSpans envelope every OTLP consumer groups by. The
    span list is FLAT (OTLP's shape): tree edges become parentSpanId
    links, and a span grafted from a worker keeps the parent id it was
    born with (obs/trace.py id-preserving merge)."""
    attrs = {"service.name": "trino_tpu"}
    if trace.query_id:
        attrs["trino_tpu.query_id"] = trace.query_id
    attrs.update(resource or {})
    spans: List[dict] = []

    def walk(sp, parent_id: Optional[str]) -> None:
        spans.append(_span_to_otlp(sp, trace, parent_id))
        for c in sp.children:
            walk(c, sp.span_id)

    for r in trace.roots:
        # a root's remote parent (the dispatching coordinator span)
        # survives as its own parent_id; local roots have none
        walk(r, r.parent_id)
    return {"resourceSpans": [{
        "resource": {"attributes": _attributes(attrs)},
        "scopeSpans": [{
            "scope": {"name": "trino_tpu.obs", "version": "1"},
            "spans": spans}]}]}


def validate_resource_spans(doc: dict) -> None:
    """Structural validation of an OTLP/JSON document — the test- and
    ingest-side contract check. Raises ValueError naming the first
    violation."""
    if not isinstance(doc, dict) or "resourceSpans" not in doc:
        raise ValueError("missing resourceSpans")
    rs = doc["resourceSpans"]
    if not isinstance(rs, list) or not rs:
        raise ValueError("resourceSpans must be a non-empty list")
    for i, r in enumerate(rs):
        if "resource" not in r or "attributes" not in r["resource"]:
            raise ValueError(f"resourceSpans[{i}] missing resource "
                             "attributes")
        sss = r.get("scopeSpans")
        if not isinstance(sss, list) or not sss:
            raise ValueError(f"resourceSpans[{i}] missing scopeSpans")
        for ss in sss:
            for sp in ss.get("spans", ()):
                tid = sp.get("traceId", "")
                sid = sp.get("spanId", "")
                if len(tid) != 32:
                    raise ValueError(
                        f"span {sp.get('name')}: traceId must be 32 "
                        f"hex chars, got {tid!r}")
                if len(sid) != 16:
                    raise ValueError(
                        f"span {sp.get('name')}: spanId must be 16 "
                        f"hex chars, got {sid!r}")
                int(tid, 16)
                int(sid, 16)
                if "name" not in sp:
                    raise ValueError("span missing name")
                start = int(sp.get("startTimeUnixNano", "0"))
                end = int(sp.get("endTimeUnixNano", "0"))
                if end < start:
                    raise ValueError(
                        f"span {sp['name']}: endTimeUnixNano < start")
                pid = sp.get("parentSpanId")
                if pid is not None and len(pid) != 16:
                    raise ValueError(
                        f"span {sp['name']}: bad parentSpanId {pid!r}")


def spans_from_otlp(doc: dict) -> List[dict]:
    """Flatten every span out of an OTLP/JSON document — the
    round-trip read half (tests assert exported ids/parents against
    the live trace through this)."""
    out: List[dict] = []
    for r in doc.get("resourceSpans", ()):
        for ss in r.get("scopeSpans", ()):
            out.extend(ss.get("spans", ()))
    return out


class FileSink:
    """JSONL append sink — one OTLP document per line."""

    name = "file"

    def __init__(self, path: str):
        self.path = path

    def export(self, doc: dict) -> None:
        line = json.dumps(doc, separators=(",", ":")) + "\n"
        with _FILE_LOCK:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line)


class HttpSink:
    """OTLP/HTTP sink: POST the JSON document to a collector.
    ``export_trace`` dispatches it on a daemon thread (async_export)
    — a down collector must cost the query thread nothing."""

    name = "http"
    async_export = True

    def __init__(self, endpoint: str, timeout_s: float = 5.0):
        ep = endpoint.rstrip("/")
        if not ep.endswith("/v1/traces"):
            ep = ep + "/v1/traces"
        self.endpoint = ep
        self.timeout_s = timeout_s

    def export(self, doc: dict) -> None:
        import urllib.request
        payload = json.dumps(doc, separators=(",", ":")).encode()
        req = urllib.request.Request(
            self.endpoint, data=payload,
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s):
            pass


def configured_sinks() -> List[object]:
    """Sinks named by process config (TRINO_TPU_OTLP_FILE /
    TRINO_TPU_OTLP_ENDPOINT); empty when export is unconfigured."""
    from ..config import CONFIG
    sinks: List[object] = []
    if CONFIG.otlp_file:
        sinks.append(FileSink(CONFIG.otlp_file))
    if CONFIG.otlp_endpoint:
        sinks.append(HttpSink(CONFIG.otlp_endpoint))
    return sinks


def _export_one(sink, doc: dict) -> bool:
    name = getattr(sink, "name", type(sink).__name__)
    try:
        sink.export(doc)
        OTLP_EXPORTS.inc(sink=name, result="ok")
        return True
    except Exception:           # noqa: BLE001 — telemetry best-effort
        OTLP_EXPORTS.inc(sink=name, result="error")
        return False


def export_trace(trace, resource: Optional[dict] = None,
                 sinks: Optional[List[object]] = None) -> int:
    """Serialize ``trace`` once and hand it to every sink; returns how
    many sinks accepted it synchronously. Sink failures are counted
    (otlp_exports_total{sink,result=error}) and swallowed — export is
    telemetry, not the query's critical path. Network sinks (those
    with ``async_export = True``, i.e. HttpSink) post from a daemon
    thread so an unreachable collector's connect timeout never rides
    the query thread."""
    if sinks is None:
        sinks = configured_sinks()
    if not sinks or trace is None or not trace.roots:
        return 0
    doc = trace_to_resource_spans(trace, resource)
    ok = 0
    for sink in sinks:
        if getattr(sink, "async_export", False):
            threading.Thread(target=_export_one, args=(sink, doc),
                             daemon=True).start()
            continue
        if _export_one(sink, doc):
            ok += 1
    return ok


def maybe_export(trace, session=None,
                 resource: Optional[dict] = None) -> int:
    """The runner-side hook: export when sinks are configured and the
    session has not opted out (``otlp_export`` session property)."""
    if trace is None or not trace.roots:
        return 0
    if session is not None:
        try:
            if not bool(session.get("otlp_export")):
                return 0
        except KeyError:        # foreign session without the knob
            pass
    return export_trace(trace, resource=resource)
