"""Process-wide metrics registry with Prometheus text exposition.

Reference parity: the reference exports engine counters through JMX
(io.airlift.stats CounterStat/DistributionStat on QueryManager,
SqlTaskManager, the exchange clients) and the prometheus-jmx bridge.
Here the registry is a small lock-safe process singleton (``METRICS``)
rendered in the Prometheus text format (version 0.0.4) at GET /metrics
on both the coordinator and the task worker.

Design notes:
- one ``threading.Lock`` per registry covers every mutation AND the
  render pass; metric operations are dict updates, so the hot-path cost
  is a lock acquire + float add (the executor increments these per
  query, not per row — never inside a jitted program).
- label support is positional-by-name: a metric declares its label
  names once; every sample supplies them as keyword arguments. A
  mismatched label set raises — silent label drift would corrupt the
  time series.
- gauges may also be fed by *collector callbacks* run at render time
  (queue depth, cache residency): values that are cheap to read but
  wasteful to push on every change.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple


def _escape(v: object) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    """Base: a named family of (label-tuple -> value) samples."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str],
                 lock: threading.Lock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._values: Dict[Tuple[str, ...], float] = {}

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} expects labels "
                f"{self.labelnames}, got {tuple(labels)}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def samples(self) -> List[Tuple[Tuple[str, ...], float]]:
        with self._lock:
            return sorted(self._values.items())

    def _render_labels(self, key: Tuple[str, ...],
                       extra: Sequence[Tuple[str, str]] = ()) -> str:
        pairs = [f'{n}="{_escape(v)}"'
                 for n, v in list(zip(self.labelnames, key)) + list(extra)]
        return "{" + ",".join(pairs) + "}" if pairs else ""

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        samples = self.samples()
        if not samples and not self.labelnames:
            # Prometheus convention: an unlabeled family is initialized
            # to 0 at registration — scrapers can alert on rate() the
            # moment the process boots, not after the first event
            samples = [((), 0.0)]
        for key, v in samples:
            lines.append(
                f"{self.name}{self._render_labels(key)} {_fmt(v)}")
        return lines


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


# wall-time-oriented default buckets: 1ms .. ~2min
DEFAULT_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0, 120.0)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames, lock)
        self.buckets = tuple(sorted(buckets))
        # per label-key: [bucket counts..., +Inf count, sum]
        self._hist: Dict[Tuple[str, ...], List[float]] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            h = self._hist.get(key)
            if h is None:
                h = [0.0] * (len(self.buckets) + 2)
                self._hist[key] = h
            for i, b in enumerate(self.buckets):
                if value <= b:
                    h[i] += 1
            h[-2] += 1           # +Inf / count
            h[-1] += value       # sum

    def count(self, **labels) -> float:
        with self._lock:
            h = self._hist.get(self._key(labels))
            return h[-2] if h else 0.0

    def snapshot(self, **labels) -> Tuple[Tuple[float, ...], float,
                                          float]:
        """(cumulative bucket counts, total count, sum) — the readback
        half of the histogram for in-process consumers (the bench load
        leg computes percentile deltas between two snapshots rather
        than re-parsing its own exposition text)."""
        with self._lock:
            h = self._hist.get(self._key(labels))
            if h is None:
                return (0.0,) * len(self.buckets), 0.0, 0.0
            return tuple(h[:len(self.buckets)]), h[-2], h[-1]

    @staticmethod
    def quantile_from_deltas(buckets: Sequence[float],
                             deltas: Sequence[float], count: float,
                             q: float) -> float:
        """Estimate the q-quantile from cumulative-bucket-count deltas
        (Prometheus histogram_quantile semantics: linear interpolation
        within the containing bucket, clamped to the largest finite
        bucket bound for the +Inf tail)."""
        if count <= 0:
            return 0.0
        rank = q * count
        prev_bound, prev_cum = 0.0, 0.0
        for bound, cum in zip(buckets, deltas):
            if cum >= rank:
                span = cum - prev_cum
                frac = ((rank - prev_cum) / span) if span > 0 else 1.0
                return prev_bound + (bound - prev_bound) * frac
            prev_bound, prev_cum = bound, cum
        return buckets[-1] if buckets else 0.0

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = sorted(self._hist.items())
        for key, h in items:
            for i, b in enumerate(self.buckets):
                lines.append(
                    f"{self.name}_bucket"
                    f"{self._render_labels(key, [('le', _fmt(b))])}"
                    f" {_fmt(h[i])}")
            lines.append(
                f"{self.name}_bucket"
                f"{self._render_labels(key, [('le', '+Inf')])}"
                f" {_fmt(h[-2])}")
            lines.append(
                f"{self.name}_sum{self._render_labels(key)} "
                f"{_fmt(h[-1])}")
            lines.append(
                f"{self.name}_count{self._render_labels(key)} "
                f"{_fmt(h[-2])}")
        return lines


class MetricsRegistry:
    """Named-metric registry; ``counter``/``gauge``/``histogram`` are
    get-or-create (idempotent across modules instrumenting the same
    family). ``render()`` produces the full text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[Callable[[], None]] = []

    def _get(self, cls, name: str, help: str,
             labelnames: Sequence[str], **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls:
                    raise ValueError(
                        f"metric {name} already registered as {m.kind}")
                return m
            m = cls(name, help, tuple(labelnames), self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get(Histogram, name, help, labelnames,
                         buckets=buckets)

    def register_collector(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` before every render; it refreshes gauges whose
        values are polled, not pushed (queue depth, cache bytes).
        Pair with ``unregister_collector`` when the owning component
        shuts down — the registry is process-global and would pin the
        callback (and keep rendering its stale gauges) forever."""
        with self._lock:
            self._collectors.append(fn)

    def unregister_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    def render(self) -> str:
        with self._lock:
            collectors = list(self._collectors)
            metrics = list(self._metrics.values())
        for fn in collectors:
            try:
                fn()
            except Exception:   # noqa: BLE001 — scrape must not fail
                pass
        lines: List[str] = []
        for m in sorted(metrics, key=lambda m: m.name):
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# the process-wide registry (the JMX MBean server analog)
METRICS = MetricsRegistry()

# shared across every runner flavor (LocalQueryRunner and the remote
# DistributedHostQueryRunner feed the same latency histogram — one
# definition so the help text and identity cannot drift)
QUERY_WALL_SECONDS = METRICS.histogram(
    "trino_tpu_query_wall_seconds",
    "End-to-end query wall time through the runner")

# scrape-friendly spot value (ROADMAP follow-on): the most recently
# completed query's peak reserved memory. A scraper sampling between
# queries sees the live high-water mark; QueryCompletedEvent carries
# the authoritative per-query figure for audit sinks.
QUERY_PEAK_MEMORY_BYTES = METRICS.gauge(
    "trino_tpu_query_peak_memory_bytes",
    "Peak reserved memory (bytes) of the most recently completed query")

# plan sanity checking (analysis/sanity.py): runs are counted so a
# fleet can alert on validation being accidentally disabled (rate
# drops to 0 while queries keep flowing); failures carry the validator
# name — the responsible optimizer pass is in the error message
PLAN_VALIDATIONS = METRICS.counter(
    "trino_tpu_plan_validations_total",
    "Plan sanity-checker batteries executed")
PLAN_VALIDATION_FAILURES = METRICS.counter(
    "trino_tpu_plan_validation_failures_total",
    "Plans rejected by the sanity checker, by validator", ("validator",))

# multi-stage MPP (trino_tpu/stage/): the partitioned worker-to-worker
# exchange. "written" counts a producing task cutting its output into
# partition frames; "read" counts a consuming task pulling its
# partition of upstream tasks (stage/repartition.py, stage/exchange.py)
# — defined here because the two directions live in different modules
# and their identity must not drift.
# overload governance (server/resourcegroups.py + server/memory.py):
# admission queueing, the cluster memory pool, and deadline
# enforcement. Defined here because producers span modules (tracker,
# group manager, memory manager, remote scheduler) and the bench load
# leg re-reads them — one identity, no drift.
QUERY_QUEUED_SECONDS = METRICS.histogram(
    "trino_tpu_query_queued_seconds",
    "Time queries spent queued in resource-group admission before "
    "starting")
QUEUE_REJECTIONS = METRICS.counter(
    "trino_tpu_queue_rejections_total",
    "Queries rejected at admission because the group queue was full "
    "(QUERY_QUEUE_FULL)")
MEMORY_POOL_BYTES = METRICS.gauge(
    "trino_tpu_memory_pool_bytes",
    "Cluster memory pool state in bytes", ("kind",))   # total|reserved
MEMORY_POOL_QUERIES = METRICS.gauge(
    "trino_tpu_memory_pool_queries",
    "Queries currently holding a cluster memory pool reservation")
MEMORY_KILLS = METRICS.counter(
    "trino_tpu_memory_kills_total",
    "Queries killed by the low-memory killer (CLUSTER_OUT_OF_MEMORY)")
DEADLINE_CANCELS = METRICS.counter(
    "trino_tpu_deadline_cancels_total",
    "Queries canceled for exceeding query_max_run_time "
    "(EXCEEDED_TIME_LIMIT)")

EXCHANGE_PARTITIONS = METRICS.counter(
    "trino_tpu_exchange_partitions_total",
    "Partitioned-exchange frames by direction", ("direction",))
EXCHANGE_PARTITION_BYTES = METRICS.counter(
    "trino_tpu_exchange_partition_bytes_total",
    "Serialized partitioned-exchange bytes by direction", ("direction",))
STAGES_SCHEDULED = METRICS.counter(
    "trino_tpu_stages_scheduled_total",
    "Worker stages dispatched by the stage-DAG scheduler")
# coordinator failover (stage/scheduler.py resume mode): per resumed
# query, stage partitions already COMMITTED on the exchange spool are
# "resumed" (served off spool, zero re-execution); the rest are
# "replayed" (re-dispatched)
FAILOVER_PARTITIONS = METRICS.counter(
    "trino_tpu_failover_partitions_total",
    "Stage partitions handled during coordinator-failover resume by "
    "outcome", ("outcome",))
# eager stage pipelining (stage/scheduler.py): the last query's share
# of exchange-connected wall time where tasks of >= 2 different stages
# ran concurrently (0 under the per-stage barrier; the bench mpp leg's
# mpp_pipeline_overlap_ratio)
MPP_OVERLAP_RATIO = METRICS.gauge(
    "trino_tpu_mpp_pipeline_overlap_ratio",
    "Pipelined stage overlap of the most recent stage-DAG query")
# ICI-native exchange (stage/ici.py): bytes moved by device-collective
# stage boundaries (jax.lax.all_to_all / in-slice replication) — the
# counterpart of the spool/HTTP leg's
# trino_tpu_exchange_partition_bytes_total
EXCHANGE_ICI_BYTES = METRICS.counter(
    "trino_tpu_exchange_ici_bytes_total",
    "Bytes exchanged at in-slice (device collective) stage boundaries",
    ("kind",))
EXCHANGE_ICI_EDGES = METRICS.counter(
    "trino_tpu_exchange_ici_edges_total",
    "Stage-boundary exchanges lowered to in-slice device collectives",
    ("kind",))

# beyond-HBM morsel streaming (exec/streamjoin.py): registered here —
# not in the lazily-imported streaming module — so every consumer
# (bench deltas, /metrics scrapes, tests) sees the same labeled
# families regardless of import order
STREAM_CHUNKS = METRICS.counter(
    "trino_tpu_stream_chunks_total",
    "Chunks processed by morsel-streamed operators", ("op",))
STREAM_H2D_BYTES = METRICS.counter(
    "trino_tpu_stream_bytes_h2d_total",
    "Bytes moved host->device by streamed-operator chunk transfers")
STREAM_OVERLAPPED = METRICS.counter(
    "trino_tpu_stream_transfers_overlapped_total",
    "Chunk transfers issued while the previous chunk's compute was "
    "still in flight (the double-buffer overlap)")

# worker-side multi-query runtime (exec/taskexec.py +
# server/task_worker.py): the shared split scheduler interleaving
# splits/chunks from every concurrent query's tasks, live per-task
# memory beats into the cluster pool, pressure-driven cache eviction,
# and the BUSY load-shed signal. Registered here — not in the lazily
# imported scheduler module — so scrapes and bench deltas see one
# family identity regardless of import order.
TASK_SCHED_QUANTA = METRICS.counter(
    "trino_tpu_task_scheduler_quanta_total",
    "Split/chunk quanta the shared task scheduler accounted, by "
    "resource group (the fairness observable)", ("group",))
TASK_SCHED_YIELDS = METRICS.counter(
    "trino_tpu_task_scheduler_yields_total",
    "Times a task handed its runner slot to a higher-priority task "
    "at a split/chunk boundary")
TASK_SCHED_RUNNABLE = METRICS.gauge(
    "trino_tpu_task_scheduler_open_tasks",
    "Tasks currently registered with the shared task scheduler "
    "(running + waiting + blocked)")
WORKER_BUSY_REJECTS = METRICS.counter(
    "trino_tpu_worker_busy_rejections_total",
    "Task dispatches this worker declined with the retryable BUSY "
    "signal under sustained load (the stage scheduler's retry/"
    "rotation machinery re-places them)")
LIVE_MEMORY_BEATS = METRICS.counter(
    "trino_tpu_worker_live_memory_beats_total",
    "Worker-reported live task reservations folded into the cluster "
    "memory pool DURING execution (status-poll beats)")
CACHE_PRESSURE_EVICTS = METRICS.counter(
    "trino_tpu_cache_pressure_evictions_total",
    "Cache entries evicted by memory-pressure governance, by cache "
    "(scan = HBM scan cache, jit = structural program caches, "
    "replicate = exchange fetch-once cache)", ("cache",))
REPLICATE_CACHE = METRICS.counter(
    "trino_tpu_exchange_replicate_cache_total",
    "Per-worker fetch-once cache lookups on replicate exchange "
    "edges, by outcome", ("result",))

# structural jitted-program caches (exec/executor.py chain/stream/
# masked programs + exec/streamjoin.py probe programs): ONE family
# definition here so the two producer modules cannot drift into
# duplicate registrations of the same name
JIT_CACHE_LOOKUPS = METRICS.counter(
    "trino_tpu_jit_cache_total",
    "Structural jitted-program cache lookups by cache and outcome",
    ("cache", "result"))

# distributed tracing + scheduler attribution (ISSUE 15): the
# worker-side split scheduler's observables (exec/taskexec.py) and the
# OTLP trace exporter (obs/otlp.py). Registered here — not in the
# lazily imported producer modules — so scrapes, the bench telemetry
# leg, and the EMA busy-shed all read one family identity.
TASK_SCHED_QUEUE_DEPTH = METRICS.gauge(
    "trino_tpu_task_scheduler_queue_depth",
    "Tasks waiting for a runner slot in the shared split scheduler "
    "(the backlog the EMA busy-shed smooths)")
TASK_QUANTUM_SECONDS = METRICS.histogram(
    "trino_tpu_task_quantum_seconds",
    "Wall seconds per scheduler quantum (the work between two "
    "split/chunk checkpoints)")
EXCHANGE_WAIT_SECONDS = METRICS.histogram(
    "trino_tpu_exchange_wait_seconds",
    "Wall seconds a consumer task spent blocked on upstream exchange "
    "commits with its runner slot released")
TASK_SCHED_LEVEL_SECONDS = METRICS.counter(
    "trino_tpu_task_scheduled_seconds_total",
    "Scheduled wall seconds accounted by the shared split scheduler, "
    "by multilevel-feedback level at grant time", ("level",))
OTLP_EXPORTS = METRICS.counter(
    "trino_tpu_otlp_exports_total",
    "OTLP trace-export attempts by sink and outcome (obs/otlp.py "
    "file/HTTP sinks)", ("sink", "result"))

# query history + learned operator statistics (obs/history.py +
# exec/learnedstats.py): terminal-query records appended to the
# durable history store, slow-query-log emissions, and the learned
# selectivity/throughput registry's observation flow. Registered here
# — not in the producer modules — so coordinator scrapes, worker
# scrapes and bench deltas all read one family identity.
HISTORY_RECORDS = METRICS.counter(
    "trino_tpu_query_history_records_total",
    "Terminal-query records appended to the coordinator's durable "
    "query-history store, by terminal state", ("state",))
SLOW_QUERY_LOGS = METRICS.counter(
    "trino_tpu_slow_query_log_total",
    "Queries whose wall time crossed the slow_query_log_ms threshold "
    "and were written to the trace-linked slow-query log")
LEARNED_STATS_OBSERVATIONS = METRICS.counter(
    "trino_tpu_learned_stats_observations_total",
    "Per-operator executions folded into the learned-stats registry "
    "(observed = this process's executors, merged = worker "
    "task-status deltas)", ("outcome",))
LEARNED_STATS_SIZE = METRICS.gauge(
    "trino_tpu_learned_stats_entries",
    "(program key, operator, occurrence) entries currently tracked "
    "by the learned-stats registry")

# streaming ingestion + continuous queries (trino_tpu/streaming/ +
# connectors/stream.py): producers POST /v1/ingest/{topic} on the
# coordinator or any worker, offset commits seal each continuous
# cycle, and the job scheduler re-dispatches incremental plans on a
# cadence. Registered here — the producers span the message log, both
# HTTP server modules and the continuous-query manager — so scrapes
# and bench deltas read one family identity regardless of import
# order.
INGEST_ROWS = METRICS.counter(
    "trino_tpu_ingest_rows_total",
    "Messages appended to the streaming message log, by topic",
    ("topic",))
INGEST_BYTES = METRICS.counter(
    "trino_tpu_ingest_bytes_total",
    "Message payload bytes appended to the streaming message log, "
    "by topic", ("topic",))
OFFSET_COMMITS = METRICS.counter(
    "trino_tpu_stream_offset_commits_total",
    "Consumer offset epochs committed to the spool-backed offset "
    "store, by outcome (committed = this process sealed the epoch, "
    "superseded = an earlier commit already won)", ("outcome",))
CONTINUOUS_CYCLES = METRICS.counter(
    "trino_tpu_continuous_cycles_total",
    "Continuous-query scheduler cycles, by outcome (advanced = new "
    "offsets committed, idle = no new messages, failed)", ("outcome",))
CONTINUOUS_JOBS = METRICS.gauge(
    "trino_tpu_continuous_queries",
    "Continuous-query jobs currently RUNNING on this coordinator")


def write_exposition(handler) -> None:
    """Serve METRICS as a Prometheus text response on a
    BaseHTTPRequestHandler — the one /metrics implementation shared by
    the coordinator and the task worker."""
    raw = METRICS.render().encode()
    handler.send_response(200)
    handler.send_header("Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
    handler.send_header("Content-Length", str(len(raw)))
    handler.end_headers()
    handler.wfile.write(raw)


def parse_exposition(text: str) -> Dict[str, Dict[Tuple[str, ...], float]]:
    """Parse Prometheus text exposition back into
    {metric_name: {(label=value, ...): value}} — the test-side decoder
    (asserting on re-parsed samples, not on string formatting)."""
    out: Dict[str, Dict[Tuple[str, ...], float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_labels, _, raw = line.rpartition(" ")
        if "{" in name_labels:
            name, _, rest = name_labels.partition("{")
            body = rest.rstrip("}")
            labels = []
            for part in _split_labels(body):
                k, _, v = part.partition("=")
                labels.append(f"{k}={v.strip(chr(34))}")
            key = tuple(labels)
        else:
            name, key = name_labels, ()
        out.setdefault(name, {})[key] = float(raw)
    return out


def _split_labels(body: str) -> List[str]:
    parts, cur, inq = [], "", False
    for ch in body:
        if ch == '"':
            inq = not inq
            cur += ch
        elif ch == "," and not inq:
            parts.append(cur)
            cur = ""
        else:
            cur += ch
    if cur:
        parts.append(cur)
    return parts
