"""Static-analysis layer: plan sanity checking + source lint.

Reference parity: sql/planner/sanity/PlanSanityChecker.java — the
reference runs every optimized plan through a validator battery
(TypeValidator, ValidateDependenciesChecker, NoDuplicatePlanNodeIds,
...) so a broken optimizer rule fails loudly at plan time instead of
as a silent wrong answer. Here that battery lives in ``sanity.py``
(wired into ``planner/optimizer.py`` per-pass under the
``plan_validation`` session property, and always into the remote
fragmenter), and ``lint.py`` adds a source-level AST lint for the two
failure classes a tensor-compiled threaded engine grows on its own:
unsynchronized shared-state writes in the threaded runtime and Python
side effects inside jit-traced functions.
"""

from .sanity import (PlanSanityChecker, PlanValidationError,
                     validate_plan)

__all__ = ["PlanSanityChecker", "PlanValidationError", "validate_plan"]
