"""Source-level AST lint for the threaded runtime + the jit boundary.

``python -m trino_tpu.analysis.lint [paths...] [--fail-on SEVERITY]``

Two rule families, each targeting a failure class this engine grows
structurally (five review rounds of PR 5/6 were lock-ordering fixes;
a Python side effect inside a traced function silently runs once at
trace time and never again):

**Shared-mutable-state races** (modules that spawn threads —
``server/coordinator.py``, ``server/task_worker.py``,
``exec/remote.py``, ``fte/*`` and anything else that calls
``threading.Thread``/``threading.Timer``):

- ``race-attr-write`` (error): an attribute write rooted at ``self``
  (``self.x = ...``, ``self.x += ...``, ``self.x[k] = ...``) in code
  reachable from a thread target without an enclosing
  ``with self.<lock>`` block.
- ``race-attr-mutate`` (error): a mutating container call
  (``self.xs.append(...)``, ``.add``, ``.pop``, ...) on a
  ``self``-rooted attribute under the same reachability rule.

Reachability is a module-local call graph seeded at every
``threading.Thread(target=...)`` / ``threading.Timer(...,  fn)``
target plus the ``do_*`` request methods of
``BaseHTTPRequestHandler`` subclasses (each request runs on its own
server thread). Calls made inside a ``with <...lock...>`` block
propagate a *locked* context to the callee, so a helper that is only
ever called under the lock is not flagged (the reference pattern:
``probe_once`` mutating ``_Stats`` under the detector lock). Handler
classes' own ``self`` writes are exempt — handler instances are
per-request, thread-local by construction. A ``with`` guard is
recognized by its context expression's last dotted segment containing
``lock`` (``self._lock``, ``st.lock``, ``self._members_lock``, ...).

**jit purity** (``exec/``, ``ops/``, ``parallel/`` — anywhere a
function is passed to ``jax.jit`` / ``shard_map`` or decorated with
them):

- ``jit-impure`` (error): a call with trace-time side effects inside
  the traced function — ``time.*``, ``datetime.now``, ``random.*`` /
  ``np.random.*`` (``jax.random`` is pure and allowed), ``open`` /
  ``print`` / ``input``. These run ONCE at trace time and are baked
  into the compiled program — a cached program replays the first
  trace's clock/sample forever.
- ``jit-closure-mutate`` (warning): mutating a closure variable
  (``results.append(x)`` where ``results`` is free) inside a traced
  function — executed per trace, not per call, which is almost never
  the intent.
- ``aot-unsafe`` (error): data-dependent Python control flow inside a
  traced function — ``.item()`` host syncs, and ``int(x)`` /
  ``float(x)`` / ``bool(x)`` concretizations in ``if``/``while``
  conditions. These already fail lazily at trace time with real data
  (ConcretizationTypeError -> deny-list); on the AOT lower path
  (exec/aot.py — ``jax.jit(fn).lower(avals).compile()`` against
  shape-only avals) there is no data at all, so such a function can
  never be pre-compiled. The rule keeps every cache-eligible program
  AOT-lowerable.

**Metrics hygiene** (every module registering on the process
registry ``METRICS``/``_METRICS``):

- ``metric-missing-help`` (error): a family registered with no help
  text — the exposition's only documentation.
- ``metric-naming`` (error): the ``trino_tpu_`` prefix plus the
  per-kind unit-suffix convention (counters ``_total``, histograms
  ``_seconds``/``_bytes``/..., gauges a unit or counted-noun suffix).
- ``metric-duplicate-registration`` (error, multi-file runs): one
  family registered from two call sites — get-or-create makes it
  legal at runtime, but duplicate definitions drift; define once
  (obs/metrics.py) and import.

**Suppressions** — one line at a time, with a reason::

    self.ended = time.time()  # tt-lint: ignore[race-attr-write] terminal-transition winner is the sole writer

Multiple rules: ``ignore[race-attr-write,race-attr-mutate]``. A
suppression with no trailing justification is itself reported
(``suppression-without-reason``, warning): silencing a race checker
without saying why defeats the point.

Cross-module reachability (``lint_paths`` multi-file runs): thread
seeds stay module-local, but a reachable ``obj.m(...)`` call is ALSO
resolved by method name against classes of the SHARED-RUNTIME callee
modules (``_CROSS_CALLEES``: ``fte/``, ``stage/``, ``obs/metrics.py``,
``obs/trace.py``, ``server/failure.py``,
``server/resourcegroups.py``, ``server/memory.py``) with the
caller's lock context propagated — so the scheduler-thread -> ``fte/spool.py``
edges (``spool.commit``/``release`` from dispatch threads) are
followed and a spool-side unlocked write is flagged in the spool's
file. The callee set is deliberately an allowlist: name-based
receiver matching across the WHOLE tree would drown the signal in
same-name methods of thread-private classes (``session.set`` on a
task-local Session is not ``Gauge.set`` on the process registry);
the allowlisted modules are exactly the ones whose instances cross
thread boundaries by design. Broaden via the ``cross_callees``
parameter (tests pass ``("",)`` to match everything).

Known limits (documented, deliberate): receiver types are matched by
method NAME (same module first, then the callee allowlist), bare-name
calls into other modules (imported functions) are not followed, and
jit bodies are scanned directly (no interprocedural purity
propagation).
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_MUTATORS = frozenset({
    "append", "extend", "insert", "add", "discard", "remove", "pop",
    "popitem", "clear", "update", "setdefault", "appendleft",
    "popleft"})

_IMPURE_ROOTS = {
    "time": "time.* reads the host clock at trace time",
    "_time": "time.* reads the host clock at trace time",
    "random": "the random module draws host entropy at trace time",
}
_IMPURE_DOTTED_PREFIXES = {
    "np.random": "np.random draws host entropy at trace time",
    "numpy.random": "numpy.random draws host entropy at trace time",
    "datetime.datetime.now": "host clock read at trace time",
    "datetime.now": "host clock read at trace time",
}
_IMPURE_BARE = {
    "open": "file I/O inside a traced function",
    "print": "I/O inside a traced function runs once, at trace time",
    "input": "blocking I/O inside a traced function",
}

_SUPPRESS_RE = re.compile(
    r"#\s*tt-lint:\s*ignore\[([A-Za-z0-9_,\- ]+)\]\s*(.*)")


@dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    severity: str          # "error" | "warning"
    message: str
    suppressed: bool = False

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.severity}] {self.rule}: {self.message}{tag}")


# --------------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------------

def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    """Base Name of an attribute/subscript chain ('self' for
    self.a.b[k])."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_lock_expr(node: ast.AST) -> bool:
    d = _dotted(node)
    if d is None:
        return False
    return "lock" in d.split(".")[-1].lower()


class _FuncInfo:
    """One function/method and its lexical context."""

    __slots__ = ("node", "cls", "parent", "qualname")

    def __init__(self, node: ast.AST, cls: Optional[str],
                 parent: Optional["_FuncInfo"], qualname: str):
        self.node = node          # FunctionDef / AsyncFunctionDef
        self.cls = cls            # enclosing class name (methods +
        #                           functions nested inside methods)
        self.parent = parent
        self.qualname = qualname


class _ModuleIndex(ast.NodeVisitor):
    """Collects functions, classes, and class->methods for one
    module."""

    def __init__(self) -> None:
        self.functions: List[_FuncInfo] = []
        self.by_node: Dict[ast.AST, _FuncInfo] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self.methods: Dict[str, List[Tuple[str, _FuncInfo]]] = {}
        self._cls_stack: List[Optional[str]] = [None]
        self._fn_stack: List[Optional[_FuncInfo]] = [None]
        self.handler_classes: Set[str] = set()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.classes[node.name] = node
        for b in node.bases:
            base = _dotted(b) or ""
            if base.split(".")[-1] == "BaseHTTPRequestHandler":
                self.handler_classes.add(node.name)
        self._cls_stack.append(node.name)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _visit_fn(self, node) -> None:
        cls = self._cls_stack[-1]
        parent = self._fn_stack[-1]
        if parent is not None and cls is not None \
                and parent.cls is not None:
            cls = parent.cls   # nested def inside a method: same class
        qual = (f"{cls}.{node.name}" if cls and parent is None
                else node.name)
        info = _FuncInfo(node, cls, parent, qual)
        self.functions.append(info)
        self.by_node[node] = info
        if cls is not None and parent is None:
            self.methods.setdefault(node.name, []).append((cls, info))
        self._fn_stack.append(info)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn


# --------------------------------------------------------------------------
# race detector
# --------------------------------------------------------------------------

# shared-runtime modules whose methods thread code in OTHER modules
# calls by design: cross-module edges are followed into these (and only
# these — see the module docstring for why this is an allowlist).
# resourcegroups + memory joined in PR 10: admission/dequeue and pool
# reservation bookkeeping run on dispatch threads (QueryTracker's
# per-query threads call groups.query_finished and memory.reserve
# concurrently), so their lock discipline must stay lint-reachable.
# hotshapes joined in PR 11: the hot-shape registry is mutated by
# query threads, task threads, and the worker pre-warm thread
# concurrently (HOT_SHAPES.record/merge/export_since), so its lock
# discipline must stay lint-reachable too. streamjoin joined in PR 12:
# its jitted-program caches are mutated by query threads and the
# worker pre-warm thread (exec/aot.py streamjoin entries).
# distributed joined in PR 13: the mesh executor now runs stage DAGs
# (stage/ici.py calls back into DistributedExecutor) and worker task
# threads execute its kernels under the unified in-slice path, so its
# state writes must stay lint-reachable next to the stage/ exchange
# modules (the ICI exchange path itself lives under stage/, already
# covered).
_CROSS_CALLEES = ("fte/", "stage/", "obs/metrics.py", "obs/trace.py",
                  "server/failure.py", "server/resourcegroups.py",
                  "server/memory.py", "exec/hotshapes.py",
                  "exec/streamjoin.py", "exec/distributed.py",
                  # PR 14: the shared split scheduler — runner/task/
                  # status threads all mutate its queues, so the race
                  # detector must see every state write
                  "exec/taskexec.py",
                  # PR 15: the OTLP exporter — query threads and the
                  # coordinator's HTTP threads both drive export/
                  # serialization, so its sink state stays reachable
                  "obs/otlp.py",
                  # PR 17: the fault-point registry — fault_point()
                  # fires from scheduler dispatch threads, worker HTTP
                  # threads and spool commit paths alike; already under
                  # the fte/ prefix, listed explicitly so narrowing
                  # that prefix can never silently drop it
                  "fte/faultpoints.py",
                  # PR 18: the coordinator result cache — query
                  # threads fill/hit it while the memory-pressure
                  # ladder (executor eviction, worker status threads)
                  # sheds it, so its LRU state must stay visible to
                  # the race detector
                  "exec/resultcache.py",
                  # PR 19: the query-history store and the
                  # learned-stats registry — per-query tracker
                  # threads append/observe while scheduler status
                  # beats merge and HTTP handler / system-table scan
                  # threads read, so their lock discipline must stay
                  # lint-reachable
                  "obs/history.py", "exec/learnedstats.py",
                  # PR 20: the streaming subsystem — ingest HTTP
                  # threads append to partition segments while
                  # continuous-job scheduler threads read windows and
                  # commit offsets, and the stream connector's scans
                  # run on worker task threads; every shared index
                  # (partition positions, topic cache, job registry)
                  # must stay visible to the race detector
                  "streaming/", "connectors/stream.py")


class _CrossIndex:
    """Method-name registry over the callee-eligible modules of one
    ``lint_paths`` run: name -> [(owning analyzer, function)]. A
    reachable attribute call resolves here AFTER module-local
    resolution; the walk happens in the OWNING analyzer so findings
    land in the callee's file."""

    def __init__(self) -> None:
        self.methods: Dict[str, List[Tuple["_RaceAnalyzer",
                                           _FuncInfo]]] = {}

    def add_module(self, analyzer: "_RaceAnalyzer") -> None:
        for name, pairs in analyzer.index.methods.items():
            for cls, fi in pairs:
                if cls.startswith("_"):
                    # a private class's instances are module-internal
                    # by convention — they do not cross module
                    # boundaries, so a cross-module name match against
                    # one is definitionally the wrong receiver (e.g.
                    # the detector-lock-guarded _Stats.record vs the
                    # public StragglerDetector.record callers mean)
                    continue
                self.methods.setdefault(name, []).append((analyzer, fi))

    def resolve(self, method: str):
        return self.methods.get(method, ())


class _RaceAnalyzer:
    """Thread-reachability analysis + self-write checks: module-local
    seeding and call graph, plus cross-module edges into a shared
    ``_CrossIndex`` when one is wired (lint_paths)."""

    def __init__(self, tree: ast.Module, path: str):
        self.tree = tree
        self.path = path
        self.index = _ModuleIndex()
        self.index.visit(tree)
        self.findings: List[Finding] = []
        # (function node, locked) states already propagated
        self._visited: Set[Tuple[int, bool]] = set()
        self.cross: Optional[_CrossIndex] = None

    # -- entry discovery ----------------------------------------------
    def _thread_targets(self) -> List[Tuple[_FuncInfo, ast.Call]]:
        out: List[Tuple[_FuncInfo, ast.Call]] = []
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            name = _dotted(call.func) or ""
            base = name.split(".")[-1]
            if base not in ("Thread", "Timer"):
                continue
            target: Optional[ast.AST] = None
            for kw in call.keywords:
                if kw.arg in ("target", "function"):
                    target = kw.value
            if target is None and len(call.args) >= 2:
                # positional forms put the callable at index 1 in BOTH
                # signatures: Thread(group, target, ...) and
                # Timer(interval, function, ...) — args[0] is group/
                # interval, never the target
                target = call.args[1]
            if target is None:
                continue
            scope = self._enclosing_function(call)
            for fi in self._resolve_callable(target, scope):
                out.append((fi, call))
        return out

    def _enclosing_function(self, node: ast.AST) -> Optional[_FuncInfo]:
        # ast has no parent links: find the innermost function whose
        # span contains the node (functions are few per module)
        best: Optional[_FuncInfo] = None
        for fi in self.index.functions:
            f = fi.node
            if f.lineno <= node.lineno <= (f.end_lineno or f.lineno):
                if best is None or f.lineno >= best.node.lineno:
                    best = fi
        return best

    def _resolve_callable(self, expr: ast.AST,
                          scope: Optional[_FuncInfo]
                          ) -> List[_FuncInfo]:
        """Function infos an expression may call into (best effort)."""
        if isinstance(expr, ast.Lambda):
            return []
        if isinstance(expr, ast.Name):
            fi = self._lookup_name(expr.id, scope)
            return [fi] if fi is not None else []
        if isinstance(expr, ast.Attribute):
            root = _root_name(expr.value)
            meth = expr.attr
            if root == "self" and scope is not None \
                    and scope.cls is not None \
                    and isinstance(expr.value, ast.Name):
                for cls, fi in self.index.methods.get(meth, ()):
                    if cls == scope.cls:
                        return [fi]
                return []
            # x.m() / self.obj.m(): match by method name against the
            # module's classes (receiver types are not tracked)
            return [fi for _, fi in self.index.methods.get(meth, ())]
        return []

    def _lookup_name(self, name: str,
                     scope: Optional[_FuncInfo]) -> Optional[_FuncInfo]:
        """Nearest visible def: siblings nested in the same (or an
        enclosing) function, then module-level functions."""
        cur = scope
        while cur is not None:
            for fi in self.index.functions:
                if fi.parent is cur and fi.node.name == name:
                    return fi
            cur = cur.parent
        for fi in self.index.functions:
            if fi.parent is None and fi.cls is None \
                    and fi.node.name == name:
                return fi
        return None

    # -- propagation --------------------------------------------------
    def analyze(self) -> List[Finding]:
        entries: List[_FuncInfo] = [fi for fi, _ in
                                    self._thread_targets()]
        for name, pairs in self.index.methods.items():
            if name.startswith("do_"):
                for cls, fi in pairs:
                    if cls in self.index.handler_classes:
                        entries.append(fi)
        for fi in entries:
            self._walk_function(fi, locked=False)
        return self.findings

    def _walk_function(self, fi: _FuncInfo, locked: bool) -> None:
        # an unlocked visit is strictly stronger than a locked one (it
        # flags everything the locked visit would not), so a locked
        # visit after an unlocked one adds nothing, while an unlocked
        # visit must re-run even after a locked one
        if (id(fi.node), False) in self._visited:
            return
        if locked and (id(fi.node), True) in self._visited:
            return
        self._visited.add((id(fi.node), locked))
        exempt_self = fi.cls in self.index.handler_classes
        self._scan_body(fi, fi.node, locked, exempt_self)

    def _scan_body(self, fi: _FuncInfo, fn_node: ast.AST, locked: bool,
                   exempt_self: bool) -> None:
        own_nested = {f.node for f in self.index.functions
                      if f.parent is fi}

        def scan(node: ast.AST, lock_depth: int) -> None:
            if node in own_nested or isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)) and node is not fn_node:
                return      # nested defs analyzed only when reached
            guarded = locked or lock_depth > 0
            if isinstance(node, ast.With):
                depth = lock_depth + (1 if any(
                    _is_lock_expr(i.context_expr)
                    for i in node.items) else 0)
                for item in node.items:
                    scan(item.context_expr, lock_depth)
                for child in node.body:
                    scan(child, depth)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)) and not guarded \
                    and not exempt_self \
                    and not (isinstance(node, ast.AnnAssign)
                             and node.value is None):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Tuple):
                        elts = list(t.elts)
                    else:
                        elts = [t]
                    for el in elts:
                        if isinstance(el, (ast.Attribute,
                                           ast.Subscript)) \
                                and _root_name(el) == "self":
                            self._emit(
                                el, "race-attr-write",
                                f"write to '{_target_repr(el)}' is "
                                "reachable from a thread target with "
                                "no enclosing lock")
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _MUTATORS \
                        and not guarded and not exempt_self \
                        and isinstance(node.func.value,
                                       (ast.Attribute, ast.Subscript)) \
                        and _root_name(node.func.value) == "self":
                    self._emit(
                        node, "race-attr-mutate",
                        f"'{_dotted(node.func) or node.func.attr}(...)'"
                        " mutates shared state reachable from a thread"
                        " target with no enclosing lock")
                for callee in self._resolve_callable(node.func, fi):
                    self._walk_function(callee, locked=guarded)
                if self.cross is not None \
                        and isinstance(node.func, ast.Attribute):
                    # cross-module edge: the scheduler thread calling
                    # spool.commit(...) walks the spool's method in
                    # the spool's analyzer, caller lock context intact
                    for other, cfi in self.cross.resolve(
                            node.func.attr):
                        if other is not self:
                            other._walk_function(cfi, locked=guarded)
            for child in ast.iter_child_nodes(node):
                scan(child, lock_depth)

        for stmt in getattr(fn_node, "body", []):
            scan(stmt, 0)

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        self.findings.append(Finding(
            self.path, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0), rule, "error", message))


def _target_repr(node: ast.AST) -> str:
    d = _dotted(node)
    if d is not None:
        return d
    base = _dotted(getattr(node, "value", None))
    return f"{base}[...]" if base else "self.<attr>"


# --------------------------------------------------------------------------
# jit purity checker
# --------------------------------------------------------------------------

class _JitAnalyzer:
    """Finds functions handed to jax.jit / shard_map and scans their
    bodies for trace-time side effects."""

    def __init__(self, tree: ast.Module, path: str):
        self.tree = tree
        self.path = path
        self.index = _ModuleIndex()
        self.index.visit(tree)
        self.findings: List[Finding] = []

    def analyze(self) -> List[Finding]:
        seen: Set[int] = set()
        for fn in self._traced_functions():
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            self._scan_traced(fn)
        return self.findings

    # -- discovery ----------------------------------------------------
    def _is_jit_name(self, expr: ast.AST) -> bool:
        d = _dotted(expr) or ""
        base = d.split(".")[-1]
        if base in ("jit", "shard_map", "pmap"):
            return True
        # partial(jax.jit, ...) used as a decorator factory
        if isinstance(expr, ast.Call) \
                and (_dotted(expr.func) or "").split(".")[-1] \
                == "partial" and expr.args:
            return self._is_jit_name(expr.args[0])
        return False

    def _traced_functions(self) -> Iterable[ast.AST]:
        for fi in self.index.functions:
            for dec in getattr(fi.node, "decorator_list", []):
                if self._is_jit_name(dec) or (
                        isinstance(dec, ast.Call)
                        and self._is_jit_name(dec.func)):
                    yield fi.node
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call) \
                    or not self._is_jit_name(call.func):
                continue
            if not call.args:
                continue
            target = call.args[0]
            if isinstance(target, ast.Lambda):
                yield target
            elif isinstance(target, ast.Name):
                scope = self._enclosing_function(call)
                fi = self._lookup_name(target.id, scope)
                if fi is not None:
                    yield fi.node

    # borrowed resolution helpers (same shapes, no inheritance needed)
    _enclosing_function = _RaceAnalyzer._enclosing_function
    _lookup_name = _RaceAnalyzer._lookup_name

    # -- body scan ----------------------------------------------------
    def _scan_traced(self, fn: ast.AST) -> None:
        local = _local_names(fn)
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                # data-dependent Python branch: int(x)/float(x)/bool(x)
                # in the condition concretizes a traced value — lazily
                # a ConcretizationTypeError with real data, a hard
                # impossibility on the AOT lower path (exec/aot.py
                # compiles against shape-only avals: no data to
                # branch on)
                for sub in ast.walk(node.test):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Name) \
                            and sub.func.id in ("int", "float",
                                                "bool") \
                            and sub.args \
                            and not isinstance(sub.args[0],
                                               ast.Constant):
                        self._emit(
                            sub, "aot-unsafe", "error",
                            f"'{sub.func.id}(...)' in a branch "
                            "condition inside a traced function "
                            "concretizes a traced value — "
                            "data-dependent Python branches cannot "
                            "be AOT-lowered")
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d is not None:
                    root = d.split(".")[0]
                    why = None
                    if d in _IMPURE_BARE:
                        why = _IMPURE_BARE[d]
                    elif root in _IMPURE_ROOTS and "." in d:
                        why = _IMPURE_ROOTS[root]
                    else:
                        for pref, msg in _IMPURE_DOTTED_PREFIXES \
                                .items():
                            if d == pref or d.startswith(pref + "."):
                                why = msg
                                break
                    if why is not None:
                        self._emit(node, "jit-impure", "error",
                                   f"'{d}' inside a jit/shard_map-"
                                   f"traced function: {why}")
                        continue
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" \
                        and not node.args:
                    self._emit(
                        node, "aot-unsafe", "error",
                        f"'{_dotted(node.func) or 'item'}()' inside "
                        "a traced function is a host sync — the AOT "
                        "lower path has no data to sync, so the "
                        "program cannot be compiled ahead of time")
                    continue
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _MUTATORS \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id not in local:
                    self._emit(
                        node, "jit-closure-mutate", "warning",
                        f"'{node.func.value.id}.{node.func.attr}"
                        "(...)' mutates a closure variable inside a "
                        "traced function — runs at trace time, not "
                        "per call")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id not in local:
                        self._emit(
                            t, "jit-closure-mutate", "warning",
                            f"subscript write to closure variable "
                            f"'{t.value.id}' inside a traced function")

    def _emit(self, node: ast.AST, rule: str, severity: str,
              message: str) -> None:
        self.findings.append(Finding(
            self.path, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0), rule, severity, message))


# --------------------------------------------------------------------------
# metrics hygiene
# --------------------------------------------------------------------------

# registrations against the process registry only: the singleton's
# canonical names (obs/metrics.py METRICS, imported as _METRICS in
# exec/executor.py). Local test registries (reg = MetricsRegistry())
# are deliberately out of scope.
_METRIC_RECEIVERS = frozenset({"METRICS", "_METRICS"})
_METRIC_KINDS = frozenset({"counter", "gauge", "histogram"})
_METRIC_PREFIX = "trino_tpu_"
# unit-suffix convention per kind (Prometheus naming): counters are
# monotonic totals; histograms carry their unit; gauges name the
# measured quantity (bytes/seconds/...) or the counted noun
_HIST_SUFFIXES = ("_seconds", "_bytes", "_millis", "_nanos")
_GAUGE_SUFFIXES = ("_bytes", "_seconds", "_ratio", "_depth",
                   "_queries", "_workers", "_shapes", "_tasks",
                   "_entries", "_chunks")


@dataclass
class _MetricReg:
    name: str
    kind: str
    path: str
    line: int
    col: int


class _MetricsAnalyzer:
    """Metrics-hygiene pass (gated in tier-1 next to the race/jit
    rules): every family on the process registry must carry non-empty
    help text (``metric-missing-help``) and follow the
    ``trino_tpu_`` prefix + per-kind unit-suffix naming convention
    (``metric-naming``). Registrations are also collected so the
    driver can flag the same family registered from two call sites
    (``metric-duplicate-registration``) — get-or-create makes that
    legal at runtime, but two definitions of one identity WILL drift
    (help text, labels), so the convention is one definition imported
    everywhere (the PR 12 stream families pattern)."""

    def __init__(self, tree: ast.Module, path: str):
        self.tree = tree
        self.path = path
        self.findings: List[Finding] = []
        self.registrations: List[_MetricReg] = []

    def analyze(self) -> List[Finding]:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr not in _METRIC_KINDS:
                continue
            recv = (_dotted(node.func.value) or "").split(".")[-1]
            if recv not in _METRIC_RECEIVERS:
                continue
            kind = node.func.attr
            if not node.args or not isinstance(node.args[0],
                                               ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue    # dynamic name: out of the rule's reach
            name = node.args[0].value
            self.registrations.append(_MetricReg(
                name, kind, self.path, node.lineno, node.col_offset))
            self._check_help(node, name)
            self._check_name(node, kind, name)
        return self.findings

    def _check_help(self, node: ast.Call, name: str) -> None:
        help_node = None
        if len(node.args) > 1:
            help_node = node.args[1]
        else:
            for kw in node.keywords:
                if kw.arg == "help":
                    help_node = kw.value
        # only ABSENT or empty-LITERAL help is a violation; a help
        # passed as a name/call is out of the rule's reach, like the
        # dynamic-name case above
        bad = help_node is None or (
            isinstance(help_node, ast.Constant)
            and not str(help_node.value or "").strip())
        if bad:
            self._emit(node, "metric-missing-help",
                       f"metric family '{name}' registered without "
                       "help text — a scraper's only documentation")

    def _check_name(self, node: ast.Call, kind: str,
                    name: str) -> None:
        if not name.startswith(_METRIC_PREFIX):
            self._emit(node, "metric-naming",
                       f"metric family '{name}' must carry the "
                       f"'{_METRIC_PREFIX}' prefix")
            return
        if kind == "counter" and not name.endswith("_total"):
            self._emit(node, "metric-naming",
                       f"counter '{name}' must end in '_total' "
                       "(Prometheus counter convention)")
        elif kind == "histogram" \
                and not name.endswith(_HIST_SUFFIXES):
            self._emit(node, "metric-naming",
                       f"histogram '{name}' must end in a unit "
                       f"suffix {_HIST_SUFFIXES}")
        elif kind == "gauge" and not name.endswith(_GAUGE_SUFFIXES):
            self._emit(node, "metric-naming",
                       f"gauge '{name}' must end in a unit/noun "
                       f"suffix {_GAUGE_SUFFIXES}")

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        self.findings.append(Finding(
            self.path, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0), rule, "error", message))


def _metric_duplicates(regs: Sequence[_MetricReg]) -> List[Finding]:
    """One finding per registration site beyond a family's first
    (ordered by path then line — the first site is the canonical
    definition the others should import)."""
    by_name: Dict[str, List[_MetricReg]] = {}
    for r in regs:
        by_name.setdefault(r.name, []).append(r)
    out: List[Finding] = []
    for name, sites in by_name.items():
        if len(sites) < 2:
            continue
        sites.sort(key=lambda r: (r.path, r.line))
        first = sites[0]
        for r in sites[1:]:
            out.append(Finding(
                r.path, r.line, r.col, "metric-duplicate-registration",
                "error",
                f"metric family '{name}' is already registered at "
                f"{first.path}:{first.line} — import that definition "
                "instead of re-registering (duplicate definitions "
                "drift)"))
    return out


def _local_names(fn: ast.AST) -> Set[str]:
    """Names bound inside ``fn`` (params, assignments, loop/with
    targets, comprehension vars, local imports, nested defs)."""
    names: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            names.add(a.arg)
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            names.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
    return names


# --------------------------------------------------------------------------
# suppression handling + driver
# --------------------------------------------------------------------------

def _apply_suppressions(findings: List[Finding],
                        src_lines: Sequence[str],
                        path: str) -> List[Finding]:
    """Mark findings suppressed by their line's tt-lint comment; a
    reason-less suppression is itself a (warning) finding."""
    out = list(findings)
    for i, line in enumerate(src_lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if m is None:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        hit = False
        for f in out:
            if f.path == path and f.line == i and f.rule in rules:
                f.suppressed = True
                hit = True
        if hit and not m.group(2).strip():
            out.append(Finding(
                path, i, line.index("#"), "suppression-without-reason",
                "warning", "tt-lint suppression carries no "
                "justification — say why the race/impurity is safe"))
    return out


def lint_source(src: str, path: str = "<string>") -> List[Finding]:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, e.offset or 0,
                        "syntax-error", "error", str(e))]
    findings = _RaceAnalyzer(tree, path).analyze()
    findings += _JitAnalyzer(tree, path).analyze()
    metrics = _MetricsAnalyzer(tree, path)
    findings += metrics.analyze()
    findings += _metric_duplicates(metrics.registrations)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return _apply_suppressions(findings, src.splitlines(), path)


def lint_paths(paths: Iterable[str],
               cross_callees: Optional[Sequence[str]] = _CROSS_CALLEES
               ) -> List[Finding]:
    """Lint many files with cross-module race reachability: every file
    is indexed first, then thread seeds propagate — following
    attribute calls into methods of the ``cross_callees`` modules (a
    pattern matches by substring of the /-normalized path; None
    disables the cross pass entirely). Findings land in the file that
    owns the flagged write; suppressions apply per file as always."""
    findings: List[Finding] = []
    sources: Dict[str, str] = {}
    analyzers: Dict[str, _RaceAnalyzer] = {}
    trees: Dict[str, ast.Module] = {}
    seen: Set[str] = set()
    files: List[str] = []
    for path in _expand(paths):
        if path in seen:
            continue
        seen.add(path)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            findings.append(Finding(path, 0, 0, "io-error", "error",
                                    str(e)))
            continue
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(path, e.lineno or 0, e.offset or 0,
                                    "syntax-error", "error", str(e)))
            continue
        files.append(path)
        sources[path] = src
        trees[path] = tree
        analyzers[path] = _RaceAnalyzer(tree, path)
    if cross_callees is not None and len(analyzers) > 1:
        cross = _CrossIndex()
        for path, an in analyzers.items():
            norm = path.replace(os.sep, "/")
            if any(pat in norm for pat in cross_callees):
                cross.add_module(an)
        for an in analyzers.values():
            an.cross = cross
    for an in analyzers.values():
        an.analyze()
    # metrics hygiene: per-file rules, then duplicate-registration
    # detection ACROSS the whole run (the same family registered in
    # two modules is exactly what a single-file pass cannot see)
    all_regs: List[_MetricReg] = []
    metric_findings: Dict[str, List[Finding]] = {}
    for path in files:
        ma = _MetricsAnalyzer(trees[path], path)
        metric_findings[path] = ma.analyze()
        all_regs.extend(ma.registrations)
    for f in _metric_duplicates(all_regs):
        metric_findings.setdefault(f.path, []).append(f)
    # collect AFTER full propagation: a caller module's analyze() may
    # have emitted findings into a callee module's analyzer
    for path in files:
        per_file = list(analyzers[path].findings)
        per_file += _JitAnalyzer(trees[path], path).analyze()
        per_file += metric_findings.get(path, [])
        per_file.sort(key=lambda f: (f.line, f.col, f.rule))
        findings.extend(_apply_suppressions(
            per_file, sources[path].splitlines(), path))
    return findings


def _expand(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        else:
            out.append(p)
    return out


def default_root() -> str:
    """The trino_tpu package directory (the default lint target)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m trino_tpu.analysis.lint",
        description="Concurrency + jit-purity lint for trino_tpu.")
    parser.add_argument("paths", nargs="*",
                        help="files/directories (default: the "
                             "trino_tpu package)")
    parser.add_argument("--fail-on", choices=("error", "warning",
                                              "none"),
                        default="error",
                        help="exit non-zero when unsuppressed findings"
                             " at/above this severity exist "
                             "(default: error)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    args = parser.parse_args(argv)
    paths = args.paths or [default_root()]
    findings = lint_paths(paths)
    active = [f for f in findings if not f.suppressed]
    shown = findings if args.show_suppressed else active
    for f in shown:
        print(f.render())
    n_err = sum(1 for f in active if f.severity == "error")
    n_warn = sum(1 for f in active if f.severity == "warning")
    n_sup = sum(1 for f in findings if f.suppressed)
    print(f"{len(active)} finding(s): {n_err} error(s), "
          f"{n_warn} warning(s); {n_sup} suppressed")
    if args.fail_on == "none":
        return 0
    if args.fail_on == "warning" and (n_err or n_warn):
        return 1
    if args.fail_on == "error" and n_err:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
