"""Plan sanity checker: invariant validators over optimized plans.

Reference parity: sql/planner/sanity/PlanSanityChecker.java and its
validator battery (SURVEY.md A.4) — TypeValidator,
ValidateDependenciesChecker, NoDuplicatePlanNodeIds,
AllFunctionsResolved... The reference runs the battery after every
IterativeOptimizer pass in tests and once before execution in
production; ours runs after every ``optimize()`` pass when the
``plan_validation`` session property is set (debug mode) and ALWAYS
before the remote fragmenter dispatches work (exec/remote.py) — a
malformed fragment would otherwise surface as an XLA trace error
30-90s into compile, or worse, as a wrong answer.

Validators (each named like its reference analog):

- ``NoDuplicatePlanNodeIds`` — the plan must be a proper tree: no node
  OBJECT may appear at two positions. Engine nodes carry no explicit
  ids (frozen dataclasses), so object identity plays the id role: a
  rewrite that grafts one subtree under two parents breaks every
  whole-tree rewriter that assumes single ownership.
- ``ValidateDependenciesChecker`` — symbol dependency closure: every
  symbol a node references (expression InputRefs, group/sort/partition
  keys, union symbol maps, ...) must exist in its sources' output
  schemas. Catches dangling InputRefs left by pruning bugs.
- ``TypeValidator`` — expression/output type consistency: InputRef
  types must agree with the source schema column they name, predicates
  must be boolean, comparisons must compare one type family, and
  set-operation symbol maps must be type-stable across branches.
- ``JoinCriteriaChecker`` — every equi-join clause must name a left
  symbol from the left source and a right symbol from the right
  source, with type agreement between the two sides (the analyzer
  inserts casts for coercions, so criteria reaching execution must
  already agree).
- ``SerdeRoundTripChecker`` (fragments only) — a fragment crossing the
  spool/exchange boundary must survive the plan wire format
  (plan/serde.py) bit-stably: encode -> JSON -> decode -> re-encode
  must reproduce the original encoding AND an equivalent plan.

A failed validator raises ``PlanValidationError`` naming the validator
and the optimizer pass that broke the invariant, and increments
``trino_tpu_plan_validation_failures_total`` (obs/metrics.py).
"""

from __future__ import annotations

import json
from dataclasses import fields as dc_fields, is_dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from ..plan.nodes import (AggregationNode, ExchangeNode, FilterNode,
                          GroupIdNode, JoinNode, MarkDistinctNode,
                          OutputNode, PartitionedOutputNode, PlanNode,
                          ProjectNode, RemoteSourceNode, SemiJoinNode,
                          SetOpNode, SortNode, TableDeleteNode,
                          TableScanNode, TableWriterNode, TopNNode,
                          UnionNode, UnnestNode, ValuesNode,
                          WindowNode)
from ..rex import Call, CaseExpr, Cast, InputRef, Lambda, RowExpr
from ..types import DecimalType, Type, is_numeric, is_string
from ..obs.metrics import PLAN_VALIDATION_FAILURES, PLAN_VALIDATIONS


class PlanValidationError(Exception):
    """A plan invariant does not hold. ``validator`` names the check
    that failed (the reference's checker class name), ``pass_name`` the
    optimizer pass (or pipeline stage) after which the invariant was
    found broken — the pass is the suspect, not the plan author."""

    # errors.classify picks this up: a broken plan is the engine's
    # compiler failing its own output, never the user's fault
    error_name = "COMPILER_ERROR"

    def __init__(self, validator: str, message: str,
                 pass_name: str = ""):
        self.validator = validator
        self.pass_name = pass_name
        where = f" after pass '{pass_name}'" if pass_name else ""
        super().__init__(
            f"plan validation failed{where}: [{validator}] {message}")


class _Violation(Exception):
    """Internal: a validator's finding before it is stamped with the
    validator name + pass name."""


# --------------------------------------------------------------------------
# traversal helpers
# --------------------------------------------------------------------------

def walk_plan(node: PlanNode) -> Iterator[PlanNode]:
    yield node
    for s in node.sources:
        yield from walk_plan(s)


def _schema(node: PlanNode,
            memo: Optional[Dict[int, Dict[str, Type]]] = None
            ) -> Dict[str, Type]:
    """output_schema, with schema-derivation failures (a dangling key
    crashing a derived schema) reported as violations instead of raw
    KeyErrors. ``memo`` (id(node) -> schema) amortizes the recursive
    derivation across a battery run — every validator visits every
    node, so uncached schemas would be recomputed once per validator
    per reference."""
    if memo is not None:
        got = memo.get(id(node))
        if got is not None:
            return got
    try:
        out = node.output_schema()
    except KeyError as e:
        raise _Violation(
            f"{type(node).__name__}: output schema references unknown "
            f"symbol {str(e)}") from e
    if memo is not None:
        memo[id(node)] = out
    return out


def _env(node: PlanNode,
         memo: Optional[Dict[int, Dict[str, Type]]] = None
         ) -> Dict[str, Type]:
    """Union of the node's source schemas (later sources win, like
    JoinNode.output_schema)."""
    env: Dict[str, Type] = {}
    for s in node.sources:
        env.update(_schema(s, memo))
    return env


def _node_label(node: PlanNode) -> str:
    return type(node).__name__


# --------------------------------------------------------------------------
# type agreement
# --------------------------------------------------------------------------

def _family(t: Type) -> str:
    """Comparison family: values of one family are mutually comparable
    after the analyzer's implicit coercions."""
    name = getattr(t, "name", "")
    base = name.split("(")[0]
    if is_string(t) or base in ("varchar", "char", "json"):
        return "string"
    if is_numeric(t) or isinstance(t, DecimalType):
        return "numeric"
    if base in ("date",) or base.startswith("timestamp") \
            or base.startswith("time"):
        return "temporal"
    if base == "boolean":
        return "boolean"
    if base == "unknown":
        return "unknown"   # typed NULL compares with anything
    return base


def types_agree(a: Type, b: Type) -> bool:
    """Loose agreement for symbol references: exact equality, or the
    same parametric base (varchar lengths may differ between a scan
    schema and a projected reference), or the same comparison family
    for families whose physical lanes are interchangeable."""
    if a == b:
        return True
    fa, fb = _family(a), _family(b)
    if "unknown" in (fa, fb):
        return True
    return fa == fb


def comparable(a: Type, b: Type) -> bool:
    fa, fb = _family(a), _family(b)
    return fa == fb or "unknown" in (fa, fb)


# --------------------------------------------------------------------------
# expression walking (lambda-aware)
# --------------------------------------------------------------------------

_CMPS = ("=", "<>", "<", "<=", ">", ">=")


def _check_expr(e: RowExpr, env: Dict[str, Type], where: str,
                bound: frozenset = frozenset()) -> None:
    if isinstance(e, InputRef):
        if e.name in bound:
            return
        t = env.get(e.name)
        if t is not None and not types_agree(e.type, t):
            raise _Violation(
                f"{where}: InputRef '{e.name}' carries type {e.type} "
                f"but the source column is {t}")
        return
    if isinstance(e, Call):
        if e.fn in _CMPS and len(e.args) == 2:
            ta, tb = e.args[0].type, e.args[1].type
            if not comparable(ta, tb):
                raise _Violation(
                    f"{where}: comparison '{e.fn}' over incomparable "
                    f"types {ta} and {tb}")
        for a in e.args:
            _check_expr(a, env, where, bound)
        return
    if isinstance(e, Cast):
        _check_expr(e.arg, env, where, bound)
        return
    if isinstance(e, Lambda):
        _check_expr(e.body, env, where, bound | frozenset(e.params))
        return
    if isinstance(e, CaseExpr):
        for c, v in e.whens:
            _check_expr(c, env, where, bound)
            _check_expr(v, env, where, bound)
        if e.default is not None:
            _check_expr(e.default, env, where, bound)


def _free_refs(e: RowExpr) -> Set[str]:
    from ..rex import input_names
    return input_names(e)


def _node_exprs(node: PlanNode) -> List[Tuple[str, RowExpr]]:
    """(description, expression) pairs evaluated against the node's
    source env."""
    out: List[Tuple[str, RowExpr]] = []
    if isinstance(node, FilterNode):
        out.append(("predicate", node.predicate))
    elif isinstance(node, ProjectNode):
        out.extend((f"assignment '{s}'", e)
                   for s, e in node.assignments.items())
    elif isinstance(node, JoinNode):
        if node.filter is not None:
            out.append(("join filter", node.filter))
    elif _is_semi_multi(node):
        if node.filter is not None:
            out.append(("semi-join filter", node.filter))
    return out


def _is_semi_multi(node: PlanNode) -> bool:
    return type(node).__name__ == "SemiJoinMultiNode"


# --------------------------------------------------------------------------
# validators
# --------------------------------------------------------------------------

class NoDuplicatePlanNodeIds:
    """The plan is a tree: one owner per node object (the reference
    checks PlanNodeId uniqueness; object identity is the id here)."""

    name = "NoDuplicatePlanNodeIds"

    def validate(self, plan: PlanNode, memo=None) -> None:
        seen: Set[int] = set()
        for node in walk_plan(plan):
            if id(node) in seen:
                raise _Violation(
                    f"{_node_label(node)} appears at more than one "
                    "position in the plan tree (shared subtree object)")
            seen.add(id(node))


class ValidateDependenciesChecker:
    """Symbol dependency closure: no dangling references anywhere."""

    name = "ValidateDependenciesChecker"

    def validate(self, plan: PlanNode, memo=None) -> None:
        memo = {} if memo is None else memo
        for node in walk_plan(plan):
            self._check_node(node, memo)

    def _require(self, node: PlanNode, syms: Iterable[str],
                 env: Dict[str, Type], what: str) -> None:
        missing = [s for s in syms if s not in env]
        if missing:
            raise _Violation(
                f"{_node_label(node)}: {what} references symbols "
                f"{missing} absent from the source schema "
                f"(available: {sorted(env)[:12]}...)")

    def _check_node(self, node: PlanNode, memo) -> None:
        label = _node_label(node)
        if isinstance(node, TableScanNode):
            if set(node.assignments) != set(node.schema):
                raise _Violation(
                    f"{label}: assignments {sorted(node.assignments)} "
                    f"and schema {sorted(node.schema)} disagree")
            return
        if isinstance(node, (ValuesNode, RemoteSourceNode,
                             TableDeleteNode)):
            return
        env = _env(node, memo)
        for what, e in _node_exprs(node):
            self._require(node, _free_refs(e), env, what)
        if isinstance(node, AggregationNode):
            self._require(node, node.group_keys, env, "group keys")
            for sym, a in node.aggregates.items():
                refs = [s for s in (a.argument, a.argument2, a.mask)
                        if s is not None]
                self._require(node, refs, env, f"aggregate '{sym}'")
        elif isinstance(node, GroupIdNode):
            self._require(node, node.all_keys, env, "grouping keys")
            for gs in node.grouping_sets:
                self._require(node, gs, env, "grouping set")
        elif isinstance(node, SemiJoinNode):
            self._require(node, [node.source_key],
                          _schema(node.source, memo), "source key")
            self._require(node, [node.filtering_key],
                          _schema(node.filtering_source, memo),
                          "filtering key")
        elif _is_semi_multi(node):
            self._require(node, node.source_keys,
                          _schema(node.source, memo), "source keys")
            self._require(node, node.filtering_keys,
                          _schema(node.filtering_source, memo),
                          "filtering keys")
        elif isinstance(node, (SortNode, TopNNode)):
            self._require(node, [k.symbol for k in node.keys], env,
                          "sort keys")
        elif isinstance(node, MarkDistinctNode):
            self._require(node, node.keys, env, "distinct keys")
        elif isinstance(node, WindowNode):
            self._require(node, node.partition_by, env, "partition by")
            self._require(node, [k.symbol for k in node.order_by], env,
                          "order by")
            for sym, f in node.functions.items():
                refs = [s for s in (f.argument, f.offset, f.default)
                        if s is not None]
                self._require(node, refs, env, f"window '{sym}'")
        elif isinstance(node, UnnestNode):
            self._require(node, node.replicate, env, "replicate")
            self._require(node, node.unnest.values(), env,
                          "unnest inputs")
        elif isinstance(node, UnionNode):
            for i, (child, smap) in enumerate(
                    zip(node.children, node.symbol_maps)):
                missing_out = [s for s in node.schema if s not in smap]
                if missing_out:
                    raise _Violation(
                        f"{label}: branch {i} symbol map is missing "
                        f"output symbols {missing_out}")
                self._require(node, [smap[s] for s in node.schema],
                              _schema(child, memo),
                              f"branch {i} symbols")
        elif isinstance(node, SetOpNode):
            self._require(node, node.left_map.values(),
                          _schema(node.left, memo), "left map")
            self._require(node, node.right_map.values(),
                          _schema(node.right, memo), "right map")
        elif isinstance(node, OutputNode):
            self._require(node, node.symbols, env, "output symbols")
        elif isinstance(node, ExchangeNode):
            self._require(node, node.partition_keys, env,
                          "partition keys")
        elif isinstance(node, PartitionedOutputNode):
            # partitioning-key closure, producer half: a key the body
            # does not produce would make the bucketing kernel KeyError
            # on every worker (or worse, partition on a stale column)
            self._require(node, node.partition_keys, env,
                          "partition keys")
        elif isinstance(node, TableWriterNode):
            self._require(node, node.symbols, env, "writer symbols")


class TypeValidator:
    """Expression/output type consistency (sanity/TypeValidator)."""

    name = "TypeValidator"

    def validate(self, plan: PlanNode, memo=None) -> None:
        memo = {} if memo is None else memo
        for node in walk_plan(plan):
            env = _env(node, memo)
            for what, e in _node_exprs(node):
                _check_expr(e, env, f"{_node_label(node)} {what}")
            if isinstance(node, FilterNode) \
                    and _family(node.predicate.type) not in (
                        "boolean", "unknown"):
                raise _Violation(
                    f"FilterNode predicate has type "
                    f"{node.predicate.type}, expected boolean")
            if isinstance(node, JoinNode) and node.filter is not None \
                    and _family(node.filter.type) not in (
                        "boolean", "unknown"):
                raise _Violation(
                    f"JoinNode filter has type {node.filter.type}, "
                    "expected boolean")
            if isinstance(node, UnionNode):
                for i, (child, smap) in enumerate(
                        zip(node.children, node.symbol_maps)):
                    cschema = _schema(child, memo)
                    for s, t in node.schema.items():
                        src = cschema.get(smap.get(s, ""), None)
                        if src is not None and not types_agree(t, src):
                            raise _Violation(
                                f"UnionNode output '{s}' is {t} but "
                                f"branch {i} provides {src}")
            if isinstance(node, AggregationNode):
                src = env
                nschema = _schema(node, memo)
                for k in node.group_keys:
                    # existence is the dependency checker's finding;
                    # here only agreement between derived and source
                    if k in src and k in nschema \
                            and not types_agree(nschema[k], src[k]):
                        raise _Violation(
                            f"AggregationNode group key '{k}' changes "
                            f"type {src[k]} -> {nschema[k]}")


class JoinCriteriaChecker:
    """Equi-join clause sidedness + type agreement."""

    name = "JoinCriteriaChecker"

    def validate(self, plan: PlanNode, memo=None) -> None:
        memo = {} if memo is None else memo
        for node in walk_plan(plan):
            if isinstance(node, JoinNode):
                lschema = _schema(node.left, memo)
                rschema = _schema(node.right, memo)
                for c in node.criteria:
                    if c.left not in lschema:
                        raise _Violation(
                            f"join clause '{c.left} = {c.right}': left "
                            f"symbol '{c.left}' is not produced by the "
                            "left source")
                    if c.right not in rschema:
                        raise _Violation(
                            f"join clause '{c.left} = {c.right}': "
                            f"right symbol '{c.right}' is not produced "
                            "by the right source")
                    lt, rt = lschema[c.left], rschema[c.right]
                    if not comparable(lt, rt):
                        raise _Violation(
                            f"join clause '{c.left} = {c.right}' "
                            f"compares {lt} with {rt} — the analyzer "
                            "should have inserted a coercion")
            elif isinstance(node, SemiJoinNode):
                st = _schema(node.source, memo).get(node.source_key)
                ft = _schema(node.filtering_source, memo).get(
                    node.filtering_key)
                if st is not None and ft is not None \
                        and not comparable(st, ft):
                    raise _Violation(
                        f"semi-join key '{node.source_key}' ({st}) "
                        f"incomparable with '{node.filtering_key}' "
                        f"({ft})")


class SerdeRoundTripChecker:
    """Fragment wire-format stability (fragments crossing the remote
    exchange / spool boundary — plan/serde.py, exec/remote.py)."""

    name = "SerdeRoundTripChecker"

    def validate(self, plan: PlanNode, memo=None) -> None:
        check_serde_round_trip(plan)


def check_serde_round_trip(plan: PlanNode):
    """Prove the wire format round-trips, returning the proven-stable
    encoding so the dispatcher can ship the exact bytes it validated
    instead of re-encoding the fragment (raises ``_Violation`` — use
    through the checker for the stamped error)."""
    from ..plan.serde import from_jsonable, to_jsonable
    try:
        enc = to_jsonable(plan)
        wire = json.dumps(enc)
    except (TypeError, ValueError) as e:
        raise _Violation(
            f"fragment is not serializable: {e}") from e
    try:
        dec = from_jsonable(json.loads(wire))
    except Exception as e:      # noqa: BLE001 — any decode break
        raise _Violation(
            f"fragment does not decode from its own wire form: "
            f"{type(e).__name__}: {e}") from e
    try:
        enc2 = to_jsonable(dec)
    except (TypeError, ValueError) as e:
        raise _Violation(
            f"decoded fragment is not re-serializable: {e}") from e
    if enc2 != enc:
        raise _Violation(
            "fragment encoding is unstable: encode(decode(x)) != "
            "encode(x) — a worker retry would execute a different "
            "plan than the first attempt")
    if not _deep_eq(plan, dec):
        raise _Violation(
            "fragment round-trip changes the plan: decode(encode("
            "x)) != x (value or key types drift across the wire)")
    return enc


def _deep_eq(a, b) -> bool:
    """Structural equality, key-type-strict for dicts (JSON stringifies
    non-str keys; dataclass __eq__ would hide the drift when both
    sides re-stringify)."""
    if is_dataclass(a) and not isinstance(a, type):
        if type(a) is not type(b):
            return False
        return all(_deep_eq(getattr(a, f.name), getattr(b, f.name))
                   for f in dc_fields(a))
    if isinstance(a, dict):
        if not isinstance(b, dict) or set(a) != set(b):
            return False
        ka = {k: type(k) for k in a}
        kb = {k: type(k) for k in b}
        if ka != kb:
            return False
        return all(_deep_eq(a[k], b[k]) for k in a)
    if isinstance(a, (tuple, list)):
        if type(a) is not type(b) or len(a) != len(b):
            return False
        return all(_deep_eq(x, y) for x, y in zip(a, b))
    if isinstance(a, float) and isinstance(b, float):
        return a == b or (a != a and b != b)    # NaN-stable
    try:
        return bool(a == b)
    except Exception:       # noqa: BLE001 — array-valued fields
        return a is b


class StageBoundaryChecker:
    """Stage-DAG boundary validator (multi-stage MPP,
    trino_tpu/stage/): partitioning-key closure and schema agreement
    across every PartitionedOutput/RemoteSource pair. Unlike the
    per-plan validators above it sees the WHOLE DAG — a single stage
    plan is internally consistent even when its RemoteSource schema
    silently drifted from what the producer stage actually emits, so
    the edge itself is the thing to check:

    - every RemoteSourceNode names an existing producer stage;
    - the producer's plan is rooted in a PartitionedOutputNode whose
      partition keys the producer body produces (key closure — the
      per-plan dependency checker covers this half too);
    - the consumer's RemoteSource schema matches the producer's output
      symbol-for-symbol with agreeing types (a drift here executes,
      then joins/aggregates garbage — the exact class of wrong-answer
      bug a validator exists for);
    - a hash-partitioned producer carries at least one key; a gather
      producer carries none.
    """

    name = "StageBoundaryChecker"

    def validate_dag(self, stages, root_plan: PlanNode) -> None:
        by_sid = {st.sid: st for st in stages}
        for st in stages:
            po = st.plan
            if not isinstance(po, PartitionedOutputNode):
                raise _Violation(
                    f"stage {st.sid} plan is rooted in "
                    f"{_node_label(po)}, expected PartitionedOutput")
            body_schema = _schema(po.source)
            missing = [k for k in po.partition_keys
                       if k not in body_schema]
            if missing:
                raise _Violation(
                    f"stage {st.sid} partitions by {missing} which its "
                    f"body does not produce "
                    f"(available: {sorted(body_schema)[:12]}...)")
            if po.kind == "hash" and not po.partition_keys:
                raise _Violation(
                    f"stage {st.sid} hash-partitions with no keys")
            if po.kind in ("gather", "replicate") and po.partition_keys:
                raise _Violation(
                    f"stage {st.sid} {po.kind}s but carries partition "
                    f"keys {list(po.partition_keys)}")
        for where, plan in [(f"stage {st.sid}", st.plan)
                            for st in stages] + [("root", root_plan)]:
            for node in walk_plan(plan):
                if not isinstance(node, RemoteSourceNode):
                    continue
                for fid in node.fragment_ids:
                    producer = by_sid.get(fid)
                    if producer is None:
                        raise _Violation(
                            f"{where}: RemoteSource names unknown "
                            f"stage {fid}")
                    pschema = _schema(producer.plan)
                    for sym, t in node.schema.items():
                        pt = pschema.get(sym)
                        if pt is None:
                            raise _Violation(
                                f"{where}: RemoteSource expects symbol "
                                f"'{sym}' which stage {fid} does not "
                                f"produce (produces: "
                                f"{sorted(pschema)[:12]}...)")
                        if not types_agree(t, pt):
                            raise _Violation(
                                f"{where}: RemoteSource symbol '{sym}' "
                                f"expects {t} but stage {fid} produces "
                                f"{pt}")


def validate_stage_dag(dag, checker: Optional["PlanSanityChecker"]
                       = None,
                       pass_name: str = "stage-fragmenter"
                       ) -> Dict[int, dict]:
    """The stage flavor of the always-on pre-dispatch battery
    (exec/remote.py): every stage plan runs the FRAGMENT battery (its
    wire form is what workers execute — serde round-trip included),
    the root plan runs the base battery, and the StageBoundaryChecker
    proves every exchange edge. Returns the round-trip-proven encoding
    per stage id — the exact bytes the scheduler ships."""
    checker = checker or PlanSanityChecker()
    payloads: Dict[int, dict] = {}
    for st in dag.stages:
        payloads[st.sid] = checker.validate_fragment(
            st.plan, pass_name)
    checker.validate(dag.root_plan, pass_name)
    boundary = StageBoundaryChecker()
    PLAN_VALIDATIONS.inc()
    try:
        boundary.validate_dag(dag.stages, dag.root_plan)
    except _Violation as e:
        PLAN_VALIDATION_FAILURES.inc(validator=boundary.name)
        raise PlanValidationError(boundary.name, str(e),
                                  pass_name) from e
    return payloads


# --------------------------------------------------------------------------
# the checker
# --------------------------------------------------------------------------

DEFAULT_VALIDATORS = (NoDuplicatePlanNodeIds(),
                      ValidateDependenciesChecker(),
                      TypeValidator(),
                      JoinCriteriaChecker())

FRAGMENT_VALIDATORS = DEFAULT_VALIDATORS + (SerdeRoundTripChecker(),)


class PlanSanityChecker:
    """Runs the validator battery; the first broken invariant raises a
    ``PlanValidationError`` naming the validator + pass."""

    def __init__(self, validators: Optional[tuple] = None):
        self.validators = (DEFAULT_VALIDATORS if validators is None
                           else tuple(validators))

    def _run(self, validators, plan: PlanNode, pass_name: str) -> None:
        PLAN_VALIDATIONS.inc()
        # one schema memo for the whole battery: every validator walks
        # every node, and output_schema() re-derives recursively
        memo: Dict[int, Dict[str, Type]] = {}
        for v in validators:
            try:
                v.validate(plan, memo)
            except _Violation as e:
                PLAN_VALIDATION_FAILURES.inc(validator=v.name)
                raise PlanValidationError(v.name, str(e),
                                          pass_name) from e

    def validate(self, plan: PlanNode, pass_name: str = "") -> None:
        self._run(self.validators, plan, pass_name)

    def validate_fragment(self, plan: PlanNode,
                          pass_name: str = "fragmenter"):
        """Fragment battery: the plan checks plus wire-format
        round-trip stability (the fragment is about to cross the
        exchange/spool boundary as JSON). Returns the proven-stable
        encoding so the dispatcher ships the bytes it validated
        instead of encoding the fragment a second time."""
        base = tuple(v for v in self.validators
                     if not isinstance(v, SerdeRoundTripChecker))
        self._run(base, plan, pass_name)
        try:
            return check_serde_round_trip(plan)
        except _Violation as e:
            PLAN_VALIDATION_FAILURES.inc(
                validator=SerdeRoundTripChecker.name)
            raise PlanValidationError(SerdeRoundTripChecker.name,
                                      str(e), pass_name) from e


def validate_plan(plan: PlanNode, pass_name: str = "",
                  fragment: bool = False) -> None:
    """One-shot convenience entry (the module-level analog of the
    reference's PlanSanityChecker.validateFinalPlan)."""
    checker = PlanSanityChecker()
    if fragment:
        checker.validate_fragment(plan, pass_name)
    else:
        checker.validate(plan, pass_name)
