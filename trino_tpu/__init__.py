"""trino_tpu — a TPU-native distributed SQL query engine.

A ground-up reimplementation of the capabilities of Trino (reference
surveyed in SURVEY.md) designed for TPUs: columnar batches are HBM-resident
jax.Arrays, operator pipelines compile to fused XLA programs via jax.jit,
and the shuffle/exchange layer lowers to XLA collectives over ICI.
"""

from . import config  # noqa: F401  — enables x64; must be first
from .types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, REAL, SMALLINT,
                    TINYINT, UNKNOWN, VARCHAR, DecimalType, Type,
                    VarcharType, parse_type)
from .columnar import Batch, Column, StringDictionary, batch_from_pylist

__version__ = "0.1.0"
